"""Discovery: membership, master election, failure detection.

Reference: org/elasticsearch/discovery/zen/ — ZenDiscovery.java
(join/leave + publish), ElectMasterService.java (lowest-sorted
master-eligible node wins, minimum_master_nodes quorum),
fd/NodesFaultDetection.java + MasterFaultDetection.java (periodic pings,
N consecutive failures → node removed / master re-elected).

Multi-host: cluster/bootstrap.py connects these pieces to a real
jax.distributed world — ``initialize_distributed`` + ``MultiHostCluster``
run rank-0 master election and ping fault-detection over the TCP transport
(``python -m elasticsearch_tpu.server --coordinator host:port``); the DATA
plane never touches this path — collectives ride ICI/DCN via XLA. A dead
host's shards reroute via cluster/routing.py and replicas promote via
cluster/replication.py.
"""
from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

from elasticsearch_tpu.cluster.state import ClusterState, DiscoveryNode


class ElectMasterService:
    """Reference: ElectMasterService — sort master-eligible nodes by id,
    lowest wins; refuse election without quorum."""

    def __init__(self, minimum_master_nodes: int = 1):
        self.minimum_master_nodes = minimum_master_nodes

    def elect(self, nodes: List[DiscoveryNode]) -> Optional[DiscoveryNode]:
        eligible = sorted((n for n in nodes if "master" in n.roles),
                          key=lambda n: n.node_id)
        if len(eligible) < self.minimum_master_nodes:
            return None  # no quorum -> no master (reference: null master, red)
        return eligible[0] if eligible else None


class FaultDetector:
    """Ping-based failure detection (reference: fd/NodesFaultDetection).

    ``ping_fn(node) -> bool`` is injected so tests (and the future TCP
    transport) supply the real ping; ``ping_retries`` consecutive failures
    mark the node dead and fire ``on_failure``."""

    def __init__(self, ping_fn: Callable[[DiscoveryNode], bool],
                 on_failure: Callable[[DiscoveryNode], None],
                 ping_retries: int = 3):
        self.ping_fn = ping_fn
        self.on_failure = on_failure
        self.ping_retries = ping_retries
        self._fail_counts: Dict[str, int] = {}

    def check(self, nodes: List[DiscoveryNode]) -> List[DiscoveryNode]:
        """One detection round; returns nodes declared failed this round.

        Strike counts are pruned against the CURRENT membership view
        first: a node that left keeps no stale strikes, so a rejoin
        under the same id starts from zero instead of inheriting old
        failures and being insta-declared dead."""
        present = {n.node_id for n in nodes}
        for nid in [k for k in self._fail_counts if k not in present]:
            del self._fail_counts[nid]
        failed = []
        for node in nodes:
            if self.ping_fn(node):
                self._fail_counts.pop(node.node_id, None)
                continue
            c = self._fail_counts.get(node.node_id, 0) + 1
            self._fail_counts[node.node_id] = c
            if c >= self.ping_retries:
                failed.append(node)
                self._fail_counts.pop(node.node_id, None)
                self.on_failure(node)
        return failed


class MasterFaultDetection:
    """Every NON-master pings the elected master (reference:
    fd/MasterFaultDetection.java); ``ping_retries`` consecutive failures
    fire ``on_master_failure`` — the trigger for a quorum election among
    the master-eligible survivors (cluster/bootstrap.py). Built on
    FaultDetector, so a master change automatically prunes the old
    incumbent's strikes."""

    def __init__(self, ping_fn: Callable[[DiscoveryNode], bool],
                 on_master_failure: Callable[[DiscoveryNode], None],
                 ping_retries: int = 3):
        self._fd = FaultDetector(ping_fn, on_master_failure,
                                 ping_retries=ping_retries)

    def check(self, master: Optional[DiscoveryNode]) -> bool:
        """One round against the current master; True when this round
        declared it dead (and fired the callback)."""
        if master is None:
            self._fd.check([])  # prunes strikes of any former master
            return False
        return bool(self._fd.check([master]))


class VoteCollector:
    """Per-node ballot box: ONE vote per term, granted only for terms
    strictly above the highest term this node has accepted a state from
    (reference: CoordinationState.handleStartJoin/handleJoin — a node
    never votes twice in a term and never votes backwards). The caller
    holds its own lock; this object is plain bookkeeping."""

    def __init__(self):
        self._voted: Dict[int, str] = {}

    def grant(self, term: int, candidate: str, current_term: int) -> bool:
        prior = self._voted.get(term)
        if prior is not None:
            return prior == candidate  # idempotent re-ask, never a switch
        if term <= current_term or term < self.highest_granted():
            # stale candidacy: a committed state — or a ballot already
            # granted in a later term — outranks it (never vote backwards)
            return False
        self._voted[term] = candidate
        return True

    def voted_in(self, term: int) -> Optional[str]:
        return self._voted.get(term)

    def seed(self, term: int, candidate: str) -> None:
        """Restore a persisted ballot (Raft's votedFor): a restarted
        voter must not grant the same term twice — without this, a
        quick bounce lets two candidates both win one term."""
        if term > 0 and candidate:
            self._voted.setdefault(term, candidate)

    def last_vote(self) -> Tuple[int, Optional[str]]:
        t = self.highest_granted()
        return t, self._voted.get(t)

    def highest_granted(self) -> int:
        """The highest term this node ever granted a ballot in. Granting
        a vote PROMISES not to honor older masters (Raft's currentTerm
        bump on vote): publications below this floor are fenced even
        before the winner's first publish lands — without it, a deposed
        master partitioned only from the candidate could still gather a
        quorum of acks at its old term from the very voters that just
        elected its successor, committing a divergent state."""
        return max(self._voted, default=0)


def election_candidate(nodes: List[DiscoveryNode]) -> Optional[DiscoveryNode]:
    """The node expected to RUN the election among the reachable
    master-eligible survivors: lowest id wins the tiebreak (zen's
    lowest-sorted-id rule applied to candidacy — every survivor computes
    the same winner, so exactly one solicits votes per detection round
    instead of the herd splitting the ballot)."""
    eligible = sorted((n for n in nodes if "master" in n.roles),
                      key=lambda n: n.node_id)
    return eligible[0] if eligible else None


class ZenDiscovery:
    """Single-process-capable zen-style discovery over a shared ClusterState.

    ``vote_master=True`` (the multi-host mode): mastership is decided by
    quorum elections and term-fenced publications (cluster/bootstrap.py),
    NOT recomputed from membership — ``_reelect`` then only clears a
    master that left the view, never assigns one (a lower-id joiner must
    not steal an elected incumbent's seat)."""

    def __init__(self, state: ClusterState, local: DiscoveryNode,
                 minimum_master_nodes: int = 1, vote_master: bool = False):
        self.state = state
        self.local = local
        self.vote_master = vote_master
        self.elect_service = ElectMasterService(minimum_master_nodes)
        self._lock = threading.Lock()
        if local.node_id not in state.nodes:
            state.add_node(local)
        self._reelect()

    def join(self, node: DiscoveryNode) -> None:
        with self._lock:
            self.state.nodes[node.node_id] = node
            self.state.next_version()
            self._reelect()

    def leave(self, node_id: str) -> None:
        with self._lock:
            self.state.nodes.pop(node_id, None)
            # shards on the departed node become unassigned (reroute input)
            for r in self.state.routing:
                if r.node_id == node_id:
                    r.state = "UNASSIGNED"
                    r.node_id = ""
            self.state.next_version()
            self._reelect()

    def _reelect(self) -> None:
        if self.vote_master:
            # elected mastership: only CLEAR a master that left the view
            # (its failure fires an election); never assign one here
            cur = self.state.master_node_id
            if cur is not None and cur not in self.state.nodes:
                self.state.master_node_id = None
            return
        winner = self.elect_service.elect(list(self.state.nodes.values()))
        self.state.master_node_id = winner.node_id if winner else None

    @property
    def is_master(self) -> bool:
        return self.state.master_node_id == self.local.node_id

    def make_fault_detector(self, ping_fn: Callable[[DiscoveryNode], bool],
                            ping_retries: int = 3) -> FaultDetector:
        return FaultDetector(
            ping_fn=ping_fn,
            on_failure=lambda n: self.leave(n.node_id),
            ping_retries=ping_retries,
        )
