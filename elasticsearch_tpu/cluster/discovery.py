"""Discovery: membership, master election, failure detection.

Reference: org/elasticsearch/discovery/zen/ — ZenDiscovery.java
(join/leave + publish), ElectMasterService.java (lowest-sorted
master-eligible node wins, minimum_master_nodes quorum),
fd/NodesFaultDetection.java + MasterFaultDetection.java (periodic pings,
N consecutive failures → node removed / master re-elected).

Multi-host: cluster/bootstrap.py connects these pieces to a real
jax.distributed world — ``initialize_distributed`` + ``MultiHostCluster``
run rank-0 master election and ping fault-detection over the TCP transport
(``python -m elasticsearch_tpu.server --coordinator host:port``); the DATA
plane never touches this path — collectives ride ICI/DCN via XLA. A dead
host's shards reroute via cluster/routing.py and replicas promote via
cluster/replication.py.
"""
from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional

from elasticsearch_tpu.cluster.state import ClusterState, DiscoveryNode


class ElectMasterService:
    """Reference: ElectMasterService — sort master-eligible nodes by id,
    lowest wins; refuse election without quorum."""

    def __init__(self, minimum_master_nodes: int = 1):
        self.minimum_master_nodes = minimum_master_nodes

    def elect(self, nodes: List[DiscoveryNode]) -> Optional[DiscoveryNode]:
        eligible = sorted((n for n in nodes if "master" in n.roles),
                          key=lambda n: n.node_id)
        if len(eligible) < self.minimum_master_nodes:
            return None  # no quorum -> no master (reference: null master, red)
        return eligible[0] if eligible else None


class FaultDetector:
    """Ping-based failure detection (reference: fd/NodesFaultDetection).

    ``ping_fn(node) -> bool`` is injected so tests (and the future TCP
    transport) supply the real ping; ``ping_retries`` consecutive failures
    mark the node dead and fire ``on_failure``."""

    def __init__(self, ping_fn: Callable[[DiscoveryNode], bool],
                 on_failure: Callable[[DiscoveryNode], None],
                 ping_retries: int = 3):
        self.ping_fn = ping_fn
        self.on_failure = on_failure
        self.ping_retries = ping_retries
        self._fail_counts: Dict[str, int] = {}

    def check(self, nodes: List[DiscoveryNode]) -> List[DiscoveryNode]:
        """One detection round; returns nodes declared failed this round."""
        failed = []
        for node in nodes:
            if self.ping_fn(node):
                self._fail_counts.pop(node.node_id, None)
                continue
            c = self._fail_counts.get(node.node_id, 0) + 1
            self._fail_counts[node.node_id] = c
            if c >= self.ping_retries:
                failed.append(node)
                self._fail_counts.pop(node.node_id, None)
                self.on_failure(node)
        return failed


class ZenDiscovery:
    """Single-process-capable zen-style discovery over a shared ClusterState."""

    def __init__(self, state: ClusterState, local: DiscoveryNode,
                 minimum_master_nodes: int = 1):
        self.state = state
        self.local = local
        self.elect_service = ElectMasterService(minimum_master_nodes)
        self._lock = threading.Lock()
        if local.node_id not in state.nodes:
            state.add_node(local)
        self._reelect()

    def join(self, node: DiscoveryNode) -> None:
        with self._lock:
            self.state.nodes[node.node_id] = node
            self.state.next_version()
            self._reelect()

    def leave(self, node_id: str) -> None:
        with self._lock:
            self.state.nodes.pop(node_id, None)
            # shards on the departed node become unassigned (reroute input)
            for r in self.state.routing:
                if r.node_id == node_id:
                    r.state = "UNASSIGNED"
                    r.node_id = ""
            self.state.next_version()
            self._reelect()

    def _reelect(self) -> None:
        winner = self.elect_service.elect(list(self.state.nodes.values()))
        self.state.master_node_id = winner.node_id if winner else None

    @property
    def is_master(self) -> bool:
        return self.state.master_node_id == self.local.node_id

    def make_fault_detector(self, ping_fn: Callable[[DiscoveryNode], bool],
                            ping_retries: int = 3) -> FaultDetector:
        return FaultDetector(
            ping_fn=ping_fn,
            on_failure=lambda n: self.leave(n.node_id),
            ping_retries=ping_retries,
        )
