"""Cluster state: nodes, index metadata, routing table, blocks.

Reference: org/elasticsearch/cluster/ClusterState.java, metadata/MetaData.java,
routing/RoutingTable.java, node/DiscoveryNodes.java. Single-node now; the
state object is already shaped for the multi-host design (parallel/ docs):
a master (process rank 0 under jax.distributed) publishes versioned states,
and shard routing maps (index, shard, primary?) → node + mesh device.
"""
from __future__ import annotations

import time
import uuid
from dataclasses import dataclass, field
from typing import Dict, List, Optional


@dataclass
class DiscoveryNode:
    node_id: str
    name: str
    transport_address: str = "local"
    roles: tuple = ("master", "data", "ingest")
    attributes: dict = field(default_factory=dict)


#: ES's NO_MASTER_BLOCK at write level (reference: DiscoverySettings
#: .NO_MASTER_BLOCK_WRITES / NoMasterBlockService): with no elected
#: master, metadata changes and document writes fail typed 503 while
#: searches keep serving the last committed state.
NO_MASTER_BLOCK = {
    "id": 2,
    "description": "no master",
    "retryable": True,
    "levels": ["write", "metadata_write"],
}


@dataclass
class ShardRouting:
    index: str
    shard_id: int
    node_id: str
    primary: bool = True
    state: str = "STARTED"  # INITIALIZING|RELOCATING|STARTED|UNASSIGNED
    device_ord: int = 0  # mesh device carrying this shard's segments


@dataclass
class IndexMetadata:
    name: str
    settings: dict
    mappings: dict
    aliases: Dict[str, dict] = field(default_factory=dict)
    state: str = "open"
    creation_date: int = field(default_factory=lambda: int(time.time() * 1000))
    uuid: str = field(default_factory=lambda: uuid.uuid4().hex)


class ClusterState:
    def __init__(self, cluster_name: str = "elasticsearch_tpu"):
        self.cluster_name = cluster_name
        self.version = 0
        # master ERA, bumped by every quorum election (reference: the
        # coordination-era ClusterState.term beside version): publications
        # from an older term are stale and rejected; (term, version)
        # lexicographically orders states across master changes the way
        # version alone orders them within one master's reign
        self.term = 0
        self.state_uuid = uuid.uuid4().hex
        self.nodes: Dict[str, DiscoveryNode] = {}
        self.master_node_id: Optional[str] = None
        self.indices: Dict[str, IndexMetadata] = {}
        self.routing: List[ShardRouting] = []
        self.templates: Dict[str, dict] = {}
        self.blocks: Dict[str, list] = {}

    def next_version(self):
        self.version += 1
        self.state_uuid = uuid.uuid4().hex

    # -- global blocks -------------------------------------------------------

    def add_global_block(self, block: dict) -> None:
        blocks = self.blocks.setdefault("global", [])
        if all(b.get("id") != block.get("id") for b in blocks):
            blocks.append(dict(block))

    def clear_global_block(self, block_id: int) -> None:
        blocks = self.blocks.get("global")
        if blocks:
            blocks[:] = [b for b in blocks if b.get("id") != block_id]

    def global_block(self, level: str) -> Optional[dict]:
        """The first global block covering ``level``, or None."""
        for b in self.blocks.get("global", []):
            if level in b.get("levels", []):
                return b
        return None

    def add_node(self, node: DiscoveryNode, master: bool = False):
        self.nodes[node.node_id] = node
        if master or self.master_node_id is None:
            self.master_node_id = node.node_id
        self.next_version()

    def add_index(self, meta: IndexMetadata, num_shards: int, node_id: str, n_devices: int = 1):
        self.indices[meta.name] = meta
        for sid in range(num_shards):
            self.routing.append(
                ShardRouting(meta.name, sid, node_id, device_ord=sid % max(n_devices, 1))
            )
        self.next_version()

    def remove_index(self, name: str):
        self.indices.pop(name, None)
        self.routing = [r for r in self.routing if r.index != name]
        self.next_version()

    def health(self) -> dict:
        unassigned = sum(1 for r in self.routing if r.state == "UNASSIGNED")
        initializing = sum(1 for r in self.routing if r.state == "INITIALIZING")
        active = sum(1 for r in self.routing if r.state == "STARTED")
        status = "green"
        if unassigned or initializing:
            status = "yellow" if active else "red"
        return {
            "cluster_name": self.cluster_name,
            "status": status,
            "timed_out": False,
            "number_of_nodes": len(self.nodes),
            "number_of_data_nodes": sum(1 for n in self.nodes.values() if "data" in n.roles),
            "active_primary_shards": sum(1 for r in self.routing if r.primary and r.state == "STARTED"),
            "active_shards": active,
            "relocating_shards": sum(1 for r in self.routing if r.state == "RELOCATING"),
            "initializing_shards": initializing,
            "unassigned_shards": unassigned,
        }

    def to_json(self) -> dict:
        return {
            "cluster_name": self.cluster_name,
            "version": self.version,
            "term": self.term,
            "state_uuid": self.state_uuid,
            "master_node": self.master_node_id,
            "blocks": {k: list(v) for k, v in self.blocks.items() if v},
            "nodes": {
                nid: {"name": n.name, "transport_address": n.transport_address,
                      "roles": list(n.roles)}
                for nid, n in self.nodes.items()
            },
            "metadata": {
                "templates": self.templates,
                "indices": {
                    name: {
                        "state": m.state,
                        "settings": m.settings,
                        "mappings": m.mappings,
                        "aliases": list(m.aliases),
                    }
                    for name, m in self.indices.items()
                },
            },
            "routing_table": {
                "indices": {
                    name: {
                        "shards": {
                            str(r.shard_id): [{
                                "state": r.state, "primary": r.primary,
                                "node": r.node_id, "shard": r.shard_id, "index": r.index,
                            }]
                            for r in self.routing if r.index == name
                        }
                    }
                    for name in self.indices
                }
            },
        }
