"""Cross-host data plane: routed writes + query-then-fetch search actions.

Reference:
- action/search/type/TransportSearchQueryThenFetchAction.java:1-140 — the
  coordinator scatters a query phase to every shard, merges the ranked
  candidates, then fetches ONLY the selected page by search-context id.
- search/action/SearchServiceTransportAction.java:1-120 — the per-node
  wire actions those phases ride.
- action/index/TransportIndexAction.java + routing/OperationRouting —
  writes hash-routed to the shard's owner node.

TPU mapping: WITHIN a process, an index's local shards execute as the
mesh/shard_map product path (parallel/); BETWEEN processes these JSON
transport actions carry query/fetch/write requests the way the reference
rides netty. Per-node query results are small (top-k ids + scores + packed
agg partials — never per-doc columns), so a cross-host search costs one
RTT per phase, not per document.

Shard ownership lives in the master-published index metadata
(`MultiHostCluster.dist_indices`): shard i of an S-shard index is owned by
`sorted(node_ids)[i % world]` at creation time. Every process creates the
full S-shard index locally (mappings and shard numbering must agree with
`cluster/routing.py::shard_id_for` everywhere); only the owned shards ever
hold documents.
"""
from __future__ import annotations

import threading
import time
import uuid
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from elasticsearch_tpu.cluster.routing import shard_id_for
from elasticsearch_tpu.cluster.transport import RemoteException, TransportError
from elasticsearch_tpu.index.seqno import (GlobalCheckpointTracker,
                                           NO_OPS_PERFORMED)
from elasticsearch_tpu.tracing import TaskCancelledException
from elasticsearch_tpu.utils import wire
from elasticsearch_tpu.utils.errors import (
    ElasticsearchTpuException, FailedToCommitClusterStateException,
    IndexNotFoundException, StalePrimaryException)
from elasticsearch_tpu.utils.faults import FAULTS

ACTION_QUERY = "indices:data/read/search[phase/query]"
ACTION_FETCH = "indices:data/read/search[phase/fetch]"
ACTION_FREE = "indices:data/read/search[free_context]"
ACTION_INDEX = "indices:data/write/index"
ACTION_DELETE = "indices:data/write/delete"
ACTION_UPDATE = "indices:data/write/update"
ACTION_GET = "indices:data/read/get"
ACTION_REFRESH = "indices:admin/refresh"
ACTION_CREATE = "indices:admin/create"
ACTION_DELETE_INDEX = "indices:admin/delete"
ACTION_SET_CLOSED = "indices:admin/set_closed"
ACTION_RECOVER = "indices:recovery/start"
ACTION_SHARD_SYNC = "indices:recovery/shard_sync"
ACTION_SHARD_FAILED = "cluster:shard_failed"
ACTION_SHARD_DOCS = "indices:monitor/shard_docs"
ACTION_SNAPSHOT = "cluster:admin/snapshot/create"
ACTION_SNAPSHOT_SHARD = "indices:admin/snapshot/shard"
ACTION_RESTORE = "cluster:admin/snapshot/restore"
ACTION_RESTORE_SHARDS = "indices:admin/snapshot/restore_shards"
ACTION_ALIASES = "indices:admin/aliases"
ACTION_APPLY_GLOBAL = "cluster:admin/apply_global_state"
ACTION_BY_QUERY = "indices:data/write/by_query"
ACTION_REST_PROXY = "internal:rest/proxy"
ACTION_CANCEL_TASKS = "cluster:admin/tasks/cancel"
ACTION_ALLOC_USAGE = "cluster:monitor/allocation/usage"
ACTION_SHARD_CKPT = "indices:monitor/shard_checkpoint"
ACTION_CLUSTER_SETTINGS = "cluster:admin/settings/apply"

_CONTEXT_TTL = 120.0
# coordinator-side cap on one search's scatter+fetch wall time when the
# request body carries no explicit `timeout`
_SEARCH_DEADLINE = 30.0


def shard_failure_entry(index: str, sid: int, exc: Optional[Exception] = None,
                        node: Optional[str] = None,
                        error_type: Optional[str] = None,
                        reason: Optional[str] = None,
                        status: Optional[int] = None) -> dict:
    """One `_shards.failures[]` element, ES-shaped (reference:
    ShardSearchFailure.toXContent): names the shard, the node, the HTTP
    status, and a typed `reason` so clients can distinguish a dead peer
    (connect_transport_error) from a per-shard execution error."""
    if exc is not None:
        error_type = error_type or getattr(exc, "error_type",
                                           type(exc).__name__)
        reason = reason or str(exc)
        status = status or getattr(exc, "status", 500)
    return {"shard": sid, "index": index, "node": node,
            "status": status or 500,
            "reason": {"type": error_type or "exception",
                       "reason": reason or ""}}


def _translog_to_replay(op: dict) -> dict:
    """Translog frame → the replay_op dict shape the recovery stream uses
    (IndexService.replay_op), preserving the (seq_no, term) identity."""
    if op.get("op") == "delete":
        return {"id": op["id"], "deleted": True,
                "version": op.get("version"),
                "seq_no": op.get("seq_no"), "term": op.get("term")}
    return {"id": op["id"], "source": op.get("source"),
            "version": op.get("version"), "type": op.get("doc_type"),
            "parent": op.get("parent"), "routing": op.get("routing"),
            "timestamp": op.get("timestamp"),
            "ttl_expiry": op.get("ttl_expiry"),
            "seq_no": op.get("seq_no"), "term": op.get("term")}


def by_query_task_action(op: str) -> str:
    """ES task action name for a by-query op (reference:
    DeleteByQueryAction.NAME / UpdateByQueryAction.NAME)."""
    return (f"indices:data/write/{op}/byquery" if op in ("delete", "update")
            else f"indices:data/write/{op}")


class DistributedDataService:
    """Per-process endpoint + coordinator for cross-host data operations."""

    def __init__(self, cluster):
        self.cluster = cluster
        self.node = cluster.node
        # search contexts: cid -> {"pairs": [(searcher, ShardDoc)], "born": t}
        self._contexts: Dict[str, dict] = {}
        self._lock = threading.Lock()
        # per-(index, shard) primary write serialization: apply + replica
        # fanout must be one atomic step, or two client threads' fanouts
        # can reach a replica out of version order
        self._write_locks: Dict[Tuple[str, int], threading.Lock] = {}
        # per-(index, shard) global-checkpoint trackers, maintained by the
        # PRIMARY owner from the local checkpoints replicas report on each
        # fanout ack (reference: ReplicationTracker on the primary)
        self._gckpts: Dict[Tuple[str, int], GlobalCheckpointTracker] = {}
        t = cluster.transport
        t.register(ACTION_QUERY, self._on_query)
        t.register(ACTION_FETCH, self._on_fetch)
        t.register(ACTION_FREE, self._on_free)
        t.register(ACTION_INDEX, self._on_index)
        t.register(ACTION_DELETE, self._on_delete)
        t.register(ACTION_UPDATE, self._on_update)
        t.register(ACTION_GET, self._on_get)
        t.register(ACTION_REFRESH, self._on_refresh)
        t.register(ACTION_CREATE, self._on_create)
        t.register(ACTION_DELETE_INDEX, self._on_delete_index)
        t.register(ACTION_SET_CLOSED, self._on_set_closed)
        t.register(ACTION_RECOVER, self._on_recover)
        t.register(ACTION_SHARD_SYNC, self._on_shard_sync)
        t.register(ACTION_SHARD_FAILED, self._on_shard_failed)
        t.register(ACTION_SHARD_DOCS, self._on_shard_docs)
        t.register(ACTION_SNAPSHOT, self._on_snapshot)
        t.register(ACTION_SNAPSHOT_SHARD, self._on_snapshot_shard)
        t.register(ACTION_RESTORE, self._on_restore)
        t.register(ACTION_RESTORE_SHARDS, self._on_restore_shards)
        t.register(ACTION_ALIASES,
                   lambda p: self.node.update_aliases(p["actions"]))
        t.register(ACTION_APPLY_GLOBAL, self._on_apply_global)
        t.register(ACTION_BY_QUERY, self._on_by_query)
        t.register(ACTION_REST_PROXY, self._on_rest_proxy)
        t.register(ACTION_CANCEL_TASKS, self._on_cancel_tasks)
        t.register(ACTION_ALLOC_USAGE, lambda p: self.local_alloc_usage())
        t.register(ACTION_SHARD_CKPT, self._on_shard_ckpt)
        t.register(ACTION_CLUSTER_SETTINGS, self._on_cluster_settings)
        self._proxy_controller = None

    # -- ownership -----------------------------------------------------------

    def resolve_index(self, index: str) -> str:
        """Resolve an alias to its single distributed index: aliases ride
        the published dist metadata (restore attaches them), and every
        process applies them to its local copy on adopt, so resolution
        works on coordinators that own no shard of the target."""
        if index in self.cluster.dist_indices:
            return index
        names = self.node.resolve_indices(index)
        if len(names) == 1 and names[0] in self.cluster.dist_indices:
            return names[0]
        return index

    def _meta(self, index: str) -> dict:
        meta = self.cluster.dist_indices.get(index)
        if meta is None:
            raise IndexNotFoundException(index)
        return meta

    def owner_of(self, index: str, shard_id: int) -> str:
        """Primary owner. assignment maps shard -> [primary, *replicas]."""
        owners = self._meta(index)["assignment"][str(shard_id)]
        if not owners:
            raise TransportError(
                f"[{index}][{shard_id}] has no active copies")
        return owners[0]

    def _local_id(self) -> str:
        return self.cluster.local.node_id

    # -- replication safety ---------------------------------------------------

    @staticmethod
    def _shard_term(meta: dict, sid: int) -> int:
        """The shard's current primary term from the published metadata
        (legacy metas without the key are term 1 — the pre-seqno world)."""
        return int(meta.setdefault("primary_terms", {})
                   .setdefault(str(sid), 1))

    @staticmethod
    def _shard_in_sync(meta: dict, sid: int) -> list:
        """The shard's explicit in-sync copy set. Legacy metas default it
        to the current assignment (every committed copy was fanout-fed)."""
        return meta.setdefault("in_sync", {}).setdefault(
            str(sid), list(meta["assignment"].get(str(sid), [])))

    def _fence_replica_op(self, index: str, sid: int,
                          op_term: Optional[int]) -> None:
        """Replica-side term fence against this node's OWN view of the
        shard's primary term (the master-published metadata): an op from
        a term older than the published one comes from a demoted primary
        that doesn't know it yet. This fences even before the new primary
        has sent a single op (the engine-level fence, which adopts terms
        from op traffic, is the backstop)."""
        if op_term is None:
            return
        meta = self.cluster.dist_indices.get(index)
        if meta is None:
            return
        cur = self._shard_term(meta, sid)
        if op_term < cur:
            raise StalePrimaryException(index, sid, op_term, cur)

    def _checkpoint_tracker(self, index: str, sid: int,
                            meta: dict) -> GlobalCheckpointTracker:
        key = (index, sid)
        with self._lock:
            t = self._gckpts.get(key)
            if t is None:
                t = self._gckpts[key] = GlobalCheckpointTracker()
        t.set_in_sync(self._shard_in_sync(meta, sid))
        return t

    def global_checkpoint(self, index: str, sid: int) -> int:
        with self._lock:
            t = self._gckpts.get((index, sid))
        return t.global_checkpoint if t is not None else NO_OPS_PERFORMED

    def _addr(self, node_id: str) -> Tuple[str, int]:
        n = self.node.cluster_state.nodes.get(node_id)
        if n is None or ":" not in n.transport_address:
            raise TransportError(f"node [{node_id}] has no transport address")
        host, port = n.transport_address.rsplit(":", 1)
        return host, int(port)

    def _send(self, node_id: str, action: str, payload: dict,
              timeout: float = 30.0) -> Any:
        return self.cluster.transport.send_remote(
            self._addr(node_id), action, payload, timeout=timeout)

    def _send_idempotent(self, node_id: str, action: str, payload: dict,
                         timeout: float = 30.0,
                         deadline: Optional[float] = None) -> Any:
        """Retrying send for IDEMPOTENT actions (query/fetch/get):
        transport-level failures back off and retry inside the caller's
        deadline, and the per-peer breaker fast-fails a node that just
        refused repeatedly instead of burning the deadline on it again
        (cluster/transport.py::send_with_retry)."""
        return self.cluster.transport.send_with_retry(
            self._addr(node_id), action, payload, timeout=timeout,
            deadline=deadline)

    # -- admin ---------------------------------------------------------------

    def create_index(self, name: str, body: Optional[dict] = None) -> dict:
        """Create an index with shards assigned round-robin across the
        current members (reference: MetaDataCreateIndexService + the
        allocation pass). Master performs it; others route to the master."""
        self.cluster.ensure_not_blocked("metadata_write")
        if not self.cluster.is_master:
            return self.cluster.transport.send_remote(
                self.cluster.master_addr, ACTION_CREATE,
                {"name": name, "body": body})
        return self._on_create({"name": name, "body": body})

    def _on_create(self, payload: dict) -> dict:
        # forwarded metadata ops re-check on ARRIVAL: a stale view may
        # route to a stepped-down or never-master node — it must fail
        # typed, never execute and publish a state the quorum's master
        # will contradict
        self.cluster.ensure_not_blocked("metadata_write")
        self.cluster._require_master(ACTION_CREATE)
        name, body = payload["name"], payload.get("body") or {}
        with self.cluster._indices_lock:
            if name in self.cluster.dist_indices:
                # re-creating would recompute the assignment over the
                # CURRENT membership and orphan every doc routed under the
                # old one
                from elasticsearch_tpu.utils.errors import \
                    IndexAlreadyExistsException

                raise IndexAlreadyExistsException(name)
            nodes = sorted(self.node.cluster_state.nodes)
            settings = dict(body.get("settings") or {})
            num_shards = int(settings.get("number_of_shards", 1))
            # number_of_replicas means CROSS-HOST copies here: the
            # declared count STAYS in the settings (echo, _shards math)
            # while the internal _local_replicas=0 marker stops each
            # process from also materializing in-process replica groups
            replicas = int(settings.get("number_of_replicas", 0))
            settings["_local_replicas"] = 0
            local_body = dict(body)
            local_body["settings"] = settings
            assignment = {}
            for i in range(num_shards):
                owners = [nodes[i % len(nodes)]]
                for r in range(1, replicas + 1):
                    cand = nodes[(i + r) % len(nodes)]
                    if cand not in owners:
                        owners.append(cand)
                assignment[str(i)] = owners
            if payload.get("pending"):
                # restore path: every copy starts INITIALIZING (not
                # searchable, not a write target) and graduates into the
                # assignment only when its replay succeeds — the
                # reference's SNAPSHOT recovery source keeps restoring
                # shards in INITIALIZING the same way
                meta = {"body": local_body, "num_shards": num_shards,
                        "replicas": replicas,
                        "assignment": {str(i): [] for i in range(num_shards)},
                        "initializing": {k: list(v)
                                         for k, v in assignment.items()},
                        "primary_terms": {str(i): 1
                                          for i in range(num_shards)},
                        "in_sync": {str(i): [] for i in range(num_shards)}}
            else:
                meta = {"body": local_body, "num_shards": num_shards,
                        "replicas": replicas, "assignment": assignment,
                        # copies being recovered: visible for write fanout
                        # (they must see live writes during the copy), NOT
                        # promotable or searchable until recovery succeeds
                        # — the reference's INITIALIZING shard state
                        "initializing": {},
                        # replication safety: per-shard primary terms and
                        # the explicit in-sync copy set promotion selects
                        # from (index/seqno.py; reference: primaryTerm in
                        # IndexMetaData + in-sync allocation ids)
                        "primary_terms": {str(i): 1
                                          for i in range(num_shards)},
                        "in_sync": {k: list(v)
                                    for k, v in assignment.items()}}
            self.cluster.dist_indices[name] = meta
            created_local = not self.node.index_exists(name)
            if created_local:
                self.node.create_index(name, local_body)
        try:
            self.cluster.publish_indices()
        except Exception:
            # the metadata change never committed (no publish quorum —
            # the master just stepped down): ROLL BACK the local half so
            # this node holds no index the majority will never know
            # about, then fail the client op typed
            with self.cluster._indices_lock:
                self.cluster.dist_indices.pop(name, None)
                if created_local and self.node.index_exists(name):
                    try:
                        self.node._delete_local_index(name)
                    except Exception:  # tpulint: allow[R006] — rollback
                        pass           # is best-effort; the typed 503
                        # below is the authoritative outcome
                # the pre-publish persist already wrote the index to
                # dist_indices.json — re-persist the rolled-back map or
                # a master restart resurrects an index the client was
                # told (503) never committed
                self.cluster._persist_dist_meta()
            raise
        return {"acknowledged": True, "index": name,
                "assignment": assignment, "local_body": local_body}

    def set_closed(self, name: str, closed: bool) -> dict:
        """Mark a distributed index open/closed in the published metadata
        (reference: MetaDataIndexStateService — open/close is cluster
        state, not a node-local flag). Peers apply it on adopt."""
        self.cluster.ensure_not_blocked("metadata_write")
        if not self.cluster.is_master:
            return self.cluster.transport.send_remote(
                self.cluster.master_addr, ACTION_SET_CLOSED,
                {"name": name, "closed": closed})
        return self._on_set_closed({"name": name, "closed": closed})

    def _on_set_closed(self, payload: dict) -> dict:
        # forwarded metadata ops re-check on ARRIVAL: a stale view may
        # route to a stepped-down or never-master node — it must fail
        # typed, never execute and publish a state the quorum's master
        # will contradict
        self.cluster.ensure_not_blocked("metadata_write")
        self.cluster._require_master(ACTION_SET_CLOSED)
        from elasticsearch_tpu.cluster.metadata import (close_index,
                                                        open_index)

        name, closed = payload["name"], payload["closed"]
        with self.cluster._indices_lock:
            meta = self.cluster.dist_indices.get(name)
            prior = None if meta is None else meta.get("closed")
            if meta is not None:
                meta["closed"] = bool(closed)
            had_local = self.node.index_exists(name)
            if had_local:
                (close_index if closed else open_index)(self.node, name)
        try:
            self.cluster.publish_indices()
        except Exception:
            # not committed: revert both halves (metadata flag + local
            # open/close) so this node doesn't diverge from the state
            # the quorum's master will republish
            with self.cluster._indices_lock:
                if meta is not None:
                    if prior is None:
                        meta.pop("closed", None)
                    else:
                        meta["closed"] = prior
                if had_local:
                    (close_index if prior else open_index)(self.node,
                                                           name)
                self.cluster._persist_dist_meta()
            raise
        return {"acknowledged": True}

    def delete_index(self, name: str) -> dict:
        """Delete a distributed index CLUSTER-WIDE: the master drops it
        from the published metadata (peers remove their local copies on
        the next publish — bootstrap._adopt_indices) and deletes its own
        copy. Reference: MetaDataDeleteIndexService. Without this, a
        local-only delete left the metadata alive and the next publish
        resurrected the index on every peer."""
        self.cluster.ensure_not_blocked("metadata_write")
        if not self.cluster.is_master:
            return self.cluster.transport.send_remote(
                self.cluster.master_addr, ACTION_DELETE_INDEX,
                {"name": name})
        return self._on_delete_index({"name": name})

    def _on_delete_index(self, payload: dict) -> dict:
        # forwarded metadata ops re-check on ARRIVAL: a stale view may
        # route to a stepped-down or never-master node — it must fail
        # typed, never execute and publish a state the quorum's master
        # will contradict
        self.cluster.ensure_not_blocked("metadata_write")
        self.cluster._require_master(ACTION_DELETE_INDEX)
        name = payload["name"]
        with self.cluster._indices_lock:
            prior = self.cluster.dist_indices.pop(name, None)
        try:
            self.cluster.publish_indices()
        except Exception:
            # the delete never committed (no publish quorum — the master
            # stepped down): restore the metadata and KEEP the local
            # shard data; destroying it before the quorum gate would
            # leave this node dataless for an index the majority still
            # serves, after telling the client 503 "not committed"
            with self.cluster._indices_lock:
                if prior is not None \
                        and name not in self.cluster.dist_indices:
                    self.cluster.dist_indices[name] = prior
                self.cluster._persist_dist_meta()
            raise
        with self.cluster._indices_lock:
            if self.node.index_exists(name):
                # bypass Node.delete_index's dist routing (we ARE it);
                # destruction happens only AFTER the quorum committed
                self.node._delete_local_index(name)
        return {"acknowledged": True}

    def refresh(self, index: str) -> None:
        index = self.resolve_index(index)
        self._meta(index)
        self.node.indices[index].refresh()
        errs = []
        for nid in self._other_nodes():
            try:
                self._send(nid, ACTION_REFRESH, {"index": index})
            except Exception as e:
                # keep going: one dead peer must not leave LATER peers
                # unrefreshed (a snapshot would then capture them stale
                # while counting their shards successful)
                errs.append(nid)
                last = e
        if errs:
            raise TransportError(
                f"refresh of [{index}] failed on {errs}: {last}")

    def _other_nodes(self) -> List[str]:
        me = self._local_id()
        return [nid for nid, n in
                sorted(self.node.cluster_state.nodes.items())
                if nid != me and ":" in n.transport_address]

    def _on_refresh(self, payload: dict) -> dict:
        self.node.indices[payload["index"]].refresh()
        return {"ok": True}

    # -- distributed snapshot / restore --------------------------------------

    def create_snapshot(self, location: str, snap_name: str,
                        indices: Optional[List[str]] = None,
                        include_global_state: bool = True,
                        repo_name: str = "_snapshot") -> dict:
        """Snapshot distributed indices into a SHARED filesystem repository:
        the master assembles the manifest, each shard's primary owner
        writes that shard's blobs itself (reference:
        snapshots/SnapshotsService.java — master drives the snapshot
        cluster-state machine; SnapshotShardsService on each data node
        writes its own shard files to the repository)."""
        payload = {"location": location, "snapshot": snap_name,
                   "indices": indices, "repo_name": repo_name,
                   "include_global_state": include_global_state}
        if not self.cluster.is_master:
            return self.cluster.transport.send_remote(
                self.cluster.master_addr, ACTION_SNAPSHOT, payload,
                timeout=300.0)
        return self._on_snapshot(payload)

    def _on_snapshot(self, payload: dict) -> dict:
        """Master: assemble the manifest via the shared create_snapshot,
        with a shard writer that fans each distributed index's shards out
        to their primary owners (one batched RPC per owner). A failed
        owner RPC records its shards failed and the snapshot PARTIAL —
        same accounting local shard failures already get."""
        from elasticsearch_tpu.index.snapshots import (FsRepository,
                                                       _local_shards_meta,
                                                       create_snapshot,
                                                       snapshot_shard)

        repo = FsRepository(payload.get("repo_name") or "_snapshot",
                            payload["location"])

        def shards_fn(iname: str, svc) -> dict:
            meta = self.cluster.dist_indices.get(iname)
            if meta is None:  # a master-local (non-distributed) index
                return _local_shards_meta(repo, svc)
            try:
                self.refresh(iname)  # refresh-consistent view everywhere
            except Exception:
                # a dead peer must degrade to PARTIAL below, not abort the
                # whole snapshot; local copies refreshed before the raise
                pass
            shards_meta: List[Optional[dict]] = [None] * meta["num_shards"]
            failed = 0
            by_owner: Dict[str, List[int]] = {}
            for sid in range(meta["num_shards"]):
                try:
                    owner = self.owner_of(iname, sid)
                except Exception:
                    # no active copies (mid-recovery / lost shard): a
                    # failed snapshot shard, same as a dead owner's
                    failed += 1
                    shards_meta[sid] = {"blobs": [], "versions": {},
                                        "failed": True}
                    continue
                by_owner.setdefault(owner, []).append(sid)
            for owner, sids in sorted(by_owner.items()):
                try:
                    if owner == self._local_id():
                        got = [snapshot_shard(repo, svc.shards[sid])
                               for sid in sids]
                    else:
                        got = self._send(
                            owner, ACTION_SNAPSHOT_SHARD,
                            {"location": payload["location"],
                             "repo_name": repo.name,
                             "index": iname, "shards": sids}, timeout=300.0)
                    for sid, m in zip(sids, got):
                        shards_meta[sid] = m
                except Exception:
                    failed += len(sids)
                    for sid in sids:
                        shards_meta[sid] = {"blobs": [], "versions": {},
                                            "failed": True}
            # the manifest must round-trip the CROSS-HOST replica count:
            # _on_create pops number_of_replicas out of the local settings,
            # so svc.settings alone would restore with zero redundancy
            settings = dict(svc.settings)
            if meta.get("replicas"):
                settings["number_of_replicas"] = meta["replicas"]
            return {"shards": shards_meta, "failed": failed,
                    "settings": settings}

        indices = payload.get("indices")
        if indices is None:
            indices = sorted(set(self.node.indices)
                             | set(self.cluster.dist_indices))
        return create_snapshot(
            self.node, repo, payload["snapshot"], indices=indices,
            include_global_state=payload.get("include_global_state", True),
            shards_fn=shards_fn)

    def _on_snapshot_shard(self, payload: dict) -> List[dict]:
        """Shard owner: write the requested shards' blobs into the shared
        repo; one batched call per owner process."""
        from elasticsearch_tpu.index.snapshots import (FsRepository,
                                                       snapshot_shard)

        repo = FsRepository(payload.get("repo_name") or "_snapshot",
                            payload["location"])
        svc = self.node.indices[payload["index"]]
        # self-contained freshness: the coordinator's refresh fan-out may
        # have failed for this peer without aborting the snapshot
        svc.refresh()
        return [snapshot_shard(repo, svc.shards[sid])
                for sid in payload["shards"]]

    def restore_snapshot(self, location: str, snap_name: str,
                         indices: Optional[List[str]] = None,
                         rename_pattern: Optional[str] = None,
                         rename_replacement: Optional[str] = None,
                         partial: bool = False,
                         repo_name: str = "_snapshot") -> dict:
        """Restore a snapshot INTO the multi-host cluster: the master
        computes a fresh cross-host shard assignment for each restored
        index, then every assigned copy replays its shard's blobs from the
        shared repository (reference: snapshots/RestoreService.java:1-120 —
        the master creates restore routing with a SNAPSHOT recovery
        source; each data node recovers its shards from the repo)."""
        self.cluster.ensure_not_blocked("metadata_write")
        payload = {"location": location, "snapshot": snap_name,
                   "indices": indices, "rename_pattern": rename_pattern,
                   "rename_replacement": rename_replacement,
                   "partial": partial, "repo_name": repo_name}
        if not self.cluster.is_master:
            return self.cluster.transport.send_remote(
                self.cluster.master_addr, ACTION_RESTORE, payload,
                timeout=300.0)
        return self._on_restore(payload)

    def _on_restore(self, payload: dict) -> dict:
        from elasticsearch_tpu.index.snapshots import FsRepository, \
            select_restore_targets

        # restore only READS the repository — never mkdir its location
        # (a url repo's location is not a local path at all)
        repo = FsRepository(payload.get("repo_name") or "_snapshot",
                            payload["location"], create=False)
        snap = payload["snapshot"]
        manifest = repo.get_manifest(snap)
        indices = payload.get("indices")
        # validate EVERY target before touching any index — a collision on
        # index B must not leave index A half-restored (shared with the
        # single-node path; the extra `exists` covers dist_indices)
        selected = select_restore_targets(
            self.node, manifest, indices, payload.get("rename_pattern"),
            payload.get("rename_replacement"),
            bool(payload.get("partial")),
            exists=lambda t: t in self.cluster.dist_indices)
        restored: List[str] = []
        total = failed = 0
        for iname, target, imeta in selected:
            num_shards = len(imeta["shards"])
            total += num_shards
            settings = dict(imeta.get("settings") or {})
            settings["number_of_shards"] = num_shards
            body = {"settings": settings, "mappings": imeta["mappings"]}
            # copies start INITIALIZING (not searchable/writable) and
            # graduate per-owner as their replays succeed — a client must
            # never see a half-replayed shard as active, and a concurrent
            # write racing the replay's external-version replay is
            # impossible because no primary exists yet
            res = self._on_create({"name": target, "body": body,
                                   "pending": True})
            desired = res["assignment"]
            aliases = imeta.get("aliases", {})
            if aliases:
                # aliases ride the published metadata so EVERY process
                # (owners and pure coordinators) can resolve them; the
                # master applies its local copy here, peers in
                # _adopt_indices on the next publish
                with self.cluster._indices_lock:
                    self.cluster.dist_indices[target]["aliases"] = aliases
                self.node.indices[target].aliases.update(aliases)
            by_owner: Dict[str, List[int]] = {}
            for sid in range(num_shards):
                for owner in desired[str(sid)]:
                    by_owner.setdefault(owner, []).append(sid)
            ok: Dict[int, set] = {sid: set() for sid in range(num_shards)}
            for owner, sids in sorted(by_owner.items()):
                sp = {"location": payload["location"],
                      "repo_name": repo.name, "snapshot": snap,
                      "src": iname, "target": target, "shards": sids,
                      "aliases": aliases, "body": res["local_body"]}
                try:
                    if owner == self._local_id():
                        self._on_restore_shards(sp)
                    else:
                        self._send(owner, ACTION_RESTORE_SHARDS, sp,
                                   timeout=300.0)
                    for sid in sids:
                        ok[sid].add(owner)
                except Exception:
                    pass  # copy stays out of the active assignment
            with self.cluster._indices_lock:
                meta = self.cluster.dist_indices[target]
                init = meta.setdefault("initializing", {})
                for sid in range(num_shards):
                    live = [o for o in desired[str(sid)] if o in ok[sid]]
                    meta["assignment"][str(sid)] = live
                    init[str(sid)] = []
                    if not live or imeta["shards"][sid].get("failed"):
                        # every copy's replay failed, or the shard's blobs
                        # were missing from a PARTIAL manifest (it came
                        # back active but EMPTY): a failed restore shard,
                        # same accounting as the single-node path
                        failed += 1
            try:
                self.cluster.publish_indices()
            except Exception:
                # the restore target never committed (publish lost
                # quorum — the master stepped down): back the working
                # metadata out like create does, so a stepped-down node
                # holds no restored index the majority never saw, and
                # fail the restore typed (already-published targets in
                # `restored` stay — they committed)
                with self.cluster._indices_lock:
                    self.cluster.dist_indices.pop(target, None)
                    self.cluster._persist_dist_meta()
                raise
            restored.append(target)
        from elasticsearch_tpu.index.snapshots import apply_global_state

        apply_global_state(self.node, manifest, indices)
        global_failed: List[str] = []
        if "global_state" in manifest and not indices:
            # templates are node-local state the publish doesn't carry:
            # fan the restored global state to every peer so a template
            # lookup works on whichever coordinator the client hits. A
            # failed peer is REPORTED (a transiently-unreachable peer
            # would otherwise silently miss the templates forever)
            gp = {"global_state": manifest["global_state"]}
            for nid in self._other_nodes():
                try:
                    self._send(nid, ACTION_APPLY_GLOBAL, gp)
                except Exception:
                    global_failed.append(nid)
        resp = {"snapshot": {"snapshot": snap, "indices": restored,
                             "shards": {"total": total, "failed": failed,
                                        "successful": total - failed}}}
        if global_failed:
            resp["snapshot"]["global_state_failed_nodes"] = global_failed
        return resp

    def _on_apply_global(self, payload: dict) -> dict:
        from elasticsearch_tpu.index.snapshots import apply_global_state

        apply_global_state(self.node, payload, None)
        return {"ok": True}

    def _on_restore_shards(self, payload: dict) -> dict:
        """Restore target: replay the assigned shards' blobs from the
        shared repository into the local index copy. The index may not
        exist locally yet when this races the metadata publish."""
        from elasticsearch_tpu.index.snapshots import (FsRepository,
                                                       replay_shard)

        index = payload["target"]
        with self.cluster._indices_lock:
            if not self.node.index_exists(index):
                self.node.create_index(index, payload.get("body"))
        svc = self.node.indices[index]
        # read-side handle: restore never writes, so never mkdir
        repo = FsRepository(payload.get("repo_name") or "_snapshot",
                            payload["location"], create=False)
        imeta = repo.get_manifest(payload["snapshot"])["indices"][
            payload["src"]]
        for sid in payload["shards"]:
            replay_shard(svc, repo, imeta, sid)
        svc.aliases.update(payload.get("aliases") or {})
        svc.refresh()
        return {"ok": True, "shards": payload["shards"]}

    # -- routed writes / reads ----------------------------------------------

    def index_doc(self, index: str, doc_id: Optional[str], source: dict,
                  routing: Optional[str] = None, **kw) -> dict:
        # NO_MASTER write block: a headless (minority / stepped-down)
        # node must fail writes typed 503, never route them into a state
        # the quorum's master will not have (searches stay unblocked)
        self.cluster.ensure_not_blocked("write")
        index = self.resolve_index(index)
        meta = self._meta(index)
        if doc_id is None:
            doc_id = uuid.uuid4().hex  # route on the final id, as the owner will
        sid = shard_id_for(doc_id, meta["num_shards"], routing)
        owner = self.owner_of(index, sid)
        if owner == self._local_id():
            return self._primary_write("index", index, sid, doc_id, source,
                                       routing, kw)
        return self._send(owner, ACTION_INDEX,
                          {"index": index, "id": doc_id, "source": source,
                           "routing": routing, "kw": kw})

    def _write_lock(self, index: str, sid: int) -> threading.Lock:
        with self._lock:
            return self._write_locks.setdefault((index, sid),
                                                threading.Lock())

    def _ensure_primary(self, op: str, index: str, sid: int,
                        payload: dict, forwarded: bool) -> Optional[dict]:
        """A write landed here but THIS node's published metadata names a
        different primary: the sender routed on stale state (or this node
        was just demoted). Applying locally would ack under the new term
        without the real primary ever seeing the op — acked-op loss — so
        forward ONE hop to the owner this node believes in (reference:
        TransportReplicationAction rerouting on stale routing). A write
        that was already forwarded and still finds no agreement fails
        typed instead of ping-ponging."""
        meta = self._meta(index)
        owners = meta["assignment"].get(str(sid), [])
        if not owners or owners[0] == self._local_id():
            return None  # we are the primary (or the shard is lost —
            # owner_of raises on the read side; writes fail below anyway)
        if forwarded:
            raise StalePrimaryException(index, sid,
                                        self._shard_term(meta, sid),
                                        self._shard_term(meta, sid))
        fwd = dict(payload)
        fwd["forwarded"] = True
        action = {"index": ACTION_INDEX, "delete": ACTION_DELETE,
                  "update": ACTION_UPDATE}[op]
        return self._send(owners[0], action, fwd)

    def _primary_write(self, op: str, index: str, sid: int, doc_id: str,
                       source: Optional[dict], routing: Optional[str],
                       kw: dict, forwarded: bool = False) -> dict:
        """Apply on the primary, then fan out to every cross-host copy —
        committed replicas AND initializing (recovering) ones — with the
        primary-assigned version (external_gte keeps replica replay
        idempotent and ordered — the reference's
        TransportShardReplicationOperationAction primary → replicas hop).
        The per-shard lock makes apply+fanout atomic so two client
        threads' fanouts cannot reach a replica out of version order."""
        # also fences writes FORWARDED to a headless node on stale routing
        self.cluster.ensure_not_blocked("write")
        rerouted = self._ensure_primary(
            op, index, sid,
            {"index": index, "id": doc_id, "source": source,
             "routing": routing, "kw": kw}, forwarded)
        if rerouted is not None:
            return rerouted
        svc = self.node.indices[index]
        with self._write_lock(index, sid):
            meta = self._meta(index)
            # stamp the op with THIS node's published view of the shard's
            # primary term; if a newer term already reached the local
            # engine (a recovery stream from the real primary), the
            # engine-level fence rejects right here — before any fanout
            term = self._shard_term(meta, sid)
            kw = dict(kw)
            kw["primary_term"] = term
            if op == "index":
                res = svc.index_doc(doc_id, source, routing=routing, **kw)
            else:
                res = svc.delete_doc(doc_id, routing=routing, **kw)
            tracker = self._checkpoint_tracker(index, sid, meta)
            tracker.update_local(
                self._local_id(),
                svc.shards[sid].engine.local_checkpoint)
            rep_kw = dict(kw)
            rep_kw.update(version=res["_version"],
                          version_type="external_gte",
                          seq_no=res.get("_seq_no"), primary_term=term)
            action = ACTION_INDEX if op == "index" else ACTION_DELETE
            copies = (meta["assignment"][str(sid)][1:]
                      + meta.get("initializing", {}).get(str(sid), []))
            for rep in copies:
                if rep == self._local_id():
                    continue
                try:
                    FAULTS.check("replication.fanout", index=index,
                                 shard=sid, target=rep, op=op)
                    r = self._send(rep, action,
                                   {"index": index, "id": doc_id,
                                    "source": source, "routing": routing,
                                    "kw": rep_kw, "replica": True})
                    if isinstance(r, dict) and "local_checkpoint" in r:
                        tracker.update_local(rep, r["local_checkpoint"])
                except RemoteException as e:
                    if e.error_type == "stale_primary_exception":
                        # the REPLICA is fine — THIS primary was demoted
                        # and doesn't know it: never ack the write, never
                        # demote the copy that fenced us (the zombie-
                        # primary window closes here). The typed 409
                        # relays as-is.
                        raise
                    self._report_copy_failed(index, sid, rep)
                except Exception:
                    # a copy that missed an acknowledged write must stop
                    # being promotable — report it failed so the master
                    # demotes it and re-syncs via the recovery stream
                    # (reference: ShardStateAction.shardFailed on a failed
                    # replication hop)
                    self._report_copy_failed(index, sid, rep)
        res["_global_checkpoint"] = tracker.global_checkpoint
        return res

    def _report_copy_failed(self, index: str, sid: int,
                            node_id: str) -> None:
        payload = {"index": index, "shard": sid, "node": node_id}
        try:
            if self.cluster.is_master:
                self._on_shard_failed(payload)
            else:
                self.cluster.transport.send_remote(
                    self.cluster.master_addr, ACTION_SHARD_FAILED,
                    payload, timeout=5.0)
        except Exception:
            pass  # master unreachable: fault detection is already dying

    def _on_shard_failed(self, payload: dict) -> dict:
        """Master: drop a failed REPLICA copy from the promotable set and
        schedule a re-sync (primary failure is fault detection's job)."""
        if not self.cluster.is_master:
            raise TransportError("shard_failed must go to the master")
        index, sid = payload["index"], payload["shard"]
        node_id = payload["node"]
        directive = None
        with self.cluster._indices_lock:
            meta = self.cluster.dist_indices.get(index)
            if meta is None:
                return {"ok": False}
            owners = meta["assignment"].get(str(sid), [])
            if node_id not in owners or owners[0] == node_id:
                return {"ok": False}
            owners.remove(node_id)
            # the copy missed an acknowledged write: it leaves the
            # in-sync set until its re-sync stream completes
            insync = self._shard_in_sync(meta, sid)
            if node_id in insync:
                insync.remove(node_id)
            if owners and node_id in self.node.cluster_state.nodes:
                # back through INITIALIZING so live writes keep fanning
                # out to it while the re-sync stream runs
                pend = meta.setdefault("initializing", {}) \
                    .setdefault(str(sid), [])
                if node_id not in pend:
                    pend.append(node_id)
                directive = {"index": index, "shard": sid,
                             "target": node_id, "source": owners[0],
                             "body": meta["body"]}
        try:
            self.cluster.publish_indices()
        except FailedToCommitClusterStateException:
            # the master just lost publish quorum and stepped down; the
            # in-sync shrink is conservative (it only REMOVES a failed
            # copy) and the quorum's master redoes allocation — the
            # REPORTER must not receive a publish error for a failure
            # report it delivered successfully
            return {"ok": False}
        if directive:
            self.start_recoveries([directive])
        return {"ok": True}

    def _on_index(self, payload: dict) -> dict:
        index, doc_id = payload["index"], payload["id"]
        routing = payload.get("routing")
        if payload.get("replica"):
            kw = payload.get("kw") or {}
            sid = shard_id_for(doc_id, self._meta(index)["num_shards"],
                               routing)
            self._fence_replica_op(index, sid, kw.get("primary_term"))
            res = self.node.indices[index].index_doc(
                doc_id, payload["source"], routing=routing, **kw)
            # the ack reports this copy's local checkpoint so the primary
            # can advance the shard's global checkpoint
            res["local_checkpoint"] = self.node.indices[index] \
                .shards[sid].engine.local_checkpoint
            return res
        sid = shard_id_for(doc_id, self._meta(index)["num_shards"], routing)
        return self._primary_write("index", index, sid, doc_id,
                                   payload["source"], routing,
                                   payload.get("kw") or {},
                                   forwarded=bool(payload.get("forwarded")))

    def delete_doc(self, index: str, doc_id: str,
                   routing: Optional[str] = None, **kw) -> dict:
        self.cluster.ensure_not_blocked("write")
        index = self.resolve_index(index)
        meta = self._meta(index)
        sid = shard_id_for(doc_id, meta["num_shards"], routing)
        owner = self.owner_of(index, sid)
        if owner == self._local_id():
            return self._primary_write("delete", index, sid, doc_id, None,
                                       routing, kw)
        return self._send(owner, ACTION_DELETE,
                          {"index": index, "id": doc_id, "routing": routing,
                           "kw": kw})

    def update_doc(self, index: str, doc_id: str, body: dict,
                   routing: Optional[str] = None, **kw) -> dict:
        """Routed partial update: executes ON the primary owner (the merge
        must read the current source there), which then fans the resulting
        full doc out through the normal replica hop (reference:
        TransportUpdateAction resolving to an index op on the primary)."""
        self.cluster.ensure_not_blocked("write")
        index = self.resolve_index(index)
        meta = self._meta(index)
        sid = shard_id_for(doc_id, meta["num_shards"], routing)
        owner = self.owner_of(index, sid)
        if owner == self._local_id():
            return self._primary_update(index, sid, doc_id, body, routing,
                                        kw)
        return self._send(owner, ACTION_UPDATE,
                          {"index": index, "id": doc_id, "body": body,
                           "routing": routing, "kw": kw})

    def _primary_update(self, index: str, sid: int, doc_id: str,
                        body: dict, routing: Optional[str],
                        kw: dict, forwarded: bool = False) -> dict:
        self.cluster.ensure_not_blocked("write")
        rerouted = self._ensure_primary(
            "update", index, sid,
            {"index": index, "id": doc_id, "body": body,
             "routing": routing, "kw": kw}, forwarded)
        if rerouted is not None:
            return rerouted
        svc = self.node.indices[index]
        with self._write_lock(index, sid):
            meta = self._meta(index)
            term = self._shard_term(meta, sid)
            # the published term rides into the engine like any primary
            # write: a demoted node whose engine already adopted a newer
            # term (via a recovery stream) fences HERE instead of acking
            # an update its replacement never sees
            kw = dict(kw)
            kw["primary_term"] = term
            res = svc.update_doc(doc_id, body, routing=routing, **kw)
            got = svc.get_doc(doc_id, routing=routing)
            copies = (meta["assignment"][str(sid)][1:]
                      + meta.get("initializing", {}).get(str(sid), []))
            if got.get("found"):
                # the merged doc's engine-assigned (seq_no, term) identity
                # rides the fanout like any primary write
                loc = svc.shards[sid].engine._locations.get(str(doc_id))
                rep_kw = {"version": res["_version"],
                          "version_type": "external_gte",
                          "seq_no": loc.seq_no if loc else None,
                          "primary_term": loc.term if loc else term}
                for rep in copies:
                    if rep == self._local_id():
                        continue
                    try:
                        FAULTS.check("replication.fanout", index=index,
                                     shard=sid, target=rep, op="update")
                        self._send(rep, ACTION_INDEX,
                                   {"index": index, "id": doc_id,
                                    "source": got["_source"],
                                    "routing": routing, "kw": rep_kw,
                                    "replica": True})
                    except RemoteException as e:
                        if e.error_type == "stale_primary_exception":
                            raise  # demoted primary: never ack
                        self._report_copy_failed(index, sid, rep)
                    except Exception:
                        self._report_copy_failed(index, sid, rep)
        return res

    def _on_update(self, payload: dict) -> dict:
        index, doc_id = payload["index"], payload["id"]
        routing = payload.get("routing")
        sid = shard_id_for(doc_id, self._meta(index)["num_shards"], routing)
        return self._primary_update(index, sid, doc_id, payload["body"],
                                    routing, payload.get("kw") or {},
                                    forwarded=bool(payload.get("forwarded")))

    def _on_delete(self, payload: dict) -> dict:
        index, doc_id = payload["index"], payload["id"]
        routing = payload.get("routing")
        if payload.get("replica"):
            from elasticsearch_tpu.utils.errors import \
                DocumentMissingException

            kw = payload.get("kw") or {}
            sid = shard_id_for(doc_id, self._meta(index)["num_shards"],
                               routing)
            self._fence_replica_op(index, sid, kw.get("primary_term"))
            eng = self.node.indices[index].shards[sid].engine
            try:
                res = self.node.indices[index].delete_doc(
                    doc_id, routing=routing, **kw)
            except DocumentMissingException:
                # a delete for a doc this copy never saw (e.g. it raced the
                # recovery snapshot): per-shard fanout ordering plus the
                # tombstones shipped by _on_shard_sync make skipping safe —
                # but the op's seq no is still processed (no-op), or this
                # copy's checkpoint stalls on the hole
                eng.note_noop(kw.get("seq_no"), kw.get("primary_term"))
                return {"found": False, "_id": doc_id,
                        "local_checkpoint": eng.local_checkpoint}
            res["local_checkpoint"] = eng.local_checkpoint
            return res
        sid = shard_id_for(doc_id, self._meta(index)["num_shards"], routing)
        return self._primary_write("delete", index, sid, doc_id, None,
                                   routing, payload.get("kw") or {},
                                   forwarded=bool(payload.get("forwarded")))

    def by_query(self, index: str, body: Optional[dict], op: str,
                 script=None, params=None) -> dict:
        """Distributed delete/update-by-query: fan one scan+apply pass to
        each PRIMARY owner for its shards, merge counts. Reference:
        AbstractAsyncBulkByScrollAction (scroll-driven scan + bulk), here
        scoped per owner so every apply runs on the doc's primary and
        fans to replicas through the ordinary write hop.

        Runs as a CANCELLABLE task: each remote owner's pass registers a
        child task (the wire header carries the parent id), so ``POST
        /_tasks/{this}/_cancel`` reaches the remote scans too; a
        cancellation mid-fanout returns the PARTIAL counts applied so
        far with a ``"canceled"`` reason, the reference's
        BulkByScrollResponse shape."""
        self.cluster.ensure_not_blocked("write")
        index = self.resolve_index(index)
        meta = self._meta(index)
        self.refresh(index)
        by_owner: Dict[str, List[int]] = {}
        out: Dict[str, Any] = {"took": 0, "total": 0, "failures": [],
                               "timed_out": False}
        for sid in range(meta["num_shards"]):
            owners = meta["assignment"][str(sid)]
            if owners:
                by_owner.setdefault(owners[0], []).append(sid)
            else:
                # a shard with no active copies (mid-reheal) must SURFACE
                # as a failure, not silently under-delete — single-doc
                # writes in the same state raise 'no active copies'
                out["failures"].append({
                    "index": index, "shard": sid,
                    "status": 503,
                    "cause": {"type": "unavailable_shards_exception",
                              "reason": f"[{index}][{sid}] has no active "
                                        f"copies"}})
        deleted = updated = noops = 0
        action = by_query_task_action(op)
        t0 = time.perf_counter()
        with self.node.tasks.task(action,
                                  description=f"{op}-by-query [{index}]") \
                as task:
            try:
                for owner, sids in sorted(by_owner.items()):
                    # cooperative checkpoint BETWEEN owners: a cancel
                    # must stop the fanout before the next destructive
                    # pass starts (the in-flight owner stops itself at
                    # its own checkpoints)
                    task.check_cancelled()
                    payload = {"index": index,
                               "query": (body or {}).get("query"),
                               "op": op, "shards": sids, "script": script,
                               "params": params}
                    try:
                        if owner == self._local_id():
                            res = self._on_by_query(payload)
                        else:
                            res = self._send(owner, ACTION_BY_QUERY,
                                             payload, timeout=300.0)
                    except Exception as e:
                        # a dead owner after earlier owners already applied
                        # destructive writes: report ITS shards failed — the
                        # caller must see partial success, not a bare 500
                        out["failures"].extend({
                            "index": index, "shard": sid, "node": owner,
                            "status": 503,
                            "cause": {"type": "node_unavailable",
                                      "reason": str(e)}} for sid in sids)
                        continue
                    deleted += res.get("deleted", 0)
                    updated += res.get("updated", 0)
                    noops += res.get("noops", 0)
                    out["total"] += res.get("total", 0)
                    out["failures"].extend(res.get("failures", []))
                    if res.get("canceled"):
                        # an owner's pass was cancelled — cascade cancel
                        # reached it first, or an operator cancelled the
                        # CHILD directly. Either way the operation is
                        # over: stop the fanout NOW (remaining owners
                        # must not run their destructive passes under a
                        # response that claims cancellation) and report
                        # whatever was applied
                        out["canceled"] = res["canceled"]
                        task.cancel(res["canceled"])
                        break
            except TaskCancelledException as e:
                out["canceled"] = str(e)
        try:
            self.refresh(index)
        except Exception:
            pass  # a dead peer is already in failures; keep the response
        if op == "delete":
            out["deleted"] = deleted
        else:
            out["updated"] = updated
            out["noops"] = noops
        out["took"] = int((time.perf_counter() - t0) * 1000)
        return out

    def _on_by_query(self, payload: dict) -> dict:
        """Owner-side by-query pass, restricted to the PRIMARY shards this
        process owns (the local index also holds replica copies of remote
        primaries — touching those here would race their owners). The
        scan loop is SHARED with the single-node REST actions
        (search/byquery.py); every apply goes through
        _primary_write/_primary_update so replicas stay in version
        order.

        Registers a CHILD task (parent = the coordinator's task, carried
        by the transport wire header): cancelling the coordinator
        cascades here, and the scan loop's cooperative checkpoints
        (search/byquery.py) stop the pass between docs — the partial
        counts applied so far return with ``"canceled"``."""
        from elasticsearch_tpu.search.byquery import (failure_entry,
                                                      run_by_query)
        from elasticsearch_tpu.utils.errors import ElasticsearchTpuException

        index, op = payload["index"], payload["op"]
        sids = set(payload["shards"])
        script = payload.get("script")
        s_params = payload.get("params")
        svc = self.node.indices[index]
        num_shards = self._meta(index)["num_shards"]
        svc.refresh()
        counted: set = set()
        counts = {"deleted": 0, "updated": 0, "noops": 0}
        failures: List[dict] = []

        def apply(doc_id, loc):
            routing = loc.routing if loc else None
            sid = shard_id_for(doc_id, num_shards, routing)
            if sid not in sids:
                return  # a replica copy: its primary handles it
            counted.add(doc_id)
            try:
                if op == "delete":
                    self._primary_write("delete", index, sid, doc_id,
                                        None, routing, {})
                    counts["deleted"] += 1
                elif script is not None:
                    self._primary_update(index, sid, doc_id,
                                         {"script": script,
                                          "params": s_params},
                                         routing, {})
                    counts["updated"] += 1
                else:
                    got = svc.get_doc(doc_id, routing=routing)
                    if got.get("found"):
                        kw: Dict[str, Any] = {}
                        if loc is not None and loc.doc_type:
                            kw["doc_type"] = loc.doc_type
                        if loc is not None and loc.parent:
                            kw["parent"] = loc.parent
                        self._primary_write("index", index, sid, doc_id,
                                            got["_source"], routing, kw)
                        counts["updated"] += 1
                    else:
                        counts["noops"] += 1
            except ElasticsearchTpuException as e:
                failures.append(failure_entry(index, doc_id, e))

        canceled: Optional[str] = None
        with self.node.tasks.task(
                by_query_task_action(payload["op"]) + "[s]",
                description=f"{payload['op']}-by-query [{index}] "
                            f"shards {sorted(sids)}"):
            try:
                run_by_query(svc, payload.get("query"), apply)
            except TaskCancelledException as e:
                canceled = str(e)
        out: Dict[str, Any] = {"total": len(counted), "failures": failures}
        if op == "delete":
            out["deleted"] = counts["deleted"]
        else:
            out["updated"] = counts["updated"]
            out["noops"] = counts["noops"]
        if canceled is not None:
            out["canceled"] = canceled
        return out

    def cancel_task_children(self, parent_node: str, parent_id: int,
                             reason: str = "by user request") -> dict:
        """Fan a parent-task cancellation to every OTHER member so their
        child tasks (registered under the wire-propagated parent id)
        cancel too — the cross-node half of ``POST /_tasks/{id}/_cancel``
        (reference: TransportCancelTasksAction's ban propagation).
        Returns per-node cancelled task listings; a dead peer is
        REPORTED in ``node_failures``, never silently skipped (its tasks
        die with it anyway)."""
        payload = {"parent_node": parent_node, "parent_id": int(parent_id),
                   "reason": reason}
        nodes: Dict[str, Any] = {}
        failures: List[dict] = []
        for nid in self._other_nodes():
            try:
                res = self._send(nid, ACTION_CANCEL_TASKS, payload,
                                 timeout=5.0)
                if res.get("tasks"):
                    nodes[nid] = {"tasks": res["tasks"]}
            except Exception as e:
                failures.append({"node_id": nid, "reason": str(e)})
        out: Dict[str, Any] = {"nodes": nodes}
        if failures:
            out["node_failures"] = failures
        return out

    def _on_cancel_tasks(self, payload: dict) -> dict:
        """Cancel every local task descending from the named parent."""
        cancelled = self.node.tasks.cancel_by_parent(
            payload.get("parent_node") or "", int(payload["parent_id"]),
            payload.get("reason") or "by user request")
        return {"tasks": {t.tagged_id: t.to_json() for t in cancelled}}

    def proxy_doc_rest(self, index: str, doc_id: str,
                       routing: Optional[str], method: str, path: str,
                       params: dict, body: Optional[bytes]):
        """Route a doc-level REST op (explain / termvectors) to the doc's
        primary owner and relay its (status, body); None when the owner
        is THIS process — the caller then runs its own handler against
        the local shards, which hold the doc. Reference: the per-node
        transport handlers behind RestExplainAction /
        RestTermVectorsAction (each executes on the shard's node)."""
        index = self.resolve_index(index)
        meta = self._meta(index)
        sid = shard_id_for(doc_id, meta["num_shards"], routing)
        owner = self.owner_of(index, sid)
        if owner == self._local_id():
            return None
        res = self._send(owner, ACTION_REST_PROXY, {
            "method": method, "path": path, "params": dict(params or {}),
            "body": (body or b"").decode("utf-8", "replace")})
        return res["status"], res["payload"]

    def suggest_fan(self, index: str,
                    suggest_body: dict) -> Tuple[dict, dict]:
        """Suggest on a distributed index: one request per PRIMARY owner,
        each restricted (via the `_shards` param) to its primary shards
        so replica copies never double-count frequencies; merged per
        entry (search/suggest.py::merge_suggest). Returns
        (merged, _shards accounting) — a failed owner counts ITS shard
        count failed, and an unassigned shard is failed too. When
        embedded in a search, a dead peer already shows in the QUERY
        phase's _shards (suggest rides the same per-shard phase in the
        reference), so the search path reports the merged result
        without double-accounting."""
        import json as _json

        from urllib.parse import quote

        from elasticsearch_tpu.search.suggest import merge_suggest

        index = self.resolve_index(index)
        meta = self._meta(index)
        by_owner: Dict[str, List[int]] = {}
        failed_shards = 0
        for sid in range(meta["num_shards"]):
            owners = meta["assignment"][str(sid)]
            if owners:
                by_owner.setdefault(owners[0], []).append(sid)
            else:
                failed_shards += 1
        payloads = []
        raw = _json.dumps(suggest_body).encode()
        for owner, sids in sorted(by_owner.items()):
            req = {"method": "POST",
                   "path": f"/{quote(index, safe='')}/_suggest",
                   "params": {"_shards": ",".join(map(str, sids))},
                   "body": raw.decode("utf-8", "replace")}
            try:
                if owner == self._local_id():
                    res = self._on_rest_proxy(req)
                else:
                    res = self._send(owner, ACTION_REST_PROXY, req)
            except Exception:
                failed_shards += len(sids)
                continue
            if res["status"] == 200:
                payloads.append(res["payload"])
            else:
                failed_shards += len(sids)
        total = meta["num_shards"]
        return merge_suggest(suggest_body, payloads), {
            "total": total, "successful": total - failed_shards,
            "failed": failed_shards}

    def nodes_fan(self) -> dict:
        """Cluster-wide /_nodes: this node's entry plus every live
        member's, each sourced from the member itself over the REST proxy
        (reference: TransportNodesInfoAction fans to all nodes and merges
        per-node responses). A dead peer simply drops out of the map."""
        out = self.node.nodes_stats()
        for nid in self._other_nodes():
            try:
                res = self._send(nid, ACTION_REST_PROXY, {
                    "method": "GET", "path": "/_nodes", "params": {}})
                if res.get("status") == 200:
                    out["nodes"].update(
                        (res.get("payload") or {}).get("nodes", {}))
            except Exception:
                pass
        return out

    def _on_rest_proxy(self, payload: dict) -> dict:
        """Dispatch a proxied REST request into this process's own route
        table (lazily built — a pure data node may never serve HTTP)."""
        ctrl = self._proxy_controller
        if ctrl is None:
            from elasticsearch_tpu.rest.server import RestController

            ctrl = self._proxy_controller = RestController(self.node)
        params = dict(payload.get("params") or {})
        # pin to THIS node: the dispatched handler must serve from local
        # shards, never re-forward (divergent ownership views would
        # ping-pong the request unboundedly)
        params["_local_only"] = "1"
        status, body = ctrl.dispatch(
            payload["method"], payload["path"], params,
            (payload.get("body") or "").encode())
        return {"status": status, "payload": body}

    def get_doc(self, index: str, doc_id: str,
                routing: Optional[str] = None, realtime: bool = True,
                with_meta: bool = False) -> dict:
        index = self.resolve_index(index)
        meta = self._meta(index)
        owner = self.owner_of(
            index, shard_id_for(doc_id, meta["num_shards"], routing))
        if owner == self._local_id():
            return self.node.indices[index].get_doc(
                doc_id, routing=routing, realtime=realtime,
                with_meta=with_meta)
        # realtime get is idempotent: transport flakes retry with backoff
        return self._send_idempotent(
            owner, ACTION_GET,
            {"index": index, "id": doc_id, "routing": routing,
             "realtime": realtime, "meta": with_meta}, timeout=10.0)

    def _on_get(self, payload: dict) -> dict:
        return self.node.indices[payload["index"]].get_doc(
            payload["id"], routing=payload.get("routing"),
            realtime=payload.get("realtime", True),
            with_meta=payload.get("meta", False))

    # -- allocation signals ---------------------------------------------------

    def local_alloc_usage(self) -> dict:
        """This node's placement signals for the allocator's usage probe
        (and the multihost `_cat/allocation` row): HBM bytes from the
        breaker hierarchy + device-resident residency bytes over the
        ``ESTPU_HBM_BYTES`` capacity, local copy count from the published
        metadata, and a serving-load score folding per-shard query totals
        with breaker-trip and eviction churn (the live ``estpu_*``
        families the LoadDecider steers by)."""
        from elasticsearch_tpu import resources

        used, capacity = resources.BREAKERS.hbm_usage()
        bstats = resources.BREAKERS.stats()
        tripped = sum(int(b.get("tripped", 0)) for b in bstats.values())
        rstats = resources.RESIDENCY.stats()
        evictions = sum(int(t.get("evictions", 0))
                        for t in rstats.get("tiers", {}).values())
        local = self._local_id()
        shards = 0
        with self.cluster._indices_lock:
            for meta in self.cluster.dist_indices.values():
                for sid in range(int(meta.get("num_shards", 0))):
                    owners = meta["assignment"].get(str(sid), [])
                    if local in owners:
                        shards += 1
        queries = 0
        for svc in list(self.node.indices.values()):
            for shard in getattr(svc, "shards", []):
                try:
                    queries += int(shard.searcher.stats.query_total)
                except Exception:  # tpulint: allow[R006] — a stats-less
                    pass           # shard must not fail the probe
        return {"hbm_used": used, "hbm_capacity": capacity,
                "shards": shards,
                "load": float(queries + 10 * tripped + evictions),
                "queries": queries, "breaker_trips": tripped,
                "evictions": evictions}

    def _on_shard_ckpt(self, payload: dict) -> dict:
        """This copy's local checkpoint — the recency signal the master's
        promotion pass ranks in-sync survivors by (the copy with the
        highest checkpoint replays the shortest suffix)."""
        svc = self.node.indices.get(payload["index"])
        if svc is None:
            return {"checkpoint": NO_OPS_PERFORMED}
        return {"checkpoint":
                svc.shards[payload["shard"]].engine.local_checkpoint}

    def _on_cluster_settings(self, payload: dict) -> dict:
        """Adopt a peer's ``PUT /_cluster/settings`` broadcast: persist
        the raw persistent/transient structure and re-apply the MERGED
        map to every live consumer (breakers, serving, allocator) — so a
        drain exclusion PUT to ANY node reaches the master's allocator."""
        self.node.cluster_settings = payload["cluster_settings"]
        merged = payload.get("merged") or {}
        from elasticsearch_tpu import resources

        resources.apply_cluster_settings(merged)
        serving = getattr(self.node, "serving", None)
        if serving is not None:
            serving.apply_cluster_settings(merged)
        alloc = getattr(self.cluster, "allocator", None)
        if alloc is not None:
            alloc.apply_cluster_settings(merged)
        return {"acknowledged": True}

    # -- shard recovery / relocation -----------------------------------------

    def _promotion_checkpoints(self) -> Dict[Tuple[str, int],
                                             Dict[str, int]]:
        """Local checkpoints of the promotion candidates, for every shard
        whose primary died leaving MORE than one in-sync survivor —
        promotion should pick the copy with the highest checkpoint so the
        new primary replays the shortest suffix. Best-effort and outside
        the indices lock: an unreachable candidate just drops out of the
        map (select_primary falls back to owner order, which is never
        unsafe — every candidate is in-sync)."""
        alive = set(self.node.cluster_state.nodes)
        wanted: Dict[Tuple[str, int], List[str]] = {}
        with self.cluster._indices_lock:
            for name, meta in self.cluster.dist_indices.items():
                for sid in range(int(meta.get("num_shards", 0))):
                    owners = meta["assignment"].get(str(sid), [])
                    if not owners or owners[0] in alive:
                        continue  # no promotion pending for this shard
                    insync = set(self._shard_in_sync(meta, sid))
                    survivors = [o for o in owners
                                 if o in alive and o in insync]
                    if len(survivors) > 1:
                        wanted[(name, sid)] = survivors
        out: Dict[Tuple[str, int], Dict[str, int]] = {}
        for (name, sid), cands in wanted.items():
            m: Dict[str, int] = {}
            for nid in cands:
                try:
                    if nid == self._local_id():
                        m[nid] = self.node.indices[name].shards[sid] \
                            .engine.local_checkpoint
                    else:
                        m[nid] = int(self._send(
                            nid, ACTION_SHARD_CKPT,
                            {"index": name, "shard": sid},
                            timeout=2.0)["checkpoint"])
                except Exception:
                    continue
            if m:
                out[(name, sid)] = m
        return out

    def reconcile(self):
        """Master-side allocation pass after a membership change: drop dead
        nodes from every copy list (which promotes the next surviving
        COMMITTED copy to primary), then top shards back up to 1+replicas
        copies on alive nodes. A new copy starts in `initializing` — it
        receives live write fanout but is not promotable or searchable —
        and graduates into `assignment` only when its recovery stream
        succeeds (_run_recoveries), so a failed recovery can never leave a
        promotable empty copy. Returns (directives, changed).
        Reference: RoutingNodes promotion + INITIALIZING→STARTED shard
        states; recovery itself mirrors RecoverySourceHandler phase 1/2 as
        ops-based streaming (see index/recovery.py for why shipping live
        docs IS our segment copy)."""
        # checkpoint probe OUTSIDE the lock: it sends transport requests
        ckpts = self._promotion_checkpoints()
        with self.cluster._indices_lock:
            alive = set(self.node.cluster_state.nodes)
            order = sorted(alive)
            directives: List[dict] = []
            changed = False
            for name, meta in self.cluster.dist_indices.items():
                want = 1 + int(meta.get("replicas", 0))
                init = meta.setdefault("initializing", {})
                for sid in range(meta["num_shards"]):
                    old_primary = (meta["assignment"][str(sid)] or [None])[0]
                    owners = [o for o in meta["assignment"][str(sid)]
                              if o in alive]
                    if owners != meta["assignment"][str(sid)]:
                        changed = True
                    # promotion only ever selects an IN-SYNC copy: a copy
                    # that missed an acknowledged write (shard_failed) or
                    # is still recovering must never become primary — it
                    # would silently roll back acked ops (reference:
                    # allocation promotes from the in-sync allocation ids)
                    insync = self._shard_in_sync(meta, sid)
                    dropped = [o for o in insync if o not in alive]
                    if dropped:
                        changed = True
                        insync[:] = [o for o in insync if o in alive]
                    from elasticsearch_tpu.cluster.routing import \
                        select_primary

                    reordered = select_primary(owners, insync,
                                               ckpts.get((name, sid)))
                    if reordered != owners:
                        owners = reordered
                        changed = True
                    meta["assignment"][str(sid)] = owners
                    if owners and owners[0] != old_primary:
                        # primary changed hands: BUMP THE TERM so any op
                        # still in flight from the demoted primary is
                        # fenced by every copy that adopts this publish
                        terms = meta.setdefault("primary_terms", {})
                        terms[str(sid)] = self._shard_term(meta, sid) + 1
                        changed = True
                    pend = [t for t in init.get(str(sid), []) if t in alive]
                    if pend != init.get(str(sid), []):
                        changed = True
                    init[str(sid)] = pend
                    if not owners:
                        continue  # lost shard: nothing to copy from
                    for k in range(len(order)):
                        if len(owners) + len(pend) >= want:
                            break
                        cand = order[(sid + k) % len(order)]
                        if cand in owners or cand in pend:
                            continue
                        pend.append(cand)
                        directives.append({
                            "index": name, "shard": sid, "target": cand,
                            "source": owners[0], "body": meta["body"]})
                        changed = True
            return directives, changed

    def _on_shard_docs(self, payload: dict) -> dict:
        svc = self.node.indices.get(payload["index"])
        if svc is None:
            return {"docs": -1}
        return {"docs": svc.shards[payload["shard"]].engine.num_docs}

    def resurrect_lost(self) -> None:
        """Gateway-style primary allocation from on-disk copies: a shard
        with NO active copies adopts the alive node holding the most
        local docs for it — a member that restarted with its data_path
        and rejoined under a new node id. Shards nobody holds data for
        stay unassigned (a visible failure, like the reference's lost
        primaries without an explicit force-allocate). Reference:
        gateway/GatewayAllocator primary allocation from shard stores."""
        with self.cluster._indices_lock:
            lost = [(name, sid)
                    for name, meta in self.cluster.dist_indices.items()
                    for sid in range(meta["num_shards"])
                    if not meta["assignment"].get(str(sid))]
        if not lost:
            return
        changed = False
        for name, sid in lost:
            best_docs, best_nid = 0, None
            for nid in sorted(self.node.cluster_state.nodes):
                try:
                    if nid == self._local_id():
                        docs = self.node.indices[name].shards[sid] \
                            .engine.num_docs
                    else:
                        docs = self._send(nid, ACTION_SHARD_DOCS,
                                          {"index": name, "shard": sid},
                                          timeout=5.0)["docs"]
                except Exception:
                    continue
                if docs > best_docs:
                    best_docs, best_nid = docs, nid
            if best_nid is None:
                continue
            with self.cluster._indices_lock:
                meta2 = self.cluster.dist_indices[name]
                owners = meta2["assignment"].get(str(sid))
                if owners == []:  # still lost (no race with a recovery)
                    owners.append(best_nid)
                    # gateway adoption is a primary change: new term, and
                    # the adopted copy is the in-sync set's sole member
                    meta2.setdefault("primary_terms", {})[str(sid)] = \
                        self._shard_term(meta2, sid) + 1
                    meta2.setdefault("in_sync", {})[str(sid)] = [best_nid]
                    changed = True
        if changed:
            try:
                self.cluster.publish_indices()
                # replicas top back up from the resurrected primaries
                directives, changed2 = self.reconcile()
                if changed2:
                    self.cluster.publish_indices()
            except FailedToCommitClusterStateException:
                # background thread on a master that just lost quorum:
                # it stepped down; the quorum's master redoes allocation
                return
            self.start_recoveries(directives)

    def start_recoveries(self, directives: List[dict]) -> None:
        """Run the recovery streams on a background thread: callers are
        transport handlers or the fault-detector loop, and a recovery can
        take as long as the shard is big. Each directive registers a
        PENDING task up front (visible in /_cluster/pending_tasks while
        queued behind earlier streams) that flips to running as its
        stream starts — cancelling it skips/aborts that stream."""
        if not directives:
            return
        tasks = [self.node.tasks.register(
            ACTION_RECOVER,
            description=f"recover [{d['index']}][{d['shard']}] "
                        f"{d['source']} -> {d['target']}",
            status="pending") for d in directives]
        threading.Thread(target=self._run_recoveries,
                         args=(directives, tasks),
                         name="tpu-recovery", daemon=True).start()

    def _run_recoveries(self, directives: List[dict],
                        tasks: Optional[list] = None) -> None:
        from elasticsearch_tpu.tracing.tasks import (reset_current,
                                                     set_current)

        promoted = False
        for i, d in enumerate(directives):
            task = tasks[i] if tasks else None
            ok = False
            token = None
            # cancelled while queued: the stream never starts, but the
            # bookkeeping below MUST still run — skipping it would leave
            # the target in `initializing` forever (write fanout keeps
            # targeting a copy whose recovery never ran, and no retry is
            # ever scheduled because the copy still looks in-flight)
            cancelled_queued = task is not None and task.cancelled
            try:
                if not cancelled_queued:
                    if task is not None:
                        task.start()
                        # current-task context: the stream's checkpoints
                        # (_on_recover / remote shard_sync) see this task
                        token = set_current(task)
                    if d["target"] == self._local_id():
                        self._on_recover(d)
                    else:
                        self._send(d["target"], ACTION_RECOVER, d,
                                   timeout=120.0)
                    ok = True
            except Exception:
                pass
            finally:
                if token is not None:
                    reset_current(token)
                if task is not None:
                    self.node.tasks.unregister(task)
            with self.cluster._indices_lock:
                meta = self.cluster.dist_indices.get(d["index"])
                if meta is None:
                    continue
                pend = meta.get("initializing", {}).get(str(d["shard"]), [])
                if d["target"] in pend:
                    pend.remove(d["target"])
                owners = meta["assignment"].get(str(d["shard"]))
                if ok and owners is not None and d["target"] not in owners \
                        and d["target"] in self.node.cluster_state.nodes:
                    owners.append(d["target"])  # INITIALIZING → STARTED
                    # recovery caught the copy up to the source's
                    # checkpoint: it joins the in-sync set and becomes
                    # promotable
                    insync = self._shard_in_sync(meta, d["shard"])
                    if d["target"] not in insync:
                        insync.append(d["target"])
                    promoted = True
        if promoted:
            try:
                self.cluster.publish_indices()
            except FailedToCommitClusterStateException:
                # recovery thread on a master that just lost quorum: the
                # graduation stays local; the quorum's master republishes
                pass

    def _on_recover(self, payload: dict) -> dict:
        """Recovery target: checkpoint handshake with the source copy,
        then EITHER replay the translog op suffix above this copy's local
        checkpoint (incremental — the seq-no era RecoveryTarget) OR pull
        the full live-doc snapshot (fallback for diverged copies, flushed
        ops, legacy frames). The index may not exist locally yet when
        recovery races the metadata publish — create it from the
        directive's body."""
        index, sid = payload["index"], payload["shard"]
        if payload.get("relocate"):
            # allocator-driven move: the deterministic wedge point — an
            # armed fault fails the stream BEFORE any registry entry or
            # index creation, so the relocation watchdog's cancel +
            # reschedule path is what recovers, not local cleanup
            FAULTS.check("relocation.stream", index=index, shard=sid,
                         source=payload["source"],
                         target=self._local_id())
        with self.cluster._indices_lock:
            if not self.node.index_exists(index):
                self.node.create_index(index, payload.get("body"))
        svc = self.node.indices[index]
        engine = svc.shards[sid].engine
        ckpt = engine.local_checkpoint
        rec = svc.recoveries.start(
            sid, "relocation" if payload.get("relocate") else "peer",
            source=payload["source"], target=self._local_id())
        copied = skipped = replayed = 0
        from elasticsearch_tpu.utils.errors import (DocumentMissingException,
                                                    VersionConflictException)

        try:
            req = {"index": index, "shard": sid, "checkpoint": ckpt,
                   "last_term": engine.term_at(ckpt),
                   "target": self._local_id()}
            try:
                # fleet-wide AOT distribution (ROADMAP #6): tell the
                # source which compiled-program blobs we already hold —
                # it ships the delta beside the docs/ops, so this node
                # never compiles a program a peer already compiled
                from elasticsearch_tpu.index import ivf_cache

                req["aot_have"] = ivf_cache.list_blob_keys("aotx")
            except Exception:  # tpulint: allow[R006] — blob-tier probe
                pass           # must never fail a recovery handshake
            res = self._send(payload["source"], ACTION_SHARD_SYNC, req,
                             timeout=60.0)
            # child task on the TARGET node (parent: the driving recovery
            # task, via the wire header): a cancel aborts the replay
            # between ops/docs, the copy stays INITIALIZING and never
            # graduates
            with self.node.tasks.task(
                    ACTION_RECOVER + "[t]",
                    description=f"recover [{index}][{sid}] "
                                f"from {payload['source']}") as task:
                if res.get("mode") == "ops":
                    rec.update(mode="ops", stage="translog")
                    for op in res["ops"]:
                        task.check_cancelled()
                        FAULTS.check("recovery.ops_replay", index=index,
                                     shard=sid, seq_no=op.get("seq_no"))
                        try:
                            svc.replay_op(sid, _translog_to_replay(op))
                            replayed += 1
                        except (VersionConflictException,
                                DocumentMissingException):
                            # racing fanout write was newer: a no-op,
                            # but its seq no still counts as processed
                            # or the checkpoint stalls on the hole
                            engine.note_noop(op.get("seq_no"),
                                             op.get("term"))
                            skipped += 1
                        rec["ops_replayed"] = replayed
                        rec["docs_skipped"] = skipped
                    # an idle new primary's bumped term still propagates
                    engine.bump_term(int(res.get("term", 0)))
                else:
                    rec.update(mode="full", stage="index")
                    for d in res["docs"]:
                        task.check_cancelled()
                        try:
                            # docs AND tombstones ride the stream (a
                            # delete that landed on the source after a
                            # racing fanout index on this copy still wins
                            # by version); percolator-registry maintenance
                            # happens atomically with the engine op
                            # (IndexService.replay_op)
                            svc.replay_op(sid, d)
                            copied += 1
                        except (VersionConflictException,
                                DocumentMissingException):
                            engine.note_noop(d.get("seq_no"),
                                             d.get("term"))
                            skipped += 1  # already newer (racing write)
                        rec["docs_copied"] = copied
                        rec["docs_skipped"] = skipped
                    # prune stale-era docs the source no longer has: a
                    # diverged copy (demoted primary whose fenced write
                    # was applied locally but never acked) may hold docs
                    # from an older term that external_gte cannot remove.
                    # Current-term docs above the snapshot horizon are
                    # racing live-fanout arrivals and must survive.
                    src_term = int(res.get("term", 0))
                    src_ckpt = int(res.get("local_checkpoint", -1))
                    snap_ids = {d["id"] for d in res["docs"]}
                    with engine._lock:
                        extras = [
                            (doc_id, loc.version, loc.seq_no, loc.term)
                            for doc_id, loc in engine._locations.items()
                            if not loc.deleted and doc_id not in snap_ids
                            and (loc.term < src_term
                                 or (loc.term == src_term
                                     and 0 <= loc.seq_no <= src_ckpt))]
                    for doc_id, cur_version, stale_seq, stale_term \
                            in extras:
                        try:
                            # the tombstone reuses the pruned doc's OWN
                            # (seq_no, term): a local cleanup must not
                            # consume numbers from the primary's stream —
                            # a generated seqno would push this copy's
                            # checkpoint past the source's and doom every
                            # future handshake to the full-copy path
                            # (same rule as recovery._recover_full_copy)
                            svc.replay_op(sid, {"id": doc_id,
                                                "deleted": True,
                                                "version": cur_version,
                                                "seq_no": stale_seq,
                                                "term": stale_term})
                        except (VersionConflictException,
                                DocumentMissingException):
                            pass
                    # adopt the source's checkpoint + term history so the
                    # NEXT bounce of this copy recovers incrementally
                    engine.adopt_seq_state(
                        {int(t): m for t, m in
                         (res.get("term_seq") or {}).items()},
                        int(res.get("local_checkpoint", -1)),
                        int(res.get("term", 0)))
            # seed the peer-compiled AOT blobs that rode the stream (the
            # compile-cache then answers `seeded`, never `fresh`, for
            # these programs — the chaos gate's compile-delta-0 check)
            rec["aot_seeded"] = self._adopt_aot_blobs(
                res.get("aot_blobs"))
            rec["stage"] = "finalize"
            svc.shards[sid].engine.refresh()
            svc.recoveries.finish(rec, ok=True)
        except Exception:
            svc.recoveries.finish(rec, ok=False)
            raise
        # shard assignment graduated on this node: adopt the census that
        # rode the relocation stream (ISSUE 15 — on a node that shares
        # no blob tier with the source, this is the ONLY way the
        # pre-warm work list arrives before traffic does), persist the
        # census (ISSUE 14 durability), and queue the pre-warm replay so
        # the copy serves its first searches compile-free
        # (serving/warmup.py; all best-effort, cooldown-guarded)
        try:
            self._adopt_census_debounced(index, res.get("census"))
            self._flush_census_debounced(index)
            wu = getattr(getattr(self.node, "serving", None),
                         "warmup", None)
            if wu is not None:
                wu.kick("shard_assignment", [index])
        except Exception:  # tpulint: allow[R006] — warmup plumbing must
            pass           # never fail a completed recovery
        return {"copied": copied, "skipped": skipped,
                "ops_replayed": replayed, "mode": rec["mode"]}

    #: per-index debounce window for the recovery-path census work —
    #: recovery actions fire once per SHARD, the census is per INDEX
    _CENSUS_DEBOUNCE_S = 5.0

    def _census_window(self, name: str, index: str):
        """(hit, stamp) for one named per-index debounce window: ``hit``
        is True when the window is still open (skip the work), and
        ``stamp()`` opens it. Lazy dicts so pickled/old instances keep
        working."""
        ts = getattr(self, name, None)
        if ts is None:
            ts = {}
            setattr(self, name, ts)
        now = time.monotonic()
        hit = now - ts.get(index, float("-inf")) < self._CENSUS_DEBOUNCE_S
        return hit, (lambda: ts.__setitem__(index, now))

    def _flush_census_debounced(self, index: str) -> None:
        """Recovery-path census flush, debounced per index: a P-shard
        relocation would otherwise pay P back-to-back load+merge+rewrite
        cycles inline in the transport path for one work list."""
        hit, stamp = self._census_window("_census_flush_ts", index)
        if hit:
            return
        stamp()
        from elasticsearch_tpu.resources import census

        census.store_census(index)

    def _export_census_debounced(self, index: str):
        """Source-side census payload for a shard_sync reply, cached per
        index for the debounce window — the P shard handshakes of one
        relocation ship ONE computed payload, not P load+merge+serialize
        cycles (the _flush_census_debounced rationale, export side)."""
        cache = getattr(self, "_census_export_cache", None)
        if cache is None:
            cache = self._census_export_cache = {}
        hit, stamp = self._census_window("_census_export_ts", index)
        if hit and index in cache:
            return cache[index]
        from elasticsearch_tpu.resources import census

        payload = census.export_census(index)
        cache[index] = payload
        stamp()
        return payload

    def _adopt_census_debounced(self, index: str, payload) -> None:
        """Target-side adoption, debounced per index: every one of a
        P-shard relocation's _on_recover calls carries the same payload
        — adopt (load+merge+store) once per window, not P times."""
        if payload is None:
            return
        hit, stamp = self._census_window("_census_adopt_ts", index)
        if hit:
            return
        from elasticsearch_tpu.resources import census

        if census.adopt_census(index, payload):
            stamp()

    #: cap on AOT executor bytes shipped per shard_sync reply — blobs
    #: ride the JSON transport base64-encoded, and one reply must not
    #: dwarf the doc payload it accompanies (the next handshake of the
    #: same relocation ships the remainder: the target re-sends its
    #: updated `aot_have` and the delta shrinks)
    _AOT_SHIP_MAX_BYTES = 32 << 20

    def _adopt_aot_blobs(self, blobs: Optional[dict]) -> int:
        """Target side: seed peer-shipped `.aotx` executor blobs into the
        local blob tier (skip-if-exists — content-addressed keys make the
        skip safe). Returns the count seeded; never raises."""
        if not blobs:
            return 0
        import base64

        from elasticsearch_tpu.index import ivf_cache

        n = 0
        for key, b64 in blobs.items():
            try:
                ivf_cache.store_blob(key, base64.b64decode(b64), "aotx",
                                     overwrite=False)
                n += 1
            except Exception:
                continue  # one bad blob must not drop the rest
        return n

    def _export_aot_blobs(self, have, target) -> Optional[dict]:
        """Source side: the `.aotx` blobs the target reported missing,
        base64 for the JSON transport, size-capped, debounced per target
        node (a P-shard relocation's handshakes would otherwise re-scan
        and re-ship the same delta P times — the census-window pattern,
        keyed by target instead of index)."""
        if have is None or target is None:
            return None
        hit, stamp = self._census_window("_aot_export_ts", str(target))
        if hit:
            return None
        import base64

        from elasticsearch_tpu.index import ivf_cache

        missing = set(ivf_cache.list_blob_keys("aotx")) - set(have)
        out: Dict[str, str] = {}
        total = 0
        for key in sorted(missing):
            blob = ivf_cache.load_blob(key, "aotx")
            if blob is None:
                continue
            if total + len(blob) > self._AOT_SHIP_MAX_BYTES:
                break  # remainder ships on the NEXT handshake's delta
            total += len(blob)
            out[key] = base64.b64encode(blob).decode("ascii")
        stamp()
        return out or None

    def _on_shard_sync(self, payload: dict) -> dict:
        """Recovery source: checkpoint comparison first — when the
        target's history is a clean prefix (log-matching on the term at
        its checkpoint) and the retained translog covers everything above
        it, answer with ``mode=ops`` and just that suffix. Otherwise
        snapshot this shard's docs AND tombstones with their full
        (version, seq_no, term) identity — RecoverySourceHandler's
        phase-1 stream in ops form; concurrent writes during the copy win
        on the target via version comparison (phase 2 for free)."""
        FAULTS.check("recovery.shard_sync", index=payload["index"],
                     shard=payload["shard"])
        svc = self.node.indices[payload["index"]]
        engine = svc.shards[payload["shard"]].engine
        svc.recoveries.source_started()
        try:
            resp = self._shard_sync_response(engine, payload)
            # the census RIDES the relocation stream beside the doc/op
            # payload (ISSUE 15 / PR 14's stated residual): the target
            # node may share no blob directory with this one, so the
            # pre-warm work list must travel in-band or the relocated
            # shard re-learns from scratch
            try:
                resp["census"] = self._export_census_debounced(
                    payload["index"])
            except Exception:  # tpulint: allow[R006] — warmup plumbing
                pass           # must never fail a recovery handshake
            # AOT executor delta beside the census (ROADMAP #6's open
            # half): the target sent the keys it holds; ship the rest
            try:
                blobs = self._export_aot_blobs(payload.get("aot_have"),
                                               payload.get("target"))
                if blobs:
                    resp["aot_blobs"] = blobs
            except Exception:  # tpulint: allow[R006] — blob shipping
                pass           # must never fail a recovery handshake
            return resp
        finally:
            svc.recoveries.source_finished()
            # the source has served this index — flush ITS census now so
            # the relocation target's pre-warm has a fresh work list to
            # read (ISSUE 14: flush on shard assignment, source side;
            # debounced — one flush covers all P shard handshakes)
            try:
                self._flush_census_debounced(payload["index"])
            except Exception:  # tpulint: allow[R006] — best-effort
                pass           # durability, never a failed handshake

    def _shard_sync_response(self, engine, payload: dict) -> dict:
        ckpt = payload.get("checkpoint")
        if ckpt is not None:
            ops = engine.recovery_ops(int(ckpt), payload.get("last_term"))
            if ops is not None:
                return {"mode": "ops", "ops": ops,
                        "term": engine.primary_term,
                        "local_checkpoint": engine.local_checkpoint,
                        "max_seq_no": engine.max_seq_no}
        with engine._lock:
            ids = [(doc_id, loc.version, loc.doc_type, loc.parent,
                    loc.routing, loc.deleted, loc.seq_no, loc.term)
                   for doc_id, loc in engine._locations.items()]
            term_seq = dict(engine._term_seq)
            src_term = engine.primary_term
            src_ckpt = engine.local_checkpoint
        docs = []
        for doc_id, version, doc_type, parent, routing, deleted, seq_no, \
                term in ids:
            if deleted:
                docs.append({"id": doc_id, "version": version,
                             "deleted": True, "seq_no": seq_no,
                             "term": term})
                continue
            got = engine.get(doc_id)
            if got is None:
                continue  # deleted mid-snapshot
            loc = engine._locations.get(doc_id)
            docs.append({"id": doc_id, "source": got["_source"],
                         "version": version, "type": doc_type,
                         "parent": parent, "routing": routing,
                         "seq_no": seq_no, "term": term,
                         # _timestamp/_ttl ride the stream too, or the
                         # recovered copy would regenerate/lose them
                         "timestamp": getattr(loc, "timestamp", None),
                         "ttl_expiry": getattr(loc, "ttl_expiry", None)})
        return {"mode": "docs", "docs": docs, "term": src_term,
                "local_checkpoint": src_ckpt, "term_seq": term_seq}

    # -- query phase (remote endpoint) ---------------------------------------

    def _on_query(self, payload: dict) -> dict:
        """Run the query phase on the requested LOCAL shards; park the
        candidate docs under a context id for the fetch phase (reference:
        SearchService.executeQueryPhase → QuerySearchResult with id)."""
        from elasticsearch_tpu.monitor import programs

        index, body = payload["index"], payload.get("body") or {}
        shard_ids = payload["shards"]
        svc = self.node.indices.get(index)
        if svc is None:
            raise IndexNotFoundException(index)
        self._prune_contexts()
        pairs: List[Tuple[Any, Any]] = []
        shards_out = []
        agg_lists: List[dict] = []
        # census scope on the OWNER (ISSUE 15): the device programs this
        # shard's query phase compiles belong to THIS node's per-index
        # census — it is the node a relocation would stream away from.
        # The replayable body records here too: each node ships a work
        # list of the traffic it actually served.
        try:
            svc._record_census_body(body)
        except Exception:  # tpulint: allow[R006] — census recording
            pass           # must never fail the query phase
        for sid in shard_ids:
            searcher = svc.groups[sid].reader().searcher
            with self.node.tracer.span("shard.query_phase", index=index,
                                       shard=sid), \
                    programs.index_scope(index):
                r = searcher.query_phase(body)
            docs_out = []
            for d in r.docs:
                docs_out.append({
                    "pos": len(pairs), "shard": sid,
                    "score": None if np.isnan(d.score) else float(d.score),
                    "sort": wire.pack(list(d.sort_values)),
                })
                pairs.append((searcher, d))
            shard_entry = {
                "shard": sid, "total": r.total_hits,
                "max_score": (None if np.isnan(r.max_score)
                              else float(r.max_score)),
                "docs": docs_out,
                "timed_out": r.timed_out,
                "terminated_early": r.terminated_early,
            }
            if r.profile is not None:
                # ?profile=true: the per-shard TPU phase breakdown rides
                # the query-phase reply (plain ints — wire-safe)
                shard_entry["profile"] = r.profile
            shards_out.append(shard_entry)
            if r.agg_partials:
                agg_lists.extend(r.agg_partials["_list"])
        cid = uuid.uuid4().hex
        with self._lock:
            self._contexts[cid] = {"pairs": pairs, "body": body,
                                   "index": index, "born": time.time()}
        return {"context_id": cid, "shards": shards_out,
                "aggs": wire.pack(agg_lists) if agg_lists else None}

    def _on_fetch(self, payload: dict) -> List[dict]:
        """Fetch-phase endpoint: resolve context positions → hit JSON
        (reference: SearchService.executeFetchPhase by context id).
        The context is freed after serving — cross-host scroll keeps its
        state on the coordinator, never here."""
        with self._lock:
            ctx = self._contexts.pop(payload["context_id"], None)
        if ctx is None:
            from elasticsearch_tpu.utils.errors import \
                SearchContextMissingException

            raise SearchContextMissingException(payload["context_id"])
        from elasticsearch_tpu.monitor import programs

        positions: List[int] = payload["positions"]
        with programs.index_scope(ctx["index"]):
            hit_of = _fetch_grouped(
                [(p,) + ctx["pairs"][p] for p in positions],
                ctx["body"], ctx["index"])
        return [hit_of[p] for p in positions]

    def _on_free(self, payload: dict) -> dict:
        with self._lock:
            self._contexts.pop(payload["context_id"], None)
        return {"ok": True}

    def _prune_contexts(self) -> None:
        now = time.time()
        with self._lock:
            for cid in [c for c, v in self._contexts.items()
                        if now - v["born"] > _CONTEXT_TTL]:
                del self._contexts[cid]

    def _free_remote(self, remote_ctx: Dict[str, str]) -> None:
        for owner, cid in remote_ctx.items():
            try:
                self._send(owner, ACTION_FREE, {"context_id": cid},
                           timeout=5.0)
            except Exception:
                pass  # TTL pruning on the owner collects it

    # -- coordinator ---------------------------------------------------------

    def search(self, index: str, body: Optional[dict] = None) -> dict:
        """Scatter the query phase over every shard owner, merge ranked
        candidates, fetch the selected page from each owner, reduce aggs.
        Mirrors TransportSearchQueryThenFetchAction's three steps.

        Observability: runs as a registered task under one root span —
        the wire header carries both, so every remote owner's
        transport.handle/shard.query_phase spans share this trace id and
        its shard tasks parent to this one."""
        from elasticsearch_tpu.monitor import programs
        from elasticsearch_tpu.serving import warmup as warmup_mod

        # census scope at the COORDINATOR (ISSUE 15): the dist plane
        # calls searcher.query_phase directly, so without this scope a
        # cluster member's device programs never attributed to the index
        # and its pre-warm work list stayed empty — relocation had
        # nothing to ship. Pre-warm replays stay out of scope, the
        # IndexService.search rule.
        prewarm = warmup_mod.in_prewarm()
        try:
            scope = None if prewarm else self.resolve_index(index)
        except Exception:
            scope = None
        with self.node.tasks.task("indices:data/read/search",
                                  description=f"indices[{index}]"):
            with self.node.tracer.span("search.coordinate", index=index):
                with programs.index_scope(scope):
                    resp = self._search_inner(index, body)
        # slow log at the COORDINATOR: the owner-side query phases call
        # searcher.query_phase directly, so without this hook a
        # distributed index's thresholds would silently never fire
        # (single-node searches record inside IndexService.search)
        svc = self.node.indices.get(self.resolve_index(index))
        if svc is not None:
            svc.slowlog.on_search(resp.get("took", 0), body, resp)
            if not prewarm:
                try:
                    svc._record_census_body(body or {})
                except Exception:  # tpulint: allow[R006] — census
                    pass           # recording never fails a search
        return resp

    def _mesh_all_local(self, index: str, svc, body: dict,
                        t0: float) -> Optional[dict]:
        """ISSUE 16: mesh-collective query-then-fetch for the co-resident
        case — every shard owner is this node, so the coordinator hands
        the whole request to the shard-mesh product path (one shard_map
        program per segment round: per-shard scoring, per-shard top-k,
        on-device all_gather + global merge, psum'd totals/agg counts)
        and TCP is demoted to control plane. Any refusal — unsupported
        body feature, breaker denial, compile rejection — returns None
        and the serial scatter loop serves the request unchanged."""
        from elasticsearch_tpu.monitor import kernels

        if not getattr(svc, "_mesh_enabled", lambda: False)():
            return None
        try:
            searchers = [g.reader().searcher for g in svc.groups]
            from elasticsearch_tpu.parallel.mesh_service import \
                try_mesh_search

            with self.node.tracer.span("shard.query_phase.mesh",
                                       index=index):
                resp = try_mesh_search(svc, searchers, body)
        except Exception:  # tpulint: allow[R006] — the scatter loop is
            kernels.record("dist_mesh_error")  # the reference path; any
            return None                        # mesh failure degrades
        if resp is None:
            kernels.record("dist_mesh_fallback")
            return None
        kernels.record("dist_mesh_search")
        resp["took"] = int((time.perf_counter() - t0) * 1000)
        return resp

    def _search_inner(self, index: str, body: Optional[dict]) -> dict:
        from elasticsearch_tpu.search.aggregations.base import (parse_aggs,
                                                                reduce_aggs)
        from elasticsearch_tpu.search.service import (_parse_sort, _sort_key)

        body = body or {}
        t0 = time.perf_counter()
        index = self.resolve_index(index)
        meta = self._meta(index)
        svc0 = self.node.indices.get(index)
        if svc0 is not None:
            from elasticsearch_tpu.cluster.metadata import check_open

            check_open(svc0, op="read")  # closed-ness is published state
        local_id = self._local_id()
        # cross-host scroll: the per-owner fetch contexts are one-shot, so
        # the coordinator MATERIALIZES the window (capped at the 10k
        # result window — DEVIATIONS.md) and pages from it; the shards see
        # a full-window query phase
        scroll = body.get("scroll")
        page_size = int(body.get("size", 10))
        if scroll:
            body = {k: v for k, v in body.items() if k != "scroll"}
            body["size"] = 10_000
            body["from"] = 0
        if body.get("query"):
            # MLT liked ids resolve via the ROUTED cross-host get before
            # the scatter — each owner only holds its own shards' docs
            from elasticsearch_tpu.search.queries import rewrite_mlt_in_body

            def _lookup(doc_id, routing=None, index=None, _ix=index):
                # an aliased _index must resolve before the dist check
                target = self.resolve_index(index or _ix)
                try:
                    if target in self.cluster.dist_indices:
                        got = self.get_doc(target, doc_id, routing=routing)
                    else:  # a like item naming a coordinator-local index
                        svc = self.node.indices.get(target)
                        if svc is None:
                            return None
                        return svc.mlt_source(doc_id, routing=routing)
                except Exception:
                    return None
                return got.get("_source") if got.get("found") else None

            q2 = rewrite_mlt_in_body(body["query"], _lookup)
            if q2 is not body["query"]:
                body = dict(body, query=q2)
        by_owner: Dict[str, List[int]] = {}
        unassigned: List[dict] = []
        for sid in range(meta["num_shards"]):
            owners = meta["assignment"][str(sid)]
            if not owners:
                unassigned.append(shard_failure_entry(
                    index, sid, error_type="unavailable_shards_exception",
                    reason="no active copies", status=503))
                continue
            by_owner.setdefault(owners[0], []).append(sid)
        sort_spec = _parse_sort(body.get("sort"))
        size = int(body.get("size", 10))
        frm = int(body.get("from", 0))
        # per-shard query/fetch deadline: the body `timeout` (which the
        # shards also apply to their collect loops) caps the COORDINATOR'S
        # total scatter+fetch wall time; without one, a default stops a
        # hung peer from wedging the search forever
        from elasticsearch_tpu.search.service import _parse_timeout

        deadline = time.monotonic() + (_parse_timeout(body.get("timeout"))
                                       or _SEARCH_DEADLINE)

        entries: List[dict] = []
        agg_lists: List[dict] = []
        remote_ctx: Dict[str, str] = {}
        profiles: List[dict] = []
        total = 0
        max_score = float("-inf")
        timed_out = False
        terminated = False
        # per-shard failures are collected, not fatal, matching the
        # reference's ShardSearchFailure accounting — unless EVERY shard
        # failed, in which case the search as a whole is an error
        failed: List[dict] = list(unassigned)
        owner_order = {nid: i for i, nid in enumerate(sorted(by_owner))}
        svc = self.node.indices.get(index)
        # ISSUE 16 mesh preference: when every shard's primary owner is
        # THIS node (co-resident on one mesh), the whole query phase runs
        # as one compiled device program per segment round instead of the
        # serial per-shard scatter below. TCP remains the control plane —
        # metadata/assignment above, remote fetch and the scatter loop as
        # the unconditional fallback (scroll and suggest keep the scatter
        # path: their post-merge machinery lives there).
        if (svc is not None and by_owner and not unassigned
                and not scroll and not body.get("suggest")
                and set(by_owner) == {local_id}):
            resp = self._mesh_all_local(index, svc, body, t0)
            if resp is not None:
                return resp
        from elasticsearch_tpu.tracing import check_cancelled

        try:
            for owner, sids in sorted(by_owner.items()):
                # cooperative checkpoint between owners: a cancelled
                # search stops scattering (already-parked remote contexts
                # free in the finally)
                check_cancelled()
                if owner == local_id:
                    for sid in sids:
                        try:
                            searcher = svc.groups[sid].reader().searcher
                            with self.node.tracer.span(
                                    "shard.query_phase", index=index,
                                    shard=sid):
                                r = searcher.query_phase(body)
                        except Exception as e:
                            # a single bad local shard degrades to a
                            # partial result, same as a dead peer's —
                            # broad on purpose: the remote path catches
                            # ANY failure, and shard placement must not
                            # change whether degradation happens
                            failed.append(shard_failure_entry(
                                index, sid, e, node=owner))
                            continue
                        total += r.total_hits
                        if r.docs and not np.isnan(r.max_score):
                            max_score = max(max_score, r.max_score)
                        timed_out |= r.timed_out
                        terminated |= r.terminated_early
                        if r.profile is not None:
                            profiles.append(_shard_profile(
                                owner, index, sid, r.profile))
                        for d in r.docs:
                            entries.append({
                                "owner": owner, "shard": sid,
                                "score": d.score, "sort": d.sort_values,
                                "local": (searcher, d), "pos": -1,
                            })
                        if r.agg_partials:
                            agg_lists.extend(r.agg_partials["_list"])
                    continue
                try:
                    res = self._send_idempotent(
                        owner, ACTION_QUERY,
                        {"index": index, "body": body, "shards": sids},
                        deadline=deadline)
                except Exception as e:
                    failed.extend(shard_failure_entry(index, sid, e,
                                                      node=owner)
                                  for sid in sids)
                    continue
                remote_ctx[owner] = res["context_id"]
                for sh in res["shards"]:
                    total += sh["total"]
                    if sh["max_score"] is not None:
                        max_score = max(max_score, sh["max_score"])
                    timed_out |= sh["timed_out"]
                    terminated |= sh["terminated_early"]
                    if sh.get("profile"):
                        profiles.append(_shard_profile(
                            owner, index, sh["shard"], sh["profile"]))
                    for d in sh["docs"]:
                        entries.append({
                            "owner": owner, "shard": sh["shard"],
                            "score": (float("nan") if d["score"] is None
                                      else d["score"]),
                            "sort": tuple(wire.unpack(d["sort"])),
                            "local": None, "pos": d["pos"],
                        })
                if res.get("aggs") is not None:
                    agg_lists.extend(wire.unpack(res["aggs"]))
            if failed and len(failed) == meta["num_shards"]:
                # graceful degradation has a floor: NOTHING answered, so
                # there is no partial result to serve (reference:
                # SearchPhaseExecutionException "all shards failed")
                raise TransportError(
                    "all shards failed: "
                    f"{[f['reason']['reason'] for f in failed]}")

            if sort_spec:
                entries.sort(key=lambda e: _sort_key(e["sort"], sort_spec))
            else:
                entries.sort(key=lambda e: (-e["score"],
                                            owner_order[e["owner"]],
                                            e["shard"], e["pos"]))
            page = entries[frm:frm + size]

            # fetch phase: local directly, remote by context positions
            hit_of: Dict[int, dict] = _fetch_grouped(
                [(i, e["local"][0], e["local"][1])
                 for i, e in enumerate(page) if e["local"] is not None],
                body, index)
            by_remote: Dict[str, List[int]] = {}
            for i, e in enumerate(page):
                if e["local"] is None:
                    by_remote.setdefault(e["owner"], []).append(i)
            for owner, idxs in by_remote.items():
                try:
                    hits = self._send_idempotent(
                        owner, ACTION_FETCH,
                        {"context_id": remote_ctx[owner],
                         "positions": [page[i]["pos"] for i in idxs]},
                        deadline=deadline)
                except Exception as e:
                    # an owner that died BETWEEN query and fetch: its
                    # page hits drop, its shards are reported failed, the
                    # rest of the page still serves (reference: fetch-
                    # phase ShardSearchFailure accounting). Drop its
                    # context from the free list too — the finally's
                    # synchronous free would block the response on the
                    # same dead peer; the owner's TTL pruning collects it
                    remote_ctx.pop(owner, None)
                    for sid in sorted({page[i]["shard"] for i in idxs}):
                        failed.append(shard_failure_entry(index, sid, e,
                                                          node=owner))
                    continue
                remote_ctx.pop(owner, None)  # served: nothing to free
                for i, h in zip(idxs, hits):
                    hit_of[i] = h
        finally:
            # owners whose contexts were never fetched (no page hits, or an
            # error later in the scatter/fetch) must not leak parked results
            self._free_remote(remote_ctx)
            remote_ctx.clear()

        # a deadline blown mid-scatter/fetch surfaces as timed_out=true
        # ONLY when it degraded something (failure entries exist) — a
        # slow-but-complete search is complete, not timed out
        timed_out |= bool(failed) and time.monotonic() > deadline
        response: Dict[str, Any] = {
            "took": int((time.perf_counter() - t0) * 1000),
            "timed_out": timed_out,
            "_shards": {"total": meta["num_shards"],
                        "successful": meta["num_shards"] - len(failed),
                        "failed": len(failed)},
            "hits": {
                "total": total,
                "max_score": (None if (max_score == float("-inf")
                                       or sort_spec) else max_score),
                # fetch-failed owners' hits are absent from hit_of: the
                # page compacts around them (partial results, not holes)
                "hits": [hit_of[i] for i in range(len(page))
                         if i in hit_of],
            },
        }
        if failed:
            response["_shards"]["failures"] = failed
        if terminated:
            response["terminated_early"] = True
        if profiles:
            response["profile"] = {"shards": profiles}
        agg_tree = parse_aggs(body.get("aggs") or body.get("aggregations"))
        if agg_tree and agg_lists:
            response["aggregations"] = reduce_aggs(agg_tree, agg_lists)
        if body.get("suggest"):
            # a dead peer already shows in the query phase's _shards above
            response["suggest"] = self.suggest_fan(index,
                                                   body["suggest"])[0]
        if scroll:
            from elasticsearch_tpu.search.service import register_scroll_hits

            full = response["hits"]["hits"]
            # search_type=scan: the first response carries NO hits by
            # contract — everything serves via scroll pages (clients like
            # helpers.scan discard the initial page)
            is_scan = str(body.get("search_type", "")) == "scan"
            response["_scroll_id"] = register_scroll_hits(
                {"size": page_size}, full, total,
                consumed=0 if is_scan else page_size)
            response["hits"]["hits"] = [] if is_scan else full[:page_size]
        return response


def _shard_profile(owner: str, index: str, sid: int, tpu: dict) -> dict:
    """One cross-host ``profile.shards[]`` entry: the owner NODE joins
    the label (the reference's profile shard ids carry the node id).
    The envelope time is the timer's MEASURED wall total — phase buckets
    overlap (topk also files under device_*), so a phase sum would
    over-report."""
    from elasticsearch_tpu.tracing.profiler import shard_profile_entry

    return shard_profile_entry(f"[{owner}][{index}][{sid}]",
                               int((tpu or {}).get("query_total_nanos", 0)),
                               tpu)


def _fetch_grouped(triples: List[Tuple[Any, Any, Any]], body: dict,
                   index_name: str) -> Dict[Any, dict]:
    """(key, searcher, ShardDoc) triples → {key: hit JSON}, batching the
    fetch phase per searcher (shared by the fetch endpoint and the
    coordinator's local-shard fetch)."""
    by_searcher: Dict[int, List[Tuple[Any, Any]]] = {}
    searchers: Dict[int, Any] = {}
    for key, searcher, doc in triples:
        searchers[id(searcher)] = searcher
        by_searcher.setdefault(id(searcher), []).append((key, doc))
    out: Dict[Any, dict] = {}
    for sk, items in by_searcher.items():
        hits = searchers[sk].fetch_phase([d for _, d in items], body,
                                         index_name)
        for (key, _d), h in zip(items, hits):
            out[key] = h
    return out
