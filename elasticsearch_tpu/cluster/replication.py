"""Write replication: primary → replica fanout and failover promotion.

Reference: org/elasticsearch/action/support/replication/
TransportShardReplicationOperationAction.java — a write executes on the
primary, then fans out synchronously to every assigned replica; a replica
that fails the op is failed-and-rerouted rather than failing the client
write. Primary failure promotes an in-sync replica
(cluster/routing/allocation — PRIMARY promotion on reroute).

TPU adaptation: replicas are full IndexShards (engine + searcher) holding
their own device-resident segments. Replication replays the logical op with
the PRIMARY's assigned version under external_gte, which makes fanout
idempotent and keeps replicas convergent (same trick the reference uses
with sequence numbers in later versions; ES 2.0 ships the version the same
way). Search can read any in-sync copy (preference _primary / _replica /
round-robin), mirroring query-then-fetch shard selection.
"""
from __future__ import annotations

import threading
from typing import Any, Callable, List, Optional

from elasticsearch_tpu.utils.errors import ElasticsearchTpuException


class ReplicationGroup:
    """One shard's copies: a primary plus N replicas."""

    def __init__(self, shard_id: int, primary, replicas: Optional[list] = None,
                 on_replica_failure: Optional[Callable] = None):
        self.shard_id = shard_id
        self.primary = primary
        self.replicas: List[Any] = list(replicas or [])
        self.failed_replicas: List[Any] = []
        self.on_replica_failure = on_replica_failure
        self._lock = threading.RLock()
        self._read_rr = 0

    # -- writes ----------------------------------------------------------------

    def index(self, doc_id, source, **kw):
        """Execute on primary, then fan out with the primary's version.

        Returns (id, version, created, replicas_failed_this_write)."""
        with self._lock:
            rid, version, created = self.primary.engine.index(doc_id, source, **kw)
            failed = self._fanout("index", rid, source=source, version=version, kw=kw)
            return rid, version, created, failed

    def delete(self, doc_id, **kw):
        with self._lock:
            version = self.primary.engine.delete(doc_id, **kw)
            failed = self._fanout("delete", doc_id, version=version, kw=kw)
            return version, failed

    def _fanout(self, op: str, doc_id, source=None, version=None, kw=None) -> int:
        """Returns how many replicas failed (and were dropped) on this op."""
        kw = dict(kw or {})
        kw.pop("version", None)
        kw.pop("version_type", None)
        kw.pop("op_type", None)
        failed = 0
        for replica in list(self.replicas):
            try:
                # _replay=True: replicas keep no translog of their own —
                # durability lives on the primary; a replica re-syncs via
                # peer recovery, so logging each op here would only grow an
                # in-memory log without bound
                if op == "index":
                    replica.engine.index(doc_id, source, version=version,
                                         version_type="external_gte",
                                         _replay=True, **kw)
                else:
                    try:
                        replica.engine.delete(doc_id, _replay=True)
                    except ElasticsearchTpuException:
                        pass  # already absent on the replica
            except Exception:
                # reference behavior: a failing replica is failed out of the
                # group (and reported to the master for reroute), the client
                # write still succeeds — but the _shards section reports it
                if replica in self.replicas:
                    self.replicas.remove(replica)
                    self.failed_replicas.append(replica)
                failed += 1
                if self.on_replica_failure:
                    self.on_replica_failure(self.shard_id, replica)
        return failed

    def replicate_current(self, doc_id: str):
        """Fan out the primary's CURRENT state of doc_id (used after partial
        updates, where the merged source only exists on the primary)."""
        with self._lock:
            eng = self.primary.engine
            loc = eng._locations.get(str(doc_id))
            if loc is None or loc.deleted:
                self._fanout("delete", doc_id)
                return
            got = eng.get(str(doc_id))
            self._fanout("index", str(doc_id), source=got["_source"],
                         version=loc.version,
                         kw={"routing": loc.routing, "doc_type": loc.doc_type,
                             "parent": loc.parent})

    # -- failover --------------------------------------------------------------

    def fail_primary(self):
        """Promote the first in-sync replica (reference: primary failure →
        allocation promotes an active replica copy)."""
        with self._lock:
            if not self.replicas:
                raise ElasticsearchTpuException(
                    f"shard [{self.shard_id}]: no replica to promote")
            old = self.primary
            self.primary = self.replicas.pop(0)
            self.failed_replicas.append(old)
            return self.primary

    # -- reads -----------------------------------------------------------------

    def reader(self, preference: Optional[str] = None):
        """Pick the copy a search should read (query-then-fetch shard pick)."""
        with self._lock:
            if preference == "_primary" or not self.replicas:
                return self.primary
            if preference == "_replica":
                return self.replicas[0]
            copies = [self.primary] + self.replicas
            self._read_rr = (self._read_rr + 1) % len(copies)
            return copies[self._read_rr]

    @property
    def copies(self) -> list:
        return [self.primary] + list(self.replicas)

    def refresh(self):
        for c in self.copies:
            c.refresh()
