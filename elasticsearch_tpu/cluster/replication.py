"""Write replication: primary → replica fanout and failover promotion.

Reference: org/elasticsearch/action/support/replication/
TransportShardReplicationOperationAction.java — a write executes on the
primary, then fans out synchronously to every assigned replica; a replica
that fails the op is failed-and-rerouted rather than failing the client
write. Primary failure promotes an in-sync replica
(cluster/routing/allocation — PRIMARY promotion on reroute).

Replication safety (the ES 6.x seq-no upgrade, index/seqno.py): the
primary stamps every op with its current PRIMARY TERM and a fresh
SEQUENCE NUMBER; replicas replay the op under that identity and REJECT
ops from a stale term (StalePrimaryException — the zombie-primary fence).
The group keeps an explicit IN-SYNC copy set in a GlobalCheckpointTracker:
the global checkpoint (min local checkpoint over in-sync copies) is what
peer recovery negotiates against, promotion only ever selects an in-sync
copy, and a replica that fails a write leaves the set until it re-syncs.

TPU adaptation: replicas are full IndexShards (engine + searcher) holding
their own device-resident segments. Replication replays the logical op
with the PRIMARY's assigned version under external_gte, which keeps
fanout idempotent and replicas convergent. Search can read any in-sync
copy (preference _primary / _replica / round-robin), mirroring
query-then-fetch shard selection.
"""
from __future__ import annotations

import threading
from typing import Any, Callable, List, Optional

from elasticsearch_tpu.index.seqno import GlobalCheckpointTracker
from elasticsearch_tpu.utils.errors import (
    ElasticsearchTpuException,
    StalePrimaryException,
)
from elasticsearch_tpu.utils.faults import FAULTS


class ReplicationGroup:
    """One shard's copies: a primary plus N replicas.

    Lock order: ``ReplicationGroup._lock`` is OUTERMOST for a
    replicated write — under it we enter the primary/replica engines
    (``Engine._lock`` → ``Translog._lock``) and the checkpoint tracker
    (``GlobalCheckpointTracker._lock``). tpulint R013's interprocedural
    lock graph verifies the whole chain acyclic; never report back into
    the group from under an engine lock.
    """

    def __init__(self, shard_id: int, primary, replicas: Optional[list] = None,
                 on_replica_failure: Optional[Callable] = None):
        self.shard_id = shard_id
        self.primary = primary
        self.replicas: List[Any] = list(replicas or [])
        self.failed_replicas: List[Any] = []
        self.on_replica_failure = on_replica_failure
        self._lock = threading.RLock()
        self._read_rr = 0
        # explicit in-sync copy set, keyed by engine commit id (the
        # in-process analogue of the reference's allocation ids)
        self.checkpoints = GlobalCheckpointTracker(
            in_sync=[c.engine.commit_id for c in self.copies])

    # -- writes ----------------------------------------------------------------

    @property
    def primary_term(self) -> int:
        return self.primary.engine.primary_term

    def index(self, doc_id, source, **kw):
        """Execute on primary, then fan out with the primary's assigned
        (version, seq_no, term) identity.

        Returns (id, version, created, replicas_failed, seq_no, term)."""
        with self._lock:
            rid, version, created = self.primary.engine.index(doc_id, source, **kw)
            loc = self.primary.engine._locations[rid]
            seq_no, term = loc.seq_no, loc.term
            failed = self._fanout("index", rid, source=source, version=version,
                                  seq_no=seq_no, term=term, kw=kw)
            self._note_checkpoints()
            return rid, version, created, failed, seq_no, term

    def delete(self, doc_id, **kw):
        with self._lock:
            version = self.primary.engine.delete(doc_id, **kw)
            loc = self.primary.engine._locations.get(str(doc_id))
            seq_no = loc.seq_no if loc else -2
            term = loc.term if loc else self.primary_term
            failed = self._fanout("delete", doc_id, version=version,
                                  seq_no=seq_no, term=term, kw=kw)
            self._note_checkpoints()
            return version, failed, seq_no, term

    def _fanout(self, op: str, doc_id, source=None, version=None,
                seq_no=None, term=None, kw=None) -> int:
        """Returns how many replicas failed (and were dropped) on this op.
        A STALE-TERM rejection is different in kind: the replica is fine,
        it is THIS primary that was demoted — the exception propagates so
        the write is never acknowledged (the zombie-primary fence)."""
        kw = dict(kw or {})
        for k in ("version", "version_type", "op_type", "seq_no",
                  "primary_term"):
            kw.pop(k, None)
        failed = 0
        for replica in list(self.replicas):
            try:
                FAULTS.check("replication.fanout", shard=self.shard_id,
                             op=op, id=str(doc_id))
                # _replay=True: replicas keep no translog of their own —
                # durability lives on the primary; a replica re-syncs via
                # peer recovery, so logging each op here would only grow an
                # in-memory log without bound
                if op == "index":
                    replica.engine.index(doc_id, source, version=version,
                                         version_type="external_gte",
                                         seq_no=seq_no, primary_term=term,
                                         _replay=True, **kw)
                else:
                    try:
                        replica.engine.delete(doc_id, seq_no=seq_no,
                                              primary_term=term,
                                              _replay=True)
                    except StalePrimaryException:
                        raise
                    except ElasticsearchTpuException:
                        # already absent on the replica: a no-op, but the
                        # seq no still counts as processed (checkpoint
                        # must not stall on the hole)
                        replica.engine.note_noop(seq_no, term)
            except StalePrimaryException:
                raise  # demoted primary: never ack, never demote the replica
            except Exception:
                # reference behavior: a failing replica is failed out of the
                # group (and reported to the master for reroute), the client
                # write still succeeds — but the _shards section reports it.
                # It also leaves the in-sync set: a copy that missed an
                # acknowledged write must never be promotable again until
                # recovery re-syncs it.
                if replica in self.replicas:
                    self.replicas.remove(replica)
                    self.failed_replicas.append(replica)
                    self.checkpoints.remove(replica.engine.commit_id)
                failed += 1
                if self.on_replica_failure:
                    self.on_replica_failure(self.shard_id, replica)
        return failed

    def _note_checkpoints(self) -> None:
        """Report every live copy's local checkpoint into the tracker;
        the global checkpoint is their in-sync minimum."""
        for c in self.copies:
            self.checkpoints.update_local(c.engine.commit_id,
                                          c.engine.local_checkpoint)

    @property
    def global_checkpoint(self) -> int:
        return self.checkpoints.global_checkpoint

    def replicate_current(self, doc_id: str):
        """Fan out the primary's CURRENT state of doc_id (used after partial
        updates, where the merged source only exists on the primary)."""
        with self._lock:
            eng = self.primary.engine
            loc = eng._locations.get(str(doc_id))
            if loc is None or loc.deleted:
                seq_no = loc.seq_no if loc else None
                term = loc.term if loc else self.primary_term
                self._fanout("delete", doc_id, seq_no=seq_no, term=term)
                return
            got = eng.get(str(doc_id))
            self._fanout("index", str(doc_id), source=got["_source"],
                         version=loc.version, seq_no=loc.seq_no,
                         term=loc.term,
                         kw={"routing": loc.routing, "doc_type": loc.doc_type,
                             "parent": loc.parent})
            self._note_checkpoints()

    # -- failover --------------------------------------------------------------

    def fail_primary(self):
        """Promote the first in-sync replica under a BUMPED primary term
        (reference: primary failure → allocation promotes an active
        in-sync copy and increments the shard's primary term). The old
        primary leaves the in-sync set; any op still carrying its term is
        fenced by every surviving copy."""
        with self._lock:
            in_sync = self.checkpoints.in_sync
            candidates = [r for r in self.replicas
                          if r.engine.commit_id in in_sync]
            if not candidates:
                raise ElasticsearchTpuException(
                    f"shard [{self.shard_id}]: no in-sync replica to promote")
            old = self.primary
            new_term = max(c.engine.primary_term for c in self.copies) + 1
            promoted = candidates[0]
            self.replicas.remove(promoted)
            self.primary = promoted
            self.primary.engine.bump_term(new_term)
            self.failed_replicas.append(old)
            self.checkpoints.remove(old.engine.commit_id)
            return self.primary

    # -- reads -----------------------------------------------------------------

    def reader(self, preference: Optional[str] = None):
        """Pick the copy a search should read (query-then-fetch shard pick)."""
        with self._lock:
            if preference == "_primary" or not self.replicas:
                return self.primary
            if preference == "_replica":
                return self.replicas[0]
            copies = [self.primary] + self.replicas
            self._read_rr = (self._read_rr + 1) % len(copies)
            return copies[self._read_rr]

    @property
    def copies(self) -> list:
        return [self.primary] + list(self.replicas)

    def refresh(self):
        for c in self.copies:
            c.refresh()
