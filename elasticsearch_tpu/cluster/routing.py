"""Shard routing & allocation: which node (and mesh device) owns each copy.

Reference: org/elasticsearch/cluster/routing/OperationRouting.java (doc →
shard hash), routing/allocation/AllocationService.java and the decider
chain under routing/allocation/decider/ (SameShardAllocationDecider,
FilterAllocationDecider, ThrottlingAllocationDecider, …), plus
BalancedShardsAllocator for even spread.

TPU adaptation: a node here is a host process; within it, shard → device
placement on the jax Mesh is handled by parallel/placement.py. Allocation
across nodes follows the same decider pattern as the reference so the
multi-host design (jax.distributed, one process per host) drops in without
changing the algorithm.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional

from elasticsearch_tpu.cluster.state import DiscoveryNode, ShardRouting
from elasticsearch_tpu.utils.hashing import routing_hash


# -- operation routing ---------------------------------------------------------

def shard_id_for(doc_id: str, num_shards: int, routing: Optional[str] = None) -> int:
    """OperationRouting.generateShardId: murmur3(routing ?: id) % shards —
    the reference's exact UTF-16LE signed murmur, so doc→shard placement
    matches ES 2.0 byte for byte."""
    key = routing if routing is not None else str(doc_id)
    return routing_hash(key) % num_shards


def select_primary(owners: List[str], in_sync: List[str]) -> List[str]:
    """The replication-safety promotion rule (reference: the allocation
    pass promoting primaries from the in-sync allocation ids): reorder
    ``owners`` so an IN-SYNC copy leads. A copy that missed an
    acknowledged write or is still recovering must never become primary —
    that would silently roll back acks — so when NO in-sync copy
    survives, the answer is an empty list (shard red; gateway
    resurrection may later re-adopt from on-disk data) rather than a
    non-in-sync promotion. Used by the master's reconcile pass
    (cluster/search_action.py) on every membership change."""
    if not owners:
        return []
    if owners[0] in in_sync:
        return list(owners)
    promotable = [o for o in owners if o in in_sync]
    if not promotable:
        return []
    first = promotable[0]
    return [first] + [o for o in owners if o != first]


# -- allocation deciders -------------------------------------------------------

ALWAYS, THROTTLE, NO = "YES", "THROTTLE", "NO"


class Decider:
    name = "base"

    def can_allocate(self, shard: ShardRouting, node: DiscoveryNode,
                     allocation: "Allocation") -> str:
        return ALWAYS


class SameShardDecider(Decider):
    """A node must not hold two copies of the same shard (reference:
    SameShardAllocationDecider)."""

    name = "same_shard"

    def can_allocate(self, shard, node, allocation):
        for existing in allocation.assigned:
            if (existing.index == shard.index and existing.shard_id == shard.shard_id
                    and existing.node_id == node.node_id):
                return NO
        return ALWAYS


class FilterDecider(Decider):
    """index.routing.allocation.{include,exclude,require}.<attr> settings
    (reference: FilterAllocationDecider)."""

    name = "filter"

    def __init__(self, index_settings: Optional[dict] = None):
        s = (index_settings or {}).get("index", index_settings or {})
        alloc = s.get("routing", {}).get("allocation", {})
        self.include = alloc.get("include", {})
        self.exclude = alloc.get("exclude", {})
        self.require = alloc.get("require", {})

    @staticmethod
    def _matches(rule_val: str, node_val: Optional[str]) -> bool:
        return node_val is not None and node_val in [v.strip() for v in str(rule_val).split(",")]

    def can_allocate(self, shard, node, allocation):
        attrs = dict(node.attributes)
        attrs.setdefault("_name", node.name)
        attrs.setdefault("_id", node.node_id)
        for k, v in self.require.items():
            if not self._matches(v, attrs.get(k)):
                return NO
        for k, v in self.exclude.items():
            if self._matches(v, attrs.get(k)):
                return NO
        if self.include:
            if not any(self._matches(v, attrs.get(k)) for k, v in self.include.items()):
                return NO
        return ALWAYS


class ThrottlingDecider(Decider):
    """Cap concurrent incoming recoveries per node (reference:
    ThrottlingAllocationDecider, node_concurrent_recoveries)."""

    name = "throttling"

    def __init__(self, concurrent_recoveries: int = 2):
        self.concurrent = concurrent_recoveries

    def can_allocate(self, shard, node, allocation):
        initializing = sum(1 for r in allocation.assigned
                           if r.node_id == node.node_id and r.state == "INITIALIZING")
        return THROTTLE if initializing >= self.concurrent else ALWAYS


@dataclass
class Allocation:
    """Mutable allocation round state."""

    nodes: List[DiscoveryNode]
    assigned: List[ShardRouting] = field(default_factory=list)


class ShardAllocator:
    """Balanced allocation with a decider chain (reference:
    AllocationService.reroute + BalancedShardsAllocator: pick the eligible
    node with the fewest shards)."""

    def __init__(self, deciders: Optional[List[Decider]] = None):
        self.deciders = deciders if deciders is not None else [
            SameShardDecider(), ThrottlingDecider()]

    def decide(self, shard: ShardRouting, node: DiscoveryNode,
               allocation: Allocation) -> str:
        verdict = ALWAYS
        for d in self.deciders:
            v = d.can_allocate(shard, node, allocation)
            if v == NO:
                return NO
            if v == THROTTLE:
                verdict = THROTTLE
        return verdict

    def allocate_index(self, index: str, num_shards: int, num_replicas: int,
                       nodes: List[DiscoveryNode],
                       index_settings: Optional[dict] = None,
                       state: str = "STARTED") -> List[ShardRouting]:
        """Assign every copy of every shard; unassignable copies come back
        with state UNASSIGNED (=> yellow/red health, like the reference)."""
        chain = self
        if index_settings:
            chain = ShardAllocator(self.deciders + [FilterDecider(index_settings)])
        alloc = Allocation(nodes=nodes)
        out: List[ShardRouting] = []
        for sid in range(num_shards):
            for copy in range(1 + num_replicas):
                shard = ShardRouting(index, sid, node_id="", primary=(copy == 0),
                                     state="UNASSIGNED")
                # fewest-shards-first among eligible nodes
                counts: Dict[str, int] = {n.node_id: 0 for n in nodes}
                for r in alloc.assigned:
                    counts[r.node_id] = counts.get(r.node_id, 0) + 1
                best = None
                for node in sorted(nodes, key=lambda n: counts.get(n.node_id, 0)):
                    v = chain.decide(shard, node, alloc)
                    if v == ALWAYS:
                        best = node
                        break
                    if v == THROTTLE and best is None:
                        best = node  # throttled target still wins over none
                if best is not None:
                    shard.node_id = best.node_id
                    # NOTE: pass state="INITIALIZING" for recovery-time
                    # allocation so ThrottlingDecider's cap is live; the
                    # default STARTED models already-recovered placement
                    shard.state = state
                alloc.assigned.append(shard)
                out.append(shard)
        return out
