"""Shard routing & allocation: which node (and mesh device) owns each copy.

Reference: org/elasticsearch/cluster/routing/OperationRouting.java (doc →
shard hash), routing/allocation/AllocationService.java and the decider
chain under routing/allocation/decider/ (SameShardAllocationDecider,
FilterAllocationDecider, ThrottlingAllocationDecider, …), plus
BalancedShardsAllocator for even spread.

TPU adaptation: a node here is a host process; within it, shard → device
placement on the jax Mesh is handled by parallel/placement.py. Allocation
across nodes follows the same decider pattern as the reference so the
multi-host design (jax.distributed, one process per host) drops in without
changing the algorithm.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

from elasticsearch_tpu.cluster.state import DiscoveryNode, ShardRouting
from elasticsearch_tpu.utils.hashing import routing_hash


# -- operation routing ---------------------------------------------------------

def shard_id_for(doc_id: str, num_shards: int, routing: Optional[str] = None) -> int:
    """OperationRouting.generateShardId: murmur3(routing ?: id) % shards —
    the reference's exact UTF-16LE signed murmur, so doc→shard placement
    matches ES 2.0 byte for byte."""
    key = routing if routing is not None else str(doc_id)
    return routing_hash(key) % num_shards


def select_primary(owners: List[str], in_sync: List[str],
                   checkpoints: Optional[Dict[str, int]] = None) -> List[str]:
    """The replication-safety promotion rule (reference: the allocation
    pass promoting primaries from the in-sync allocation ids): reorder
    ``owners`` so an IN-SYNC copy leads. A copy that missed an
    acknowledged write or is still recovering must never become primary —
    that would silently roll back acks — so when NO in-sync copy
    survives, the answer is an empty list (shard red; gateway
    resurrection may later re-adopt from on-disk data) rather than a
    non-in-sync promotion.

    Among the promotable in-sync copies, ``checkpoints`` (node id →
    local checkpoint, best-effort) breaks the tie by RECENCY: the copy
    with the highest local checkpoint wins, so the promotion's follow-up
    re-replication replays the shortest op suffix to the other
    survivors. Copies with no report sort below any reported one (an
    unreachable copy must not out-rank a known-fresh one on position
    alone); with no map at all the owners order decides, as before.
    Used by the master's reconcile pass (cluster/search_action.py) on
    every membership change."""
    if not owners:
        return []
    if owners[0] in in_sync:
        # the sitting primary survived in-sync: no promotion happens, so
        # recency must not reorder (a spurious reorder would bump the
        # term and fence in-flight ops for nothing)
        return list(owners)
    promotable = [o for o in owners if o in in_sync]
    if not promotable:
        return []
    if checkpoints:
        best = max(promotable,
                   key=lambda o: (checkpoints.get(o, -2),
                                  -owners.index(o)))
    else:
        best = promotable[0]
    return [best] + [o for o in owners if o != best]


# -- allocation deciders -------------------------------------------------------

ALWAYS, THROTTLE, NO = "YES", "THROTTLE", "NO"


class Decider:
    name = "base"

    def can_allocate(self, shard: ShardRouting, node: DiscoveryNode,
                     allocation: "Allocation") -> str:
        return ALWAYS


class SameShardDecider(Decider):
    """A node must not hold two copies of the same shard (reference:
    SameShardAllocationDecider)."""

    name = "same_shard"

    def can_allocate(self, shard, node, allocation):
        for existing in allocation.assigned:
            if (existing.index == shard.index and existing.shard_id == shard.shard_id
                    and existing.node_id == node.node_id):
                return NO
        return ALWAYS


class FilterDecider(Decider):
    """index.routing.allocation.{include,exclude,require}.<attr> settings
    (reference: FilterAllocationDecider)."""

    name = "filter"

    def __init__(self, index_settings: Optional[dict] = None):
        s = (index_settings or {}).get("index", index_settings or {})
        alloc = s.get("routing", {}).get("allocation", {})
        self.include = alloc.get("include", {})
        self.exclude = alloc.get("exclude", {})
        self.require = alloc.get("require", {})

    @staticmethod
    def _matches(rule_val: str, node_val: Optional[str]) -> bool:
        return node_val is not None and node_val in [v.strip() for v in str(rule_val).split(",")]

    def can_allocate(self, shard, node, allocation):
        attrs = dict(node.attributes)
        attrs.setdefault("_name", node.name)
        attrs.setdefault("_id", node.node_id)
        for k, v in self.require.items():
            if not self._matches(v, attrs.get(k)):
                return NO
        for k, v in self.exclude.items():
            if self._matches(v, attrs.get(k)):
                return NO
        if self.include:
            if not any(self._matches(v, attrs.get(k)) for k, v in self.include.items()):
                return NO
        return ALWAYS


class ThrottlingDecider(Decider):
    """Cap concurrent incoming recoveries per node (reference:
    ThrottlingAllocationDecider, node_concurrent_recoveries)."""

    name = "throttling"

    def __init__(self, concurrent_recoveries: int = 2):
        self.concurrent = concurrent_recoveries

    def can_allocate(self, shard, node, allocation):
        initializing = sum(1 for r in allocation.assigned
                           if r.node_id == node.node_id and r.state == "INITIALIZING")
        return THROTTLE if initializing >= self.concurrent else ALWAYS


class WatermarkDecider(Decider):
    """HBM/host-pressure watermarks over the breakers' ``ESTPU_HBM_BYTES``
    capacity (reference: DiskThresholdDecider, with device memory in
    place of disk). Three thresholds, ES
    ``cluster.routing.allocation.disk.watermark.*`` grammar (percent or
    absolute byte-size strings):

    - **low** — no NEW shard copy is allocated to a node at/over it
      (relocations already under way complete);
    - **high** — the allocator actively moves shards OFF the node
      (:meth:`over_high`);
    - **flood_stage** — the node is an emergency: besides ``NO`` here,
      the allocator treats its shards as first to move.

    ``usage_fn(node_id) -> (used_bytes, capacity_bytes)`` supplies the
    live signal (the allocator's cached per-node usage probe); a node
    with no report allocates freely (an unknown must not strand
    recovery — the reference likewise allocates when disk info is
    missing)."""

    name = "watermark"

    def __init__(self, usage_fn: Callable[[str], Optional[Tuple[int, int]]],
                 low: str = "85%", high: str = "90%",
                 flood_stage: str = "95%"):
        self.usage_fn = usage_fn
        self.set_watermarks(low, high, flood_stage)

    def set_watermarks(self, low, high, flood_stage) -> None:
        self.low, self.high, self.flood_stage = (str(low), str(high),
                                                 str(flood_stage))

    def _threshold(self, spec: str, capacity: int) -> int:
        from elasticsearch_tpu.resources.breakers import parse_limit

        return parse_limit(spec, capacity)

    def level(self, node_id: str) -> str:
        """``ok`` | ``low`` | ``high`` | ``flood`` — the `_cat/allocation`
        watermark column and the allocator's move-away trigger."""
        usage = self.usage_fn(node_id)
        if usage is None:
            return "ok"
        used, capacity = usage
        if capacity <= 0:
            return "ok"
        for name, spec in (("flood", self.flood_stage), ("high", self.high),
                           ("low", self.low)):
            limit = self._threshold(spec, capacity)
            if limit >= 0 and used >= limit:
                return name
        return "ok"

    def over_high(self, node_id: str) -> bool:
        return self.level(node_id) in ("high", "flood")

    def can_allocate(self, shard, node, allocation):
        return NO if self.level(node.node_id) != "ok" else ALWAYS


class LoadDecider(Decider):
    """Serving-pressure signal over the live ``estpu_*`` families
    (per-shard qps, breaker trips, residency eviction churn — the
    allocator's usage probe aggregates them into one per-node score).
    A node whose score is over ``factor ×`` the fleet mean is too hot to
    receive MORE work: rebalancing toward it throttles (it stays a legal
    last resort — recovery of a red shard outranks load shaping, so this
    decider never answers NO)."""

    name = "load"

    def __init__(self, load_fn: Callable[[str], Optional[float]],
                 mean_fn: Callable[[], float], factor: float = 2.0):
        self.load_fn = load_fn
        self.mean_fn = mean_fn
        self.factor = factor

    def can_allocate(self, shard, node, allocation):
        score = self.load_fn(node.node_id)
        if score is None:
            return ALWAYS
        mean = self.mean_fn()
        if mean <= 0.0:
            return ALWAYS
        return THROTTLE if score > self.factor * mean else ALWAYS


class ClusterFilterDecider(Decider):
    """Cluster-level ``cluster.routing.allocation.{include,exclude,
    require}._name/_id`` (reference: the cluster-scope half of
    FilterAllocationDecider) — the node-drain lever: setting
    ``exclude._name`` makes every copy on the named nodes illegal, and
    the allocator relocates them away. Values are comma-separated exact
    names/ids."""

    name = "cluster_filter"

    def __init__(self):
        self.include: Dict[str, str] = {}
        self.exclude: Dict[str, str] = {}
        self.require: Dict[str, str] = {}

    def apply_cluster_settings(self, flat: Dict[str, object]) -> None:
        """Rebuild from the MERGED settings map (absent key = reset),
        the same idempotent contract as the breaker service."""
        prefix = "cluster.routing.allocation."
        for rule in ("include", "exclude", "require"):
            d: Dict[str, str] = {}
            for k, v in flat.items():
                if k.startswith(f"{prefix}{rule}.") and v is not None:
                    d[k[len(prefix) + len(rule) + 1:]] = str(v)
            setattr(self, rule, d)

    def excludes(self, node: DiscoveryNode) -> bool:
        """True when ``node`` is named by an exclude/require rule — the
        drain trigger (can_allocate vetoes NEW copies; this answers
        whether EXISTING copies must move away)."""
        return self.can_allocate(None, node, None) == NO

    def can_allocate(self, shard, node, allocation):
        attrs = dict(node.attributes)
        attrs.setdefault("_name", node.name)
        attrs.setdefault("_id", node.node_id)
        for k, v in self.require.items():
            if not FilterDecider._matches(v, attrs.get(k)):
                return NO
        for k, v in self.exclude.items():
            if FilterDecider._matches(v, attrs.get(k)):
                return NO
        if self.include:
            if not any(FilterDecider._matches(v, attrs.get(k))
                       for k, v in self.include.items()):
                return NO
        return ALWAYS


@dataclass
class Allocation:
    """Mutable allocation round state."""

    nodes: List[DiscoveryNode]
    assigned: List[ShardRouting] = field(default_factory=list)


class ShardAllocator:
    """Balanced allocation with a decider chain (reference:
    AllocationService.reroute + BalancedShardsAllocator: pick the eligible
    node with the fewest shards)."""

    def __init__(self, deciders: Optional[List[Decider]] = None):
        self.deciders = deciders if deciders is not None else [
            SameShardDecider(), ThrottlingDecider()]

    def decide(self, shard: ShardRouting, node: DiscoveryNode,
               allocation: Allocation) -> str:
        verdict = ALWAYS
        for d in self.deciders:
            v = d.can_allocate(shard, node, allocation)
            if v == NO:
                return NO
            if v == THROTTLE:
                verdict = THROTTLE
        return verdict

    def decide_verbose(self, shard: ShardRouting, node: DiscoveryNode,
                       allocation: Allocation) -> List[dict]:
        """Every decider's individual verdict — the ``?explain`` payload
        of ``POST /_cluster/reroute`` (reference: RerouteExplanation's
        Decision.Multi, one entry per decider)."""
        out: List[dict] = []
        for d in self.deciders:
            v = d.can_allocate(shard, node, allocation)
            out.append({"decider": d.name, "decision": v,
                        "explanation":
                            f"[{d.name}] answered {v} for "
                            f"[{shard.index}][{shard.shard_id}] on "
                            f"node [{node.node_id}]"})
        return out

    def allocate_index(self, index: str, num_shards: int, num_replicas: int,
                       nodes: List[DiscoveryNode],
                       index_settings: Optional[dict] = None,
                       state: str = "STARTED") -> List[ShardRouting]:
        """Assign every copy of every shard; unassignable copies come back
        with state UNASSIGNED (=> yellow/red health, like the reference)."""
        chain = self
        if index_settings:
            chain = ShardAllocator(self.deciders + [FilterDecider(index_settings)])
        alloc = Allocation(nodes=nodes)
        out: List[ShardRouting] = []
        for sid in range(num_shards):
            for copy in range(1 + num_replicas):
                shard = ShardRouting(index, sid, node_id="", primary=(copy == 0),
                                     state="UNASSIGNED")
                # fewest-shards-first among eligible nodes
                counts: Dict[str, int] = {n.node_id: 0 for n in nodes}
                for r in alloc.assigned:
                    counts[r.node_id] = counts.get(r.node_id, 0) + 1
                best = None
                for node in sorted(nodes, key=lambda n: counts.get(n.node_id, 0)):
                    v = chain.decide(shard, node, alloc)
                    if v == ALWAYS:
                        best = node
                        break
                    if v == THROTTLE and best is None:
                        best = node  # throttled target still wins over none
                if best is not None:
                    shard.node_id = best.node_id
                    # NOTE: pass state="INITIALIZING" for recovery-time
                    # allocation so ThrottlingDecider's cap is live; the
                    # default STARTED models already-recovered placement
                    shard.state = state
                alloc.assigned.append(shard)
                out.append(shard)
        return out
