"""Rivers — intentionally absent (documented stub, SURVEY §2.11).

Reference: org/elasticsearch/river/ — the pull-based ingestion plugins
deprecated in ES 1.5 and REMOVED in the 2.0 line this rebuild targets
(RiversService remained only as a migration shim). The supported
replacements are the same ones the reference pointed users at: push
ingestion through the bulk API (`POST /_bulk`) or an external feeder
process using the Python client.

Any attempt to register a river raises, matching the reference's removal
rather than pretending support.
"""
from __future__ import annotations

from elasticsearch_tpu.utils.errors import IllegalArgumentException


def register_river(name: str, config: dict) -> None:
    raise IllegalArgumentException(
        f"rivers were removed in the 2.0 line (river [{name}] cannot be "
        f"registered); use the _bulk API or an external feeder instead")
