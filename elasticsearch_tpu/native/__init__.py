"""ctypes bindings for the C++ codec (native/codec.cpp), with pure-python
fallbacks.

The .so is compiled with g++ on first import and cached next to the source
keyed by a source hash, so a source edit triggers a rebuild and a cold
container builds exactly once (~1s). If no compiler is available the
numpy/zlib fallbacks keep every feature working — the codec is a fast
path, not a correctness dependency.
"""
from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import threading
import zlib
from typing import Optional

import numpy as np

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
_SRC = os.path.join(_REPO_ROOT, "native", "codec.cpp")
_BUILD_DIR = os.path.join(_REPO_ROOT, "native", "build")

_lib = None
_lib_tried = False
_lock = threading.Lock()


def _build_and_load() -> Optional[ctypes.CDLL]:
    global _lib, _lib_tried
    with _lock:
        if _lib is not None or _lib_tried:
            return _lib
        _lib_tried = True
        try:
            with open(_SRC, "rb") as f:
                tag = hashlib.sha256(f.read()).hexdigest()[:16]
            so_path = os.path.join(_BUILD_DIR, f"codec_{tag}.so")
            if not os.path.exists(so_path):
                os.makedirs(_BUILD_DIR, exist_ok=True)
                # pid-unique tmp: concurrent first-builds (multiple procs)
                # must not interleave into one file; os.replace is atomic
                tmp = f"{so_path}.{os.getpid()}.tmp.so"
                subprocess.run(
                    ["g++", "-O3", "-shared", "-fPIC", "-std=c++17",
                     "-o", tmp, _SRC],
                    check=True, capture_output=True, timeout=120,
                )
                os.replace(tmp, so_path)
            lib = ctypes.CDLL(so_path)
            u64, i64p, u8p, u32 = (ctypes.c_uint64, ctypes.POINTER(ctypes.c_int64),
                                   ctypes.POINTER(ctypes.c_uint8), ctypes.c_uint32)
            lib.et_crc32.restype = u32
            lib.et_crc32.argtypes = [u8p, u64, u32]
            for fn in ("et_vbyte_encode", "et_delta_encode"):
                getattr(lib, fn).restype = u64
                getattr(lib, fn).argtypes = [i64p, u64, u8p]
            for fn in ("et_vbyte_decode", "et_delta_decode"):
                getattr(lib, fn).restype = u64
                getattr(lib, fn).argtypes = [u8p, u64, i64p, u64]
            _lib = lib
        except Exception:
            _lib = None
        return _lib


def native_available() -> bool:
    return _build_and_load() is not None


def crc32(data: bytes, seed: int = 0) -> int:
    lib = _build_and_load()
    if lib is None:
        return zlib.crc32(data, seed) & 0xFFFFFFFF
    buf = (ctypes.c_uint8 * len(data)).from_buffer_copy(data) if data else (ctypes.c_uint8 * 1)()
    return int(lib.et_crc32(buf, len(data), seed))


def _as_i64(arr) -> np.ndarray:
    return np.ascontiguousarray(np.asarray(arr, dtype=np.int64))


def _encode(arr, fn_native: str, fn_py) -> bytes:
    a = _as_i64(arr)
    lib = _build_and_load()
    if lib is None:
        return fn_py(a)
    out = np.empty(10 * max(1, a.size), dtype=np.uint8)
    n = getattr(lib, fn_native)(
        a.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)), a.size,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)))
    return out[:n].tobytes()


def _decode(data: bytes, count: int, fn_native: str, fn_py) -> np.ndarray:
    lib = _build_and_load()
    if lib is None:
        return fn_py(data, count)
    src = np.frombuffer(data, dtype=np.uint8)
    out = np.empty(count, dtype=np.int64)
    n = getattr(lib, fn_native)(
        src.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)), src.size,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)), count)
    return out[:n]


# -- pure-python fallbacks -----------------------------------------------------

def _py_zigzag(a: np.ndarray) -> np.ndarray:
    return (a.astype(np.uint64) << np.uint64(1)) ^ (a >> np.int64(63)).astype(np.uint64)


def _py_vbyte_encode(a: np.ndarray) -> bytes:
    out = bytearray()
    for u in _py_zigzag(a).tolist():
        while u >= 0x80:
            out.append((u & 0x7F) | 0x80)
            u >>= 7
        out.append(u)
    return bytes(out)


def _py_vbyte_decode(data: bytes, count: int) -> np.ndarray:
    out = np.empty(count, dtype=np.int64)
    i = k = 0
    n = len(data)
    while k < count and i < n:
        u = 0
        shift = 0
        done = False
        while i < n:
            b = data[i]
            i += 1
            u |= (b & 0x7F) << shift
            if not (b & 0x80):
                done = True
                break
            shift += 7
        if not done:
            break
        out[k] = (u >> 1) ^ -(u & 1)
        k += 1
    return out[:k]


def _py_delta_encode(a: np.ndarray) -> bytes:
    return _py_vbyte_encode(np.diff(a, prepend=np.int64(0)))


def _py_delta_decode(data: bytes, count: int) -> np.ndarray:
    return np.cumsum(_py_vbyte_decode(data, count))


# -- public API ----------------------------------------------------------------

def vbyte_encode(arr) -> bytes:
    """zigzag-varint encode an int64 array (Lucene writeVLong family)."""
    return _encode(arr, "et_vbyte_encode", _py_vbyte_encode)


def vbyte_decode(data: bytes, count: int) -> np.ndarray:
    return _decode(data, count, "et_vbyte_decode", _py_vbyte_decode)


def delta_encode(arr) -> bytes:
    """delta + zigzag-varint for sorted sequences (postings doc-id gaps)."""
    return _encode(arr, "et_delta_encode", _py_delta_encode)


def delta_decode(data: bytes, count: int) -> np.ndarray:
    return _decode(data, count, "et_delta_decode", _py_delta_decode)
