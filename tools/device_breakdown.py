"""Break down the single-query device program cost on the real backend.

Times each piece of the hybrid BM25 single-query program at bench shapes
(1M docs) to find where the ~70 ms goes. The tunneled backend's
``block_until_ready`` does not actually block, so every timed program
reduces its big outputs to a handful of scalars ON DEVICE (``max`` —
algebraically irreducible, unlike ``sum``) and the harness times the
host PULL of those scalars: enqueue → execute → tiny d2h, exactly like
the product's packed-result pull. Run: `python tools/device_breakdown.py
[docs]`.
"""
import os
import sys
import time

import numpy as np

docs = int(sys.argv[1]) if len(sys.argv) > 1 else 1 << 20
sys.argv = [sys.argv[0]]
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import bench  # noqa: E402

from elasticsearch_tpu.utils.platform import (  # noqa: E402
    enable_compilation_cache, ensure_cpu_if_requested)

ensure_cpu_if_requested()  # JAX_PLATFORMS=cpu must not touch the tunnel
enable_compilation_cache()

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
from jax import lax  # noqa: E402

from elasticsearch_tpu.index.segment import build_dense_impact  # noqa: E402
from elasticsearch_tpu.search.context import split_runs  # noqa: E402
from elasticsearch_tpu.utils.shapes import pow2_bucket  # noqa: E402

vocab = 30000
u_doc, tf, tfn, offsets, df, idf, doc_len = bench.build_corpus(docs, vocab, 42)
D = pow2_bucket(docs, minimum=64)
nnz = u_doc.shape[0]
nnz_pad = pow2_bucket(nnz, minimum=8)

print(f"docs={docs} D={D} nnz={nnz}", flush=True)
t0 = time.perf_counter()
rows, impact = build_dense_impact(u_doc, tfn, offsets, df, D)
F = impact.shape[0]
print(f"dense block: F={F} ({int((rows >= 0).sum())} dense terms) "
      f"built in {time.perf_counter() - t0:.1f}s", flush=True)

pad_doc = np.full(nnz_pad, D, np.int32)
pad_doc[:nnz] = u_doc
pad_tfn = np.zeros(nnz_pad, np.float32)
pad_tfn[:nnz] = tfn

d_impact = jax.device_put(impact)
d_doc = jax.device_put(pad_doc)
d_tfn = jax.device_put(pad_tfn)

qs = bench.make_queries(16, vocab, df, 42)

# per-query prep exactly like HybridTGroupPrim.build
preps = []
Tmax, Pmax, Rmax = 1, 1, 1
for q in qs:
    qw = np.zeros(F, np.float32)
    runs = []
    qrows, qrw = [], []
    for t in q:
        t = int(t)
        w = float(idf[t])
        r = int(rows[t])
        if r >= 0:
            qw[r] += w
            qrows.append(r)
            qrw.append(w)
        else:
            s0 = int(offsets[t])
            runs.append((s0, int(offsets[t + 1]) - s0, w))
    starts, lens, ws, max_len = split_runs(runs) if runs else ([], [], [], 1)
    Tmax = max(Tmax, len(starts), 1)
    Pmax = max(Pmax, pow2_bucket(max_len))
    Rmax = max(Rmax, len(qrows), 1)
    preps.append((qw, qrows, qrw, starts, lens, ws))
T = pow2_bucket(Tmax, minimum=1)
R = pow2_bucket(Rmax, minimum=1)
P = Pmax
tail_elems = [sum(l for l in p[4]) for p in preps]
print(f"shapes: T={T} P={P} R={R}; tail elems/query "
      f"p50={int(np.median(tail_elems))} max={max(tail_elems)}", flush=True)


def pad(a, n, fill, dtype):
    out = np.full(n, fill, dtype)
    out[: len(a)] = a
    return out


per_q = [(jax.device_put(preps[i][0]),
          jax.device_put(pad(preps[i][1], R, 0, np.int32)),
          jax.device_put(pad(preps[i][2], R, 0.0, np.float32)),
          jax.device_put(pad(preps[i][3], T, 0, np.int32)),
          jax.device_put(pad(preps[i][4], T, 0, np.int32)),
          jax.device_put(pad(preps[i][5], T, 0.0, np.float32)))
         for i in range(len(preps))]

NEG = jnp.float32(-3.4e38)


def scatter_tail(dd, dt, starts, lens, ws):
    def per_chunk(start, length, w):
        clamped = jnp.minimum(start, nnz_pad - P)
        shift = start - clamped
        docs_w = lax.dynamic_slice(dd, (clamped,), (P,))
        tfn_w = lax.dynamic_slice(dt, (clamped,), (P,))
        idxv = jnp.arange(P, dtype=jnp.int32)
        valid = (idxv >= shift) & (idxv < shift + length)
        return docs_w, jnp.where(valid, tfn_w * w, 0.0)

    dws, contrib = jax.vmap(per_chunk)(starts, lens, ws)
    z = jnp.zeros(D, jnp.float32)
    return z.at[dws.reshape(-1)].add(contrib.reshape(-1), mode="drop")


def scatter_tail_sorted(dd, dt, starts, lens, ws):
    """Per-chunk scatter with the unique-indices hint, scan over chunks
    (each postings chunk is sorted by doc id and unique; padding maps to
    the dropped out-of-range row D)."""
    def step(acc, slw):
        start, length, w = slw
        clamped = jnp.minimum(start, nnz_pad - P)
        shift = start - clamped
        docs_w = lax.dynamic_slice(dd, (clamped,), (P,))
        tfn_w = lax.dynamic_slice(dt, (clamped,), (P,))
        idxv = jnp.arange(P, dtype=jnp.int32)
        valid = (idxv >= shift) & (idxv < shift + length)
        docs_m = jnp.where(valid, docs_w, D)
        acc = acc.at[docs_m].add(jnp.where(valid, tfn_w * w, 0.0),
                                 mode="drop", unique_indices=True)
        return acc, None

    z = jnp.zeros(D, jnp.float32)
    acc, _ = lax.scan(step, z, (starts, lens, ws))
    return acc


def dense_mv(imp, qw):
    return jnp.dot(qw, imp, precision=lax.Precision.HIGHEST)


def dense_rowgather(imp, qr, qv):
    return jnp.einsum("r,rd->d", qv, imp[qr],
                      precision=lax.Precision.HIGHEST)


def topk_blocked(s, k=10, block=8192):
    # the PRODUCT's blocked selection — measuring a private copy would
    # silently diverge from what the engine ships
    from elasticsearch_tpu.ops.scoring import exact_topk

    return exact_topk(s, k, block)


# --- timed programs: all reduce to small outputs on device ------------------
def full_current(imp, dd, dt, qw, qr, qv, st, ln, ws):
    dense = dense_mv(imp, qw)
    s = dense + scatter_tail(dd, dt, st, ln, ws)
    m = s > 0
    masked = jnp.where(m, s, NEG)
    vals, idx = lax.top_k(masked, 10)
    return vals, idx, jnp.sum(m.astype(jnp.int32))


def full_new(imp, dd, dt, qw, qr, qv, st, ln, ws):
    dense = dense_rowgather(imp, qr, qv)
    s = dense + scatter_tail_sorted(dd, dt, st, ln, ws)
    m = s > 0
    masked = jnp.where(m, s, NEG)
    vals, idx = topk_blocked(masked)
    return vals, idx, jnp.sum(m.astype(jnp.int32))


d_live = jax.device_put(np.ones(D, bool))


def full_candidates(imp, dd, dt, qw, qr, qv, st, ln, ws):
    """The product's scatter-free fast path (ESTPU_TAIL_MODE=candidates)."""
    from elasticsearch_tpu.ops.scoring import bm25_hybrid_candidates_topk

    return bm25_hybrid_candidates_topk(imp, qr, qv, dd, dt, st, ln, ws,
                                       d_live, P=P, D=D, k=10,
                                       topk_block=8192)


PROGS = {
    # candidates runs FIRST: an arg-pruning/buffer-count interaction with
    # the later jitted programs breaks its re-invocation when it runs last
    "FULL candidates (no scatter)": full_candidates,
    "dense matvec HIGHEST -> max": lambda imp, dd, dt, qw, qr, qv, st, ln, ws:
        dense_mv(imp, qw).max(),
    "dense matvec DEFAULT -> max": lambda imp, dd, dt, qw, qr, qv, st, ln, ws:
        jnp.dot(qw, imp, precision=lax.Precision.DEFAULT).max(),
    "dense row-gather -> max": lambda imp, dd, dt, qw, qr, qv, st, ln, ws:
        dense_rowgather(imp, qr, qv).max(),
    "tail scatter flat -> max": lambda imp, dd, dt, qw, qr, qv, st, ln, ws:
        scatter_tail(dd, dt, st, ln, ws).max(),
    "tail scatter scan/unique -> max": lambda imp, dd, dt, qw, qr, qv, st, ln, ws:
        scatter_tail_sorted(dd, dt, st, ln, ws).max(),
    "dense mv + topk flat": lambda imp, dd, dt, qw, qr, qv, st, ln, ws:
        lax.top_k(dense_mv(imp, qw), 10),
    "dense mv + topk blocked": lambda imp, dd, dt, qw, qr, qv, st, ln, ws:
        topk_blocked(dense_mv(imp, qw)),
    "scatter flat + topk flat": lambda imp, dd, dt, qw, qr, qv, st, ln, ws:
        lax.top_k(scatter_tail(dd, dt, st, ln, ws), 10),
    "mv + scatter -> max (no topk)": lambda imp, dd, dt, qw, qr, qv, st, ln, ws:
        (dense_mv(imp, qw) + scatter_tail(dd, dt, st, ln, ws)).max(),
    "FULL current": full_current,
    "FULL new": full_new,
}


def run(name, jf):
    outs = jf(d_impact, d_doc, d_tfn, *per_q[0])  # compile
    np.asarray(jax.device_get(outs), dtype=object)  # full pull (small)
    times = np.full(len(per_q), np.inf)
    for _ in range(3):
        for i, inp in enumerate(per_q):
            t0 = time.perf_counter()
            jax.device_get(jf(d_impact, d_doc, d_tfn, *inp))
            times[i] = min(times[i], time.perf_counter() - t0)
    print(f"{name:34s} p50 {np.percentile(times * 1000, 50):8.2f} ms "
          f"max {times.max() * 1000:8.2f} ms", flush=True)
    return outs


results = {}
for name, fn in PROGS.items():
    try:
        # the candidates op is already jitted (static P/D/k); an outer
        # jit wrapper trips an arg-pruning/buffer-count mismatch
        # tpulint: allow[R001] — one-shot profiler: each iteration jits a
        # DIFFERENT program exactly once (no per-iteration retrace)
        jf = fn if "candidates" in name else jax.jit(fn)
        results[name] = run(name, jf)
    except Exception as e:
        print(f"{name:34s} FAILED: {type(e).__name__}: {str(e)[:120]}",
              flush=True)
        results[name] = None

if results.get("FULL current") is not None and results.get("FULL new") is not None:
    v1, i1, t1 = [np.asarray(x) for x in results["FULL current"]]
    v2, i2, t2 = [np.asarray(x) for x in results["FULL new"]]
    print(f"agreement: top1 {int(i1[0]) == int(i2[0])}, "
          f"vals close {np.allclose(v1, v2, rtol=2e-5)}, totals {int(t1)}=={int(t2)}")
else:
    print("agreement: skipped (a FULL program failed)")
