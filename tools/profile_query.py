"""Profile the single-query product path on the TPU (bench headline)."""
import cProfile
import io
import pstats
import sys
import time

import numpy as np

docs = int(sys.argv[1]) if len(sys.argv) > 1 else 1 << 18
sys.argv = [sys.argv[0]]  # keep bench's module-level argparse inert
sys.path.insert(0, "/root/repo")
import bench

from elasticsearch_tpu.utils.platform import (enable_compilation_cache,
                                              ensure_cpu_if_requested)

ensure_cpu_if_requested()
enable_compilation_cache()

vocab = 30000
u_doc, tf, tfn, offsets, df, idf, doc_len = bench.build_corpus(docs, vocab, 42)
node, seg = bench.make_msmarco_node(u_doc, tf, tfn, offsets, df, doc_len,
                                    docs, vocab)
seg.inverted["body"].dense_block()
qs = bench.make_queries(8, vocab, df, 42)
bodies = [{"query": {"match": {"body": " ".join(f"t{t}" for t in q)}},
           "size": 10} for q in qs]
for b in bodies:
    node.search("msmarco", b)
# steady state timing
times = []
for _ in range(3):
    for b in bodies:
        t0 = time.perf_counter()
        node.search("msmarco", b)
        times.append(time.perf_counter() - t0)
print(f"docs={docs} p50={np.percentile(np.array(times)*1000, 50):.2f} ms",
      file=sys.stderr)

pr = cProfile.Profile()
pr.enable()
for _ in range(3):
    for b in bodies:
        node.search("msmarco", b)
pr.disable()
s = io.StringIO()
pstats.Stats(pr, stream=s).sort_stats("cumulative").print_stats(45)
print(s.getvalue(), file=sys.stderr)
