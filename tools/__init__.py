"""Developer tools (profilers, A/B benches, tpulint static analysis).

A real package (not a namespace package) so `python -m tools.tpulint`
and test imports resolve regardless of the pytest import mode.
"""
