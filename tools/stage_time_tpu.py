"""Time individual device-program stages with bench-like shapes (CPU)."""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from elasticsearch_tpu.utils.platform import ensure_cpu_if_requested

ensure_cpu_if_requested()
import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

D = 1 << 20
F = 256
nnz = 1 << 26  # scaled ~46M/5.6 for 262k docs
rng = np.random.default_rng(0)


def t(fn, *a, n=5):
    fn(*a)  # compile
    jax.block_until_ready(fn(*a))
    t0 = time.perf_counter()
    for _ in range(n):
        out = fn(*a)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / n * 1000


impact = jnp.asarray(rng.standard_normal((F, D)), jnp.float32)
qw = jnp.asarray(rng.standard_normal(F), jnp.float32)

f_hi = jax.jit(lambda q, i: jnp.dot(q, i, precision=lax.Precision.HIGHEST))
f_def = jax.jit(lambda q, i: jnp.dot(q, i))
print(f"matvec HIGHEST: {t(f_hi, qw, impact):.1f} ms")
print(f"matvec DEFAULT: {t(f_def, qw, impact):.1f} ms")

scores = jnp.asarray(rng.standard_normal(D), jnp.float32)
f_topk = jax.jit(lambda s: lax.top_k(s, 10))
print(f"top_k D={D}: {t(f_topk, scores):.1f} ms")

doc_ids = jnp.asarray(rng.integers(0, D, nnz), jnp.int32)
tfn = jnp.asarray(rng.standard_normal(nnz), jnp.float32)
from elasticsearch_tpu.ops.scoring import bm25_score_segment

for P in (1 << 12, 1 << 15):
    T = 8
    starts = jnp.asarray(rng.integers(0, nnz - P, T), jnp.int32)
    lens = jnp.full(T, P // 2, jnp.int32)
    ws = jnp.ones(T, jnp.float32)
    # tpulint: allow[R001] — microbench: one distinct program per P shape
    # class, each jitted and timed exactly once by design
    f_seg = jax.jit(lambda d, tf, s, l, w: bm25_score_segment(
        d, tf, s, l, w, P=P, D=D))
    print(f"scatter tail P={P} T={T}: {t(f_seg, doc_ids, tfn, starts, lens, ws):.1f} ms")

# full hybrid like the single-query program
from elasticsearch_tpu.ops.scoring import bm25_score_hybrid

P = 1 << 15
T = 8
starts = jnp.asarray(rng.integers(0, nnz - P, T), jnp.int32)
lens = jnp.full(T, P // 2, jnp.int32)
ws = jnp.ones(T, jnp.float32)
f_h = jax.jit(lambda i, q, d, tf, s, l, w: bm25_score_hybrid(
    i, q, d, tf, s, l, w, P=P, D=D))
print(f"hybrid full: {t(f_h, impact, qw, doc_ids, tfn, starts, lens, ws):.1f} ms")
