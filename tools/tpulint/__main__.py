"""CLI: ``python -m tools.tpulint [paths] [--json] [--baseline FILE]``.

Exit codes: 0 = clean (or all findings baselined), 1 = new violations,
2 = usage/baseline error. Run from the repo root so reported paths match
the baseline fingerprints.
"""
from __future__ import annotations

import argparse
import json
import os
import sys

from tools.tpulint.analyzer import RULES, lint_paths

# the directory that contains tools/ — reported paths and baseline
# fingerprints are relative to it no matter where the CLI is invoked from
REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
from tools.tpulint.baseline import (
    DEFAULT_BASELINE,
    filter_baselined,
    load_baseline,
    write_baseline,
)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tools.tpulint",
        description="JAX/TPU-aware static analysis for elasticsearch_tpu "
                    "(rules R001-R007; see docs/STATIC_ANALYSIS.md)")
    ap.add_argument("paths", nargs="*", default=[],
                    help="files or directories to lint "
                         "(default: the repo's elasticsearch_tpu package)")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="emit findings as a JSON document on stdout")
    ap.add_argument("--baseline", default=DEFAULT_BASELINE,
                    help="baseline file of grandfathered findings")
    ap.add_argument("--no-baseline", action="store_true",
                    help="report every finding, ignoring the baseline")
    ap.add_argument("--write-baseline", action="store_true",
                    help="write the current finding set to --baseline "
                         "and exit 0 (dev helper)")
    args = ap.parse_args(argv)

    paths = args.paths or [os.path.join(REPO_ROOT, "elasticsearch_tpu")]
    try:
        found = lint_paths(paths, root=REPO_ROOT)
    except FileNotFoundError as e:
        print(f"tpulint: {e}", file=sys.stderr)
        return 2
    if args.write_baseline:
        doc = write_baseline(found, args.baseline)
        print(f"wrote {len(doc['violations'])} baseline entr"
              f"{'y' if len(doc['violations']) == 1 else 'ies'} "
              f"to {args.baseline}", file=sys.stderr)
        return 0

    try:
        budget = load_baseline(args.baseline) if not args.no_baseline else {}
    except ValueError as e:
        print(f"tpulint: {e}", file=sys.stderr)
        return 2
    new, old = filter_baselined(found, budget)

    if args.as_json:
        print(json.dumps({
            "rules": RULES,
            "violations": [v.to_json() for v in new],
            "baselined": [v.to_json() for v in old],
            "counts": {"new": len(new), "baselined": len(old)},
        }, indent=2))
    else:
        for v in new:
            print(v.format())
        if old:
            print(f"({len(old)} grandfathered finding"
                  f"{'' if len(old) == 1 else 's'} suppressed by "
                  f"{args.baseline})", file=sys.stderr)
        if new:
            print(f"tpulint: {len(new)} violation"
                  f"{'' if len(new) == 1 else 's'}", file=sys.stderr)
    return 1 if new else 0


if __name__ == "__main__":
    sys.exit(main())
