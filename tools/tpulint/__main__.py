"""CLI: ``python -m tools.tpulint [paths] [--json] [--baseline FILE]``.

Runs the two-pass whole-program analyzer (symbol table + call graph,
then the dataflow rules) over the given paths — pass ``--per-file`` to
fall back to the old single-file mode (no traced-context inference, no
R013/R014). ``--changed [BASE]`` builds the full project (the call graph
needs every module) but reports only findings in files changed vs the
git base ref (default HEAD) — the fast pre-commit mode.

Exit codes: 0 = clean (or all findings baselined), 1 = new violations,
2 = usage/baseline error. Run from the repo root so reported paths match
the baseline fingerprints.
"""
from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys

from tools.tpulint.analyzer import RULES, SEVERITY, lint_paths

# the directory that contains tools/ — reported paths and baseline
# fingerprints are relative to it no matter where the CLI is invoked from
REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
from tools.tpulint.baseline import (
    DEFAULT_BASELINE,
    filter_baselined,
    load_baseline,
    write_baseline,
)

# the default whole-program scope: the product package, the tools that
# analyze it, and the bench entry point
DEFAULT_SCOPE = ("elasticsearch_tpu", "tools", "bench.py")

SARIF_SCHEMA = ("https://raw.githubusercontent.com/oasis-tcs/sarif-spec/"
                "master/Schemata/sarif-schema-2.1.0.json")


def _sarif_result(v, suppressed_by: str = "") -> dict:
    out = {
        "ruleId": v.rule,
        "level": SEVERITY.get(v.rule, "warning"),
        "message": {"text": v.message},
        "locations": [{
            "physicalLocation": {
                "artifactLocation": {"uri": v.path,
                                     "uriBaseId": "SRCROOT"},
                "region": {"startLine": max(v.line, 1),
                           "startColumn": v.col + 1,
                           "snippet": {"text": v.snippet}},
            },
        }],
    }
    if suppressed_by:
        out["suppressions"] = [{"kind": "external",
                                "justification": suppressed_by}]
    return out


def _sarif_doc(new, baselined) -> dict:
    """SARIF 2.1.0: one run, every rule in the driver catalogue (ids +
    default severity levels), new findings as plain results, baselined
    findings as suppressed results — CI annotates the former and can
    still audit the latter."""
    rules = [{
        "id": rid,
        "shortDescription": {"text": RULES[rid]},
        "defaultConfiguration": {"level": SEVERITY.get(rid, "warning")},
        "helpUri": "docs/STATIC_ANALYSIS.md",
    } for rid in sorted(RULES)]
    results = [_sarif_result(v) for v in new]
    results += [_sarif_result(v, suppressed_by="grandfathered in "
                              "tools/tpulint/baseline.json")
                for v in baselined]
    return {
        "$schema": SARIF_SCHEMA,
        "version": "2.1.0",
        "runs": [{
            "tool": {"driver": {
                "name": "tpulint",
                "informationUri": "docs/STATIC_ANALYSIS.md",
                "rules": rules,
            }},
            "originalUriBaseIds": {"SRCROOT": {"uri": "file:///./"}},
            "results": results,
        }],
    }


def _changed_files(base: str) -> list:
    """Root-relative python files changed vs ``base``: tracked diffs
    PLUS untracked (not-yet-added) files — a brand-new module with
    violations must not pass the pre-commit mode clean just because
    ``git add`` hasn't run yet.

    ``--name-status -M`` (not ``--name-only``): a plain name listing
    reports a renamed file under its OLD path, which no longer exists
    and was silently skipped — a rename that also edits the file would
    dodge the pre-commit gate entirely. Status parsing follows the
    rename to the new path and drops deletions."""
    out = subprocess.run(
        ["git", "diff", "--name-status", "-M", base, "--"],
        cwd=REPO_ROOT, capture_output=True, text=True, check=True)
    untracked = subprocess.run(
        ["git", "ls-files", "--others", "--exclude-standard"],
        cwd=REPO_ROOT, capture_output=True, text=True, check=True)
    seen = []
    for ln in out.stdout.splitlines():
        parts = ln.rstrip().split("\t")
        if len(parts) < 2:
            continue
        status = parts[0]
        if status.startswith("D"):
            continue  # deleted: nothing to lint
        # renames/copies are "R###\told\tnew" — lint the NEW path
        path = parts[2] if status[:1] in ("R", "C") and len(parts) > 2 \
            else parts[1]
        if path.endswith(".py") and path not in seen:
            seen.append(path)
    for ln in untracked.stdout.splitlines():
        ln = ln.strip()
        if ln.endswith(".py") and ln not in seen:
            seen.append(ln)
    return seen


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tools.tpulint",
        description="JAX/TPU-aware whole-program static analysis for "
                    "elasticsearch_tpu (rules R001-R020; see "
                    "docs/STATIC_ANALYSIS.md)")
    ap.add_argument("paths", nargs="*", default=[],
                    help="files or directories to lint (default: "
                         "elasticsearch_tpu/ + tools/ + bench.py)")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="emit findings as a JSON document on stdout "
                         "(each with a per-rule severity)")
    ap.add_argument("--sarif", action="store_true", dest="as_sarif",
                    help="emit findings as SARIF 2.1.0 on stdout (CI PR "
                         "annotation format); baselined findings ride "
                         "along with a suppression entry")
    ap.add_argument("--baseline", default=DEFAULT_BASELINE,
                    help="baseline file of grandfathered findings")
    ap.add_argument("--no-baseline", action="store_true",
                    help="report every finding, ignoring the baseline")
    ap.add_argument("--write-baseline", action="store_true",
                    help="write the current finding set to --baseline "
                         "and exit 0 (dev helper)")
    ap.add_argument("--prune-baseline", action="store_true",
                    help="audit --baseline for stale entries (findings "
                         "that no longer fire); exits 1 when any are "
                         "stale so the justified list can't rot silently")
    ap.add_argument("--fix", action="store_true",
                    help="with --prune-baseline: rewrite the baseline "
                         "with live entries only (file removed when "
                         "nothing survives)")
    ap.add_argument("--per-file", action="store_true",
                    help="single-file mode: skip the project call graph "
                         "(no traced-context inference, no R013/R014)")
    ap.add_argument("--changed", nargs="?", const="HEAD", default=None,
                    metavar="BASE",
                    help="report only findings in files changed vs the "
                         "git BASE ref (default HEAD); the project index "
                         "is still built over the full default scope so "
                         "interprocedural rules see every caller")
    args = ap.parse_args(argv)

    paths = args.paths or [os.path.join(REPO_ROOT, p)
                           for p in DEFAULT_SCOPE]
    report_only = None
    if args.changed is not None:
        try:
            changed = _changed_files(args.changed)
        except (OSError, subprocess.CalledProcessError) as e:
            print(f"tpulint: --changed failed: {e}", file=sys.stderr)
            return 2
        report_only = set(changed)
        if not report_only:
            # nothing can be reported — skip the project build entirely
            # (the advertised fast path must actually be fast)
            if args.as_sarif:
                print(json.dumps(_sarif_doc([], []), indent=2))
            elif args.as_json:
                print(json.dumps({
                    "rules": RULES, "severity": SEVERITY,
                    "violations": [], "baselined": [],
                    "counts": {"new": 0, "baselined": 0}}, indent=2))
            else:
                print("tpulint: no python files changed", file=sys.stderr)
            return 0
        # changed files outside the default scope still get analyzed
        # (joined into the same project index)
        paths = list(paths) + [
            os.path.join(REPO_ROOT, f) for f in changed
            if os.path.exists(os.path.join(REPO_ROOT, f))
            and not any(f == p or f.startswith(p + "/")
                        for p in DEFAULT_SCOPE)]
    try:
        if args.per_file:
            found = lint_paths(paths, root=REPO_ROOT)
        else:
            from tools.tpulint.project import lint_project

            found = lint_project(paths, root=REPO_ROOT)
    except FileNotFoundError as e:
        print(f"tpulint: {e}", file=sys.stderr)
        return 2
    if args.prune_baseline:
        # staleness is judged against the FULL finding set — a --changed
        # subset would mark every entry outside the diff stale
        from tools.tpulint.baseline import prune_baseline

        stale = prune_baseline(found, args.baseline, fix=args.fix)
        for e in stale:
            print(f"stale baseline entry: {e['rule']} {e['path']} "
                  f"({e['dead']} of {e.get('count', 1)} unused) — "
                  f"{e['snippet']!r}", file=sys.stderr)
        if stale:
            print(f"tpulint: {len(stale)} stale baseline entr"
                  f"{'y' if len(stale) == 1 else 'ies'}"
                  + (" pruned" if args.fix else
                     " (run with --fix to prune)"), file=sys.stderr)
            return 0 if args.fix else 1
        print("tpulint: baseline is live (no stale entries)",
              file=sys.stderr)
        return 0
    if report_only is not None:
        found = [v for v in found if v.path in report_only]
    if args.write_baseline:
        doc = write_baseline(found, args.baseline)
        print(f"wrote {len(doc['violations'])} baseline entr"
              f"{'y' if len(doc['violations']) == 1 else 'ies'} "
              f"to {args.baseline}", file=sys.stderr)
        return 0

    try:
        budget = load_baseline(args.baseline) if not args.no_baseline else {}
    except ValueError as e:
        print(f"tpulint: {e}", file=sys.stderr)
        return 2
    new, old = filter_baselined(found, budget)

    if args.as_sarif:
        print(json.dumps(_sarif_doc(new, old), indent=2))
        return 1 if new else 0
    if args.as_json:
        def _row(v):
            d = v.to_json()
            d["severity"] = SEVERITY.get(v.rule, "warning")
            return d

        print(json.dumps({
            "rules": RULES,
            "severity": SEVERITY,
            "violations": [_row(v) for v in new],
            "baselined": [_row(v) for v in old],
            "counts": {"new": len(new), "baselined": len(old)},
        }, indent=2))
    else:
        for v in new:
            print(v.format())
        if old:
            print(f"({len(old)} grandfathered finding"
                  f"{'' if len(old) == 1 else 's'} suppressed by "
                  f"{args.baseline})", file=sys.stderr)
        if new:
            print(f"tpulint: {len(new)} violation"
                  f"{'' if len(new) == 1 else 's'}", file=sys.stderr)
    return 1 if new else 0


if __name__ == "__main__":
    sys.exit(main())
