"""Runtime retrace auditor — tpulint's dynamic counterpart.

Static analysis (R001) catches the *patterns* that cause recompile storms;
this module catches the storms themselves: it wraps ``jax.jit`` so every
(re)trace of a jitted callable increments a counter, letting benches and
tests assert "steady state traces nothing" instead of inferring it from
latency jitter.

How counting works: ``jax.jit(f)`` executes ``f``'s Python body exactly
once per trace (cache miss), so a counting shim around ``f`` *is* a trace
counter. Each ``jax.jit(...)`` construction gets its own key
(``qualname#seq``) — a cached program re-called with known shapes counts
nothing; a new shape class counts one; the R001 jit-in-loop bug shows up
as an ever-growing key population. Callables jitted *inside* an outer
trace (e.g. a jitted helper vmapped by another jitted fn) count once per
outer trace; that inflation is deterministic and disappears in
steady-state deltas, which is what the assertions use.

Install order matters: the codebase binds ``jax.jit`` at import time
(``@partial(jax.jit, static_argnames=...)``), so call ``install()``
*before* importing ``elasticsearch_tpu``/``bench`` (see tools/tpu_ab.py),
or use the ``trace_audit()`` context manager around code that builds its
programs inside (program factories, tests).
"""
from __future__ import annotations

import functools
import itertools
import threading
from contextlib import contextmanager
from typing import Dict, List, Optional


class TraceBudgetExceeded(AssertionError):
    """A jitted callable retraced more often than the declared bound."""


class TraceAuditor:
    """Per-program trace counters with snapshot/delta helpers."""

    def __init__(self, max_traces: Optional[int] = None):
        self.max_traces = max_traces
        self._lock = threading.Lock()
        self._counts: Dict[str, int] = {}
        # optional per-trace observer: called OUTSIDE the lock as
        # reporter(key, args, kwargs) with the traced call's abstract
        # arguments, so a registry can attribute the compile to a
        # (program, shapes) key (elasticsearch_tpu tracing/retrace.py
        # wires this into the device-program observatory). A reporter
        # failure must never break tracing — exceptions are swallowed.
        self._reporter = None
        # per-thread totals: tracing runs synchronously on the calling
        # thread, so this attributes each trace to the request that paid
        # it — the profiler's compile/execute split reads it to stay
        # correct under concurrent searches (a neighbor thread's
        # first-call compile must not misclassify THIS thread's cached
        # execution). LRU-bounded: a thread-per-connection server would
        # otherwise grow one entry per thread that ever traced, forever.
        # Eviction (and ident reuse) is safe for the snapshot/delta
        # pattern because both reads happen on the SAME live thread
        # within one request.
        from collections import OrderedDict

        self._thread_counts: "OrderedDict[int, int]" = OrderedDict()

    _THREAD_CAP = 512

    def set_reporter(self, fn) -> None:
        """Install the per-trace observer (None to remove)."""
        self._reporter = fn

    def _record(self, key: str, args: tuple = (),
                kwargs: Optional[dict] = None) -> None:
        tid = threading.get_ident()
        with self._lock:
            n = self._counts.get(key, 0) + 1
            self._counts[key] = n
            self._thread_counts[tid] = self._thread_counts.get(tid, 0) + 1
            self._thread_counts.move_to_end(tid)
            while len(self._thread_counts) > self._THREAD_CAP:
                self._thread_counts.popitem(last=False)
        rep = self._reporter
        if rep is not None:
            try:
                rep(key, args, kwargs or {})
            except Exception:
                pass  # observability must never fail the traced program
        if self.max_traces is not None and n > self.max_traces:
            raise TraceBudgetExceeded(
                f"jitted `{key}` traced {n} times "
                f"(budget {self.max_traces}) — recompilation storm; check "
                "static_argnames cardinality and argument shape bucketing")

    def counts(self) -> Dict[str, int]:
        """Per-program trace counts (key = `qualname#construction-seq`)."""
        with self._lock:
            return dict(self._counts)

    def total(self) -> int:
        with self._lock:
            return sum(self._counts.values())

    def thread_total(self) -> int:
        """Traces recorded on the CALLING thread (exact: jit tracing is
        synchronous in the caller)."""
        with self._lock:
            return self._thread_counts.get(threading.get_ident(), 0)

    def snapshot(self) -> Dict[str, int]:
        return self.counts()

    def traces_since(self, snap: Dict[str, int]) -> Dict[str, int]:
        now = self.counts()
        return {k: n - snap.get(k, 0) for k, n in now.items()
                if n - snap.get(k, 0) > 0}

    def assert_max(self, max_traces: int) -> None:
        worst = max(self.counts().values(), default=0)
        if worst > max_traces:
            offenders = [k for k, n in self.counts().items()
                         if n > max_traces]
            raise TraceBudgetExceeded(
                f"{len(offenders)} jitted callable(s) exceeded the "
                f"{max_traces}-trace budget: {sorted(offenders)[:5]}")

    def assert_no_new_traces_since(self, snap: Dict[str, int]) -> None:
        delta = self.traces_since(snap)
        if delta:
            raise TraceBudgetExceeded(
                "steady state retraced: " + ", ".join(
                    f"{k}×{n}" for k, n in sorted(delta.items())[:8]))


_active: List[TraceAuditor] = []
_orig_jit = None
_seq = itertools.count()


def _counting_jit(orig_jit):
    def jit(fun=None, **kwargs):
        if fun is None:  # jax.jit(static_argnames=...) decorator form
            return lambda f: jit(f, **kwargs)
        if not callable(fun):
            return orig_jit(fun, **kwargs)
        key = f"{getattr(fun, '__qualname__', repr(fun))}#{next(_seq)}"

        @functools.wraps(fun)
        def counted(*args, **kw):
            # args are abstract values here (the body runs under trace):
            # reporters read only .shape/.dtype, never concrete data
            for auditor in list(_active):
                auditor._record(key, args, kw)
            return fun(*args, **kw)

        return orig_jit(counted, **kwargs)

    jit.__tpulint_counting__ = True
    return jit


def install(max_traces: Optional[int] = None) -> TraceAuditor:
    """Patch ``jax.jit`` process-wide and return the auditor. Call before
    importing modules that bind jax.jit at import time. Nested installs
    share one patch; each gets its own auditor."""
    global _orig_jit
    import jax

    if not getattr(jax.jit, "__tpulint_counting__", False):
        _orig_jit = jax.jit
        jax.jit = _counting_jit(_orig_jit)
    auditor = TraceAuditor(max_traces=max_traces)
    _active.append(auditor)
    return auditor


def uninstall(auditor: Optional[TraceAuditor] = None) -> None:
    """Detach ``auditor`` (or the most recent). Restores the pristine
    ``jax.jit`` once no auditor is active — already-wrapped callables keep
    working, they just stop counting."""
    global _orig_jit
    import jax

    if auditor is None and _active:
        auditor = _active[-1]
    if auditor in _active:
        _active.remove(auditor)
    if not _active and _orig_jit is not None:
        jax.jit = _orig_jit
        _orig_jit = None


@contextmanager
def trace_audit(max_traces: Optional[int] = None):
    """Context manager: count every trace of jits *constructed inside*,
    optionally enforcing a per-program budget at trace time.

        with trace_audit(max_traces=1) as audit:
            prog = jax.jit(f)
            prog(x); prog(x)          # 1 trace — fine
        audit.counts()                # {'f#0': 1}
    """
    auditor = install(max_traces=max_traces)
    try:
        yield auditor
    finally:
        uninstall(auditor)
