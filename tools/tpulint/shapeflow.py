"""tpulint pass 3: symbolic shape-flow analysis over the device data plane.

Passes 1 and 2 know *where* traced code is (the call-graph fixpoints) and
*what statements* it contains (the per-file rule visitors). Neither knows
what the values flowing through it look like — and the whole eager-scoring
economy rests on value-shape invariants no syntactic rule can check:

* every device program is **statically shaped** — a host dimension that
  reaches a jit static argument or a cached program factory must come
  from a *bounded* universe (pow2 buckets), or every distinct request
  compiles a distinct program (the recompile storm the program
  observatory's shape-key census measures at runtime);
* every variable dimension is **pow2-padded** — which means every array
  entering a mesh program carries *padding lanes*, and a reduction over
  them (`sum`/`max`/`top_k`/`segment_sum`/`psum`) is only sound under a
  dominating validity mask (`jnp.where`, a mask multiply, a live/length
  mask) — otherwise padded lanes leak into scores;
* every MXU matmul runs in its **intended dtype** — bf16 sweeps and f32
  re-ranks mix only at declared cast points, and a stray float64/int64
  spelling in traced code silently promotes the whole path.

This module is an abstract interpreter over the pass-1 project index that
propagates a small shape/dtype lattice through the code and gates those
invariants as four rules:

**The dim lattice (R017).** Host-side integer values classify as::

      Unknown  <  Concrete  <  PaddedPow2  <  DataDependent

  - ``Concrete`` — literals and closure constants (`k = 10`);
  - ``PaddedPow2`` — produced by the padding helpers (`pow2_bucket`,
    `round_up` — utils/shapes.py) or joins of padded values (`max` of
    pow2 buckets is a pow2 bucket: the `Pmax` accumulation idiom);
  - ``DataDependent`` — derived from `len()`, `.shape`/`.size` of host
    data, dict sizes: an unbounded universe;
  - ``Unknown`` — no evidence either way (never alarms).

  Joins take the higher classification, except that the padding helpers
  are *bucketing points*: ``pow2_bucket(anything)`` is PaddedPow2 — the
  `Q = len(qs); Q = pow2_bucket(Q)` rebinding idiom converges to padded,
  not data-dependent. Dim values propagate interprocedurally: a worklist
  fixpoint joins call-site actuals into callee parameters and callee
  return summaries back into call expressions, over the same resolver
  pass 1 uses — so ``Q = len(bodies)`` in search/batch.py is visible at
  the `_bm25_program(..., Q=Q, ...)` edge in parallel/executor.py even
  though no single file shows both.

  **R017 (recompile storm)** fires where a DataDependent value reaches a
  *program factory* call (a function that registers its result with the
  AOT executable cache — `aot.wrap` — the executor's `_*_program`
  family) or a jit static argument, from host code. This generalizes
  R001's third arm (a syntactically-direct `len()` static argument)
  through dataflow: the storm is just as real two assignments and one
  call away. The program observatory's shape-key census is the dynamic
  ground truth this rule approximates statically — a key family the
  census saw vary at runtime must never be classified Concrete here
  (tests/unit cross-validates exactly that on a live node).

**The padded-lane taint (R018).** Inside *collective program bodies*
  (shard_map/`wrap` roots — the mesh invariant says every array entering
  one is pow2-padded), array values classify as::

      Unknown | Tainted | Mask | Validated

  Parameters enter Tainted (padding lanes present, unmasked); parameters
  with mask-like names (`live`, `mask`, ...) and comparison results are
  Mask; `jnp.where(cond, x, y)` and mask multiplies/ands produce
  Validated; elementwise/shape ops propagate; calls the analysis cannot
  see into produce Unknown (no false alarms through helpers).
  **R018 (padding soundness)** fires when a reduction (`sum`/`max`/
  `top_k`/`topk_auto`/`segment_sum`/`psum`/...) consumes a Tainted
  operand: padded lanes reach the reduction with no dominating mask.

**The dtype lattice (R019).** Inside traced functions, local dtypes are
  tracked through `dtype=` keywords and `.astype(...)`; **R019 (dtype
  discipline)** fires on (a) a float64/int64 dtype spelling in traced
  code — the silent-promotion trap — and (b) a matmul (`jnp.dot`/
  `matmul`/`einsum`/`@`/`lax.dot_general`) whose operands are known to
  mix bf16 and f32 outside a declared cast point.

**Reservation release paths (R020).** The resource-accounting twin of
  R015: an acquisition of breaker/residency budget (`track`/`put_array`/
  `force`/`break_or_reserve`/`_reserve`, resolved against the project
  symbol table so arbitrary `.track()` methods don't match) followed by
  fallible calls *before* the token/charge is stored, returned, or
  released, with no enclosing `try` whose handler/finally releases —
  an exception on that path strands the reservation and wedges admission
  control (the breaker counts bytes nobody holds). The clean exemplars
  are residency.py's own `put_array`/`_rehydrate` try/except-release
  pattern.

Contracts: three annotations declare the invariants the interpreter
cannot derive (each a targeted `allow`): ``# tpulint: bucketed`` (R017 —
the dim is bounded/padded by construction upstream), ``# tpulint:
masked`` (R018 — padded lanes are neutral for this reduction: zero-
padded, repeat-padded, or pre-masked upstream), ``# tpulint: cast``
(R019 — a declared MXU cast point).

Everything stays stdlib-``ast`` (no JAX import, no device); the whole-
project pass shares the tier-1 <30s budget with passes 1 and 2, and the
report (`analyze(index)`) carries reach/classification stats for the
bench `analysis` record and the census cross-validation test.
"""
from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Set, Tuple, Union

from tools.tpulint.analyzer import Violation, snippet_at
from tools.tpulint.project import (FnSymbol, ModuleRecord, ProjectIndex,
                                   _Resolver, _attr_chain, _fn_params,
                                   _name)

# ---------------------------------------------------------------------------
# the dim lattice
# ---------------------------------------------------------------------------

UNKNOWN, CONCRETE, PADDED, DATADEP = 0, 1, 2, 3
KIND_NAMES = {UNKNOWN: "Unknown", CONCRETE: "Concrete",
              PADDED: "PaddedPow2", DATADEP: "DataDependent"}


@dataclass(frozen=True)
class Dim:
    """One abstract host-side integer (a candidate shape dim)."""
    kind: int
    origin: str = ""  # provenance of the classification, for messages

    def join(self, other: "Dim") -> "Dim":
        if other.kind > self.kind:
            return other
        if self.kind == other.kind and not self.origin:
            return Dim(self.kind, other.origin)
        return self


DIM_UNKNOWN = Dim(UNKNOWN)
DIM_CONCRETE = Dim(CONCRETE)

#: value of a local can be a single dim or a tuple of dims (a function
#: returning ``(starts, lens, P)`` keeps P's classification addressable
#: through the caller's tuple unpack)
DimVal = Union[Dim, Tuple[Dim, ...]]

# The padding helpers: calling one of these IS the bucketing point, so
# the result is PaddedPow2 regardless of the operand (utils/shapes.py;
# name-matched so fixtures and future helpers with the same contract
# participate without central registration).
PAD_PRODUCER_NAMES = {"pow2_bucket", "round_up"}
# min/max/arithmetic join operand classifications (max of pow2 buckets
# is a pow2 bucket; min(k, D) is bounded by both operands' universes —
# the join keeps the worst one, which is the conservative direction).
DIM_JOIN_CALLS = {"min", "max"}
DIM_TRANSPARENT_CALLS = {"int", "abs"}  # int(x) keeps x's classification


def _join_all(dims: Sequence[Dim]) -> Dim:
    out = DIM_UNKNOWN
    for d in dims:
        out = out.join(d)
    return out


def _as_single(v: DimVal) -> Dim:
    if isinstance(v, tuple):
        return _join_all(v)
    return v


# ---------------------------------------------------------------------------
# the array-taint lattice (R018) and dtype lattice (R019)
# ---------------------------------------------------------------------------

ARR_UNKNOWN, ARR_VALIDATED, ARR_MASK, ARR_TAINT = 0, 1, 2, 3

import re as _re

# parameter/operand names that denote validity masks rather than payload
# arrays: `live`, `mask`, `valid`, `keep`, `exists`, bitvec lanes
_MASKY_RE = _re.compile(r"(?:^|_)(?:mask|live|valid|keep|exists|bits?|"
                        r"sel|hit)s?(?:$|_)", _re.IGNORECASE)

# reductions whose padded-lane soundness R018 gates. Exact-name matched
# on the call chain tail (or the method name): jnp/np reductions, lax
# top-k, segment reductions, mesh collectives, and the in-repo top-k
# dispatcher that takes no mask (`topk_auto` — its mask-aware siblings
# `knn_topk_auto`/`merge_candidate_topk` carry the live mask explicitly
# and are deliberately absent).
REDUCTION_NAMES = {
    "sum", "max", "min", "mean", "prod", "amax", "amin", "argmax",
    "argmin", "nansum", "nanmax", "nanmin", "top_k", "segment_sum",
    "segment_max", "psum", "pmax", "pmin", "pmean", "topk_auto",
    "cumsum", "median", "average",
}
# elementwise / shape ops that PRESERVE the operand's taint state (the
# padding lanes travel along)
_ELEMENTWISE_NAMES = {
    "exp", "log", "log1p", "sqrt", "abs", "negative", "square", "tanh",
    "sigmoid", "clip", "maximum", "minimum", "power", "astype",
    "reshape", "transpose", "ravel", "flatten", "squeeze", "expand_dims",
    "broadcast_to", "swapaxes", "asarray", "array", "take_along_axis",
    "sort", "argsort", "flip", "roll", "copy", "bitcast_convert_type",
    "convert_element_type",
}
# dtype spellings → canonical short names (the R019 vocabulary)
_DTYPE_CANON = {
    "bfloat16": "bf16", "float16": "f16", "float32": "f32",
    "float64": "f64", "int8": "i8", "int16": "i16", "int32": "i32",
    "int64": "i64", "uint32": "u32", "uint8": "u8", "bool_": "b1",
    "bool": "b1",
}
_WIDE_DTYPES = {"f64", "i64"}
_MATMUL_NAMES = {"dot", "matmul", "einsum", "tensordot", "dot_general",
                 "vdot"}

# ---------------------------------------------------------------------------
# R020 vocabulary
# ---------------------------------------------------------------------------

# Acquisition method names, valid only when the resolved owner looks
# like the resource-accounting layer (class or module named *Residency*/
# *Breaker*/*residency*/*breakers*): a reservation of budget that must be
# paired with a release on every path until ownership transfers.
ACQUIRE_NAMES = {"track", "put_array", "force", "break_or_reserve",
                 "_reserve"}
_ACQ_OWNER_RE = _re.compile(r"(?:residency|breaker|Registry)",
                            _re.IGNORECASE)
# Release spellings an except/finally (or the liability region itself)
# can use to discharge the reservation
RELEASE_NAMES = {"close", "release", "_release", "_untrack", "evict",
                 "rollback", "unreserve", "untrack"}
# Builtins that cannot raise in a way that strands a reservation (pure
# conversions / container peeks) — anything else between an acquisition
# and its escape is a fallible call
_SAFE_CALLS = {
    "len", "int", "float", "str", "bool", "list", "dict", "tuple", "set",
    "frozenset", "sorted", "min", "max", "sum", "abs", "round", "repr",
    "isinstance", "issubclass", "getattr", "hasattr", "id", "iter",
    "next", "enumerate", "zip", "range", "print", "format", "type",
    "any", "all", "map", "filter", "reversed", "hash",
}
# method spellings that are container/string peeks, not fallible work —
# `self._cache.items()` between an acquisition and its store is not a
# path that can strand the reservation
_SAFE_METHODS = {
    "items", "keys", "values", "get", "append", "extend", "add",
    "pop", "popitem", "move_to_end", "setdefault", "discard", "copy",
    "sort", "reverse", "count", "index", "strip", "split", "join",
    "startswith", "endswith", "lower", "upper", "format", "update",
}


# ---------------------------------------------------------------------------
# per-function summaries and the report
# ---------------------------------------------------------------------------

@dataclass
class FnSummary:
    """Interprocedural dim facts for one function."""
    param_in: Dict[str, Dim] = field(default_factory=dict)
    ret: DimVal = DIM_UNKNOWN
    env: Dict[str, DimVal] = field(default_factory=dict)


@dataclass
class ShapeFlowReport:
    """The pass-3 result: violations plus the coverage/classification
    stats the bench `analysis` record and the census test consume."""
    violations: List[Violation] = field(default_factory=list)
    functions: int = 0            # fns the dim fixpoint evaluated
    factories: List[str] = field(default_factory=list)   # factory sids
    collective_bodies: int = 0    # fns in R018 scope
    traced_fns: int = 0           # fns in R019 scope
    dims_classified: Dict[str, int] = field(
        default_factory=lambda: {n: 0 for n in KIND_NAMES.values()})
    #: factory sid -> {param: lattice kind name} — the join over every
    #: resolvable call site's actuals (the census cross-validation view:
    #: a dim the runtime census saw VARY must not be Concrete here)
    factory_param_dims: Dict[str, Dict[str, str]] = field(
        default_factory=dict)


# ---------------------------------------------------------------------------
# helpers over the pass-1 index
# ---------------------------------------------------------------------------

def _chain_tail(chain: Optional[str]) -> str:
    if not chain:
        return ""
    return chain.rpartition(".")[2]


def _sid_qual(sid: str) -> str:
    return sid.partition(":")[2]


def _sid_module(sid: str) -> str:
    return sid.partition(":")[0]


class _FnScope:
    """One function's resolution context: record, symbol, resolver."""

    def __init__(self, index: ProjectIndex, sym: FnSymbol):
        self.index = index
        self.sym = sym
        self.rec: ModuleRecord = index.records[sym.module]
        self.res = _Resolver(index, self.rec)

    def resolve_call(self, call: ast.Call) -> Optional[FnSymbol]:
        """Callee symbol for a call expression, or None. Mirrors the
        pass-1 resolution order: self-attr methods, module-local names,
        import chains (incl. module singletons)."""
        fn = call.func
        bare = _name(fn)
        if bare is not None:
            local = self.rec.symbols.get(bare)
            if local is not None:
                return local
            # Class() -> __init__
            if bare in self.rec.classes:
                init = self.rec.symbols.get(f"{bare}.__init__")
                if init is not None:
                    return init
            sid = self.res.resolve_chain(bare)
            return self.index.symbols.get(sid) if sid else None
        chain = _attr_chain(fn)
        if chain is None:
            return None
        if chain.startswith("self.") and chain.count(".") == 1:
            sid = self.res.resolve_self_attr(self.sym.cls, chain[5:])
            if sid is None and self.sym.cls is not None:
                # typed instance attribute: self.<attr>.<meth> handled
                # below; plain self.<meth> unresolved stays None
                pass
            return self.index.symbols.get(sid) if sid else None
        if chain.startswith("self.") and chain.count(".") == 2:
            _self, attr, meth = chain.split(".")
            tgt = self.res.attr_type_of(self.rec, self.sym.cls, attr)
            if tgt is not None:
                sid = self.res.resolve_method(tgt[0], tgt[1], meth)
                return self.index.symbols.get(sid) if sid else None
            return None
        sid = self.res.resolve_chain(chain)
        return self.index.symbols.get(sid) if sid else None


def _map_actuals(callee: FnSymbol,
                 call: ast.Call) -> List[Tuple[str, ast.AST]]:
    """(callee_param, actual expression) pairs for a call, skipping
    ``self`` for method callees (attribute calls never pass it)."""
    params = list(callee.params)
    if callee.cls is not None and params and params[0] in ("self", "cls"):
        params = params[1:]
    out: List[Tuple[str, ast.AST]] = []
    for i, a in enumerate(call.args):
        if isinstance(a, ast.Starred):
            break
        if i < len(params):
            out.append((params[i], a))
    pset = set(params)
    for kw in call.keywords:
        if kw.arg is not None and kw.arg in pset:
            out.append((kw.arg, kw.value))
    return out


def _assign_targets(t: ast.AST, out: List[str]) -> None:
    if isinstance(t, ast.Name):
        out.append(t.id)
    elif isinstance(t, (ast.Tuple, ast.List)):
        for e in t.elts:
            _assign_targets(e, out)
    elif isinstance(t, ast.Starred):
        _assign_targets(t.value, out)


def _stmts_in_order(node: ast.AST) -> List[ast.stmt]:
    """Every statement of a function body in document order, not
    descending into nested function/class definitions."""
    out: List[ast.stmt] = []

    def walk(body: Sequence[ast.stmt]) -> None:
        for stmt in body:
            out.append(stmt)
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue
            for fname in ("body", "orelse", "finalbody"):
                sub = getattr(stmt, fname, None)
                if sub:
                    walk(sub)
            for h in getattr(stmt, "handlers", []) or []:
                walk(h.body)

    walk(node.body)
    return out


# ---------------------------------------------------------------------------
# the interprocedural dim fixpoint (R017 substrate)
# ---------------------------------------------------------------------------

class _DimFlow:
    """Worklist fixpoint over every project function: per-function local
    dim environments, callee parameter joins, return summaries."""

    def __init__(self, index: ProjectIndex):
        self.index = index
        self.summaries: Dict[str, FnSummary] = {}
        self.scopes: Dict[str, _FnScope] = {}
        self.callers: Dict[str, Set[str]] = {}
        for sid, sym in index.symbols.items():
            self.summaries[sid] = FnSummary(
                param_in={p: DIM_UNKNOWN for p in sym.params})
            self.scopes[sid] = _FnScope(index, sym)
            for e in sym.edges:
                self.callers.setdefault(e.callee, set()).add(sid)
        self._dirty: Set[str] = set()

    # -- expression evaluation ----------------------------------------------

    def _dim_of(self, expr: ast.AST, sid: str,
                env: Dict[str, DimVal]) -> DimVal:
        scope = self.scopes[sid]
        summ = self.summaries[sid]
        if isinstance(expr, ast.Constant):
            return DIM_CONCRETE if isinstance(expr.value, (int, bool)) \
                else DIM_UNKNOWN
        if isinstance(expr, ast.Name):
            if expr.id in env:
                return env[expr.id]
            return summ.param_in.get(expr.id, DIM_UNKNOWN)
        if isinstance(expr, ast.Attribute):
            # host .shape/.size/.nbytes of anything is data-dependent —
            # R017 only *checks* in host code, so the trace-time-static
            # reading of these never reaches a verdict
            if expr.attr in ("shape", "size", "nbytes"):
                return Dim(DATADEP, ".%s at %s:%d" % (
                    expr.attr, scope.rec.path,
                    getattr(expr, "lineno", 0)))
            return DIM_UNKNOWN
        if isinstance(expr, ast.Tuple):
            return tuple(_as_single(self._dim_of(e, sid, env))
                         for e in expr.elts)
        if isinstance(expr, ast.Subscript):
            base = self._dim_of(expr.value, sid, env)
            if isinstance(base, tuple):
                sl = expr.slice
                if isinstance(sl, ast.Constant) and \
                        isinstance(sl.value, int) and \
                        -len(base) <= sl.value < len(base):
                    return base[sl.value]
                return _join_all(base)
            if isinstance(base, Dim) and base.kind == DATADEP:
                return base  # x.shape[0], x.shape[1:]
            return DIM_UNKNOWN
        if isinstance(expr, ast.BinOp):
            return _as_single(self._dim_of(expr.left, sid, env)).join(
                _as_single(self._dim_of(expr.right, sid, env)))
        if isinstance(expr, ast.UnaryOp):
            return self._dim_of(expr.operand, sid, env)
        if isinstance(expr, ast.IfExp):
            return _as_single(self._dim_of(expr.body, sid, env)).join(
                _as_single(self._dim_of(expr.orelse, sid, env)))
        if isinstance(expr, ast.Call):
            return self._dim_of_call(expr, sid, env)
        return DIM_UNKNOWN

    def _dim_of_call(self, call: ast.Call, sid: str,
                     env: Dict[str, DimVal]) -> DimVal:
        scope = self.scopes[sid]
        chain = _attr_chain(call.func)
        tail = _chain_tail(chain) or (_name(call.func) or "")
        if tail in PAD_PRODUCER_NAMES:
            return Dim(PADDED, "%s at %s:%d" % (
                tail, scope.rec.path, call.lineno))
        if tail == "len":
            return Dim(DATADEP, "len() at %s:%d" % (
                scope.rec.path, call.lineno))
        if tail in DIM_TRANSPARENT_CALLS and len(call.args) == 1:
            return self._dim_of(call.args[0], sid, env)
        if tail in DIM_JOIN_CALLS:
            return _join_all([_as_single(self._dim_of(a, sid, env))
                              for a in call.args
                              if not isinstance(a, ast.Starred)])
        callee = scope.resolve_call(call)
        if callee is None:
            return DIM_UNKNOWN
        # propagate actuals into the callee's parameter joins
        csum = self.summaries.get(callee.sid)
        if csum is None:
            return DIM_UNKNOWN
        for pname, aexpr in _map_actuals(callee, call):
            d = _as_single(self._dim_of(aexpr, sid, env))
            old = csum.param_in.get(pname, DIM_UNKNOWN)
            new = old.join(d)
            if new != old:
                csum.param_in[pname] = new
                self._dirty.add(callee.sid)
        return csum.ret

    # -- per-function evaluation --------------------------------------------

    def _eval_fn(self, sid: str) -> None:
        sym = self.index.symbols[sid]
        summ = self.summaries[sid]
        env: Dict[str, DimVal] = dict(summ.env)
        ret: DimVal = DIM_UNKNOWN
        stmts = _stmts_in_order(sym.node)
        for _round in range(4):
            changed = False
            rets: List[DimVal] = []
            for stmt in stmts:
                if isinstance(stmt, ast.Assign):
                    v = self._dim_of(stmt.value, sid, env)
                    for t in stmt.targets:
                        changed |= self._bind(t, v, env)
                elif isinstance(stmt, ast.AnnAssign) and stmt.value:
                    v = self._dim_of(stmt.value, sid, env)
                    changed |= self._bind(stmt.target, v, env)
                elif isinstance(stmt, ast.AugAssign):
                    names: List[str] = []
                    _assign_targets(stmt.target, names)
                    v = _as_single(self._dim_of(stmt.value, sid, env))
                    for n in names:
                        old = _as_single(env.get(n, DIM_UNKNOWN))
                        new = old.join(v)
                        if new != old:
                            env[n] = new
                            changed = True
                elif isinstance(stmt, ast.Return) and stmt.value:
                    rets.append(self._dim_of(stmt.value, sid, env))
                elif isinstance(stmt, ast.Expr):
                    self._dim_of(stmt.value, sid, env)  # edge effects
            if rets:
                ret = self._join_rets(rets)
            if not changed:
                break
        old_ret = summ.ret
        summ.env = env
        summ.ret = ret
        if ret != old_ret:
            for caller in self.callers.get(sid, ()):
                self._dirty.add(caller)

    @staticmethod
    def _join_rets(rets: List[DimVal]) -> DimVal:
        tuples = [r for r in rets if isinstance(r, tuple)]
        if len(tuples) == len(rets) and tuples and \
                len({len(t) for t in tuples}) == 1:
            width = len(tuples[0])
            return tuple(_join_all([t[i] for t in tuples])
                         for i in range(width))
        return _join_all([_as_single(r) for r in rets])

    @staticmethod
    def _bind(target: ast.AST, v: DimVal, env: Dict[str, DimVal]) -> bool:
        changed = False
        if isinstance(target, ast.Name):
            if env.get(target.id) != v:
                env[target.id] = v
                changed = True
        elif isinstance(target, (ast.Tuple, ast.List)):
            elts = target.elts
            vals: Sequence[DimVal]
            if isinstance(v, tuple) and len(v) == len(elts) and \
                    not any(isinstance(e, ast.Starred) for e in elts):
                vals = v
            else:
                vals = [_as_single(v)] * len(elts)
            for e, ev in zip(elts, vals):
                changed |= _DimFlow._bind(e, ev, env)
        elif isinstance(target, ast.Starred):
            changed |= _DimFlow._bind(target.value, _as_single(v), env)
        return changed

    # -- the fixpoint --------------------------------------------------------

    def run(self) -> None:
        work = sorted(self.summaries)
        seen_rounds = 0
        while work and seen_rounds < 12:
            seen_rounds += 1
            self._dirty = set()
            for sid in work:
                self._eval_fn(sid)
            work = sorted(self._dirty)


# ---------------------------------------------------------------------------
# R017: recompile-storm detection over the dim fixpoint
# ---------------------------------------------------------------------------

def _wrap_sids(index: ProjectIndex) -> Set[str]:
    """sids of the AOT registration point: ``wrap`` in an ``aot``
    module (parallel/aot.py in the real tree; any `aot.py` in
    fixtures)."""
    out = set()
    for sid in index.symbols:
        mod, qual = _sid_module(sid), _sid_qual(sid)
        if qual == "wrap" and (mod == "aot" or mod.endswith(".aot")):
            out.add(sid)
    return out


def _factory_sids(index: ProjectIndex) -> Set[str]:
    """Program factories: functions whose body registers a compiled
    program with the AOT cache (a resolved call edge to `aot:wrap`)."""
    wraps = _wrap_sids(index)
    if not wraps:
        return set()
    return {sym.sid for sym in index.symbols.values()
            if any(e.callee in wraps and e.kind == "call"
                   for e in sym.edges)}


class _R017Checker(ast.NodeVisitor):
    """One host-side function: flag factory/static call edges whose
    actual dims are DataDependent."""

    def __init__(self, flow: _DimFlow, sid: str, factories: Set[str],
                 out: List[Violation]):
        self.flow = flow
        self.sid = sid
        self.scope = flow.scopes[sid]
        self.env = flow.summaries[sid].env
        self.factories = factories
        self.out = out

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        pass  # nested defs are their own symbols

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Call(self, node: ast.Call) -> None:
        self.generic_visit(node)
        callee = self.scope.resolve_call(node)
        if callee is None:
            return
        is_factory = callee.sid in self.factories
        statics = callee.statics
        if not is_factory and not statics:
            return
        for pname, aexpr in _map_actuals(callee, node):
            if not is_factory and pname not in statics:
                continue
            d = _as_single(self.flow._dim_of(aexpr, self.sid, self.env))
            if d.kind != DATADEP:
                continue
            kind = ("program factory '%s'" % callee.qual) if is_factory \
                else ("jit static argument '%s' of '%s'"
                      % (pname, callee.qual))
            origin = (" (%s)" % d.origin) if d.origin else ""
            rec = self.scope.rec
            self.out.append(Violation(
                "R017", rec.path, node.lineno, node.col_offset,
                "recompile storm: argument '%s' to %s is data-dependent"
                "%s — every distinct value compiles and caches a new "
                "program (unbounded shape-key census); bucket it "
                "(pow2_bucket/round_up) or declare the call "
                "`# tpulint: bucketed`" % (pname, kind, origin),
                snippet_at(rec.lines, node.lineno)))


def _check_r017(index: ProjectIndex, flow: _DimFlow,
                factories: Set[str], out: List[Violation]) -> None:
    traced = set(index.traced)
    for sid, sym in index.symbols.items():
        # only HOST code builds programs; a factory-shaped call inside a
        # traced body is trace-time-static by construction
        if sid in traced or sym.is_root:
            continue
        checker = _R017Checker(flow, sid, factories, out)
        for stmt in sym.node.body:
            checker.visit(stmt)


def _factory_param_view(flow: _DimFlow,
                        factories: Set[str]) -> Dict[str, Dict[str, str]]:
    out: Dict[str, Dict[str, str]] = {}
    for sid in sorted(factories):
        summ = flow.summaries.get(sid)
        if summ is None:
            continue
        out[sid] = {p: KIND_NAMES[d.kind]
                    for p, d in sorted(summ.param_in.items())}
    return out


# ---------------------------------------------------------------------------
# R018: padded-lane taint inside collective program bodies
# ---------------------------------------------------------------------------

class _TaintEval:
    """Flow-sensitive (document-order) array-taint evaluation of one
    collective body."""

    def __init__(self, scope: _FnScope, out: List[Violation]):
        self.scope = scope
        self.out = out
        self.check = False
        self.env: Dict[str, int] = {}
        sym = scope.sym
        params = _fn_params(sym.node)
        for p in params:
            if p in ("self", "cls"):
                continue
            self.env[p] = ARR_MASK if _MASKY_RE.search(p) else ARR_TAINT

    # -- expression states ---------------------------------------------------

    def state_of(self, expr: ast.AST) -> int:
        if isinstance(expr, ast.Name):
            return self.env.get(expr.id, ARR_UNKNOWN)
        if isinstance(expr, ast.Constant):
            return ARR_VALIDATED
        if isinstance(expr, ast.Compare):
            return ARR_MASK
        if isinstance(expr, ast.UnaryOp):
            return self.state_of(expr.operand)
        if isinstance(expr, ast.Subscript):
            return self.state_of(expr.value)
        if isinstance(expr, ast.IfExp):
            return max(self.state_of(expr.body),
                       self.state_of(expr.orelse))
        if isinstance(expr, ast.BinOp):
            ls, rs = self.state_of(expr.left), self.state_of(expr.right)
            if isinstance(expr.op, (ast.Mult, ast.BitAnd)):
                # a mask multiply/and validates the other operand
                if ls == ARR_MASK or rs == ARR_MASK or \
                        self._masky(expr.left) or self._masky(expr.right):
                    if ls == ARR_MASK and rs == ARR_MASK:
                        return ARR_MASK
                    return ARR_VALIDATED
            if ls == ARR_TAINT or rs == ARR_TAINT:
                return ARR_TAINT
            if ls == ARR_UNKNOWN or rs == ARR_UNKNOWN:
                return ARR_UNKNOWN
            return max(ls, rs)
        if isinstance(expr, ast.Call):
            return self._call_state(expr)
        if isinstance(expr, (ast.Tuple, ast.List)):
            sts = [self.state_of(e) for e in expr.elts]
            if any(s == ARR_TAINT for s in sts):
                return ARR_TAINT
            return ARR_UNKNOWN
        if isinstance(expr, ast.Attribute):
            return ARR_UNKNOWN
        return ARR_UNKNOWN

    @staticmethod
    def _masky(expr: ast.AST) -> bool:
        n = _name(expr)
        if n is not None and _MASKY_RE.search(n):
            return True
        if isinstance(expr, ast.Subscript):
            return _TaintEval._masky(expr.value)
        return isinstance(expr, ast.Compare)

    def _operand(self, call: ast.Call) -> Optional[ast.AST]:
        if call.args and not isinstance(call.args[0], ast.Starred):
            return call.args[0]
        return None

    def _call_state(self, call: ast.Call) -> int:
        chain = _attr_chain(call.func)
        tail = _chain_tail(chain) or (_name(call.func) or "")
        # the reduction check itself happens in visit(); here we only
        # compute the VALUE state of the call expression
        if tail == "where" and len(call.args) == 3:
            return ARR_VALIDATED
        if tail in ("pad", "pad_to"):
            return ARR_TAINT  # fresh padding lanes
        if tail == "astype" or tail in _ELEMENTWISE_NAMES:
            # receiver method (x.astype) or jnp.op(x, ...): propagate
            if isinstance(call.func, ast.Attribute) and \
                    tail not in ("asarray", "array") and \
                    not self._jnp_rooted(chain):
                return self.state_of(call.func.value)
            op = self._operand(call)
            return self.state_of(op) if op is not None else ARR_UNKNOWN
        if tail in ("all_gather", "concatenate", "stack", "hstack",
                    "vstack"):
            op = self._operand(call)
            return self.state_of(op) if op is not None else ARR_UNKNOWN
        if tail in REDUCTION_NAMES:
            return ARR_VALIDATED  # a reduction's OUTPUT has no pad lanes
        return ARR_UNKNOWN  # helper the analysis can't see into

    def _jnp_rooted(self, chain: Optional[str]) -> bool:
        if not chain:
            return False
        return chain.split(".")[0] in self.scope.rec.info.jnp | \
            {"lax", "jax", "np"}

    # -- the walk ------------------------------------------------------------

    def run(self) -> None:
        # round 1 stabilizes the environment (forward-declared names,
        # loop-carried state) with checks off; round 2 reports
        stmts = _stmts_in_order(self.scope.sym.node)
        self.check = False
        for stmt in stmts:
            self._stmt(stmt)
        self.check = True
        for stmt in stmts:
            self._stmt(stmt)

    def _stmt(self, stmt: ast.stmt) -> None:
        if isinstance(stmt, ast.Assign):
            v = self._value_with_checks(stmt.value)
            for t in stmt.targets:
                self._bind(t, v)
        elif isinstance(stmt, ast.AnnAssign) and stmt.value:
            self._bind(stmt.target, self._value_with_checks(stmt.value))
        elif isinstance(stmt, ast.AugAssign):
            self._value_with_checks(stmt.value)
        elif isinstance(stmt, ast.Return) and stmt.value:
            self._value_with_checks(stmt.value)
        elif isinstance(stmt, ast.Expr):
            self._value_with_checks(stmt.value)

    def _bind(self, target: ast.AST, state: int) -> None:
        if isinstance(target, ast.Name):
            self.env[target.id] = state
        elif isinstance(target, (ast.Tuple, ast.List)):
            for e in target.elts:
                self._bind(e, state)
        elif isinstance(target, ast.Starred):
            self._bind(target.value, state)

    def _value_with_checks(self, expr: ast.AST) -> int:
        if self.check:
            for call in [n for n in ast.walk(expr)
                         if isinstance(n, ast.Call)]:
                self._check_reduction(call)
        return self.state_of(expr)

    def _check_reduction(self, call: ast.Call) -> None:
        chain = _attr_chain(call.func)
        tail = _chain_tail(chain) or (_name(call.func) or "")
        if tail not in REDUCTION_NAMES:
            return
        if isinstance(call.func, ast.Attribute) and \
                not self._jnp_rooted(chain):
            operand: Optional[ast.AST] = call.func.value  # x.sum()
        else:
            operand = self._operand(call)
        if operand is None:
            return
        if self.state_of(operand) != ARR_TAINT:
            return
        rec = self.scope.rec
        self.out.append(Violation(
            "R018", rec.path, call.lineno, call.col_offset,
            "padding soundness: reduction '%s' consumes an operand "
            "carrying pow2-padded lanes with no dominating validity "
            "mask — padded lanes leak into the result; mask first "
            "(jnp.where / mask multiply) or declare the operand "
            "`# tpulint: masked`" % tail,
            snippet_at(rec.lines, call.lineno)))


def _r018_scope(index: ProjectIndex) -> List[str]:
    """Collective program bodies: functions handed whole to shard_map/
    `wrap`. The mesh invariant — every array entering one is pow2-padded
    on its variable axes — holds exactly there, so parameters are
    born Tainted. Inner roots (scan/cond/pallas bodies) see tiles and
    accumulators whose padding story belongs to their enclosing
    program, not to them — tainting their params would indict every
    online-softmax accumulator, so they stay out of scope."""
    return sorted(sid for sid, sym in index.symbols.items()
                  if sym.is_collective_root)


def _check_r018(index: ProjectIndex, out: List[Violation]) -> List[str]:
    scope_sids = _r018_scope(index)
    for sid in scope_sids:
        sym = index.symbols[sid]
        _TaintEval(_FnScope(index, sym), out).run()
    return scope_sids


# ---------------------------------------------------------------------------
# R019: dtype discipline inside traced code
# ---------------------------------------------------------------------------

def _dtype_of_expr(expr: ast.AST) -> Optional[str]:
    """Canonical dtype named by a dtype-position expression
    (`jnp.bfloat16`, `np.float64`, `"float32"`), else None."""
    if isinstance(expr, ast.Constant) and isinstance(expr.value, str):
        return _DTYPE_CANON.get(expr.value)
    if isinstance(expr, ast.Attribute):
        return _DTYPE_CANON.get(expr.attr)
    if isinstance(expr, ast.Name):
        return _DTYPE_CANON.get(expr.id)
    if isinstance(expr, ast.Call):  # jnp.dtype("float64")
        if expr.args and not isinstance(expr.args[0], ast.Starred):
            return _dtype_of_expr(expr.args[0])
    return None


class _DtypeChecker(ast.NodeVisitor):
    """One traced function: local dtype tracking + the two R019 arms."""

    def __init__(self, scope: _FnScope, out: List[Violation]):
        self.scope = scope
        self.out = out
        self.env: Dict[str, str] = {}

    def visit_FunctionDef(self, node):
        pass  # nested defs are their own symbols

    visit_AsyncFunctionDef = visit_FunctionDef

    def _flag_wide(self, expr: ast.AST, where: str) -> None:
        d = _dtype_of_expr(expr)
        if d in _WIDE_DTYPES:
            rec = self.scope.rec
            self.out.append(Violation(
                "R019", rec.path, expr.lineno, expr.col_offset,
                "dtype discipline: %s spelling in traced code (%s) — "
                "silent f64/i64 promotion widens the whole device path; "
                "use the 32-bit dtype, or declare an intended cast "
                "`# tpulint: cast`" % (
                    "float64" if d == "f64" else "int64", where),
                snippet_at(rec.lines, expr.lineno)))

    def _operand_dtype(self, expr: ast.AST) -> Optional[str]:
        if isinstance(expr, ast.Name):
            return self.env.get(expr.id)
        if isinstance(expr, ast.Call) and \
                isinstance(expr.func, ast.Attribute) and \
                expr.func.attr == "astype" and expr.args:
            return _dtype_of_expr(expr.args[0])
        if isinstance(expr, ast.Attribute) and expr.attr == "T":
            return self._operand_dtype(expr.value)
        if isinstance(expr, ast.Subscript):
            return self._operand_dtype(expr.value)
        return None

    def _check_matmul(self, node: ast.AST, lhs: ast.AST,
                      rhs: ast.AST, opname: str) -> None:
        dl, dr = self._operand_dtype(lhs), self._operand_dtype(rhs)
        if dl is None or dr is None or dl == dr:
            return
        if {dl, dr} == {"bf16", "f32"}:
            rec = self.scope.rec
            self.out.append(Violation(
                "R019", rec.path, node.lineno, node.col_offset,
                "dtype discipline: MXU matmul '%s' mixes bf16 and f32 "
                "operands — the implicit promotion costs the bf16 "
                "throughput win and hides the intended precision; cast "
                "both sides explicitly at a declared cast point "
                "(`# tpulint: cast`)" % opname,
                snippet_at(rec.lines, node.lineno)))

    def visit_Assign(self, node: ast.Assign) -> None:
        self.generic_visit(node)
        d = self._operand_dtype(node.value)
        if isinstance(node.value, ast.Call):
            for kw in node.value.keywords:
                if kw.arg == "dtype":
                    d = _dtype_of_expr(kw.value) or d
        if d is not None:
            names: List[str] = []
            for t in node.targets:
                _assign_targets(t, names)
            for n in names:
                self.env[n] = d

    def visit_BinOp(self, node: ast.BinOp) -> None:
        self.generic_visit(node)
        if isinstance(node.op, ast.MatMult):
            self._check_matmul(node, node.left, node.right, "@")

    def visit_Call(self, node: ast.Call) -> None:
        self.generic_visit(node)
        chain = _attr_chain(node.func)
        tail = _chain_tail(chain) or (_name(node.func) or "")
        if tail == "astype" and node.args:
            self._flag_wide(node.args[0], ".astype(...)")
        for kw in node.keywords:
            if kw.arg == "dtype":
                self._flag_wide(kw.value, "dtype= keyword")
        if tail in _MATMUL_NAMES:
            args = [a for a in node.args
                    if not isinstance(a, ast.Starred)]
            if tail == "einsum" and len(args) >= 3:
                self._check_matmul(node, args[1], args[2], tail)
            elif tail != "einsum" and len(args) >= 2:
                self._check_matmul(node, args[0], args[1], tail)


def _check_r019(index: ProjectIndex, out: List[Violation]) -> int:
    scope_sids = sorted(set(index.traced) |
                        {sid for sid, s in index.symbols.items()
                         if s.is_root})
    for sid in scope_sids:
        sym = index.symbols.get(sid)
        if sym is None:
            continue
        checker = _DtypeChecker(_FnScope(index, sym), out)
        for stmt in sym.node.body:
            checker.visit(stmt)
    return len(scope_sids)


# ---------------------------------------------------------------------------
# R020: reservation-leak (release-path) checking
# ---------------------------------------------------------------------------

def _release_in(body: Sequence[ast.stmt]) -> bool:
    for stmt in body:
        for n in ast.walk(stmt):
            if isinstance(n, ast.Call):
                tail = _chain_tail(_attr_chain(n.func)) or \
                    (_name(n.func) or "")
                if tail in RELEASE_NAMES:
                    return True
    return False


@dataclass
class _OrderedStmt:
    stmt: ast.stmt
    protected: bool  # inside a try whose handler/finally releases


def _flatten_protected(node: ast.AST) -> List[_OrderedStmt]:
    out: List[_OrderedStmt] = []

    def walk(body: Sequence[ast.stmt], protected: bool) -> None:
        for stmt in body:
            out.append(_OrderedStmt(stmt, protected))
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue
            if isinstance(stmt, ast.Try):
                covered = protected or _release_in(
                    [s for h in stmt.handlers for s in h.body]
                    + list(stmt.finalbody))
                walk(stmt.body, covered)
                for h in stmt.handlers:
                    walk(h.body, protected)
                walk(stmt.orelse, protected)
                walk(stmt.finalbody, protected)
                continue
            for fname in ("body", "orelse", "finalbody"):
                sub = getattr(stmt, fname, None)
                if sub:
                    walk(sub, protected)
            for h in getattr(stmt, "handlers", []) or []:
                walk(h.body, protected)

    walk(node.body, False)
    return out


def _acquire_call(scope: _FnScope,
                  stmt: ast.stmt) -> Optional[Tuple[ast.Call, str]]:
    """(call, acquisition name) when this statement's value is a
    resolved breaker/residency acquisition."""
    value = None
    if isinstance(stmt, (ast.Assign, ast.AnnAssign)) and \
            getattr(stmt, "value", None) is not None:
        value = stmt.value
    elif isinstance(stmt, ast.Expr):
        value = stmt.value
    if not isinstance(value, ast.Call):
        return None
    chain = _attr_chain(value.func)
    tail = _chain_tail(chain)
    if tail not in ACQUIRE_NAMES:
        return None
    callee = scope.resolve_call(value)
    if callee is None:
        return None
    qual, mod = _sid_qual(callee.sid), _sid_module(callee.sid)
    owner = qual.rpartition(".")[0] or mod.rpartition(".")[2]
    if not (_ACQ_OWNER_RE.search(owner) or
            _ACQ_OWNER_RE.search(mod.rpartition(".")[2])):
        return None
    return value, tail


def _names_in(node: ast.AST) -> Set[str]:
    return {n.id for n in ast.walk(node) if isinstance(n, ast.Name)}


def _scan_nodes(stmt: ast.stmt) -> List[ast.AST]:
    """AST regions a liability scan may attribute to THIS flattened
    entry: a compound statement contributes only its header expressions
    (its children re-appear later in document order — judging the whole
    subtree here would see the body before it runs)."""
    if isinstance(stmt, (ast.If, ast.While)):
        return [stmt.test]
    if isinstance(stmt, (ast.For, ast.AsyncFor)):
        return [stmt.iter]
    if isinstance(stmt, (ast.With, ast.AsyncWith)):
        out: List[ast.AST] = []
        for item in stmt.items:
            out.append(item.context_expr)
        return out
    if isinstance(stmt, ast.Try):
        return []
    return [stmt]


def _is_risky(stmt: ast.stmt, token: Optional[str]) -> bool:
    """Does this statement contain a fallible call that is NOT a
    release/method on the token itself and not a safe builtin?"""
    for region in _scan_nodes(stmt):
        for n in ast.walk(region):
            if not isinstance(n, ast.Call):
                continue
            if isinstance(n.func, ast.Attribute):
                recv = _name(n.func.value)
                if token is not None and recv == token:
                    continue  # tok.close() / tok.anything
                if n.func.attr in _SAFE_METHODS:
                    continue
                return True
            fname = _name(n.func) or ""
            if fname in _SAFE_CALLS:
                continue
            return True
    return False


def _token_fate(stmt: ast.stmt, token: str) -> Optional[str]:
    """'escape' (stored/returned/passed — ownership transferred),
    'release' (closed/released), or None (no mention / plain read)."""
    mentions = False
    for region in _scan_nodes(stmt):
        for n in ast.walk(region):
            if isinstance(n, ast.Call):
                if isinstance(n.func, ast.Attribute) and \
                        _name(n.func.value) == token and \
                        n.func.attr in RELEASE_NAMES:
                    return "release"
                for a in list(n.args) + [kw.value for kw in n.keywords]:
                    if token in _names_in(a):
                        return "escape"  # ownership transferred
            if isinstance(n, ast.Name) and n.id == token:
                mentions = True
    if not mentions:
        return None
    if isinstance(stmt, (ast.Return,)) and stmt.value is not None and \
            token in _names_in(stmt.value):
        return "escape"
    if isinstance(stmt, ast.Assign) and token in _names_in(stmt.value):
        return "escape"  # stored into a container/attribute
    if isinstance(stmt, ast.Expr) and isinstance(stmt.value, ast.Yield):
        return "escape"
    return None


def _commit_stmt(stmt: ast.stmt) -> bool:
    """A void acquisition's liability ends when the guarded state is
    committed: a store into instance state (`self._x[...] = h` /
    `self._x = h`) or a return."""
    if isinstance(stmt, ast.Return):
        return True
    if isinstance(stmt, (ast.Assign, ast.AnnAssign)):
        targets = stmt.targets if isinstance(stmt, ast.Assign) \
            else [stmt.target]
        for t in targets:
            base = t
            while isinstance(base, ast.Subscript):
                base = base.value
            if isinstance(base, ast.Attribute):
                return True
    return False


def _bound_token(stmt: ast.stmt) -> Optional[str]:
    if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1:
        t = stmt.targets[0]
        if isinstance(t, ast.Name):
            return t.id
    if isinstance(stmt, ast.AnnAssign) and \
            isinstance(stmt.target, ast.Name):
        return stmt.target.id
    return None


def _check_r020_fn(index: ProjectIndex, sym: FnSymbol,
                   out: List[Violation]) -> None:
    scope = _FnScope(index, sym)
    ordered = _flatten_protected(sym.node)
    for i, ostmt in enumerate(ordered):
        acq = _acquire_call(scope, ostmt.stmt)
        if acq is None:
            continue
        call, acq_name = acq
        # the acquisition implementation itself (ResidencyRegistry.track
        # calling breaker.force) is the primitive being modeled — its own
        # internal calls are covered by analyzing ITS callers; still
        # checked here like any other caller.
        token = _bound_token(ostmt.stmt)
        risky_line = 0
        leaked = False
        for later in ordered[i + 1:]:
            stmt = later.stmt
            if token is not None:
                fate = _token_fate(stmt, token)
                if fate is not None:
                    break  # escaped or released: liability over
            else:
                # void charge: released / committed ends liability
                done = False
                for region in _scan_nodes(stmt):
                    for n in ast.walk(region):
                        if isinstance(n, ast.Call):
                            tail = _chain_tail(_attr_chain(n.func)) or \
                                (_name(n.func) or "")
                            if tail in RELEASE_NAMES:
                                done = True
                                break
                    if done:
                        break
                if done or _commit_stmt(stmt):
                    break
            if not later.protected and _is_risky(stmt, token):
                leaked = True
                if not risky_line:
                    risky_line = getattr(stmt, "lineno", 0)
        if not leaked:
            continue
        rec = scope.rec
        what = "token" if token is not None else "charge"
        out.append(Violation(
            "R020", rec.path, call.lineno, call.col_offset,
            "reservation leak: '%s' acquires breaker/residency budget "
            "but a fallible call (line %d) runs before the %s is "
            "stored, returned, or released, outside any try whose "
            "except/finally releases it — an exception on that path "
            "strands the reservation and wedges admission control"
            % (acq_name, risky_line, what),
            snippet_at(rec.lines, call.lineno)))


def _check_r020(index: ProjectIndex, out: List[Violation]) -> None:
    for sid in sorted(index.symbols):
        sym = index.symbols[sid]
        # acquisition implementations police their own callees; skip the
        # defining methods so `def track(self): self.breaker.force(n)`
        # doesn't flag itself acquiring-within-acquire
        tail = _sid_qual(sym.sid).rpartition(".")[2]
        if tail in ACQUIRE_NAMES or tail in RELEASE_NAMES:
            continue
        _check_r020_fn(index, sym, out)


# ---------------------------------------------------------------------------
# entry points
# ---------------------------------------------------------------------------

def analyze(index: ProjectIndex) -> ShapeFlowReport:
    """Run pass 3 over a built project index. Memoized on the index:
    lint_index, the bench `analysis` record, and the census test share
    one evaluation."""
    cached = getattr(index, "_shapeflow_report", None)
    if cached is not None:
        return cached
    report = ShapeFlowReport()
    flow = _DimFlow(index)
    flow.run()
    report.functions = len(flow.summaries)
    factories = _factory_sids(index)
    report.factories = sorted(factories)
    report.factory_param_dims = _factory_param_view(flow, factories)
    for summ in flow.summaries.values():
        for v in summ.env.values():
            report.dims_classified[KIND_NAMES[_as_single(v).kind]] += 1
    _check_r017(index, flow, factories, report.violations)
    report.collective_bodies = len(
        _check_r018(index, report.violations))
    report.traced_fns = _check_r019(index, report.violations)
    _check_r020(index, report.violations)
    report.violations.sort(
        key=lambda v: (v.path, v.line, v.col, v.rule))
    index._shapeflow_report = report  # type: ignore[attr-defined]
    return report


def shapeflow_violations(index: ProjectIndex) -> List[Violation]:
    """The pass-3 findings for lint_index (suppressions applied by the
    caller per record, like every other pass)."""
    return list(analyze(index).violations)
