"""Grandfathered-violation baseline for tpulint.

The baseline pins *specific* pre-existing findings so the CI gate can sit
at zero new violations while old sites are worked off. Entries fingerprint
by ``(rule, path, stripped source line)`` with an occurrence budget — NOT
by line number, so unrelated edits above a grandfathered site don't churn
the file. Every entry must carry a ``justification`` string; the gate
refuses an unexplained baseline (an empty baseline needs no file at all).
"""
from __future__ import annotations

import json
import os
from collections import Counter
from typing import Dict, List, Sequence, Tuple

from tools.tpulint.analyzer import Violation

DEFAULT_BASELINE = os.path.join(os.path.dirname(__file__), "baseline.json")


def _fingerprint(v: Violation) -> Tuple[str, str, str]:
    return (v.rule, v.path, v.snippet)


def load_baseline(path: str = DEFAULT_BASELINE) -> Counter:
    """fingerprint -> allowed occurrence count. Missing file = empty."""
    if not os.path.exists(path):
        return Counter()
    with open(path, "r", encoding="utf-8") as fh:
        data = json.load(fh)
    budget: Counter = Counter()
    for entry in data.get("violations", []):
        if not str(entry.get("justification", "")).strip():
            raise ValueError(
                f"baseline entry {entry.get('rule')} at {entry.get('path')} "
                "has no justification — grandfathered sites must say why")
        key = (entry["rule"], entry["path"], entry["snippet"])
        budget[key] += int(entry.get("count", 1))
    return budget


def filter_baselined(
    violations: Sequence[Violation], budget: Counter
) -> Tuple[List[Violation], List[Violation]]:
    """Split into (new, grandfathered). Budget is consumed per occurrence
    in file order, so a grandfathered pattern that *multiplies* still
    fails the gate."""
    remaining = Counter(budget)
    new: List[Violation] = []
    old: List[Violation] = []
    for v in violations:
        key = _fingerprint(v)
        if remaining[key] > 0:
            remaining[key] -= 1
            old.append(v)
        else:
            new.append(v)
    return new, old


def write_baseline(violations: Sequence[Violation], path: str,
                   justification: str = "grandfathered at gate adoption") -> dict:
    """Serialize the current finding set as the new baseline (dev helper
    behind ``--write-baseline``; entries still need real justifications
    before review)."""
    grouped: Dict[Tuple[str, str, str], int] = Counter(
        _fingerprint(v) for v in violations)
    doc = {
        "comment": "tpulint grandfathered violations — see "
                   "docs/STATIC_ANALYSIS.md for the workflow",
        "violations": [
            {"rule": r, "path": p, "snippet": s, "count": c,
             "justification": justification}
            for (r, p, s), c in sorted(grouped.items())
        ],
    }
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(doc, fh, indent=2, sort_keys=False)
        fh.write("\n")
    return doc
