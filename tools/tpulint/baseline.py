"""Grandfathered-violation baseline for tpulint.

The baseline pins *specific* pre-existing findings so the CI gate can sit
at zero new violations while old sites are worked off. Entries fingerprint
by ``(rule, path, stripped source line)`` with an occurrence budget — NOT
by line number, so unrelated edits above a grandfathered site don't churn
the file. Every entry must carry a ``justification`` string; the gate
refuses an unexplained baseline (an empty baseline needs no file at all).
"""
from __future__ import annotations

import json
import os
from collections import Counter
from typing import Dict, List, Sequence, Tuple

from tools.tpulint.analyzer import Violation

DEFAULT_BASELINE = os.path.join(os.path.dirname(__file__), "baseline.json")


def _fingerprint(v: Violation) -> Tuple[str, str, str]:
    return (v.rule, v.path, v.snippet)


def load_baseline(path: str = DEFAULT_BASELINE) -> Counter:
    """fingerprint -> allowed occurrence count. Missing file = empty."""
    if not os.path.exists(path):
        return Counter()
    with open(path, "r", encoding="utf-8") as fh:
        data = json.load(fh)
    budget: Counter = Counter()
    for entry in data.get("violations", []):
        if not str(entry.get("justification", "")).strip():
            raise ValueError(
                f"baseline entry {entry.get('rule')} at {entry.get('path')} "
                "has no justification — grandfathered sites must say why")
        key = (entry["rule"], entry["path"], entry["snippet"])
        budget[key] += int(entry.get("count", 1))
    return budget


def filter_baselined(
    violations: Sequence[Violation], budget: Counter
) -> Tuple[List[Violation], List[Violation]]:
    """Split into (new, grandfathered). Budget is consumed per occurrence
    in file order, so a grandfathered pattern that *multiplies* still
    fails the gate."""
    remaining = Counter(budget)
    new: List[Violation] = []
    old: List[Violation] = []
    for v in violations:
        key = _fingerprint(v)
        if remaining[key] > 0:
            remaining[key] -= 1
            old.append(v)
        else:
            new.append(v)
    return new, old


def prune_baseline(violations: Sequence[Violation],
                   path: str = DEFAULT_BASELINE,
                   fix: bool = False) -> List[dict]:
    """Stale-entry audit: the justified-entry list must not rot. An entry
    (or part of its occurrence ``count``) is stale when the analyzer no
    longer produces a matching finding — the grandfathered site was fixed
    or deleted, and keeping the entry would silently excuse a future
    regression at the same fingerprint.

    ``violations`` is the full un-baselined finding set. Returns the
    stale entries (each with a ``dead`` count of unused occurrences).
    With ``fix=True`` the file is rewritten with live counts only —
    and deleted outright when nothing survives (an empty baseline needs
    no file at all)."""
    if not os.path.exists(path):
        return []
    with open(path, "r", encoding="utf-8") as fh:
        doc = json.load(fh)
    actual = Counter(_fingerprint(v) for v in violations)
    remaining = Counter(actual)
    stale: List[dict] = []
    live_entries: List[dict] = []
    for entry in doc.get("violations", []):
        key = (entry["rule"], entry["path"], entry["snippet"])
        want = int(entry.get("count", 1))
        live = min(want, remaining[key])
        remaining[key] -= live
        if live < want:
            stale.append(dict(entry, dead=want - live))
        if live > 0:
            live_entries.append(dict(entry, count=live))
    if fix and stale:
        if live_entries:
            doc["violations"] = live_entries
            with open(path, "w", encoding="utf-8") as fh:
                json.dump(doc, fh, indent=2, sort_keys=False)
                fh.write("\n")
        else:
            os.remove(path)
    return stale


def write_baseline(violations: Sequence[Violation], path: str,
                   justification: str = "grandfathered at gate adoption") -> dict:
    """Serialize the current finding set as the new baseline (dev helper
    behind ``--write-baseline``; entries still need real justifications
    before review)."""
    grouped: Dict[Tuple[str, str, str], int] = Counter(
        _fingerprint(v) for v in violations)
    doc = {
        "comment": "tpulint grandfathered violations — see "
                   "docs/STATIC_ANALYSIS.md for the workflow",
        "violations": [
            {"rule": r, "path": p, "snippet": s, "count": c,
             "justification": justification}
            for (r, p, s), c in sorted(grouped.items())
        ],
    }
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(doc, fh, indent=2, sort_keys=False)
        fh.write("\n")
    return doc
