"""tpulint rule visitors (R001–R014, pass 2 of the whole-program
analysis).

One recursive walk per file carries the context every rule needs: the
loop stack (R001/R002), the traced-function stack with its static/traced
parameter split (R003/R004), the lock-held stack (R005), and the
collective depth (R014). A module pre-pass first resolves import
aliases (``jnp``/``np``/``jax``/``lax``), the module's jitted callables
with their ``static_argnames``, and — for lock-disciplined modules —
the module/instance lock names and the shared mutable globals they
guard. In project mode (tools/tpulint/project.py), ``FileContext``
additionally carries the call-graph-inferred traced/collective function
sets, so the traced checks enter helpers the per-file view can't see;
R013's lock-graph findings are computed globally in project.py.
"""
from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Set, Tuple

from tools.tpulint.analyzer import Violation, snippet_at

# Dynamic-shape producers: output size depends on input *values*.
DYNAMIC_SHAPE_FNS = {"nonzero", "flatnonzero", "argwhere", "unique"}
# Container-mutating method names used for shared-state write detection.
MUTATOR_METHODS = {
    "append", "add", "update", "pop", "popitem", "clear", "remove",
    "extend", "insert", "setdefault", "discard", "appendleft",
}
MUTABLE_FACTORIES = {"dict", "list", "set", "OrderedDict", "defaultdict",
                     "deque", "Counter"}


@dataclass
class FileContext:
    path: str
    lines: Sequence[str]
    hot: bool = False      # R002 applies
    ops: bool = False      # R003 host-annotation check applies
    locked: bool = False   # R005 applies
    swallow: bool = False  # R006 applies (failure-domain modules)
    timing: bool = False   # R007 applies (tracing//monitor/ modules)
    budget: bool = False   # R008 applies (product package, not resources/)
    blocking: bool = False  # R010 applies (serving/ modules)
    threads: bool = False  # R011 applies (cluster/ modules)
    audit: bool = False    # R012 applies (product modules outside the
    #                        trace-audited packages)
    host_lines: Set[int] = field(default_factory=set)
    # whole-program pass 2 (tools/tpulint/project.py): functions of THIS
    # module inferred traced (qualname -> traced parameter names) or in
    # collective (shard_map/psum) reach, from the project call graph.
    # Empty in single-file mode — only local jit roots enter trace then.
    ext_traced: Dict[str, Set[str]] = field(default_factory=dict)
    ext_collective: Set[str] = field(default_factory=set)


@dataclass
class JitTarget:
    """A callable known to be jitted, with its static parameter names."""
    statics: Set[str] = field(default_factory=set)


# ---------------------------------------------------------------------------
# small AST helpers
# ---------------------------------------------------------------------------

def _name(node: ast.AST) -> Optional[str]:
    return node.id if isinstance(node, ast.Name) else None


def _attr_chain(node: ast.AST) -> Optional[str]:
    """Dotted name for Name/Attribute chains ('jax.numpy', 'self._lock')."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _is_none(node: ast.AST) -> bool:
    return isinstance(node, ast.Constant) and node.value is None


def _const_str_seq(node: ast.AST) -> Set[str]:
    """Static-argnames value → the set of names it declares."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return {node.value}
    if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
        return {e.value for e in node.elts
                if isinstance(e, ast.Constant) and isinstance(e.value, str)}
    return set()


def _param_names(fn: ast.AST) -> List[str]:
    a = fn.args
    names = [p.arg for p in getattr(a, "posonlyargs", [])]
    names += [p.arg for p in a.args]
    names += [p.arg for p in a.kwonlyargs]
    return names


def _all_param_names(fn: ast.AST) -> List[str]:
    """_param_names plus *args/**kwargs — the traced-value universe (a
    vararg inside a traced body is a tracer tuple; static_argnums
    indexing stays on _param_names, matching jax's positional rules)."""
    a = fn.args
    names = _param_names(fn)
    if a.vararg is not None:
        names.append(a.vararg.arg)
    if a.kwarg is not None:
        names.append(a.kwarg.arg)
    return names


class _ModuleInfo:
    """Pre-pass over the module body: aliases, jitted callables, locks."""

    def __init__(self, tree: ast.Module):
        self.jax: Set[str] = set()
        self.jnp: Set[str] = set()
        self.np: Set[str] = set()
        self.lax: Set[str] = set()    # `from jax import lax [as l]`
        self.jit_names: Set[str] = set()      # `from jax import jit [as j]`
        self.partial_names: Set[str] = set()  # functools.partial aliases
        self.jitted: Dict[str, JitTarget] = {}
        self.wrapped_fns: Set[str] = set()    # g in `f = jax.jit(g)`
        self.module_locks: Set[str] = set()
        self.module_conds: Set[str] = set()   # threading.Condition globals
        self.shared_globals: Set[str] = set()
        self.time_mods: Set[str] = set()      # names bound to `import time`
        self.wall_fns: Set[str] = set()       # `from time import time [as t]`
        self.put_fns: Set[str] = set()        # `from jax import device_put`
        # R009: names referring to the metrics module / registry objects
        # and the kernel-dispatch counter module
        self.metrics_mods: Set[str] = set()   # `from ...monitor import metrics`
        self.metrics_objs: Set[str] = set()   # `from ...metrics import SHARED`
        self.kernels_mods: Set[str] = set()   # `from ...monitor import kernels`
        # R011: threading aliases + every function/method def by bare
        # name, so a Thread(target=...) can resolve to its loop body
        self.threading_mods: Set[str] = set()  # `import threading [as t]`
        self.thread_fns: Set[str] = set()      # `from threading import Thread`
        self.fn_defs: Dict[str, ast.AST] = {}
        self.method_defs: Dict[Tuple[str, str], ast.AST] = {}
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for al in node.names:
                    bound = al.asname or al.name.split(".")[0]
                    if al.name == "jax":
                        self.jax.add(bound)
                    elif al.name == "jax.numpy":
                        # unaliased `import jax.numpy` is referenced as
                        # `jax.numpy.<fn>` — the dotted module IS the alias
                        self.jnp.add(al.asname or "jax.numpy")
                    elif al.name == "numpy":
                        self.np.add(bound)
                    elif al.name == "functools":
                        self.partial_names.add(f"{bound}.partial")
                    elif al.name == "time":
                        self.time_mods.add(bound)
                    elif al.name == "threading":
                        self.threading_mods.add(bound)
            elif isinstance(node, ast.ImportFrom):
                if node.module == "time":
                    for al in node.names:
                        if al.name == "time":
                            self.wall_fns.add(al.asname or "time")
                if node.module == "threading":
                    for al in node.names:
                        if al.name == "Thread":
                            self.thread_fns.add(al.asname or "Thread")
                if node.module and node.module.endswith(".monitor"):
                    for al in node.names:
                        if al.name == "metrics":
                            self.metrics_mods.add(al.asname or "metrics")
                        elif al.name == "kernels":
                            self.kernels_mods.add(al.asname or "kernels")
                if node.module and node.module.endswith("monitor.metrics"):
                    for al in node.names:
                        self.metrics_objs.add(al.asname or al.name)
                if node.module and node.module.endswith("monitor.kernels"):
                    for al in node.names:
                        if al.name == "record":
                            self.kernels_mods.add("")  # bare record()
                if node.module == "jax":
                    for al in node.names:
                        if al.name == "jit":
                            self.jit_names.add(al.asname or "jit")
                        if al.name == "numpy":
                            self.jnp.add(al.asname or "numpy")
                        if al.name == "device_put":
                            self.put_fns.add(al.asname or "device_put")
                        if al.name == "lax":
                            self.lax.add(al.asname or "lax")
                elif node.module == "functools":
                    for al in node.names:
                        if al.name == "partial":
                            self.partial_names.add(al.asname or "partial")
                elif node.module == "jax.numpy":
                    pass  # `from jax.numpy import X` — per-symbol, skip
        # second sweep needs the aliases resolved first
        for node in ast.walk(tree):
            if isinstance(node, ast.Call) and self.is_jit_expr(node):
                for arg in node.args[:1]:
                    nm = _name(arg)
                    if nm:
                        self.wrapped_fns.add(nm)
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.fn_defs.setdefault(node.name, node)
                statics = self.decorator_jit(node)
                if statics is not None:
                    self.jitted[node.name] = JitTarget(set(statics))
            elif isinstance(node, ast.ClassDef):
                # methods keyed per class: R011's self.<method> thread
                # targets must resolve within the RIGHT class (bare-name
                # first-def-wins checked the wrong body when two classes
                # shared a method name)
                for item in node.body:
                    if isinstance(item, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                        self.method_defs[(node.name, item.name)] = item
        for stmt in tree.body:
            if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1:
                tgt = _name(stmt.targets[0])
                if not tgt:
                    continue
                val = stmt.value
                if isinstance(val, ast.Call):
                    chain = _attr_chain(val.func) or ""
                    if chain.endswith((".Lock", ".RLock")) or chain in (
                            "Lock", "RLock"):
                        self.module_locks.add(tgt)
                        continue
                    if chain.endswith(".Condition") or chain == "Condition":
                        # a Condition's `with` acquires its lock — R010
                        # treats it as lock-holding (R005 lock semantics
                        # deliberately unchanged)
                        self.module_conds.add(tgt)
                        continue
                    if self.is_jit_expr(val):
                        self.jitted[tgt] = JitTarget(self.jit_statics(val))
                        continue
                    fname = chain.rpartition(".")[2]
                    if fname in MUTABLE_FACTORIES:
                        self.shared_globals.add(tgt)
                elif isinstance(val, (ast.Dict, ast.List, ast.Set,
                                      ast.DictComp, ast.ListComp,
                                      ast.SetComp)):
                    self.shared_globals.add(tgt)

    # -- jit expression recognition -----------------------------------------

    def _is_bare_jit(self, node: ast.AST) -> bool:
        chain = _attr_chain(node)
        if chain in self.jit_names:
            return True
        return bool(chain) and "." in chain and \
            chain.split(".")[0] in self.jax and chain.endswith(".jit")

    def is_jit_expr(self, call: ast.Call) -> bool:
        """True for `jax.jit(...)` and `partial(jax.jit, ...)` calls."""
        if self._is_bare_jit(call.func):
            return True
        chain = _attr_chain(call.func)
        if (chain in self.partial_names or chain == "partial") and call.args:
            return self._is_bare_jit(call.args[0])
        return False

    def jit_statics(self, call: ast.Call) -> Set[str]:
        statics: Set[str] = set()
        for kw in call.keywords:
            if kw.arg == "static_argnames":
                statics |= _const_str_seq(kw.value)
        return statics

    def decorator_jit(self, fn) -> Optional[Set[str]]:
        """Static names when `fn` carries a jit decorator, else None."""
        for dec in fn.decorator_list:
            if self._is_bare_jit(dec):
                return set()
            if isinstance(dec, ast.Call) and self.is_jit_expr(dec):
                statics = self.jit_statics(dec)
                for kw in dec.keywords:
                    if kw.arg == "static_argnums":
                        params = _param_names(fn)
                        nums = kw.value
                        idxs = [e.value for e in getattr(nums, "elts", [nums])
                                if isinstance(e, ast.Constant)
                                and isinstance(e.value, int)]
                        statics |= {params[i] for i in idxs
                                    if 0 <= i < len(params)}
                return statics
        return None


def _walk_skip_static_attrs(node: ast.AST):
    """ast.walk, but skip subtrees under ``.shape``/``.dtype``/``.ndim``/
    ``.size`` attribute access — those are trace-time STATIC properties
    of a traced array (``if x.dtype == jnp.bfloat16:`` resolves at trace
    time and is legal Python branching)."""
    work = [node]
    while work:
        n = work.pop()
        if isinstance(n, ast.Attribute) and n.attr in ("shape", "dtype",
                                                       "ndim", "size"):
            continue
        yield n
        work.extend(ast.iter_child_nodes(n))


# ---------------------------------------------------------------------------
# the walk
# ---------------------------------------------------------------------------

@dataclass
class _TracedCtx:
    fn_name: str
    traced: Set[str]


class _Checker(ast.NodeVisitor):
    def __init__(self, ctx: FileContext, mod: _ModuleInfo):
        self.ctx = ctx
        self.mod = mod
        self.out: List[Violation] = []
        self.loop_depth = 0            # For/While (R001 jit-in-loop)
        self.iter_depth = 0            # + comprehensions (R002 per-hit)
        self.traced_stack: List[_TracedCtx] = []
        self.lock_depth = 0            # inside `with <known lock>`
        self.block_depth = 0           # inside `with <lock OR condition>`
        self.coll_depth = 0            # inside collective (R014) reach
        self.qual_stack: List[str] = []  # class+fn names — the project
        #                                  symbol qualname convention
        self.class_stack: List[str] = []
        self.class_locks: Dict[str, Set[str]] = {}  # class -> self lock attrs
        self.class_conds: Dict[str, Set[str]] = {}  # class -> self cond attrs
        self.fn_stack: List[str] = []
        # R007: per-scope names holding a time.time() result (module
        # scope at index 0; one frame per function)
        self.wall_names: List[Set[str]] = [set()]
        # R009: per-scope names bound to metric objects (`h = m.histogram(
        # ...)`) and names tainted as device values (`x = jnp.sum(...)`)
        self.metric_names: List[Set[str]] = [set()]
        self.device_names: List[Set[str]] = [set()]

    # -- emit ----------------------------------------------------------------

    def _emit(self, rule: str, node: ast.AST, message: str) -> None:
        line = getattr(node, "lineno", 0)
        self.out.append(Violation(rule, self.ctx.path, line,
                                  getattr(node, "col_offset", 0), message,
                                  snippet_at(self.ctx.lines, line)))

    # -- structure visitors --------------------------------------------------

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        if self.ctx.locked or self.ctx.blocking:
            locks: Set[str] = set()
            conds: Set[str] = set()
            for sub in ast.walk(node):
                if isinstance(sub, ast.Assign) and len(sub.targets) == 1:
                    chain = _attr_chain(sub.targets[0]) or ""
                    if chain.startswith("self.") and isinstance(
                            sub.value, ast.Call):
                        vchain = _attr_chain(sub.value.func) or ""
                        if vchain.endswith((".Lock", ".RLock")) or \
                                vchain in ("Lock", "RLock"):
                            locks.add(chain[len("self."):])
                        elif vchain.endswith(".Condition") or \
                                vchain == "Condition":
                            conds.add(chain[len("self."):])
            self.class_locks[node.name] = locks
            self.class_conds[node.name] = conds
        self.class_stack.append(node.name)
        self.qual_stack.append(node.name)
        self.generic_visit(node)
        self.qual_stack.pop()
        self.class_stack.pop()

    def _visit_function(self, node) -> None:
        qual = ".".join(self.qual_stack + [node.name])
        statics = self.mod.decorator_jit(node)
        wrapped = node.name in self.mod.wrapped_fns
        # ext_traced: the whole-program pass inferred this function is
        # reachable from a jit/pallas/shard_map body (with the traced
        # parameter subset refined from its call sites)
        ext = self.ctx.ext_traced.get(qual)
        entering_trace = (statics is not None or wrapped
                          or bool(self.traced_stack) or ext is not None)
        if entering_trace:
            if statics is not None or wrapped or self.traced_stack:
                traced = set(_all_param_names(node)) - (statics or set())
            else:
                traced = set(ext or ())
            if ext:
                traced |= ext
            if self.traced_stack:  # nested def inherits the outer view
                traced |= self.traced_stack[-1].traced
            self.traced_stack.append(_TracedCtx(node.name, traced))
        entering_coll = qual in self.ctx.ext_collective or self.coll_depth
        if entering_coll:
            self.coll_depth += 1
        if (statics is not None or wrapped) and self.loop_depth:
            self._emit("R001", node,
                       f"jitted function `{node.name}` is (re)defined inside "
                       "a loop — every iteration builds a fresh callable and "
                       "retraces; hoist the jit out of the loop")
        self.qual_stack.append(node.name)
        self.fn_stack.append(node.name)
        self.wall_names.append(set())
        self.metric_names.append(set())
        self.device_names.append(set())
        # loop/iter context does not cross a function boundary
        saved = (self.loop_depth, self.iter_depth)
        self.loop_depth = self.iter_depth = 0
        self.generic_visit(node)
        self.loop_depth, self.iter_depth = saved
        self.device_names.pop()
        self.metric_names.pop()
        self.wall_names.pop()
        self.fn_stack.pop()
        self.qual_stack.pop()
        if entering_coll:
            self.coll_depth -= 1
        if entering_trace:
            self.traced_stack.pop()

    visit_FunctionDef = _visit_function
    visit_AsyncFunctionDef = _visit_function

    def visit_Lambda(self, node: ast.Lambda) -> None:
        if self.traced_stack:
            traced = set(_param_names(node)) | self.traced_stack[-1].traced
            self.traced_stack.append(_TracedCtx("<lambda>", traced))
            self.generic_visit(node)
            self.traced_stack.pop()
        else:
            self.generic_visit(node)

    def _visit_loop(self, node) -> None:
        self.loop_depth += 1
        self.iter_depth += 1
        self.generic_visit(node)
        self.loop_depth -= 1
        self.iter_depth -= 1

    visit_For = _visit_loop
    visit_AsyncFor = _visit_loop

    def visit_While(self, node: ast.While) -> None:
        self._check_control_flow(node)
        self._visit_loop(node)

    def _visit_comp(self, node) -> None:
        self.iter_depth += 1
        self.generic_visit(node)
        self.iter_depth -= 1

    visit_ListComp = _visit_comp
    visit_SetComp = _visit_comp
    visit_DictComp = _visit_comp
    visit_GeneratorExp = _visit_comp

    def visit_With(self, node: ast.With) -> None:
        holds = any(self._is_lock_expr(item.context_expr)
                    for item in node.items)
        # R010 lock surface: `with cond:` acquires the condition's lock
        holds_block = holds or (self.ctx.blocking and any(
            self._is_cond_expr(item.context_expr) for item in node.items))
        if holds:
            self.lock_depth += 1
        if holds_block:
            self.block_depth += 1
        self.generic_visit(node)
        if holds:
            self.lock_depth -= 1
        if holds_block:
            self.block_depth -= 1

    def visit_If(self, node: ast.If) -> None:
        self._check_control_flow(node)
        self.generic_visit(node)

    # -- R004 ---------------------------------------------------------------

    def _check_control_flow(self, node) -> None:
        if not self.traced_stack:
            return
        traced = self.traced_stack[-1].traced
        test = node.test
        # `x is None` / `x is not None` switches on pytree *structure*
        # (resolved at trace time), not on a traced value — allowed.
        if isinstance(test, ast.Compare) and \
                all(isinstance(op, (ast.Is, ast.IsNot)) for op in test.ops) \
                and (_is_none(test.left)
                     or all(_is_none(c) for c in test.comparators)):
            return
        hits = sorted({n.id for n in _walk_skip_static_attrs(test)
                       if isinstance(n, ast.Name) and n.id in traced})
        if hits:
            kind = "while" if isinstance(node, ast.While) else "if"
            self._emit("R004", node,
                       f"Python `{kind}` on traced value(s) "
                       f"{', '.join(hits)} inside jitted "
                       f"`{self.traced_stack[-1].fn_name}` — this reads a "
                       "tracer as a bool (use jnp.where / lax.cond, or "
                       "declare the argument in static_argnames)")

    # -- R001 / R002 / R003 call+subscript checks ---------------------------

    def visit_Call(self, node: ast.Call) -> None:
        mod = self.mod
        if mod.is_jit_expr(node) and self.loop_depth:
            self._emit("R001", node,
                       "jax.jit(...) constructed inside a loop — the program "
                       "cache keys on callable identity, so every iteration "
                       "recompiles; build once outside and reuse")
        self._check_static_call_args(node)
        self._check_sync(node)
        self._check_dynamic_shapes(node)
        self._check_offbudget_put(node)
        self._check_metric_record(node)
        self._check_blocking_wait(node)
        self._check_cluster_thread(node)
        self._check_collective_purity(node)
        self.generic_visit(node)

    # -- R014 ---------------------------------------------------------------

    def _touches_traced(self, node: ast.AST) -> bool:
        if not self.traced_stack:
            return False
        traced = self.traced_stack[-1].traced
        return any(isinstance(n, ast.Name)
                   and (n.id in traced or n.id in self.device_names[-1])
                   for n in ast.walk(node))

    def _check_collective_purity(self, node: ast.Call) -> None:
        """R014: inside a collective (shard_map/psum) program — reached
        through the call graph, not just the lexical body — ANY host
        sync or device transfer stalls every chip in the mesh, because
        the collective's other participants block on the straggler at
        the next psum/all_gather. Flags ``jax.device_get``, ``.item()``,
        ``jax.device_put``, and host pulls (``np.asarray``/``np.array``,
        ``int``/``float``/``bool`` casts) of traced values. Branching on
        device values and un-padded dynamic shapes inside the same
        programs fire as R004/R003 — collective reach is traced reach."""
        if not self.coll_depth:
            return
        f = node.func
        chain = _attr_chain(f) or ""
        head, _, fn = chain.rpartition(".")
        if fn == "device_get" and head in self.mod.jax:
            self._emit("R014", node,
                       "jax.device_get inside a collective program — a "
                       "blocking host sync stalls every chip in the mesh "
                       "at the next collective; return the value from "
                       "the program and pull it after")
            return
        if chain in self.mod.put_fns or (fn == "device_put"
                                         and head in self.mod.jax):
            self._emit("R014", node,
                       "jax.device_put inside a collective program — "
                       "device placement belongs on the host side, "
                       "before the program is dispatched")
            return
        if isinstance(f, ast.Attribute) and f.attr == "item" \
                and not node.args and not node.keywords:
            self._emit("R014", node,
                       ".item() inside a collective program forces a "
                       "host sync that stalls every chip in the mesh; "
                       "keep it an array and pull after the program "
                       "returns")
            return
        if head in self.mod.np and fn in ("asarray", "array") and \
                node.args and self._touches_traced(node.args[0]):
            self._emit("R014", node,
                       f"np.{fn} of a traced value inside a collective "
                       "program — a device→host transfer stalls every "
                       "chip in the mesh; keep the computation in jnp "
                       "and pull after the program returns")
            return
        if _name(f) in ("int", "float", "bool") and len(node.args) == 1 \
                and self._touches_traced(node.args[0]):
            self._emit("R014", node,
                       f"{_name(f)}(...) cast of a traced value inside a "
                       "collective program — concretizing blocks every "
                       "chip in the mesh (and fails under trace); use "
                       "jnp dtype casts instead")

    # -- R009 ---------------------------------------------------------------

    METRIC_FACTORIES = {"counter", "gauge", "histogram", "labels"}
    RECORD_METHODS = {"inc", "dec", "observe", "set"}

    def _is_metric_expr(self, node: ast.AST) -> bool:
        """Does ``node`` resolve to a metrics registry / metric object?
        Recognized roots: names imported from monitor.metrics, the
        module alias itself, a tracked local (`h = m.histogram(...)`),
        or an attribute chain with a literal ``metrics`` segment
        (``self.metrics``, ``node.metrics``)."""
        if isinstance(node, ast.Call):
            f = node.func
            if isinstance(f, ast.Attribute) and \
                    f.attr in self.METRIC_FACTORIES:
                return self._is_metric_expr(f.value)
            # MetricsRegistry(...) / metrics.MetricsRegistry(...) and kin
            chain = _attr_chain(f)
            if chain:
                root = chain.split(".")[0]
                return root in self.mod.metrics_objs \
                    or root in self.mod.metrics_mods
            return False
        nm = _name(node)
        if nm:
            return nm in self.mod.metrics_objs \
                or nm in self.mod.metrics_mods \
                or any(nm in frame for frame in self.metric_names)
        chain = _attr_chain(node)
        if not chain:
            return False
        parts = chain.split(".")
        return parts[0] in self.mod.metrics_objs \
            or parts[0] in self.mod.metrics_mods \
            or any(pt in ("metrics", "METRICS") for pt in parts)

    def _is_record_call(self, node: ast.Call) -> bool:
        f = node.func
        if isinstance(f, ast.Attribute) and f.attr in self.RECORD_METHODS:
            return self._is_metric_expr(f.value)
        # monitor/kernels.py::record — the dispatch-counter twin
        chain = _attr_chain(f) or ""
        head, _, fn = chain.rpartition(".")
        return fn == "record" and head in self.mod.kernels_mods

    def _is_device_operand(self, node: ast.AST) -> bool:
        """Expression that (syntactically) carries a device value into a
        record call: a jnp-rooted call, a name assigned from one, or a
        subscript/attribute/binop over either. Host pulls neutralize —
        ``jax.device_get(x)`` / ``np.asarray(x)`` hand a HOST value to
        the record call (the sync happened, visibly, outside)."""
        if isinstance(node, ast.Call):
            chain = _attr_chain(node.func) or ""
            head, _, fn = chain.rpartition(".")
            if head in self.mod.jax and fn == "device_get":
                return False
            if head in self.mod.np and fn in ("asarray", "array"):
                return False
            if head in self.mod.jnp:
                return True
            return any(self._is_device_operand(a) for a in node.args) \
                or any(self._is_device_operand(k.value)
                       for k in node.keywords)
        if isinstance(node, (ast.Attribute, ast.Subscript)):
            return self._is_device_operand(node.value)
        if isinstance(node, ast.BinOp):
            return self._is_device_operand(node.left) \
                or self._is_device_operand(node.right)
        nm = _name(node)
        return bool(nm) and nm in self.device_names[-1]

    def _assigned_device(self, val: ast.AST) -> bool:
        """Assignment RHS that taints its target as a device value."""
        if isinstance(val, ast.Call):
            chain = _attr_chain(val.func) or ""
            head, _, fn = chain.rpartition(".")
            root = chain.split(".")[0]
            if head in self.mod.jax and fn == "device_get":
                return False
            if head in self.mod.np and fn in ("asarray", "array"):
                return False
            # jnp.* AND jax.*/lax.* ops produce device values
            # (jax.lax.psum, lax.top_k, jax.vmap(...)(...))
            return head in self.mod.jnp or root in self.mod.jax \
                or root in self.mod.lax
        if isinstance(val, (ast.Attribute, ast.Subscript)):
            return self._is_device_operand(val)
        nm = _name(val)
        return bool(nm) and nm in self.device_names[-1]

    def _check_metric_record(self, node: ast.Call) -> None:
        """R009: the hard observability constraint — recording a metric
        must never touch a device value on the hot path. Inside traced
        code a counter ticks once per COMPILE, not per execution (and
        holds a lock under trace); a device-array argument forces a
        blocking host sync inside the record call."""
        if not self._is_record_call(node):
            return
        if self.traced_stack:
            self._emit("R009", node,
                       "metric record call inside jit-traced "
                       f"`{self.traced_stack[-1].fn_name}` — it would tick "
                       "once per compile, not per execution, and lock "
                       "under trace; record on host after the program "
                       "returns")
            return
        for arg in list(node.args) + [k.value for k in node.keywords]:
            if self._is_device_operand(arg):
                self._emit("R009", arg,
                           "device-array argument to a metric record "
                           "call — this blocks on a device sync inside "
                           "the record path; pull the scalar to host "
                           "first (float(jax.device_get(x))) and record "
                           "the plain value")
                return

    # -- R010 ---------------------------------------------------------------

    def _check_blocking_wait(self, node: ast.Call) -> None:
        """R010: an UNBOUNDED ``.wait()`` (Event/Condition) or zero-arg
        ``.get()`` (queue) while holding a lock in a serving module —
        one lost notify (or a crashed drain thread) wedges every parked
        request behind the held lock. A timeout (positional or
        ``timeout=``) bounds the wait so the caller re-checks state;
        ``block=False`` gets are non-blocking."""
        if not self.ctx.blocking or not self.block_depth:
            return
        f = node.func
        if not isinstance(f, ast.Attribute):
            return
        if f.attr == "wait":
            if node.args or any(kw.arg == "timeout"
                                for kw in node.keywords):
                return
            self._emit("R010", node,
                       "unbounded .wait() while holding a lock in a "
                       "serving module — a lost notify wedges every "
                       "parked request behind this lock; pass timeout= "
                       "and re-check state in a loop")
        elif f.attr == "get":
            # bounded/non-blocking forms pass: get(timeout=...),
            # get(block=False), get(False), get(True, 5) — but
            # get(True) / get(block=True) are UNBOUNDED blocking gets,
            # the exact hazard the rule exists for. Exactly one
            # positional that isn't the literal True is a plain
            # dict-style get(key) — not a queue wait.
            if any(kw.arg == "timeout" for kw in node.keywords):
                return
            if len(node.args) >= 2:
                return  # positional (block, timeout)
            blk = next((kw.value for kw in node.keywords
                        if kw.arg == "block"), None)
            if blk is not None and not (
                    isinstance(blk, ast.Constant) and blk.value is True):
                return  # block=False / dynamic: benefit of the doubt
            if len(node.args) == 1 and not (
                    isinstance(node.args[0], ast.Constant)
                    and node.args[0].value is True):
                return  # get(False) non-blocking / dict get(key)
            self._emit("R010", node,
                       "unbounded queue .get() while holding a lock in "
                       "a serving module — bound it (timeout=) or make "
                       "it non-blocking (block=False) so the drain path "
                       "can't wedge behind an empty queue")

    # -- R011 ---------------------------------------------------------------

    def _check_cluster_thread(self, node: ast.Call) -> None:
        """R011: ``threading.Thread(...)`` in a background-thread module
        (cluster/, monitor/, serving/) must be ``daemon=True`` (a
        control-plane or watchdog thread must never block interpreter
        exit) and, when its target's body loops, every loop must consult
        a stop/closed gate (the ``_fault_loop`` pattern ``while not
        self._stop.wait(interval)``, or the drain loop's ``if
        self._closed: return``) — an ungated loop outlives close() and
        keeps probing/publishing/draining a torn-down node."""
        if not self.ctx.threads:
            return
        chain = _attr_chain(node.func) or ""
        head, _, fn = chain.rpartition(".")
        if not (chain in self.mod.thread_fns
                or (fn == "Thread" and head in self.mod.threading_mods)):
            return
        daemon = next((kw.value for kw in node.keywords
                       if kw.arg == "daemon"), None)
        if not (isinstance(daemon, ast.Constant) and daemon.value is True):
            self._emit("R011", node,
                       "background thread without daemon=True — a "
                       "non-daemon control-plane/watchdog thread blocks "
                       "interpreter shutdown; pass daemon=True and gate "
                       "its loop on a stop Event (or closed flag)")
        target = next((kw.value for kw in node.keywords
                       if kw.arg == "target"), None)
        fn_node = self._resolve_thread_target(target)
        if fn_node is None:
            return  # external/opaque target: only the daemon check applies
        # While loops only: a for over a finite work list terminates on
        # its own; the hazard is the indefinite polling loop
        for sub in ast.walk(fn_node):
            if isinstance(sub, ast.While) and not self._stop_gated(sub):
                self._emit("R011", sub,
                           f"loop in thread target `{fn_node.name}` is not "
                           "gated on a stop Event — check a `stop` Event "
                           "or `closed` flag in the loop (the _fault_loop "
                           "pattern: `while not self._stop.wait(interval)`)"
                           " so close() actually stops the thread")

    def _resolve_thread_target(self, target) -> Optional[ast.AST]:
        """target= resolved to a function/method DEFINED IN THIS MODULE:
        a bare name, or ``self.<method>`` resolved within the ENCLOSING
        class only (a same-named method of another class must not be
        checked in its place). Anything else — another object's method,
        an inherited method — is out of static reach."""
        if target is None:
            return None
        nm = _name(target)
        if nm:
            return self.mod.fn_defs.get(nm)
        if isinstance(target, ast.Attribute) and \
                _name(target.value) == "self" and self.class_stack:
            return self.mod.method_defs.get(
                (self.class_stack[-1], target.attr))
        return None

    @staticmethod
    def _stop_gated(loop) -> bool:
        """Anywhere in the loop (test or body — `while True: ... if
        stop.is_set(): break` counts), a name/attribute containing
        'stop' or 'closed' is consulted — both spellings of the same
        shutdown-gate pattern (`while not self._stop.wait(i)` in the
        control plane, `if self._closed: return` in the serving drain
        loop)."""
        for sub in ast.walk(loop):
            if isinstance(sub, ast.Attribute) and (
                    "stop" in sub.attr.lower()
                    or "closed" in sub.attr.lower()):
                return True
            if isinstance(sub, ast.Name) and (
                    "stop" in sub.id.lower()
                    or "closed" in sub.id.lower()):
                return True
        return False

    # -- R008 ---------------------------------------------------------------

    def _check_offbudget_put(self, node: ast.Call) -> None:
        """Raw ``jax.device_put`` in the product package bypasses the
        residency registry: the placed bytes never show in the breaker/
        residency accounting (/_nodes), so the admission-control layer is
        blind to them. Route through RESIDENCY.device_put (always-resident
        structures), RESIDENCY.put_array (evictable host-mirrored copies)
        or RESIDENCY.track (caches), or justify a transient per-call
        upload with `# tpulint: offbudget`."""
        if not self.ctx.budget:
            return
        chain = _attr_chain(node.func) or ""
        head, _, fn = chain.rpartition(".")
        is_put = (chain in self.mod.put_fns
                  or (fn == "device_put" and head in self.mod.jax))
        if is_put:
            self._emit("R008", node,
                       "raw jax.device_put bypasses the residency registry "
                       "(unaccounted HBM) — use resources.RESIDENCY."
                       "device_put/put_array/track, or justify a transient "
                       "upload with `# tpulint: offbudget`")

    def _check_static_call_args(self, node: ast.Call) -> None:
        target = self.mod.jitted.get(_name(node.func) or "")
        if target is None or not target.statics:
            return
        for kw in node.keywords:
            if kw.arg not in target.statics:
                continue
            if isinstance(kw.value, (ast.List, ast.Dict, ast.Set,
                                     ast.ListComp, ast.DictComp, ast.SetComp,
                                     ast.GeneratorExp)):
                self._emit("R001", kw.value,
                           f"unhashable value passed to static argument "
                           f"`{kw.arg}` of jitted `{_name(node.func)}` — "
                           "jit static args must be hashable (use a tuple "
                           "or frozenset)")
            elif isinstance(kw.value, ast.Call) and \
                    _name(kw.value.func) == "len":
                self._emit("R001", kw.value,
                           f"raw len(...) passed to static argument "
                           f"`{kw.arg}` of jitted `{_name(node.func)}` — "
                           "every distinct size compiles a new program; "
                           "bucket it first (utils.shapes.pow2_bucket)")

    # -- R002 ---------------------------------------------------------------

    def _is_host_pull(self, node: ast.AST) -> bool:
        """Call that moves a device array to host (np.asarray/np.array/
        jax.device_get)."""
        if not isinstance(node, ast.Call):
            return False
        chain = _attr_chain(node.func) or ""
        head, _, fn = chain.rpartition(".")
        if head in self.mod.np and fn in ("asarray", "array"):
            return True
        return head in self.mod.jax and fn == "device_get"

    @staticmethod
    def _is_scalar_index(sl: ast.AST) -> bool:
        if isinstance(sl, ast.Slice):
            return False
        if isinstance(sl, ast.Tuple):
            return all(not isinstance(e, ast.Slice) for e in sl.elts)
        return True

    def _check_sync(self, node: ast.Call) -> None:
        f = node.func
        if isinstance(f, ast.Attribute) and f.attr == "item" \
                and not node.args and not node.keywords:
            # traced context fires EVERYWHERE (a traced value has no
            # concrete scalar, regardless of which file it lives in) —
            # the whole-program pass reaches helpers the hot-path list
            # never covered; collective reach reports as R014 instead
            if self.traced_stack and not self.coll_depth:
                self._emit("R002", node,
                           ".item() inside jitted "
                           f"`{self.traced_stack[-1].fn_name}` — a traced "
                           "value has no concrete scalar (trace-time "
                           "error); keep it an array and pull on host "
                           "after the program returns")
            elif self.ctx.hot and not self.traced_stack and self.iter_depth:
                self._emit("R002", node,
                           ".item() inside a loop is one blocking device "
                           "sync per iteration — pull the whole array to "
                           "host once before the loop")
        if not self.ctx.hot:
            return
        if _name(f) in ("int", "float", "bool") and len(node.args) == 1:
            arg = node.args[0]
            if isinstance(arg, ast.Subscript) and \
                    self._is_host_pull(arg.value) and \
                    self._is_scalar_index(arg.slice):
                self._emit("R002", node,
                           f"{_name(f)}(np.asarray(...)[i]) transfers a "
                           "device array to pull one scalar — hoist the "
                           "host copy and index it instead")

    def visit_Subscript(self, node: ast.Subscript) -> None:
        if self.ctx.hot and self.iter_depth and \
                self._is_host_pull(node.value) and \
                self._is_scalar_index(node.slice):
            self._emit("R002", node,
                       "scalar index into np.asarray(...) inside a loop — "
                       "one full device→host transfer per iteration; copy "
                       "to host once before the loop")
        if self.traced_stack:
            sl = node.slice
            masky = isinstance(sl, (ast.Compare, ast.BoolOp)) or (
                isinstance(sl, ast.UnaryOp) and isinstance(sl.op, ast.Not))
            if masky:
                self._emit("R003", node,
                           "boolean-mask indexing inside jitted "
                           f"`{self.traced_stack[-1].fn_name}` yields a "
                           "data-dependent shape — use jnp.where(mask, x, "
                           "fill) or size=-bounded jnp.nonzero")
        self.generic_visit(node)

    # -- R003 ---------------------------------------------------------------

    def _check_dynamic_shapes(self, node: ast.Call) -> None:
        chain = _attr_chain(node.func) or ""
        head, _, fn = chain.rpartition(".")
        has_size = any(kw.arg == "size" for kw in node.keywords)
        if self.traced_stack and head in self.mod.jnp:
            if fn in DYNAMIC_SHAPE_FNS and not has_size:
                self._emit("R003", node,
                           f"jnp.{fn} without size= inside jitted "
                           f"`{self.traced_stack[-1].fn_name}` — the result "
                           "shape depends on data; pass size= (+ fill_value) "
                           "to keep the program statically shaped")
            elif fn == "where" and len(node.args) == 1:
                self._emit("R003", node,
                           "single-argument jnp.where inside jitted "
                           f"`{self.traced_stack[-1].fn_name}` returns "
                           "data-dependent indices — use the three-argument "
                           "form or size=-bounded jnp.nonzero")
        elif self.ctx.ops and not self.traced_stack \
                and head in self.mod.np and fn in DYNAMIC_SHAPE_FNS:
            if node.lineno not in self.ctx.host_lines:
                self._emit("R003", node,
                           f"np.{fn} in a device-op module: dynamic-shape "
                           "host call is ambiguous next to traced code — "
                           "annotate the line `# tpulint: host` (build path) "
                           "or move to a size=-bounded device form")

    # -- R006 ---------------------------------------------------------------

    def visit_ExceptHandler(self, node: ast.ExceptHandler) -> None:
        """Flag `except [Exception|BaseException]: pass` in failure-domain
        modules: the swallowed fault (a dead peer, a failed fsync, a lost
        replica ack) never reaches retry/breaker/partial-result
        accounting. Typed catches (`except DocumentMissingException:
        pass`) and handlers that DO something (log, record a failure
        entry, continue a loop with accounting) are fine."""
        if self.ctx.swallow and self._is_broad_catch(node.type) \
                and self._is_noop_body(node.body):
            what = ("bare except" if node.type is None
                    else _attr_chain(node.type) or "broad except")
            self._emit("R006", node,
                       f"`{what}: pass` swallows every failure on this "
                       "path — record it (failure entry, stats counter, "
                       "shard-failed report) or narrow the catch; if the "
                       "swallow is genuinely safe, justify it with "
                       "`# tpulint: allow[R006]` or a baseline entry")
        self.generic_visit(node)

    @classmethod
    def _is_broad_catch(cls, t: Optional[ast.AST]) -> bool:
        if t is None:
            return True  # bare `except:`
        if isinstance(t, ast.Tuple):  # `except (Exception,):` counts too
            return any(cls._is_broad_catch(e) for e in t.elts)
        chain = _attr_chain(t) or ""
        return chain.rpartition(".")[2] in ("Exception", "BaseException")

    @staticmethod
    def _is_noop_body(body) -> bool:
        """pass / `...` / a bare string — anything that does no work."""
        return all(isinstance(s, ast.Pass)
                   or (isinstance(s, ast.Expr)
                       and isinstance(s.value, ast.Constant))
                   for s in body)

    # -- R007 ---------------------------------------------------------------

    def _is_wall_call(self, node: ast.AST) -> bool:
        """`time.time()` (or a `from time import time` alias) call."""
        if not isinstance(node, ast.Call) or node.args or node.keywords:
            return False
        chain = _attr_chain(node.func) or ""
        if chain in self.mod.wall_fns:
            return True
        head, _, fn = chain.rpartition(".")
        return fn == "time" and head in self.mod.time_mods

    def _wall_operand(self, node: ast.AST) -> bool:
        return self._is_wall_call(node) or (
            isinstance(node, ast.Name) and node.id in self.wall_names[-1])

    def visit_BinOp(self, node: ast.BinOp) -> None:
        """R007: a wall-clock reading on either side of a subtraction IS
        a duration computation — in a timing module it must come from
        time.monotonic()/perf_counter (time.time() steps under NTP
        adjustments and skews every span/latency it feeds). Epoch
        timestamps (`int(time.time() * 1000)`) never subtract and stay
        legal."""
        if self.ctx.timing and isinstance(node.op, ast.Sub) and (
                self._wall_operand(node.left)
                or self._wall_operand(node.right)):
            self._emit("R007", node,
                       "wall-clock time.time() feeds a duration "
                       "computation — use time.monotonic() or "
                       "time.perf_counter() for span/duration "
                       "measurement (wall clock steps under NTP; "
                       "timestamps that are never subtracted are fine)")
        self.generic_visit(node)

    # -- R005 ---------------------------------------------------------------

    def _is_lock_expr(self, expr: ast.AST) -> bool:
        nm = _name(expr)
        if nm and nm in self.mod.module_locks:
            return True
        chain = _attr_chain(expr) or ""
        if chain.startswith("self.") and self.class_stack:
            return chain[len("self."):] in self.class_locks.get(
                self.class_stack[-1], set())
        return False

    def _is_cond_expr(self, expr: ast.AST) -> bool:
        nm = _name(expr)
        if nm and nm in self.mod.module_conds:
            return True
        chain = _attr_chain(expr) or ""
        if chain.startswith("self.") and self.class_stack:
            return chain[len("self."):] in self.class_conds.get(
                self.class_stack[-1], set())
        return False

    def _shared_target_root(self, node: ast.AST) -> Optional[str]:
        """'self.X' / module-global name when `node` resolves to shared
        state owned by a lock in this file, else None."""
        base = node
        while isinstance(base, (ast.Subscript, ast.Attribute)):
            if isinstance(base, ast.Attribute) and _name(base.value) == "self":
                if self.class_stack and self.class_locks.get(
                        self.class_stack[-1]):
                    return f"self.{base.attr}"
                return None
            base = base.value
        nm = _name(base)
        if nm and nm in self.mod.shared_globals and self.mod.module_locks:
            # plain Name target only counts when it is the *container being
            # mutated* (subscript/del) or rebound via `global`
            return nm
        return None

    def _in_exempt_method(self) -> bool:
        """__init__/__new__ build unshared state; `_private` helpers follow
        the codebase's caller-holds-the-lock convention (see engine.py's
        `_remove_existing`, called under `index()`'s lock)."""
        if not self.fn_stack:
            return True  # module level runs at import, single-threaded
        name = self.fn_stack[0] if not self.class_stack else self.fn_stack[-1]
        if self.class_stack:
            return name in ("__init__", "__new__") or (
                name.startswith("_") and not name.startswith("__"))
        return False

    def _check_mutation(self, node: ast.AST, root: Optional[str]) -> None:
        if not self.ctx.locked or root is None or self.lock_depth \
                or self._in_exempt_method():
            return
        owner = (f"class `{self.class_stack[-1]}`" if self.class_stack
                 else "this module")
        self._emit("R005", node,
                   f"`{root}` is shared mutable state of {owner} (accessed "
                   "from threadpool workers) written without holding its "
                   "lock — wrap in `with <lock>:` or move into a "
                   "caller-locked `_private` helper")

    def visit_Assign(self, node: ast.Assign) -> None:
        if self.ctx.locked:
            for tgt in node.targets:
                if isinstance(tgt, (ast.Attribute, ast.Subscript)):
                    self._check_mutation(tgt, self._shared_target_root(tgt))
        if self.ctx.timing:
            # track `t0 = time.time()` so a later `... - t0` flags
            # (R007); any OTHER reassignment clears the taint — a name
            # rebound to time.monotonic() must not keep flagging
            wall = self._is_wall_call(node.value)
            for tgt in node.targets:
                nm = _name(tgt)
                if nm:
                    (self.wall_names[-1].add if wall
                     else self.wall_names[-1].discard)(nm)
        # R009 name tracking: `h = m.histogram(...)` makes h a metric
        # object; `x = jnp.sum(...)` taints x as a device value. Any
        # other reassignment clears either mark.
        is_metric = self._is_metric_expr(node.value)
        is_dev = self._assigned_device(node.value)
        for tgt in node.targets:
            nm = _name(tgt)
            if nm:
                (self.metric_names[-1].add if is_metric
                 else self.metric_names[-1].discard)(nm)
                (self.device_names[-1].add if is_dev
                 else self.device_names[-1].discard)(nm)
        self.generic_visit(node)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        if self.ctx.locked and isinstance(node.target,
                                          (ast.Attribute, ast.Subscript)):
            self._check_mutation(node.target,
                                 self._shared_target_root(node.target))
        self.generic_visit(node)

    def visit_Delete(self, node: ast.Delete) -> None:
        if self.ctx.locked:
            for tgt in node.targets:
                if isinstance(tgt, (ast.Attribute, ast.Subscript)):
                    self._check_mutation(tgt, self._shared_target_root(tgt))
        self.generic_visit(node)

    def visit_Expr(self, node: ast.Expr) -> None:
        if self.ctx.locked and isinstance(node.value, ast.Call):
            f = node.value.func
            if isinstance(f, ast.Attribute) and f.attr in MUTATOR_METHODS:
                self._check_mutation(node.value,
                                     self._shared_target_root(f.value))
        self.generic_visit(node)


def _check_import_time_jit(tree: ast.Module, ctx: FileContext,
                           mod: _ModuleInfo, out: List[Violation]) -> None:
    """R012: an import-time ``jax.jit`` binding (a jit decorator on a
    top-level function/method, or a module-level ``x = jax.jit(...)``
    assignment) in a module OUTSIDE the trace-audited packages compiles
    its program whenever the module happens to be imported before the
    auditor's install point — the program then escapes compile
    attribution (the observatory's census and the profiler's
    compile/execute split both under-report). The audited packages
    (``ops/``, ``models/``, ``parallel/``) call
    ``tracing/retrace.ensure_installed()`` in their ``__init__`` before
    any submodule binds, so bindings there are covered regardless of
    import order; everywhere else the binding must move into a factory
    function (bound at first call, long after install) or into an
    audited package."""
    if not ctx.audit:
        return

    def _emit(node: ast.AST, what: str) -> None:
        out.append(Violation(
            "R012", ctx.path, node.lineno, node.col_offset,
            f"import-time jax.jit binding ({what}) outside the "
            "trace-audited packages (ops/, models/, parallel/) — the "
            "program can compile before tracing/retrace installs the "
            "auditor and escapes compile attribution; bind inside a "
            "factory function or move the module under an audited "
            "package", snippet_at(ctx.lines, node.lineno)))

    def _check_stmts(stmts) -> None:
        for stmt in stmts:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if mod.decorator_jit(stmt) is not None:
                    _emit(stmt, f"decorator on `{stmt.name}`")
            elif isinstance(stmt, ast.ClassDef):
                # class bodies execute at import too — a jitted method
                # binds exactly like a top-level function
                _check_stmts(stmt.body)
            elif isinstance(stmt, (ast.Assign, ast.AnnAssign)) and \
                    isinstance(stmt.value, ast.Call) and \
                    mod.is_jit_expr(stmt.value):
                _emit(stmt, "module-level assignment")
            elif isinstance(stmt, (ast.If, ast.For, ast.While, ast.With,
                                   ast.Try)):
                # module-level control flow still executes at import —
                # `if HAS_JAX:` / `try:` guards around a binding don't
                # defer it (only a def does)
                for attr in ("body", "orelse", "finalbody"):
                    _check_stmts(getattr(stmt, attr, ()) or ())
                for h in getattr(stmt, "handlers", ()) or ():
                    _check_stmts(h.body)

    _check_stmts(tree.body)


def _check_memoized_jit(tree: ast.Module, ctx: FileContext,
                        mod: _ModuleInfo, out: List[Violation]) -> None:
    """R012 (memoization arm): a jit-derived program stored into a
    module-level cache inside a hot-path module —
    ``_PROGRAMS[key] = jax.jit(...)`` — is a process memo: it dedupes
    compiles for THIS process but bypasses the ``parallel.aot``
    AotProgram factory, so a warm restart re-traces and re-compiles
    every shape class instead of loading the compiled-executable blob,
    and the program never joins the factory-key discipline the census
    pre-warm replays against. Route the jitted callable through
    ``aot.wrap(fn, name, key)`` (or construct an ``AotProgram``)
    BEFORE memoizing; the wrap is the blessed shape and is not
    flagged."""
    if not ctx.hot:
        return

    # module-level container names (the memo dicts)
    memos: Set[str] = set()
    for stmt in tree.body:
        if isinstance(stmt, ast.Assign):
            tgts, val = stmt.targets, stmt.value
        elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
            tgts, val = [stmt.target], stmt.value
        else:
            continue
        chain = _attr_chain(val.func) if isinstance(val, ast.Call) else ""
        if isinstance(val, ast.Dict) or chain in (
                "dict", "defaultdict", "collections.defaultdict",
                "OrderedDict", "collections.OrderedDict"):
            for t in tgts:
                nm = _name(t)
                if nm:
                    memos.add(nm)
    if not memos:
        return

    # names the aot factory is visible under in this module
    wrap_fns: Set[str] = {"AotProgram"}
    aot_mods: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom) and node.module:
            if node.module.endswith("parallel.aot"):
                for al in node.names:
                    if al.name in ("wrap", "AotProgram"):
                        wrap_fns.add(al.asname or al.name)
            elif node.module.endswith(".parallel") or node.module == "parallel":
                for al in node.names:
                    if al.name == "aot":
                        aot_mods.add(al.asname or "aot")

    def _is_wrap(call: ast.Call) -> bool:
        chain = _attr_chain(call.func) or ""
        if chain in wrap_fns:
            return True
        root, _, leaf = chain.rpartition(".")
        return leaf in ("wrap", "AotProgram") and root in aot_mods

    def _derived(val: ast.AST, jit_names: Set[str]) -> bool:
        if isinstance(val, ast.Call):
            if _is_wrap(val):
                return False
            if mod.is_jit_expr(val):
                return True
            # `partial(jax.jit, ...)(fn)` — the outer call applies a
            # jit-building partial; unwrap one level
            return isinstance(val.func, ast.Call) and \
                mod.is_jit_expr(val.func)
        nm = _name(val)
        return nm in jit_names if nm else False

    def _emit(node: ast.AST, root: str) -> None:
        out.append(Violation(
            "R012", ctx.path, node.lineno, node.col_offset,
            f"process-memoized jax.jit program (`{root}[...] = <jit>`) "
            "outside the AotProgram factory in a hot-path module — a "
            "warm restart re-traces and re-compiles every shape class "
            "and the census pre-warm cannot replay it; route through "
            "parallel.aot.wrap(fn, name, key) before memoizing",
            snippet_at(ctx.lines, node.lineno)))

    def _scan(stmts, jit_names: Set[str]) -> None:
        for st in stmts:
            if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef,
                               ast.ClassDef)):
                _scan(st.body, set())  # fresh scope
                continue
            if isinstance(st, ast.Expr) and isinstance(st.value, ast.Call):
                c = st.value
                if isinstance(c.func, ast.Attribute) and \
                        c.func.attr == "setdefault" and \
                        _name(c.func.value) in memos and \
                        len(c.args) >= 2 and _derived(c.args[1], jit_names):
                    _emit(st, _name(c.func.value) or "")
            if isinstance(st, (ast.Assign, ast.AnnAssign)):
                tgts = (st.targets if isinstance(st, ast.Assign)
                        else [st.target])
                val = st.value
                if val is not None:
                    derived = _derived(val, jit_names)
                    for tgt in tgts:
                        if isinstance(tgt, ast.Subscript) and derived and \
                                _name(tgt.value) in memos:
                            _emit(st, _name(tgt.value) or "")
                        nm = _name(tgt)
                        if nm:
                            (jit_names.add if derived
                             else jit_names.discard)(nm)
            for attr in ("body", "orelse", "finalbody"):
                _scan(getattr(st, attr, ()) or (), jit_names)
            for h in getattr(st, "handlers", ()) or ():
                _scan(h.body, jit_names)

    _scan(tree.body, set())


def check_module(tree: ast.Module, ctx: FileContext) -> List[Violation]:
    mod = _ModuleInfo(tree)
    checker = _Checker(ctx, mod)
    checker.visit(tree)
    _check_import_time_jit(tree, ctx, mod, checker.out)
    _check_memoized_jit(tree, ctx, mod, checker.out)
    return checker.out
