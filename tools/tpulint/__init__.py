"""tpulint — a JAX/TPU-aware static-analysis pass for elasticsearch_tpu.

The paper's core bet is that per-segment scoring runs as batched,
statically-shaped device programs. That bet silently breaks whenever a
dynamic shape, tracer leak, or per-hit host sync creeps into a jitted
path — failures that surface not as exceptions but as recompile storms
and serialized device↔host ping-pong on TPU. (R006 guards a different
invariant of the same production-scale bet: faults in the distributed
failure domain must be ACCOUNTED, never swallowed.) tpulint catches the
known failure classes at review time:

  R001  recompilation hazards: jit construction inside a loop; unhashable
        or unbucketed high-cardinality values fed to ``static_argnames``.
  R002  host↔device sync in hot paths (``ops/``, ``search/``,
        ``rest/server.py``): ``.item()`` / scalar ``np.asarray(x)[i]``
        pulls inside per-hit loops, scalar casts of device pulls.
  R003  dynamic-shape leaks: ``jnp.nonzero``/``unique``/``where(cond)``
        without ``size=`` and boolean-mask indexing inside traced code;
        un-annotated host ``np.nonzero``-family calls in ``ops/``.
  R004  tracer leaks: Python ``if``/``while`` on traced arguments inside
        jitted functions.
  R005  lock discipline: mutation of shared state in threadpool-visible
        modules (engine/translog/ivf_cache/threadpool) outside a
        ``with <lock>`` block.
  R007  wall-clock durations: ``time.time()`` feeding a subtraction in
        the timing modules (``tracing/``, ``monitor/``) — spans and
        latencies must use ``time.monotonic()``/``perf_counter``.
  R006  swallowed failures: bare ``except Exception: pass`` in the
        failure-domain layers (``cluster/``, ``index/``, ``rest/``) —
        a fault that never reaches retry/breaker/partial-result
        accounting becomes silent data loss.

Suppress a finding in place with ``# tpulint: allow[R00x]`` on the line
(or an immediately preceding comment line); mark intentional host-side
build code with ``# tpulint: host``. Grandfathered sites live in
``tools/tpulint/baseline.json``.

Run: ``python -m tools.tpulint [paths] [--json]``.

``tools.tpulint.trace_audit`` is the runtime counterpart: it wraps
``jax.jit`` to count (re)traces per callable and assert an upper bound,
so benches and tests can prove steady-state means zero recompiles.
"""
from tools.tpulint.analyzer import (  # noqa: F401
    RULES,
    Violation,
    lint_file,
    lint_paths,
    lint_source,
)
from tools.tpulint.baseline import (  # noqa: F401
    DEFAULT_BASELINE,
    filter_baselined,
    load_baseline,
    write_baseline,
)
