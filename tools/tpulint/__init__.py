"""tpulint — JAX/TPU-aware whole-program static analysis for
elasticsearch_tpu.

The paper's core bet is that per-segment scoring runs as batched,
statically-shaped device programs — since the shard_map mesh executor,
*collective* device programs, where one stray host sync stalls every
chip. That bet silently breaks whenever a dynamic shape, tracer leak,
or per-hit host sync creeps into a jitted path — failures that surface
not as exceptions but as recompile storms and serialized device↔host
ping-pong on TPU.

tpulint v3 is a THREE-PASS analyzer: pass 1 (``tools/tpulint/project.py``)
builds a project-wide symbol table + call graph and infers which
functions are transitively reachable from ``jax.jit`` / ``pallas_call``
/ ``shard_map`` bodies (traced reach), which sit inside collective
programs, which run CONCURRENTLY (reachable from thread roots: Thread
targets, pool submissions, REST/transport handlers), and which locks
are held at every acquire site — and on entry to every function —
interprocedurally; pass 2 (``tools/tpulint/rules.py`` + the project
rules) runs sixteen rules over that view — R001 recompile hazards,
R002 host syncs (traced reach + hot-path loops), R003 dynamic shapes,
R004 tracer leaks, R005 lock discipline, R006 swallowed failures, R007
wall-clock durations, R008 unaccounted device placement, R009 metric
recording on the device path, R010 unbounded waits under serving
locks, R011 ungated cluster threads, R012 import-time jit bindings
escaping compile attribution, R013 lock-order cycles + lock-held calls
into unbounded waits, R014 collective purity, R015 Eraser-style
lockset races (a write without the attribute's inferred/declared
guard), R016 atomicity violations (check-then-act across a lock
release); pass 3 (``tools/tpulint/shapeflow.py``) is a symbolic
shape-flow abstract interpreter over the pass-1 call graph — dims
classify into a Concrete < PaddedPow2 < DataDependent lattice and flow
interprocedurally — behind R017 recompile storms (a data-dependent dim
riding a program-factory cache key or jit static arg), R018 padding
soundness (an unmasked reduction over padded lanes in a collective
body), R019 dtype discipline (f64/i64 spellings, mixed bf16×f32 MXU
matmuls in traced code), and R020 reservation leaks (a
breaker/residency charge with a fallible call before its
commit/release). R002/R003/R004/R009 fire THROUGH helper calls — a
violation two modules away from the jit body is found where it lives.

Suppress a finding in place with ``# tpulint: allow[R0xx]`` on the line
(or an immediately preceding comment line); mark intentional host-side
build code with ``# tpulint: host``; declare an attribute's guarding
lock with ``# tpulint: guarded_by(self._lock)``; declare shapeflow
invariants at the cast/pad point with ``# tpulint: bucketed`` /
``masked`` / ``cast`` (≡ allow[R017]/[R018]/[R019]). Grandfathered
sites live in ``tools/tpulint/baseline.json``; ``--prune-baseline``
audits them against the live finding set.

Run: ``python -m tools.tpulint [--changed [BASE]] [--json] [--sarif]
[paths]`` — or install ``tools/tpulint/hooks/pre-commit`` to gate
every commit on the changed-file subset.

``tools.tpulint.trace_audit`` is the runtime counterpart: it wraps
``jax.jit`` to count (re)traces per callable and assert an upper bound,
so benches and tests can prove steady-state means zero recompiles.
"""
from tools.tpulint.analyzer import (  # noqa: F401
    RULES,
    SEVERITY,
    Violation,
    lint_file,
    lint_paths,
    lint_source,
)
from tools.tpulint.project import (  # noqa: F401
    analyze_sources,
    build_project,
    lint_project,
    lint_sources,
)
from tools.tpulint.baseline import (  # noqa: F401
    DEFAULT_BASELINE,
    filter_baselined,
    load_baseline,
    write_baseline,
)
