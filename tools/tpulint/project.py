"""tpulint pass 1: whole-program symbol table, call graph, and the
dataflow facts pass 2 consumes.

The per-file rules (tools/tpulint/rules.py) see one module at a time, so
a host sync or metrics record hidden one helper call away from a
``@jax.jit`` body is invisible to them. This module builds the project
view that closes that blind spot:

* **Symbol table** — every function/method in the analyzed file set,
  keyed ``module:qualname`` (``elasticsearch_tpu.ops.scoring:topk_auto``,
  ``...executor:MeshSearchExecutor._search_round``, nested defs as
  ``outer.inner``), with import aliasing resolved per module (``import
  a.b as x`` / ``from a.b import f as g`` / relative forms).
* **Call graph** — CALL edges for resolvable calls (bare names, local
  aliases, ``mod.fn`` chains, ``self.method`` within the enclosing class
  and its project-resolvable bases, ``Class()`` → ``__init__``), REF
  edges for function references passed as arguments (``jax.vmap(f)``,
  ``partial(self._run, ...)``) and for nested defs (a helper defined
  inside a traced body is traced).
* **Traced-context inference** — a fixpoint marks every function
  transitively reachable from a ``jax.jit`` / ``pallas_call`` /
  ``shard_map`` body as traced, refining per-parameter tracedness from
  call sites (an argument that is a literal or a static parameter of a
  traced caller stays static; everything else is a potential tracer).
  Pass 2 enters these functions exactly like locally-jitted ones, so
  R002/R003/R004/R009 fire through helper calls instead of path lists.
* **Collective reach** — traced roots passed to ``shard_map`` (directly
  or via the executor's ``wrap`` idiom) or containing ``psum`` /
  ``all_gather`` collectives, plus everything they reach: the R014
  scope, where ANY host sync stalls every chip in the mesh.
* **Lock graph (R013)** — which locks are held at each ``with lock:``
  site, interprocedurally: held→acquired edges (including acquires
  buried in callees), cycle detection over them, and lock-held calls
  into unbounded blocking waits (``Event.wait()`` / ``queue.get()``
  with no timeout — the R010 hazard generalized past ``serving/``).
* **Concurrent reach (R015/R016)** — a fixpoint over the call graph
  from the *thread roots*: ``threading.Thread`` targets, thread-pool
  ``execute``/``submit`` arguments, REST route handlers
  (``rc.add("GET", ..., handler)``) and transport/task ``register``
  callbacks. Everything reachable runs (potentially) concurrently with
  every other reachable function — the Eraser-style scope.
* **Per-attribute locksets (R015)** — every ``self.<attr>`` access is
  recorded with the guards (locks AND condition locks) held at it,
  lexically plus the interprocedural *held-on-entry* context (the meet
  over all call sites — the ``_private`` caller-locked convention made
  precise). Intersecting guard sets across an attribute's concurrent
  accesses infers its guarding lock (or ``# tpulint:
  guarded_by(<lock>)`` declares it); a concurrent write without the
  guard is R015.
* **Atomicity (R016)** — within one function, a *read-only* guarded
  region of an attribute followed by a later guarded write of the same
  attribute under the same lock, with the lock released in between:
  the check-then-act / get-or-create shape whose window a concurrent
  writer can slip through.

Everything stays stdlib-``ast``: no JAX import, no device, fast enough
for tier-1 (the gate asserts a full-repo pass under 30s).
"""
from __future__ import annotations

import ast
import os
import re
from dataclasses import dataclass, field
from typing import Dict, FrozenSet, List, Optional, Sequence, Set, Tuple

from tools.tpulint.analyzer import (Suppressions, Violation,
                                    iter_python_files, snippet_at)

# Function-wrapper call names whose function-valued arguments get traced
# (the callable is compiled/trace-executed, not called on host). `wrap`
# is the executor's shard_map-or-jit closure idiom (parallel/executor.py
# `_collectives`): program bodies reach shard_map exclusively through it.
TRACED_WRAPPER_NAMES = {
    "jit", "vmap", "pmap", "grad", "value_and_grad", "checkpoint",
    "remat", "scan", "cond", "while_loop", "fori_loop", "switch",
    "pallas_call", "shard_map", "wrap",
}
# The subset that compiles a *collective* program: its body runs
# SPMD across every mesh slot, so host syncs inside stall all chips.
COLLECTIVE_WRAPPER_NAMES = {"shard_map", "wrap"}
# Collective ops: a traced function calling one of these IS part of a
# collective program even when the shard_map wrapper is out of reach.
COLLECTIVE_OP_NAMES = {"psum", "all_gather", "pmean", "pmax", "pmin",
                       "ppermute", "axis_index", "all_to_all"}

_LOCK_SUFFIXES = (".Lock", ".RLock")
_QUEUE_NAMES = {"Queue", "LifoQueue", "PriorityQueue", "SimpleQueue"}

# Thread-root spellings (R015/R016 concurrent reach). A function-valued
# argument at one of these call shapes runs on another thread (or on a
# pool/handler thread concurrently with its siblings):
#   Thread(target=f)                     -- the classic daemon loop
#   pool.execute(f, ...) / pool.submit(f, ...)
#                                        -- utils.threadpool submissions
#                                           (every REST request runs here)
#   t.register(ACTION, self._on_x) / tasks.register(..., on_cancel=f)
#                                        -- transport handlers + cancel
#                                           callbacks (remote/any thread)
#   rc.add("GET", "/path", handler)      -- REST route table (dispatched
#                                           from pool threads)
_POOL_SUBMIT_NAMES = {"execute", "submit"}
_REGISTER_NAMES = {"register"}
_HTTP_METHODS = {"GET", "POST", "PUT", "DELETE", "HEAD", "OPTIONS",
                 "PATCH"}
# container-mutating method names: a `self.x.append(...)` is a WRITE of
# self.x for lockset purposes (mirrors rules.MUTATOR_METHODS; kept here
# to avoid an import cycle at module load)
_MUTATORS = {
    "append", "add", "update", "pop", "popitem", "clear", "remove",
    "extend", "insert", "setdefault", "discard", "appendleft",
    "popleft", "move_to_end",
}
# `# tpulint: guarded_by(self._lock)` — declares the guarding lock of
# the instance attribute assigned on the same line (the declaration
# site is the attribute's __init__ assignment)
_GUARDED_BY_RE = re.compile(r"#\s*tpulint:\s*guarded_by\(\s*([A-Za-z_."
                            r"][A-Za-z0-9_.]*)\s*\)")


def module_name_for(relpath: str) -> str:
    """'elasticsearch_tpu/ops/scoring.py' -> 'elasticsearch_tpu.ops.scoring'."""
    p = relpath.replace(os.sep, "/")
    if p.endswith("/__init__.py"):
        p = p[: -len("/__init__.py")]
    elif p.endswith("__init__.py"):
        p = ""
    elif p.endswith(".py"):
        p = p[:-3]
    return p.strip("/").replace("/", ".")


def _name(node: ast.AST) -> Optional[str]:
    return node.id if isinstance(node, ast.Name) else None


def _attr_chain(node: ast.AST) -> Optional[str]:
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _fn_params(node, *, include_var: bool = True) -> List[str]:
    a = node.args
    names = [p.arg for p in getattr(a, "posonlyargs", [])]
    names += [p.arg for p in a.args]
    if include_var and a.vararg is not None:
        names.append(a.vararg.arg)
    names += [p.arg for p in a.kwonlyargs]
    if include_var and a.kwarg is not None:
        names.append(a.kwarg.arg)
    return names


@dataclass
class CallEdge:
    callee: str                       # sid 'module:qual'
    kind: str                         # 'call' | 'ref'
    line: int = 0
    # per-argument classification for traced-param refinement:
    # (callee_param, 'const') | (param, ('param', caller_param)) |
    # (param, 'dyn'); all_dyn short-circuits (e.g. *args splat)
    args: List[Tuple[str, object]] = field(default_factory=list)
    all_dyn: bool = False
    held: Tuple[str, ...] = ()        # lock ids held at the call site
    gheld: Tuple[str, ...] = ()       # guard ids (locks + condition
    #                                   locks) held — the R015 context


@dataclass
class AttrAccess:
    """One ``self.<attr>`` access inside a function body."""
    attr: str
    kind: str                         # 'r' read | 'w' write | 'm' mutate
    #                                   (method-call write: .append/.pop —
    #                                   reads AND writes the container)
    line: int
    gheld: Tuple[str, ...]            # guard ids lexically held
    epochs: Tuple[Tuple[str, int], ...]  # (guard, region epoch) pairs —
    #                                   the epoch bumps every time the
    #                                   guard is fully released, so two
    #                                   accesses under the same guard in
    #                                   DIFFERENT epochs straddle a
    #                                   release window (R016)


@dataclass
class FnSymbol:
    sid: str
    module: str
    qual: str
    node: ast.AST
    cls: Optional[str]
    params: List[str]
    statics: Set[str] = field(default_factory=set)
    is_root: bool = False             # locally jit-rooted
    root_all_params: bool = False     # wrapper-marked: every param traced
    is_collective_root: bool = False
    has_collective_call: bool = False
    is_thread_root: bool = False      # R015: runs on its own/pool thread
    edges: List[CallEdge] = field(default_factory=list)
    # lock facts (with-block granularity; flow within a fn is lexical)
    acquires: List[Tuple[str, int]] = field(default_factory=list)
    lock_edges: List[Tuple[str, str, int]] = field(default_factory=list)
    direct_waits: List[Tuple[int, str]] = field(default_factory=list)
    waits_under: List[Tuple[str, int, str]] = field(default_factory=list)
    # R015/R016: every self.<attr> access with its held-guard context
    attr_accesses: List[AttrAccess] = field(default_factory=list)


@dataclass
class ClassRec:
    name: str
    bases: List[str]                  # attr-chain strings
    locks: Set[str] = field(default_factory=set)
    conds: Set[str] = field(default_factory=set)
    events: Set[str] = field(default_factory=set)
    queues: Set[str] = field(default_factory=set)
    # instance-attribute types from constructor-call assignments
    # (`self.translog = Translog(path)`): attr -> ctor chain string,
    # resolved lazily against imports — this is what lets the lock graph
    # follow `self.translog.append()` across the engine/translog boundary
    attr_types: Dict[str, str] = field(default_factory=dict)
    # every instance attribute this class assigns anywhere (`self.x =`)
    # — the owner-resolution universe for R015's per-attribute locksets
    attrs: Set[str] = field(default_factory=set)
    # attr -> (declared guard expression, declaration line) from
    # `# tpulint: guarded_by(...)`
    guards: Dict[str, Tuple[str, int]] = field(default_factory=dict)


class ModuleRecord:
    """One analyzed file: tree, suppressions, imports, symbols, classes."""

    def __init__(self, relpath: str, source: str):
        from tools.tpulint import rules as _rules

        self.path = relpath.replace(os.sep, "/")
        self.modname = module_name_for(self.path)
        self.source = source
        self.tree = ast.parse(source, filename=self.path)
        self.lines = source.splitlines()
        self.supp = Suppressions(source)
        self.info = _rules._ModuleInfo(self.tree)
        self.symbols: Dict[str, FnSymbol] = {}
        self.classes: Dict[str, ClassRec] = {}
        # local name -> ('module', modname) | ('symbol', modname, name)
        self.imports: Dict[str, Tuple] = {}
        # module-level shared objects
        self.mod_locks: Set[str] = set()
        self.mod_conds: Set[str] = set()
        self.mod_events: Set[str] = set()
        self.mod_queues: Set[str] = set()
        # module-level singletons (`RESIDENCY = ResidencyRegistry()`):
        # name -> ctor chain, for `resources.RESIDENCY.track(...)` reach
        self.mod_obj_types: Dict[str, str] = {}
        # line -> guard expression from `# tpulint: guarded_by(...)`
        # (associated with the self.<attr> assignment on that line by
        # the symbol collector). A standalone comment covers the first
        # code line below it — the Suppressions block convention.
        self.guard_lines: Dict[int, str] = {}
        for i, text in enumerate(self.lines, start=1):
            m = _GUARDED_BY_RE.search(text)
            if not m:
                continue
            self.guard_lines.setdefault(i, m.group(1))
            if text.lstrip().startswith("#"):
                j = i + 1
                while j <= len(self.lines) and (
                        self.lines[j - 1].lstrip().startswith("#")
                        or not self.lines[j - 1].strip()):
                    j += 1
                self.guard_lines.setdefault(j, m.group(1))


def _ctor_kind(call: ast.Call) -> Optional[str]:
    """'lock'/'cond'/'event'/'queue' for threading/queue constructors."""
    chain = _attr_chain(call.func) or ""
    tail = chain.rpartition(".")[2]
    if chain.endswith(_LOCK_SUFFIXES) or tail in ("Lock", "RLock"):
        return "lock"
    if tail == "Condition":
        return "cond"
    if tail == "Event":
        return "event"
    if tail in _QUEUE_NAMES:
        return "queue"
    return None


class ProjectIndex:
    """The whole-program analysis result pass 2 consumes."""

    def __init__(self, records: List[ModuleRecord], module_set: Set[str]):
        self.records = {r.modname: r for r in records}
        self.by_path = {r.path: r for r in records}
        self.module_set = module_set
        self.symbols: Dict[str, FnSymbol] = {}
        for r in records:
            for s in r.symbols.values():
                self.symbols[s.sid] = s
        # filled by analyze():
        self.traced: Dict[str, Set[str]] = {}       # sid -> traced params
        self.collective: Set[str] = set()
        self.lock_edges: Dict[Tuple[str, str], Tuple[str, int]] = {}
        self.lock_cycles: List[List[str]] = []
        self.wait_violations: List[Tuple[str, int, str]] = []  # path,line,msg
        # R015/R016 (filled by the concurrency pass):
        self.concurrent: Set[str] = set()           # sids in thread reach
        self.held_on_entry: Dict[str, FrozenSet[str]] = {}
        # attr identity 'mod:Cls.attr' -> (guard id, declared?,
        #                                  guarded count, unguarded count)
        self.attr_guards: Dict[str, Tuple[str, bool, int, int]] = {}
        self.race_violations: List[Tuple[str, str, int, str]] = []
        #                       (rule, path, line, msg)

    # -- views keyed the way pass 2 wants them ------------------------------

    def traced_for_module(self, modname: str) -> Dict[str, Set[str]]:
        out: Dict[str, Set[str]] = {}
        prefix = modname + ":"
        for sid, params in self.traced.items():
            if sid.startswith(prefix):
                out[sid[len(prefix):]] = params
        return out

    def collective_for_module(self, modname: str) -> Set[str]:
        prefix = modname + ":"
        return {sid[len(prefix):] for sid in self.collective
                if sid.startswith(prefix)}


# ---------------------------------------------------------------------------
# pass 1a: symbols, classes, imports
# ---------------------------------------------------------------------------

class _SymbolCollector(ast.NodeVisitor):
    def __init__(self, rec: ModuleRecord):
        self.rec = rec
        self.stack: List[Tuple[str, str]] = []  # ('class'|'fn', name)

    def _qual(self, name: str) -> str:
        return ".".join([n for _k, n in self.stack] + [name])

    def _cls(self) -> Optional[str]:
        for kind, name in reversed(self.stack):
            if kind == "class":
                return name
        return None

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        rec = ClassRec(node.name,
                       [c for c in (_attr_chain(b) for b in node.bases) if c])
        for sub in ast.walk(node):
            if isinstance(sub, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
                targets = (sub.targets if isinstance(sub, ast.Assign)
                           else [sub.target])
                for t in targets:
                    chain = _attr_chain(t) or ""
                    if chain.startswith("self.") and "." not in chain[5:]:
                        rec.attrs.add(chain[5:])
                        lineno = getattr(sub, "lineno", 0)
                        guard = self.rec.guard_lines.get(lineno)
                        if guard:
                            rec.guards.setdefault(chain[5:],
                                                  (guard, lineno))
            if isinstance(sub, ast.Assign) and len(sub.targets) == 1 and \
                    isinstance(sub.value, ast.Call):
                chain = _attr_chain(sub.targets[0]) or ""
                if chain.startswith("self.") and "." not in chain[5:]:
                    kind = _ctor_kind(sub.value)
                    if kind:
                        getattr(rec, kind + "s").add(chain[5:])
                    else:
                        ctor = _attr_chain(sub.value.func)
                        tail = (ctor or "").rpartition(".")[2]
                        # constructor-shaped (CapWord) calls only — a
                        # helper-call assignment is not a type witness
                        if ctor and tail[:1].isupper():
                            rec.attr_types.setdefault(chain[5:], ctor)
        # first definition wins (shadowed re-defs are rare and benign)
        self.rec.classes.setdefault(node.name, rec)
        self.stack.append(("class", node.name))
        self.generic_visit(node)
        self.stack.pop()

    def _visit_fn(self, node) -> None:
        qual = self._qual(node.name)
        if qual not in self.rec.symbols:
            sym = FnSymbol(sid=f"{self.rec.modname}:{qual}",
                           module=self.rec.modname, qual=qual, node=node,
                           cls=self._cls(), params=_fn_params(node))
            statics = self.rec.info.decorator_jit(node)
            if statics is not None:
                sym.is_root, sym.statics = True, set(statics)
            elif node.name in self.rec.info.wrapped_fns:
                sym.is_root = True
            self.rec.symbols[qual] = sym
        self.stack.append(("fn", node.name))
        self.generic_visit(node)
        self.stack.pop()

    visit_FunctionDef = _visit_fn
    visit_AsyncFunctionDef = _visit_fn


def _collect_imports(rec: ModuleRecord, module_set: Set[str]) -> None:
    """All imports anywhere in the tree (this codebase imports inside
    functions heavily); function-local bindings are treated module-wide,
    an over-approximation that only ever *adds* resolvable edges."""
    pkg = rec.modname.rpartition(".")[0]
    for node in ast.walk(rec.tree):
        if isinstance(node, ast.Import):
            for al in node.names:
                bound = al.asname or al.name.split(".")[0]
                target = al.name if al.asname else al.name.split(".")[0]
                rec.imports.setdefault(bound, ("module", target))
        elif isinstance(node, ast.ImportFrom):
            base = node.module or ""
            if node.level:
                parts = rec.modname.split(".")
                # from . / .. : drop (level) tail components (the module
                # itself counts as one for non-package modules)
                keep = len(parts) - node.level
                if rec.path.endswith("__init__.py"):
                    keep += 1
                base_parts = parts[:max(keep, 0)]
                base = ".".join(base_parts + ([node.module]
                                              if node.module else []))
            for al in node.names:
                bound = al.asname or al.name
                full = f"{base}.{al.name}" if base else al.name
                if full in module_set:
                    rec.imports.setdefault(bound, ("module", full))
                else:
                    rec.imports.setdefault(bound, ("symbol", base, al.name))
    # module-level shared-object registry
    for stmt in rec.tree.body:
        if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 and \
                isinstance(stmt.value, ast.Call):
            tgt = _name(stmt.targets[0])
            kind = _ctor_kind(stmt.value)
            if tgt and kind:
                {"lock": rec.mod_locks, "cond": rec.mod_conds,
                 "event": rec.mod_events, "queue": rec.mod_queues}[kind].add(tgt)
            elif tgt:
                ctor = _attr_chain(stmt.value.func)
                tail = (ctor or "").rpartition(".")[2]
                if ctor and tail[:1].isupper():
                    rec.mod_obj_types.setdefault(tgt, ctor)


# ---------------------------------------------------------------------------
# pass 1b: per-symbol body walk (edges + lock regions + waits)
# ---------------------------------------------------------------------------

class _Resolver:
    def __init__(self, index: "ProjectIndex", rec: ModuleRecord):
        self.index = index
        self.rec = rec

    def _module_symbol(self, modname: str, qual: str) -> Optional[str]:
        mod = self.index.records.get(modname)
        if mod is None:
            return None
        if qual in mod.symbols:
            return f"{modname}:{qual}"
        # Class -> its constructor
        if qual in mod.classes and f"{qual}.__init__" in mod.symbols:
            return f"{modname}:{qual}.__init__"
        # module singleton: RESIDENCY.device_put -> ResidencyRegistry...
        head, _, meth = qual.partition(".")
        if meth and "." not in meth and head in mod.mod_obj_types:
            tgt = self.resolve_ctor(mod, mod.mod_obj_types[head])
            if tgt is not None:
                return self.resolve_method(tgt[0], tgt[1], meth)
        return None

    def resolve_ctor(self, rec: ModuleRecord,
                     ctor: str) -> Optional[Tuple[ModuleRecord, str]]:
        """Constructor chain -> (record, class) defining the type."""
        if "." not in ctor:
            if ctor in rec.classes:
                return (rec, ctor)
            bound = rec.imports.get(ctor)
            if bound and bound[0] == "symbol":
                trec = self.index.records.get(bound[1])
                if trec is not None and bound[2] in trec.classes:
                    return (trec, bound[2])
            return None
        root, _, rest = ctor.partition(".")
        bound = rec.imports.get(root)
        if bound and bound[0] == "module":
            full = bound[1].split(".") + rest.split(".")
            for i in range(len(full) - 1, 0, -1):
                trec = self.index.records.get(".".join(full[:i]))
                if trec is not None:
                    qual = ".".join(full[i:])
                    if "." not in qual and qual in trec.classes:
                        return (trec, qual)
        return None

    def resolve_method(self, rec: ModuleRecord, cls: str,
                       meth: str) -> Optional[str]:
        """<cls>.<meth> in ``rec``, walking project-resolvable bases."""
        seen: Set[Tuple[str, str]] = set()
        frontier = [(rec, cls)]
        for _ in range(8):
            nxt = []
            for r, c in frontier:
                if (r.modname, c) in seen:
                    continue
                seen.add((r.modname, c))
                qual = f"{c}.{meth}"
                if qual in r.symbols:
                    return r.symbols[qual].sid
                crec = r.classes.get(c)
                if crec is None:
                    continue
                for b in crec.bases:
                    tgt = self.resolve_ctor(r, b)
                    if tgt is not None:
                        nxt.append(tgt)
            if not nxt:
                break
            frontier = nxt
        return None

    def attr_type_of(self, rec: ModuleRecord, cls: Optional[str],
                     attr: str) -> Optional[Tuple[ModuleRecord, str]]:
        """Type of self.<attr> from constructor assignments, walking
        project-resolvable bases; ctor resolved against the DEFINING
        class's module imports."""
        seen: Set[Tuple[str, str]] = set()
        frontier = [(rec, cls)]
        for _ in range(8):
            nxt = []
            for r, c in frontier:
                if c is None or (r.modname, c) in seen:
                    continue
                seen.add((r.modname, c))
                crec = r.classes.get(c)
                if crec is None:
                    continue
                if attr in crec.attr_types:
                    return self.resolve_ctor(r, crec.attr_types[attr])
                for b in crec.bases:
                    tgt = self.resolve_ctor(r, b)
                    if tgt is not None:
                        nxt.append(tgt)
            if not nxt:
                break
            frontier = nxt
        return None

    def guard_id(self, cls_name: Optional[str],
                 chain: Optional[str]) -> Optional[str]:
        """Guard id for an expression chain: a known lock OR Condition
        (``with self._cv:`` acquires the condition's lock, so it guards
        state exactly like a bare lock for R015/R016 lockset purposes).
        Same id namespace as the R013 lock ids."""
        if not chain:
            return None
        if chain.startswith("self.") and "." not in chain[5:]:
            attr = chain[5:]
            for kind in ("locks", "conds"):
                owner = self.owner_class_of_attr(cls_name, kind, attr)
                if owner is not None:
                    return f"{owner[0]}:{owner[1]}.{attr}"
            return None
        parts = chain.split(".")
        if len(parts) == 1:
            if chain in self.rec.mod_locks or chain in self.rec.mod_conds:
                return f"{self.rec.modname}:{chain}"
            bound = self.rec.imports.get(chain)
            if bound and bound[0] == "symbol":
                target = self.index.records.get(bound[1])
                if target is not None and (bound[2] in target.mod_locks
                                           or bound[2] in target.mod_conds):
                    return f"{target.modname}:{bound[2]}"
            return None
        bound = self.rec.imports.get(parts[0])
        if bound and bound[0] == "module":
            full = bound[1].split(".") + parts[1:]
            mod, name = ".".join(full[:-1]), full[-1]
            target = self.index.records.get(mod)
            if target is not None and (name in target.mod_locks
                                       or name in target.mod_conds):
                return f"{target.modname}:{name}"
        return None

    def resolve_chain(self, chain: str) -> Optional[str]:
        """'alias.sub.fn' -> sid, via the module's import bindings."""
        parts = chain.split(".")
        bound = self.rec.imports.get(parts[0])
        if bound is None:
            # this module's own singleton: RESIDENCY.track(...)
            if len(parts) == 2 and parts[0] in self.rec.mod_obj_types:
                tgt = self.resolve_ctor(self.rec,
                                        self.rec.mod_obj_types[parts[0]])
                if tgt is not None:
                    return self.resolve_method(tgt[0], tgt[1], parts[1])
            return None
        if bound[0] == "module":
            full = bound[1].split(".") + parts[1:]
            # longest prefix that is an analyzed module; remainder is the
            # symbol path inside it
            for i in range(len(full) - 1, 0, -1):
                mod = ".".join(full[:i])
                if mod in self.index.module_set:
                    return self._module_symbol(mod, ".".join(full[i:]))
            return None
        _k, base, name = bound
        return self._module_symbol(base, ".".join([name] + parts[1:]))

    def resolve_self_attr(self, cls_name: Optional[str],
                          attr: str) -> Optional[str]:
        """self.<attr> within ``cls_name``, walking project-resolvable
        base classes (depth-limited)."""
        seen: Set[Tuple[str, str]] = set()
        frontier = [(self.rec, cls_name)]
        for _ in range(8):
            nxt = []
            for rec, cname in frontier:
                if cname is None or (rec.modname, cname) in seen:
                    continue
                seen.add((rec.modname, cname))
                qual = f"{cname}.{attr}"
                if qual in rec.symbols:
                    return rec.symbols[qual].sid
                crec = rec.classes.get(cname)
                if crec is None:
                    continue
                for b in crec.bases:
                    if b in rec.classes:
                        nxt.append((rec, b))
                        continue
                    sid = None if "." in b else None
                    bound = rec.imports.get(b.split(".")[0])
                    if bound and bound[0] == "symbol":
                        brec = self.index.records.get(bound[1])
                        if brec is not None:
                            nxt.append((brec, bound[2]))
                    del sid
            if not nxt:
                break
            frontier = nxt
        return None

    def resolve_attr_objects(self, cls_name: Optional[str], attr_kind: str,
                             attr: str) -> bool:
        """Is self.<attr> a known lock/cond/event/queue of cls (or a
        project-resolvable base)?"""
        frontier = [(self.rec, cls_name)]
        seen: Set[Tuple[str, str]] = set()
        for _ in range(8):
            nxt = []
            for rec, cname in frontier:
                if cname is None or (rec.modname, cname) in seen:
                    continue
                seen.add((rec.modname, cname))
                crec = rec.classes.get(cname)
                if crec is None:
                    continue
                if attr in getattr(crec, attr_kind):
                    return True
                for b in crec.bases:
                    if b in rec.classes:
                        nxt.append((rec, b))
                    else:
                        bound = rec.imports.get(b.split(".")[0])
                        if bound and bound[0] == "symbol":
                            brec = self.index.records.get(bound[1])
                            if brec is not None:
                                nxt.append((brec, bound[2]))
            if not nxt:
                return False
            frontier = nxt
        return False

    def owner_class_of_attr(self, cls_name: Optional[str], attr_kind: str,
                            attr: str) -> Optional[Tuple[str, str]]:
        """(modname, class) defining self.<attr>, for stable lock ids."""
        frontier = [(self.rec, cls_name)]
        seen: Set[Tuple[str, str]] = set()
        for _ in range(8):
            nxt = []
            for rec, cname in frontier:
                if cname is None or (rec.modname, cname) in seen:
                    continue
                seen.add((rec.modname, cname))
                crec = rec.classes.get(cname)
                if crec is None:
                    continue
                if attr in getattr(crec, attr_kind):
                    return (rec.modname, cname)
                for b in crec.bases:
                    if b in rec.classes:
                        nxt.append((rec, b))
                    else:
                        bound = rec.imports.get(b.split(".")[0])
                        if bound and bound[0] == "symbol":
                            brec = self.index.records.get(bound[1])
                            if brec is not None:
                                nxt.append((brec, bound[2]))
            if not nxt:
                return None
            frontier = nxt
        return None


def _iter_own_body(node):
    """Statements of a function body, NOT descending into nested defs
    (their bodies belong to their own symbols)."""
    work = list(node.body)
    while work:
        stmt = work.pop()
        yield stmt
        for sub in ast.iter_child_nodes(stmt):
            if not isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                work.append(sub)


def _expr_static(expr: ast.AST, nonstatic: Set[str],
                 jnp_aliases: Set[str]) -> bool:
    """Is this expression a trace-time constant? Free names outside
    ``nonstatic`` are closure/global constants (the program-factory
    idiom: config closed over by the traced body); ``.shape``/``.dtype``
    /``.ndim`` and ``len()`` of ANYTHING are static under trace; jnp-
    rooted calls produce device values and are never static."""
    if isinstance(expr, ast.Constant):
        return True
    if isinstance(expr, ast.Name):
        return expr.id not in nonstatic
    if isinstance(expr, ast.Attribute):
        if expr.attr in ("shape", "ndim", "dtype", "size"):
            return True
        return _expr_static(expr.value, nonstatic, jnp_aliases)
    if isinstance(expr, ast.Subscript):
        return _expr_static(expr.value, nonstatic, jnp_aliases) and \
            _expr_static(expr.slice, nonstatic, jnp_aliases)
    if isinstance(expr, (ast.Tuple, ast.List, ast.Set)):
        return all(_expr_static(e, nonstatic, jnp_aliases)
                   for e in expr.elts)
    if isinstance(expr, ast.BinOp):
        return _expr_static(expr.left, nonstatic, jnp_aliases) and \
            _expr_static(expr.right, nonstatic, jnp_aliases)
    if isinstance(expr, ast.UnaryOp):
        return _expr_static(expr.operand, nonstatic, jnp_aliases)
    if isinstance(expr, ast.BoolOp):
        return all(_expr_static(v, nonstatic, jnp_aliases)
                   for v in expr.values)
    if isinstance(expr, ast.Compare):
        return _expr_static(expr.left, nonstatic, jnp_aliases) and \
            all(_expr_static(c, nonstatic, jnp_aliases)
                for c in expr.comparators)
    if isinstance(expr, ast.IfExp):
        return all(_expr_static(e, nonstatic, jnp_aliases)
                   for e in (expr.test, expr.body, expr.orelse))
    if isinstance(expr, ast.Slice):
        return all(e is None or _expr_static(e, nonstatic, jnp_aliases)
                   for e in (expr.lower, expr.upper, expr.step))
    if isinstance(expr, ast.Call):
        chain = _attr_chain(expr.func) or ""
        root = chain.split(".")[0]
        if root in jnp_aliases:
            return False  # device-value producer
        if _name(expr.func) == "len":
            return True   # static under trace regardless of operand
        # the callee expression itself must be static too: x.sum() is a
        # method of a traced value, not a closure helper
        return _expr_static(expr.func, nonstatic, jnp_aliases) and \
            all(_expr_static(a, nonstatic, jnp_aliases)
                for a in expr.args) and \
            all(_expr_static(kw.value, nonstatic, jnp_aliases)
                for kw in expr.keywords)
    return False


def _nonstatic_locals(rec: ModuleRecord, sym: FnSymbol) -> Set[str]:
    """Names of ``sym`` that may hold trace-dependent (device) values:
    parameters, loop/with/except/lambda bindings, and assignments whose
    RHS isn't provably static. Everything else — closure constants and
    statically-derived locals (``kp = min(4 * k, D)`` over closure ints,
    ``S = av.shape[0]``) — classifies as static at call sites, so
    config threaded through helper calls doesn't false-trace R004."""
    jnp = rec.info.jnp
    nonstatic: Set[str] = set(sym.params)
    assigns: List[Tuple[Set[str], ast.AST]] = []

    def _targets(t, out: Set[str]) -> None:
        if isinstance(t, ast.Name):
            out.add(t.id)
        elif isinstance(t, (ast.Tuple, ast.List)):
            for e in t.elts:
                _targets(e, out)
        elif isinstance(t, ast.Starred):
            _targets(t.value, out)

    for stmt in _iter_own_body(sym.node):
        if isinstance(stmt, ast.Assign):
            names: Set[str] = set()
            for t in stmt.targets:
                _targets(t, names)
            assigns.append((names, stmt.value))
        elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
            names = set()
            _targets(stmt.target, names)
            assigns.append((names, stmt.value))
        elif isinstance(stmt, ast.AugAssign):
            names = set()
            _targets(stmt.target, names)
            assigns.append((names, stmt.value))
        elif isinstance(stmt, (ast.For, ast.AsyncFor)):
            _targets(stmt.target, nonstatic)
        elif isinstance(stmt, (ast.With, ast.AsyncWith)):
            for item in stmt.items:
                if item.optional_vars is not None:
                    _targets(item.optional_vars, nonstatic)
        elif isinstance(stmt, ast.ExceptHandler) and stmt.name:
            nonstatic.add(stmt.name)
        elif isinstance(stmt, ast.Lambda):
            nonstatic.update(_fn_params(stmt))
        elif isinstance(stmt, (ast.ListComp, ast.SetComp, ast.DictComp,
                               ast.GeneratorExp)):
            for gen in stmt.generators:
                _targets(gen.target, nonstatic)
        elif isinstance(stmt, (ast.NamedExpr,)):
            _targets(stmt.target, nonstatic)
    # demotion fixpoint: an assigned name goes nonstatic when ANY of its
    # bindings references something nonstatic (or a jnp producer)
    changed = True
    while changed:
        changed = False
        for names, rhs in assigns:
            if names <= nonstatic:
                continue
            if not _expr_static(rhs, nonstatic, jnp):
                before = len(nonstatic)
                nonstatic |= names
                changed = changed or len(nonstatic) != before
    return nonstatic


class _BodyWalker(ast.NodeVisitor):
    """One pass over a single function body (nested defs excluded — they
    are their own symbols, linked by a REF edge)."""

    def __init__(self, rec: ModuleRecord, sym: FnSymbol, res: _Resolver):
        self.rec = rec
        self.sym = sym
        self.res = res
        self.held: List[str] = []
        # guard stack for R015/R016: locks AND condition locks (R013's
        # `held` stays locks-only so the lock graph is unchanged)
        self.gheld: List[str] = []
        # guard -> release count: bumps when the guard is FULLY released,
        # so accesses in different epochs straddle a release window
        self.epoch: Dict[str, int] = {}
        self._sync_memo: Dict[str, bool] = {}
        self.aliases: Dict[str, str] = {}   # local name -> sid
        self.nonstatic: Set[str] = _nonstatic_locals(rec, sym)

    # -- resolution ----------------------------------------------------------

    def _resolve_callable(self, expr: ast.AST) -> Optional[str]:
        nm = _name(expr)
        if nm is not None:
            if nm in self.aliases:
                return self.aliases[nm]
            # nested siblings / enclosing-scope defs: try successively
            # shorter prefixes of this symbol's qual
            parts = self.sym.qual.split(".")
            for i in range(len(parts), -1, -1):
                qual = ".".join(parts[:i] + [nm])
                if qual in self.rec.symbols:
                    return self.rec.symbols[qual].sid
            if nm in self.rec.classes and \
                    f"{nm}.__init__" in self.rec.symbols:
                return self.rec.symbols[f"{nm}.__init__"].sid
            if nm in self.rec.imports:
                return self.res.resolve_chain(nm)
            return None
        chain = _attr_chain(expr)
        if not chain:
            return None
        root, _, rest = chain.partition(".")
        if root in ("self", "cls") and self.sym.cls and rest:
            parts = rest.split(".")
            if len(parts) == 1:
                return self.res.resolve_self_attr(self.sym.cls, rest)
            if len(parts) == 2:
                # self.<attr>.<method> via constructor type inference
                tinfo = self.res.attr_type_of(self.rec, self.sym.cls,
                                              parts[0])
                if tinfo is not None:
                    return self.res.resolve_method(tinfo[0], tinfo[1],
                                                   parts[1])
            return None
        # ClassName.method within this module
        if root in self.rec.classes and rest and "." not in rest:
            qual = f"{root}.{rest}"
            if qual in self.rec.symbols:
                return self.rec.symbols[qual].sid
        return self.res.resolve_chain(chain)

    def _lock_id(self, expr: ast.AST) -> Optional[str]:
        chain = _attr_chain(expr)
        if not chain:
            return None
        if chain.startswith("self.") and "." not in chain[5:]:
            attr = chain[5:]
            owner = self.res.owner_class_of_attr(self.sym.cls, "locks", attr)
            if owner is not None:
                return f"{owner[0]}:{owner[1]}.{attr}"
            return None
        parts = chain.split(".")
        if len(parts) == 1:
            if chain in self.rec.mod_locks:
                return f"{self.rec.modname}:{chain}"
            bound = self.rec.imports.get(chain)
            if bound and bound[0] == "symbol":  # from mod import LOCK
                target = self.res.index.records.get(bound[1])
                if target is not None and bound[2] in target.mod_locks:
                    return f"{target.modname}:{bound[2]}"
            return None
        # imported module-level lock: mod.LOCK / pkg.sub.LOCK
        bound = self.rec.imports.get(parts[0])
        if bound and bound[0] == "module":
            full = bound[1].split(".") + parts[1:]
            mod, name = ".".join(full[:-1]), full[-1]
            target = self.res.index.records.get(mod)
            if target is not None and name in target.mod_locks:
                return f"{target.modname}:{name}"
        return None

    def _guard_id(self, expr: ast.AST) -> Optional[str]:
        return self.res.guard_id(self.sym.cls, _attr_chain(expr))

    # -- R015/R016 attribute-access recording --------------------------------

    def _is_sync_attr(self, attr: str) -> bool:
        """self.<attr> is itself a lock/cond/event/queue (a
        synchronization object, not guarded data) or a method of the
        class (a code reference, not mutable state)."""
        cached = self._sync_memo.get(attr)
        if cached is None:
            cached = any(
                self.res.resolve_attr_objects(self.sym.cls, k, attr)
                for k in ("locks", "conds", "events", "queues")) or \
                self.res.resolve_self_attr(self.sym.cls, attr) is not None
            self._sync_memo[attr] = cached
        return cached

    def _record_access(self, attr: str, kind: str, line: int) -> None:
        if self.sym.cls is None or self._is_sync_attr(attr):
            return
        gheld = tuple(dict.fromkeys(self.gheld))
        epochs = tuple((g, self.epoch.get(g, 0)) for g in gheld)
        self.sym.attr_accesses.append(
            AttrAccess(attr, kind, line, gheld, epochs))

    @staticmethod
    def _self_attr_base(t: ast.AST) -> Optional[str]:
        """X for ``self.X`` / ``self.X[...]`` / ``self.X.y`` chains —
        the attribute whose object a store/mutator call touches."""
        base = t
        while isinstance(base, (ast.Subscript, ast.Attribute)):
            if isinstance(base, ast.Attribute) and \
                    _name(base.value) == "self":
                return base.attr
            base = base.value
        return None

    def _record_targets(self, t: ast.AST) -> None:
        if isinstance(t, (ast.Tuple, ast.List)):
            for e in t.elts:
                self._record_targets(e)
            return
        if isinstance(t, ast.Starred):
            self._record_targets(t.value)
            return
        attr = self._self_attr_base(t)
        if attr is not None:
            self._record_access(attr, "w", getattr(t, "lineno", 0))
        # subscript indices are reads (`self.d[self.k] = v` reads self.k)
        node = t
        while isinstance(node, (ast.Subscript, ast.Attribute)):
            if isinstance(node, ast.Subscript):
                self.visit(node.slice)
            node = node.value

    def visit_Attribute(self, node: ast.Attribute) -> None:
        if isinstance(node.value, ast.Name) and node.value.id == "self" \
                and isinstance(node.ctx, ast.Load):
            self._record_access(node.attr, "r", node.lineno)
        self.generic_visit(node)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        self._record_targets(node.target)
        self.visit(node.value)

    def visit_AnnAssign(self, node: ast.AnnAssign) -> None:
        self._record_targets(node.target)
        if node.value is not None:
            self.visit(node.value)

    def visit_Delete(self, node: ast.Delete) -> None:
        for t in node.targets:
            attr = self._self_attr_base(t)
            if attr is not None:
                self._record_access(attr, "w", node.lineno)
        self.generic_visit(node)

    def _is_known(self, expr: ast.AST, kind: str) -> bool:
        """Receiver resolves to a known event/queue/cond object."""
        chain = _attr_chain(expr)
        if not chain:
            return False
        if chain.startswith("self.") and "." not in chain[5:]:
            return self.res.resolve_attr_objects(self.sym.cls, kind,
                                                 chain[5:])
        if "." not in chain:
            return chain in {"events": self.rec.mod_events,
                             "queues": self.rec.mod_queues,
                             "conds": self.rec.mod_conds}[kind]
        return False

    # -- structure -----------------------------------------------------------

    def _skip_nested(self, node) -> None:
        qual = f"{self.sym.qual}.{node.name}"
        nested = self.rec.symbols.get(qual)
        if nested is not None:
            self.sym.edges.append(CallEdge(nested.sid, "ref",
                                           getattr(node, "lineno", 0)))
        # body handled when the nested symbol itself is walked

    visit_FunctionDef = _skip_nested
    visit_AsyncFunctionDef = _skip_nested

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        return  # function-local classes: out of scope

    def visit_Assign(self, node: ast.Assign) -> None:
        if len(node.targets) == 1:
            tgt = _name(node.targets[0])
            if tgt:
                sid = None
                if isinstance(node.value, (ast.Name, ast.Attribute)):
                    sid = self._resolve_callable(node.value)
                if sid is not None:
                    self.aliases[tgt] = sid
                else:
                    self.aliases.pop(tgt, None)
        for t in node.targets:
            self._record_targets(t)
        self.visit(node.value)

    def visit_With(self, node: ast.With) -> None:
        ids = []
        gids = []
        for item in node.items:
            self.visit(item.context_expr)
            lid = self._lock_id(item.context_expr)
            if lid is not None:
                for h in self.held:
                    if h != lid:
                        self.sym.lock_edges.append((h, lid, node.lineno))
                self.sym.acquires.append((lid, node.lineno))
                self.held.append(lid)
                ids.append(lid)
            gid = self._guard_id(item.context_expr)
            if gid is not None and gid not in self.gheld:
                self.gheld.append(gid)
                gids.append(gid)
        for stmt in node.body:
            self.visit(stmt)
        for _ in ids:
            self.held.pop()
        for gid in reversed(gids):
            self.gheld.remove(gid)
            if gid not in self.gheld:
                # fully released: later regions on this guard are a NEW
                # epoch — an R016 window opens here
                self.epoch[gid] = self.epoch.get(gid, 0) + 1

    visit_AsyncWith = visit_With

    # -- calls ---------------------------------------------------------------

    def _classify_arg(self, expr: ast.AST):
        nm = _name(expr)
        if nm is not None and nm in self.sym.params:
            return ("param", nm)
        if _expr_static(expr, self.nonstatic, self.rec.info.jnp):
            return "const"
        return "dyn"

    def _map_args(self, call: ast.Call, callee: FnSymbol,
                  drop_self: bool) -> Tuple[List[Tuple[str, object]], bool]:
        cparams = [p for p in _fn_params(callee.node, include_var=False)]
        if drop_self and cparams and cparams[0] in ("self", "cls"):
            cparams = cparams[1:]
        cnode = callee.node
        has_var = cnode.args.vararg is not None
        out: List[Tuple[str, object]] = []
        all_dyn = False
        for i, a in enumerate(call.args):
            if isinstance(a, ast.Starred):
                all_dyn = True
                continue
            if i < len(cparams):
                out.append((cparams[i], self._classify_arg(a)))
            elif has_var:
                out.append((cnode.args.vararg.arg, self._classify_arg(a)))
        for kw in call.keywords:
            if kw.arg is None:       # **kwargs splat
                all_dyn = True
            elif kw.arg in cparams:
                out.append((kw.arg, self._classify_arg(kw.value)))
            elif cnode.args.kwarg is not None:
                out.append((cnode.args.kwarg.arg,
                            self._classify_arg(kw.value)))
        return out, all_dyn

    def _wait_desc(self, node: ast.Call) -> Optional[str]:
        """Unbounded-blocking-wait shapes (R010's, receiver-verified):
        ``Event.wait()`` with no timeout, ``queue.get()`` blocking with
        no timeout. Condition.wait is excluded — it RELEASES the lock it
        holds."""
        f = node.func
        if not isinstance(f, ast.Attribute):
            return None
        if f.attr == "wait":
            if node.args or any(kw.arg == "timeout" for kw in node.keywords):
                return None
            if self._is_known(f.value, "events"):
                return "Event.wait()"
            return None
        if f.attr == "get":
            if not self._is_known(f.value, "queues"):
                return None
            if any(kw.arg == "timeout" for kw in node.keywords):
                return None
            if len(node.args) >= 2:
                return None
            blk = next((kw.value for kw in node.keywords
                        if kw.arg == "block"), None)
            if blk is not None and not (isinstance(blk, ast.Constant)
                                        and blk.value is True):
                return None
            if len(node.args) == 1 and not (
                    isinstance(node.args[0], ast.Constant)
                    and node.args[0].value is True):
                return None
            return "queue.get()"
        return None

    def _mark_thread_roots(self, node: ast.Call, base: str) -> None:
        """R015 concurrent reach: function-valued arguments at the
        thread-root spellings run on their own thread / a pool thread /
        a transport or cancel callback thread."""
        cands: List[ast.AST] = []
        if base == "Thread":
            tkw = next((kw.value for kw in node.keywords
                        if kw.arg == "target"), None)
            if tkw is not None:
                cands.append(tkw)
        elif base in _POOL_SUBMIT_NAMES and \
                isinstance(node.func, ast.Attribute):
            cands.extend(node.args)
        elif base in _REGISTER_NAMES and \
                isinstance(node.func, ast.Attribute):
            cands.extend(node.args)
            cands.extend(kw.value for kw in node.keywords)
        elif base == "add" and isinstance(node.func, ast.Attribute) \
                and node.args and isinstance(node.args[0], ast.Constant) \
                and node.args[0].value in _HTTP_METHODS:
            cands.extend(node.args[1:])
        for a in cands:
            if isinstance(a, (ast.Name, ast.Attribute)):
                asid = self._resolve_callable(a)
                if asid is not None and asid in self.res.index.symbols:
                    self.res.index.symbols[asid].is_thread_root = True

    def visit_Call(self, node: ast.Call) -> None:
        sid = self._resolve_callable(node.func)
        chain = _attr_chain(node.func) or ""
        base = chain.rpartition(".")[2]
        if sid is not None:
            callee = self.res.index.symbols.get(sid)
            if callee is not None:
                drop_self = isinstance(node.func, ast.Attribute) or \
                    sid.endswith(".__init__")
                args, all_dyn = self._map_args(node, callee, drop_self)
                self.sym.edges.append(CallEdge(
                    sid, "call", node.lineno, args, all_dyn,
                    tuple(self.held), tuple(dict.fromkeys(self.gheld))))
        # container-mutating method on self.<attr>: a WRITE of the attr
        # for lockset purposes (popitem/move_to_end/append/...)
        if isinstance(node.func, ast.Attribute) and \
                node.func.attr in _MUTATORS:
            mattr = self._self_attr_base(node.func.value)
            if mattr is not None:
                self._record_access(mattr, "m", node.lineno)
        self._mark_thread_roots(node, base)
        # wrapper-marked roots: function-valued args get traced/collective
        if base in TRACED_WRAPPER_NAMES:
            for a in list(node.args) + [kw.value for kw in node.keywords]:
                asid = None
                if isinstance(a, (ast.Name, ast.Attribute)):
                    asid = self._resolve_callable(a)
                if asid is not None and asid in self.res.index.symbols:
                    tgt = self.res.index.symbols[asid]
                    tgt.is_root = True
                    tgt.root_all_params = True
                    if base in COLLECTIVE_WRAPPER_NAMES:
                        tgt.is_collective_root = True
        if base in COLLECTIVE_OP_NAMES:
            self.sym.has_collective_call = True
        # .acquire() on a known lock: an acquire event (edge target) even
        # though no lexical held-region opens (release is untracked)
        if isinstance(node.func, ast.Attribute) and \
                node.func.attr == "acquire":
            lid = self._lock_id(node.func.value)
            if lid is not None:
                for h in self.held:
                    if h != lid:
                        self.sym.lock_edges.append((h, lid, node.lineno))
                self.sym.acquires.append((lid, node.lineno))
        desc = self._wait_desc(node)
        if desc is not None:
            self.sym.direct_waits.append((node.lineno, desc))
            if self.held:
                self.sym.waits_under.append((self.held[-1], node.lineno,
                                             desc))
        # function REFERENCES passed as arguments (vmap/partial/callbacks)
        for a in list(node.args) + [kw.value for kw in node.keywords]:
            if isinstance(a, (ast.Name, ast.Attribute)):
                asid = self._resolve_callable(a)
                if asid is not None:
                    self.sym.edges.append(CallEdge(asid, "ref", node.lineno))
        self.generic_visit(node)


# ---------------------------------------------------------------------------
# pass 1c: fixpoints
# ---------------------------------------------------------------------------

def _traced_fixpoint(index: ProjectIndex) -> None:
    traced = index.traced
    work: List[str] = []
    for sid, sym in index.symbols.items():
        if sym.is_root:
            params = set(sym.params)
            if not sym.root_all_params:
                params -= sym.statics
            traced[sid] = params
            work.append(sid)
    while work:
        sid = work.pop()
        sym = index.symbols.get(sid)
        if sym is None:
            continue
        cur = traced.get(sid, set())
        for e in sym.edges:
            callee = index.symbols.get(e.callee)
            if callee is None:
                continue
            if e.kind == "ref" or e.all_dyn:
                want = set(callee.params)
            else:
                want = set()
                for param, kind in e.args:
                    if kind == "const":
                        continue
                    if isinstance(kind, tuple):
                        if kind[1] in cur:
                            want.add(param)
                    else:
                        want.add(param)
            # params the callee's OWN jit binding declares static stay
            # static: under an outer trace the inner jit still requires
            # hashable Python statics there (passing a tracer is a
            # different error, raised loudly at runtime)
            want -= callee.statics
            prev = traced.get(e.callee)
            if prev is None:
                traced[e.callee] = want
                work.append(e.callee)
            elif not want <= prev:
                prev |= want
                work.append(e.callee)


def _collective_fixpoint(index: ProjectIndex) -> None:
    roots = {sid for sid, s in index.symbols.items()
             if s.is_collective_root
             or (s.has_collective_call and sid in index.traced)}
    seen = set(roots)
    work = list(roots)
    while work:
        sid = work.pop()
        sym = index.symbols.get(sid)
        if sym is None:
            continue
        for e in sym.edges:
            if e.callee in index.symbols and e.callee not in seen:
                seen.add(e.callee)
                work.append(e.callee)
    index.collective = seen


def _concurrent_fixpoint(index: ProjectIndex) -> None:
    """CONCURRENT-REACH: everything transitively reachable (call or ref
    edges) from a thread root runs on a non-main thread — or on a pool/
    handler thread concurrently with its siblings. This is the scope in
    which an unguarded write can actually race (R015/R016)."""
    roots = {sid for sid, s in index.symbols.items() if s.is_thread_root}
    seen = set(roots)
    work = list(roots)
    while work:
        sid = work.pop()
        sym = index.symbols.get(sid)
        if sym is None:
            continue
        for e in sym.edges:
            if e.callee in index.symbols and e.callee not in seen:
                seen.add(e.callee)
                work.append(e.callee)
    index.concurrent = seen


def _held_entry_fixpoint(index: ProjectIndex) -> None:
    """Guards held ON ENTRY to each function: the meet (intersection)
    over every call site of (caller's entry context ∪ guards lexically
    held at the call). This is the `_private helpers run caller-locked`
    convention made precise — a helper whose EVERY caller holds the lock
    counts as guarded; one unlocked call site and the guarantee is gone.
    Thread roots and ref-edge targets (callbacks — invocation context
    unknown) enter with nothing held."""
    incoming: Dict[str, List[Tuple[str, Tuple[str, ...], str]]] = {}
    for sid, sym in index.symbols.items():
        for e in sym.edges:
            if e.callee in index.symbols:
                incoming.setdefault(e.callee, []).append(
                    (sid, e.gheld, e.kind))
    # None = ⊤ (no call site resolved yet); sets only ever shrink
    H: Dict[str, Optional[FrozenSet[str]]] = {}
    for sid, sym in index.symbols.items():
        if sym.is_thread_root or sid not in incoming:
            H[sid] = frozenset()
        else:
            H[sid] = None
    changed = True
    while changed:
        changed = False
        for sid in index.symbols:
            cur = H[sid]
            if cur == frozenset():
                continue  # already at the lattice bottom
            contribs: List[FrozenSet[str]] = []
            for caller, gheld, kind in incoming.get(sid, ()):
                if kind == "ref":
                    contribs.append(frozenset())
                    continue
                hc = H.get(caller)
                if hc is None:
                    continue  # unknown caller: optimistic, re-met later
                contribs.append(hc | frozenset(gheld))
            if not contribs:
                continue
            new = frozenset.intersection(*contribs)
            if cur is not None:
                new &= cur
            if new != cur:
                H[sid] = new
                changed = True
    index.held_on_entry = {sid: (h if h is not None else frozenset())
                           for sid, h in H.items()}


_INIT_FNS = ("__init__", "__new__")


def _is_init_qual(qual: str) -> bool:
    return any(part in _INIT_FNS for part in qual.split("."))


def _race_analysis(index: ProjectIndex) -> None:
    """Eraser-style per-attribute lockset inference + the two findings:

    R015 — a concurrent, non-__init__ WRITE to an attribute whose guard
    (declared via ``# tpulint: guarded_by(...)``, or inferred as the
    lock held at the majority of the attribute's concurrent accesses,
    minimum two guarded sites) is not held at the write.

    R016 — within one concurrent function, a read-ONLY guarded region
    of the attribute followed by a later guarded write under the same
    lock with the lock released in between: check-then-act with a
    window a concurrent writer can slip through.

    __init__/__new__ accesses never count (the object has not been
    published yet — the init-before-publish precision rule), accesses
    outside concurrent reach never count (nothing to race with), and
    sync-object attributes were excluded at record time."""
    resolvers = {m: _Resolver(index, rec)
                 for m, rec in index.records.items()}
    H = index.held_on_entry
    conc = index.concurrent
    strength = {"r": 0, "w": 1, "m": 2}
    # site-level dedup: one record per (fn, attr, line), strongest kind
    # wins — the Attribute read under a same-line write/mutator is the
    # same access, not extra evidence
    sites: Dict[Tuple[str, str, int], Tuple[FnSymbol, AttrAccess]] = {}
    for sid, sym in index.symbols.items():
        if sym.cls is None:
            continue
        for acc in sym.attr_accesses:
            key = (sid, acc.attr, acc.line)
            prev = sites.get(key)
            if prev is None or strength[acc.kind] > strength[prev[1].kind]:
                sites[key] = (sym, acc)

    owner_memo: Dict[Tuple[str, Optional[str], str],
                     Tuple[str, str]] = {}

    def owner_of(sym: FnSymbol, attr: str) -> Tuple[str, str]:
        key = (sym.module, sym.cls, attr)
        got = owner_memo.get(key)
        if got is None:
            o = resolvers[sym.module].owner_class_of_attr(
                sym.cls, "attrs", attr)
            got = o if o is not None else (sym.module, sym.cls or "")
            owner_memo[key] = got
        return got

    # 1. group non-init accesses by attribute identity. Guard INFERENCE
    # counts evidence from every access (a lock discipline is a
    # discipline wherever it is exercised); the unguarded-majority
    # denominator and the R015/R016 findings only consider CONCURRENT
    # accesses — nothing races on a single-threaded path
    entries: Dict[Tuple[str, str, str],
                  List[Tuple[FnSymbol, AttrAccess, FrozenSet[str],
                             bool]]] = {}
    for (sid, _attr, _line), (sym, acc) in sites.items():
        if _is_init_qual(sym.qual):
            continue
        ident = owner_of(sym, acc.attr) + (acc.attr,)
        lockset = frozenset(H.get(sid, frozenset())) | frozenset(acc.gheld)
        entries.setdefault(ident, []).append(
            (sym, acc, lockset, sid in conc))

    # 2. per-attribute guard: declared beats inferred; inference wants a
    # majority discipline (>= 2 guarded sites, more guarded sites than
    # concurrent unguarded ones)
    guards: Dict[Tuple[str, str, str], Tuple[str, bool, int, int]] = {}
    for ident, rows in entries.items():
        omod, ocls, attr = ident
        declared = None
        orec = index.records.get(omod)
        crec = orec.classes.get(ocls) if orec is not None else None
        if crec is not None and attr in crec.guards:
            gexpr, gline = crec.guards[attr]
            declared = resolvers[omod].guard_id(ocls, gexpr)
            if declared is None:
                # a silent fall-through to inference would let a typo'd
                # declaration weaken the discipline the author believes
                # is gate-enforced — surface it where it is written
                index.race_violations.append((
                    "R015", orec.path, gline,
                    f"`# tpulint: guarded_by({gexpr})` on `self.{attr}` "
                    f"does not resolve to a known lock or Condition of "
                    f"`{ocls}` (typo? renamed lock? the guard must be a "
                    "`threading.Lock`/`RLock`/`Condition` assigned as "
                    "`self.<attr>` in this class or a module-level "
                    "lock) — fix the expression or remove the "
                    "annotation"))
        if declared is not None:
            held = sum(1 for _s, _a, ls, _c in rows if declared in ls)
            guards[ident] = (declared, True, held, len(rows) - held)
            continue
        counts: Dict[str, int] = {}
        for _s, _a, ls, _c in rows:
            for g in ls:
                counts[g] = counts.get(g, 0) + 1
        if not counts:
            continue
        best = max(sorted(counts), key=lambda g: counts[g])
        cnt = counts[best]
        unguarded = sum(1 for _s, _a, ls, c in rows
                        if c and best not in ls)
        if cnt >= 2 and cnt > unguarded:
            guards[ident] = (best, False, cnt, unguarded)
    index.attr_guards = {f"{m}:{c}.{a}": v
                         for (m, c, a), v in guards.items()}

    # 3. R015: concurrent writes without the guard
    out = index.race_violations
    for ident, (g, declared, cnt, uncnt) in sorted(guards.items()):
        omod, ocls, attr = ident
        for sym, acc, ls, is_conc in entries[ident]:
            if not is_conc or acc.kind not in ("w", "m") or g in ls:
                continue
            path = index.records[sym.module].path
            how = ("declared via `# tpulint: guarded_by(...)`" if declared
                   else f"held at {cnt} other access"
                        f"{'' if cnt == 1 else 'es'}")
            out.append((
                "R015", path, acc.line,
                f"write to `self.{attr}` (of `{omod}:{ocls}`) without its "
                f"guarding lock `{g}` ({how}) in thread-reachable code — "
                "a concurrent holder of the lock can interleave and the "
                "write is lost or torn; wrap it in `with <lock>:`, or "
                "justify with `# tpulint: allow[R015]` / declare a "
                "different discipline with `# tpulint: guarded_by(...)`"))

    # 4. R016: check-then-act across a release window, per function
    per_fn: Dict[Tuple[str, str], List[AttrAccess]] = {}
    for (sid, attr, _line), (sym, acc) in sites.items():
        if sid in conc and not _is_init_qual(sym.qual):
            per_fn.setdefault((sid, attr), []).append(acc)
    for (sid, attr), accs in sorted(per_fn.items()):
        sym = index.symbols[sid]
        ident = owner_of(sym, attr) + (attr,)
        ginfo = guards.get(ident)
        if ginfo is None:
            continue
        g = ginfo[0]
        reads: Dict[int, List[AttrAccess]] = {}
        writes: Dict[int, List[AttrAccess]] = {}
        for acc in accs:
            em = dict(acc.epochs)
            if g not in em:
                continue
            (reads if acc.kind == "r" else writes).setdefault(
                em[g], []).append(acc)
        for e1 in sorted(reads):
            if e1 in writes:
                continue  # check and act under ONE hold: atomic, legal
            later = []
            for e2 in writes:
                if e2 <= e1:
                    continue
                wline = min(a.line for a in writes[e2])
                # an act region that RE-READS the attribute under the
                # lock before writing is the re-validate idiom — only a
                # BLIND write acts on the stale check
                if any(a.line <= wline for a in reads.get(e2, ())):
                    continue
                later.append(e2)
            if not later:
                continue
            racc = min(reads[e1], key=lambda a: a.line)
            wacc = min(writes[min(later)], key=lambda a: a.line)
            path = index.records[sym.module].path
            out.append((
                "R016", path, wacc.line,
                f"`{g.rpartition(':')[2]}` is released between the "
                f"guarded check of `self.{attr}` (line {racc.line}) and "
                "this guarded act on it — the state can change in the "
                "window, so two threads both pass the check "
                "(check-then-act / get-or-create); hold the lock across "
                "both, or re-validate under the lock before acting "
                "(`# tpulint: allow[R016]` with a justification if the "
                "gap is intended)"))
            break


def _lock_analysis(index: ProjectIndex) -> None:
    # transitive acquires / waits per symbol (call edges only)
    acq: Dict[str, Set[str]] = {sid: {l for l, _ in s.acquires}
                                for sid, s in index.symbols.items()}
    waits: Dict[str, Optional[Tuple[str, str]]] = {
        sid: ((s.direct_waits[0][1], sid) if s.direct_waits else None)
        for sid, s in index.symbols.items()}
    changed = True
    while changed:
        changed = False
        for sid, sym in index.symbols.items():
            a = acq[sid]
            w = waits[sid]
            for e in sym.edges:
                if e.kind != "call" or e.callee not in acq:
                    continue
                extra = acq[e.callee] - a
                if extra:
                    a |= extra
                    changed = True
                if w is None and waits[e.callee] is not None:
                    waits[sid] = waits[e.callee]
                    changed = True
                    w = waits[sid]
    # global held -> acquired edges with witnesses
    edges = index.lock_edges
    for sid, sym in index.symbols.items():
        rec = index.records[sym.module]
        for h, l, line in sym.lock_edges:
            edges.setdefault((h, l), (rec.path, line))
        for e in sym.edges:
            if e.kind != "call" or not e.held:
                continue
            callee_acqs = acq.get(e.callee, ())
            for l in callee_acqs:
                for h in e.held:
                    if h != l:
                        edges.setdefault((h, l), (rec.path, e.line))
            # lock-held call into an unbounded blocking wait
            cw = waits.get(e.callee)
            if cw is not None:
                desc, where = cw
                index.wait_violations.append((
                    rec.path, e.line,
                    f"call into an unbounded blocking wait ({desc} "
                    f"reached via `{where}`) while holding "
                    f"`{e.held[-1]}` — a lost notify or a dead producer "
                    "wedges every thread queued behind this lock; bound "
                    "the wait (timeout=) or release the lock first"))
    # direct waits under a held lock (R010 owns serving/; R013 the rest)
    for sid, sym in index.symbols.items():
        rec = index.records[sym.module]
        if "/serving/" in "/" + rec.path:
            continue
        for h, line, desc in sym.waits_under:
            index.wait_violations.append((
                rec.path, line,
                f"unbounded {desc} while holding `{h}` — a lost notify "
                "wedges every thread queued behind this lock; bound the "
                "wait (timeout=) or park outside the lock"))
    # cycle detection (self-edges excluded: RLock re-entry is legal)
    graph: Dict[str, Set[str]] = {}
    for (h, l) in edges:
        graph.setdefault(h, set()).add(l)
        graph.setdefault(l, set())
    index.lock_cycles = _find_cycles(graph)


def _find_cycles(graph: Dict[str, Set[str]]) -> List[List[str]]:
    """Elementary cycles, one representative per cyclic SCC (Tarjan +
    one in-SCC walk) — enough for reporting; the gate needs zero."""
    idx: Dict[str, int] = {}
    low: Dict[str, int] = {}
    on: Set[str] = set()
    stack: List[str] = []
    sccs: List[List[str]] = []
    counter = [0]

    def strongconnect(v: str) -> None:
        idx[v] = low[v] = counter[0]
        counter[0] += 1
        stack.append(v)
        on.add(v)
        for w in graph.get(v, ()):
            if w not in idx:
                strongconnect(w)
                low[v] = min(low[v], low[w])
            elif w in on:
                low[v] = min(low[v], idx[w])
        if low[v] == idx[v]:
            comp = []
            while True:
                w = stack.pop()
                on.discard(w)
                comp.append(w)
                if w == v:
                    break
            if len(comp) > 1:
                sccs.append(comp)

    for v in sorted(graph):
        if v not in idx:
            strongconnect(v)
    cycles: List[List[str]] = []
    for comp in sccs:
        cset = set(comp)
        start = min(comp)
        # DFS within the SCC, tracking the current path: a cyclic SCC
        # always contains a back-edge to a path node, so this cannot
        # dead-end the way a greedy no-revisit walk could (a walk that
        # strays into a side branch of the SCC would report NOTHING for
        # a genuinely cyclic component — a silently passing gate)
        path: List[str] = [start]
        on_path = {start}
        iters = [iter(sorted(w for w in graph.get(start, ())
                             if w in cset))]
        visited = {start}
        found: List[str] = []
        while iters and not found:
            try:
                w = next(iters[-1])
            except StopIteration:
                iters.pop()
                on_path.discard(path.pop())
                continue
            if w in on_path:
                found = path[path.index(w):]
            elif w not in visited:
                visited.add(w)
                path.append(w)
                on_path.add(w)
                iters.append(iter(sorted(x for x in graph.get(w, ())
                                         if x in cset)))
        if found:
            cycles.append(found)
    return cycles


# ---------------------------------------------------------------------------
# entry points
# ---------------------------------------------------------------------------

def _relpath(path: str, root: Optional[str]) -> str:
    rel = path
    if root:
        ap, ar = os.path.abspath(path), os.path.abspath(root)
        if ap == ar or ap.startswith(ar + os.sep):
            rel = os.path.relpath(ap, ar)
    return rel.replace(os.sep, "/")


def build_project(paths: Sequence[str], root: Optional[str] = None,
                  overlay: Optional[Dict[str, str]] = None,
                  ) -> Tuple[ProjectIndex, List[Violation]]:
    """Pass 1 over real files. ``overlay`` maps root-relative paths to
    replacement sources (seeded-violation regression tests). Returns the
    index plus R000 syntax-error violations for unparseable files."""
    sources: Dict[str, str] = {}
    for f in iter_python_files(paths):
        rel = _relpath(f, root)
        if overlay and rel in overlay:
            sources[rel] = overlay[rel]
            continue
        with open(f, "r", encoding="utf-8") as fh:
            sources[rel] = fh.read()
    if overlay:
        for rel, src in overlay.items():
            sources.setdefault(rel, src)
    return analyze_sources(sources)


def analyze_sources(sources: Dict[str, str],
                    ) -> Tuple[ProjectIndex, List[Violation]]:
    """Pass 1 over in-memory sources {relpath: source} (fixture entry)."""
    records: List[ModuleRecord] = []
    errors: List[Violation] = []
    for rel in sorted(sources):
        try:
            records.append(ModuleRecord(rel, sources[rel]))
        except SyntaxError as e:
            errors.append(Violation("R000", rel.replace(os.sep, "/"),
                                    e.lineno or 0, e.offset or 0,
                                    f"syntax error: {e.msg}", ""))
    module_set = {r.modname for r in records}
    # packages exist as modules even without their __init__ in the set
    for r in records:
        parts = r.modname.split(".")
        for i in range(1, len(parts)):
            module_set.add(".".join(parts[:i]))
    index = ProjectIndex(records, module_set)
    for rec in records:
        _collect_imports(rec, module_set)
        _SymbolCollector(rec).visit(rec.tree)
    index.symbols = {}
    for rec in records:
        for s in rec.symbols.values():
            index.symbols[s.sid] = s
    for rec in records:
        res = _Resolver(index, rec)
        for s in rec.symbols.values():
            walker = _BodyWalker(rec, s, res)
            for stmt in s.node.body:
                walker.visit(stmt)
    _traced_fixpoint(index)
    _collective_fixpoint(index)
    _lock_analysis(index)
    _concurrent_fixpoint(index)
    _held_entry_fixpoint(index)
    _race_analysis(index)
    return index, errors


def _project_violations(index: ProjectIndex) -> List[Violation]:
    """R013 findings from the global lock graph, attributed to witness
    files (suppressions applied by the caller per file)."""
    out: List[Violation] = []
    for cycle in index.lock_cycles:
        hops = []
        witness = None
        ring = cycle + [cycle[0]]
        for a, b in zip(ring, ring[1:]):
            w = index.lock_edges.get((a, b))
            hops.append(f"{a} → {b}" + (f" ({w[0]}:{w[1]})" if w else ""))
            if witness is None and w is not None:
                witness = w
        path, line = witness if witness else ("<project>", 0)
        rec = index.by_path.get(path)
        out.append(Violation(
            "R013", path, line, 0,
            "lock-order cycle: " + "; ".join(hops) + " — two threads "
            "acquiring these locks in different orders deadlock; pick one "
            "global acquisition order (or split the critical sections)",
            snippet_at(rec.lines, line) if rec else ""))
    for path, line, msg in index.wait_violations:
        rec = index.by_path.get(path)
        out.append(Violation("R013", path, line, 0, msg,
                             snippet_at(rec.lines, line) if rec else ""))
    for rule, path, line, msg in index.race_violations:
        rec = index.by_path.get(path)
        out.append(Violation(rule, path, line, 0, msg,
                             snippet_at(rec.lines, line) if rec else ""))
    return out


def lint_project(paths: Sequence[str], root: Optional[str] = None,
                 overlay: Optional[Dict[str, str]] = None,
                 ) -> List[Violation]:
    """The two-pass whole-program lint: build the project index, then run
    every per-file rule with the graph-inferred traced/collective context,
    plus the global R013 lock-graph findings."""
    index, errors = build_project(paths, root=root, overlay=overlay)
    return lint_index(index) + errors


def lint_sources(sources: Dict[str, str]) -> List[Violation]:
    """Two-pass lint over in-memory sources (multi-module fixtures)."""
    index, errors = analyze_sources(sources)
    return lint_index(index) + errors


def lint_index(index: ProjectIndex) -> List[Violation]:
    from tools.tpulint import analyzer as _an
    from tools.tpulint import rules as _rules
    from tools.tpulint import shapeflow as _shapeflow

    out: List[Violation] = []
    for rec in index.records.values():
        ctx = _an.make_file_context(
            rec.path, rec.lines, rec.supp,
            ext_traced=index.traced_for_module(rec.modname),
            ext_collective=index.collective_for_module(rec.modname))
        found = _rules.check_module(rec.tree, ctx)
        out.extend(v for v in found if not rec.supp.suppressed(v))
    for v in _project_violations(index):
        rec = index.by_path.get(v.path)
        if rec is not None and rec.supp.suppressed(v):
            continue
        out.append(v)
    for v in _shapeflow.shapeflow_violations(index):
        rec = index.by_path.get(v.path)
        if rec is not None and rec.supp.suppressed(v):
            continue
        out.append(v)
    return sorted(out, key=lambda v: (v.path, v.line, v.col, v.rule))
