"""tpulint core: file/source entry points, suppressions, violation type.

The analysis is purely syntactic (stdlib ``ast``) so it runs in tier-1 CI
with no JAX import and no device. Rules are calibrated to this codebase's
idioms — ``@partial(jax.jit, static_argnames=...)`` program factories,
host-side numpy build paths beside device-side jnp trace paths, and
ES-style "public methods lock, ``_private`` helpers run caller-locked"
concurrency discipline — documented in docs/STATIC_ANALYSIS.md.
"""
from __future__ import annotations

import ast
import os
import re
from dataclasses import asdict, dataclass
from typing import Dict, List, Optional, Sequence, Set

RULES: Dict[str, str] = {
    "R001": "recompilation hazard (jit-in-loop / unhashable or "
            "high-cardinality static argument)",
    "R002": "host-device sync in a hot path",
    "R003": "dynamic shape in traced code / un-annotated host build path",
    "R004": "tracer leak (Python control flow on a traced value)",
    "R005": "shared mutable state written without holding the lock",
    "R006": "failure swallowed (`except Exception: pass`) in a "
            "failure-domain module",
    "R007": "wall-clock time.time() feeding a duration computation in a "
            "timing module (use time.monotonic()/perf_counter)",
    "R008": "raw jax.device_put bypassing the residency registry "
            "(unaccounted HBM — route through elasticsearch_tpu.resources)",
    "R009": "metric recording on the device path (record call inside "
            "jit-traced code, or a device-array argument to a record "
            "call — pull the scalar to host first)",
    "R010": "unbounded blocking wait (Event.wait/Condition.wait/queue.get "
            "without timeout) while holding a lock in a serving module — "
            "one lost notify wedges every parked request behind it",
    "R011": "background thread in a cluster module without daemon=True, "
            "or with a loop not gated on a stop Event (the _fault_loop "
            "pattern) — an ungated control-plane thread outlives close() "
            "and keeps publishing/probing a dead cluster",
    "R012": "import-time jax.jit binding outside the trace-audited "
            "packages (ops/, models/, parallel/) — the program can "
            "compile before tracing/retrace installs the auditor and "
            "escapes compile attribution (observatory census + profiler "
            "compile/execute split under-report); also a process-"
            "memoized jit program in a hot-path module-level cache not "
            "routed through the parallel.aot AotProgram factory (warm "
            "restarts re-compile; the census pre-warm cannot replay it)",
    "R013": "lock-order hazard: a cycle in the interprocedural "
            "held→acquired lock graph (potential deadlock), or a "
            "lock-held call chain into an unbounded blocking wait",
    "R014": "collective impurity: host sync / device transfer inside a "
            "shard_map/psum collective program (reachable through the "
            "call graph) — one stalled chip stalls every chip in the "
            "mesh",
    "R015": "lockset race: a write to an instance attribute whose "
            "inferred (or guarded_by-declared) guarding lock is not held, "
            "in code reachable from a thread root (Thread targets, pool "
            "submissions, REST/transport handlers)",
    "R016": "atomicity violation: the guard lock is released between a "
            "guarded check of an attribute and the guarded act that "
            "depends on it (check-then-act / get-or-create) — the state "
            "can change in the gap",
    "R017": "recompile storm: a data-dependent dimension (len()/.shape "
            "of host data/dict size, unbucketed) reaches a jit static "
            "argument or a cached program factory — every distinct value "
            "compiles a new program (unbounded shape-key census); bucket "
            "it (pow2_bucket/round_up) or declare the call site "
            "`# tpulint: bucketed`",
    "R018": "padding soundness: a reduction (sum/max/top_k/segment_sum/"
            "psum) over an operand carrying pow2-padded lanes with no "
            "dominating validity mask (where/mask multiply/length mask) "
            "— padded lanes leak into scores; mask first or declare the "
            "operand `# tpulint: masked`",
    "R019": "dtype discipline: bf16/f32 mixing on an MXU matmul path "
            "outside a declared cast point, or a float64/int64 spelling "
            "in traced code (silent f64/i64 promotion) — declare "
            "intended casts `# tpulint: cast`",
    "R020": "reservation leak: a breaker/residency acquisition (track/"
            "put_array/force/break_or_reserve) with fallible calls before "
            "the token is stored or released and no except/finally "
            "release path — an exception strands the reservation and "
            "wedges admission control",
}

# Per-rule severity, surfaced in --json for pre-commit tooling. `error`
# = breaks correctness or wedges the process (trace failures, deadlocks,
# device syncs inside programs, unlocked shared state); `warning` =
# degrades perf/observability but runs. The GATE fails on both — the
# split is for triage order, not for skipping.
SEVERITY: Dict[str, str] = {
    "R000": "error", "R001": "warning", "R002": "error", "R003": "error",
    "R004": "error", "R005": "error", "R006": "warning", "R007": "warning",
    "R008": "warning", "R009": "error", "R010": "error", "R011": "warning",
    "R012": "warning", "R013": "error", "R014": "error", "R015": "error",
    "R016": "error", "R017": "warning", "R018": "error", "R019": "error",
    "R020": "error",
}

# R002 scope: files whose per-query work sits on the request hot path.
HOT_PATH_MARKERS = ("/ops/", "/search/", "/rest/server.py")
# R006 scope: the failure-domain layers — a swallowed exception here turns
# a reportable fault (dead peer, failed fsync, lost replica) into silent
# data loss or a wedged cluster. Justified swallows carry a baseline entry
# or an inline allow.
SWALLOW_PATH_MARKERS = ("/cluster/", "/index/", "/rest/")
# R003 host-annotation scope: device-op modules where an un-annotated
# host numpy dynamic-shape call is ambiguous (build path or trace leak?).
OPS_PATH_MARKERS = ("/ops/",)
# R005 scope: modules whose state is mutated from utils.threadpool workers
# (every REST request runs on a pool thread; these are the write targets).
LOCKED_MODULE_MARKERS = (
    "/index/engine.py",
    "/index/translog.py",
    "/index/ivf_cache.py",
    "/utils/threadpool.py",
)
# R007 scope: the timing-sensitive modules — span durations, task running
# times, phase profiles, stats counters. A wall-clock duration silently
# corrupts under NTP step adjustments; epoch TIMESTAMPS (no subtraction)
# stay legal.
TIMING_PATH_MARKERS = ("/tracing/", "/monitor/")
# R008 scope: the product package — device placements must route through
# the residency registry's choke points (resources/residency.py) so HBM
# is accounted; resources/ itself implements them, and bench/tools are
# measurement code outside the serving budget.
BUDGET_PATH_MARKERS = ("/elasticsearch_tpu/",)
BUDGET_EXEMPT_MARKERS = ("/elasticsearch_tpu/resources/",)
# R010 scope: the serving front-end — request threads park on events and
# the drain thread sleeps on a condition; an UNBOUNDED wait while holding
# a lock turns one lost notify (or a crashed drain loop) into every
# parked client wedging forever. Timeout-bounded waits re-check state.
BLOCKING_PATH_MARKERS = ("/serving/",)
# R011 scope: every package that runs background threads — the cluster
# control plane (fault detection, elections, publish), the serving
# front-end (coalescer drain) and the monitor package (watchdog tick,
# flight sampling). A thread that is not daemon=True (or whose loop
# never checks a stop/closed gate) survives close() and keeps
# probing/publishing/draining a torn-down node, wedging test teardown
# and process exit — the watchdog/recorder threads are born under the
# rule rather than grandfathered past it.
THREADS_PATH_MARKERS = ("/cluster/", "/monitor/", "/serving/")
# R012 scope: the product package MINUS the packages whose __init__
# installs the trace auditor before their submodules bind jax.jit
# (tracing/retrace.py install-order contract). An import-time binding
# anywhere else races the install point: imported early (a Client-only
# path, a test importing one module), its programs compile uncounted and
# the observatory's compile attribution silently under-reports.
AUDIT_PATH_MARKERS = ("/elasticsearch_tpu/",)
AUDIT_EXEMPT_MARKERS = ("/elasticsearch_tpu/ops/",
                        "/elasticsearch_tpu/models/",
                        "/elasticsearch_tpu/parallel/")

_ALLOW_RE = re.compile(r"#\s*tpulint:\s*allow\[\s*([A-Z0-9,\s]+?)\s*\]")
_HOST_RE = re.compile(r"#\s*tpulint:\s*host\b")
_OFFBUDGET_RE = re.compile(r"#\s*tpulint:\s*offbudget\b")
# shapeflow contracts (pass 3): each declares one invariant the abstract
# interpreter cannot see and is equivalent to a targeted allow[...] —
#   bucketed  — the dim is padded/bounded by construction upstream (R017)
#   masked    — the padded lanes of this operand are neutral for the
#               reduction (zero-padded, pre-selected, or mesh-invariant
#               masked upstream) (R018)
#   cast      — a declared dtype cast point on the MXU path (R019)
_BUCKETED_RE = re.compile(r"#\s*tpulint:\s*bucketed\b")
_MASKED_RE = re.compile(r"#\s*tpulint:\s*masked\b")
_CAST_RE = re.compile(r"#\s*tpulint:\s*cast\b")


@dataclass(frozen=True)
class Violation:
    rule: str
    path: str
    line: int
    col: int
    message: str
    snippet: str  # stripped source line — the baseline fingerprint

    def format(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: {self.rule} {self.message}"

    def to_json(self) -> dict:
        return asdict(self)


class Suppressions:
    """Per-line ``# tpulint: allow[...]`` / ``# tpulint: host`` markers.

    A marker on a violating line suppresses that line; a marker inside a
    standalone comment block covers the rest of the block and the first
    code line after it (so the justification can sit above the code).
    ``host`` declares a statement as intentional host-side build code and
    is equivalent to ``allow[R003]``; ``offbudget`` declares a raw device
    placement as intentionally unaccounted (transient per-call upload)
    and is equivalent to ``allow[R008]``. The shapeflow contracts
    ``bucketed``/``masked``/``cast`` are equivalent to
    ``allow[R017]``/``allow[R018]``/``allow[R019]`` and document the
    invariant the abstract interpreter cannot derive.
    """

    def __init__(self, source: str):
        self.allow: Dict[int, Set[str]] = {}
        self.host: Set[int] = set()
        lines = source.splitlines()
        for i, text in enumerate(lines, start=1):
            rules: Set[str] = set()
            for m in _ALLOW_RE.finditer(text):
                rules |= {r.strip() for r in m.group(1).split(",") if r.strip()}
            is_host = bool(_HOST_RE.search(text))
            if is_host:
                rules.add("R003")
            if _OFFBUDGET_RE.search(text):
                rules.add("R008")
            if _BUCKETED_RE.search(text):
                rules.add("R017")
            if _MASKED_RE.search(text):
                rules.add("R018")
            if _CAST_RE.search(text):
                rules.add("R019")
            if not rules:
                continue
            covered = [i]
            if text.lstrip().startswith("#"):
                # walk past the rest of the comment block (blank lines
                # included) to the first code line
                j = i + 1
                while j <= len(lines) and (
                        lines[j - 1].lstrip().startswith("#")
                        or not lines[j - 1].strip()):
                    covered.append(j)
                    j += 1
                covered.append(j)
            for ln in covered:
                self.allow.setdefault(ln, set()).update(rules)
                if is_host:
                    self.host.add(ln)

    def suppressed(self, v: Violation) -> bool:
        return v.rule in self.allow.get(v.line, ())


def _matches(path: str, markers: Sequence[str]) -> bool:
    p = "/" + path.replace(os.sep, "/").lstrip("/")
    return any(m in p for m in markers)


def make_file_context(path: str, lines: Sequence[str], supp: "Suppressions",
                      *, ext_traced=None, ext_collective=None, **overrides):
    """FileContext with path-inferred scoping (overridable per flag) plus
    the project-level traced/collective maps (pass 2 of the whole-program
    analysis; empty in single-file mode)."""
    from tools.tpulint import rules as _rules

    def flag(name: str, default: bool) -> bool:
        v = overrides.get(name)
        return default if v is None else v

    return _rules.FileContext(
        path=path,
        lines=lines,
        hot=flag("hot", _matches(path, HOT_PATH_MARKERS)),
        ops=flag("ops", _matches(path, OPS_PATH_MARKERS)),
        locked=flag("locked", _matches(path, LOCKED_MODULE_MARKERS)),
        swallow=flag("swallow", _matches(path, SWALLOW_PATH_MARKERS)),
        timing=flag("timing", _matches(path, TIMING_PATH_MARKERS)),
        budget=flag("budget", _matches(path, BUDGET_PATH_MARKERS)
                    and not _matches(path, BUDGET_EXEMPT_MARKERS)),
        blocking=flag("blocking", _matches(path, BLOCKING_PATH_MARKERS)),
        threads=flag("threads", _matches(path, THREADS_PATH_MARKERS)),
        audit=flag("audit", _matches(path, AUDIT_PATH_MARKERS)
                   and not _matches(path, AUDIT_EXEMPT_MARKERS)),
        host_lines=supp.host,
        ext_traced=ext_traced or {},
        ext_collective=ext_collective or set(),
    )


def lint_source(
    source: str,
    path: str = "<string>",
    *,
    hot: Optional[bool] = None,
    ops: Optional[bool] = None,
    locked: Optional[bool] = None,
    swallow: Optional[bool] = None,
    timing: Optional[bool] = None,
    budget: Optional[bool] = None,
    blocking: Optional[bool] = None,
    threads: Optional[bool] = None,
    audit: Optional[bool] = None,
) -> List[Violation]:
    """Lint one source string, single-file mode (no call graph — only
    locally visible jit roots enter traced context). ``hot``/``ops``/
    ``locked``/``swallow``/``timing``/``budget``/``blocking``/``threads``/
    ``audit`` override the path-based scoping (fixture tests use these;
    production runs infer from the path)."""
    from tools.tpulint import rules as _rules

    tree = ast.parse(source, filename=path)
    supp = Suppressions(source)
    lines = source.splitlines()
    ctx = make_file_context(
        path, lines, supp, hot=hot, ops=ops, locked=locked,
        swallow=swallow, timing=timing, budget=budget, blocking=blocking,
        threads=threads, audit=audit)
    found = _rules.check_module(tree, ctx)
    return [v for v in found if not supp.suppressed(v)]


def lint_file(path: str, root: Optional[str] = None) -> List[Violation]:
    with open(path, "r", encoding="utf-8") as fh:
        source = fh.read()
    # report paths relative to `root` for files under it (the baseline
    # fingerprints on this form, so it must not depend on cwd or on
    # absolute-vs-relative invocation); files elsewhere keep their path
    rel = path
    if root:
        ap, ar = os.path.abspath(path), os.path.abspath(root)
        if ap == ar or ap.startswith(ar + os.sep):
            rel = os.path.relpath(ap, ar)
    try:
        return lint_source(source, rel.replace(os.sep, "/"))
    except SyntaxError as e:
        return [Violation("R000", rel, e.lineno or 0, e.offset or 0,
                          f"syntax error: {e.msg}", "")]


def iter_python_files(paths: Sequence[str]) -> List[str]:
    out: List[str] = []
    for p in paths:
        if os.path.isfile(p):
            out.append(p)
            continue
        if not os.path.isdir(p):
            # a typo'd/renamed path must not silently lint zero files and
            # report the gate green
            raise FileNotFoundError(f"no such file or directory: {p}")
        for dirpath, dirnames, filenames in os.walk(p):
            dirnames[:] = sorted(d for d in dirnames
                                 if d not in ("__pycache__", ".git"))
            out.extend(os.path.join(dirpath, f)
                       for f in sorted(filenames) if f.endswith(".py"))
    return out


def lint_paths(paths: Sequence[str],
               root: Optional[str] = None) -> List[Violation]:
    found: List[Violation] = []
    for f in iter_python_files(paths):
        found.extend(lint_file(f, root=root))
    return sorted(found, key=lambda v: (v.path, v.line, v.col, v.rule))


def snippet_at(lines: Sequence[str], lineno: int) -> str:
    if 1 <= lineno <= len(lines):
        return lines[lineno - 1].strip()
    return ""
