"""A/B the single-query product path on TPU: topk staging x impact dtype.

Run: python tools/tpu_ab.py [docs_pow2]   (fresh process per config —
programs cache per executor, env flags read at trace time)
"""
import json
import os
import subprocess
import sys

docs = sys.argv[1] if len(sys.argv) > 1 else str(1 << 20)

INNER = r"""
import os, sys, time
import numpy as np
sys.path.insert(0, os.environ["AB_REPO"])  # -c code has no __file__
sys.argv = [sys.argv[0]]
# retrace auditor BEFORE bench/elasticsearch_tpu bind jax.jit at import
# (tools/tpulint/trace_audit.py): the timed loop below must not retrace
from tools.tpulint import trace_audit as _ta
_audit = _ta.install()
import bench
from elasticsearch_tpu.utils.platform import (enable_compilation_cache,
                                              ensure_cpu_if_requested)
ensure_cpu_if_requested()  # no-op on TPU runs; unblocks CPU when tunnel is down
enable_compilation_cache()
docs = int(os.environ["AB_DOCS"]); vocab = 30000
u_doc, tf, tfn, offsets, df, idf, doc_len = bench.build_corpus(docs, vocab, 42)
node, seg = bench.make_msmarco_node(u_doc, tf, tfn, offsets, df, doc_len,
                                    docs, vocab)
seg.inverted["body"].dense_block()
qs = bench.make_queries(12, vocab, df, 42)
bodies = [{"query": {"match": {"body": " ".join(f"t{t}" for t in q)}},
           "size": 10} for q in qs]
for b in bodies:
    node.search("msmarco", b)
_steady = _audit.snapshot()  # warmup compiled every program it will need
times = []
for _ in range(3):
    for b in bodies:
        t0 = time.perf_counter()
        node.search("msmarco", b)
        times.append(time.perf_counter() - t0)
# any trace during the timed loop is a recompile polluting the percentiles
_retraced = _audit.traces_since(_steady)
import json as _j
cpu_times, cpu_tops = bench.cpu_bm25_latency(u_doc, tfn, offsets, idf,
                                             qs, docs, 10, runs=1)
agree = 0
for q, ct in zip(qs, cpu_tops):
    r = node.search("msmarco", {"query": {"match": {"body": " ".join(
        f"t{t}" for t in q)}}, "size": 1})
    if r["hits"]["hits"] and int(r["hits"]["hits"][0]["_id"]) == ct[0]:
        agree += 1
print(_j.dumps({"p50_ms": float(np.percentile(np.array(times) * 1000, 50)),
                "cpu_p50_ms": float(np.percentile(np.array(cpu_times) * 1000, 50)),
                "top1_agree": f"{agree}/{len(qs)}",
                "retraces_timed": sum(_retraced.values())}))
"""

CONFIGS = [
    # r5: the tail/scatter strategy is the big lever — A/B it first
    ("default(auto)", {}),
    ("tail_candidates", {"ESTPU_TAIL_MODE": "candidates"}),
    ("tail_scatter", {"ESTPU_TAIL_MODE": "scatter"}),
    ("cand+flat_topk", {"ESTPU_TAIL_MODE": "candidates",
                        "ESTPU_BLOCKED_TOPK": "0"}),
    ("scatter+blocked", {"ESTPU_TAIL_MODE": "scatter",
                         "ESTPU_BLOCKED_TOPK": "1"}),
    ("cand+bf16", {"ESTPU_TAIL_MODE": "candidates",
                   "ESTPU_IMPACT_BF16": "1"}),
]
for name, extra in CONFIGS:
    env = dict(os.environ)
    env.update(extra)
    env["AB_DOCS"] = docs
    env["AB_REPO"] = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    r = subprocess.run([sys.executable, "-u", "-c", INNER], env=env,
                       capture_output=True, text=True, timeout=900)
    line = (r.stdout.strip().splitlines() or ["{}"])[-1]
    try:
        d = json.loads(line)
    except Exception:
        d = {"error": r.stderr.strip().splitlines()[-3:]}
    print(name, "->", json.dumps(d), flush=True)
