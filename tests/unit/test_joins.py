"""Nested (block-join) and parent/child join tests.

Reference behaviors: NestedQueryBuilder (per-object match semantics — the
whole point of nested vs object arrays), inner_hits, nested/reverse_nested
aggregations, HasChild/HasParentQueryBuilder with score modes.
"""
import numpy as np
import pytest

from elasticsearch_tpu.index.index_service import IndexService


@pytest.fixture()
def nested_svc():
    s = IndexService("posts", mappings_json={"properties": {
        "title": {"type": "text"},
        "comments": {"type": "nested", "properties": {
            "author": {"type": "keyword"},
            "stars": {"type": "integer"},
            "text": {"type": "text"},
        }},
    }})
    s.index_doc("1", {"title": "post one", "comments": [
        {"author": "alice", "stars": 5, "text": "great stuff"},
        {"author": "bob", "stars": 1, "text": "terrible"},
    ]})
    s.index_doc("2", {"title": "post two", "comments": [
        {"author": "alice", "stars": 1, "text": "meh"},
        {"author": "carol", "stars": 5, "text": "wonderful"},
    ]})
    s.index_doc("3", {"title": "post three no comments"})
    for sh in s.shards:
        sh.refresh()
    yield s
    s.close()


def ids(resp):
    return sorted(h["_id"] for h in resp["hits"]["hits"])


def test_nested_per_object_semantics(nested_svc):
    # alice AND stars=5 must match within the SAME comment: only doc 1.
    # (A flattened object mapping would also wrongly match doc 2.)
    q = {"nested": {"path": "comments", "query": {"bool": {"must": [
        {"term": {"comments.author": "alice"}},
        {"term": {"comments.stars": 5}},
    ]}}}}
    assert ids(nested_svc.search({"query": q})) == ["1"]


def test_nested_children_hidden_from_toplevel(nested_svc):
    resp = nested_svc.search({"query": {"match_all": {}}, "size": 50})
    assert ids(resp) == ["1", "2", "3"]
    assert resp["hits"]["total"] == 3
    assert nested_svc.count({"query": {"match_all": {}}})["count"] == 3


def test_nested_score_modes(nested_svc):
    base = {"path": "comments", "query": {"match": {"comments.text": "great wonderful"}}}
    for mode in ("avg", "sum", "max", "min", "none"):
        q = {"nested": dict(base, score_mode=mode)}
        resp = nested_svc.search({"query": q})
        assert resp["hits"]["total"] == 2
        if mode == "none":
            # filter semantics: constant score = boost (1.0), like ES's
            # ToParentBlockJoinQuery under ScoreMode.None
            assert all(h["_score"] == 1.0 for h in resp["hits"]["hits"])
        else:
            assert all(h["_score"] > 0 for h in resp["hits"]["hits"])


def test_nested_inner_hits(nested_svc):
    q = {"nested": {"path": "comments",
                    "query": {"term": {"comments.author": "alice"}},
                    "inner_hits": {}}}
    resp = nested_svc.search({"query": q})
    assert resp["hits"]["total"] == 2
    for h in resp["hits"]["hits"]:
        ih = h["inner_hits"]["comments"]["hits"]
        assert ih["total"] == 1
        inner = ih["hits"][0]
        assert inner["_source"]["author"] == "alice"
        assert inner["_nested"]["field"] == "comments"
    doc1 = next(h for h in resp["hits"]["hits"] if h["_id"] == "1")
    assert doc1["inner_hits"]["comments"]["hits"]["hits"][0]["_nested"]["offset"] == 0


def test_nested_agg_and_reverse(nested_svc):
    body = {"size": 0, "aggs": {"c": {"nested": {"path": "comments"}, "aggs": {
        "by_author": {"terms": {"field": "comments.author"}, "aggs": {
            "back": {"reverse_nested": {}}}},
        "avg_stars": {"avg": {"field": "comments.stars"}},
    }}}}
    resp = nested_svc.search(body)
    agg = resp["aggregations"]["c"]
    assert agg["doc_count"] == 4  # 4 comments across live roots
    assert agg["avg_stars"]["value"] == pytest.approx(3.0)
    buckets = {b["key"]: b for b in agg["by_author"]["buckets"]}
    assert buckets["alice"]["doc_count"] == 2
    assert buckets["alice"]["back"]["doc_count"] == 2  # two distinct posts


def test_nested_delete_cascades(nested_svc):
    nested_svc.delete_doc("1")
    for sh in nested_svc.shards:
        sh.refresh()
    q = {"nested": {"path": "comments", "query": {"term": {"comments.author": "bob"}}}}
    assert ids(nested_svc.search({"query": q})) == []
    # agg no longer counts doc1's comments
    body = {"size": 0, "aggs": {"c": {"nested": {"path": "comments"}}}}
    assert nested_svc.search(body)["aggregations"]["c"]["doc_count"] == 2


def test_nested_survives_merge(nested_svc):
    for sh in nested_svc.shards:
        sh.engine.merge()
    q = {"nested": {"path": "comments", "query": {"bool": {"must": [
        {"term": {"comments.author": "alice"}}, {"term": {"comments.stars": 5}}]}}}}
    assert ids(nested_svc.search({"query": q})) == ["1"]


def test_multilevel_nested_path_joins_to_root():
    s = IndexService("deep", mappings_json={"properties": {
        "a": {"type": "nested", "properties": {
            "name": {"type": "keyword"},
            "b": {"type": "nested", "properties": {"v": {"type": "integer"}}},
        }},
    }})
    s.index_doc("1", {"a": [{"name": "x", "b": [{"v": 1}, {"v": 2}]},
                            {"name": "y", "b": [{"v": 3}]}]})
    s.index_doc("2", {"a": [{"name": "z", "b": [{"v": 9}]}]})
    for sh in s.shards:
        sh.refresh()
    # direct deep path at top level joins straight to the ROOT doc
    q = {"nested": {"path": "a.b", "query": {"term": {"a.b.v": 3}}}}
    assert ids(s.search({"query": q})) == ["1"]
    # nested-inside-nested: same-object semantics at the intermediate level
    q = {"nested": {"path": "a", "query": {"bool": {"must": [
        {"term": {"a.name": "x"}},
        {"nested": {"path": "a.b", "query": {"term": {"a.b.v": 2}}}}]}}}}
    assert ids(s.search({"query": q})) == ["1"]
    q = {"nested": {"path": "a", "query": {"bool": {"must": [
        {"term": {"a.name": "y"}},
        {"nested": {"path": "a.b", "query": {"term": {"a.b.v": 2}}}}]}}}}
    assert ids(s.search({"query": q})) == []  # v=2 lives under x, not y
    # chained nested aggs + reverse_nested back to root
    body = {"size": 0, "aggs": {"l1": {"nested": {"path": "a"}, "aggs": {
        "l2": {"nested": {"path": "a.b"}, "aggs": {
            "back": {"reverse_nested": {}}}}}}}}
    agg = s.search(body)["aggregations"]["l1"]
    assert agg["doc_count"] == 3
    assert agg["l2"]["doc_count"] == 4
    assert agg["l2"]["back"]["doc_count"] == 2
    s.close()


def test_bulk_preserves_parent_and_update_preserves_join():
    from elasticsearch_tpu.node import Node

    n = Node()
    n.indices["shop2"] = IndexService("shop2")
    n.bulk([
        {"index": {"_index": "shop2", "_type": "store", "_id": "p1"}},
        {"name": "main store"},
        {"index": {"_index": "shop2", "_type": "product", "_id": "c1", "parent": "p1"}},
        {"item": "green shoe"},
    ])
    svc = n.indices["shop2"]
    for sh in svc.shards:
        sh.refresh()
    q = {"has_child": {"type": "product", "query": {"match": {"item": "green"}}}}
    assert ids(svc.search({"query": q})) == ["p1"]
    # partial update must not sever the parent link
    svc.update_doc("c1", {"doc": {"price": 10}}, routing="p1")
    for sh in svc.shards:
        sh.refresh()
    q = {"has_child": {"type": "product", "query": {"term": {"price": 10}}}}
    assert ids(svc.search({"query": q})) == ["p1"]
    svc.close()


def test_has_child_inside_filter_agg(pc_svc):
    body = {"size": 0, "aggs": {"f": {"filter": {
        "has_child": {"type": "product", "query": {"match": {"item": "shoe"}}}}}}}
    resp = pc_svc.search(body)
    assert resp["aggregations"]["f"]["doc_count"] == 1  # p1


@pytest.fixture()
def pc_svc():
    s = IndexService("shop", settings={"index": {"number_of_shards": 2}})
    s.index_doc("p1", {"name": "store one"}, doc_type="store")
    s.index_doc("p2", {"name": "store two"}, doc_type="store")
    # children routed to the parent's shard via routing=parent
    s.index_doc("c1", {"item": "red shoe"}, doc_type="product", parent="p1", routing="p1")
    s.index_doc("c2", {"item": "blue shoe"}, doc_type="product", parent="p1", routing="p1")
    s.index_doc("c3", {"item": "red hat"}, doc_type="product", parent="p2", routing="p2")
    for sh in s.shards:
        sh.refresh()
    yield s
    s.close()


def test_has_child(pc_svc):
    q = {"has_child": {"type": "product", "query": {"match": {"item": "red"}}}}
    assert ids(pc_svc.search({"query": q})) == ["p1", "p2"]
    q = {"has_child": {"type": "product", "query": {"match": {"item": "blue"}}}}
    assert ids(pc_svc.search({"query": q})) == ["p1"]


def test_has_child_min_children(pc_svc):
    q = {"has_child": {"type": "product", "query": {"match": {"item": "shoe"}},
                       "min_children": 2}}
    assert ids(pc_svc.search({"query": q})) == ["p1"]


def test_has_child_score_mode_sum(pc_svc):
    q = {"has_child": {"type": "product", "query": {"match": {"item": "shoe"}},
                       "score_mode": "sum"}}
    resp = pc_svc.search({"query": q})
    assert [h["_id"] for h in resp["hits"]["hits"]] == ["p1"]
    assert resp["hits"]["hits"][0]["_score"] > 0


def test_has_parent(pc_svc):
    q = {"has_parent": {"parent_type": "store", "query": {"match": {"name": "one"}}}}
    assert ids(pc_svc.search({"query": q})) == ["c1", "c2"]


def test_children_agg(pc_svc):
    body = {"size": 0,
            "query": {"term": {"_type": "store"}},
            "aggs": {"kids": {"children": {"type": "product"}}}}
    resp = pc_svc.search(body)
    assert resp["aggregations"]["kids"]["doc_count"] == 3
