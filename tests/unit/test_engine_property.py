"""Property test: random engine op interleavings vs a model dict.

SURVEY §4: "Property tests: random docs/queries, engine ops interleaving
(index/delete/update/refresh) vs model dict." Reference behavioral frame:
org/elasticsearch/index/engine/InternalEngine.java — realtime GET reads
through the write buffer, search sees only refreshed state, versions are
monotonic per id and survive deletes (tombstones).

The model is two dicts: `live` (what a realtime GET must see NOW) and
`segment_resident` (what search must see). The engine's documented TPU
adaptation: additions become searchable at REFRESH (buffer freeze), but
deletes — including the delete half of a re-index/update — hit the frozen
segment's live mask IMMEDIATELY (segment.delete_local), so search loses a
doc the moment it is deleted or updated, and regains the new copy at the
next refresh.
"""
import random

import pytest

from elasticsearch_tpu.node import Node


OPS = ("index", "index_existing", "update", "delete", "delete_missing",
       "refresh", "merge")
WEIGHTS = (30, 15, 15, 12, 4, 18, 6)


def _random_doc(rng):
    return {
        "title": " ".join(rng.choices(
            ["alpha", "beta", "gamma", "delta", "fox"], k=rng.randint(1, 4))),
        "rank": rng.randint(0, 99),
    }


@pytest.mark.parametrize("seed", [7, 41, 1234])
def test_engine_ops_interleaving_matches_model(seed):
    rng = random.Random(seed)
    node = Node()
    node.create_index("prop", {
        "settings": {"index": {"number_of_shards": 1}},
        "mappings": {"properties": {
            "title": {"type": "text"}, "rank": {"type": "integer"}}}})
    svc = node.indices["prop"]

    live = {}              # id -> (source, version), realtime view
    segment_resident = {}  # id -> source, what search must return
    next_id = 0

    def check_realtime(doc_id):
        got = svc.get_doc(doc_id)
        if doc_id in live:
            src, ver = live[doc_id]
            assert got["found"], (doc_id, got)
            assert got["_source"] == src
            assert got["_version"] == ver
        else:
            assert not got.get("found"), (doc_id, got)

    for step in range(200):
        op = rng.choices(OPS, weights=WEIGHTS)[0]
        existing = sorted(live)
        if op in ("index_existing", "update", "delete") and not existing:
            op = "index"
        if op == "index":
            doc_id = f"d{next_id}"
            next_id += 1
            src = _random_doc(rng)
            r = svc.index_doc(doc_id, src)
            assert r["created"] and r["_version"] >= 1
            live[doc_id] = (src, r["_version"])
        elif op == "index_existing":
            doc_id = rng.choice(existing)
            src = _random_doc(rng)
            r = svc.index_doc(doc_id, src)
            assert not r["created"]
            assert r["_version"] == live[doc_id][1] + 1  # monotonic per id
            live[doc_id] = (src, r["_version"])
            # re-index deletes the segment copy; new copy waits for refresh
            segment_resident.pop(doc_id, None)
        elif op == "update":
            doc_id = rng.choice(existing)
            rank = rng.randint(100, 199)
            r = svc.update_doc(doc_id, {"doc": {"rank": rank}})
            src = dict(live[doc_id][0], rank=rank)
            assert r["_version"] == live[doc_id][1] + 1
            live[doc_id] = (src, r["_version"])
            segment_resident.pop(doc_id, None)
        elif op == "delete":
            doc_id = rng.choice(existing)
            r = svc.delete_doc(doc_id)
            assert r["found"]
            del live[doc_id]
            segment_resident.pop(doc_id, None)  # instant search visibility
        elif op == "delete_missing":
            from elasticsearch_tpu.utils.errors import \
                DocumentMissingException

            with pytest.raises(DocumentMissingException):
                svc.delete_doc(f"missing-{step}")
        elif op == "refresh":
            svc.refresh()
            segment_resident = {i: s for i, (s, _v) in live.items()}
        elif op == "merge":
            svc.force_merge(1)
            # merge rewrites segments; it must not change visibility

        # realtime GET reads through the buffer at every step
        check_realtime(rng.choice(existing) if existing else "d0")
        if live:
            check_realtime(rng.choice(sorted(live)))

        # search sees exactly the segment-resident set at every step
        if op in ("refresh", "merge", "delete", "update") or step % 17 == 0:
            res = node.search("prop", {"query": {"match_all": {}},
                                       "size": 500})
            got_ids = sorted(h["_id"] for h in res["hits"]["hits"])
            assert got_ids == sorted(segment_resident), (step, op)
            assert res["hits"]["total"] == len(segment_resident)

    # final convergence: refresh and compare content, not just ids
    svc.refresh()
    res = node.search("prop", {"query": {"match_all": {}}, "size": 500})
    assert res["hits"]["total"] == len(live)
    for h in res["hits"]["hits"]:
        assert h["_source"] == live[h["_id"]][0]
    node.close()
