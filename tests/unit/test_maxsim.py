"""Multi-vector MaxSim (ColBERT-style token-matrix queries) — ISSUE-9.

Acceptance surface: a MaxSim query returns parity with a numpy
reference through BOTH the sequential serving path (Node.search ->
KnnQuery._execute_maxsim) and the coalesced serving path (concurrent
identical-shape searches micro-batched through serving/coalescer ->
search/batch.knn_topk_fused_batch), plus the executor product API
(MeshSearchExecutor.search_maxsim) and the device dedup-by-max merge
primitive.
"""
import threading

import numpy as np
import pytest

from elasticsearch_tpu.monitor import kernels
from elasticsearch_tpu.node import Node


@pytest.fixture(scope="module")
def corpus():
    rng = np.random.RandomState(17)
    V = rng.randn(300, 8).astype(np.float32)
    n = Node()
    n.create_index("mv", {"settings": {"number_of_shards": 1},
                          "mappings": {"properties": {
                              "emb": {"type": "dense_vector", "dims": 8},
                              "tag": {"type": "keyword"}}}})
    svc = n.indices["mv"]
    for i in range(300):
        svc.index_doc(str(i), {"emb": [float(x) for x in V[i]],
                               "tag": f"g{i % 3}"})
    svc.refresh()
    yield n, V
    n.close()


def _maxsim_ref(tokens, V, k):
    """Numpy reference: per-doc score = max over query tokens of the ES
    cosine score (1+cos)/2; top-k by (score desc, doc asc)."""
    Vn = V / np.maximum(np.linalg.norm(V, axis=1, keepdims=True), 1e-12)
    Tn = tokens / np.maximum(
        np.linalg.norm(tokens, axis=1, keepdims=True), 1e-12)
    S = (1.0 + Tn @ Vn.T) * 0.5
    per_doc = S.max(axis=0)
    order = np.lexsort((np.arange(V.shape[0]), -per_doc))[:k]
    return order, per_doc


def test_maxsim_sequential_parity_with_numpy(corpus):
    n, V = corpus
    rng = np.random.RandomState(3)
    for trial in range(3):
        T = rng.randn(rng.randint(2, 5), 8).astype(np.float32)
        body = {"query": {"knn": {
            "field": "emb",
            "query_vectors": [[float(x) for x in t] for t in T],
            "k": 7, "num_candidates": 100}}, "size": 7}
        before = kernels.snapshot().get("knn_maxsim", 0)
        r = n.search("mv", body)
        assert kernels.snapshot().get("knn_maxsim", 0) > before
        ref_ids, per_doc = _maxsim_ref(T, V, 7)
        got = [int(h["_id"]) for h in r["hits"]["hits"]]
        assert got == ref_ids.tolist(), (trial, got, ref_ids)
        np.testing.assert_allclose(
            [h["_score"] for h in r["hits"]["hits"]],
            per_doc[ref_ids], rtol=1e-5)


def test_maxsim_nested_query_vector_spelling(corpus):
    """A nested list under query_vector means the same as query_vectors."""
    n, V = corpus
    T = np.asarray([[1.0] * 8, [-1.0] * 8], np.float32)
    a = n.search("mv", {"query": {"knn": {
        "field": "emb", "query_vector": T.tolist(), "k": 5,
        "num_candidates": 50}}, "size": 5})
    b = n.search("mv", {"query": {"knn": {
        "field": "emb", "query_vectors": T.tolist(), "k": 5,
        "num_candidates": 50}}, "size": 5})
    assert [h["_id"] for h in a["hits"]["hits"]] == \
        [h["_id"] for h in b["hits"]["hits"]]


def test_maxsim_filter_composes(corpus):
    n, V = corpus
    T = np.asarray([[1.0] * 8, [-1.0] * 8], np.float32)
    r = n.search("mv", {"query": {"knn": {
        "field": "emb", "query_vectors": T.tolist(), "k": 6,
        "num_candidates": 100,
        "filter": {"term": {"tag": "g1"}}}}, "size": 6})
    assert r["hits"]["hits"]
    assert all(int(h["_id"]) % 3 == 1 for h in r["hits"]["hits"])
    # parity against the reference restricted to the filtered set
    Vn = V / np.maximum(np.linalg.norm(V, axis=1, keepdims=True), 1e-12)
    Tn = T / np.maximum(np.linalg.norm(T, axis=1, keepdims=True), 1e-12)
    per_doc = ((1.0 + Tn @ Vn.T) * 0.5).max(axis=0)
    allowed = np.asarray([i % 3 == 1 for i in range(300)])
    per_doc = np.where(allowed, per_doc, -np.inf)
    ref = np.lexsort((np.arange(300), -per_doc))[:6]
    assert [int(h["_id"]) for h in r["hits"]["hits"]] == ref.tolist()


def test_maxsim_coalesced_parity(corpus):
    """Concurrent identical-shape MaxSim searches coalesce into ONE
    fused batch (knn_fused_batch counter advances, batch-size histogram
    records > 1) and every client gets the sequential answer."""
    n, V = corpus
    T = np.random.RandomState(5).randn(2, 8).astype(np.float32)
    body = {"query": {"knn": {
        "field": "emb", "query_vectors": [[float(x) for x in t] for t in T],
        "k": 5, "num_candidates": 100}}, "size": 5}
    seq = n.search("mv", body)
    sig = [(h["_id"], round(h["_score"], 5)) for h in seq["hits"]["hits"]]
    ref_ids, per_doc = _maxsim_ref(T, V, 5)
    assert [int(h) for h, _ in sig] == ref_ids.tolist()

    n.serving.apply_cluster_settings({
        "serving.coalescer.mode": "always",
        "serving.coalescer.max_wait": "60ms",
        "serving.coalescer.idle_gap": "25ms"})
    try:
        N = 8
        results = [None] * N
        barrier = threading.Barrier(N)

        def client(i):
            barrier.wait()
            results[i] = n.search("mv", dict(body))

        before = kernels.snapshot().get("knn_fused_batch", 0)
        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(N)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
        for r in results:
            assert r is not None
            assert [(h["_id"], round(h["_score"], 5))
                    for h in r["hits"]["hits"]] == sig
        assert kernels.snapshot().get("knn_fused_batch", 0) - before >= 2
    finally:
        n.serving.apply_cluster_settings({})


def test_maxsim_msearch_batches(corpus):
    """Explicit _msearch of uniform MaxSim bodies rides the fused knn
    batch tier (mixed token counts repeat-pad to one tensor)."""
    n, V = corpus
    rng = np.random.RandomState(9)
    T2 = rng.randn(2, 8).astype(np.float32)
    T3 = rng.randn(3, 8).astype(np.float32)
    pairs = []
    refs = []
    for T in (T2, T3, T2, T3):
        pairs.append(({"index": "mv"}, {"query": {"knn": {
            "field": "emb",
            "query_vectors": [[float(x) for x in t] for t in T],
            "k": 5, "num_candidates": 100}}, "size": 5}))
        refs.append(_maxsim_ref(T, V, 5)[0].tolist())
    before = kernels.snapshot().get("knn_fused_batch", 0)
    resp = n.msearch(pairs)
    assert kernels.snapshot().get("knn_fused_batch", 0) - before >= 4
    for r, ref in zip(resp["responses"], refs):
        assert [int(h["_id"]) for h in r["hits"]["hits"]] == ref


def test_maxsim_executor_parity(corpus):
    n, V = corpus
    ex = n.indices["mv"].mesh_executor()
    if ex is None:
        pytest.skip("no mesh executor on this backend")
    rng = np.random.RandomState(11)
    T = rng.randn(3, 8).astype(np.float32)
    ref_ids, per_doc = _maxsim_ref(T, V, 6)
    vals, shard, local, ordn, _tot = ex.search_maxsim(
        "emb", np.stack([T, T]), k=6)
    for qi in range(2):
        assert [int(x) for x in local[qi]] == ref_ids.tolist()
        np.testing.assert_allclose(vals[qi], per_doc[ref_ids], rtol=1e-5)


def test_ragged_query_vectors_is_a_typed_error():
    """A ragged token list must raise QueryParsingException (HTTP 400),
    not leak numpy's ValueError (HTTP 500)."""
    from elasticsearch_tpu.search.queries import KnnQuery
    from elasticsearch_tpu.utils.errors import QueryParsingException

    with pytest.raises(QueryParsingException, match="malformed knn"):
        KnnQuery("emb", [[1.0, 2.0], [1.0, 2.0, 3.0]], k=3)


def test_mesh_compile_single_token_query_vectors(corpus):
    """A single-token query_vectors body (nested list, maxsim=False) must
    hand VecsPrim the 1-D vector — the raw body value is [1, dims] and
    would make the prim derive dims = 1."""
    n, V = corpus
    from elasticsearch_tpu.parallel.compiler import (MeshQueryCompiler,
                                                     VecsPrim)
    from elasticsearch_tpu.search.queries import KnnQuery

    svc = n.indices["mv"]
    q = KnnQuery("emb", [[1.0] * 8], k=3, ann=False)
    assert not q.maxsim
    comp = MeshQueryCompiler(svc.mappings, svc.analysis, D=512)
    comp.compile(q, None, None)
    vp = next(p for p in comp.prims if isinstance(p, VecsPrim))
    assert vp.qvec.shape == (8,)


def test_merge_candidate_topk_dedups_and_orders():
    import jax.numpy as jnp

    from elasticsearch_tpu.ops.knn import merge_candidate_topk

    vals = jnp.asarray([[0.9, 0.8, 0.9, 0.5, -jnp.inf, 0.8]])
    ids = jnp.asarray([[7, 3, 3, 9, 0, 7]], dtype=jnp.int32)
    v, i, nuniq = merge_candidate_topk(vals, ids, k=3)
    # doc 3 max = 0.9, doc 7 max = 0.9 (tie -> lower id first), doc 9
    assert np.asarray(i)[0].tolist() == [3, 7, 9]
    np.testing.assert_allclose(np.asarray(v)[0], [0.9, 0.9, 0.5])
    assert int(np.asarray(nuniq)[0]) == 3  # 3, 7, 9 (the -inf id-0 slot
    # is invalid and must not count)
