"""Regression tests for the concurrency bugs tpulint R015/R016 found
(ISSUE 15 adoption pass) — each pins the FIXED discipline so a refactor
that drops the lock (or reintroduces the stale-snapshot write) fails
deterministically, not flakily.

1. bootstrap `_publish` commit: the (`_committed_meta`,
   `_committed_snapshot`) pair must update under `_indices_lock` — an
   unlocked two-field update let `_on_meta` (transport thread) pair the
   NEW freshness key with the OLD snapshot and hand an elected master
   stale metadata under a fresh key (R015).
2. bootstrap `_takeover`: the `_meta_term` stamp must take
   `_indices_lock` like every other write of it (R015).
3. watcher `check_now`: the act region must re-read the CURRENT
   listener list under the lock — writing back the poll snapshot's list
   reverted a concurrent remove()+add() cycle and silently dropped the
   re-added listeners (R016's check-then-act window).

The instrumentation swaps the cluster instance's class for a subclass
whose ``__setattr__`` records any write of the guarded fields made
without `_indices_lock` held (tracked per thread through a lock proxy)
— the discipline itself is the assertion, so the test cannot pass by
lucky scheduling.
"""
import os
import socket
import threading

import pytest

from elasticsearch_tpu.watcher import ResourceWatcherService

GUARDED = ("_meta_term", "_committed_meta", "_committed_snapshot")


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _instrument(cluster):
    """Record writes of the commit-metadata fields made while
    `_indices_lock` is NOT held by the writing thread."""
    real = cluster._indices_lock
    tls = threading.local()

    class _LockProxy:
        def __enter__(self):
            real.acquire()
            tls.depth = getattr(tls, "depth", 0) + 1
            return self

        def __exit__(self, *exc):
            tls.depth -= 1
            real.release()
            return False

    violations = []
    base = cluster.__class__

    class _Instrumented(base):
        def __setattr__(self, name, value):
            if name in GUARDED and not getattr(tls, "depth", 0):
                violations.append(name)
            object.__setattr__(self, name, value)

    cluster._indices_lock = _LockProxy()
    cluster.__class__ = _Instrumented
    return violations


@pytest.fixture()
def pair():
    from elasticsearch_tpu.cluster.bootstrap import MultiHostCluster
    from elasticsearch_tpu.node import Node
    from elasticsearch_tpu.utils.faults import FAULTS

    port = _free_port()
    node0 = Node(name="rr-rank0")
    c0 = MultiHostCluster(node0, rank=0, world=2, transport_port=port,
                          ping_interval=0)
    node1 = Node(name="rr-rank1")
    c1 = MultiHostCluster(node1, rank=1, world=2, transport_port=port,
                          ping_interval=0)
    yield c0, c1
    FAULTS.clear()
    try:
        c1.close()
    finally:
        c0.close()
        node1.close()
        node0.close()


def test_publish_commit_pair_updates_hold_indices_lock(pair):
    c0, c1 = pair
    v0, v1 = _instrument(c0), _instrument(c1)
    c0.data.create_index("rlk", {"settings": {"number_of_shards": 1,
                                              "number_of_replicas": 0}})
    c0.data.index_doc("rlk", "1", {"v": 1})
    assert v0 == [], f"unlocked commit-metadata writes on master: {v0}"
    assert v1 == [], f"unlocked commit-metadata writes on follower: {v1}"
    # the committed (key, content) pair the lock protects is coherent:
    # _on_meta's advertised key matches the snapshot it serves
    got = c1._on_meta({})
    assert (got["meta_term"], got["indices_version"]) == c1._committed_meta
    assert "rlk" in got["indices"]


def test_takeover_meta_term_stamp_holds_indices_lock(pair):
    c0, c1 = pair
    v1 = _instrument(c1)
    term = c1.node.cluster_state.term + 1
    # local-copy takeover (best_meta address None): the non-master wins
    # an election and stamps _meta_term — the write R015 flagged
    assert c1._takeover(term, (0, 0, None), voters=[])
    assert v1 == [], f"unlocked commit-metadata writes in takeover: {v1}"
    assert c1.is_master
    assert c1._meta_term == term


def test_watcher_readd_during_poll_keeps_new_listeners(tmp_path):
    """Deterministic interleave of the R016 window: a path is removed
    and re-added (fresh listener list) between check_now()'s snapshot
    and its act region. The fixed act re-reads the current list under
    the lock; the old code wrote the snapshot's stale list back and the
    re-added listener never fired again."""
    svc = ResourceWatcherService()
    path = str(tmp_path / "w.txt")
    with open(path, "w") as fh:
        fh.write("a")
    os.utime(path, (1_000_000, 1_000_000))
    old_events, new_events = [], []
    svc.add(path, lambda p, e: old_events.append(e))

    fired = {"done": False}

    def hooked(p):  # instance attr shadows the staticmethod
        mt = ResourceWatcherService._mtime(p)
        if not fired["done"]:
            fired["done"] = True
            # the interleaved remove+re-add, exactly in the window
            # between the snapshot and the guarded act
            svc.remove(p)
            svc.add(p, lambda pp, e: new_events.append(e))
        return mt

    svc._mtime = hooked
    os.utime(path, (1_000_010, 1_000_010))
    assert svc.check_now() >= 1          # old listener sees this change
    assert old_events == ["changed"]
    os.utime(path, (1_000_020, 1_000_020))
    svc.check_now()
    # the re-added listener survived the concurrent poll round: it sees
    # the SECOND change (stale-list write-back lost it entirely)
    assert new_events == ["changed"], \
        "re-added listener was dropped by the stale-snapshot write-back"
    assert old_events == ["changed"]     # the removed one stayed removed
