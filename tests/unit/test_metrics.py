"""Continuous metrics substrate (monitor/metrics.py + consumers).

Covers the ISSUE-7 acceptance surface: histogram bucket/percentile math,
Prometheus text-exposition well-formedness (parsed by a strict
mini-parser, label escaping round-trip), the tracer-sink span→histogram
flow, a mixed search+index workload scrape containing the required
families, /_cluster/stats fan-out over an in-process 2-node cluster, the
per-node scrape after a distributed search, hot-threads sampling
semantics, _cat/thread_pool h=/largest, and the bench metrics-delta
helpers.
"""
import json
import re
import socket
import threading
import time

import pytest

from elasticsearch_tpu.monitor.metrics import (DEFAULT_LATENCY_BUCKETS,
                                               Histogram, MetricsRegistry,
                                               OVERFLOW_LABEL, SHARED,
                                               counters_delta,
                                               escape_label_value,
                                               process_counters, span_sink)
from elasticsearch_tpu.node import Node
from elasticsearch_tpu.rest.server import RestController


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


# ---------------------------------------------------------------------------
# a strict exposition-format parser (the round-trip the acceptance demands)
# ---------------------------------------------------------------------------

_SAMPLE_RE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(?:\{(.*)\})? (\+Inf|-?[0-9][0-9.e+-]*)$")
_LABEL_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')


def _unescape(v: str) -> str:
    return v.replace("\\n", "\n").replace('\\"', '"').replace("\\\\", "\\")


def parse_exposition(text: str):
    """(types, helps, samples) or raise — every line must be a comment,
    blank, or a well-formed sample; every sample's base family must have
    a preceding # TYPE."""
    types, helps = {}, {}
    samples = []  # (name, labels dict, float value)
    for line in text.splitlines():
        if not line.strip():
            continue
        if line.startswith("# TYPE "):
            _, _, name, kind = line.split(" ", 3)
            assert kind in ("counter", "gauge", "histogram"), line
            types[name] = kind
            continue
        if line.startswith("# HELP "):
            _, _, name, h = line.split(" ", 3)
            helps[name] = h
            continue
        assert not line.startswith("#"), f"unknown comment: {line}"
        m = _SAMPLE_RE.match(line)
        assert m, f"malformed sample line: {line!r}"
        name, rawlabels, value = m.groups()
        labels = {}
        if rawlabels:
            consumed = 0
            for lm in _LABEL_RE.finditer(rawlabels):
                labels[lm.group(1)] = _unescape(lm.group(2))
                consumed = lm.end()
            leftover = rawlabels[consumed:].strip(", ")
            assert not leftover, f"unparsed labels {leftover!r} in {line!r}"
        base = re.sub(r"_(bucket|sum|count)$", "", name)
        assert base in types or name in types, \
            f"sample {name} has no # TYPE"
        samples.append((name, labels,
                        float("inf") if value == "+Inf" else float(value)))
    return types, helps, samples


def sample_value(samples, name, **labels):
    for n, ls, v in samples:
        if n == name and all(ls.get(k) == str(w) for k, w in labels.items()):
            return v
    return None


# ---------------------------------------------------------------------------
# histogram math
# ---------------------------------------------------------------------------

class TestHistogram:
    def test_bucketing_and_counts(self):
        h = Histogram(DEFAULT_LATENCY_BUCKETS)
        for _ in range(50):
            h.observe(0.001)
        for _ in range(40):
            h.observe(0.01)
        for _ in range(10):
            h.observe(0.1)
        assert h.count == 100
        assert abs(h.sum - (50 * 0.001 + 40 * 0.01 + 10 * 0.1)) < 1e-9
        assert h.max == pytest.approx(0.1)

    def test_percentiles_interpolate_within_bucket(self):
        h = Histogram(DEFAULT_LATENCY_BUCKETS)
        for _ in range(50):
            h.observe(0.001)
        for _ in range(40):
            h.observe(0.01)
        for _ in range(10):
            h.observe(0.1)
        # p50 falls in 0.001's bucket (bounds 0.0008 .. 0.0016)
        assert 0.0008 <= h.percentile(50) <= 0.0016
        # p99 falls in 0.1's bucket, clamped by the exact max
        assert 0.05 <= h.percentile(99) <= 0.1
        assert h.percentile(100) == pytest.approx(0.1)

    def test_all_zero_observations_clamp_to_max(self):
        # p50 interpolating inside bucket 0 must not exceed the exact
        # max of 0.0 (the "estimate never exceeds max" invariant)
        h = Histogram(DEFAULT_LATENCY_BUCKETS)
        for _ in range(3):
            h.observe(0.0)
        assert h.percentile(50) == 0.0
        s = h.summary()
        assert s["p50_seconds"] <= s["max_seconds"] == 0.0

    def test_empty_and_single(self):
        h = Histogram(DEFAULT_LATENCY_BUCKETS)
        assert h.percentile(99) == 0.0
        h.observe(0.0042)
        assert 0.0 < h.percentile(50) <= 0.0064
        s = h.summary()
        assert s["count"] == 1 and s["max_seconds"] == pytest.approx(0.0042)

    def test_overflow_bucket_beyond_top_bound(self):
        h = Histogram((0.001, 0.01))
        h.observe(5.0)  # past every finite bound
        assert h.counts[-1] == 1
        # estimated inside the (top bound, exact max] overflow bucket
        assert 0.01 < h.percentile(99) <= 5.0
        assert h.percentile(100) == pytest.approx(5.0)


class TestRegistry:
    def test_counter_gauge_and_labels(self):
        r = MetricsRegistry()
        c = r.counter("t_total", "help", ("k",))
        c.labels("a").inc()
        c.labels("a").inc(2)
        c.labels("b").inc()
        g = r.gauge("t_gauge", "help")
        g.set(42)
        vals = r.counter_values()
        assert vals['t_total{k="a"}'] == 3
        assert vals['t_total{k="b"}'] == 1

    def test_family_is_idempotent_by_name(self):
        r = MetricsRegistry()
        a = r.counter("x_total", "h", ("k",))
        b = r.counter("x_total", "different help ignored", ("k",))
        assert a is b

    def test_label_cardinality_cap_collapses_to_overflow(self):
        r = MetricsRegistry()
        c = r.counter("capped_total", "h", ("k",), max_series=2)
        for i in range(6):
            c.labels(f"v{i}").inc()
        series = c.series()
        assert len(series) <= 3  # 2 real + the overflow bucket
        assert any(lv == (OVERFLOW_LABEL,) for lv, _ in series)
        # no count lost: everything past the cap landed in _other_
        assert sum(ch.value for _, ch in series) == 6


# ---------------------------------------------------------------------------
# exposition well-formedness
# ---------------------------------------------------------------------------

class TestExposition:
    def test_roundtrip_counter_gauge_histogram(self):
        r = MetricsRegistry()
        r.counter("a_total", "counts a", ("k",)).labels("x").inc(3)
        r.gauge("b_bytes", "bytes of b").set(1.5)
        h = r.histogram("c_seconds", "latency of c", ("op",))
        h.labels("read").observe(0.003)
        h.labels("read").observe(0.3)
        types, helps, samples = parse_exposition(r.expose())
        assert types == {"a_total": "counter", "b_bytes": "gauge",
                         "c_seconds": "histogram"}
        assert helps["a_total"] == "counts a"
        assert sample_value(samples, "a_total", k="x") == 3
        assert sample_value(samples, "b_bytes") == 1.5
        assert sample_value(samples, "c_seconds_count", op="read") == 2
        assert sample_value(
            samples, "c_seconds_sum", op="read") == pytest.approx(0.303)
        # bucket lines are CUMULATIVE and end at +Inf == count
        buckets = [(ls["le"], v) for n, ls, v in samples
                   if n == "c_seconds_bucket"]
        assert buckets[-1][0] == "+Inf" and buckets[-1][1] == 2
        cum = [v for _, v in buckets]
        assert cum == sorted(cum), "bucket counts must be cumulative"

    def test_label_escaping_roundtrip(self):
        ugly = 'a"b\\c\nd'
        assert escape_label_value(ugly) == 'a\\"b\\\\c\\nd'
        r = MetricsRegistry()
        r.counter("esc_total", "h", ("k",)).labels(ugly).inc()
        _, _, samples = parse_exposition(r.expose())
        assert sample_value(samples, "esc_total", k=ugly) == 1

    def test_help_newline_escaped(self):
        r = MetricsRegistry()
        r.counter("nl_total", "line1\nline2").inc()
        text = r.expose()
        assert "# HELP nl_total line1\\nline2" in text
        parse_exposition(text)  # single-line HELP parses


# ---------------------------------------------------------------------------
# tracer sink
# ---------------------------------------------------------------------------

class TestSpanSink:
    def test_finished_spans_land_in_histogram(self):
        from elasticsearch_tpu.tracing import Tracer

        r = MetricsRegistry()
        t = Tracer("n1")
        t.set_sink(span_sink(r))
        with t.span("phase.alpha"):
            pass
        with t.span("phase.alpha"):
            with t.span("phase.beta"):
                pass
        _, _, samples = parse_exposition(r.expose())
        assert sample_value(samples, "estpu_span_duration_seconds_count",
                            span="phase.alpha") == 2
        assert sample_value(samples, "estpu_span_duration_seconds_count",
                            span="phase.beta") == 1

    def test_error_spans_counted_and_sink_failure_is_swallowed(self):
        from elasticsearch_tpu.tracing import Tracer

        r = MetricsRegistry()
        t = Tracer("n1")
        t.set_sink(span_sink(r))
        with pytest.raises(ValueError):
            with t.span("phase.err"):
                raise ValueError("boom")
        _, _, samples = parse_exposition(r.expose())
        assert sample_value(samples, "estpu_span_errors_total",
                            span="phase.err") == 1
        # a broken sink must not break spans
        t.set_sink(lambda sp: 1 / 0)
        with t.span("phase.ok"):
            pass
        assert t.stats()["finished_total"] == 2


# ---------------------------------------------------------------------------
# the acceptance scrape: mixed search+index workload
# ---------------------------------------------------------------------------

@pytest.fixture()
def workload_node(tmp_path):
    n = Node(name="metrics-node", data_path=str(tmp_path))
    n.create_index("logs", {
        "settings": {"number_of_shards": 1},
        "mappings": {"properties": {"msg": {"type": "string"},
                                    "v": {"type": "integer"}}}})
    rc = RestController(n)
    for i in range(8):
        s, _ = rc.dispatch("PUT", f"/logs/_doc/{i}", {},
                           json.dumps({"msg": "hello world", "v": i}).encode())
        assert s in (200, 201)
    s, _ = rc.dispatch("POST", "/logs/_refresh", {}, b"")
    assert s == 200
    body = b'{"query": {"match": {"msg": "hello"}}}'
    for _ in range(4):
        s, r = rc.dispatch("POST", "/logs/_search", {}, body)
        assert s == 200 and r["hits"]["total"] == 8
    yield n, rc
    n.close()


class TestScrape:
    def test_wellformed_and_required_families(self, workload_node):
        n, rc = workload_node
        s, text = rc.dispatch("GET", "/_prometheus/metrics", {}, b"")
        assert s == 200 and isinstance(text, str)
        types, _, samples = parse_exposition(text)

        # search-latency histogram with populated buckets
        assert types["estpu_rest_request_duration_seconds"] == "histogram"
        inf = sample_value(samples,
                           "estpu_rest_request_duration_seconds_bucket",
                           endpoint="/{index}/_search", method="POST",
                           le="+Inf")
        assert inf == 4
        # per-endpoint request counters with status class
        assert types["estpu_rest_requests_total"] == "counter"
        assert sample_value(samples, "estpu_rest_requests_total",
                            endpoint="/{index}/_search", method="POST",
                            status="2xx") == 4
        assert sample_value(samples, "estpu_rest_requests_total",
                            endpoint="/{index}/_doc/{id}", method="PUT",
                            status="2xx") == 8
        # breaker used-bytes gauges (all five breakers)
        assert types["estpu_breaker_used_bytes"] == "gauge"
        for br in ("parent", "fielddata", "request", "in_flight_requests",
                   "segments"):
            assert sample_value(samples, "estpu_breaker_used_bytes",
                                breaker=br) is not None, br
        # threadpool queue + rejected counters
        assert sample_value(samples, "estpu_threadpool_queue_depth",
                            pool="search") is not None
        assert types["estpu_threadpool_rejected_total"] == "counter"
        assert sample_value(samples, "estpu_threadpool_rejected_total",
                            pool="search") is not None
        # jit compile counter
        assert types["estpu_jit_traces_total"] == "counter"
        assert sample_value(samples, "estpu_jit_traces_total") >= 0
        # span histogram fed by the tracer sink (search spans exist)
        assert sample_value(samples, "estpu_span_duration_seconds_count",
                            span="search") >= 4
        # write path: indexing ops + translog fsync (disk-backed index)
        assert sample_value(samples, "estpu_indexing_operations_total",
                            op="index") == 8
        assert sample_value(samples,
                            "estpu_translog_fsyncs_total") >= 8

    def test_nodes_stats_carries_percentile_summaries(self, workload_node):
        n, rc = workload_node
        s, st = rc.dispatch("GET", "/_nodes/stats", {}, b"")
        assert s == 200
        mets = st["nodes"][n.node_id]["metrics"]
        fam = mets["estpu_rest_request_duration_seconds"]
        row = next(r for r in fam
                   if r["labels"]["endpoint"] == "/{index}/_search")
        assert row["count"] == 4
        assert 0 < row["p50_seconds"] <= row["p99_seconds"]
        assert row["p99_seconds"] <= row["max_seconds"] * 1.0001

    def test_status_classes_split(self, workload_node):
        n, rc = workload_node
        s, _ = rc.dispatch("GET", "/nope/_doc/1", {}, b"")
        assert s == 404
        s, text = rc.dispatch("GET", "/_prometheus/metrics", {}, b"")
        _, _, samples = parse_exposition(text)
        assert sample_value(samples, "estpu_rest_requests_total",
                            endpoint="/{index}/_doc/{id}", method="GET",
                            status="4xx") == 1


# ---------------------------------------------------------------------------
# cluster stats fan-out + per-node scrape over a real 2-node cluster
# ---------------------------------------------------------------------------

@pytest.fixture()
def two_node_cluster():
    """Two MultiHostClusters in-process over real TCP (the
    test_observability/test_faults harness): rank 0 is the
    master+coordinator, rank 1 owns half the shards."""
    from elasticsearch_tpu.cluster.bootstrap import MultiHostCluster

    port = _free_port()
    node0 = Node(name="rank0")
    c0 = MultiHostCluster(node0, rank=0, world=2, transport_port=port,
                          ping_interval=0)
    node1 = Node(name="rank1")
    c1 = MultiHostCluster(node1, rank=1, world=2, transport_port=port)
    c0.data.create_index("evt", {
        "settings": {"number_of_shards": 2},
        "mappings": {"properties": {"n": {"type": "integer"}}}})
    assig = c0.dist_indices["evt"]["assignment"]
    assert len({o[0] for o in assig.values()}) == 2, assig
    for i in range(24):
        c0.data.index_doc("evt", str(i), {"n": i})
    c0.data.refresh("evt")
    yield c0, c1
    try:
        c1.close()
    finally:
        c0.close()
        node1.close()
        node0.close()


class TestClusterStats:
    def test_single_node_shape(self):
        n = Node(name="cs1")
        n.create_index("a", {"settings": {"number_of_shards": 1}})
        n.indices["a"].index_doc("1", {"x": 1})
        n.indices["a"].refresh()
        rc = RestController(n)
        s, cs = rc.dispatch("GET", "/_cluster/stats", {}, b"")
        assert s == 200
        assert cs["indices"]["count"] == 1
        assert cs["indices"]["docs"]["count"] == 1
        assert cs["indices"]["segments"]["count"] >= 1
        assert cs["nodes"]["count"]["total"] == 1
        assert cs["nodes"]["process"]["mem"]["resident_in_bytes"] > 0
        assert cs["status"] in ("green", "yellow", "red")
        assert "_index_names" not in cs
        n.close()

    def test_docs_count_primaries_only(self):
        # replicas hold the same documents: docs.count must not inflate
        # by the replication factor (store/segments DO count every copy)
        n = Node(name="cs-repl")
        n.create_index("r", {"settings": {"number_of_shards": 1,
                                          "number_of_replicas": 1}})
        for i in range(3):
            n.indices["r"].index_doc(str(i), {"x": i})
        n.indices["r"].refresh()
        rc = RestController(n)
        s, cs = rc.dispatch("GET", "/_cluster/stats", {}, b"")
        assert s == 200
        assert cs["indices"]["docs"]["count"] == 3
        assert cs["indices"]["shards"]["primaries"] == 1
        assert cs["indices"]["shards"]["total"] == 2
        n.close()

    def test_fanout_aggregates_both_members(self, two_node_cluster):
        c0, c1 = two_node_cluster
        r = c0.data.search("evt", {"size": 24})
        assert r["hits"]["total"] == 24
        # an index that exists ONLY on the remote member must still be
        # counted by the coordinator's index-name union
        c1.node.create_index("only1", {"settings": {"number_of_shards": 1}})
        c1.node.indices["only1"].index_doc("1", {"z": 1})
        c1.node.indices["only1"].refresh()
        rc = RestController(c0.node)
        s, cs = rc.dispatch("GET", "/_cluster/stats", {}, b"")
        assert s == 200
        # both members counted; the distributed index counted ONCE, the
        # remote-only local index counted too
        assert cs["nodes"]["count"]["total"] == 2
        assert cs["indices"]["count"] == 2
        # docs live on their owner processes; the fan-out sums them all
        assert cs["indices"]["docs"]["count"] == 25
        # shards from both owners
        assert cs["indices"]["shards"]["total"] >= 3
        assert cs["nodes"]["thread_pool"]["completed"] >= 0
        assert "_index_names" not in cs

    def test_each_member_scrape_reflects_the_distributed_search(
            self, two_node_cluster):
        c0, c1 = two_node_cluster
        r = c0.data.search("evt", {"size": 24})
        assert r["hits"]["total"] == 24
        # coordinator side: its scrape shows the coordinate span + tx bytes
        _, _, s0 = parse_exposition(
            RestController(c0.node).dispatch(
                "GET", "/_prometheus/metrics", {}, b"")[1])
        assert sample_value(s0, "estpu_span_duration_seconds_count",
                            span="search.coordinate") >= 1
        assert sample_value(s0, "estpu_transport_bytes_total",
                            direction="tx") > 0
        # remote owner side: ITS scrape shows the shard query work it
        # served and the frames it received — per-node registries stay
        # per-node even in-process
        _, _, s1 = parse_exposition(
            RestController(c1.node).dispatch(
                "GET", "/_prometheus/metrics", {}, b"")[1])
        assert sample_value(s1, "estpu_span_duration_seconds_count",
                            span="shard.query_phase") >= 1
        assert sample_value(s1, "estpu_span_duration_seconds_count",
                            span="transport.handle") >= 1
        assert sample_value(s1, "estpu_transport_bytes_total",
                            direction="rx") > 0
        # per-action transport latency recorded on the coordinator
        q_act = "indices:data/read/search[phase/query]"
        assert sample_value(
            s0, "estpu_transport_action_duration_seconds_count",
            action=q_act) >= 1


# ---------------------------------------------------------------------------
# hot threads sampling + _cat/thread_pool satellites
# ---------------------------------------------------------------------------

class TestHotThreads:
    def test_sampling_collates_stacks_busiest_first(self):
        n = Node(name="ht-node")
        rc = RestController(n)
        stop = threading.Event()

        def burn():
            x = 0
            while not stop.is_set():
                x += 1
            return x

        t = threading.Thread(target=burn, name="busy-burner", daemon=True)
        t.start()
        try:
            s, text = rc.dispatch(
                "GET", "/_nodes/hot_threads",
                {"interval": "10ms", "snapshots": "4", "threads": "8"}, b"")
        finally:
            stop.set()
            t.join(timeout=2)
            n.close()
        assert s == 200
        assert text.startswith(f"::: {{{n.name}}}")
        assert "snapshots=4" in text
        assert "busy-burner" in text
        # collation lines: M/N snapshots sharing following K elements
        m = re.search(r"(\d+)/4 snapshots sharing following (\d+) elements",
                      text)
        assert m and 1 <= int(m.group(1)) <= 4
        # the burner is 100% busy across samples
        assert re.search(r"100\.0% \(4 out of 4 snapshots non-idle\) usage "
                         r"by thread 'busy-burner'", text)

    def test_idle_threads_filtered_unless_asked(self):
        n = Node(name="ht2-node")
        rc = RestController(n)
        try:
            _, with_idle = rc.dispatch(
                "GET", "/_nodes/hot_threads",
                {"interval": "5ms", "snapshots": "2", "threads": "64",
                 "ignore_idle_threads": "false"}, b"")
            _, without = rc.dispatch(
                "GET", "/_nodes/hot_threads",
                {"interval": "5ms", "snapshots": "2", "threads": "64"}, b"")
        finally:
            n.close()
        # pool workers parked in queue.get are idle: reported only when
        # ignore_idle_threads=false
        assert with_idle.count("usage by thread") > \
            without.count("usage by thread")


class TestCatThreadPool:
    def test_pool_rows_include_largest_and_h_selection(self):
        from elasticsearch_tpu.rest.server import _cat_json_rows, _cat_table

        n = Node(name="ctp-node")
        rc = RestController(n)
        try:
            s, rows = rc.dispatch("GET", "/_cat/thread_pool",
                                  {"pools": "true"}, b"")
            assert s == 200
            by_name = {r["name"]: r for r in rows}
            assert "largest" in by_name["search"]
            assert "queue_size" in by_name["search"]
            assert by_name["management"]["largest"] >= 1  # ran this request
            # format=json keeps the full declared column set (threads/
            # queue_size must not vanish for existing consumers)
            json_rows = _cat_json_rows(rows, {})
            assert {"name", "threads", "queue_size", "largest",
                    "completed"} <= set(json_rows[0])
            # h= selects columns through the one serialization layer
            # (the same path every other _cat endpoint uses over HTTP)
            sel = _cat_json_rows(rows, {"h": "name,largest"})
            assert all(set(r.keys()) == {"name", "largest"} for r in sel)
            # unknown h columns silently drop (RestTable semantics)
            sel2 = _cat_json_rows(rows, {"h": "name,frobnicate"})
            assert all(set(r.keys()) == {"name"} for r in sel2)
            # text table form honors h= too
            table = _cat_table(rows, {"h": "name,largest", "v": "true"})
            assert table.splitlines()[0].split() == ["name", "largest"]
        finally:
            n.close()


# ---------------------------------------------------------------------------
# bench delta helpers
# ---------------------------------------------------------------------------

class TestBenchDelta:
    def test_process_counters_and_delta(self):
        from elasticsearch_tpu.monitor import kernels

        before = process_counters()
        assert "kernels.executor_prep_hit" in before
        assert "jit.traces_total" in before
        kernels.record("executor_prep_hit")
        kernels.record("executor_prep_miss", 2)
        after = process_counters()
        d = counters_delta(before, after)
        assert d["kernels.executor_prep_hit"] == 1
        assert d["kernels.executor_prep_miss"] == 2

    def test_unknown_sentinel_becomes_typed_null(self):
        # the -1 snapshot sentinel (trace auditor absent) must surface
        # as None (JSON null) in the delta — unavailable, never a number
        # a consumer could mix into arithmetic (and never a fake 0)
        d = counters_delta({"jit.traces_total": -1.0},
                           {"jit.traces_total": -1.0})
        assert d["jit.traces_total"] is None
        d = counters_delta({"jit.traces_total": -1.0},
                           {"jit.traces_total": 5.0})
        assert d["jit.traces_total"] is None
        d = counters_delta({"a": None}, {"a": 3.0})
        assert d["a"] is None

    def test_shared_registry_counters_in_snapshot(self):
        SHARED.counter("estpu_test_shared_total", "t").inc(3)
        snap = process_counters()
        assert snap.get("estpu_test_shared_total") >= 3
