"""Hybrid retrieval (ISSUE 19): fused lexical+vector stage 1, MaxSim
stage 2.

Acceptance surface: (a) a hybrid search returns byte-identical hits to a
host numpy fusion of the two engines' exact scores (RRF and linear, the
dense-impact gather AND the scatter stage-1 variants), (b) stage 1 is
ONE device program per segment shape class and a fusion-parameter sweep
never retraces (R017 proof via hybrid.TRACE_COUNTS), (c) the coalesced
batch tier returns the sequential results, (d) a stage-2 breaker denial
degrades to stage-1 results with a typed partial response — never a 500,
and (e) knn/maxsim rescore bodies route through the stage-2 window
re-rank with the same degrade contract.
"""
import numpy as np
import pytest

from elasticsearch_tpu.monitor import kernels
from elasticsearch_tpu.node import Node

DIMS = 8


# ---------------------------------------------------------------------------
# host reference fusion (numpy mirror of search/hybrid._fuse_math)
# ---------------------------------------------------------------------------

def _rrf_ref(scores, mask, rank_constant, weight):
    key = np.where(mask, scores, -np.inf).astype(np.float32)
    order = np.argsort(-key, kind="stable")
    rank = np.argsort(order, kind="stable")
    contrib = np.where(
        mask,
        np.float32(1.0) / (np.float32(rank_constant) + np.float32(1.0)
                           + rank.astype(np.float32)),
        np.float32(0.0)).astype(np.float32)
    return (np.float32(weight) * contrib).astype(np.float32)


def _fuse_ref(ls, lm, vs, vm, method, weights, rank_constant):
    if method == "linear":
        fused = (np.float32(weights[0]) * np.where(lm, ls, np.float32(0))
                 + np.float32(weights[1]) * np.where(vm, vs, np.float32(0)))
    else:
        fused = (_rrf_ref(ls, lm, rank_constant, weights[0])
                 + _rrf_ref(vs, vm, rank_constant, weights[1]))
    return fused.astype(np.float32), lm | vm


def _engine_scores(n, index, lex_query, qvec, num_candidates, n_docs,
                   vboost=1.0):
    """Exact per-engine dense score vectors via oversized single-engine
    searches on the SAME index (same idf, same segment layout)."""
    lex = n.search(index, {"query": lex_query, "size": n_docs})
    ls = np.zeros(n_docs, np.float32)
    lm = np.zeros(n_docs, bool)
    for h in lex["hits"]["hits"]:
        ls[int(h["_id"])] = np.float32(h["_score"])
        lm[int(h["_id"])] = True
    knn = n.search(index, {"query": {"knn": {
        "field": "emb", "query_vector": [float(x) for x in qvec],
        "k": n_docs, "num_candidates": n_docs}}, "size": n_docs})
    vs = np.zeros(n_docs, np.float32)
    for h in knn["hits"]["hits"]:
        vs[int(h["_id"])] = np.float32(h["_score"])
    # the hybrid candidate cutoff: top num_candidates by (-score, id)
    order = np.argsort(-vs, kind="stable")
    rank = np.argsort(order, kind="stable")
    vm = rank < num_candidates
    return ls, lm, (vs * np.float32(vboost)).astype(np.float32), vm


def _ref_hits(fused, mask, k):
    eff = np.where(mask, fused, -np.inf)
    top = np.lexsort((np.arange(fused.size), -eff))[:k]
    top = [int(i) for i in top if np.isfinite(eff[i])]
    return [(str(i), float(fused[i])) for i in top], int(mask.sum())


def _got_hits(r):
    return [(h["_id"], float(h["_score"])) for h in r["hits"]["hits"]]


@pytest.fixture(scope="module")
def dense_corpus():
    """320 docs; "alpha" in ≥ df_threshold docs so the lexical side takes
    the dense-impact gather program."""
    rng = np.random.RandomState(42)
    V = rng.randn(320, DIMS).astype(np.float32)
    n = Node()
    n.create_index("hyb", {"settings": {"number_of_shards": 1},
                           "mappings": {"properties": {
                               "emb": {"type": "dense_vector",
                                       "dims": DIMS},
                               "body": {"type": "text"}}}})
    svc = n.indices["hyb"]
    for i in range(320):
        words = []
        if rng.rand() < 0.85:
            words.append("alpha")
        if rng.rand() < 0.55:
            words.append("beta")
        if not words:
            words = ["gamma"]
        svc.index_doc(str(i), {"emb": [float(x) for x in V[i]],
                               "body": " ".join(words)})
    svc.refresh()
    yield n, V, 320
    n.close()


@pytest.fixture(scope="module")
def sparse_corpus():
    """120 docs with rare terms: no dense impact rows → the scatter
    stage-1 variant."""
    rng = np.random.RandomState(7)
    V = rng.randn(120, DIMS).astype(np.float32)
    n = Node()
    n.create_index("hys", {"settings": {"number_of_shards": 1},
                           "mappings": {"properties": {
                               "emb": {"type": "dense_vector",
                                       "dims": DIMS},
                               "body": {"type": "text"}}}})
    svc = n.indices["hys"]
    words = ["quick", "brown", "fox", "lazy", "dog"]
    for i in range(120):
        t = " ".join(rng.choice(words, size=rng.randint(1, 4)))
        svc.index_doc(str(i), {"emb": [float(x) for x in V[i]],
                               "body": t})
    svc.refresh()
    yield n, V, 120
    n.close()


def _hybrid_body(qvec, method="rrf", weights=(1.0, 1.0), rank_constant=60.0,
                 nc=50, k=10, lex="alpha beta", boost=1.0, size=10):
    return {"query": {"hybrid": {
        "query": {"match": {"body": lex}},
        "knn": {"field": "emb", "query_vector": [float(x) for x in qvec],
                "k": k, "num_candidates": nc, "boost": boost},
        "fusion": {"method": method, "weights": list(weights),
                   "rank_constant": rank_constant},
    }}, "size": size}


# ---------------------------------------------------------------------------
# stage-1 byte-identity vs host reference fusion
# ---------------------------------------------------------------------------

class TestStage1Parity:
    def test_rrf_byte_identical_dense_gather_variant(self, dense_corpus):
        n, V, N = dense_corpus
        rng = np.random.RandomState(1)
        for trial in range(3):
            qv = rng.randn(DIMS).astype(np.float32)
            nc, rc, w = 40 + 10 * trial, 10.0 + trial, (1.0, 1.5 + trial)
            before = kernels.snapshot().get("hybrid_fused_topk", 0)
            r = n.search("hyb", _hybrid_body(qv, "rrf", w, rc, nc=nc))
            assert kernels.snapshot().get("hybrid_fused_topk", 0) > before
            ls, lm, vs, vm = _engine_scores(
                n, "hyb", {"match": {"body": "alpha beta"}}, qv, nc, N)
            fused, mask = _fuse_ref(ls, lm, vs, vm, "rrf", w, rc)
            ref, tot = _ref_hits(fused, mask, 10)
            assert _got_hits(r) == ref, trial
            assert r["hits"]["total"] == tot

    def test_linear_byte_identical_with_knn_boost(self, dense_corpus):
        n, V, N = dense_corpus
        qv = np.random.RandomState(2).randn(DIMS).astype(np.float32)
        r = n.search("hyb", _hybrid_body(qv, "linear", (0.3, 2.0), nc=60,
                                         boost=1.7))
        ls, lm, vs, vm = _engine_scores(
            n, "hyb", {"match": {"body": "alpha beta"}}, qv, 60, N,
            vboost=1.7)
        fused, mask = _fuse_ref(ls, lm, vs, vm, "linear", (0.3, 2.0), 60.0)
        ref, tot = _ref_hits(fused, mask, 10)
        assert _got_hits(r) == ref
        assert r["hits"]["total"] == tot

    def test_rrf_byte_identical_scatter_variant(self, sparse_corpus):
        n, V, N = sparse_corpus
        qv = np.random.RandomState(3).randn(DIMS).astype(np.float32)
        from elasticsearch_tpu.search.hybrid import TRACE_COUNTS

        r = n.search("hys", _hybrid_body(qv, "rrf", (1.0, 1.0), 20.0,
                                         nc=30, lex="quick fox"))
        assert TRACE_COUNTS["hybrid_fused_topk_scatter"] >= 1
        ls, lm, vs, vm = _engine_scores(
            n, "hys", {"match": {"body": "quick fox"}}, qv, 30, N)
        fused, mask = _fuse_ref(ls, lm, vs, vm, "rrf", (1.0, 1.0), 20.0)
        ref, tot = _ref_hits(fused, mask, 10)
        assert _got_hits(r) == ref
        assert r["hits"]["total"] == tot

    def test_generic_fallback_parity_with_fast_path(self, dense_corpus):
        """min_score disables the fused fast path → HybridQuery.execute
        (each engine its own program + one fusion program). Same ids and
        ordering; scores agree to fp rounding (the lexical gather and
        matmul forms reassociate differently)."""
        n, V, N = dense_corpus
        qv = np.random.RandomState(4).randn(DIMS).astype(np.float32)
        fast = n.search("hyb", _hybrid_body(qv, "rrf", (1.0, 2.0), 30.0))
        body = _hybrid_body(qv, "rrf", (1.0, 2.0), 30.0)
        body["min_score"] = 0.0
        generic = n.search("hyb", body)
        assert [h[0] for h in _got_hits(generic)] == \
            [h[0] for h in _got_hits(fast)]
        np.testing.assert_allclose(
            [h[1] for h in _got_hits(generic)],
            [h[1] for h in _got_hits(fast)], rtol=1e-6)
        assert generic["hits"]["total"] == fast["hits"]["total"]

    def test_tie_discipline_matches_lax_top_k(self):
        """All-identical docs tie on the fused score: the returned order
        must be ascending doc id — exactly lax.top_k's first-occurrence
        tie break, and the (-score, id) host discipline."""
        n = Node()
        n.create_index("ties", {"settings": {"number_of_shards": 1},
                                "mappings": {"properties": {
                                    "emb": {"type": "dense_vector",
                                            "dims": DIMS},
                                    "body": {"type": "text"}}}})
        svc = n.indices["ties"]
        for i in range(40):
            svc.index_doc(str(i), {"emb": [1.0] * DIMS, "body": "same"})
        svc.refresh()
        for method in ("rrf", "linear"):
            r = n.search("ties", _hybrid_body(
                np.ones(DIMS), method, lex="same", nc=40))
            ids = [int(h["_id"]) for h in r["hits"]["hits"]]
            if method == "linear":
                assert ids == list(range(10)), method
            else:
                # RRF ranks of tied scores follow stable argsort order =
                # ascending id, so fused scores are strictly decreasing
                # in id and the top-10 is still ids 0..9
                assert ids == list(range(10)), method
        n.close()


# ---------------------------------------------------------------------------
# one-program proof + R017 (trace counts)
# ---------------------------------------------------------------------------

class TestTraceDiscipline:
    def test_stage1_is_one_program_and_weight_sweep_never_retraces(
            self, dense_corpus):
        n, V, N = dense_corpus
        from elasticsearch_tpu.search.hybrid import TRACE_COUNTS

        rng = np.random.RandomState(5)
        n.search("hyb", _hybrid_body(rng.randn(DIMS)))  # warm the program
        baseline = dict(TRACE_COUNTS)
        # sweep EVERY fusion operand: weights, rank_constant,
        # num_candidates, knn boost, query vector — all traced
        for t in range(4):
            r = n.search("hyb", _hybrid_body(
                rng.randn(DIMS), "rrf", (1.0 + t, 2.0 - 0.3 * t),
                rank_constant=5.0 + 7 * t, nc=25 + 5 * t,
                boost=0.5 + 0.25 * t))
            assert r["hits"]["hits"]
        assert dict(TRACE_COUNTS) == baseline, \
            "fusion-parameter sweep retraced a stage-1 program (R017)"
        # the sweep ran 4 full searches with zero new traces: every
        # segment round reused the ONE fused stage-1 program (other
        # tests' corpora have different static D, hence >= 1 overall)
        assert TRACE_COUNTS["hybrid_fused_topk"] >= 1


# ---------------------------------------------------------------------------
# coalesced / batched tier
# ---------------------------------------------------------------------------

class TestBatchedTier:
    def test_batch_bucket_key_and_solo_contracts(self, dense_corpus):
        n, V, N = dense_corpus
        from elasticsearch_tpu.search.batch import batch_field
        from elasticsearch_tpu.search.queries import parse_query

        svc = n.indices["hyb"]
        q = parse_query(_hybrid_body(V[0])["query"])
        assert batch_field(svc, q) == "__hybrid__:rrf:body:emb"
        # rerank bodies re-order per request → sequential
        body = _hybrid_body(V[0])
        body["query"]["hybrid"]["rerank"] = {
            "query_vectors": [[1.0] * DIMS], "window_size": 5}
        assert batch_field(svc, parse_query(body["query"])) is None
        # a knn filter de-amortizes too
        body2 = _hybrid_body(V[0])
        body2["query"]["hybrid"]["knn"]["filter"] = {
            "term": {"body": "alpha"}}
        assert batch_field(svc, parse_query(body2["query"])) is None

    def test_coalesced_batch_parity_with_sequential(self, dense_corpus):
        n, V, N = dense_corpus
        from elasticsearch_tpu.search.batch import execute_batch

        rng = np.random.RandomState(6)
        bodies = [_hybrid_body(rng.randn(DIMS), "rrf",
                               (1.0, 1.0 + t), rank_constant=60.0,
                               nc=30 + 10 * t, size=8)
                  for t in range(4)]
        svc = n.indices["hyb"]
        before = kernels.snapshot().get("hybrid_fused_batch", 0)
        batched = execute_batch(svc, bodies)
        assert batched is not None
        assert kernels.snapshot().get("hybrid_fused_batch", 0) > before
        for body, br in zip(bodies, batched):
            sr = n.search("hyb", body)
            assert [h["_id"] for h in br["hits"]["hits"]] == \
                [h["_id"] for h in sr["hits"]["hits"]]
            np.testing.assert_allclose(
                [h["_score"] for h in br["hits"]["hits"]],
                [h["_score"] for h in sr["hits"]["hits"]], rtol=1e-6)
            assert br["hits"]["total"] == sr["hits"]["total"]

    def test_coalesced_batch_parity_padded(self, dense_corpus):
        """pad_pow2=True is the coalescer's flush shape — results must
        stay identical to the unpadded batch."""
        n, V, N = dense_corpus
        from elasticsearch_tpu.search.batch import execute_batch

        rng = np.random.RandomState(8)
        bodies = [_hybrid_body(rng.randn(DIMS), "linear", (0.5, 1.5),
                               size=6) for _ in range(3)]
        svc = n.indices["hyb"]
        plain = execute_batch(svc, bodies)
        padded = execute_batch(svc, bodies, pad_pow2=True)
        assert plain is not None and padded is not None
        for a, b in zip(plain, padded):
            assert [h["_id"] for h in a["hits"]["hits"]] == \
                [h["_id"] for h in b["hits"]["hits"]]
            assert a["hits"]["total"] == b["hits"]["total"]


# ---------------------------------------------------------------------------
# mesh path: host orchestration by design
# ---------------------------------------------------------------------------

class TestMeshPath:
    def test_mesh_compiler_classifies_hybrid_by_design(self):
        from elasticsearch_tpu.analysis.registry import AnalysisRegistry
        from elasticsearch_tpu.index.mappings import Mappings
        from elasticsearch_tpu.parallel.compiler import (MeshCompileError,
                                                         MeshQueryCompiler)
        from elasticsearch_tpu.search.queries import parse_query

        mappings = Mappings({"properties": {
            "body": {"type": "text"},
            "emb": {"type": "dense_vector", "dims": DIMS}}})
        comp = MeshQueryCompiler(mappings, AnalysisRegistry(), D=16)
        q = parse_query(_hybrid_body(np.ones(DIMS))["query"])
        with pytest.raises(MeshCompileError) as ei:
            comp.compile(q, None, None)
        assert ei.value.by_design  # counts as mesh_host_by_design, not
        # against the fallback==0 budget

    def test_multi_shard_parity_with_host_fusion(self):
        """2 shards: the mesh plane refuses by design, the host loop
        merges per-shard fused top-k — still byte-identical to the host
        reference built from the same index's engine scores."""
        rng = np.random.RandomState(12)
        V = rng.randn(160, DIMS).astype(np.float32)
        n = Node()
        n.create_index("hym", {"settings": {"number_of_shards": 2},
                               "mappings": {"properties": {
                                   "emb": {"type": "dense_vector",
                                           "dims": DIMS},
                                   "body": {"type": "text"}}}})
        svc = n.indices["hym"]
        for i in range(160):
            svc.index_doc(str(i), {"emb": [float(x) for x in V[i]],
                                   "body": "alpha" if i % 3 else
                                           "alpha beta"})
        svc.refresh()
        qv = rng.randn(DIMS).astype(np.float32)
        r = n.search("hym", _hybrid_body(qv, "rrf", (1.0, 1.0), 60.0,
                                         nc=40))
        # per-shard engines: reconstruct each shard's candidate cutoff
        # from the per-shard knn searches is index-routing dependent, so
        # assert the weaker-but-sufficient contract here: hybrid totals
        # equal the union reported by the engines and ordering follows
        # (-score, shard, local) on finite scores
        got = _got_hits(r)
        assert got
        scores = [s for _, s in got]
        assert scores == sorted(scores, reverse=True)
        assert r["hits"]["total"] >= len(got)
        assert r["_shards"]["successful"] == 2
        n.close()


# ---------------------------------------------------------------------------
# stage 2: rerank + breaker degrade (typed partial, never a 500)
# ---------------------------------------------------------------------------

class TestStage2Rerank:
    def _rerank_body(self, qvec, T, window=10):
        body = _hybrid_body(qvec)
        body["query"]["hybrid"]["rerank"] = {
            "query_vectors": [[float(x) for x in t] for t in T],
            "window_size": window}
        return body

    def test_rerank_applied_matches_numpy_maxsim(self, dense_corpus):
        n, V, N = dense_corpus
        rng = np.random.RandomState(13)
        qv = rng.randn(DIMS).astype(np.float32)
        T = rng.randn(3, DIMS).astype(np.float32)
        stage1 = n.search("hyb", _hybrid_body(qv))
        win = [int(h["_id"]) for h in stage1["hits"]["hits"]]
        r = n.search("hyb", self._rerank_body(qv, T))
        assert r["hybrid"] == {"rerank": "applied", "window": len(win)}
        Vn = V / np.maximum(np.linalg.norm(V, axis=1, keepdims=True),
                            1e-12)
        Tn = T / np.maximum(np.linalg.norm(T, axis=1, keepdims=True),
                            1e-12)
        ms = ((1.0 + Tn @ Vn.T) * 0.5).max(axis=0)
        ref = sorted(win, key=lambda i: (-ms[i], i))
        assert [int(h["_id"]) for h in r["hits"]["hits"]] == ref
        np.testing.assert_allclose(
            [h["_score"] for h in r["hits"]["hits"]],
            [ms[i] for i in ref], rtol=1e-5)

    def test_breaker_denial_degrades_to_stage1_typed_partial(
            self, dense_corpus):
        n, V, N = dense_corpus
        from elasticsearch_tpu.monitor.metrics import SHARED
        from elasticsearch_tpu.resources import BREAKERS

        rng = np.random.RandomState(14)
        qv = rng.randn(DIMS).astype(np.float32)
        T = rng.randn(2, DIMS).astype(np.float32)
        stage1 = n.search("hyb", _hybrid_body(qv))
        br = BREAKERS.breaker("request")
        old = br.limit
        br.limit = 1
        try:
            r = n.search("hyb", self._rerank_body(qv, T))
        finally:
            br.limit = old
        # typed partial: stage-1 hits untouched, degradation marked,
        # no exception escaped (never a 500)
        assert r["hybrid"]["rerank"] == "declined"
        assert r["hybrid"]["degraded_to"] == "stage1"
        assert r["hybrid"]["reason"]["type"] == "circuit_breaking_exception"
        assert _got_hits(r) == _got_hits(stage1)
        declines = {k: v for k, v in SHARED.counter_values().items()
                    if "hybrid_rerank" in k and "decline" in k}
        assert sum(declines.values()) >= 1

    def test_rerank_admission_counter_ticks(self, dense_corpus):
        n, V, N = dense_corpus
        from elasticsearch_tpu.monitor.metrics import SHARED

        def admits():
            return sum(v for k, v in SHARED.counter_values().items()
                       if "hybrid_rerank" in k and "admit" in k)

        rng = np.random.RandomState(15)
        before = admits()
        n.search("hyb", self._rerank_body(
            rng.randn(DIMS).astype(np.float32),
            rng.randn(2, DIMS).astype(np.float32)))
        assert admits() > before

    def test_rerank_dims_mismatch_is_typed_400(self, dense_corpus):
        n, V, N = dense_corpus
        from elasticsearch_tpu.utils.errors import QueryParsingException

        body = _hybrid_body(np.ones(DIMS))
        body["query"]["hybrid"]["rerank"] = {
            "query_vectors": [[1.0] * (DIMS + 1)], "window_size": 5}
        with pytest.raises(QueryParsingException):
            n.search("hyb", body)


# ---------------------------------------------------------------------------
# knn/maxsim rescore routed through the stage-2 window path
# ---------------------------------------------------------------------------

class TestKnnRescore:
    def test_knn_rescore_parity_with_numpy_maxsim(self, dense_corpus):
        n, V, N = dense_corpus
        rng = np.random.RandomState(16)
        T = rng.randn(3, DIMS).astype(np.float32)
        before = kernels.snapshot().get("hybrid_rerank", 0)
        r = n.search("hyb", {
            "query": {"match": {"body": "alpha"}},
            "rescore": {"window_size": 10, "query": {
                "rescore_query": {"knn": {
                    "field": "emb",
                    "query_vectors": [[float(x) for x in t] for t in T],
                    "k": 10}},
                "query_weight": 0.0, "rescore_query_weight": 1.0,
                "score_mode": "total"}},
            "size": 10})
        # the stage-2 window path ran (NOT a whole-segment sweep)
        assert kernels.snapshot().get("hybrid_rerank", 0) > before
        base = n.search("hyb", {"query": {"match": {"body": "alpha"}},
                                "size": 10})
        win = [int(h["_id"]) for h in base["hits"]["hits"]]
        Vn = V / np.maximum(np.linalg.norm(V, axis=1, keepdims=True),
                            1e-12)
        Tn = T / np.maximum(np.linalg.norm(T, axis=1, keepdims=True),
                            1e-12)
        ms = ((1.0 + Tn @ Vn.T) * 0.5).max(axis=0)
        ref = sorted(win, key=lambda i: (-ms[i], i))
        assert [int(h["_id"]) for h in r["hits"]["hits"]] == ref
        np.testing.assert_allclose(
            [h["_score"] for h in r["hits"]["hits"]],
            [ms[i] for i in ref], rtol=1e-5)

    def test_knn_rescore_breaker_denial_keeps_original_order(
            self, dense_corpus):
        n, V, N = dense_corpus
        from elasticsearch_tpu.resources import BREAKERS

        rng = np.random.RandomState(17)
        T = rng.randn(2, DIMS).astype(np.float32)
        base = n.search("hyb", {"query": {"match": {"body": "alpha"}},
                                "size": 10})
        br = BREAKERS.breaker("request")
        old = br.limit
        br.limit = 1
        try:
            r = n.search("hyb", {
                "query": {"match": {"body": "alpha"}},
                "rescore": {"window_size": 10, "query": {
                    "rescore_query": {"knn": {
                        "field": "emb",
                        "query_vectors": [[float(x) for x in t]
                                          for t in T],
                        "k": 10}}}},
                "size": 10})
        finally:
            br.limit = old
        assert [h["_id"] for h in r["hits"]["hits"]] == \
            [h["_id"] for h in base["hits"]["hits"]]


# ---------------------------------------------------------------------------
# DSL validation (typed 400s)
# ---------------------------------------------------------------------------

class TestParse:
    def test_malformed_bodies_raise_typed_errors(self):
        from elasticsearch_tpu.search.hybrid import parse_hybrid
        from elasticsearch_tpu.utils.errors import QueryParsingException

        bad = [
            {"query": {"match_all": {}}},  # missing knn
            {"knn": {"field": "e", "query_vector": [1.0]}},  # missing query
            {"query": {"match_all": {}}, "knn": {"field": "e"}},
            {"query": {"match_all": {}},
             "knn": {"field": "e", "query_vector": [1.0]},
             "fusion": {"method": "zap"}},
            {"query": {"match_all": {}},
             "knn": {"field": "e", "query_vector": [1.0]},
             "fusion": {"weights": [1.0, -2.0]}},
            {"query": {"match_all": {}},
             "knn": {"field": "e", "query_vector": [1.0]},
             "rerank": {"window_size": 3}},  # rerank w/o vectors
            {"query": {"match_all": {}},
             "knn": {"field": "e", "query_vector": [1.0]},
             "rerank": {"query_vectors": [[1.0]], "window_size": 0}},
            {"query": {"match_all": {}},  # token matrix belongs in rerank
             "knn": {"field": "e", "query_vectors": [[1.0], [2.0]]}},
        ]
        for body in bad:
            with pytest.raises(QueryParsingException):
                parse_hybrid(body)

    def test_weights_and_rrf_k_aliases(self):
        from elasticsearch_tpu.search.hybrid import parse_hybrid

        q = parse_hybrid({
            "lexical": {"match_all": {}},
            "vector": {"field": "e", "vector": [1.0, 2.0]},
            "fusion": {"rrf_k": 11}})
        assert q.rank_constant == 11.0
        assert q.weights == (1.0, 1.0)


# ---------------------------------------------------------------------------
# MaxSim-ADC kernel (ops/pallas_kernels.py): Pallas interpret == XLA == numpy
# ---------------------------------------------------------------------------

class TestMaxSimAdcKernel:
    def _case(self, rng, W=96, M=4, K=128, T=5):
        codes = rng.randint(0, K, size=(W, M)).astype(np.int32)
        luts = rng.randn(T, M, K).astype(np.float32)
        # numpy reference: per (token, doc) ADC sum over subspaces, max
        # over tokens
        per = np.zeros((T, W), np.float32)
        for t in range(T):
            for m in range(M):
                per[t] += luts[t, m, codes[:, m]]
        return codes, luts, per.max(axis=0)

    def test_xla_fallback_matches_numpy(self):
        import jax.numpy as jnp

        from elasticsearch_tpu.ops.pallas_kernels import _maxsim_adc_xla

        rng = np.random.RandomState(20)
        codes, luts, ref = self._case(rng)
        # XLA form takes [T, M, K] tables, codes i32[W, M]
        got = np.asarray(_maxsim_adc_xla(jnp.asarray(codes),
                                         jnp.asarray(luts)))
        np.testing.assert_allclose(got, ref, rtol=1e-5)

    def test_pallas_interpret_matches_numpy(self):
        import jax.numpy as jnp

        from elasticsearch_tpu.ops.pallas_kernels import maxsim_adc_pallas

        rng = np.random.RandomState(21)
        W, M, K, T = 128, 4, 128, 5
        codes, luts, ref = self._case(rng, W=W, M=M, K=K, T=T)
        Tp = 8  # kernel pads the token axis to a multiple of 8
        luts_t = np.zeros((M, K, Tp), np.float32)
        luts_t[:, :, :T] = luts.transpose(1, 2, 0)
        got = np.asarray(maxsim_adc_pallas(
            jnp.asarray(codes), jnp.asarray(luts_t), t_real=T, tile=64,
            interpret=True))
        np.testing.assert_allclose(got, ref, rtol=1e-5)

    def test_auto_dispatcher_env_override_and_fallback(self, monkeypatch):
        import jax.numpy as jnp

        from elasticsearch_tpu.ops import pallas_kernels as pk

        rng = np.random.RandomState(22)
        codes, luts, ref = self._case(rng)
        monkeypatch.setenv("ESTPU_MAXSIM_KERNEL", "xla")
        got = np.asarray(pk.maxsim_adc_auto(jnp.asarray(codes),
                                            jnp.asarray(luts)))
        np.testing.assert_allclose(got, ref, rtol=1e-5)
        # auto on CPU also lands on XLA (not broken, just not a TPU)
        monkeypatch.setenv("ESTPU_MAXSIM_KERNEL", "auto")
        got2 = np.asarray(pk.maxsim_adc_auto(jnp.asarray(codes),
                                             jnp.asarray(luts)))
        np.testing.assert_allclose(got2, ref, rtol=1e-5)
