"""delete-by-query / update-by-query, indices query, template query tests.

Reference: org.elasticsearch delete-by-query (2.0 plugin semantics),
IndicesQueryBuilder, TemplateQueryBuilder.
"""
import pytest

from elasticsearch_tpu.node import Node
from elasticsearch_tpu.rest.server import RestController


@pytest.fixture()
def node():
    n = Node()
    n.create_index("a1", {"mappings": {"properties": {
        "tag": {"type": "keyword"}, "v": {"type": "long"}}}})
    n.create_index("b1", {"mappings": {"properties": {
        "tag": {"type": "keyword"}, "v": {"type": "long"}}}})
    for i in range(10):
        n.indices["a1"].index_doc(str(i), {"tag": "even" if i % 2 == 0 else "odd", "v": i})
        n.indices["b1"].index_doc(str(i), {"tag": "bee", "v": i})
    for s in n.indices.values():
        s.refresh()
    yield n
    for s in n.indices.values():
        s.close()


def test_delete_by_query(node):
    rc = RestController(node)
    status, out = rc.dispatch("POST", "/a1/_delete_by_query", {},
                              b'{"query": {"term": {"tag": "odd"}}}')
    assert status == 200 and out["deleted"] == 5
    assert node.indices["a1"].num_docs == 5
    r = node.search("a1", {"query": {"term": {"tag": "odd"}}})
    assert r["hits"]["total"] == 0


def test_update_by_query_with_script(node):
    rc = RestController(node)
    status, out = rc.dispatch(
        "POST", "/a1/_update_by_query", {},
        b'{"query": {"term": {"tag": "even"}},'
        b' "script": "ctx._source.v = ctx._source.v + 100"}')
    assert status == 200 and out["updated"] == 5
    node.indices["a1"].refresh()
    r = node.search("a1", {"query": {"range": {"v": {"gte": 100}}}, "size": 20})
    assert r["hits"]["total"] == 5


def test_delete_by_query_beyond_scan_window(node):
    # regression: >10k matches must loop until exhausted, not truncate
    import elasticsearch_tpu.search.byquery as bq

    rc = RestController(node)
    orig = bq.scan_ids
    calls = {"n": 0}

    def tiny_scan(svc, query, seen):
        calls["n"] += 1
        resp = svc.search({"query": query or {"match_all": {}},
                           "size": 3, "_source": False})
        return [h["_id"] for h in resp["hits"]["hits"] if h["_id"] not in seen]

    bq.scan_ids = tiny_scan
    try:
        status, out = rc.dispatch("POST", "/a1/_delete_by_query", {},
                                  b'{"query": {"match_all": {}}}')
    finally:
        bq.scan_ids = orig
    assert out["deleted"] == 10 and calls["n"] >= 4  # looped past the window
    assert node.indices["a1"].num_docs == 0


def test_indices_query_routes_by_owning_index(node):
    q = {"indices": {"indices": ["a1"],
                     "query": {"term": {"tag": "even"}},
                     "no_match_query": {"term": {"tag": "bee"}}}}
    r = node.search("a1,b1", {"query": q, "size": 50})
    by_index = {}
    for h in r["hits"]["hits"]:
        by_index.setdefault(h["_index"], []).append(h["_id"])
    assert len(by_index.get("a1", [])) == 5  # even docs in a1
    assert len(by_index.get("b1", [])) == 10  # bee docs via no_match_query
    # no_match_query: "none" drops other indices entirely
    q["indices"]["no_match_query"] = "none"
    r = node.search("a1,b1", {"query": q, "size": 50})
    assert all(h["_index"] == "a1" for h in r["hits"]["hits"])


def test_template_query(node):
    q = {"template": {"query": {"term": {"tag": "{{t}}"}},
                      "params": {"t": "even"}}}
    r = node.search("a1", {"query": q, "size": 20})
    assert r["hits"]["total"] == 5
