"""Batched _msearch tiers vs sequential execution.

search/batch.py: tier 1 (pure-dense fused streaming top-k) and tier 2
(hybrid matmul + batched scatter tails, queries.hybrid_bm25_topk_batch)
must return exactly what Q independent Node.search calls return — ids,
scores, totals — and must actually serve via the batched kernels
(counters), not fall back.
"""
import functools

import numpy as np
import pytest

from elasticsearch_tpu.monitor import kernels
from elasticsearch_tpu.node import Node

VOCAB = ["alpha", "beta", "gamma", "delta", "epsilon", "zeta", "eta",
         "theta", "iota", "kappa"]


@pytest.fixture(scope="module")
def node():
    from elasticsearch_tpu.index import segment as segmod

    # drop the dense-block df bar so the small corpus builds one, making
    # the fused/hybrid tiers reachable (same knob as test_impact_bf16)
    orig = segmod.build_dense_impact
    segmod.build_dense_impact = functools.partial(orig, df_threshold=8)
    n = Node()
    # pin the mesh data plane off: this module exists to cover the HOST
    # fused tiers (the mesh batched path has its own parity suite in
    # tests/integration/test_mesh_qtf.py)
    n.create_index("mx", {"settings": {"index": {"number_of_shards": 2,
                                                 "search": {"mesh": "false"}}},
                          "mappings": {"properties": {
                              "body": {"type": "text"}}}})
    svc = n.indices["mx"]
    rng = np.random.default_rng(11)
    for i in range(120):
        # frequent head words + a rare per-doc tail word
        words = list(rng.choice(VOCAB[:4], size=6)) + \
            [VOCAB[4 + int(rng.integers(0, 6))], f"rare{i % 37}"]
        svc.index_doc(str(i), {"body": " ".join(words)})
    svc.refresh()
    yield n
    segmod.build_dense_impact = orig
    n.close()


def _pairs(queries):
    return [({"index": "mx"}, {"query": {"match": {"body": q}}, "size": 10})
            for q in queries]


def _assert_matches_sequential(node, queries, expect_counter):
    kernels.reset()
    resp = node.msearch(_pairs(queries))
    assert kernels.snapshot().get(expect_counter, 0) >= len(queries), \
        kernels.snapshot()
    for q, r in zip(queries, resp["responses"]):
        seq = node.search("mx", {"query": {"match": {"body": q}},
                                 "size": 10})
        got = [(h["_id"], round(h["_score"], 4)) for h in r["hits"]["hits"]]
        want = [(h["_id"], round(h["_score"], 4))
                for h in seq["hits"]["hits"]]
        assert got == want, (q, got, want)
        assert r["hits"]["total"] == seq["hits"]["total"], q


def test_pure_dense_batch_tier1(node):
    # head words only -> every term maps to a dense impact row
    _assert_matches_sequential(
        node, ["alpha beta", "gamma", "beta delta", "alpha gamma delta"],
        "bm25_fused_topk")


def test_mixed_tail_batch_tier2(node):
    # rare words have short postings runs -> scatter tails alongside the
    # dense head terms; tier 1 refuses, tier 2 serves
    _assert_matches_sequential(
        node, ["alpha rare1", "beta rare5 rare9", "gamma rare20",
               "delta rare3 alpha"],
        "bm25_hybrid")


def test_unbatchable_falls_back_sequential(node):
    kernels.reset()
    resp = node.msearch([
        ({"index": "mx"}, {"query": {"match": {"body": "alpha"}},
                           "size": 5}),
        ({"index": "mx"}, {"query": {"match": {"body": {
            "query": "alpha beta", "operator": "and"}}}, "size": 5}),
    ])
    assert len(resp["responses"]) == 2
    for r in resp["responses"]:
        assert r["hits"]["total"] > 0


def test_partial_batching_splits_around_ineligible_items(node):
    """One aggs item must no longer de-amortize the batch: the eligible
    subset still serves via the fused tier, the aggs item runs
    sequentially, and every response matches sequential execution."""
    kernels.reset()
    pairs = _pairs(["alpha beta", "gamma", "beta delta"])
    pairs.insert(1, ({"index": "mx"}, {
        "query": {"match_all": {}}, "size": 0,
        "aggs": {"words": {"terms": {"field": "body"}}}}))
    resp = node.msearch(pairs)
    assert kernels.snapshot().get("bm25_fused_topk", 0) >= 3
    assert "aggregations" in resp["responses"][1]
    for i, q in ((0, "alpha beta"), (2, "gamma"), (3, "beta delta")):
        seq = node.search("mx", {"query": {"match": {"body": q}},
                                 "size": 10})
        got = [(h["_id"], round(h["_score"], 4))
               for h in resp["responses"][i]["hits"]["hits"]]
        want = [(h["_id"], round(h["_score"], 4))
                for h in seq["hits"]["hits"]]
        assert got == want, (q, got, want)


def test_malformed_item_error_matches_sequential_shape(node):
    """A typed malformed-query item becomes a per-item msearch failure
    with EXACTLY the error string the sequential path reports, while
    the rest of the batch stays fused."""
    kernels.reset()
    bad = {"query": {"definitely_not_a_query": {}}}
    resp = node.msearch(_pairs(["alpha", "beta gamma"])
                        + [({"index": "mx"}, bad)])
    assert kernels.snapshot().get("bm25_fused_topk", 0) >= 2
    entry = resp["responses"][2]
    assert entry["status"] == 400 and "error" in entry
    # sequential reference: a lone msearch item takes the per-item
    # error path in Node.msearch — the strings must match exactly
    seq_entry = node.msearch([({"index": "mx"}, bad)])["responses"][0]
    assert entry == seq_entry
