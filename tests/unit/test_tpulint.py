"""tpulint rule fixtures: one known-bad and one known-good example per
rule (R001–R005), the suppression/host-annotation mechanism, the baseline
budget semantics, the --json CLI mode, and the runtime trace auditor.

The clean-gate companion (test_tpulint_clean.py) runs the analyzer over
the real package; this file proves each detector actually detects.
"""
import json
import textwrap

import pytest

from tools.tpulint import lint_source
from tools.tpulint.analyzer import Violation
from tools.tpulint.baseline import filter_baselined, load_baseline


def lint(src: str, *, hot: bool = False, locked: bool = False,
         ops: bool = False, swallow: bool = False, timing: bool = False,
         budget: bool = False, blocking: bool = False,
         threads: bool = False, audit: bool = False,
         path: str = "elasticsearch_tpu/x/mod.py"):
    # every scope flag is opt-in for fixtures (audit included: the
    # default fixture path would otherwise drag R012 into every
    # unrelated fixture that binds jit at its top level)
    return lint_source(textwrap.dedent(src), path, hot=hot, ops=ops,
                       locked=locked, swallow=swallow, timing=timing,
                       budget=budget, blocking=blocking, threads=threads,
                       audit=audit)


def rules_of(violations):
    return sorted({v.rule for v in violations})


# ---------------------------------------------------------------------------
# R001 — recompilation hazards
# ---------------------------------------------------------------------------

class TestR001:
    def test_bad_jit_in_loop(self):
        vs = lint("""
            import jax
            def run(xs):
                for x in xs:
                    f = jax.jit(lambda v: v + 1)
                    f(x)
        """)
        assert rules_of(vs) == ["R001"]

    def test_bad_jitted_def_in_loop(self):
        vs = lint("""
            import jax
            while True:
                @jax.jit
                def f(x):
                    return x
        """)
        assert rules_of(vs) == ["R001"]

    def test_bad_unhashable_static_arg(self):
        vs = lint("""
            import jax
            from functools import partial

            @partial(jax.jit, static_argnames=("ks",))
            def f(x, *, ks):
                return x

            def g(x):
                return f(x, ks=[1, 2])
        """)
        assert rules_of(vs) == ["R001"]
        assert "unhashable" in vs[0].message

    def test_bad_raw_len_static_arg(self):
        vs = lint("""
            import jax
            from functools import partial

            @partial(jax.jit, static_argnames=("n",))
            def f(x, *, n):
                return x

            def g(hits, x):
                return f(x, n=len(hits))
        """)
        assert rules_of(vs) == ["R001"]
        assert "bucket" in vs[0].message

    def test_good_program_factory(self):
        # the codebase idiom: build once per shape class, cache, reuse
        vs = lint("""
            import jax
            from functools import partial

            _PROGRAMS = {}

            @partial(jax.jit, static_argnames=("n", "metric"))
            def f(x, *, n, metric):
                return x

            def g(x, n, metric):
                return f(x, n=n, metric=metric)

            def make(shape_class):
                prog = _PROGRAMS.get(shape_class)
                if prog is None:
                    prog = jax.jit(lambda v: v * 2)
                    _PROGRAMS[shape_class] = prog
                return prog
        """)
        assert vs == []


# ---------------------------------------------------------------------------
# R002 — host-device sync in hot paths
# ---------------------------------------------------------------------------

class TestR002:
    BAD_PER_HIT = """
        import numpy as np
        def handler(scores, mask, hits):
            out = []
            for loc in hits:
                matched = bool(np.asarray(mask)[loc])
                out.append(float(np.asarray(scores)[loc]))
            return out
    """

    def test_bad_scalar_pull_in_loop(self):
        vs = lint(self.BAD_PER_HIT, hot=True)
        assert rules_of(vs) == ["R002"]
        assert len(vs) >= 2  # both the bool(...) and float(...) sites

    def test_bad_item_in_comprehension(self):
        vs = lint("""
            def handler(xs):
                return [x.item() for x in xs]
        """, hot=True)
        assert rules_of(vs) == ["R002"]

    def test_good_hoisted_host_copy(self):
        vs = lint("""
            import numpy as np
            def handler(scores, mask, hits):
                mask_h = np.asarray(mask)
                scores_h = np.asarray(scores)
                return [(bool(mask_h[loc]), float(scores_h[loc]))
                        for loc in hits]
        """, hot=True)
        assert vs == []

    def test_good_slice_transfer_in_loop(self):
        # bulk slices are the RIGHT pattern — only scalar pulls are flagged
        vs = lint("""
            import numpy as np
            def handler(segs):
                return [np.asarray(s.mask)[: s.num_docs] for s in segs]
        """, hot=True)
        assert vs == []

    def test_cold_path_not_flagged(self):
        assert lint(self.BAD_PER_HIT, hot=False) == []


# ---------------------------------------------------------------------------
# R003 — dynamic shapes in traced code / ambiguous host build calls
# ---------------------------------------------------------------------------

class TestR003:
    def test_bad_nonzero_without_size(self):
        vs = lint("""
            import jax
            import jax.numpy as jnp

            @jax.jit
            def f(x):
                return jnp.nonzero(x > 0)
        """)
        assert rules_of(vs) == ["R003"]

    def test_bad_boolean_mask_indexing(self):
        vs = lint("""
            import jax
            import jax.numpy as jnp

            @jax.jit
            def f(x, lo):
                return x[x > lo]
        """)
        assert rules_of(vs) == ["R003"]

    def test_bad_single_arg_where_and_unique(self):
        vs = lint("""
            import jax
            import jax.numpy as jnp

            @jax.jit
            def f(x):
                return jnp.where(x > 0), jnp.unique(x)
        """)
        assert [v.rule for v in vs] == ["R003", "R003"]

    def test_good_size_bounded_forms(self):
        vs = lint("""
            import jax
            import jax.numpy as jnp

            @jax.jit
            def f(x):
                idx = jnp.nonzero(x > 0, size=8, fill_value=-1)
                return jnp.where(x > 0, x, 0.0), idx
        """)
        assert vs == []

    def test_bad_unannotated_host_nonzero_in_ops(self):
        vs = lint("""
            import numpy as np
            def build(exists):
                return np.nonzero(exists)[0]
        """, ops=True)
        assert rules_of(vs) == ["R003"]

    def test_good_host_annotated_nonzero_in_ops(self):
        vs = lint("""
            import numpy as np
            def build(exists):
                return np.nonzero(exists)[0]  # tpulint: host
        """, ops=True)
        assert vs == []

    def test_bad_unaliased_jax_numpy_import(self):
        # `import jax.numpy` without an alias must still register as jnp
        vs = lint("""
            import jax
            import jax.numpy

            @jax.jit
            def f(x):
                return jax.numpy.nonzero(x > 0)
        """)
        assert rules_of(vs) == ["R003"]

    def test_host_nonzero_outside_ops_not_flagged(self):
        vs = lint("""
            import numpy as np
            def build(exists):
                return np.nonzero(exists)[0]
        """, ops=False)
        assert vs == []


# ---------------------------------------------------------------------------
# R004 — tracer leaks
# ---------------------------------------------------------------------------

class TestR004:
    def test_bad_if_on_traced_value(self):
        vs = lint("""
            import jax

            @jax.jit
            def f(x):
                if x > 0:
                    return x
                return -x
        """)
        assert rules_of(vs) == ["R004"]

    def test_bad_while_on_traced_value(self):
        vs = lint("""
            import jax

            @jax.jit
            def f(x):
                while x < 10:
                    x = x * 2
                return x
        """)
        assert rules_of(vs) == ["R004"]

    def test_good_branch_on_static_argname(self):
        vs = lint("""
            import jax
            from functools import partial

            @partial(jax.jit, static_argnames=("metric",))
            def f(x, *, metric):
                if metric == "l2":
                    return -x
                return x
        """)
        assert vs == []

    def test_good_is_none_structure_switch(self):
        # pytree-structure dispatch resolves at trace time — allowed
        vs = lint("""
            import jax

            @jax.jit
            def f(x, mask):
                if mask is None:
                    return x
                return x * mask
        """)
        assert vs == []


# ---------------------------------------------------------------------------
# R005 — lock discipline in threadpool-visible modules
# ---------------------------------------------------------------------------

class TestR005:
    def test_bad_unlocked_instance_mutation(self):
        vs = lint("""
            import threading

            class Engine:
                def __init__(self):
                    self._lock = threading.Lock()
                    self.docs = {}

                def add(self, k, v):
                    self.docs[k] = v
        """, locked=True)
        assert rules_of(vs) == ["R005"]

    def test_bad_unlocked_module_global_mutation(self):
        vs = lint("""
            import threading

            _LOCK = threading.Lock()
            _CACHE = {}

            def put(k, v):
                _CACHE[k] = v
        """, locked=True)
        assert rules_of(vs) == ["R005"]

    def test_good_locked_mutation(self):
        vs = lint("""
            import threading

            class Engine:
                def __init__(self):
                    self._lock = threading.Lock()
                    self.docs = {}

                def add(self, k, v):
                    with self._lock:
                        self.docs[k] = v
                        self.count = len(self.docs)
        """, locked=True)
        assert vs == []

    def test_good_private_helper_caller_locked(self):
        # the engine.py convention: `_private` helpers run under the
        # caller's lock and are exempt
        vs = lint("""
            import threading

            class Engine:
                def __init__(self):
                    self._lock = threading.Lock()
                    self.docs = {}

                def add(self, k, v):
                    with self._lock:
                        self._put(k, v)

                def _put(self, k, v):
                    self.docs[k] = v
        """, locked=True)
        assert vs == []

    def test_unlocked_module_not_checked(self):
        vs = lint("""
            import threading

            class Engine:
                def __init__(self):
                    self._lock = threading.Lock()
                    self.docs = {}

                def add(self, k, v):
                    self.docs[k] = v
        """, locked=False)
        assert vs == []


# ---------------------------------------------------------------------------
# suppressions
# ---------------------------------------------------------------------------

class TestR006:
    def test_bad_except_exception_pass(self):
        vs = lint("""
            def fan_out(peers):
                for p in peers:
                    try:
                        p.send()
                    except Exception:
                        pass
        """, swallow=True)
        assert rules_of(vs) == ["R006"]

    def test_bad_bare_except_pass(self):
        vs = lint("""
            def close(ch):
                try:
                    ch.close()
                except:
                    pass
        """, swallow=True)
        assert rules_of(vs) == ["R006"]

    def test_bad_tuple_catch_and_ellipsis_body(self):
        # the evasions: tuple form and a no-op `...` body must still flag
        vs = lint("""
            def fan_out(p):
                try:
                    p.send()
                except (ValueError, Exception):
                    pass
                try:
                    p.send()
                except Exception:
                    ...
        """, swallow=True)
        assert [v.rule for v in vs] == ["R006", "R006"]

    def test_good_typed_catch_and_accounted_failure(self):
        vs = lint("""
            def fan_out(peers, failures):
                for p in peers:
                    try:
                        p.send()
                    except ConnectionError:
                        pass
                    except Exception as e:
                        failures.append(str(e))
        """, swallow=True)
        assert vs == []

    def test_good_inline_allow(self):
        # the marker sits on the `except` line — that's where R006 anchors
        # (and what the baseline fingerprints on)
        vs = lint("""
            def close(ch):
                try:
                    ch.close()
                except Exception:  # tpulint: allow[R006] — none left to tell
                    pass
        """, swallow=True)
        assert vs == []

    def test_not_flagged_outside_failure_domain(self):
        vs = lint("""
            def close(ch):
                try:
                    ch.close()
                except Exception:
                    pass
        """, swallow=False)
        assert vs == []


# ---------------------------------------------------------------------------
# R007 — wall-clock durations in timing modules
# ---------------------------------------------------------------------------

class TestR007:
    def test_bad_direct_subtraction(self):
        vs = lint("""
            import time
            def span(t0):
                return time.time() - t0
        """, timing=True)
        assert rules_of(vs) == ["R007"]

    def test_bad_t0_then_subtract(self):
        vs = lint("""
            import time
            def measure(fn):
                t0 = time.time()
                fn()
                return time.time() - t0
        """, timing=True)
        assert rules_of(vs) == ["R007"]
        assert "monotonic" in vs[0].message

    def test_bad_from_import_alias(self):
        vs = lint("""
            from time import time as now
            def dur(work):
                start = now()
                work()
                return now() - start
        """, timing=True)
        assert rules_of(vs) == ["R007"]

    def test_reassignment_clears_taint(self):
        # a name rebound from time.time() to monotonic() must stop
        # flagging — only the wall-clock binding is tainted
        vs = lint("""
            import time
            def measure(fn):
                t0 = time.time()
                stamp = int(t0 * 1000)
                t0 = time.monotonic()
                fn()
                return time.monotonic() - t0
        """, timing=True)
        assert vs == []

    def test_good_monotonic_duration(self):
        vs = lint("""
            import time
            def measure(fn):
                t0 = time.monotonic()
                fn()
                return time.perf_counter() - t0
        """, timing=True)
        assert vs == []

    def test_good_wallclock_timestamp(self):
        # epoch timestamps never subtract — legal in timing modules
        # (monitor/stats.py stamps events this way)
        vs = lint("""
            import time
            def stamp(event):
                event["timestamp"] = int(time.time() * 1000)
                return event
        """, timing=True)
        assert vs == []

    def test_not_flagged_outside_timing_modules(self):
        vs = lint("""
            import time
            def took():
                t0 = time.time()
                return time.time() - t0
        """, timing=False)
        assert vs == []

    def test_inline_allow(self):
        vs = lint("""
            import time
            def drift():
                # comparing wall clocks across hosts IS the point here
                return time.time() - 0.0  # tpulint: allow[R007]
        """, timing=True)
        assert vs == []


class TestR008:
    """Unaccounted device placement (HBM bypassing resources/)."""

    def test_bad_raw_device_put(self):
        vs = lint("""
            import jax
            def place(arr):
                return jax.device_put(arr)
        """, budget=True)
        assert rules_of(vs) == ["R008"]
        assert "residency" in vs[0].message

    def test_bad_from_import_alias(self):
        vs = lint("""
            from jax import device_put as dp
            def place(arr):
                return dp(arr)
        """, budget=True)
        assert rules_of(vs) == ["R008"]

    def test_good_offbudget_annotation(self):
        vs = lint("""
            import jax
            def place(q):
                # transient per-query upload
                return jax.device_put(q)  # tpulint: offbudget
        """, budget=True)
        assert vs == []

    def test_scoped_by_path_not_flag(self):
        # the product package is in scope, resources/ (the choke point
        # implementation) and code outside the package are not
        import textwrap as _tw

        src = _tw.dedent("""
            import jax
            def place(arr):
                return jax.device_put(arr)
        """)
        assert any(v.rule == "R008" for v in lint_source(
            src, "elasticsearch_tpu/index/segment.py"))
        assert not lint_source(src,
                               "elasticsearch_tpu/resources/residency.py")
        assert not lint_source(src, "bench.py")

    def test_routed_through_registry_is_clean(self):
        vs = lint("""
            from elasticsearch_tpu import resources
            def place(arr):
                return resources.RESIDENCY.device_put(arr, label="x")
        """, budget=True)
        assert vs == []


class TestR009:
    """Metric recording on the device path (the metrics substrate's hard
    constraint: no record calls inside jit-traced code, no device-array
    arguments into record calls)."""

    def test_bad_record_inside_traced_code(self):
        vs = lint("""
            import jax
            from elasticsearch_tpu.monitor import metrics

            REG = metrics.MetricsRegistry()
            HITS = REG.counter("estpu_hits_total")

            @jax.jit
            def score(x):
                HITS.inc()
                return x * 2
        """)
        assert rules_of(vs) == ["R009"]
        assert "jit-traced" in vs[0].message

    def test_bad_chained_record_inside_traced_code(self):
        vs = lint("""
            import jax
            from elasticsearch_tpu.monitor.metrics import SHARED

            @jax.jit
            def score(x):
                SHARED.histogram("lat").labels("a").observe(1.0)
                return x
        """)
        assert rules_of(vs) == ["R009"]

    def test_bad_kernels_record_inside_traced_code(self):
        vs = lint("""
            import jax
            from elasticsearch_tpu.monitor import kernels

            @jax.jit
            def f(x):
                kernels.record("bm25_scatter")
                return x
        """)
        assert rules_of(vs) == ["R009"]

    def test_bad_device_array_argument(self):
        vs = lint("""
            import jax.numpy as jnp
            from elasticsearch_tpu.monitor.metrics import SHARED

            def after(scores):
                top = jnp.max(scores)
                SHARED.histogram("score").observe(top)
        """)
        assert rules_of(vs) == ["R009"]
        assert "device" in vs[0].message

    def test_bad_direct_jnp_argument(self):
        vs = lint("""
            import jax.numpy as jnp
            from elasticsearch_tpu.monitor.metrics import SHARED

            def after(scores):
                SHARED.counter("total").inc(jnp.sum(scores))
        """)
        assert rules_of(vs) == ["R009"]

    def test_good_host_pull_then_record(self):
        vs = lint("""
            import jax
            import jax.numpy as jnp
            from elasticsearch_tpu.monitor.metrics import SHARED

            def after(scores):
                top = jnp.max(scores)
                v = float(jax.device_get(top))
                SHARED.histogram("score").observe(v)
        """)
        assert vs == []

    def test_good_host_record_and_attr_registry(self):
        # node.metrics / self.metrics chains on the host path are the
        # product idiom (rest dispatch, transport) — clean
        vs = lint("""
            import time

            def finish(self, dt):
                m = self.node.metrics
                m.counter("estpu_rest_requests_total",
                          "h", ("s",)).labels("2xx").inc()
                m.histogram("estpu_rest_request_duration_seconds",
                            "h").observe(dt)
        """)
        assert vs == []

    def test_good_jax_at_set_not_a_record_call(self):
        # jnp's functional update spells .set() too — target is an
        # array, not a metric; must not flag
        vs = lint("""
            import jax
            import jax.numpy as jnp

            @jax.jit
            def f(x):
                return x.at[0].set(1.0)
        """)
        assert vs == []

    def test_reassignment_clears_device_taint(self):
        vs = lint("""
            import jax.numpy as jnp
            from elasticsearch_tpu.monitor.metrics import SHARED

            def after(scores, n):
                top = jnp.max(scores)
                top = float(n)
                SHARED.histogram("score").observe(top)
        """)
        assert vs == []


class TestR010:
    """Unbounded blocking waits while holding a lock in serving modules
    (the coalescer's drain-path wedge hazard)."""

    def test_bad_event_wait_under_lock(self):
        vs = lint("""
            import threading

            class Coalescer:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._evt = threading.Event()

                def drain(self):
                    with self._lock:
                        self._evt.wait()
        """, blocking=True)
        assert rules_of(vs) == ["R010"]
        assert "timeout" in vs[0].message

    def test_bad_condition_wait_under_its_own_lock(self):
        # `with cond:` acquires the condition's lock — the classic shape
        vs = lint("""
            import threading

            class Q:
                def __init__(self):
                    self._cv = threading.Condition()

                def drain(self):
                    with self._cv:
                        self._cv.wait()
        """, blocking=True)
        assert rules_of(vs) == ["R010"]

    def test_bad_queue_get_under_module_lock(self):
        vs = lint("""
            import queue
            import threading

            _LOCK = threading.Lock()
            _Q = queue.Queue()

            def drain():
                with _LOCK:
                    return _Q.get()
        """, blocking=True)
        assert rules_of(vs) == ["R010"]
        assert "queue" in vs[0].message

    def test_bad_block_true_forms_still_flag(self):
        # get(True) / get(block=True) are unbounded blocking gets — the
        # spelled-out default must not evade the rule
        vs = lint("""
            import queue
            import threading

            _LOCK = threading.Lock()
            _Q = queue.Queue()

            def a():
                with _LOCK:
                    return _Q.get(True)

            def b():
                with _LOCK:
                    return _Q.get(block=True)
        """, blocking=True)
        assert [v.rule for v in vs] == ["R010", "R010"]

    def test_good_nonblocking_and_dict_style_gets(self):
        vs = lint("""
            import queue
            import threading

            _LOCK = threading.Lock()
            _Q = queue.Queue()
            _D = {}

            def a():
                with _LOCK:
                    return _Q.get(False)      # non-blocking

            def b():
                with _LOCK:
                    return _Q.get(True, 5)    # positional timeout

            def c():
                with _LOCK:
                    return _Q.get(block=True, timeout=2)

            def d(key):
                with _LOCK:
                    return _D.get(key)        # dict get, not a queue wait
        """, blocking=True)
        assert vs == []

    def test_good_timeout_bounded_waits(self):
        vs = lint("""
            import queue
            import threading

            class Coalescer:
                def __init__(self):
                    self._cv = threading.Condition()
                    self._evt = threading.Event()
                    self._q = queue.Queue()

                def drain(self):
                    with self._cv:
                        self._cv.wait(timeout=0.5)
                    with self._cv:
                        self._evt.wait(0.05)
                    with self._cv:
                        return self._q.get(timeout=1.0)
        """, blocking=True)
        assert vs == []

    def test_good_unbounded_wait_without_lock(self):
        # parking OUTSIDE any lock is the correct shape — not flagged
        vs = lint("""
            import threading

            class Entry:
                def __init__(self):
                    self._lock = threading.Lock()
                    self.done = threading.Event()

                def wait_result(self):
                    self.done.wait()
        """, blocking=True)
        assert vs == []

    def test_scope_only_serving_modules(self):
        src = """
            import threading

            class W:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._evt = threading.Event()

                def run(self):
                    with self._lock:
                        self._evt.wait()
        """
        assert any(v.rule == "R010" for v in lint_source(
            textwrap.dedent(src), "elasticsearch_tpu/serving/coalescer.py"))
        assert not lint_source(textwrap.dedent(src),
                               "elasticsearch_tpu/index/other.py")


class TestR011:
    """Background threads in cluster modules: daemon=True mandatory, and
    a thread target's While loop must consult a stop Event (the
    _fault_loop pattern) — an ungated control-plane loop outlives
    close() and keeps probing/publishing a torn-down cluster."""

    def test_bad_non_daemon_thread(self):
        vs = lint("""
            import threading

            def start(svc):
                t = threading.Thread(target=svc.run, name="bg")
                t.start()
        """, threads=True)
        assert rules_of(vs) == ["R011"]
        assert "daemon=True" in vs[0].message

    def test_bad_ungated_while_loop_in_target(self):
        vs = lint("""
            import threading
            import time

            class Cluster:
                def _loop(self):
                    while True:
                        self.ping_all()
                        time.sleep(1.0)

                def start(self):
                    threading.Thread(target=self._loop,
                                     daemon=True).start()
        """, threads=True)
        assert rules_of(vs) == ["R011"]
        assert "stop" in vs[0].message.lower()

    def test_bad_both_violations_flag_separately(self):
        vs = lint("""
            from threading import Thread

            def loop():
                while True:
                    poll()

            def start():
                Thread(target=loop).start()
        """, threads=True)
        assert [v.rule for v in vs] == ["R011", "R011"]

    def test_good_fault_loop_pattern(self):
        # the production shape: daemon=True + stop-Event-gated loop
        vs = lint("""
            import threading

            class Cluster:
                def __init__(self):
                    self._stop = threading.Event()

                def _fault_loop(self, interval):
                    while not self._stop.wait(interval):
                        self.run_fd_round()

                def start(self):
                    threading.Thread(target=self._fault_loop,
                                     args=(1.0,), name="fd",
                                     daemon=True).start()
        """, threads=True)
        assert vs == []

    def test_good_break_on_stop_inside_body(self):
        # `while True: ... if stop.is_set(): break` consults the Event
        vs = lint("""
            import threading

            _STOP = threading.Event()

            def loop():
                while True:
                    if _STOP.is_set():
                        break
                    work()

            def start():
                threading.Thread(target=loop, daemon=True).start()
        """, threads=True)
        assert vs == []

    def test_good_oneshot_target_with_for_loop(self):
        # a for over a finite work list terminates on its own — only the
        # daemon flag is required
        vs = lint("""
            import threading

            class Data:
                def _run(self, directives):
                    for d in directives:
                        self.recover(d)

                def start(self, directives):
                    threading.Thread(target=self._run,
                                     args=(directives,),
                                     daemon=True).start()
        """, threads=True)
        assert vs == []

    def test_target_resolves_within_enclosing_class(self):
        """Two classes sharing a method name: the checker must inspect
        the STARTING class's body — first-def-wins by bare name let an
        ungated loop ship unflagged behind a same-named clean method
        defined earlier (and flagged the symmetric clean case)."""
        vs = lint("""
            import threading
            import time

            class Clean:
                def _run(self):
                    self.ping_once()

            class Dirty:
                def _run(self):
                    while True:
                        self.ping_all()
                        time.sleep(1.0)

                def start(self):
                    threading.Thread(target=self._run,
                                     daemon=True).start()
        """, threads=True)
        assert rules_of(vs) == ["R011"]
        assert "stop" in vs[0].message.lower()
        # the symmetric case: clean method behind an earlier dirty name
        vs = lint("""
            import threading
            import time

            class Dirty:
                def _run(self):
                    while True:
                        time.sleep(1.0)

            class Clean:
                def _run(self):
                    self.ping_once()

                def start(self):
                    threading.Thread(target=self._run,
                                     daemon=True).start()
        """, threads=True)
        assert rules_of(vs) == []

    def test_opaque_target_only_daemon_checked(self):
        # another object's method is out of static reach: daemon=True is
        # still enforced, the loop check is not
        vs = lint("""
            import threading

            def start(self):
                threading.Thread(target=self.data.resurrect,
                                 daemon=True).start()
        """, threads=True)
        assert vs == []

    def test_scope_background_thread_modules(self):
        """R011 covers every package that runs background threads:
        cluster/ (control plane), monitor/ (watchdog tick) and serving/
        (coalescer drain) — the watchdog/recorder threads are born under
        the rule, not grandfathered past it. index/ stays out."""
        src = """
            import threading

            def start(svc):
                threading.Thread(target=svc.run).start()
        """
        for marker in ("elasticsearch_tpu/cluster/bootstrap.py",
                       "elasticsearch_tpu/monitor/watchdog.py",
                       "elasticsearch_tpu/serving/coalescer.py"):
            assert any(v.rule == "R011" for v in lint_source(
                textwrap.dedent(src), marker)), marker
        assert not any(v.rule == "R011" for v in lint_source(
            textwrap.dedent(src), "elasticsearch_tpu/index/engine.py"))

    def test_good_closed_flag_gate(self):
        # the serving drain-loop spelling of the shutdown gate: a
        # `while True` whose body consults a `closed` flag is gated —
        # same contract as the stop Event, different name
        vs = lint("""
            import threading

            class Drain:
                def __init__(self):
                    self._closed = False

                def _drain_loop(self):
                    while True:
                        if self._closed:
                            return
                        self.flush_due()

                def start(self):
                    threading.Thread(target=self._drain_loop,
                                     daemon=True).start()
        """, threads=True)
        assert vs == []


class TestR012:
    """Import-time jax.jit bindings outside the trace-audited packages:
    a program bound before tracing/retrace installs the auditor escapes
    compile attribution (the device-program observatory's census and the
    profiler's compile/execute split both under-report). ops/, models/
    and parallel/ install the auditor in their package __init__ before
    any submodule binds, so bindings there are exempt."""

    BAD = """
        import jax
        from functools import partial

        @partial(jax.jit, static_argnames=("k",))
        def score(x, *, k):
            return x * k
    """

    def test_bad_toplevel_decorator(self):
        vs = lint(self.BAD, audit=True)
        assert rules_of(vs) == ["R012"]
        assert "escapes compile attribution" in vs[0].message

    def test_bad_module_level_assignment(self):
        vs = lint("""
            import jax

            prog = jax.jit(lambda x: x + 1)
        """, audit=True)
        assert rules_of(vs) == ["R012"]

    def test_bad_jitted_method_of_toplevel_class(self):
        vs = lint("""
            import jax

            class Scorer:
                @jax.jit
                def run(self, x):
                    return x
        """, audit=True)
        assert rules_of(vs) == ["R012"]

    def test_bad_guarded_and_annotated_bindings_still_flag(self):
        # module-level if/try/with and AnnAssign all EXECUTE at import —
        # a guard around the binding doesn't defer it (only a def does)
        vs = lint("""
            import jax

            try:
                prog = jax.jit(lambda x: x + 1)
            except Exception:
                prog = None

            if True:
                @jax.jit
                def score(x):
                    return x

            other: object = jax.jit(lambda x: x - 1)
        """, audit=True)
        assert [v.rule for v in vs] == ["R012", "R012", "R012"]

    def test_good_factory_binding(self):
        # the blessed shape: bind at first call, long after install
        vs = lint("""
            import jax

            def make_program(k):
                @jax.jit
                def score(x):
                    return x * k
                return score
        """, audit=True)
        assert vs == []

    def test_scope_audited_packages_exempt(self):
        src = textwrap.dedent(self.BAD)
        assert any(v.rule == "R012" for v in lint_source(
            src, "elasticsearch_tpu/search/queries.py"))
        assert any(v.rule == "R012" for v in lint_source(
            src, "elasticsearch_tpu/index/segment.py"))
        for exempt in ("elasticsearch_tpu/ops/scoring.py",
                       "elasticsearch_tpu/models/dual_encoder.py",
                       "elasticsearch_tpu/parallel/executor.py"):
            assert not any(v.rule == "R012"
                           for v in lint_source(src, exempt)), exempt
        # measurement code outside the product package is out of scope
        assert not any(v.rule == "R012"
                       for v in lint_source(src, "bench.py"))

    def test_allow_suppression(self):
        vs = lint("""
            import jax

            # tpulint: allow[R012] — bound under an install-order test
            prog = jax.jit(lambda x: x + 1)
        """, audit=True)
        assert vs == []


class TestR012MemoizedJit:
    """R012 memoization arm (ISSUE 16 / ROADMAP #6 residual): a
    jit-derived program stored into a module-level memo dict inside a
    HOT-path module bypasses the AotProgram factory — warm restarts
    re-compile every shape class and the census pre-warm cannot replay
    the program. `aot.wrap(fn, name, key)` before memoizing is the
    blessed shape and passes."""

    def test_bad_memoized_jit_assignment(self):
        vs = lint("""
            import jax
            from functools import partial

            _PROGRAMS = {}

            def program(key, chunk):
                prog = _PROGRAMS.get(key)
                if prog is None:
                    prog = jax.jit(lambda x: x + 1)
                    _PROGRAMS[key] = prog
                return prog
        """, hot=True)
        assert rules_of(vs) == ["R012"]
        assert "parallel.aot.wrap" in vs[0].message

    def test_bad_direct_subscript_store_and_partial_jit(self):
        vs = lint("""
            import jax
            from functools import partial

            _CACHE: dict = {}

            def program(k):
                _CACHE[k] = partial(jax.jit, static_argnames=("n",))(
                    lambda x, n: x * n)
                return _CACHE[k]
        """, hot=True)
        assert rules_of(vs) == ["R012"]

    def test_bad_setdefault_store(self):
        vs = lint("""
            import jax

            _P = {}

            def program(k):
                _P.setdefault(k, jax.jit(lambda x: x))
                return _P[k]
        """, hot=True)
        assert rules_of(vs) == ["R012"]

    def test_good_wrapped_before_memoizing(self):
        # the blessed shape: route through the AotProgram factory first
        vs = lint("""
            import jax

            _PROGRAMS = {}

            def program(key):
                prog = _PROGRAMS.get(key)
                if prog is None:
                    from elasticsearch_tpu.parallel import aot
                    prog = _PROGRAMS[key] = aot.wrap(
                        jax.jit(lambda x: x + 1), "score", key)
                return prog
        """, hot=True)
        assert vs == []

    def test_good_non_jit_values_and_cold_path(self):
        # memoizing arbitrary values is fine; so is the same store in a
        # module outside the hot-path packages
        src = """
            import jax

            _PROGRAMS = {}

            def program(key):
                _PROGRAMS[key] = {"meta": key}
                return _PROGRAMS[key]

            def cold(key):
                prog = jax.jit(lambda x: x)
                return prog(1)
        """
        assert lint(src, hot=True) == []
        vs = lint("""
            import jax

            _P = {}

            def program(k):
                _P[k] = jax.jit(lambda x: x)
                return _P[k]
        """, hot=False)
        assert vs == []

    def test_allow_suppression(self):
        vs = lint("""
            import jax

            _P = {}

            def program(k):
                # tpulint: allow[R012] — eager first-call latch by design
                _P[k] = jax.jit(lambda x: x)
                return _P[k]
        """, hot=True)
        assert vs == []


class TestPqTierFixtures:
    """PQ-tier discipline (ISSUE 9): the codebook BUILD path is a
    host-side freeze-time scan and must carry `# tpulint: host` (R003),
    and code-array/codebook placement must route through the residency
    choke point instead of raw jax.device_put (R008). Fixture versions
    of ops/pq.py's two discipline points, plus a direct clean lint of
    the real module (it is NEW — no baseline entries shield it)."""

    def test_bad_unannotated_pq_build_live_scan(self):
        # build_pq's live-row scan without the host annotation
        vs = lint("""
            import numpy as np
            def build_pq(vecs, exists):
                ids = np.nonzero(exists)[0]
                return vecs[ids]
        """, ops=True)
        assert rules_of(vs) == ["R003"]

    def test_good_pq_build_host_annotated(self):
        vs = lint("""
            import numpy as np
            def build_pq(vecs, exists):
                ids = np.nonzero(exists)[0]  # tpulint: host
                return vecs[ids]
        """, ops=True)
        assert vs == []

    def test_bad_code_array_raw_device_put(self):
        # placing the uint8 code slab around the accounting
        vs = lint("""
            import jax
            def place_pq(parts):
                codes = jax.device_put(parts.codes)
                books = jax.device_put(parts.codebooks)
                return codes, books
        """, budget=True)
        assert [v.rule for v in vs] == ["R008", "R008"]

    def test_good_code_array_through_residency(self):
        # the real shape: evictable fielddata handle for the codes,
        # accounted device_put for the codebooks
        vs = lint("""
            from elasticsearch_tpu import resources
            def place_pq(parts):
                handle = resources.RESIDENCY.put_array(
                    parts.codes, label="pq.codes", tier="fielddata",
                    best_effort=True)
                books = resources.RESIDENCY.device_put(
                    parts.codebooks, label="pq.codebooks")
                return handle, books
        """, budget=True)
        assert vs == []

    def test_real_pq_module_is_clean(self):
        import pathlib

        mod = (pathlib.Path(__file__).resolve().parents[2]
               / "elasticsearch_tpu" / "ops" / "pq.py")
        assert lint_source(mod.read_text(),
                           "elasticsearch_tpu/ops/pq.py") == []


class TestSuppression:
    def test_same_line_allow(self):
        vs = lint("""
            import jax

            @jax.jit
            def f(x):
                if x > 0:  # tpulint: allow[R004]
                    return x
                return -x
        """)
        assert vs == []

    def test_preceding_comment_allow(self):
        vs = lint("""
            import jax

            @jax.jit
            def f(x):
                # tpulint: allow[R004] — measured: trace-time constant here
                if x > 0:
                    return x
                return -x
        """)
        assert vs == []

    def test_comment_block_with_blank_line_before_code(self):
        vs = lint("""
            import jax

            @jax.jit
            def f(x):
                # tpulint: allow[R004] — justification paragraph that ends
                # with a blank line before the code, a common style

                if x > 0:
                    return x
                return -x
        """)
        assert vs == []

    def test_allow_is_rule_specific(self):
        vs = lint("""
            import jax

            @jax.jit
            def f(x):
                if x > 0:  # tpulint: allow[R001]
                    return x
                return -x
        """)
        assert rules_of(vs) == ["R004"]

    def test_multi_rule_allow(self):
        vs = lint("""
            import jax
            import jax.numpy as jnp

            @jax.jit
            def f(x):
                return jnp.nonzero(x > 0)  # tpulint: allow[R003, R004]
        """)
        assert vs == []


# ---------------------------------------------------------------------------
# baseline
# ---------------------------------------------------------------------------

class TestBaseline:
    def _v(self, rule="R002", path="a.py", line=5, snippet="x = y[i]"):
        return Violation(rule, path, line, 0, "msg", snippet)

    def test_budget_consumed_per_occurrence(self):
        from collections import Counter
        vs = [self._v(line=5), self._v(line=9)]
        budget = Counter({("R002", "a.py", "x = y[i]"): 1})
        new, old = filter_baselined(vs, budget)
        assert len(old) == 1 and len(new) == 1  # duplication still gates

    def test_line_number_drift_still_matches(self):
        from collections import Counter
        budget = Counter({("R002", "a.py", "x = y[i]"): 1})
        new, old = filter_baselined([self._v(line=999)], budget)
        assert new == [] and len(old) == 1

    def test_justification_required(self, tmp_path):
        p = tmp_path / "baseline.json"
        p.write_text(json.dumps({"violations": [
            {"rule": "R002", "path": "a.py", "snippet": "x", "count": 1}
        ]}))
        with pytest.raises(ValueError, match="justification"):
            load_baseline(str(p))

    def test_missing_baseline_is_empty(self, tmp_path):
        assert load_baseline(str(tmp_path / "nope.json")) == {}


# ---------------------------------------------------------------------------
# CLI / --json
# ---------------------------------------------------------------------------

class TestCli:
    BAD = textwrap.dedent("""
        import jax

        @jax.jit
        def f(x):
            if x > 0:
                return x
            return -x
    """)

    def test_json_mode_and_exit_codes(self, tmp_path, capsys):
        from tools.tpulint.__main__ import main

        target = tmp_path / "ops"
        target.mkdir()
        bad = target / "bad.py"
        bad.write_text(self.BAD)
        rc = main([str(bad), "--json",
                   "--baseline", str(tmp_path / "none.json")])
        out = json.loads(capsys.readouterr().out)
        assert rc == 1
        assert out["counts"] == {"new": 1, "baselined": 0}
        (v,) = out["violations"]
        assert v["rule"] == "R004" and v["path"] == str(bad)
        assert "rules" in out  # rule legend rides along for tooling

    def test_write_baseline_then_clean(self, tmp_path, capsys):
        from tools.tpulint.__main__ import main

        bad = tmp_path / "bad.py"
        bad.write_text(self.BAD)
        bl = tmp_path / "baseline.json"
        assert main([str(bad), "--write-baseline",
                     "--baseline", str(bl)]) == 0
        capsys.readouterr()
        assert main([str(bad), "--baseline", str(bl)]) == 0
        assert main([str(bad), "--baseline", str(bl),
                     "--no-baseline"]) == 1

    def test_clean_file_exits_zero(self, tmp_path, capsys):
        from tools.tpulint.__main__ import main

        good = tmp_path / "good.py"
        good.write_text("def f(x):\n    return x\n")
        assert main([str(good),
                     "--baseline", str(tmp_path / "none.json")]) == 0

    def test_missing_path_is_an_error_not_clean(self, tmp_path, capsys):
        # a typo'd path must not silently lint nothing and report green
        from tools.tpulint.__main__ import main

        assert main([str(tmp_path / "renamed_away"),
                     "--baseline", str(tmp_path / "none.json")]) == 2


# ---------------------------------------------------------------------------
# whole-program pass: call-graph propagation, R013, R014, --changed
# ---------------------------------------------------------------------------

from tools.tpulint import lint_sources  # noqa: E402


class TestCallGraphPropagation:
    """Traced-context inference (tpulint v2 tentpole): violations
    surface through helper calls across modules — no path allowlist
    involved — and the existing annotation machinery keeps working at
    the helper."""

    THREE_MODULES = {
        "pkg/a.py": """
import jax
from pkg.b import helper

@jax.jit
def entry(x):
    return helper(x)
""",
        "pkg/b.py": """
from pkg.c import deep

def helper(x):
    return deep(x)
""",
        "pkg/c.py": """
import jax.numpy as jnp

def deep(x):
    return jnp.nonzero(x > 0)
""",
    }

    def test_violation_two_calls_deep(self):
        vs = lint_sources(self.THREE_MODULES)
        assert [(v.rule, v.path) for v in vs] == [("R003", "pkg/c.py")]
        assert "deep" in vs[0].message

    def test_annotation_at_the_helper_suppresses(self):
        srcs = dict(self.THREE_MODULES)
        srcs["pkg/c.py"] = srcs["pkg/c.py"].replace(
            "return jnp.nonzero(x > 0)",
            "return jnp.nonzero(x > 0)  # tpulint: allow[R003]")
        assert lint_sources(srcs) == []

    def test_single_file_mode_cannot_see_it(self):
        # the blind spot the whole-program pass exists for: per-file
        # linting of the helper alone reports nothing
        assert lint_source(textwrap.dedent(self.THREE_MODULES["pkg/c.py"]),
                           "pkg/c.py") == []

    def test_metrics_record_reachable_from_jit_body(self):
        vs = lint_sources({
            "q/a.py": """
import jax
from q.m import note

@jax.jit
def run(x):
    note()
    return x
""",
            "q/m.py": """
from elasticsearch_tpu.monitor import metrics

REG = metrics.MetricsRegistry()
C = REG.counter("hits")

def note():
    C.inc()
""",
        })
        assert [(v.rule, v.path) for v in vs] == [("R009", "q/m.py")]

    def test_item_in_traced_helper_fires_without_hot_path(self):
        # R002's traced branch follows the graph, not HOT_PATH_MARKERS:
        # a cluster-layer helper reached from a jit body still flags
        vs = lint_sources({
            "elasticsearch_tpu/cluster/extra.py": """
def pull_scalar(x):
    return x.item()
""",
            "elasticsearch_tpu/ops/entry2.py": """
import jax
from elasticsearch_tpu.cluster.extra import pull_scalar

@jax.jit
def go(x):
    return pull_scalar(x)
""",
        })
        assert [(v.rule, v.path) for v in vs] == [
            ("R002", "elasticsearch_tpu/cluster/extra.py")]

    def test_static_config_through_helpers_stays_static(self):
        # the dataflow refinement: closure config (metric strings, shape
        # ints, .shape/.dtype reads) classifies static at call sites, so
        # helpers branching on them don't false-fire R004
        vs = lint_sources({
            "pkg2/prog.py": """
import jax

from pkg2.helper import score

def make(metric, k):
    def body(x):
        kp = min(4 * k, 128)
        return score(x, kp, metric)
    return jax.jit(body)
""",
            "pkg2/helper.py": """
import jax.numpy as jnp

def score(x, k, metric):
    if metric == "l2":
        x = -x
    if x.dtype == jnp.bfloat16:
        x = x.astype(jnp.float32)
    if k > 8:
        return x * 2
    return x
""",
        })
        assert vs == []

    def test_dynamic_arg_through_helper_still_traced(self):
        # ...but an argument derived from the traced value DOES trace
        vs = lint_sources({
            "pkg3/prog.py": """
import jax

from pkg3.helper import gate

def make():
    def body(x):
        return gate(x, x.sum())
    return jax.jit(body)
""",
            "pkg3/helper.py": """
def gate(x, threshold):
    if threshold > 0:
        return x
    return -x
""",
        })
        assert [(v.rule, v.path) for v in vs] == [("R004", "pkg3/helper.py")]


class TestR013LockOrder:
    """Interprocedural lock-order analysis: held→acquired edges across
    modules, cycle detection, and lock-held calls into unbounded waits
    (the R010 hazard generalized past serving/)."""

    CYCLE = {
        "l/a.py": """
import threading
from l.b import take_b

LOCK_A = threading.Lock()

def f():
    with LOCK_A:
        take_b()
""",
        "l/b.py": """
import threading
from l.c import take_c

LOCK_B = threading.Lock()

def take_b():
    with LOCK_B:
        take_c()
""",
        "l/c.py": """
import threading
import l.a

LOCK_C = threading.Lock()

def take_c():
    with LOCK_C:
        with l.a.LOCK_A:
            pass
""",
    }

    def test_three_lock_cycle_across_modules(self):
        vs = [v for v in lint_sources(self.CYCLE) if v.rule == "R013"]
        assert vs, "3-lock cycle not detected"
        assert any("lock-order cycle" in v.message for v in vs)
        # the cycle names the participating locks with witness sites
        msg = next(v.message for v in vs if "lock-order cycle" in v.message)
        assert "LOCK_A" in msg and ".py:" in msg

    def test_consistent_global_order_is_clean(self):
        vs = lint_sources({
            "g/a.py": """
import threading

LOCK_A = threading.Lock()
LOCK_B = threading.Lock()

def f():
    with LOCK_A:
        with LOCK_B:
            pass
""",
            "g/b.py": """
from g.a import LOCK_A, LOCK_B

def g():
    with LOCK_A:
        with LOCK_B:
            pass
""",
        })
        assert vs == []

    def test_lock_held_call_into_unbounded_wait(self):
        vs = lint_sources({
            "w/a.py": """
import threading
from w.b import drain

LOCK = threading.Lock()

def f():
    with LOCK:
        drain()
""",
            "w/b.py": """
import threading

EVT = threading.Event()

def drain():
    EVT.wait()
""",
        })
        assert [(v.rule, v.path) for v in vs] == [("R013", "w/a.py")]
        assert "Event.wait()" in vs[0].message

    def test_bounded_wait_through_call_is_clean(self):
        vs = lint_sources({
            "w2/a.py": """
import threading
from w2.b import drain

LOCK = threading.Lock()

def f():
    with LOCK:
        drain()
""",
            "w2/b.py": """
import threading

EVT = threading.Event()

def drain():
    EVT.wait(timeout=0.5)
""",
        })
        assert vs == []

    def test_direct_unbounded_wait_under_lock_outside_serving(self):
        vs = lint_sources({
            "d/a.py": """
import threading

LOCK = threading.Lock()
EVT = threading.Event()

def f():
    with LOCK:
        EVT.wait()
""",
        })
        assert [(v.rule, v.path) for v in vs] == [("R013", "d/a.py")]

    def test_cycle_detector_survives_side_branches(self):
        """A cyclic SCC with a dead-end side branch (a→b, b→c, c→b,
        b→d, d→a): a greedy no-revisit walk strays into the branch and
        reports NOTHING for a genuinely cyclic component — the DFS
        back-edge detector must still find a real cycle."""
        from tools.tpulint.project import _find_cycles

        g = {"a": {"b"}, "b": {"c", "d"}, "c": {"b"}, "d": {"a"}}
        cycles = _find_cycles(g)
        assert cycles, "cyclic SCC reported no cycle"
        for cyc in cycles:
            ring = cyc + [cyc[0]]
            assert all(b in g.get(a, ()) for a, b in zip(ring, ring[1:])), \
                cyc

    def test_inline_allow_suppresses_at_witness(self):
        srcs = dict(self.CYCLE)
        # the A→B edge's witness is the call made while holding LOCK_A
        srcs["l/a.py"] = srcs["l/a.py"].replace(
            "        take_b()",
            "        take_b()  # tpulint: allow[R013] — reviewed: "
            "f() only runs single-threaded at boot")
        vs = [v for v in lint_sources(srcs)
              if v.rule == "R013" and "cycle" in v.message]
        # the cycle's witness line carries the allow — suppressed there
        assert all(v.path != "l/a.py" for v in vs)


class TestR014CollectivePurity:
    """Host syncs inside shard_map/psum programs, reached through the
    call graph (the toy version of the mesh executor's wrap(body, ...)
    idiom)."""

    def test_host_sync_in_shard_map_helper(self):
        vs = lint_sources({
            "s/prog.py": """
import jax
from jax.experimental.shard_map import shard_map

from s.helper import merge

def build(mesh):
    def body(x):
        s = jax.lax.psum(x, "shard")
        return merge(s)
    return jax.jit(shard_map(body, mesh=mesh, in_specs=None,
                             out_specs=None))
""",
            "s/helper.py": """
import jax
import numpy as np

def merge(s):
    host = np.asarray(s)
    jax.device_get(s)
    return host
""",
        })
        r014 = [v for v in vs if v.rule == "R014"]
        assert [v.path for v in r014] == ["s/helper.py", "s/helper.py"]
        assert any("np.asarray" in v.message for v in r014)
        assert any("device_get" in v.message for v in r014)

    def test_pure_collective_program_is_clean(self):
        vs = lint_sources({
            "s2/prog.py": """
import jax
import jax.numpy as jnp
from jax.experimental.shard_map import shard_map

from s2.helper import merge

def build(mesh):
    def body(x):
        s = jax.lax.psum(x, "shard")  # tpulint: masked
        return merge(s)
    return jax.jit(shard_map(body, mesh=mesh, in_specs=None,
                             out_specs=None))
""",
            "s2/helper.py": """
import jax.numpy as jnp

def merge(s):
    return jnp.maximum(s, 0.0) * 2.0
""",
        })
        assert vs == []

    def test_item_and_cast_in_collective_body(self):
        vs = lint_sources({
            "s3/prog.py": """
import jax
from jax import lax

def make(mesh, wrap):
    def body(x, k):
        t = lax.psum(x, "shard")  # tpulint: masked
        n = int(t)
        return t.item() + n
    return wrap(body, None, None)
""",
        })
        assert sorted(v.rule for v in vs) == ["R014", "R014"]
        assert any(".item()" in v.message for v in vs)
        assert any("int(...)" in v.message for v in vs)

    def test_host_math_on_static_closures_is_clean(self):
        # np on static metadata at trace time is legal inside a
        # collective body (the executor's pack_spec unpacking idiom)
        vs = lint_sources({
            "s4/prog.py": """
import jax
import numpy as np
from jax import lax

def make(mesh, wrap, shapes):
    def body(x):
        n = int(np.prod(shapes[0]))
        return lax.psum(x[:n], "shard")  # tpulint: masked
    return wrap(body, None, None)
""",
        })
        assert vs == []


class TestR015Lockset:
    """Eraser-style per-attribute lockset inference over CONCURRENT
    reach: a write without the inferred (or declared) guard, reachable
    from a thread root, is a race."""

    # the canonical bad shape: the unguarded write sits TWO calls deep
    # from a Thread target, in a class whose other accesses are locked
    RACY = {
        "r15/svc.py": """
import threading


class Registry:
    def __init__(self):
        self._lock = threading.Lock()
        self._state = []

    def record(self, item):
        with self._lock:
            self._state.append(item)

    def snapshot(self):
        with self._lock:
            return list(self._state)

    def reset_unlocked(self):
        self._state = []


REGISTRY = Registry()
""",
        "r15/worker.py": """
import threading

from r15.svc import REGISTRY


def step():
    REGISTRY.reset_unlocked()


def worker():
    step()


def spawn():
    t = threading.Thread(target=worker, daemon=True)
    t.start()
""",
    }

    def test_unguarded_write_two_calls_from_thread_root(self):
        vs = [v for v in lint_sources(self.RACY) if v.rule == "R015"]
        assert [(v.path, "self._state" in v.message) for v in vs] == \
            [("r15/svc.py", True)]
        assert "Registry._lock" in vs[0].message

    def test_without_thread_root_stays_clean(self):
        srcs = dict(self.RACY)
        srcs["r15/worker.py"] = srcs["r15/worker.py"].replace(
            "    t = threading.Thread(target=worker, daemon=True)\n"
            "    t.start()", "    worker()")
        assert [v for v in lint_sources(srcs) if v.rule == "R015"] == []

    def test_pool_submission_is_a_thread_root(self):
        srcs = dict(self.RACY)
        srcs["r15/worker.py"] = """
from r15.pool import POOL
from r15.svc import REGISTRY


def step():
    REGISTRY.reset_unlocked()


def worker():
    step()


def spawn():
    POOL.execute(worker)
"""
        srcs["r15/pool.py"] = """
class FixedThreadPool:
    def execute(self, fn, *args):
        return fn(*args)


POOL = FixedThreadPool()
"""
        vs = [v for v in lint_sources(srcs) if v.rule == "R015"]
        assert [v.path for v in vs] == ["r15/svc.py"]

    def test_guarded_by_annotation_declares_the_guard(self):
        # only ONE guarded access: majority inference alone would stay
        # silent — the declaration makes the discipline explicit
        vs = lint_sources({
            "g15/svc.py": """
import threading

from g15.run import spawn


class Census:
    def __init__(self):
        self._lock = threading.Lock()
        # tpulint: guarded_by(self._lock)
        self._gens = {}

    def bump(self, k):
        with self._lock:
            self._gens[k] = self._gens.get(k, 0) + 1

    def forget(self, k):
        self._gens.pop(k, None)


CENSUS = Census()
""",
            "g15/run.py": """
import threading

from g15 import svc


def worker():
    svc.CENSUS.bump("a")
    svc.CENSUS.forget("a")


def spawn():
    threading.Thread(target=worker, daemon=True).start()
""",
        })
        hits = [v for v in vs if v.rule == "R015"]
        assert [("forget" in v.snippet or "pop" in v.snippet)
                for v in hits] == [True]
        assert "guarded_by" in hits[0].message

    def test_unresolvable_guarded_by_is_flagged(self):
        # a typo'd declaration must SURFACE, not silently downgrade to
        # majority inference (which here would check nothing)
        vs = lint_sources({
            "b15/svc.py": """
import threading

from b15.run import spawn


class Census:
    def __init__(self):
        self._lock = threading.Lock()
        # tpulint: guarded_by(self._lok)
        self._gens = {}

    def bump(self, k):
        with self._lock:
            self._gens[k] = self._gens.get(k, 0) + 1

    def forget(self, k):
        self._gens.pop(k, None)


CENSUS = Census()
""",
            "b15/run.py": """
import threading

from b15 import svc


def worker():
    svc.CENSUS.bump("a")
    svc.CENSUS.forget("a")


def spawn():
    threading.Thread(target=worker, daemon=True).start()
""",
        })
        hits = [v for v in vs if v.rule == "R015"]
        assert any("does not resolve" in v.message
                   and "self._lok" in v.message for v in hits), hits

    def test_init_then_publish_stays_clean(self):
        # lock-free init-before-publish: __init__ builds state unshared;
        # the thread only READS afterwards — no inference, no finding
        vs = lint_sources({
            "i15/svc.py": """
import threading


class Holder:
    def __init__(self, items):
        self._items = list(items)
        self._ready = True

    def view(self):
        return list(self._items)


def worker(h):
    h.view()


def spawn():
    h = Holder([1, 2])
    threading.Thread(target=worker, daemon=True).start()
""",
        })
        assert [v for v in vs if v.rule in ("R015", "R016")] == []

    def test_caller_locked_private_helper_is_guarded(self):
        # the `_private runs caller-locked` convention: every call site
        # holds the lock, so the helper's writes count as guarded (the
        # held-on-entry meet — no false positive)
        vs = lint_sources({
            "p15/svc.py": """
import threading


class Engine:
    def __init__(self):
        self._lock = threading.Lock()
        self._docs = {}

    def index(self, k, v):
        with self._lock:
            self._remove_existing(k)
            self._docs[k] = v

    def delete(self, k):
        with self._lock:
            self._remove_existing(k)

    def _remove_existing(self, k):
        self._docs.pop(k, None)


ENGINE = Engine()


def worker():
    ENGINE.index("a", 1)
    ENGINE.delete("a")


def spawn():
    import threading
    threading.Thread(target=worker, daemon=True).start()
""",
        })
        assert [v for v in vs if v.rule == "R015"] == []

    def test_inline_allow_suppresses(self):
        srcs = dict(self.RACY)
        srcs["r15/svc.py"] = srcs["r15/svc.py"].replace(
            "        self._state = []\n\n\nREGISTRY",
            "        self._state = []  # tpulint: allow[R015] — "
            "reviewed: reset only runs in tests\n\n\nREGISTRY")
        assert [v for v in lint_sources(srcs) if v.rule == "R015"] == []


class TestR016Atomicity:
    """Check-then-act across a lock release: a read-only guarded region
    followed by a later BLIND guarded write of the same attribute."""

    BAD = {
        "a16/svc.py": """
import threading


class Cache:
    def __init__(self):
        self._lock = threading.Lock()
        self._entries = {}

    def get_or_make(self, k, build):
        with self._lock:
            v = self._entries.get(k)
        if v is None:
            v = build(k)
            with self._lock:
                self._entries[k] = v
        return v


CACHE = Cache()


def worker():
    CACHE.get_or_make("a", lambda k: k)


def spawn():
    threading.Thread(target=worker, daemon=True).start()
""",
    }

    def test_released_check_then_act_flags(self):
        vs = [v for v in lint_sources(self.BAD) if v.rule == "R016"]
        assert [v.path for v in vs] == ["a16/svc.py"]
        assert "self._entries" in vs[0].message
        assert "released between" in vs[0].message

    def test_held_through_is_clean(self):
        srcs = {"a16/svc.py": self.BAD["a16/svc.py"].replace(
            """        with self._lock:
            v = self._entries.get(k)
        if v is None:
            v = build(k)
            with self._lock:
                self._entries[k] = v
        return v""",
            """        with self._lock:
            v = self._entries.get(k)
            if v is None:
                v = build(k)
                self._entries[k] = v
        return v""")}
        assert [v for v in lint_sources(srcs) if v.rule == "R016"] == []

    def test_revalidated_act_is_clean(self):
        # double-checked under the lock: the act region re-reads before
        # writing — the stale-check window is closed
        srcs = {"a16/svc.py": self.BAD["a16/svc.py"].replace(
            """            with self._lock:
                self._entries[k] = v""",
            """            with self._lock:
                if k not in self._entries:
                    self._entries[k] = v""")}
        assert [v for v in lint_sources(srcs) if v.rule == "R016"] == []

    def test_condition_wait_loop_is_legal(self):
        # `with cv: while not pred: cv.wait(...)` then act under the
        # SAME hold — Condition.wait releases and reacquires, but the
        # check and the act share one lexical region
        vs = lint_sources({
            "c16/svc.py": """
import threading


class Mailbox:
    def __init__(self):
        self._cv = threading.Condition()
        self._items = []

    def put(self, item):
        with self._cv:
            self._items.append(item)
            self._cv.notify_all()

    def take(self):
        with self._cv:
            while not self._items:
                self._cv.wait(timeout=0.05)
            return self._items.pop()


BOX = Mailbox()


def worker():
    BOX.put(1)
    BOX.take()


def spawn():
    threading.Thread(target=worker, daemon=True).start()
""",
        })
        assert [v for v in vs if v.rule in ("R015", "R016")] == []

    def test_inline_allow_suppresses(self):
        srcs = {"a16/svc.py": self.BAD["a16/svc.py"].replace(
            "                self._entries[k] = v",
            "                self._entries[k] = v  "
            "# tpulint: allow[R016] — reviewed: last-write-wins is fine "
            "for this cache")}
        assert [v for v in lint_sources(srcs) if v.rule == "R016"] == []


class TestConcurrentReach:
    """The CONCURRENT-REACH fixpoint recognizes every thread-root
    spelling the serving/cluster stack actually uses."""

    def _index(self, sources):
        from tools.tpulint.project import analyze_sources

        index, errors = analyze_sources(
            {k: textwrap.dedent(v) for k, v in sources.items()})
        assert errors == []
        return index

    def test_rest_route_handlers_are_roots(self):
        index = self._index({
            "rr/server.py": """
def _cat_health(node, params, body):
    return {}


def register_all(rc):
    rc.add("GET", "/_cat/health", _cat_health)
""",
        })
        assert "rr.server:_cat_health" in index.concurrent

    def test_transport_register_callbacks_are_roots(self):
        index = self._index({
            "tr/action.py": """
class Service:
    def __init__(self, transport):
        transport.register("indices:data/read", self._on_read)

    def _on_read(self, payload):
        return payload
""",
        })
        assert "tr.action:Service._on_read" in index.concurrent

    def test_plain_calls_do_not_root(self):
        index = self._index({
            "pc/mod.py": """
def helper():
    return 1


def main():
    helper()
""",
        })
        assert index.concurrent == set()


class TestChangedModeAndSeverity:
    BAD = textwrap.dedent("""
        import jax

        @jax.jit
        def f(x):
            if x > 0:
                return x
            return -x
    """)

    def test_severity_in_json(self, tmp_path, capsys):
        from tools.tpulint.__main__ import main

        bad = tmp_path / "bad.py"
        bad.write_text(self.BAD)
        rc = main([str(bad), "--json",
                   "--baseline", str(tmp_path / "none.json")])
        out = json.loads(capsys.readouterr().out)
        assert rc == 1
        (v,) = out["violations"]
        assert v["rule"] == "R004" and v["severity"] == "error"
        assert out["severity"]["R001"] == "warning"
        assert out["severity"]["R013"] == "error"

    def test_changed_mode_filters_to_changed_files(self, tmp_path,
                                                   capsys, monkeypatch):
        import tools.tpulint.__main__ as cli

        bad1 = tmp_path / "one.py"
        bad2 = tmp_path / "two.py"
        bad1.write_text(self.BAD)
        bad2.write_text(self.BAD)
        # both files violate; git says only one changed
        monkeypatch.setattr(cli, "_changed_files",
                            lambda base: [str(bad1)])
        rc = cli.main([str(bad1), str(bad2), "--changed", "HEAD", "--json",
                       "--baseline", str(tmp_path / "none.json")])
        out = json.loads(capsys.readouterr().out)
        assert rc == 1
        assert {v["path"] for v in out["violations"]} == {str(bad1)}

    def test_changed_mode_no_changes_is_clean(self, tmp_path, capsys,
                                              monkeypatch):
        import tools.tpulint.__main__ as cli

        bad = tmp_path / "one.py"
        bad.write_text(self.BAD)
        monkeypatch.setattr(cli, "_changed_files", lambda base: [])
        assert cli.main([str(bad), "--changed", "HEAD",
                         "--baseline", str(tmp_path / "none.json")]) == 0

    def test_per_file_mode_still_available(self, tmp_path, capsys):
        from tools.tpulint.__main__ import main

        bad = tmp_path / "bad.py"
        bad.write_text(self.BAD)
        assert main([str(bad), "--per-file",
                     "--baseline", str(tmp_path / "none.json")]) == 1

    def test_sarif_output(self, tmp_path, capsys):
        """--sarif: SARIF 2.1.0 for CI PR annotation — rule catalogue
        with default severity levels, results with physical locations,
        exit code matching the plain mode."""
        from tools.tpulint.__main__ import main

        bad = tmp_path / "bad.py"
        bad.write_text(self.BAD)
        rc = main([str(bad), "--sarif",
                   "--baseline", str(tmp_path / "none.json")])
        out = json.loads(capsys.readouterr().out)
        assert rc == 1
        assert out["version"] == "2.1.0"
        assert "sarif-schema-2.1.0" in out["$schema"]
        run = out["runs"][0]
        rules = {r["id"]: r for r in run["tool"]["driver"]["rules"]}
        assert {"R001", "R004", "R015", "R016"} <= set(rules)
        assert rules["R015"]["defaultConfiguration"]["level"] == "error"
        assert rules["R001"]["defaultConfiguration"]["level"] == "warning"
        (res,) = run["results"]
        assert res["ruleId"] == "R004" and res["level"] == "error"
        loc = res["locations"][0]["physicalLocation"]
        assert loc["artifactLocation"]["uri"].endswith("bad.py")
        assert loc["region"]["startLine"] >= 1
        assert loc["region"]["snippet"]["text"]

    def test_sarif_baselined_findings_carry_suppressions(self, tmp_path,
                                                         capsys):
        from tools.tpulint.__main__ import main
        from tools.tpulint.baseline import write_baseline
        from tools.tpulint.project import lint_project

        bad = tmp_path / "bad.py"
        bad.write_text(self.BAD)
        found = lint_project([str(bad)])
        base = tmp_path / "base.json"
        doc = write_baseline(found, str(base))
        for v in doc["violations"]:
            v["justification"] = "test fixture"
        base.write_text(json.dumps(doc))
        rc = main([str(bad), "--sarif", "--baseline", str(base)])
        out = json.loads(capsys.readouterr().out)
        assert rc == 0  # fully baselined: clean exit, audit trail kept
        (res,) = out["runs"][0]["results"]
        assert res["suppressions"][0]["kind"] == "external"


# ---------------------------------------------------------------------------
# runtime trace auditor
# ---------------------------------------------------------------------------

class TestTraceAudit:
    def test_counts_and_steady_state(self):
        import jax
        import jax.numpy as jnp

        from tools.tpulint.trace_audit import (TraceBudgetExceeded,
                                               trace_audit)

        with trace_audit() as audit:
            @jax.jit
            def f(x):
                return x * 2

            f(jnp.ones((8,)))
            f(jnp.ones((8,)))  # cache hit — no new trace
            snap = audit.snapshot()
            f(jnp.ones((8,)))
            audit.assert_no_new_traces_since(snap)  # steady state holds
            f(jnp.ones((16,)))  # new shape class → retrace
            with pytest.raises(TraceBudgetExceeded):
                audit.assert_no_new_traces_since(snap)
            assert audit.total() == 2
        # the context detaches ITS auditor; jax.jit reverts to pristine
        # only when no auditor remains — the package installs a process-
        # global one at import for the search profiler's compile/execute
        # split (tracing/retrace.py), which legitimately stays
        from tools.tpulint import trace_audit as ta

        assert audit not in ta._active
        if not ta._active:
            assert not getattr(jax.jit, "__tpulint_counting__", False)

    def test_budget_enforced_at_trace_time(self):
        import jax
        import jax.numpy as jnp

        from tools.tpulint.trace_audit import (TraceBudgetExceeded,
                                               trace_audit)

        with trace_audit(max_traces=1):
            @jax.jit
            def g(x):
                return x + 1

            g(jnp.ones((4,)))
            with pytest.raises(TraceBudgetExceeded):
                g(jnp.ones((5,)))

    def test_partial_jit_idiom_counted(self):
        # the codebase's @partial(jax.jit, static_argnames=...) pattern
        from functools import partial

        import jax
        import jax.numpy as jnp

        from tools.tpulint.trace_audit import trace_audit

        with trace_audit() as audit:
            @partial(jax.jit, static_argnames=("n",))
            def f(x, *, n):
                return x * n

            f(jnp.ones((4,)), n=2)
            f(jnp.ones((4,)), n=2)
            f(jnp.ones((4,)), n=3)  # new static value → retrace
            assert audit.total() == 2


# ---------------------------------------------------------------------------
# hybrid retrieval fixtures: the fused stage-1 / MaxSim stage-2 pipeline's
# failure modes, phrased as minimal reproducers (R003, R009)
# ---------------------------------------------------------------------------

class TestHybridFixtures:
    def test_bad_boolean_mask_candidate_set_in_fused_program(self):
        # candidate gating inside the fused program must be a bit-vector
        # where(), never a data-dependent boolean gather
        vs = lint("""
            import jax
            import jax.numpy as jnp

            @jax.jit
            def fuse(lex_scores, vec_scores, vec_rank, kc):
                cand = vec_scores[vec_rank < kc]
                return lex_scores + jnp.sum(cand)
        """)
        assert rules_of(vs) == ["R003"]

    def test_good_bit_vector_candidate_gate(self):
        vs = lint("""
            import jax
            import jax.numpy as jnp

            @jax.jit
            def fuse(lex_scores, vec_scores, vec_rank, kc):
                vm = vec_rank < kc
                return lex_scores + jnp.where(vm, vec_scores, 0.0)
        """)
        assert vs == []

    def test_bad_rerank_admission_counter_inside_traced_body(self):
        # stage-2 admit/decline counters are host-side admission
        # decisions; recording inside the traced MaxSim body is R009
        vs = lint("""
            import jax
            from elasticsearch_tpu.monitor.metrics import SHARED

            @jax.jit
            def maxsim_window(tokens, vecs):
                SHARED.counter("estpu_hybrid_rerank_total").inc()
                return (tokens @ vecs.T).max(axis=0)
        """)
        assert rules_of(vs) == ["R009"]

    def test_bad_fused_score_recorded_as_device_array(self):
        vs = lint("""
            import jax.numpy as jnp
            from elasticsearch_tpu.monitor.metrics import SHARED

            def after_fuse(fused):
                SHARED.histogram("estpu_hybrid_top").observe(
                    jnp.max(fused))
        """)
        assert rules_of(vs) == ["R009"]

    def test_good_host_pull_then_admission_counter(self):
        vs = lint("""
            import jax
            import jax.numpy as jnp
            from elasticsearch_tpu.monitor.metrics import SHARED

            def rerank(window, score_fn):
                out = score_fn(window)
                top = float(jax.device_get(jnp.max(out)))
                SHARED.counter("estpu_hybrid_rerank_total").labels(
                    decision="admit").inc()
                SHARED.histogram("estpu_hybrid_top").observe(top)
                return out
        """)
        assert vs == []
