"""Ring-attention sequence-parallel encode vs the plain encoder.

The 'sp' path (models/ring_encoder.py) must produce the same embeddings as
`model.apply` — same params, same masking, exact online-softmax — up to
bf16 matmul tolerance, on an 8-device ('sp',) mesh.
"""
import numpy as np
import pytest

from elasticsearch_tpu.models.dual_encoder import (DualEncoderConfig,
                                                   SimpleTokenizer,
                                                   build_model, init_params)
from elasticsearch_tpu.models.ring_encoder import build_sp_mesh, ring_encode


@pytest.fixture(scope="module")
def setup(eight_devices):
    cfg = DualEncoderConfig(vocab_size=512, max_len=64, d_model=64,
                            n_heads=4, n_layers=2, d_ff=128, embed_dim=32)
    model = build_model(cfg)
    params = init_params(cfg, seed=3)
    return cfg, model, params


def _batch(cfg, rng, B, L, ragged=True):
    ids = rng.integers(1, cfg.vocab_size, size=(B, L)).astype(np.int32)
    mask = np.ones((B, L), np.float32)
    if ragged:
        for i in range(B):
            n = rng.integers(L // 3, L + 1)
            ids[i, n:] = 0
            mask[i, n:] = 0.0
    return ids, mask


def test_ring_encode_matches_dense(setup):
    cfg, model, params = setup
    rng = np.random.default_rng(0)
    ids, mask = _batch(cfg, rng, B=4, L=cfg.max_len)
    dense = np.asarray(model.apply(params, ids, mask))
    mesh = build_sp_mesh(8)
    ring = np.asarray(ring_encode(cfg, params, ids, mask, mesh))
    assert ring.shape == dense.shape
    # unit vectors: compare by cosine (bf16 matmul order differs)
    cos = np.sum(ring * dense, axis=-1)
    assert np.all(cos > 0.999), cos
    np.testing.assert_allclose(ring, dense, atol=3e-2)


def test_ring_encode_pads_ragged_length(setup):
    """L not divisible by S: ring_encode right-pads with mask 0 and the
    padding must not change the embedding."""
    cfg, model, params = setup
    rng = np.random.default_rng(1)
    L = 30  # not a multiple of 8
    ids, mask = _batch(cfg, rng, B=2, L=L, ragged=False)
    dense = np.asarray(model.apply(params, ids, mask))
    mesh = build_sp_mesh(8)
    ring = np.asarray(ring_encode(cfg, params, ids, mask, mesh))
    cos = np.sum(ring * dense, axis=-1)
    assert np.all(cos > 0.999), cos


def test_ring_encode_padding_may_cross_max_len(eight_devices):
    """L == cfg.max_len with max_len not divisible by S: the ring pads past
    max_len with mask-0 positions (clipped position ids) — valid input must
    not be rejected and the result must match dense."""
    cfg = DualEncoderConfig(vocab_size=256, max_len=60, d_model=32,
                            n_heads=2, n_layers=1, d_ff=64, embed_dim=16)
    model = build_model(cfg)
    params = init_params(cfg, seed=9)
    rng = np.random.default_rng(4)
    ids, mask = _batch(cfg, rng, B=2, L=60, ragged=False)
    dense = np.asarray(model.apply(params, ids, mask))
    ring = np.asarray(ring_encode(cfg, params, ids, mask, build_sp_mesh(8)))
    cos = np.sum(ring * dense, axis=-1)
    assert np.all(cos > 0.999), cos


def test_ring_encode_rejects_overlong(setup):
    cfg, model, params = setup
    mesh = build_sp_mesh(8)
    ids = np.zeros((1, cfg.max_len + 8), np.int32)
    mask = np.ones((1, cfg.max_len + 8), np.float32)
    with pytest.raises(ValueError):
        ring_encode(cfg, params, ids, mask, mesh)


def test_ring_encode_long_context_smoke(eight_devices):
    """A sequence length the dense path would spend [B,H,L,L] memory on:
    per-device ring peak is [B, H, L/8, L/8] — 64x smaller."""
    cfg = DualEncoderConfig(vocab_size=512, max_len=1024, d_model=64,
                            n_heads=4, n_layers=1, d_ff=128, embed_dim=32)
    params = init_params(cfg, seed=5)
    tok = SimpleTokenizer(cfg)
    ids, mask = tok(["long document " * 300], max_len=1024)
    mesh = build_sp_mesh(8)
    out = np.asarray(ring_encode(cfg, params, ids, mask, mesh))
    assert out.shape == (1, 32)
    assert np.all(np.isfinite(out))
    np.testing.assert_allclose(np.linalg.norm(out, axis=-1), 1.0, atol=1e-3)
