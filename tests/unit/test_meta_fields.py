"""Meta-field batch (round-3 verdict task 8): _timestamp, _ttl,
_field_names, _size — mapping + index + query round trips.

Reference: mapper/internal/TimestampFieldMapper.java:1-336,
TTLFieldMapper.java:1-228, SizeFieldMapper.java, FieldNamesFieldMapper.java.
"""
import time

import pytest

from elasticsearch_tpu.node import Node


def test_timestamp_indexed_and_range_queryable():
    n = Node()
    n.create_index("ts", {"mappings": {
        "_timestamp": {"enabled": True},
        "properties": {"t": {"type": "text"}}}})
    svc = n.indices["ts"]
    svc.index_doc("old", {"t": "x"}, timestamp="2020-01-01")
    svc.index_doc("new", {"t": "x"}, timestamp="2023-06-15")
    svc.index_doc("auto", {"t": "x"})  # default: now
    svc.refresh()
    r = n.search("ts", {"query": {"range": {"_timestamp": {
        "gte": "2022-01-01", "lte": "2024-01-01"}}}})
    assert sorted(h["_id"] for h in r["hits"]["hits"]) == ["new"]
    r2 = n.search("ts", {"query": {"range": {"_timestamp": {
        "lte": int(time.time() * 1000) + 1000}}}})
    assert r2["hits"]["total"] == 3
    # sortable like any date column
    r3 = n.search("ts", {"query": {"match_all": {}},
                         "sort": [{"_timestamp": "asc"}], "size": 3})
    assert [h["_id"] for h in r3["hits"]["hits"]][:2] == ["old", "new"]
    n.close()


def test_ttl_purges_on_refresh_and_merge():
    n = Node()
    n.create_index("tt", {"mappings": {
        "_timestamp": {"enabled": True},
        "_ttl": {"enabled": True},
        "properties": {"t": {"type": "text"}}}})
    svc = n.indices["tt"]
    svc.index_doc("dead", {"t": "x"}, ttl=1)  # expires ~immediately
    svc.index_doc("alive", {"t": "x"}, ttl="1h")
    svc.index_doc("forever", {"t": "x"})  # no ttl
    time.sleep(0.01)
    svc.refresh()  # purge runs before freeze
    r = n.search("tt", {"query": {"match_all": {}}})
    assert sorted(h["_id"] for h in r["hits"]["hits"]) == ["alive", "forever"]
    assert not svc.get_doc("dead")["found"]
    # expiry survives a merge (meta carries the resolved value)
    svc.index_doc("dead2", {"t": "x"}, ttl=1)
    svc.refresh()
    time.sleep(0.01)
    svc.force_merge(1)
    svc.refresh()
    r2 = n.search("tt", {"query": {"match_all": {}}})
    assert sorted(h["_id"] for h in r2["hits"]["hits"]) == ["alive", "forever"]
    n.close()


def test_field_names_backs_exists_queries():
    n = Node()
    n.create_index("fn", {"mappings": {"properties": {
        "a": {"type": "text"}, "b": {"type": "long"}}}})
    svc = n.indices["fn"]
    svc.index_doc("1", {"a": "hello"})
    svc.index_doc("2", {"b": 7})
    svc.index_doc("3", {"a": "world", "b": 9})
    svc.refresh()
    r = n.search("fn", {"query": {"term": {"_field_names": "a"}}})
    assert sorted(h["_id"] for h in r["hits"]["hits"]) == ["1", "3"]
    r2 = n.search("fn", {"query": {"term": {"_field_names": "b"}}})
    assert sorted(h["_id"] for h in r2["hits"]["hits"]) == ["2", "3"]
    # missing = NOT _field_names (the reference implements missing this way)
    r3 = n.search("fn", {"query": {"bool": {"must_not": [
        {"term": {"_field_names": "b"}}]}}})
    assert sorted(h["_id"] for h in r3["hits"]["hits"]) == ["1"]
    n.close()


def test_field_names_can_be_disabled():
    n = Node()
    n.create_index("fnoff", {"mappings": {
        "_field_names": {"enabled": False},
        "properties": {"a": {"type": "text"}}}})
    svc = n.indices["fnoff"]
    svc.index_doc("1", {"a": "x"})
    svc.refresh()
    seg = svc.shards[0].segments[0]
    assert "_field_names" not in seg.keywords
    n.close()


def test_size_meta_field():
    n = Node()
    n.create_index("sz", {"mappings": {
        "_size": {"enabled": True},
        "properties": {"t": {"type": "text"}}}})
    svc = n.indices["sz"]
    svc.index_doc("small", {"t": "x"})
    svc.index_doc("big", {"t": "x " * 200})
    svc.refresh()
    r = n.search("sz", {"query": {"range": {"_size": {"gt": 100}}}})
    assert [h["_id"] for h in r["hits"]["hits"]] == ["big"]
    r2 = n.search("sz", {"query": {"match_all": {}},
                         "sort": [{"_size": "desc"}], "size": 2})
    assert [h["_id"] for h in r2["hits"]["hits"]] == ["big", "small"]
    n.close()


def test_timestamp_ttl_survive_translog_replay(tmp_path):
    n = Node(data_path=str(tmp_path))
    n.create_index("dur", {"mappings": {
        "_timestamp": {"enabled": True}, "_ttl": {"enabled": True},
        "properties": {"t": {"type": "text"}}}})
    svc = n.indices["dur"]
    # ttl is RELATIVE TO _timestamp (TTLFieldMapper: expiry = ts + ttl), so
    # doc 1 pins the timestamp only; doc 2 gets a now-based ttl
    svc.index_doc("1", {"t": "x"}, timestamp="2022-03-04")
    svc.index_doc("2", {"t": "x"}, ttl="10h")
    n.close()  # no flush: docs ride the translog to the next open

    n2 = Node(data_path=str(tmp_path))
    svc2 = n2.indices["dur"]
    svc2.refresh()
    seg = svc2.shards[0].segments[0]
    ts = int(seg.numerics["_timestamp"].exact[seg.id_map["1"]])
    from elasticsearch_tpu.utils.dates import parse_date

    assert ts == parse_date("2022-03-04",
                            "strict_date_optional_time||epoch_millis")
    now = int(time.time() * 1000)
    exp = int(seg.numerics["_ttl"].exact[seg.id_map["2"]])
    assert now + 9 * 3600 * 1000 < exp <= now + 10 * 3600 * 1000
    n2.close()


def test_ttl_numeric_and_bad_values():
    from elasticsearch_tpu.index.doc_parser import _ttl_to_millis
    from elasticsearch_tpu.utils.errors import MapperParsingException

    assert _ttl_to_millis("60000") == 60000  # REST delivers params as str
    assert _ttl_to_millis(5000) == 5000
    assert _ttl_to_millis("2h") == 2 * 3600 * 1000
    import pytest as _pytest

    with _pytest.raises(MapperParsingException):
        _ttl_to_millis("soon")


def test_mapping_json_roundtrip_is_faithful():
    """to_json must invert _parse_field/_parse_properties: the gateway
    re-parses it on restart, so any dropped attribute (index_options,
    nested structure, copy_to, boost, ...) silently changes behavior after
    a restart. Caught live by the r4 IVF-cache work: {type: ivf} vanished
    and index-time ANN builds degraded to lazy."""
    from elasticsearch_tpu.index.mappings import Mappings

    body = {
        "_all": {"enabled": False},
        "dynamic_templates": [
            {"strings_as_keywords": {
                "match_mapping_type": "string",
                "mapping": {"type": "keyword"}}}],
        "properties": {
            "title": {"type": "text", "analyzer": "english", "boost": 2.0,
                      "copy_to": ["all_text"], "store": True,
                      "fields": {"raw": {"type": "keyword",
                                         "ignore_above": 64}}},
            "all_text": {"type": "text"},
            "tag": {"type": "keyword", "null_value": "none",
                    "include_in_all": False},
            "when": {"type": "date", "format": "epoch_millis"},
            "emb": {"type": "dense_vector", "dims": 8,
                    "similarity": "l2_norm",
                    "index_options": {"type": "ivf"}},
            "author": {"properties": {
                "name": {"type": "text", "search_analyzer": "whitespace"}}},
            "comments": {"type": "nested", "properties": {
                "body": {"type": "text"},
                "votes": {"type": "long", "doc_values": False}}},
        },
    }
    m1 = Mappings(body)
    j1 = m1.to_json()
    m2 = Mappings(j1)
    assert m2.to_json() == j1  # fixpoint
    assert m1.fields.keys() == m2.fields.keys()
    for name, a in m1.fields.items():
        assert m2.fields[name] == a, name
    assert m1.nested_paths == m2.nested_paths
    assert m2._all_enabled is False
    assert m2.dynamic_templates == m1.dynamic_templates
    emb = m2.get("emb")
    assert emb.index_options == {"type": "ivf"} and emb.similarity == "l2_norm"
    raw = m2.get("title.raw")
    assert raw is not None and raw.ignore_above == 64
    votes = m2.get("comments.votes")
    assert votes.nested and votes.nested_path == "comments"
    assert votes.doc_values is False
