"""watcher / river / tribe (SURVEY §2.11 — r3 verdict honesty sweep)."""
import time

import pytest

from elasticsearch_tpu.river import register_river
from elasticsearch_tpu.utils.errors import IllegalArgumentException
from elasticsearch_tpu.watcher import ResourceWatcherService


def test_resource_watcher_fires_events(tmp_path):
    svc = ResourceWatcherService(interval=0.05)
    p = tmp_path / "synonyms.txt"
    events = []
    svc.add(str(p), lambda path, ev: events.append(ev))
    assert svc.check_now() == 0
    p.write_text("a, b")
    assert svc.check_now() == 1 and events == ["created"]
    time.sleep(0.02)
    p.write_text("a, b, c")
    import os

    os.utime(p, (time.time(), time.time() + 1))  # force mtime change
    svc.check_now()
    assert events[-1] == "changed"
    p.unlink()
    svc.check_now()
    assert events[-1] == "deleted"


def test_river_registration_rejected_like_2x():
    with pytest.raises(IllegalArgumentException):
        register_river("couchdb", {})


def test_tribe_state_federation_is_explicit_stub():
    from elasticsearch_tpu.tribe import TribeNode

    t = TribeNode([])
    with pytest.raises(NotImplementedError):
        t.merged_cluster_state()
