"""watcher / river / tribe (SURVEY §2.11 — r3 verdict honesty sweep)."""
import time

import pytest

from elasticsearch_tpu.river import register_river
from elasticsearch_tpu.utils.errors import IllegalArgumentException
from elasticsearch_tpu.watcher import ResourceWatcherService


def test_resource_watcher_fires_events(tmp_path):
    svc = ResourceWatcherService(interval=0.05)
    p = tmp_path / "synonyms.txt"
    events = []
    svc.add(str(p), lambda path, ev: events.append(ev))
    assert svc.check_now() == 0
    p.write_text("a, b")
    assert svc.check_now() == 1 and events == ["created"]
    time.sleep(0.02)
    p.write_text("a, b, c")
    import os

    os.utime(p, (time.time(), time.time() + 1))  # force mtime change
    svc.check_now()
    assert events[-1] == "changed"
    p.unlink()
    svc.check_now()
    assert events[-1] == "deleted"


def test_river_registration_rejected_like_2x():
    with pytest.raises(IllegalArgumentException):
        register_river("couchdb", {})


def test_tribe_state_federation_is_explicit_stub():
    from elasticsearch_tpu.tribe import TribeNode

    t = TribeNode([])
    with pytest.raises(NotImplementedError):
        t.merged_cluster_state()


def test_tribe_search_fans_out_over_http():
    """The advertised read-only fan-out must work against real endpoints
    (review regression: Client was constructed with the wrong parameter)."""
    from elasticsearch_tpu.node import Node
    from elasticsearch_tpu.rest.server import RestServer
    from elasticsearch_tpu.tribe import TribeNode

    nodes, servers, urls = [], [], []
    for i in range(2):
        n = Node(name=f"trib{i}")
        srv = RestServer(n, host="127.0.0.1", port=0)
        srv.start(background=True)
        nodes.append(n)
        servers.append(srv)
        urls.append(f"http://127.0.0.1:{srv.port}")
        n.create_index("logs", {})
        svc = n.indices["logs"]
        for j in range(12):
            svc.index_doc(f"c{i}-{j}", {"msg": "error in module"})
        svc.refresh()
    try:
        t = TribeNode(urls)
        r = t.search_remote("logs", {"query": {"match": {"msg": "error"}}},
                            size=15)
        assert r["hits"]["total"] == 24
        # size forwarded to remotes: > 10 hits can come from one cluster
        assert len(r["hits"]["hits"]) == 15
    finally:
        for srv, n in zip(servers, nodes):
            srv.stop()
            n.close()
