"""Span query tests (reference: Span*QueryBuilder + Lucene SpanQuery tests).

Positions are deterministic: docs are simple whitespace phrases, so the
expected interval algebra can be stated by hand.
"""
import numpy as np
import pytest

from elasticsearch_tpu.index.index_service import IndexService


@pytest.fixture()
def svc():
    s = IndexService("spans", mappings_json={"properties": {
        "body": {"type": "text", "analyzer": "whitespace"},
        "alt": {"type": "text", "analyzer": "whitespace"},
    }})
    docs = [
        "the quick brown fox",             # 0: quick@1 brown@2 fox@3
        "quick red fox",                   # 1: quick@0 fox@2
        "fox quick",                       # 2: reversed order
        "quick a b c d e fox",             # 3: far apart (gap 5)
        "the lazy dog",                    # 4: no match
        "quick brown quick fox",           # 5: multiple occurrences
    ]
    for i, t in enumerate(docs):
        s.index_doc(str(i), {"body": t, "alt": "fox sleeps"})
    for sh in s.shards:
        sh.refresh()
    yield s
    s.close()


def hits(svc, query):
    resp = svc.search({"query": query, "size": 20})
    return sorted(h["_id"] for h in resp["hits"]["hits"])


def test_span_term(svc):
    assert hits(svc, {"span_term": {"body": "quick"}}) == ["0", "1", "2", "3", "5"]
    assert hits(svc, {"span_term": {"body": {"value": "dog"}}}) == ["4"]


def test_span_near_in_order_slop0(svc):
    q = {"span_near": {"clauses": [
        {"span_term": {"body": "quick"}},
        {"span_term": {"body": "fox"}}], "slop": 0, "in_order": True}}
    # adjacent in-order only: doc 5 (quick@2 fox@3); doc 0 has brown between
    assert hits(svc, q) == ["5"]


def test_span_near_slop(svc):
    q = {"span_near": {"clauses": [
        {"span_term": {"body": "quick"}},
        {"span_term": {"body": "fox"}}], "slop": 1, "in_order": True}}
    # gap of one token allowed: docs 0 (brown), 1 (red), 5
    assert hits(svc, q) == ["0", "1", "5"]
    q["span_near"]["slop"] = 5
    assert hits(svc, q) == ["0", "1", "3", "5"]


def test_span_near_unordered(svc):
    q = {"span_near": {"clauses": [
        {"span_term": {"body": "quick"}},
        {"span_term": {"body": "fox"}}], "slop": 0, "in_order": False}}
    # doc 2 "fox quick" qualifies unordered at slop 0 (adjacent)
    assert hits(svc, q) == ["2", "5"]


def test_span_first(svc):
    # fox within first 3 positions: doc 1 (fox@2) and doc 2 (fox@0)
    q = {"span_first": {"match": {"span_term": {"body": "fox"}}, "end": 3}}
    assert hits(svc, q) == ["1", "2"]


def test_span_or(svc):
    q = {"span_or": {"clauses": [
        {"span_term": {"body": "dog"}}, {"span_term": {"body": "red"}}]}}
    assert hits(svc, q) == ["1", "4"]


def test_span_not(svc):
    # quick spans NOT immediately followed by brown (post=1):
    # doc0 quick@1 brown@2 excluded; doc5 has quick@2 (brown@1 before it) ok
    q = {"span_not": {
        "include": {"span_term": {"body": "quick"}},
        "exclude": {"span_term": {"body": "brown"}},
        "post": 1}}
    got = hits(svc, q)
    assert "1" in got and "2" in got and "3" in got and "5" in got
    assert "0" not in got


def test_span_multi_prefix(svc):
    q = {"span_near": {"clauses": [
        {"span_multi": {"match": {"prefix": {"body": "qui"}}}},
        {"span_term": {"body": "fox"}}], "slop": 1, "in_order": True}}
    assert hits(svc, q) == ["0", "1", "5"]


def test_span_multi_wildcard_and_fuzzy(svc):
    assert hits(svc, {"span_multi": {"match": {"wildcard": {"body": "d*g"}}}}) == ["4"]
    assert hits(svc, {"span_multi": {"match": {
        "fuzzy": {"body": {"value": "quickk", "fuzziness": 1}}}}}) == ["0", "1", "2", "3", "5"]


def test_field_masking_span(svc):
    # alt:"fox sleeps" -> fox@0; mask alt's fox as body and require it right
    # before body's quick: doc2 has body quick@1 and masked fox@0
    q = {"span_near": {"clauses": [
        {"field_masking_span": {"query": {"span_term": {"alt": "fox"}}, "field": "body"}},
        {"span_term": {"body": "quick"}}], "slop": 0, "in_order": True}}
    # masked fox@0 then quick@1 adjacent in-order: doc0 (quick@1) and doc2
    # (quick@1); doc1/doc3/doc5 have quick@0 which overlaps the masked span
    assert hits(svc, q) == ["0", "2"]


def test_span_scores_positive_and_deterministic(svc):
    resp = svc.search({"query": {"span_term": {"body": "fox"}}})
    scores = [h["_score"] for h in resp["hits"]["hits"]]
    assert all(s > 0 for s in scores)
    resp2 = svc.search({"query": {"span_term": {"body": "fox"}}})
    assert scores == [h["_score"] for h in resp2["hits"]["hits"]]


def test_span_multi_expands_per_segment():
    # regression: expansion must be recomputed per segment — terms present
    # only in a later segment were missed when the cache was query-global
    s = IndexService("seg2", mappings_json={"properties": {
        "body": {"type": "text", "analyzer": "whitespace"}}})
    s.index_doc("0", {"body": "alpha beta"})
    for sh in s.shards:
        sh.refresh()
    s.index_doc("1", {"body": "dog gamma"})
    for sh in s.shards:
        sh.refresh()
    assert hits(s, {"span_multi": {"match": {"prefix": {"body": "do"}}}}) == ["1"]
    # wildcard char-class metacharacters terminate the literal prefix
    assert hits(s, {"span_multi": {"match": {"wildcard": {"body": "d[ou]g"}}}}) == ["1"]
    s.close()


def test_span_term_missing_value_raises():
    from elasticsearch_tpu.search.queries import parse_query
    from elasticsearch_tpu.utils.errors import QueryParsingException

    with pytest.raises(QueryParsingException):
        parse_query({"span_term": {"body": {"boost": 2.0}}})


def test_span_in_bool_filter_context(svc):
    q = {"bool": {"filter": [{"span_near": {"clauses": [
        {"span_term": {"body": "quick"}},
        {"span_term": {"body": "fox"}}], "slop": 0, "in_order": True}}]}}
    assert hits(svc, q) == ["5"]


def test_common_shapes_avoid_per_doc_host_walk(svc, monkeypatch):
    """R4: the common span shapes execute as device/vectorized programs —
    the per-doc host interval walk (.spans) must never run for them."""
    from elasticsearch_tpu.search import spans as S

    def boom(self, ctx, doc):
        raise AssertionError("per-doc host walk on a device-eligible shape")

    for cls in (S.SpanTermNode, S.SpanOrNode, S.SpanNearNode,
                S.SpanFirstNode, S.SpanNotNode, S.SpanMultiNode):
        monkeypatch.setattr(cls, "spans", boom)

    assert hits(svc, {"span_term": {"body": "quick"}})
    assert hits(svc, {"span_or": {"clauses": [
        {"span_term": {"body": "dog"}}, {"span_term": {"body": "red"}}]}})
    assert hits(svc, {"span_near": {"clauses": [
        {"span_term": {"body": "quick"}}, {"span_term": {"body": "fox"}}],
        "slop": 1, "in_order": True}})
    assert hits(svc, {"span_near": {"clauses": [
        {"span_term": {"body": "quick"}}, {"span_term": {"body": "fox"}}],
        "slop": 0, "in_order": False}})
    assert hits(svc, {"span_first": {
        "match": {"span_term": {"body": "fox"}}, "end": 3}})
    assert hits(svc, {"span_not": {
        "include": {"span_term": {"body": "quick"}},
        "exclude": {"span_term": {"body": "brown"}}, "post": 1}})
    # or-of-terms inside first and not also stay vectorized
    assert hits(svc, {"span_first": {"match": {"span_or": {"clauses": [
        {"span_term": {"body": "fox"}}, {"span_term": {"body": "dog"}}]}},
        "end": 3}})


def test_span_truncation_is_surfaced():
    """MAX_SPANS_PER_CLAUSE truncation ticks a kernel counter instead of
    silently narrowing results (r3 verdict weak #8)."""
    from elasticsearch_tpu.monitor import kernels
    from elasticsearch_tpu.search import spans as S

    s = IndexService("trunc", mappings_json={"properties": {
        "body": {"type": "text", "analyzer": "whitespace"}}})
    # one doc with > MAX_SPANS_PER_CLAUSE occurrences of 'a'
    text = " ".join(["a"] * (S.MAX_SPANS_PER_CLAUSE + 10) + ["b"])
    s.index_doc("1", {"body": text})
    for sh in s.shards:
        sh.refresh()
    kernels.reset()
    # nested near-of-near forces the HOST walk (device path covers flat
    # term clauses), where truncation applies
    q = {"span_near": {"clauses": [
        {"span_near": {"clauses": [{"span_term": {"body": "a"}},
                                   {"span_term": {"body": "a"}}],
         "slop": 10, "in_order": False}},
        {"span_term": {"body": "b"}}], "slop": 200, "in_order": False}}
    s.search({"query": q, "size": 5})
    assert kernels.snapshot().get("span_clause_truncated", 0) >= 1
    s.close()


def test_span_near_unordered_three_clauses_explores_alternatives():
    """Unordered near with >= 3 clauses must not take the greedy
    nearest-per-clause shortcut: with b@7, a@10, b@14, c@15 the b nearest
    to the anchor (b@7, distance 3) yields window [7,16) with
    matchSlop 6 > 5, but Lucene's NearSpansUnordered finds the b@14
    window [10,16) with matchSlop 3 <= 5. Routed to the host walk, which
    explores all combinations (spans.py::_device_near guard)."""
    s = IndexService("span_unord3", mappings_json={"properties": {
        "body": {"type": "text", "analyzer": "whitespace"}}})
    toks = [f"x{i}" for i in range(18)]
    toks[7] = "b"
    toks[10] = "a"
    toks[14] = "b"
    toks[15] = "c"
    s.index_doc("0", {"body": " ".join(toks)})
    for sh in s.shards:
        sh.refresh()
    q = {"span_near": {"clauses": [
        {"span_term": {"body": "a"}},
        {"span_term": {"body": "b"}},
        {"span_term": {"body": "c"}}], "slop": 5, "in_order": False}}
    assert hits(s, q) == ["0"]
    # tighter slop excludes even the best window (matchSlop 3)
    q["span_near"]["slop"] = 2
    assert hits(s, q) == []
    s.close()


def test_span_near_unordered_repeated_term_overlap_quirk():
    """Lucene 5's NearSpansUnordered allows overlapping subspans, so
    span_near [a, a] unordered matches a SINGLE 'a' occurrence (both
    subspans sit on the same position; matchSlop is negative). The
    2-clause device program reproduces this: nearest-'a'-to-anchor is the
    anchor itself."""
    s = IndexService("span_rep", mappings_json={"properties": {
        "body": {"type": "text", "analyzer": "whitespace"}}})
    s.index_doc("0", {"body": "z z a z z"})   # single occurrence
    s.index_doc("1", {"body": "a w a"})        # two occurrences
    s.index_doc("2", {"body": "w w w"})        # none
    for sh in s.shards:
        sh.refresh()
    q = {"span_near": {"clauses": [
        {"span_term": {"body": "a"}},
        {"span_term": {"body": "a"}}], "slop": 1, "in_order": False}}
    assert hits(s, q) == ["0", "1"]
    # ordered requires two DISTINCT ascending positions (docSpansOrdered)
    q["span_near"]["in_order"] = True
    q["span_near"]["slop"] = 2
    assert hits(s, q) == ["1"]
    s.close()
