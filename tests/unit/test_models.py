"""Dual encoder: forward shapes, unit-norm, training improves loss, tp/dp
sharding on the 8-device mesh."""
import numpy as np
import pytest

from elasticsearch_tpu.models import (
    DualEncoderConfig, SimpleTokenizer, batch_sharding, build_model,
    contrastive_loss, init_params, make_train_step, param_shardings)
from elasticsearch_tpu.parallel.mesh import training_mesh

CFG = DualEncoderConfig(vocab_size=128, max_len=12, d_model=32, n_heads=2,
                        n_layers=1, d_ff=64, embed_dim=16)


def _batch(rng, B):
    ids = rng.integers(1, CFG.vocab_size, size=(B, CFG.max_len)).astype(np.int32)
    mask = np.ones((B, CFG.max_len), np.float32)
    return ids, mask


def test_forward_unit_norm():
    import jax

    model = build_model(CFG)
    params = init_params(CFG)
    rng = np.random.default_rng(0)
    ids, mask = _batch(rng, 4)
    z = jax.jit(model.apply)(params, ids, mask)
    assert z.shape == (4, CFG.embed_dim)
    assert np.allclose(np.linalg.norm(np.asarray(z), axis=1), 1.0, atol=1e-3)


def test_padding_does_not_change_embedding():
    model = build_model(CFG)
    params = init_params(CFG)
    rng = np.random.default_rng(1)
    ids, mask = _batch(rng, 2)
    mask[:, 8:] = 0.0
    z1 = np.asarray(model.apply(params, ids, mask))
    ids2 = ids.copy()
    ids2[:, 8:] = 77  # garbage under the mask
    z2 = np.asarray(model.apply(params, ids2, mask))
    assert np.allclose(z1, z2, atol=1e-2)  # bf16 tolerance


def test_train_step_reduces_loss():
    step, tx = make_train_step(CFG, lr=3e-3)
    params = init_params(CFG)
    opt_state = tx.init(params)
    rng = np.random.default_rng(2)
    q_ids, q_mask = _batch(rng, 8)
    # positives = near-identical token sequences (learnable signal)
    d_ids = q_ids.copy()
    batch = (q_ids, q_mask, d_ids, q_mask)
    losses = []
    for _ in range(5):
        params, opt_state, loss = step(params, opt_state, batch)
        losses.append(float(loss))
    assert losses[-1] < losses[0]


def test_sharded_train_step(eight_devices):
    import jax

    mesh = training_mesh(8)
    assert dict(mesh.shape) == {"dp": 2, "tp": 4}
    step, tx = make_train_step(CFG)
    params = init_params(CFG)
    sh = param_shardings(mesh, params)
    # at least one param must actually be tp-sharded
    specs = [s.spec for s in jax.tree_util.tree_leaves(sh)]
    assert any("tp" in str(sp) for sp in specs)
    params = jax.device_put(params, sh)
    opt_state = tx.init(params)
    rng = np.random.default_rng(3)
    bs = batch_sharding(mesh)
    q_ids, q_mask = _batch(rng, 4)
    batch = tuple(jax.device_put(a, bs) for a in (q_ids, q_mask, q_ids, q_mask))
    with mesh:
        params, opt_state, loss = step(params, opt_state, batch)
    assert np.isfinite(float(loss))


def test_tokenizer():
    tok = SimpleTokenizer(CFG)
    ids, mask = tok(["hello world", "a b c d"])
    assert ids.shape == (2, CFG.max_len)
    assert mask[0].sum() == 2 and mask[1].sum() == 4
    assert (ids[0, :2] > 0).all() and ids[0, 2] == 0


def test_orbax_checkpoint_roundtrip(tmp_path):
    """SURVEY §5: orbax checkpoints for the dual encoder — params round-trip
    bit-exact and restored params produce identical embeddings."""
    import numpy as np

    from elasticsearch_tpu.models import build_model, init_params
    from elasticsearch_tpu.models.dual_encoder import (DualEncoderConfig,
                                                       load_checkpoint,
                                                       save_checkpoint)

    cfg = DualEncoderConfig(vocab_size=64, max_len=8, d_model=16, n_heads=2,
                            n_layers=1, d_ff=32, embed_dim=8)
    model = build_model(cfg)
    params = init_params(cfg, seed=3)
    path = str(tmp_path / "ckpt")
    save_checkpoint(path, params, step=7, cfg=cfg)
    got = load_checkpoint(path)
    assert got["step"] == 7
    assert got["config"]["d_model"] == 16
    rng = np.random.default_rng(0)
    ids = rng.integers(1, 64, size=(2, 8)).astype(np.int32)
    mask = np.ones((2, 8), np.float32)
    a = np.asarray(model.apply(params, ids, mask))
    b = np.asarray(model.apply(got["params"], ids, mask))
    np.testing.assert_array_equal(a, b)
