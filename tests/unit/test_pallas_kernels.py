"""Pallas fused kNN kernel vs the XLA path and an exact numpy oracle.

Runs in interpret mode on CPU (tests); the same kernel compiles for TPU and
is dispatched by knn_topk_auto when running on a real chip.
"""
import numpy as np
import pytest

from elasticsearch_tpu.ops.knn import knn_topk
from elasticsearch_tpu.ops.pallas_kernels import knn_topk_auto, knn_topk_pallas


def _exact_topk(q, v, mask, k, metric):
    qn = q / np.maximum(np.linalg.norm(q, axis=-1, keepdims=True), 1e-12)
    vn = v / np.maximum(np.linalg.norm(v, axis=-1, keepdims=True), 1e-12)
    if metric == "cosine":
        s = (1 + qn @ vn.T) / 2
    elif metric in ("dot_product", "dot"):
        s = (1 + q @ v.T) / 2
    else:
        d2 = ((q[:, None, :] - v[None, :, :]) ** 2).sum(-1)
        s = 1.0 / (1.0 + d2)
    s = np.where(mask[None, :], s, -np.inf)
    idx = np.argsort(-s, axis=1)[:, :k]
    return np.take_along_axis(s, idx, axis=1), idx


@pytest.mark.parametrize("metric", ["cosine", "dot_product", "l2_norm"])
def test_pallas_knn_matches_oracle(metric):
    import jax.numpy as jnp

    rng = np.random.default_rng(3)
    Q, D, dims, k = 4, 8192, 64, 10
    q = rng.normal(size=(Q, dims)).astype(np.float32)
    v = rng.normal(size=(D, dims)).astype(np.float32)
    mask = rng.random(D) > 0.1
    pv, pi = knn_topk_pallas(jnp.asarray(q), jnp.asarray(v), jnp.asarray(mask),
                             k=k, metric=metric, interpret=True)
    ev, ei = _exact_topk(q, v, mask, k, metric)
    pv, pi = np.asarray(pv), np.asarray(pi)
    # scores agree to bf16 matmul tolerance (relative: dot magnitudes scale
    # with dims); recall@k vs the exact oracle must be near-perfect
    np.testing.assert_allclose(pv, ev, rtol=5e-3, atol=5e-3)
    recall = np.mean([len(set(pi[i]) & set(ei[i])) / k for i in range(Q)])
    assert recall >= 0.95
    # masked docs never surface
    assert not np.isin(pi, np.nonzero(~mask)[0]).any()
    # results descending per row
    assert (np.diff(pv, axis=1) <= 1e-6).all()


def test_pallas_matches_xla_path():
    import jax.numpy as jnp

    rng = np.random.default_rng(4)
    Q, D, dims, k = 2, 4096, 32, 5
    q = jnp.asarray(rng.normal(size=(Q, dims)).astype(np.float32))
    v = jnp.asarray(rng.normal(size=(D, dims)).astype(np.float32))
    m = jnp.asarray(np.ones(D, dtype=bool))
    pv, _ = knn_topk_pallas(q, v, m, k=k, metric="cosine", interpret=True)
    xv, _ = knn_topk(q, v, m, k=k, metric="cosine")
    np.testing.assert_allclose(np.asarray(pv), np.asarray(xv), atol=5e-3)


def test_auto_dispatch_falls_back_on_cpu():
    import jax.numpy as jnp

    rng = np.random.default_rng(5)
    q = jnp.asarray(rng.normal(size=(2, 16)).astype(np.float32))
    v = jnp.asarray(rng.normal(size=(100, 16)).astype(np.float32))  # not tile-aligned
    m = jnp.asarray(np.ones(100, dtype=bool))
    vals, idx = knn_topk_auto(q, v, m, k=3)
    assert vals.shape == (2, 3) and idx.shape == (2, 3)


# -- fused dense-impact BM25 kernel (round-2) ---------------------------------

def test_pallas_bm25_dense_topk_matches_xla():
    import jax.numpy as jnp
    from jax import lax
    from elasticsearch_tpu.ops.pallas_kernels import bm25_dense_topk_pallas

    rng = np.random.default_rng(5)
    Q, F, D, k = 8, 64, 4096, 10
    # sparse nonneg impacts (tfnorm-like), sparse query weights (idf*boost)
    impact = (rng.random((F, D)) < 0.05).astype(np.float32) * rng.random((F, D)).astype(np.float32) * 2.5
    qw = np.zeros((Q, F), np.float32)
    for i in range(Q):
        terms = rng.choice(F, size=4, replace=False)
        qw[i, terms] = rng.random(4) * 3.0
    mask = rng.random(D) > 0.05

    pv, pi = bm25_dense_topk_pallas(jnp.asarray(qw), jnp.asarray(impact),
                                    jnp.asarray(mask), k=k, tile=1024,
                                    q_tile=8, interpret=True)
    scores = jnp.dot(jnp.asarray(qw), jnp.asarray(impact),
                     precision=lax.Precision.HIGHEST)
    masked = jnp.where(jnp.asarray(mask)[None, :], scores, -jnp.inf)
    ev, ei = lax.top_k(masked, k)
    pv, pi, ev, ei = map(np.asarray, (pv, pi, ev, ei))
    np.testing.assert_allclose(pv, ev, rtol=5e-3, atol=5e-3)
    recall = np.mean([len(set(pi[i]) & set(ei[i])) / k for i in range(Q)])
    assert recall >= 0.95
    assert not np.isin(pi, np.nonzero(~mask)[0]).any()
    assert (np.diff(pv, axis=1) <= 1e-6).all()


def test_bm25_dense_topk_auto_xla_fallback():
    # CPU (no TPU): auto path must take XLA and give exact results
    import jax.numpy as jnp
    from elasticsearch_tpu.ops.pallas_kernels import bm25_dense_topk_auto

    rng = np.random.default_rng(6)
    Q, F, D, k = 3, 16, 512, 5
    impact = rng.random((F, D)).astype(np.float32)
    qw = rng.random((Q, F)).astype(np.float32)
    mask = np.ones(D, bool)
    vals, idx = bm25_dense_topk_auto(jnp.asarray(qw), jnp.asarray(impact),
                                     jnp.asarray(mask), k=k)
    exact = np.asarray(qw @ impact)
    want = np.argsort(-exact, axis=1)[:, :k]
    assert (np.asarray(idx) == want).all()


def test_knn_auto_pads_small_q():
    # Q=1 must not crash on the padded path (CPU takes XLA anyway; this
    # asserts the pad/slice contract via the pallas kernel in interpret)
    import jax.numpy as jnp
    from elasticsearch_tpu.ops.pallas_kernels import knn_topk_pallas

    rng = np.random.default_rng(7)
    dims, D, k = 128, 4096, 5
    q = rng.normal(size=(1, dims)).astype(np.float32)
    qpad = np.concatenate([q, np.zeros((7, dims), np.float32)], axis=0)
    v = rng.normal(size=(D, dims)).astype(np.float32)
    mask = np.ones(D, bool)
    pv, pi = knn_topk_pallas(jnp.asarray(qpad), jnp.asarray(v),
                             jnp.asarray(mask), k=k, metric="cosine",
                             tile=2048, interpret=True)
    ev, ei = _exact_topk(q, v, mask, k, "cosine")
    assert len(set(np.asarray(pi)[0]) & set(ei[0])) >= 4


def test_bm25_dense_topk_early_exit_tie_parity():
    """The early-exit while-loop selection must match lax.top_k over the
    dense bf16 score row exactly — including id order under heavy exact
    ties (quantized impacts), fully-masked regions, and a dense cluster
    competing for every slot late in the sweep."""
    import jax.numpy as jnp
    import numpy as np
    from jax import lax

    from elasticsearch_tpu.ops.pallas_kernels import bm25_dense_topk_pallas

    rng = np.random.default_rng(3)
    Q, F, D, k = 16, 16, 4096, 10
    for quant in (0.05, 1.0, 0.5):  # 1.0 → near-total tie rows
        qw = (rng.random((Q, F)) * 2).astype(np.float32)
        impact = rng.random((F, D)).astype(np.float32)
        impact = (impact / quant).round() * quant
        mask = rng.random(D) > 0.3
        mask[:600] = False
        v, i = bm25_dense_topk_pallas(
            jnp.asarray(qw), jnp.asarray(impact), jnp.asarray(mask),
            k=k, tile=512, q_tile=8, interpret=True)
        sc = np.asarray(jnp.dot(jnp.asarray(qw).astype(jnp.bfloat16),
                                jnp.asarray(impact).astype(jnp.bfloat16),
                                preferred_element_type=jnp.float32))
        sc = np.where(mask[None, :], sc, -np.inf)
        wv, wi = lax.top_k(jnp.asarray(sc), k)
        np.testing.assert_allclose(np.asarray(v), np.asarray(wv), rtol=1e-6)
        np.testing.assert_array_equal(np.asarray(i), np.asarray(wi))
