"""Observability subsystem tests (elasticsearch_tpu/tracing/):

- tracer: span nesting/parent links, monotonic durations, chrome dump
- task registry: lifecycle, parent cascade cancel, pending views
- wire header: sanitization + transport propagation
- slow logs: threshold-driven recording off live settings
- profiler: ?profile=true phase breakdown with the device
  compile/execute split + retrace counts (bool+kNN per acceptance)
- cross-process: one trace id spanning coordinator + remote owner, and
  /_tasks listing + parent cancel of a running delete-by-query whose
  child runs on the remote primary owner
"""
import json
import socket
import threading
import time

import pytest

from elasticsearch_tpu.node import Node
from elasticsearch_tpu.rest.server import RestController
from elasticsearch_tpu.tracing import (TaskCancelledException, TaskRegistry,
                                       Tracer, adopt_wire_context,
                                       check_cancelled, wire_context)
from elasticsearch_tpu.tracing.tasks import ResourceNotFoundException
from elasticsearch_tpu.utils import wire


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


# -- tracer --------------------------------------------------------------------

class TestTracer:
    def test_nested_spans_share_trace_and_link_parents(self):
        tr = Tracer("n1")
        with tr.span("outer") as outer:
            with tr.span("inner") as inner:
                pass
        assert inner.trace_id == outer.trace_id
        assert inner.parent_id == outer.span_id
        assert outer.parent_id is None
        # finished ring holds both, inner first (closed first)
        names = [s.name for s in tr.spans()]
        assert names == ["inner", "outer"]
        assert all(s.duration >= 0 for s in tr.spans())

    def test_separate_roots_get_separate_traces(self):
        tr = Tracer("n1")
        with tr.span("a"):
            pass
        with tr.span("b"):
            pass
        a, b = tr.spans()
        assert a.trace_id != b.trace_id

    def test_error_recorded_and_raised(self):
        tr = Tracer("n1")
        with pytest.raises(ValueError):
            with tr.span("boom"):
                raise ValueError("nope")
        sp = tr.spans()[0]
        assert "ValueError" in sp.error

    def test_ring_bounded_counters_exact(self):
        tr = Tracer("n1", max_spans=4)
        for i in range(10):
            with tr.span(f"s{i}"):
                pass
        assert len(tr.spans()) == 4
        st = tr.stats()
        assert st["started_total"] == st["finished_total"] == 10

    def test_chrome_trace_shape(self):
        tr = Tracer("n1")
        with tr.span("work", index="idx"):
            pass
        dump = tr.chrome_trace()
        (ev,) = dump["traceEvents"]
        assert ev["ph"] == "X" and ev["dur"] >= 1
        assert ev["args"]["trace_id"] and ev["args"]["index"] == "idx"
        assert dump["otherData"]["node"] == "n1"

    def test_adopted_header_joins_remote_trace(self):
        tr = Tracer("n1")
        header = {"trace": {"trace_id": "t" * 16, "span_id": "p" * 16}}
        with adopt_wire_context(header):
            with tr.span("child"):
                pass
        sp = tr.spans()[0]
        assert sp.trace_id == "t" * 16
        assert sp.parent_id == "p" * 16


# -- task registry -------------------------------------------------------------

class TestTaskRegistry:
    def test_lifecycle_and_listing(self):
        reg = TaskRegistry("n1")
        with reg.task("indices:data/read/search", description="d") as t:
            assert reg.get(t.id) is t
            (listed,) = reg.list_tasks()
            j = listed.to_json()
            assert j["action"] == "indices:data/read/search"
            assert j["cancellable"] and not j["cancelled"]
            assert j["running_time_in_nanos"] >= 0
        assert reg.get(t.id) is None
        assert reg.stats() == {"current": 0, "completed_total": 1,
                               "cancelled_total": 0}

    def test_checkpoint_raises_only_when_cancelled(self):
        reg = TaskRegistry("n1")
        check_cancelled()  # no current task: no-op
        with reg.task("a") as t:
            check_cancelled()  # running, not cancelled: no-op
            t.cancel("because")
            with pytest.raises(TaskCancelledException) as ei:
                check_cancelled()
            assert "because" in str(ei.value)
        assert reg.stats()["cancelled_total"] == 1

    def test_cancel_cascades_to_local_descendants(self):
        reg = TaskRegistry("n1")
        parent = reg.register("p")
        child = reg.register("c", parent=(parent.node, parent.id))
        grandchild = reg.register("g", parent=(child.node, child.id))
        other = reg.register("other")
        cancelled = reg.cancel(parent.id)
        assert {t.id for t in cancelled} == {parent.id, child.id,
                                             grandchild.id}
        assert not other.cancelled

    def test_cancel_missing_is_404(self):
        with pytest.raises(ResourceNotFoundException):
            TaskRegistry("n1").cancel(99)

    def test_nested_tasks_parent_automatically(self):
        reg = TaskRegistry("n1")
        with reg.task("outer") as outer:
            with reg.task("inner") as inner:
                assert inner.parent == ("n1", outer.id)

    def test_pending_view(self):
        reg = TaskRegistry("n1")
        t = reg.register("indices:recovery/start", status="pending")
        (row,) = reg.pending_tasks()
        assert row["insert_order"] == t.id
        assert row["source"] == "indices:recovery/start"
        t.start()
        assert reg.pending_tasks() == []
        reg.unregister(t)

    def test_late_child_of_cancelled_parent_is_born_cancelled(self):
        """The cancel BAN: a child registering AFTER its parent's cancel
        fanout processed (the dispatch was in flight) must not escape
        the cascade and run its destructive pass to completion."""
        reg = TaskRegistry("n1")
        # remote-parent form: the coordinator lives on another node
        reg.cancel_by_parent("coord-node", 42, "user said stop")
        late = reg.register("indices:data/write/delete/byquery[s]",
                            parent=("coord-node", 42))
        try:
            assert late.cancelled
            with pytest.raises(TaskCancelledException):
                late.check_cancelled()
        finally:
            reg.unregister(late)
        # unrelated parents are unaffected
        free = reg.register("x", parent=("coord-node", 43))
        assert not free.cancelled
        reg.unregister(free)

    def test_local_cancel_bans_late_children_too(self):
        reg = TaskRegistry("n1")
        parent = reg.register("p")
        reg.cancel(parent.id)
        late = reg.register("c", parent=("n1", parent.id))
        assert late.cancelled
        reg.unregister(late)
        reg.unregister(parent)


# -- wire header ---------------------------------------------------------------

class TestWireHeader:
    def test_sanitize_whitelists_and_bounds(self):
        dirty = {"trace": {"trace_id": "t1", "span_id": "s1",
                           "evil": {"nested": 1}},
                 "task": {"node": "n", "id": 7, "extra": "x"},
                 "junk": "dropped"}
        clean = wire.sanitize_ctx(dirty)
        assert clean == {"trace": {"trace_id": "t1", "span_id": "s1"},
                         "task": {"node": "n", "id": 7}}
        assert wire.sanitize_ctx({"trace": {"trace_id": "x" * 200}}) is None
        assert wire.sanitize_ctx("garbage") is None
        # wrong TYPES are dropped key-by-key, not passed through: a
        # string task id would blow up the adopter's int() and fail a
        # valid frame, and bool is never accepted where int is
        assert wire.sanitize_ctx({"task": {"node": "n", "id": "abc"}}) \
            == {"task": {"node": "n"}}
        assert wire.sanitize_ctx({"task": {"node": "n", "id": True}}) \
            == {"task": {"node": "n"}}
        assert wire.sanitize_ctx({"trace": {"trace_id": 7,
                                            "span_id": "s"}}) == \
            {"trace": {"span_id": "s"}}

    def test_adopt_parent_ignores_junk_header(self):
        from elasticsearch_tpu.tracing.tasks import adopt_parent, \
            wire_parent

        with adopt_parent({"node": "n", "id": "abc"}):
            assert wire_parent() is None  # ignored, never raised
        with adopt_parent({"node": "n", "id": 5}):
            assert wire_parent() == ("n", 5)

    def test_attach_extract_roundtrip(self):
        frame = {"action": "a", "payload": {}}
        wire.attach_ctx(frame, {"trace": {"trace_id": "t", "span_id": "s"}})
        assert wire.extract_ctx(frame) == {"trace": {"trace_id": "t",
                                                     "span_id": "s"}}
        assert wire.extract_ctx({"action": "a"}) is None

    def test_wire_context_captures_task_and_span(self):
        reg = TaskRegistry("n9")
        tr = Tracer("n9")
        assert wire_context() is None
        with reg.task("act") as t:
            with tr.span("sp") as sp:
                ctx = wire_context()
        assert ctx["task"] == {"node": "n9", "id": t.id}
        assert ctx["trace"] == {"trace_id": sp.trace_id,
                                "span_id": sp.span_id}


# -- slow logs -----------------------------------------------------------------

class TestSlowlog:
    def test_threshold_drives_recording(self):
        from elasticsearch_tpu.index.index_service import IndexService

        svc = IndexService("slow", settings={"index": {
            "number_of_shards": 1,
            "search": {"slowlog": {"threshold": {"query": {
                "warn": "0ms"}}}}}})
        try:
            svc.index_doc("1", {"t": "hello"})
            svc.refresh()
            svc.search({"query": {"match_all": {}}})
            log = svc.slowlog.query.to_json()
            assert log["total"] >= 1
            entry = log["entries"][0]
            assert entry["level"] == "warn" and entry["index"] == "slow"
            assert "match_all" in (entry.get("source") or "")
        finally:
            svc.close()

    def test_no_thresholds_no_entries(self):
        from elasticsearch_tpu.index.index_service import IndexService

        svc = IndexService("quiet", settings={"index": {
            "number_of_shards": 1}})
        try:
            svc.index_doc("1", {"t": "x"})
            svc.refresh()
            svc.search({"query": {"match_all": {}}})
            assert svc.slowlog.query.to_json()["total"] == 0
            assert svc.slowlog.index.to_json()["total"] == 0
        finally:
            svc.close()

    def test_indexing_slowlog_and_node_totals(self):
        from elasticsearch_tpu.index.index_service import IndexService
        from elasticsearch_tpu.monitor.stats import aggregate_slowlog

        svc = IndexService("wslow", settings={"index": {
            "number_of_shards": 1,
            "indexing.slowlog.threshold.index.info": "0ms"}})
        quiet = IndexService("wquiet", settings={"index": {
            "number_of_shards": 1}})
        try:
            svc.index_doc("1", {"t": "x"})
            log = svc.slowlog.index.to_json()
            assert log["total"] == 1
            assert log["entries"][0]["level"] == "info"
            # per-NODE aggregation: only the indices handed in count —
            # another node's indices never bleed into this gauge
            assert aggregate_slowlog([svc, quiet]) == {
                "search_slow_total": 0, "indexing_slow_total": 1}
            assert aggregate_slowlog([quiet]) == {
                "search_slow_total": 0, "indexing_slow_total": 0}
        finally:
            svc.close()
            quiet.close()

    def test_dynamic_settings_update_applies(self):
        from elasticsearch_tpu.cluster.metadata import update_index_settings
        from elasticsearch_tpu.index.index_service import IndexService

        svc = IndexService("dyn", settings={"index": {
            "number_of_shards": 1}})
        try:
            svc.index_doc("1", {"t": "x"})
            svc.refresh()
            svc.search({"query": {"match_all": {}}})
            assert svc.slowlog.query.to_json()["total"] == 0
            update_index_settings(svc, {
                "index.search.slowlog.threshold.query.trace": "0ms"})
            svc.search({"query": {"match_all": {}}})
            assert svc.slowlog.query.to_json()["total"] == 1
        finally:
            svc.close()


# -- search profiler -----------------------------------------------------------

@pytest.fixture()
def knn_node():
    n = Node(name="prof-node")
    n.create_index("pidx", {
        "settings": {"number_of_shards": 2},
        "mappings": {"properties": {
            "t": {"type": "string"},
            "v": {"type": "dense_vector", "dims": 4}}}})
    for i in range(12):
        n.indices["pidx"].index_doc(
            str(i), {"t": f"hello doc {i}",
                     "v": [0.1 * i, 0.2, 0.3, 0.4]})
    n.indices["pidx"].refresh()
    yield n
    n.indices["pidx"].close()


class TestProfile:
    BOOL_KNN = {"query": {"bool": {"must": [
        {"match": {"t": "hello"}},
        {"knn": {"field": "v", "query_vector": [0.1, 0.2, 0.3, 0.4],
                 "k": 5}}]}}}

    def test_bool_knn_profile_separates_compile_from_execute(self, knn_node):
        ctrl = RestController(knn_node)
        status, resp = ctrl.dispatch(
            "POST", "/pidx/_search", {"profile": "true"},
            json.dumps(self.BOOL_KNN).encode())
        assert status == 200 and resp["hits"]["total"] > 0
        shards = resp["profile"]["shards"]
        assert len(shards) == 2  # per-shard breakdown
        for sp in shards:
            phases = sp["tpu"]["phases"]
            # the acceptance split: compile and execute are SEPARATE keys
            assert "device_compile_nanos" in phases
            assert "device_execute_nanos" in phases
            for key in ("rewrite_nanos", "executor_build_nanos",
                        "topk_nanos", "host_sync_nanos"):
                assert key in phases
            # retrace count included (-1 only when the auditor is absent)
            assert isinstance(sp["tpu"]["retraces"], int)
            assert sp["tpu"]["segments"] >= 1
            # reference envelope intact for existing consumers
            q = sp["searches"][0]["query"][0]
            assert q["time_in_nanos"] >= 0
        # device work happened somewhere (compile on first shapes,
        # execute on cached ones)
        total_dev = sum(sp["tpu"]["phases"]["device_compile_nanos"]
                        + sp["tpu"]["phases"]["device_execute_nanos"]
                        for sp in shards)
        assert total_dev > 0

    def test_steady_state_executes_without_retraces(self, knn_node):
        from elasticsearch_tpu.tracing import retrace

        if retrace.auditor() is None:
            pytest.skip("trace auditor unavailable")
        body = dict(self.BOOL_KNN, profile=True)
        knn_node.indices["pidx"].search(body)  # warm: compile everything
        resp = knn_node.indices["pidx"].search(body)
        for sp in resp["profile"]["shards"]:
            assert sp["tpu"]["retraces"] == 0
            assert sp["tpu"]["phases"]["device_compile_nanos"] == 0
            assert sp["tpu"]["phases"]["device_execute_nanos"] > 0

    def test_profile_false_adds_nothing(self, knn_node):
        resp = knn_node.indices["pidx"].search(
            {"query": {"match_all": {}}})
        assert "profile" not in resp


# -- REST task endpoints (single node) ----------------------------------------

class TestTaskEndpoints:
    def test_tasks_listing_and_cat(self):
        n = Node(name="t-node")
        ctrl = RestController(n)
        started = threading.Event()
        release = threading.Event()

        def long_task():
            with n.tasks.task("indices:data/write/delete/byquery",
                              description="delete-by-query [x]"):
                started.set()
                release.wait(5)

        th = threading.Thread(target=long_task)
        th.start()
        try:
            assert started.wait(5)
            s, body = ctrl.dispatch("GET", "/_tasks", {}, b"")
            assert s == 200
            tasks = body["nodes"][n.node_id]["tasks"]
            (tid,) = [k for k, v in tasks.items()
                      if v["action"].endswith("delete/byquery")]
            assert tasks[tid]["cancellable"]
            # GET /_tasks/{id}
            s, one = ctrl.dispatch("GET", f"/_tasks/{tid}", {}, b"")
            assert s == 200 and one["task"]["id"] == int(tid.split(":")[1])
            # actions= filter
            s, none = ctrl.dispatch("GET", "/_tasks",
                                    {"actions": "cluster:*"}, b"")
            assert none["nodes"][n.node_id]["tasks"] == {}
            # cat rows
            s, rows = ctrl.dispatch("GET", "/_cat/tasks", {}, b"")
            assert any(r["task_id"] == tid for r in rows)
        finally:
            release.set()
            th.join(5)
        s, body = ctrl.dispatch("GET", "/_tasks", {}, b"")
        assert body["nodes"][n.node_id]["tasks"] == {}

    def test_cancel_endpoint_flips_task(self):
        n = Node(name="c-node")
        ctrl = RestController(n)
        t = n.tasks.register("indices:data/read/scroll")
        try:
            s, body = ctrl.dispatch("POST",
                                    f"/_tasks/{t.tagged_id}/_cancel",
                                    {}, b"")
            assert s == 200
            assert t.cancelled
            assert t.tagged_id in body["nodes"][n.node_id]["tasks"]
            with pytest.raises(TaskCancelledException):
                t.check_cancelled()
        finally:
            n.tasks.unregister(t)

    def test_cancel_missing_task_404(self):
        n = Node(name="m-node")
        ctrl = RestController(n)
        s, body = ctrl.dispatch("POST", f"/_tasks/{n.node_id}:9999/_cancel",
                                {}, b"")
        assert s == 404
        assert body["error"]["type"] == "resource_not_found_exception"

    def test_pending_tasks_views(self):
        n = Node(name="p-node")
        ctrl = RestController(n)
        t = n.tasks.register("indices:recovery/start",
                             description="recover [i][0]",
                             status="pending")
        try:
            s, body = ctrl.dispatch("GET", "/_cluster/pending_tasks", {},
                                    b"")
            assert s == 200
            (row,) = body["tasks"]
            assert row["source"] == "indices:recovery/start"
            assert row["priority"] == "NORMAL"
            s, rows = ctrl.dispatch("GET", "/_cat/pending_tasks", {}, b"")
            assert rows and rows[0]["insertOrder"] == str(t.id)
            s, health = ctrl.dispatch("GET", "/_cluster/health", {}, b"")
            assert health["number_of_pending_tasks"] == 1
        finally:
            n.tasks.unregister(t)
        s, body = ctrl.dispatch("GET", "/_cluster/pending_tasks", {}, b"")
        assert body["tasks"] == []

    def test_byquery_cancel_reports_partial(self):
        """Single-node delete-by-query: cancel mid-scan → 200 with
        partial counts + "canceled"."""
        n = Node(name="bq-node")
        n.create_index("bq", {"settings": {"number_of_shards": 1}})
        for i in range(30):
            n.indices["bq"].index_doc(str(i), {"v": i})
        n.indices["bq"].refresh()
        ctrl = RestController(n)
        orig_delete = n.indices["bq"].delete_doc
        state = {"n": 0}

        def slow_delete(doc_id, **kw):
            state["n"] += 1
            if state["n"] == 3:
                # cancel OUR task from within (deterministic: no sleeps)
                (task,) = n.tasks.list_tasks(
                    actions="indices:data/write/delete/byquery")
                task.cancel("test says stop")
            return orig_delete(doc_id, **kw)

        n.indices["bq"].delete_doc = slow_delete
        s, body = ctrl.dispatch("POST", "/bq/_delete_by_query", {},
                                b'{"query": {"match_all": {}}}')
        assert s == 200
        assert "canceled" in body and "test says stop" in body["canceled"]
        assert 0 < body["deleted"] < 30  # partial, durable
        n.indices["bq"].refresh()
        left = n.indices["bq"].search({"size": 0})["hits"]["total"]
        assert left == 30 - body["deleted"]

    def test_scroll_cancel_stops_the_drain(self):
        """The scroll task spans the CONTEXT, not one page: cancel it
        between pages and the next page fails typed, context freed."""
        n = Node(name="sc-node")
        n.create_index("sc", {"settings": {"number_of_shards": 1}})
        for i in range(30):
            n.indices["sc"].index_doc(str(i), {"v": i})
        n.indices["sc"].refresh()
        ctrl = RestController(n)
        s, r = ctrl.dispatch(
            "POST", "/sc/_search", {},
            b'{"scroll": "1m", "size": 2, "query": {"match_all": {}}}')
        sid = r["_scroll_id"]
        s, page = ctrl.dispatch("GET", "/_search/scroll",
                                {"scroll_id": sid}, b"")
        assert s == 200 and page["hits"]["hits"]
        # the persistent scroll task is listed BETWEEN pages
        (task,) = n.tasks.list_tasks(actions="indices:data/read/scroll")
        s, _ = ctrl.dispatch("POST", f"/_tasks/{task.tagged_id}/_cancel",
                             {}, b"")
        assert s == 200
        # EAGER cleanup on cancel: context + task are gone immediately —
        # an abandoned client never sending another page must not pin
        # the snapshot in memory or leave a zombie /_tasks entry
        assert n.tasks.list_tasks(actions="indices:data/read/scroll") == []
        from elasticsearch_tpu.search.service import scroll_state

        assert scroll_state(sid) is None
        s, body = ctrl.dispatch("GET", "/_search/scroll",
                                {"scroll_id": sid}, b"")
        assert s == 404  # the drain is over

    def test_clear_scroll_retires_the_task(self):
        n = Node(name="cs-node")
        n.create_index("cs", {"settings": {"number_of_shards": 1}})
        n.indices["cs"].index_doc("1", {"v": 1})
        n.indices["cs"].refresh()
        ctrl = RestController(n)
        _s, r = ctrl.dispatch(
            "POST", "/cs/_search", {},
            b'{"scroll": "1m", "size": 1, "query": {"match_all": {}}}')
        sid = r["_scroll_id"]
        ctrl.dispatch("GET", "/_search/scroll", {"scroll_id": sid}, b"")
        assert n.tasks.list_tasks(actions="indices:data/read/scroll")
        s, _ = ctrl.dispatch("DELETE", "/_search/scroll",
                             {"scroll_id": sid}, b"")
        assert n.tasks.list_tasks(actions="indices:data/read/scroll") == []

    def test_node_trace_endpoint_chrome_format(self):
        n = Node(name="tr-node")
        n.create_index("tr", {"settings": {"number_of_shards": 1}})
        n.indices["tr"].index_doc("1", {"t": "x"})
        n.indices["tr"].refresh()
        ctrl = RestController(n)
        ctrl.dispatch("POST", "/tr/_search", {}, b"{}")
        s, dump = ctrl.dispatch("GET", "/_nodes/_local/trace", {}, b"")
        assert s == 200
        assert dump["traceEvents"], "search should have recorded spans"
        assert all(ev["ph"] == "X" for ev in dump["traceEvents"])
        assert any(ev["name"] == "search" for ev in dump["traceEvents"])


# -- cross-process propagation + cancellation ---------------------------------

@pytest.fixture()
def two_node_cluster():
    """Two full MultiHostClusters IN-PROCESS over real TCP (the transport
    doesn't care) — the same harness test_faults.py uses: rank 0 is
    master+coordinator, rank 1 owns half the shards."""
    from elasticsearch_tpu.cluster.bootstrap import MultiHostCluster

    port = _free_port()
    node0 = Node(name="rank0")
    c0 = MultiHostCluster(node0, rank=0, world=2, transport_port=port,
                          ping_interval=0)
    node1 = Node(name="rank1")
    c1 = MultiHostCluster(node1, rank=1, world=2, transport_port=port)
    c0.data.create_index("evt", {
        "settings": {"number_of_shards": 2},
        "mappings": {"properties": {"n": {"type": "integer"}}}})
    assig = c0.dist_indices["evt"]["assignment"]
    assert len({o[0] for o in assig.values()}) == 2, assig
    for i in range(24):
        c0.data.index_doc("evt", str(i), {"n": i})
    c0.data.refresh("evt")
    yield c0, c1
    try:
        c1.close()
    finally:
        c0.close()
        node1.close()
        node0.close()


class TestCrossProcess:
    def test_search_spans_share_one_trace_id(self, two_node_cluster):
        c0, c1 = two_node_cluster
        r = c0.data.search("evt", {"size": 24})
        assert r["hits"]["total"] == 24
        root = [s for s in c0.node.tracer.spans()
                if s.name == "search.coordinate"][-1]
        # coordinator-side: the scatter send rides under the root
        sends = [s for s in c0.node.tracer.spans()
                 if s.name == "transport.send"
                 and s.trace_id == root.trace_id]
        assert sends, "remote query phase should record a send span"
        # remote side: handle + shard query spans JOINED the same trace
        remote = [s for s in c1.node.tracer.spans()
                  if s.trace_id == root.trace_id]
        remote_names = {s.name for s in remote}
        assert "transport.handle" in remote_names
        assert "shard.query_phase" in remote_names
        # and the remote handle span hangs off a coordinator send span
        send_ids = {s.span_id for s in sends}
        assert any(s.parent_id in send_ids for s in remote
                   if s.name == "transport.handle")

    def test_profile_true_merges_remote_shard_phases(self, two_node_cluster):
        c0, _c1 = two_node_cluster
        r = c0.data.search("evt", {
            "size": 5, "profile": True,
            "query": {"bool": {"must": [{"match_all": {}}]}}})
        shards = r["profile"]["shards"]
        assert len(shards) == 2
        # one entry per shard, each labeled with its OWNER node
        owners = {sp["id"].split("]")[0].lstrip("[") for sp in shards}
        assert len(owners) == 2
        for sp in shards:
            assert "device_compile_nanos" in sp["tpu"]["phases"]
            assert "device_execute_nanos" in sp["tpu"]["phases"]

    def test_tasks_list_and_parent_cancel_stop_remote_byquery(
            self, two_node_cluster, monkeypatch):
        """Acceptance: GET /_tasks lists a running delete-by-query with
        its remote child task; POST /_tasks/{parent}/_cancel terminates
        both; the partial response reports "canceled"."""
        from elasticsearch_tpu.cluster.search_action import \
            DistributedDataService
        from elasticsearch_tpu.search import byquery

        c0, c1 = two_node_cluster
        ctrl0 = RestController(c0.node)

        # throttle every primary delete so the scan is observably
        # in-flight; signal once the first scan round begins
        scanning = threading.Event()
        orig_scan = byquery.scan_ids

        def signaled_scan(svc, query, seen):
            scanning.set()
            return orig_scan(svc, query, seen)

        monkeypatch.setattr(byquery, "scan_ids", signaled_scan)
        orig_write = DistributedDataService._primary_write

        def slow_write(self, *a, **kw):
            time.sleep(0.03)
            return orig_write(self, *a, **kw)

        monkeypatch.setattr(DistributedDataService, "_primary_write",
                            slow_write)

        result = {}

        def run():
            s, body = ctrl0.dispatch("POST", "/evt/_delete_by_query", {},
                                     b'{"query": {"match_all": {}}}')
            result["status"], result["body"] = s, body

        th = threading.Thread(target=run)
        th.start()
        try:
            assert scanning.wait(10)
            # poll /_tasks until the coordinator task AND its remote
            # child are both visible (the fanout is sequential)
            deadline = time.monotonic() + 10
            parent_id = child = None
            while time.monotonic() < deadline:
                _s, listing = ctrl0.dispatch("GET", "/_tasks", {}, b"")
                flat = {tid: t
                        for entry in listing["nodes"].values()
                        for tid, t in entry.get("tasks", {}).items()}
                parents = [tid for tid, t in flat.items()
                           if t["action"] ==
                           "indices:data/write/delete/byquery"]
                children = [(tid, t) for tid, t in flat.items()
                            if t["action"].endswith("byquery[s]")
                            and t.get("parent_task_id")]
                if parents and children:
                    parent_id = parents[0]
                    # a child registered on the REMOTE node, linked to
                    # the coordinator's task id
                    remote_children = [
                        (tid, t) for tid, t in children
                        if tid.startswith(c1.local.node_id)
                        and t["parent_task_id"] == parent_id]
                    if remote_children:
                        child = remote_children[0]
                        break
                time.sleep(0.02)
            assert parent_id is not None, "coordinator task never listed"
            assert child is not None, \
                "remote child task never listed with parent link"

            s, cancel_body = ctrl0.dispatch(
                "POST", f"/_tasks/{parent_id}/_cancel", {}, b"")
            assert s == 200
            cancelled_ids = {tid for entry in cancel_body["nodes"].values()
                             for tid in entry.get("tasks", {})}
            assert parent_id in cancelled_ids
            th.join(30)
            assert not th.is_alive()
            assert result["status"] == 200
            body = result["body"]
            assert "canceled" in body, body
            # partial: something may have been deleted, but not all 24
            assert body.get("deleted", 0) < 24
            # both tasks are gone from the registry afterwards
            _s, after = ctrl0.dispatch("GET", "/_tasks", {}, b"")
            leftover = [t for entry in after["nodes"].values()
                        for t in entry.get("tasks", {}).values()
                        if "byquery" in t["action"]]
            assert leftover == []
        finally:
            th.join(30)

    def test_cancel_remote_task_by_id_relays(self, two_node_cluster):
        c0, c1 = two_node_cluster
        ctrl0 = RestController(c0.node)
        t = c1.node.tasks.register("indices:data/read/scroll")
        try:
            s, body = ctrl0.dispatch(
                "POST", f"/_tasks/{t.tagged_id}/_cancel", {}, b"")
            assert s == 200
            assert t.cancelled
        finally:
            c1.node.tasks.unregister(t)

    def test_distributed_search_slowlog_records(self, two_node_cluster):
        """Distributed searches bypass IndexService.search, so the
        coordinator-side hook must record the slow log — thresholds on a
        multi-host index must not silently never fire."""
        c0, _c1 = two_node_cluster
        svc = c0.node.indices["evt"]
        svc.settings.setdefault("index", {})[
            "search.slowlog.threshold.query.trace"] = "0ms"
        before = svc.slowlog.query.total
        c0.data.search("evt", {"size": 1})
        assert svc.slowlog.query.total == before + 1

    def test_local_prefix_cancels_like_get(self, two_node_cluster):
        # GET and POST _cancel must accept the same "_local:{id}" form
        c0, _c1 = two_node_cluster
        ctrl0 = RestController(c0.node)
        t = c0.node.tasks.register("indices:data/read/scroll")
        try:
            s, one = ctrl0.dispatch("GET", f"/_tasks/_local:{t.id}", {},
                                    b"")
            assert s == 200 and one["task"]["id"] == t.id
            s, _ = ctrl0.dispatch("POST", f"/_tasks/_local:{t.id}/_cancel",
                                  {}, b"")
            assert s == 200 and t.cancelled
        finally:
            c0.node.tasks.unregister(t)

    def test_cancelled_queued_recovery_clears_initializing(
            self, two_node_cluster):
        """A recovery task cancelled while still QUEUED must not leak
        its target in the shard's `initializing` list — the copy would
        look in-flight forever and never re-heal."""
        c0, c1 = two_node_cluster
        target = c1.local.node_id
        with c0._indices_lock:
            meta = c0.dist_indices["evt"]
            meta.setdefault("initializing", {}).setdefault("0", [])
            if target not in meta["initializing"]["0"]:
                meta["initializing"]["0"].append(target)
        t = c0.node.tasks.register("indices:recovery/start",
                                   status="pending")
        t.cancel("queued no more")
        before_owners = list(c0.dist_indices["evt"]["assignment"]["0"])
        c0.data._run_recoveries([{
            "index": "evt", "shard": 0, "target": target,
            "source": c0.local.node_id, "body": meta["body"]}], [t])
        assert target not in c0.dist_indices["evt"]["initializing"]["0"]
        # a cancelled stream never graduates the copy
        assert c0.dist_indices["evt"]["assignment"]["0"] == before_owners
        assert c0.node.tasks.get(t.id) is None
