"""Direct parity coverage for ops/knn.py's chunked scan (ISSUE-9
satellite: knn_topk_chunked had no direct unit test) — against
knn_topk across chunk boundaries, all three metrics, and a masked
tail, plus the chunk-divisibility contract."""
import numpy as np
import pytest

from elasticsearch_tpu.ops.knn import knn_topk, knn_topk_chunked

METRICS = ("cosine", "dot_product", "l2_norm")


def _setup(D=256, dims=16, Q=5, live=None, seed=0):
    import jax

    rng = np.random.default_rng(seed)
    vecs = rng.standard_normal((D, dims)).astype(np.float32)
    queries = rng.standard_normal((Q, dims)).astype(np.float32)
    mask = np.ones(D, bool) if live is None else live
    return (jax.device_put(queries), jax.device_put(vecs),
            jax.device_put(mask))


@pytest.mark.parametrize("metric", METRICS)
def test_chunked_matches_unchunked_all_metrics(metric):
    q, v, m = _setup()
    vals_a, idx_a = knn_topk(q, v, m, k=7, metric=metric, use_bf16=False)
    vals_b, idx_b = knn_topk_chunked(q, v, m, k=7, metric=metric,
                                     chunk=64, use_bf16=False)
    # random floats: ties measure-zero, so ids match exactly
    np.testing.assert_array_equal(np.asarray(idx_a), np.asarray(idx_b))
    np.testing.assert_allclose(np.asarray(vals_a), np.asarray(vals_b),
                               rtol=1e-6)


def test_chunked_across_chunk_boundaries():
    """k straddling chunk sizes: winners spread across chunks and a k
    larger than one chunk's local top-k contribution still merges
    exactly (the per-chunk contribution is min(k, chunk))."""
    q, v, m = _setup(D=512, Q=3)
    for chunk, k in ((32, 48), (64, 64), (128, 10)):
        vals_a, idx_a = knn_topk(q, v, m, k=k, use_bf16=False)
        vals_b, idx_b = knn_topk_chunked(q, v, m, k=k, chunk=chunk,
                                         use_bf16=False)
        np.testing.assert_array_equal(np.asarray(idx_a),
                                      np.asarray(idx_b))
        np.testing.assert_allclose(np.asarray(vals_a),
                                   np.asarray(vals_b), rtol=1e-6)


def test_chunked_masked_tail():
    """A padded tail (mask False past n live docs) never surfaces: ids
    stay below n and parity holds against the unchunked form."""
    D, n = 256, 180
    live = np.zeros(D, bool)
    live[:n] = True
    q, v, m = _setup(D=D, live=live)
    vals_a, idx_a = knn_topk(q, v, m, k=9, use_bf16=False)
    vals_b, idx_b = knn_topk_chunked(q, v, m, k=9, chunk=64,
                                     use_bf16=False)
    assert np.asarray(idx_b).max() < n
    np.testing.assert_array_equal(np.asarray(idx_a), np.asarray(idx_b))
    np.testing.assert_allclose(np.asarray(vals_a), np.asarray(vals_b),
                               rtol=1e-6)
    # a fully-masked final chunk contributes nothing but -inf slots
    live2 = np.zeros(D, bool)
    live2[:5] = True
    q2, v2, m2 = _setup(D=D, live=live2, seed=1)
    vals_c, idx_c = knn_topk_chunked(q2, v2, m2, k=9, chunk=64,
                                     use_bf16=False)
    vc = np.asarray(vals_c)
    assert np.isneginf(vc[:, 5:]).all()
    assert np.asarray(idx_c)[:, :5].max() < 5


def test_chunked_rejects_undivisible_corpus():
    q, v, m = _setup(D=250)
    with pytest.raises(ValueError):
        knn_topk_chunked(q, v, m, k=5, chunk=64)


def test_chunked_bf16_parity_with_bf16_unchunked():
    """bf16 parity too: the chunked matmul computes the same row values
    as the full one (same dtype path), so merged top-k agrees."""
    q, v, m = _setup(D=256, seed=2)
    vals_a, idx_a = knn_topk(q, v, m, k=5, use_bf16=True)
    vals_b, idx_b = knn_topk_chunked(q, v, m, k=5, chunk=64,
                                     use_bf16=True)
    np.testing.assert_array_equal(np.asarray(idx_a), np.asarray(idx_b))
    np.testing.assert_allclose(np.asarray(vals_a), np.asarray(vals_b),
                               rtol=1e-6)
