"""utils/wire pack/unpack round trips (the cross-host JSON wire codec)."""
import json

import numpy as np
import pytest

from elasticsearch_tpu.utils import wire


@pytest.mark.parametrize("obj", [
    None, True, 3, 2.5, "x", [1, "a", None],
    {"a": 1, "b": [2.5, {"c": "d"}]},
    (1, 2, "three"),
    {3: "int-key", (1, 2): "tuple-key", 2.5: "float-key"},
    {"s": {1, 2, 3}},
    b"\x00\xffbytes",
    float("inf"), float("-inf"),
])
def test_round_trip(obj):
    packed = wire.pack(obj)
    wired = json.loads(json.dumps(packed))  # must survive the JSON frame
    assert wire.unpack(wired) == obj


def test_nan_round_trip():
    out = wire.unpack(json.loads(json.dumps(wire.pack(float("nan")))))
    assert np.isnan(out)


@pytest.mark.parametrize("arr", [
    np.arange(12, dtype=np.int32).reshape(3, 4),
    np.array([1.5, -2.5], dtype=np.float32),
    np.array([], dtype=np.float64),
    np.array(7, dtype=np.int64),  # 0-d
    np.array([True, False]),
])
def test_ndarray_round_trip(arr):
    out = wire.unpack(json.loads(json.dumps(wire.pack(arr))))
    assert isinstance(out, np.ndarray)
    assert out.dtype == arr.dtype and out.shape == arr.shape
    assert np.array_equal(out, arr)


def test_nested_agg_partial_shape():
    partial = {"groups": {"buckets": {("a", 1): {"count": np.int64(3),
                                                 "sums": np.ones(4)}},
                          "missing": 0}}
    out = wire.unpack(json.loads(json.dumps(wire.pack(partial))))
    assert out["groups"]["missing"] == 0
    b = out["groups"]["buckets"][("a", 1)]
    assert b["count"] == 3 and np.array_equal(b["sums"], np.ones(4))


def test_unpackable_type_raises():
    with pytest.raises(TypeError):
        wire.pack(object())
