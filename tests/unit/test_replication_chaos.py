"""Replication-safety chaos matrix (tier-1, seed-deterministic).

The kill-primary and rejoin-recovery scenarios run under a FIXED SEED
MATRIX in the normal pytest gate: the `transport.send` kill fault draws
from `random.Random(seed)` (utils/faults.py), so a regression replays
identically instead of needing a manual soak. The invariants asserted
are seed-independent:

- killing a primary mid-bulk and promoting the replica loses ZERO
  acknowledged ops (unacked ops may or may not survive — that's what
  "unacknowledged" means)
- a write raced to the demoted-but-unaware primary is fenced with a
  typed 409 `stale_primary_exception`, never silently acked
- the bounced node rejoins via CHECKPOINT-BASED recovery: `_recovery`
  counters prove ops replayed < docs in shard (no full-copy storm), and
  a diverged zombie copy falls back to a pruning full copy

Same in-process two-node-cluster harness as tests/unit/test_faults.py
(ping_interval=0: node death is declared explicitly, deterministically).
"""
import json
import socket
import time

import pytest

from elasticsearch_tpu.cluster.transport import PeerBreaker, TransportError
from elasticsearch_tpu.utils.errors import StalePrimaryException
from elasticsearch_tpu.utils.faults import FAULTS

#: the tier-1 chaos matrix — three fixed seeds, same grammar as
#: ESTPU_FAULTS "transport.send:prob=0.6:seed=<s>" for subprocess runs
CHAOS_SEEDS = [101, 202, 303]


@pytest.fixture(autouse=True)
def _clean_slate():
    FAULTS.clear()
    yield
    FAULTS.clear()


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


@pytest.fixture()
def replicated_cluster():
    """Two MultiHostClusters in-process; index `evt` with 2 shards and 1
    replica, so each node is primary for one shard and replica for the
    other."""
    from elasticsearch_tpu.cluster.bootstrap import MultiHostCluster
    from elasticsearch_tpu.node import Node

    port = _free_port()
    node0 = Node(name="rank0")
    # minimum_master_nodes=1: this harness declares node death EXPLICITLY
    # (_kill_node) and keeps the master serving alone afterwards — the
    # pre-quorum replication-safety semantics under test here; the
    # coordination-layer quorum/step-down behavior has its own matrix in
    # test_coordination_chaos.py
    c0 = MultiHostCluster(node0, rank=0, world=2, transport_port=port,
                          ping_interval=0, minimum_master_nodes=1)
    node1 = Node(name="rank1")
    c1 = MultiHostCluster(node1, rank=1, world=2, transport_port=port,
                          ping_interval=0, minimum_master_nodes=1)
    c0.data.create_index("evt", {
        "settings": {"number_of_shards": 2, "number_of_replicas": 1},
        "mappings": {"properties": {"n": {"type": "integer"}}}})
    meta = c0.dist_indices["evt"]
    assert all(len(v) == 2 for v in meta["assignment"].values()), meta
    assert meta["in_sync"] == meta["assignment"]
    assert meta["primary_terms"] == {"0": 1, "1": 1}
    yield c0, c1
    FAULTS.clear()
    try:
        c1.close()
    finally:
        c0.close()
        node1.close()
        node0.close()


def _arm_kill(addr, prob, seed):
    """Make every transport connect to `addr` fail with the seeded
    probability — the deterministic stand-in for a dying node."""
    host, port = addr
    FAULTS.inject(
        "transport.send", error=ConnectionRefusedError, count=-1,
        prob=prob, seed=seed,
        match=lambda ctx: ctx.get("address") == (host, port))


def _kill_node(c0, c1):
    """Declare node1 dead on the master (what the fault detector would
    do after N failed pings) — promotes in-sync survivors, bumps terms."""
    n1 = c0.node.cluster_state.nodes[c1.local.node_id]
    c0._on_node_failed(n1)


def _rejoin(c0, c1):
    """Replicate the bootstrap join handshake for an already-running
    member (bootstrap.MultiHostCluster.__init__'s non-master branch)."""
    got = c1.transport.send_remote(
        c1.master_addr, "cluster:join",
        {"node_id": c1.local.node_id, "name": c1.node.name,
         "transport_address": c1.local.transport_address})
    c1._adopt(got["nodes"], got.get("version", 0))
    c1._adopt_indices(got.get("indices", {}), got.get("indices_version", 0))


def _wait_for(cond, timeout=15.0, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return
        time.sleep(0.05)
    raise AssertionError(f"timed out waiting for {msg}")


def _bulk_with_midstream_kill(c0, c1, seed, n_docs=40, kill_at=10,
                              prob=0.6):
    """Index n_docs through the coordinator, arming the seeded kill fault
    after `kill_at` acks. Returns the set of ACKNOWLEDGED doc ids."""
    acked = set()
    for i in range(n_docs):
        if i == kill_at:
            host, port = c1.local.transport_address.rsplit(":", 1)
            _arm_kill((host, int(port)), prob, seed)
        doc_id = f"d{i}"
        try:
            res = c0.data.index_doc("evt", doc_id, {"n": i})
            assert res.get("_seq_no") is not None
            acked.add(doc_id)
        except (TransportError, OSError):
            pass  # unacked: the client was TOLD it failed
    return acked


@pytest.mark.parametrize("seed", CHAOS_SEEDS)
def test_kill_primary_mid_bulk_zero_acked_loss_and_stale_fence(
        replicated_cluster, seed):
    c0, c1 = replicated_cluster
    acked = _bulk_with_midstream_kill(c0, c1, seed)
    assert acked, "no write acked at all"
    old_terms = dict(c0.dist_indices["evt"]["primary_terms"])

    _kill_node(c0, c1)
    meta = c0.dist_indices["evt"]
    # every shard now has the SURVIVOR as its primary, and every shard
    # that changed hands runs under a BUMPED term
    for sid in ("0", "1"):
        assert meta["assignment"][sid][0] == c0.local.node_id
        assert c0.local.node_id in meta["in_sync"][sid]
    bumped = [sid for sid in ("0", "1")
              if meta["primary_terms"][sid] > old_terms[sid]]
    assert bumped, "no term bump despite a primary changing hands"

    # ZERO acked-op loss: every acknowledged doc is served by the
    # promoted copies (reads now route entirely to the survivor)
    c0.node.indices["evt"].refresh()
    for doc_id in sorted(acked):
        got = c0.data.get_doc("evt", doc_id)
        assert got.get("found"), f"ACKED doc {doc_id} lost after promotion"

    # a write raced to the demoted-but-unaware primary: node1 still
    # holds the stale metadata (the kill fault ate the publishes), so it
    # applies locally and fans out — the promoted copy fences the stale
    # term and the client gets a typed 409, NOT a silent ack
    sid_old_primary = next(
        sid for sid in ("0", "1") if meta["primary_terms"][sid]
        > old_terms[sid])
    assert c1.dist_indices["evt"]["assignment"][sid_old_primary][0] \
        == c1.local.node_id, "node1 should still believe it is primary"
    from elasticsearch_tpu.cluster.routing import shard_id_for

    zombie_id = next(f"z{k}" for k in range(1000)
                     if shard_id_for(f"z{k}", 2) == int(sid_old_primary))
    with pytest.raises(Exception) as ei:
        c1.data.index_doc("evt", zombie_id, {"n": -1})
    assert getattr(ei.value, "error_type", "") == "stale_primary_exception"
    assert getattr(ei.value, "status", 0) == 409
    # the promoted primary never saw the fenced write
    assert not c0.node.indices["evt"].shards[int(sid_old_primary)] \
        .engine.exists(zombie_id)

    # REJOIN: the bounced node recovers; the shard it wrote the zombie
    # doc to has DIVERGED history → pruning full copy; its other copy is
    # a clean prefix → checkpoint ops-replay
    FAULTS.clear()
    c0.transport.breaker = PeerBreaker()
    c1.transport.breaker = PeerBreaker()
    _rejoin(c0, c1)
    _wait_for(lambda: all(
        c1.local.node_id in c0.dist_indices["evt"]["assignment"][s]
        for s in ("0", "1")), msg="rejoined copies to graduate")
    recs = {e["shard"]: e for e in
            c1.node.indices["evt"].recoveries.entries()
            if e["type"] == "peer" and e["stage"] == "done"}
    assert recs[int(sid_old_primary)]["mode"] == "full"  # diverged
    other = 1 - int(sid_old_primary)
    assert recs[other]["mode"] == "ops"                  # clean prefix
    # the zombie doc did not survive its copy's re-sync
    assert not c1.node.indices["evt"].shards[int(sid_old_primary)] \
        .engine.exists(zombie_id)
    # graduated copies are back in the in-sync set
    assert all(c1.local.node_id in c0.dist_indices["evt"]["in_sync"][s]
               for s in ("0", "1"))


@pytest.mark.parametrize("seed", CHAOS_SEEDS)
def test_rejoin_recovers_incrementally_via_ops_replay(
        replicated_cluster, seed):
    c0, c1 = replicated_cluster
    acked = _bulk_with_midstream_kill(c0, c1, seed)
    _kill_node(c0, c1)

    # the promoted primaries keep taking writes while node1 is down
    extra = set()
    for i in range(40, 48):
        res = c0.data.index_doc("evt", f"d{i}", {"n": i})
        extra.add(f"d{i}")
        assert res.get("_seq_no") is not None

    FAULTS.clear()
    c0.transport.breaker = PeerBreaker()
    c1.transport.breaker = PeerBreaker()
    _rejoin(c0, c1)
    _wait_for(lambda: all(
        c1.local.node_id in c0.dist_indices["evt"]["assignment"][s]
        for s in ("0", "1")), msg="rejoined copies to graduate")

    # NO full copy anywhere: node1's copies were clean prefixes, so both
    # shards recovered by replaying only op suffixes above their local
    # checkpoints (a shard may recover more than once: the mid-bulk
    # demotion scheduled a re-sync besides the join-time stream — every
    # stream must still be incremental)
    recs = [e for e in c1.node.indices["evt"].recoveries.entries()
            if e["type"] == "peer" and e["stage"] == "done"]
    assert {e["shard"] for e in recs} == {0, 1}
    assert all(e["mode"] == "ops" for e in recs), recs
    total_ops_replayed = sum(e["ops_replayed"] for e in recs)
    total_docs = sum(
        c0.node.indices["evt"].shards[s].engine.num_docs for s in (0, 1))
    assert 0 < total_ops_replayed < total_docs, (
        f"replayed {total_ops_replayed} vs {total_docs} docs — "
        f"an incremental recovery must move less than the whole shard")

    # the GET {index}/_recovery endpoint proves it the acceptance way
    from elasticsearch_tpu.rest.server import RestController

    status, body = RestController(c1.node).dispatch(
        "GET", "/evt/_recovery", {}, b"")
    assert status == 200
    peer_rows = [sh for sh in body["evt"]["shards"]
                 if sh.get("mode") == "ops"]
    assert {sh["id"] for sh in peer_rows} == {0, 1}
    for sh in peer_rows:
        docs_in_shard = c1.node.indices["evt"].shards[sh["id"]] \
            .engine.num_docs
        assert sh["translog"]["recovered"] < docs_in_shard

    # and the recovered copies serve every acked doc
    c1.node.indices["evt"].refresh()
    for doc_id in sorted(acked | extra):
        sid = None
        from elasticsearch_tpu.cluster.routing import shard_id_for
        sid = shard_id_for(doc_id, 2)
        assert c1.node.indices["evt"].shards[sid].engine.exists(doc_id), \
            f"acked doc {doc_id} missing on the rejoined copy"

    # node-level gauges aggregated the incremental recoveries
    nodes = c1.node.nodes_stats()["nodes"]
    rec = nodes[c1.node.node_id]["indices"]["recovery"]
    assert rec["incremental"] >= 2
    assert rec["ops_replayed"] == total_ops_replayed


def test_env_spec_arms_new_points():
    """The ESTPU_FAULTS grammar covers the new replication-safety points
    (subprocess cluster members arm through it)."""
    from elasticsearch_tpu.utils.faults import FaultRegistry, _parse_env_spec

    r = FaultRegistry()
    _parse_env_spec(
        "replication.fanout:prob=0.3:seed=42;recovery.ops_replay:count=2",
        r)
    assert r.active("replication.fanout")
    assert r.active("recovery.ops_replay")
