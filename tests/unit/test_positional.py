"""Device positional program tests vs a numpy sloppy-freq oracle.

Round-1 verdict item 5: phrase/span interval verification on device with
Lucene-style scoring (phrase as a pseudo-term: idf_sum * tfNorm(freq)).
The oracle mirrors the program's documented semantics (greedy
nearest-to-expected window per anchor) and equals Lucene's on
non-degenerate phrases.
"""
import numpy as np
import pytest

from elasticsearch_tpu.node import Node

DOCS = {
    "1": "the quick brown fox jumps over the lazy dog",
    "2": "quick fox",                    # adjacent, no 'brown'
    "3": "quick brown smart fox",        # fox at +3 from quick (slop 1 for 'quick fox'? dist 3→ window)
    "4": "fox quick brown",              # reversed order
    "5": "brown quick brown fox brown fox",  # repeats
    "6": "the fox",
}


@pytest.fixture(scope="module")
def node():
    n = Node()
    n.create_index("p", {"mappings": {"properties": {
        "t": {"type": "text", "analyzer": "whitespace"}}}})
    svc = n.indices["p"]
    for did, text in DOCS.items():
        svc.index_doc(did, {"t": text})
    svc.refresh()
    yield n
    n.close()


# --- numpy oracle -----------------------------------------------------------

def _tokens(text):
    return text.split()


def oracle_phrase_freq(text, terms, slop):
    """Greedy nearest-window per anchor occurrence of terms[0]."""
    toks = _tokens(text)
    pos = {t: [i for i, x in enumerate(toks) if x == t] for t in set(terms)}
    if any(not pos.get(t) for t in terms):
        return 0.0
    freq = 0.0
    for p0 in pos[terms[0]]:
        adjs = [p0]
        ok = True
        for delta, t in enumerate(terms[1:], start=1):
            expected = p0 + delta
            q = min(pos[t], key=lambda x: abs(x - expected))
            adjs.append(q - delta)
            if slop == 0 and q != expected:
                ok = False
                break
        if not ok:
            continue
        mlen = max(adjs) - min(adjs)
        if mlen <= slop:
            freq += 1.0 / (1.0 + mlen)
    return freq


def oracle_phrase_score(node, field, terms, slop, doc_id):
    """idf_sum * tfNorm(freq) with BM25 k1=1.2, b=0.75 over the corpus."""
    texts = DOCS
    n_docs = len(texts)
    k1, b = 1.2, 0.75
    lens = {d: len(_tokens(t)) for d, t in texts.items()}
    avg = sum(lens.values()) / n_docs
    idf_sum = 0.0
    for t in dict.fromkeys(terms):
        df = sum(1 for txt in texts.values() if t in _tokens(txt))
        idf_sum += np.log(1.0 + (n_docs - df + 0.5) / (df + 0.5))
    f = oracle_phrase_freq(texts[doc_id], terms, slop)
    if f == 0:
        return 0.0
    norm = k1 * (1 - b + b * lens[doc_id] / avg)
    return idf_sum * f * (k1 + 1) / (f + norm)


# --- tests ------------------------------------------------------------------

def search_scores(node, body):
    r = node.search("p", body)
    return {h["_id"]: h["_score"] for h in r["hits"]["hits"]}


def test_exact_phrase_matches_and_scores(node):
    got = search_scores(node, {"query": {"match_phrase": {"t": "quick brown fox"}},
                              "size": 10})
    want_ids = {d for d, txt in DOCS.items()
                if oracle_phrase_freq(txt, ["quick", "brown", "fox"], 0) > 0}
    assert set(got) == want_ids == {"1", "5"}
    for d, s in got.items():
        want = oracle_phrase_score(node, "t", ["quick", "brown", "fox"], 0, d)
        assert abs(s - want) < 1e-4, (d, s, want)


def test_exact_phrase_two_terms(node):
    got = search_scores(node, {"query": {"match_phrase": {"t": "quick fox"}},
                              "size": 10})
    assert set(got) == {"2"}
    want = oracle_phrase_score(node, "t", ["quick", "fox"], 0, "2")
    assert abs(got["2"] - want) < 1e-4


def test_sloppy_phrase(node):
    terms = ["quick", "fox"]
    for slop in (1, 2, 3):
        got = search_scores(node, {"query": {"match_phrase": {
            "t": {"query": "quick fox", "slop": slop}}}, "size": 10})
        want_ids = {d for d, txt in DOCS.items()
                    if oracle_phrase_freq(txt, terms, slop) > 0}
        assert set(got) == want_ids, (slop, set(got), want_ids)
        for d, s in got.items():
            want = oracle_phrase_score(node, "t", terms, slop, d)
            assert abs(s - want) < 1e-4, (slop, d, s, want)


def test_phrase_repeated_terms(node):
    # "brown fox" in doc 5 occurs twice → freq 2 at slop 0
    assert oracle_phrase_freq(DOCS["5"], ["brown", "fox"], 0) == 2.0
    got = search_scores(node, {"query": {"match_phrase": {"t": "brown fox"}},
                              "size": 10})
    assert "5" in got
    want = oracle_phrase_score(node, "t", ["brown", "fox"], 0, "5")
    assert abs(got["5"] - want) < 1e-4


def test_no_per_doc_python_loops_in_phrase(node, monkeypatch):
    """The execute path must not walk docs on host: forbid ndarray.__iter__
    over doc-sized arrays by asserting the old helper is gone."""
    from elasticsearch_tpu.search.queries import MatchPhraseQuery

    assert not hasattr(MatchPhraseQuery, "_phrase_in_doc")
    assert not hasattr(MatchPhraseQuery, "_positions_for")


def test_span_near_ordered_device(node):
    body = {"query": {"span_near": {
        "clauses": [{"span_term": {"t": "quick"}},
                    {"span_term": {"t": "fox"}}],
        "slop": 2, "in_order": True}}, "size": 10}
    got = search_scores(node, body)
    # ordered chaining: quick…fox within width-2 ≤ slop
    want = set()
    for d, txt in DOCS.items():
        toks = _tokens(txt)
        qs = [i for i, x in enumerate(toks) if x == "quick"]
        fs = [i for i, x in enumerate(toks) if x == "fox"]
        for q in qs:
            nxt = [f for f in fs if f > q]
            if nxt and (min(nxt) - q + 1) - 2 <= 2:
                want.add(d)
    assert set(got) == want, (set(got), want)
    # reversed order doc 4 must NOT match in_order near with slop 0
    body0 = {"query": {"span_near": {
        "clauses": [{"span_term": {"t": "quick"}},
                    {"span_term": {"t": "fox"}}],
        "slop": 0, "in_order": True}}, "size": 10}
    got0 = search_scores(node, body0)
    assert "4" not in got0 and "2" in got0


def test_phrase_prefix_still_works(node):
    got = search_scores(node, {"query": {"match_phrase_prefix": {"t": "quick bro"}},
                              "size": 10})
    assert "1" in got


def test_freq_segmented_matches_scatter_high_multiplicity():
    """_freq_segmented == scatter-add freq under heavy per-doc anchor
    multiplicity (tf up to 64), unsorted anchor order, and padding."""
    import jax.numpy as jnp

    from elasticsearch_tpu.ops.positional import _freq_segmented

    rng = np.random.default_rng(13)
    D, A = 256, 2048
    docs = rng.integers(0, 40, A).astype(np.int32)  # heavy duplication
    w = (rng.random(A) * 2).astype(np.float32)
    match = rng.random(A) > 0.3
    got = np.asarray(_freq_segmented(
        jnp.asarray(docs), jnp.asarray(match), jnp.asarray(w), D=D))
    want = np.zeros(D, np.float32)
    np.add.at(want, docs[match], w[match])
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)
    # all-masked and single-doc edge cases
    got0 = np.asarray(_freq_segmented(
        jnp.asarray(docs), jnp.zeros(A, bool), jnp.asarray(w), D=D))
    assert not got0.any()
    one = np.full(A, 7, np.int32)
    got1 = np.asarray(_freq_segmented(
        jnp.asarray(one), jnp.ones(A, bool), jnp.asarray(w), D=D))
    np.testing.assert_allclose(got1[7], w.sum(), rtol=2e-5)
    assert not np.delete(got1, 7).any()
