"""Geo completion tests (round-1 verdict item 7) vs numpy haversine oracle.

Reference: search/aggregations/bucket/geogrid/GeoHashGridParser.java,
search/aggregations/bucket/range/geodistance/, search/sort/
GeoDistanceSortParser.java, index/query/GeoShapeQueryBuilder.java.
"""
import numpy as np
import pytest

from elasticsearch_tpu.node import Node
from elasticsearch_tpu.search.geo import (geohash_decode, geohash_encode_cell,
                                          geohash_bits, haversine_np)

CITIES = {
    "paris": (48.8566, 2.3522),
    "london": (51.5074, -0.1278),
    "berlin": (52.5200, 13.4050),
    "madrid": (40.4168, -3.7038),
    "rome": (41.9028, 12.4964),
    "nyc": (40.7128, -74.0060),
    "tokyo": (35.6762, 139.6503),
}


@pytest.fixture(scope="module")
def node():
    n = Node()
    n.create_index("g", {"mappings": {"properties": {
        "loc": {"type": "geo_point"}, "name": {"type": "keyword"}}}})
    svc = n.indices["g"]
    for name, (lat, lon) in CITIES.items():
        svc.index_doc(name, {"loc": {"lat": lat, "lon": lon}, "name": name})
    svc.index_doc("noloc", {"name": "noloc"})
    svc.refresh()
    yield n
    n.close()


def test_geohash_roundtrip():
    for lat, lon in CITIES.values():
        for p in (1, 3, 5, 7):
            lat_bits, lon_bits = geohash_bits(p)
            nlat, nlon = 1 << lat_bits, 1 << lon_bits
            lat_cell = min(int((lat + 90.0) / 180.0 * nlat), nlat - 1)
            lon_cell = min(int((lon + 180.0) / 360.0 * nlon), nlon - 1)
            gh = geohash_encode_cell(lon_cell * nlat + lat_cell, p)
            dec_lat, dec_lon = geohash_decode(gh)
            assert abs(dec_lat - lat) <= 180.0 / nlat
            assert abs(dec_lon - lon) <= 360.0 / nlon


def test_known_geohash():
    # well-known value: Paris ≈ u09t (precision 4)
    lat, lon = CITIES["paris"]
    lat_bits, lon_bits = geohash_bits(4)
    nlat, nlon = 1 << lat_bits, 1 << lon_bits
    lat_cell = min(int((lat + 90.0) / 180.0 * nlat), nlat - 1)
    lon_cell = min(int((lon + 180.0) / 360.0 * nlon), nlon - 1)
    assert geohash_encode_cell(lon_cell * nlat + lat_cell, 4) == "u09t"


def test_geohash_grid_agg(node):
    r = node.search("g", {"size": 0, "aggs": {
        "grid": {"geohash_grid": {"field": "loc", "precision": 1}}}})
    buckets = {b["key"]: b["doc_count"] for b in r["aggregations"]["grid"]["buckets"]}
    # precision-1 cells: paris/london/madrid → u/g/e zone boundaries; verify
    # against oracle encoding
    total = sum(buckets.values())
    assert total == len(CITIES)
    for name, (lat, lon) in CITIES.items():
        lat_bits, lon_bits = geohash_bits(1)
        nlat, nlon = 1 << lat_bits, 1 << lon_bits
        cell = (min(int((lon + 180.0) / 360.0 * nlon), nlon - 1) * nlat
                + min(int((lat + 90.0) / 180.0 * nlat), nlat - 1))
        gh = geohash_encode_cell(cell, 1)
        assert gh in buckets, (name, gh, buckets)


def test_geo_distance_agg(node):
    origin = CITIES["paris"]
    r = node.search("g", {"size": 0, "aggs": {
        "rings": {"geo_distance": {
            "field": "loc", "origin": {"lat": origin[0], "lon": origin[1]},
            "unit": "km",
            "ranges": [{"to": 500}, {"from": 500, "to": 1500},
                       {"from": 1500}]}}}})
    buckets = r["aggregations"]["rings"]["buckets"]
    by_key = {b["key"]: b["doc_count"] for b in buckets}
    # oracle
    want = {"*-500.0": 0, "500.0-1500.0": 0, "1500.0-*": 0}
    for name, (lat, lon) in CITIES.items():
        d = haversine_np(lat, lon, origin[0], origin[1]) / 1000.0
        if d < 500:
            want["*-500.0"] += 1
        elif d < 1500:
            want["500.0-1500.0"] += 1
        else:
            want["1500.0-*"] += 1
    assert by_key == want, (by_key, want)


def test_geo_distance_sort(node):
    origin = CITIES["paris"]
    r = node.search("g", {"query": {"exists": {"field": "name"}},
                          "sort": [{"_geo_distance": {
                              "loc": {"lat": origin[0], "lon": origin[1]},
                              "order": "asc", "unit": "km"}}],
                          "size": 10})
    got = [h["_id"] for h in r["hits"]["hits"]]
    oracle = sorted(CITIES, key=lambda c: haversine_np(*CITIES[c], *origin))
    # noloc has no geo point: dropped from the sorted candidates (matches
    # the numeric-sort missing handling)
    assert got == oracle, (got, oracle)
    dists = [h["sort"][0] for h in r["hits"]["hits"]]
    assert dists[0] == 0.0 or dists[0] < 1.0  # paris itself
    assert dists == sorted(dists)
    # oracle distance check (km, 0.5% tolerance)
    for cid, d in zip(got, dists):
        want = haversine_np(*CITIES[cid], *origin) / 1000.0
        assert abs(d - want) <= max(0.005 * want, 0.5), (cid, d, want)


def test_geo_shape_queries(node):
    # envelope around western europe: [left, top], [right, bottom]
    r = node.search("g", {"query": {"geo_shape": {"loc": {"shape": {
        "type": "envelope", "coordinates": [[-5.0, 53.0], [15.0, 40.0]]}}}},
        "size": 10})
    ids = {h["_id"] for h in r["hits"]["hits"]}
    assert ids == {"paris", "london", "berlin", "madrid", "rome"}
    # polygon roughly around France (lon, lat rings)
    r2 = node.search("g", {"query": {"geo_shape": {"loc": {"shape": {
        "type": "polygon",
        "coordinates": [[[-1.5, 43.0], [7.0, 43.0], [8.0, 49.5],
                         [2.0, 51.0], [-4.0, 48.5], [-1.5, 43.0]]]}}}},
        "size": 10})
    assert {h["_id"] for h in r2["hits"]["hits"]} == {"paris"}
    # circle: 400km around london → london + paris
    r3 = node.search("g", {"query": {"geo_shape": {"loc": {"shape": {
        "type": "circle", "coordinates": [-0.1278, 51.5074],
        "radius": "400km"}}}}, "size": 10})
    assert {h["_id"] for h in r3["hits"]["hits"]} == {"london", "paris"}


def test_geohash_grid_high_precision(node):
    # precision 12 needs int64 cell ids (60 bits) — must not truncate
    r = node.search("g", {"size": 0, "aggs": {
        "grid": {"geohash_grid": {"field": "loc", "precision": 12}}}})
    buckets = r["aggregations"]["grid"]["buckets"]
    assert len(buckets) == len(CITIES)  # every city its own 12-char cell
    assert all(len(b["key"]) == 12 and b["doc_count"] == 1 for b in buckets)
    # each key decodes back to its city within cell resolution
    keys = {b["key"] for b in buckets}
    for lat, lon in CITIES.values():
        best = min(keys, key=lambda k: haversine_np(*geohash_decode(k), lat, lon))
        dec_lat, dec_lon = geohash_decode(best)
        assert haversine_np(dec_lat, dec_lon, lat, lon) < 5.0  # meters
