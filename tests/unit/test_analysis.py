from elasticsearch_tpu.analysis.analyzer import get_analyzer, build_custom_analyzer
from elasticsearch_tpu.analysis.filters import porter_stem, shingle_filter
from elasticsearch_tpu.analysis.tokenizers import (
    standard_tokenizer,
    path_hierarchy_tokenizer,
    edge_ngram_tokenizer,
)
from elasticsearch_tpu.analysis.char_filters import html_strip
from elasticsearch_tpu.analysis.registry import AnalysisRegistry


def test_standard_analyzer():
    an = get_analyzer("standard")
    assert an.tokens("The Quick-Brown Fox, jumped!") == ["the", "quick", "brown", "fox", "jumped"]


def test_standard_positions_and_gaps():
    an = get_analyzer("english")
    toks = an.analyze("the quick fox")  # "the" is a stopword -> position gap
    assert toks == [("quick", 1), ("fox", 2)]


def test_english_stemming():
    an = get_analyzer("english")
    assert an.tokens("running runs runner") == ["run", "run", "runner"]


def test_porter_classic_vectors():
    vectors = {
        "caresses": "caress", "ponies": "poni", "ties": "ti", "caress": "caress",
        "cats": "cat", "feed": "feed", "agreed": "agre", "plastered": "plaster",
        "motoring": "motor", "sing": "sing", "conflated": "conflat",
        "troubled": "troubl", "sized": "size", "hopping": "hop", "falling": "fall",
        "happy": "happi", "relational": "relat", "conditional": "condit",
        "vietnamization": "vietnam", "predication": "predic",
        "triplicate": "triplic", "formative": "form", "electrical": "electr",
        "hopefulness": "hope", "goodness": "good", "revival": "reviv",
        "allowance": "allow", "inference": "infer", "adjustable": "adjust",
        "defensible": "defens", "effective": "effect", "probate": "probat",
        "rate": "rate", "cease": "ceas", "controll": "control", "roll": "roll",
    }
    for w, want in vectors.items():
        assert porter_stem(w) == want, (w, porter_stem(w), want)


def test_keyword_whitespace_simple():
    assert get_analyzer("keyword").tokens("New York") == ["New York"]
    assert get_analyzer("whitespace").tokens("a-b c") == ["a-b", "c"]
    assert get_analyzer("simple").tokens("a1 b2-c") == ["a", "b", "c"]


def test_html_strip():
    assert html_strip("<p>Hello &amp; <b>world</b></p>").split() == ["Hello", "&", "world"]


def test_custom_analyzer_with_shared_filters():
    reg = AnalysisRegistry(
        {
            "analysis": {
                "filter": {"my_stop": {"type": "stop", "stopwords": ["foo"]}},
                "analyzer": {
                    "my_an": {"tokenizer": "standard", "filter": ["lowercase", "my_stop"]}
                },
            }
        }
    )
    assert reg.get("my_an").tokens("Foo BAR baz") == ["bar", "baz"]


def test_shingles():
    toks = [("quick", 0), ("brown", 1), ("fox", 2)]
    out = [t for t, _ in shingle_filter(toks)]
    assert out == ["quick", "quick brown", "brown", "brown fox", "fox"]


def test_edge_ngram_and_path_hierarchy():
    assert [t for t, _ in edge_ngram_tokenizer("quick", 1, 3)] == ["q", "qu", "qui"]
    assert [t for t, _ in path_hierarchy_tokenizer("/a/b/c")] == ["/a", "/a/b", "/a/b/c"]


def test_synonyms():
    an = build_custom_analyzer(
        "syn",
        {"tokenizer": "whitespace", "filter": ["lowercase", "my_syn"]},
        {"filter": {"my_syn": {"type": "synonym", "synonyms": ["usa, united states => america"]}}},
    )
    assert an.tokens("USA rules") == ["america", "rules"]


def test_light_language_stemmers():
    """snowball/stemmer language table (r3 verdict: the filters.py 'R3'
    promise) — light UniNE-family stemming: inflected forms of one lemma
    map to one stem, and stems actually shrink."""
    from elasticsearch_tpu.analysis.filters import light_stem, stemmer_filter

    pairs = [
        ("french", ["chanteuse", "chanteuses"]),
        ("french", ["nationale", "nationales"]),
        ("german", ["kindern", "kinder"]),
        ("german", ["häusern", "hauses"]),
        ("spanish", ["gatos", "gato"]),
        ("italian", ["bellissima", "bellissime"]),
        ("portuguese", ["gatos", "gato"]),
        ("dutch", ["huizen", "huize"]),
        ("swedish", ["flickorna", "flickor"]),
        ("russian", ["книгами", "книгах"]),
    ]
    for lang, words in pairs:
        stems = {light_stem(w, lang) for w in words}
        assert len(stems) == 1, (lang, words, stems)
        assert len(next(iter(stems))) < max(len(w) for w in words)
    # filter plumbing: language kwarg + aliases
    toks = [("kindern", 0)]
    assert stemmer_filter(toks, language="german") == [("kind", 0)]
    assert stemmer_filter(toks, language="light_german") == [("kind", 0)]
    # english still runs real Porter
    assert stemmer_filter([("running", 0)], language="english") == [("run", 0)]
    # unknown language: identity, never a crash
    assert stemmer_filter([("словами", 0)], language="klingon") == [("словами", 0)]


def test_snowball_filter_in_custom_analyzer():
    from elasticsearch_tpu.analysis.registry import AnalysisRegistry

    reg = AnalysisRegistry({"analysis": {
        "filter": {"de_stem": {"type": "snowball", "language": "german"}},
        "analyzer": {"de": {"type": "custom", "tokenizer": "standard",
                            "filter": ["lowercase", "de_stem"]}}}})
    an = reg.get("de")
    assert [t for t, _ in an.analyze("Kindern spielen")] == ["kind", "spiel"]


def test_stemmer_folded_suffixes_and_capitalized_names():
    """Review regressions: accented suffixes must match folded words
    (nação/nações stem together) and ES's capitalized snowball names work."""
    from elasticsearch_tpu.analysis.filters import light_stem, stemmer_filter

    assert light_stem("nação", "portuguese") == light_stem("nações", "portuguese")
    assert stemmer_filter([("Kindern", 0)], language="German") == [("kindern", 0)] or \
        stemmer_filter([("kindern", 0)], language="German") == [("kind", 0)]


def test_language_and_snowball_analyzers():
    """SnowballAnalyzerProvider + per-language analyzer providers: analyzer
    names like 'german' and {type: snowball, language: X} resolve."""
    an = get_analyzer("german")
    # 'Die' is a GERMAN stopword (language stop lists since r4 — the
    # english-only list used to let it through)
    assert an.tokens("Die Kindern spielen") == ["kind", "spiel"]
    reg = AnalysisRegistry({"analysis": {"analyzer": {
        "sb": {"type": "snowball", "language": "French"}}}})
    assert reg.get("sb").tokens("les chanteuses nationales") == [
        "chant", "national"]  # 'les' stopped by the french list
    # mappable on fields end to end
    from elasticsearch_tpu.node import Node

    n = Node()
    n.create_index("fr", {"mappings": {"properties": {
        "t": {"type": "text", "analyzer": "french"}}}})
    svc = n.indices["fr"]
    svc.index_doc("1", {"t": "les chanteuses"})
    svc.refresh()
    r = n.search("fr", {"query": {"match": {"t": "chanteuse"}}})
    assert r["hits"]["total"] == 1
    n.close()
