from elasticsearch_tpu.analysis.analyzer import get_analyzer, build_custom_analyzer
from elasticsearch_tpu.analysis.filters import porter_stem, shingle_filter
from elasticsearch_tpu.analysis.tokenizers import (
    standard_tokenizer,
    path_hierarchy_tokenizer,
    edge_ngram_tokenizer,
)
from elasticsearch_tpu.analysis.char_filters import html_strip
from elasticsearch_tpu.analysis.registry import AnalysisRegistry


def test_standard_analyzer():
    an = get_analyzer("standard")
    assert an.tokens("The Quick-Brown Fox, jumped!") == ["the", "quick", "brown", "fox", "jumped"]


def test_standard_positions_and_gaps():
    an = get_analyzer("english")
    toks = an.analyze("the quick fox")  # "the" is a stopword -> position gap
    assert toks == [("quick", 1), ("fox", 2)]


def test_english_stemming():
    an = get_analyzer("english")
    assert an.tokens("running runs runner") == ["run", "run", "runner"]


def test_porter_classic_vectors():
    vectors = {
        "caresses": "caress", "ponies": "poni", "ties": "ti", "caress": "caress",
        "cats": "cat", "feed": "feed", "agreed": "agre", "plastered": "plaster",
        "motoring": "motor", "sing": "sing", "conflated": "conflat",
        "troubled": "troubl", "sized": "size", "hopping": "hop", "falling": "fall",
        "happy": "happi", "relational": "relat", "conditional": "condit",
        "vietnamization": "vietnam", "predication": "predic",
        "triplicate": "triplic", "formative": "form", "electrical": "electr",
        "hopefulness": "hope", "goodness": "good", "revival": "reviv",
        "allowance": "allow", "inference": "infer", "adjustable": "adjust",
        "defensible": "defens", "effective": "effect", "probate": "probat",
        "rate": "rate", "cease": "ceas", "controll": "control", "roll": "roll",
    }
    for w, want in vectors.items():
        assert porter_stem(w) == want, (w, porter_stem(w), want)


def test_keyword_whitespace_simple():
    assert get_analyzer("keyword").tokens("New York") == ["New York"]
    assert get_analyzer("whitespace").tokens("a-b c") == ["a-b", "c"]
    assert get_analyzer("simple").tokens("a1 b2-c") == ["a", "b", "c"]


def test_html_strip():
    assert html_strip("<p>Hello &amp; <b>world</b></p>").split() == ["Hello", "&", "world"]


def test_custom_analyzer_with_shared_filters():
    reg = AnalysisRegistry(
        {
            "analysis": {
                "filter": {"my_stop": {"type": "stop", "stopwords": ["foo"]}},
                "analyzer": {
                    "my_an": {"tokenizer": "standard", "filter": ["lowercase", "my_stop"]}
                },
            }
        }
    )
    assert reg.get("my_an").tokens("Foo BAR baz") == ["bar", "baz"]


def test_shingles():
    toks = [("quick", 0), ("brown", 1), ("fox", 2)]
    out = [t for t, _ in shingle_filter(toks)]
    assert out == ["quick", "quick brown", "brown", "brown fox", "fox"]


def test_edge_ngram_and_path_hierarchy():
    assert [t for t, _ in edge_ngram_tokenizer("quick", 1, 3)] == ["q", "qu", "qui"]
    assert [t for t, _ in path_hierarchy_tokenizer("/a/b/c")] == ["/a", "/a/b", "/a/b/c"]


def test_synonyms():
    an = build_custom_analyzer(
        "syn",
        {"tokenizer": "whitespace", "filter": ["lowercase", "my_syn"]},
        {"filter": {"my_syn": {"type": "synonym", "synonyms": ["usa, united states => america"]}}},
    )
    assert an.tokens("USA rules") == ["america", "rules"]
