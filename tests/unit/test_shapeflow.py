"""tpulint v3 pass 3 (tools/tpulint/shapeflow.py): the symbolic
shape-flow lattice and its four gate rules, plus the CLI/workflow
satellites that ride on it.

Fixture tests pin each rule's exact firing semantics (and each
contract's suppression semantics); the soundness test cross-checks the
abstract dim classification against ``jax.eval_shape`` on the REAL
executor program factories; the census test cross-validates R017's
DataDependent verdicts against the program observatory's shape-key
census on a live (CPU-mesh) node — the dynamic ground truth for what
actually rides a program cache key.
"""
import json
import os
import shutil
import stat
import subprocess
import sys

import numpy as np
import pytest

from tools.tpulint import lint_sources
from tools.tpulint.analyzer import Violation
from tools.tpulint.project import analyze_sources, build_project
from tools.tpulint import shapeflow

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


# ---------------------------------------------------------------------------
# R017 — recompile storm
# ---------------------------------------------------------------------------

class TestR017RecompileStorm:
    AOT = "def wrap(fn, program, key):\n    return fn\n"
    FACTORY = """
from pkg import aot

_CACHE = {}

def _score_program(Q, D):
    key = (Q, D)
    fn = _CACHE.get(key)
    if fn is None:
        def body(x):
            return x
        fn = aot.wrap(body, "score", key)
        _CACHE[key] = fn
    return fn
"""

    def test_datadep_dim_into_factory_flagged_bucketed_clean(self):
        vs = lint_sources({
            "pkg/aot.py": self.AOT,
            "pkg/factory.py": self.FACTORY,
            "pkg/host.py": """
from pkg.factory import _score_program
from elasticsearch_tpu.utils.shapes import pow2_bucket

def bad(queries, docs):
    Q = len(queries)
    prog = _score_program(Q, 128)
    return prog(docs)

def good(queries, docs):
    Q = pow2_bucket(len(queries))
    prog = _score_program(Q, 128)
    return prog(docs)
""",
        })
        assert [(v.rule, v.path, v.line) for v in vs] == \
            [("R017", "pkg/host.py", 7)]
        assert "recompile" in vs[0].message

    def test_bucketed_contract_suppresses(self):
        vs = lint_sources({
            "pkg/aot.py": self.AOT,
            "pkg/factory.py": """
from pkg import aot

def _score_program(Q, D):
    def body(x):
        return x
    return aot.wrap(body, "score", (Q, D))
""",
            "pkg/host.py": """
from pkg.factory import _score_program

def declared(queries, docs):
    Q = len(queries)
    prog = _score_program(Q, 128)  # tpulint: bucketed
    return prog(docs)
""",
        })
        assert vs == []

    def test_jit_static_arg_and_interprocedural_flow(self):
        """The statics arm (a DataDependent value bound to a
        static_argnames param of a jit symbol) plus two-hop value flow:
        the ``len()`` is two calls away from the static binding."""
        vs = lint_sources({
            "s/mod.py": """
import jax
from functools import partial

@partial(jax.jit, static_argnames=("n",))
def padded(x, n):
    return x

def caller(x, data):
    n = len(data)
    return padded(x, n)
""",
            "s/indirect.py": """
from s.mod import padded

def layer1(x, data):
    m = len(data)
    return layer2(x, m)

def layer2(x, m):
    return padded(x, m)
""",
        })
        assert [(v.rule, v.path, v.line) for v in vs] == \
            [("R017", "s/indirect.py", 9), ("R017", "s/mod.py", 11)]


# ---------------------------------------------------------------------------
# R018 — padding soundness
# ---------------------------------------------------------------------------

class TestR018PaddingSoundness:
    def test_unmasked_reduction_in_collective_body(self):
        """Only the raw-operand sum fires: the jnp.where-validated, the
        mask-multiplied, and the unresolved-helper reductions are all
        clean (helpers give Unknown, never flagged)."""
        vs = lint_sources({"m/prog.py": """
import jax.numpy as jnp
from jax.experimental.shard_map import shard_map

def build(mesh):
    def body(scores, live):
        totals = jnp.sum(scores, axis=1)
        masked = jnp.where(live, scores, 0.0)
        ok = jnp.sum(masked, axis=1)
        ok2 = jnp.sum(scores * live, axis=1)
        unk = jnp.sum(helper(scores))
        return totals + ok + ok2 + unk
    return shard_map(body, mesh=mesh, in_specs=(), out_specs=())
"""})
        assert [(v.rule, v.line) for v in vs] == [("R018", 7)]
        assert "mask" in vs[0].message

    def test_masked_contract_suppresses(self):
        vs = lint_sources({"m/prog.py": """
import jax.numpy as jnp
from jax.experimental.shard_map import shard_map

def build(mesh):
    def body(scores, live):
        totals = jnp.sum(scores, axis=1)  # tpulint: masked
        return totals
    return shard_map(body, mesh=mesh, in_specs=(), out_specs=())
"""})
        assert vs == []


# ---------------------------------------------------------------------------
# R019 — dtype discipline
# ---------------------------------------------------------------------------

class TestR019DtypeDiscipline:
    def test_wide_dtypes_and_mxu_mixing_in_traced_code(self):
        """f64 spellings (astype and dtype= kw) and a bf16@f32 matmul
        fire inside jit; the same f64 spelling in plain host code is
        legal (numpy accumulators)."""
        vs = lint_sources({"t/mod.py": """
import jax
import jax.numpy as jnp
from functools import partial

@jax.jit
def bad_wide(x):
    return x.astype(jnp.float64)

@jax.jit
def bad_mixed(a, b):
    al = a.astype(jnp.bfloat16)
    bl = b.astype(jnp.float32)
    return al @ bl

@jax.jit
def bad_kw(x):
    return x + jnp.zeros((4,), dtype=jnp.float64)

@jax.jit
def good(x):
    return x.astype(jnp.float32)

def host_ok(x):
    return x.astype("float64")
"""})
        assert [(v.rule, v.line) for v in vs] == \
            [("R019", 8), ("R019", 14), ("R019", 18)]

    def test_cast_contract_suppresses(self):
        vs = lint_sources({"t/mod.py": """
import jax
import jax.numpy as jnp

@jax.jit
def declared(x):
    return x.astype(jnp.float64)  # tpulint: cast
"""})
        assert vs == []


# ---------------------------------------------------------------------------
# R020 — reservation leak
# ---------------------------------------------------------------------------

class TestR020ReservationLeak:
    RESIDENCY = """
class ResidencyRegistry:
    def track(self, n, label=""):
        return object()

    def _release(self, n):
        pass

RESIDENCY = ResidencyRegistry()
"""

    def test_token_form_risky_call_before_handoff(self):
        """A fallible call between track() and the store that hands the
        token off leaks the charge on exception; store-first and the
        try/except-release shapes are both clean."""
        vs = lint_sources({
            "r/residency.py": self.RESIDENCY,
            "r/user.py": """
from r.residency import RESIDENCY

def bad(data, store):
    tok = RESIDENCY.track(len(data), label="x")
    prepare(store)
    store["k"] = tok

def good_store_first(data, store):
    tok = RESIDENCY.track(len(data), label="x")
    store["k"] = tok
    prepare(store)

def good_protected(data, store):
    tok = RESIDENCY.track(len(data), label="x")
    try:
        prepare(store)
    except Exception:
        tok.close()
        raise
    store["k"] = tok
""",
        })
        assert [(v.rule, v.path, v.line) for v in vs] == \
            [("R020", "r/user.py", 5)]
        assert "exception" in vs[0].message

    def test_void_form_breaker_charge(self):
        """force() has no token: liability runs until an explicit
        release or a commit (attribute store / return)."""
        vs = lint_sources({
            "r/breakers.py": """
class CircuitBreaker:
    def force(self, n):
        pass

    def release(self, n):
        pass

BREAKER = CircuitBreaker()
""",
            "r/vuser.py": """
from r.breakers import BREAKER

class Holder:
    def bad(self, n, items):
        BREAKER.force(n)
        risky(items)
        self._committed = n

    def good_release(self, n, items):
        BREAKER.force(n)
        BREAKER.release(n)
        risky(items)

    def good_commit_first(self, n, items):
        BREAKER.force(n)
        self._committed = n
        risky(items)
""",
        })
        assert [(v.rule, v.line) for v in vs] == [("R020", 6)]


# ---------------------------------------------------------------------------
# the ShapeFlowReport view
# ---------------------------------------------------------------------------

class TestShapeFlowReport:
    def test_fixture_report(self):
        index, errors = analyze_sources({
            "pkg/aot.py": TestR017RecompileStorm.AOT,
            "pkg/factory.py": TestR017RecompileStorm.FACTORY,
            "pkg/host.py": """
from pkg.factory import _score_program
from elasticsearch_tpu.utils.shapes import pow2_bucket

def bad(queries, docs):
    Q = len(queries)
    prog = _score_program(Q, 128)
    return prog(docs)

def good(queries, docs):
    Q = pow2_bucket(len(queries))
    prog = _score_program(Q, 128)
    return prog(docs)
""",
        })
        assert errors == []
        rep = shapeflow.analyze(index)
        assert rep.factories == ["pkg.factory:_score_program"]
        # Q joins DataDependent (bad) with PaddedPow2 (good) → DataDep;
        # the literal 128 stays Concrete
        assert rep.factory_param_dims["pkg.factory:_score_program"] == \
            {"Q": "DataDependent", "D": "Concrete"}
        assert rep.dims_classified["DataDependent"] >= 1
        assert rep.dims_classified["PaddedPow2"] >= 1
        # memoized on the index (lint/bench/census share one evaluation)
        assert shapeflow.analyze(index) is rep

    def test_real_executor_factories_classified(self):
        """The adoption pass is visible in the abstract domain: the
        executor's five program factories exist as factories, and the
        bm25 cache-key dims are all PaddedPow2 — the Q-axis bucketing
        fix, as the analyzer sees it."""
        index, _errors = build_project(
            [os.path.join(REPO_ROOT, "elasticsearch_tpu")], root=REPO_ROOT)
        rep = shapeflow.analyze(index)
        pfx = "elasticsearch_tpu.parallel.executor:"
        for fac in ("_bm25_program", "_knn_program", "_maxsim_program",
                    "_dsl_program", "_psum_program"):
            assert pfx + fac in rep.factories, rep.factories
        bm25 = rep.factory_param_dims[pfx + "_bm25_program"]
        for p in ("Q", "T", "P", "D", "k"):
            assert bm25[p] == "PaddedPow2", (p, bm25)
        # nothing DataDependent reaches the bm25 key — the R017 claim
        assert "DataDependent" not in bm25.values()


# ---------------------------------------------------------------------------
# satellites: --prune-baseline, --changed rename fix, pre-commit hook
# ---------------------------------------------------------------------------

def _v(rule, path, line, snippet):
    return Violation(rule, path, line, 0, "msg", snippet)


class TestPruneBaseline:
    DOC = {"violations": [
        {"rule": "R001", "path": "a.py", "snippet": "x = foo()",
         "count": 2, "justification": "j"},
        {"rule": "R002", "path": "b.py", "snippet": "y = bar()",
         "count": 1, "justification": "j"},
    ]}

    def test_audit_reports_stale_without_touching_file(self, tmp_path):
        from tools.tpulint.baseline import prune_baseline

        bl = tmp_path / "baseline.json"
        bl.write_text(json.dumps(self.DOC))
        live = [_v("R001", "a.py", 3, "x = foo()")]
        stale = prune_baseline(live, str(bl), fix=False)
        # one of R001's two budgeted occurrences died, R002 entirely
        assert [(e["rule"], e["dead"]) for e in stale] == \
            [("R001", 1), ("R002", 1)]
        assert json.loads(bl.read_text()) == self.DOC

    def test_fix_rewrites_live_counts_only(self, tmp_path):
        from tools.tpulint.baseline import prune_baseline

        bl = tmp_path / "baseline.json"
        bl.write_text(json.dumps(self.DOC))
        live = [_v("R001", "a.py", 3, "x = foo()")]
        stale = prune_baseline(live, str(bl), fix=True)
        assert [e["rule"] for e in stale] == ["R001", "R002"]
        out = json.loads(bl.read_text())
        assert out["violations"] == [
            {"rule": "R001", "path": "a.py", "snippet": "x = foo()",
             "count": 1, "justification": "j"}]

    def test_fix_removes_file_when_nothing_survives(self, tmp_path):
        from tools.tpulint.baseline import prune_baseline

        bl = tmp_path / "baseline.json"
        bl.write_text(json.dumps(self.DOC))
        assert prune_baseline([], str(bl), fix=True)
        assert not bl.exists()

    def test_fully_live_baseline_is_clean(self, tmp_path):
        from tools.tpulint.baseline import prune_baseline

        bl = tmp_path / "baseline.json"
        bl.write_text(json.dumps(self.DOC))
        live = [_v("R001", "a.py", 3, "x = foo()"),
                _v("R001", "a.py", 9, "x = foo()"),
                _v("R002", "b.py", 4, "y = bar()")]
        assert prune_baseline(live, str(bl), fix=False) == []
        assert json.loads(bl.read_text()) == self.DOC


def _git(args, cwd):
    subprocess.run(
        ["git", "-c", "user.email=dev@example.com", "-c", "user.name=dev",
         *args], cwd=str(cwd), check=True, capture_output=True)


def test_changed_follows_renames(tmp_path, monkeypatch):
    """Regression for the --changed rename bug: --name-only reported a
    renamed file under its OLD (nonexistent) path, which was silently
    skipped — a rename that also edits the file dodged the gate. The
    status parser must surface the NEW path."""
    import tools.tpulint.__main__ as cli

    repo = tmp_path / "repo"
    repo.mkdir()
    _git(["init", "-q"], repo)
    (repo / "alpha.py").write_text("x = 1\n" * 40)
    (repo / "keep.py").write_text("z = 0\n")
    _git(["add", "-A"], repo)
    _git(["commit", "-qm", "c0"], repo)
    _git(["mv", "alpha.py", "beta.py"], repo)
    p = repo / "beta.py"
    p.write_text(p.read_text() + "y = 2\n")  # rename + edit
    _git(["add", "-A"], repo)
    monkeypatch.setattr(cli, "REPO_ROOT", str(repo))
    got = cli._changed_files("HEAD")
    assert got == ["beta.py"]


def test_precommit_hook_blocks_seeded_violation(tmp_path):
    """The shipped hook, run as git would run it, in a throwaway repo:
    exits 0 on a clean tree, exits 1 (blocking the commit) when an
    untracked module carries a violation, and leaves the SARIF record
    behind."""
    repo = tmp_path / "repo"
    shutil.copytree(os.path.join(REPO_ROOT, "tools"), str(repo / "tools"),
                    ignore=shutil.ignore_patterns("__pycache__"))
    (repo / "elasticsearch_tpu").mkdir()
    (repo / "elasticsearch_tpu" / "__init__.py").write_text("")
    (repo / "bench.py").write_text("")
    _git(["init", "-q"], repo)
    _git(["add", "-A"], repo)
    _git(["commit", "-qm", "c0"], repo)
    hook = repo / "tools" / "tpulint" / "hooks" / "pre-commit"
    hook.chmod(hook.stat().st_mode | stat.S_IXUSR)
    env = dict(os.environ)
    env["PATH"] = os.path.dirname(sys.executable) + os.pathsep + \
        env.get("PATH", "")
    env.pop("PYTHONPATH", None)

    r = subprocess.run([str(hook)], cwd=str(repo), env=env,
                       capture_output=True, text=True)
    assert r.returncode == 0, r.stderr

    (repo / "elasticsearch_tpu" / "seeded.py").write_text(
        "import jax\n"
        "import jax.numpy as jnp\n"
        "\n"
        "@jax.jit\n"
        "def seeded(x):\n"
        "    return x.astype(jnp.float64)\n")
    r = subprocess.run([str(hook)], cwd=str(repo), env=env,
                       capture_output=True, text=True)
    assert r.returncode == 1, (r.stdout, r.stderr)
    assert "blocking commit" in r.stderr
    sarif = json.loads((repo / ".git" / "tpulint-precommit.sarif")
                       .read_text())
    rules = [res["ruleId"] for res in sarif["runs"][0]["results"]]
    assert "R019" in rules


# ---------------------------------------------------------------------------
# soundness: abstract dims vs jax.eval_shape on the real factories
# ---------------------------------------------------------------------------

def test_shapeflow_sound_vs_eval_shape(monkeypatch, eight_devices):
    """The lattice's operational claim, checked against JAX's own
    abstract evaluator: for pow2-bucketed cache-key dims, every factory
    program traces STATICALLY (eval_shape succeeds — no data-dependent
    shapes inside), and the output dims are functions of the key dims
    alone — so equal keys really do mean one compiled program, which is
    exactly what R017 protects. aot.wrap is stubbed to identity (its
    blob cache is orthogonal to shape semantics)."""
    import jax
    import jax.numpy as jnp

    from elasticsearch_tpu.parallel import aot
    from elasticsearch_tpu.parallel import executor as exmod
    from elasticsearch_tpu.parallel import shard_mesh

    monkeypatch.setattr(aot, "wrap", lambda fn, name, key: fn)
    mesh = shard_mesh(1)  # single slot: wrap == plain jit (no collectives)
    f32, i32, b8 = jnp.float32, jnp.int32, jnp.bool_
    S = jax.ShapeDtypeStruct

    for Q, T, D, k in [(4, 8, 64, 8), (8, 4, 128, 16)]:
        nnz, P, dims = 4 * D, 8, 8
        prog = exmod._bm25_program(mesh, {}, Q=Q, T=T, P=P, D=D, k=k)
        out = jax.eval_shape(prog, S((nnz,), i32), S((nnz,), f32),
                             S((Q, T), i32), S((Q, T), i32),
                             S((Q, T), f32), S((D,), b8))
        assert [o.shape for o in out] == [(Q, k)] * 3 + [(Q,)]

        prog = exmod._knn_program(mesh, {}, Q=Q, dims=dims, D=D, k=k,
                                  metric="dot")
        out = jax.eval_shape(prog, S((Q, dims), f32), S((D, dims), f32),
                             S((D,), b8))
        assert [o.shape for o in out] == [(Q, k)] * 3

        prog = exmod._maxsim_program(mesh, {}, Q=Q, T=T, dims=dims, D=D,
                                     k=k, metric="dot")
        out = jax.eval_shape(prog, S((Q, T, dims), f32), S((D, dims), f32),
                             S((D,), b8))
        assert [o.shape for o in out] == [(Q, k)] * 3

    prog = exmod._psum_program(mesh, {}, (4, 5))
    out = jax.eval_shape(prog, S((4, 5), f32))
    assert out.shape == (4, 5)


# ---------------------------------------------------------------------------
# census cross-validation: R017 verdicts vs the observatory ground truth
# ---------------------------------------------------------------------------

def test_census_cross_validates_r017(eight_devices):
    """Dynamic ground truth for the static verdicts: run real searches
    with different query counts on a live 8-slot mesh, read the program
    observatory's shape-key census, and check that every cache-key dim
    the census actually saw VARY is classified non-Concrete by
    shapeflow — a dim the analyzer called Concrete but the census saw
    take two values would be a missed recompile storm."""
    from elasticsearch_tpu.analysis.registry import AnalysisRegistry
    from elasticsearch_tpu.index.doc_parser import DocumentParser
    from elasticsearch_tpu.index.mappings import Mappings
    from elasticsearch_tpu.index.segment import SegmentBuilder
    from elasticsearch_tpu.monitor.programs import REGISTRY, index_scope
    from elasticsearch_tpu.parallel import MeshSearchExecutor, shard_mesh

    mappings = Mappings({"properties": {"body": {"type": "text"}}})
    reg = AnalysisRegistry()
    rng = np.random.default_rng(11)
    vocab = [f"w{i}" for i in range(30)]
    docs = [" ".join(rng.choice(vocab, size=10)) for _ in range(64)]
    shards = []
    for i in range(8):
        parser = DocumentParser(mappings, reg)
        builder = SegmentBuilder(mappings)
        for j, text in enumerate(docs[i::8]):
            builder.add(parser.parse(str(j), {"body": text}))
        shards.append(builder.freeze())
    ex = MeshSearchExecutor(shard_mesh(8), shards)

    REGISTRY.reset()
    with index_scope("census_xval"):
        # 3 queries → Q bucket 4; 5 queries → Q bucket 8: the Q key
        # family takes two values in the census
        ex.search_terms("body", [[("w1", 1.0)]] * 3, k=10)
        ex.search_terms("body", [[("w2", 1.0)]] * 5, k=10)
    census = REGISTRY.census("census_xval")
    bm25 = [e for e in census if e["program"] == "mesh_bm25"]
    assert bm25, census

    seen = {}
    for e in bm25:
        for part in e["shapes"].split("|"):
            name, val = part.split("=")
            seen.setdefault(name, set()).add(val)
    assert len(seen.get("Q", ())) >= 2, seen  # census really saw Q vary

    index, _errors = build_project(
        [os.path.join(REPO_ROOT, "elasticsearch_tpu")], root=REPO_ROOT)
    rep = shapeflow.analyze(index)
    dims = rep.factory_param_dims[
        "elasticsearch_tpu.parallel.executor:_bm25_program"]
    for name, vals in seen.items():
        if len(vals) < 2 or name not in dims:
            continue
        assert dims[name] != "Concrete", (name, vals, dims)


# ---------------------------------------------------------------------------
# hybrid fusion fixture: fusion weights are traced operands, not statics
# ---------------------------------------------------------------------------

class TestR017HybridFusionWeights:
    """The hybrid stage-1 contract: per-request fusion parameters
    (weights, rank_constant, candidate cutoff) ride the program as traced
    operands. Letting the request's weight-vector arity reach the program
    cache key turns every weight-shape variation into a fresh trace —
    exactly R017's recompile storm."""

    def test_weight_arity_into_fuse_program_key_flagged(self):
        vs = lint_sources({
            "h/aot.py": TestR017RecompileStorm.AOT,
            "h/fuse.py": """
from h import aot

_JITTED = {}

def _fuse_program(W, D):
    key = (W, D)
    fn = _JITTED.get(key)
    if fn is None:
        def body(scores, weights):
            return scores
        fn = aot.wrap(body, "hybrid_fuse", key)
        _JITTED[key] = fn
    return fn
""",
            "h/exec.py": """
from h.fuse import _fuse_program

def hybrid_topk(scores, weights):
    W = len(weights)
    prog = _fuse_program(W, 4096)
    return prog(scores, weights)
""",
        })
        assert [(v.rule, v.path, v.line) for v in vs] == \
            [("R017", "h/exec.py", 6)]

    def test_fixed_arity_traced_weights_clean(self):
        # the shipped discipline: engine count is a config constant, the
        # weight VALUES are operands — nothing data-dependent reaches
        # the key
        vs = lint_sources({
            "h/aot.py": TestR017RecompileStorm.AOT,
            "h/fuse.py": """
from h import aot

N_ENGINES = 2
_JITTED = {}

def _fuse_program(D):
    key = (N_ENGINES, D)
    fn = _JITTED.get(key)
    if fn is None:
        def body(scores, weights):
            return scores
        fn = aot.wrap(body, "hybrid_fuse", key)
        _JITTED[key] = fn
    return fn
""",
            "h/exec.py": """
from h.fuse import _fuse_program

def hybrid_topk(scores, weights):
    prog = _fuse_program(4096)
    return prog(scores, weights)
""",
        })
        assert vs == []
