"""bf16 dense-impact storage (ESTPU_IMPACT_BF16) — SURVEY §6 "quantized
impacts" lever. The block halves its HBM and multiplies natively on the
MXU; scores must stay within bf16 tolerance of the f32 path and preserve
ranking on non-tied corpora."""
import os

import numpy as np
import pytest

from elasticsearch_tpu.node import Node

DOCS = [
    " ".join(f"w{(i * 7 + j * 3) % 23}" for j in range(12))
    for i in range(48)
]


def _scores(node, q):
    r = node.search("bf", {"query": {"match": {"body": q}}, "size": 48})
    return {h["_id"]: h["_score"] for h in r["hits"]["hits"]}, \
        [h["_id"] for h in r["hits"]["hits"]]


def _build(monkeypatch, bf16: bool):
    if bf16:
        monkeypatch.setenv("ESTPU_IMPACT_BF16", "1")
    else:
        monkeypatch.delenv("ESTPU_IMPACT_BF16", raising=False)
    # compare the HOST path that consumes the device block (the mesh prims
    # restack from the f32 host mirror and are unaffected by the flag)
    monkeypatch.setenv("ESTPU_DISABLE_MESH", "1")
    # the dense block qualifies terms by df >= max(128, D/256); drop the
    # bar so the tiny corpus builds one
    import functools

    from elasticsearch_tpu.index import segment as segmod

    if not hasattr(segmod, "_orig_build_dense_impact"):
        segmod._orig_build_dense_impact = segmod.build_dense_impact
    monkeypatch.setattr(
        segmod, "build_dense_impact",
        functools.partial(segmod._orig_build_dense_impact, df_threshold=2))
    node = Node()
    node.create_index("bf", {"mappings": {"properties": {
        "body": {"type": "text"}}}})
    svc = node.indices["bf"]
    for i, t in enumerate(DOCS):
        svc.index_doc(str(i), {"body": t})
    svc.refresh()
    return node


def test_bf16_impact_scores_within_tolerance(monkeypatch):
    node32 = _build(monkeypatch, bf16=False)
    s32, order32 = _scores(node32, "w1 w7 w14")
    seg = node32.indices["bf"].shards[0].segments[0]
    blk32 = seg.inverted["body"].dense_block()
    node16 = _build(monkeypatch, bf16=True)
    s16, order16 = _scores(node16, "w1 w7 w14")
    seg16 = node16.indices["bf"].shards[0].segments[0]
    blk16 = seg16.inverted["body"].dense_block()
    if blk32 is None or blk16 is None:
        pytest.skip("corpus built no dense block at this threshold")
    import jax.numpy as jnp

    assert blk16[1].dtype == jnp.bfloat16
    assert blk32[1].dtype == jnp.float32
    assert blk16[1].nbytes * 2 == blk32[1].nbytes  # budget halves
    assert set(s16) == set(s32)
    for d in s32:
        assert s16[d] == pytest.approx(s32[d], rel=2e-2, abs=1e-3), d
    node32.close()
    node16.close()


def test_bf16_impact_flag_is_off_by_default(monkeypatch):
    monkeypatch.delenv("ESTPU_IMPACT_BF16", raising=False)
    node = _build(monkeypatch, bf16=False)
    seg = node.indices["bf"].shards[0].segments[0]
    blk = seg.inverted["body"].dense_block()
    if blk is not None:
        import jax.numpy as jnp

        assert blk[1].dtype == jnp.float32
    assert os.environ.get("ESTPU_IMPACT_BF16") is None
    node.close()
