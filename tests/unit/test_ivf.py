"""IVF-flat ANN tests — recall vs exact numpy oracle, engine integration.

SURVEY §2.4 knn row / round-1 verdict item 6. FAISS-style contract: on
clustered data, probing enough lists to cover num_candidates vectors gives
recall@10 ≥ 0.95 while scoring only a fraction of the corpus.
"""
import numpy as np
import pytest

from elasticsearch_tpu.ops.ivf import (IvfIndex, build_ivf,
                                        ivf_candidate_scores, kmeans)


def _clustered(n, dims, n_clusters, seed=0):
    rng = np.random.RandomState(seed)
    centers = rng.randn(n_clusters, dims).astype(np.float32) * 5
    assign = rng.randint(0, n_clusters, n)
    x = centers[assign] + rng.randn(n, dims).astype(np.float32)
    return x.astype(np.float32)


def test_kmeans_converges():
    x = _clustered(2000, 16, 10)
    cents, assign = kmeans(x, 10, iters=10)
    assert cents.shape == (10, 16)
    assert assign.shape == (2000,)
    # every cluster non-trivially populated on clustered data
    counts = np.bincount(assign, minlength=10)
    assert (counts > 0).sum() >= 8


def test_ivf_recall_vs_exact():
    n, dims = 20_000, 32
    x = _clustered(n, dims, 64, seed=1)
    D = 1 << int(np.ceil(np.log2(n)))
    vecs = np.zeros((D, dims), np.float32)
    vecs[:n] = x
    exists = np.zeros(D, bool)
    exists[:n] = True
    idx = build_ivf(vecs, exists, D)
    assert idx is not None

    import jax

    d_vecs = jax.device_put(vecs)
    rng = np.random.RandomState(2)
    # exact oracle: cosine
    xn = x / np.maximum(np.linalg.norm(x, axis=1, keepdims=True), 1e-12)
    hits = 0
    trials = 20
    for t in range(trials):
        q = x[rng.randint(n)] + rng.randn(dims).astype(np.float32) * 0.1
        qn = q / max(np.linalg.norm(q), 1e-12)
        exact = np.argsort(-(xn @ qn), kind="stable")[:10]
        scores, mask = ivf_candidate_scores(idx, d_vecs, q, 2000, "cosine", D)
        s = np.array(scores)
        s[~np.asarray(mask)] = -np.inf
        approx = np.argsort(-s, kind="stable")[:10]
        hits += len(set(exact.tolist()) & set(approx.tolist()))
    recall = hits / (10 * trials)
    assert recall >= 0.95, recall
    # and it probed far fewer than n vectors
    nprobe = idx.nprobe_for(2000)
    assert nprobe * idx.Lmax < n


def test_nprobe_for_clamps_degenerate_num_candidates():
    """ISSUE-9 satellite: nprobe must stay in [1, C] for num_candidates
    <= 0 and > ntotal (the raw ceil/avg_len math returns 0 or > C)."""
    n, dims = 4000, 16
    x = _clustered(n, dims, 32, seed=4)
    D = 4096
    vecs = np.zeros((D, dims), np.float32)
    vecs[:n] = x
    exists = np.zeros(D, bool)
    exists[:n] = True
    idx = build_ivf(vecs, exists, D)
    assert idx is not None
    assert idx.nprobe_for(0) == 1
    assert idx.nprobe_for(-100) == 1
    assert idx.nprobe_for(1) == 1
    assert 1 <= idx.nprobe_for(idx.ntotal) <= idx.C
    assert idx.nprobe_for(idx.ntotal + 1) <= idx.C
    assert idx.nprobe_for(10 ** 9) == idx.C
    # monotone in num_candidates
    probes = [idx.nprobe_for(nc) for nc in (1, 100, 1000, n, 10 ** 9)]
    assert probes == sorted(probes)
    # degenerate avg_len < 1 (more lists than vectors is impossible by
    # construction, but a restored index could carry avg_len < 1): the
    # clamp still holds
    tiny = IvfIndex(centroids=None, lists=None, list_lens=None, C=8,
                    Lmax=1, sentinel=8, avg_len=0.5)
    assert tiny.nprobe_for(0) == 1
    assert 1 <= tiny.nprobe_for(10 ** 9) <= tiny.C


def test_ivf_declines_tiny_corpus():
    vecs = np.random.RandomState(0).randn(32, 8).astype(np.float32)
    exists = np.ones(32, bool)
    assert build_ivf(vecs, exists, 32) is None


def test_knn_ann_through_engine():
    from elasticsearch_tpu.node import Node

    n = Node()
    n.create_index("v", {"mappings": {"properties": {
        "emb": {"type": "dense_vector", "dims": 8,
                "index_options": {"type": "ivf"}},
        "tag": {"type": "keyword"}}}})
    svc = n.indices["v"]
    rng = np.random.RandomState(3)
    centers = rng.randn(4, 8).astype(np.float32) * 4
    for i in range(400):
        c = i % 4
        v = centers[c] + rng.randn(8).astype(np.float32) * 0.2
        svc.index_doc(str(i), {"emb": [float(x) for x in v],
                               "tag": f"c{c}"})
    svc.refresh()
    # query an exact stored vector: its own doc must come back first (the
    # self-match is cleanly separated from every neighbour)
    target = svc.shards[0].engine.get("101")["_source"]["emb"]
    r = n.search("v", {"query": {"knn": {"field": "emb", "query_vector": target,
                                         "k": 5, "num_candidates": 200}},
                       "size": 5})
    ids = [int(h["_id"]) for h in r["hits"]["hits"]]
    assert ids[0] == 101, ids
    assert all(i % 4 == 101 % 4 for i in ids), ids
    q = [float(x) for x in centers[1]]
    # filter composes with the ANN path
    r3 = n.search("v", {"query": {"knn": {"field": "emb", "query_vector": q,
                                          "k": 5, "num_candidates": 200,
                                          "filter": {"term": {"tag": "c2"}}}},
                        "size": 5})
    assert all(int(h["_id"]) % 4 == 2 for h in r3["hits"]["hits"])
    n.close()


def test_ivf_built_eagerly_at_freeze():
    """r3 verdict weak #9: IVF must be built at freeze (index time), not
    lazily on the first query after restart/merge."""
    from elasticsearch_tpu.node import Node

    n = Node()
    n.create_index("eager", {"mappings": {"properties": {
        "emb": {"type": "dense_vector", "dims": 8,
                "index_options": {"type": "ivf"}}}}})
    svc = n.indices["eager"]
    rng = np.random.default_rng(5)
    for i in range(128):
        svc.index_doc(str(i), {"emb": [float(x) for x in rng.random(8)]})
    svc.refresh()
    seg = svc.shards[0].segments[0]
    assert seg.vectors["emb"]._ivf not in (None, False)  # no query ran yet
    # merges rebuild eagerly too (merge -> freeze path)
    for i in range(128, 160):
        svc.index_doc(str(i), {"emb": [float(x) for x in rng.random(8)]})
    svc.refresh()
    svc.force_merge(1)
    seg2 = svc.shards[0].segments[0]
    assert seg2.vectors["emb"]._ivf not in (None, False)
    n.close()


def test_ivf_codec_roundtrip():
    """write_ivf/read_ivf: the durable ANN form restores an equivalent
    index (same probes, same candidates) without re-running k-means."""
    from elasticsearch_tpu.index.store import read_ivf, write_ivf
    from elasticsearch_tpu.ops.ivf import build_ivf, ivf_candidate_scores

    rng = np.random.default_rng(9)
    vecs = rng.standard_normal((512, 16)).astype(np.float32)
    exists = np.ones(512, bool)
    ivf = build_ivf(vecs, exists, 512, metric="cosine")
    blob = write_ivf(ivf)
    back = read_ivf(blob)
    assert back.C == ivf.C and back.Lmax == ivf.Lmax
    assert back.metric == ivf.metric and back.sentinel == ivf.sentinel
    np.testing.assert_array_equal(np.asarray(back.lists),
                                  np.asarray(ivf.lists))
    np.testing.assert_allclose(np.asarray(back.centroids),
                               np.asarray(ivf.centroids), rtol=1e-6)
    import jax

    q = rng.standard_normal(16).astype(np.float32)
    dv = jax.device_put(vecs)
    s1, m1 = ivf_candidate_scores(ivf, dv, q, 64, "cosine", 512)
    s2, m2 = ivf_candidate_scores(back, dv, q, 64, "cosine", 512)
    np.testing.assert_array_equal(np.asarray(m1), np.asarray(m2))
    np.testing.assert_allclose(np.asarray(s1)[np.asarray(m1)],
                               np.asarray(s2)[np.asarray(m2)], rtol=1e-6)


def test_ivf_codec_detects_corruption():
    from elasticsearch_tpu.index.store import (CorruptStoreException,
                                               read_ivf, write_ivf)
    from elasticsearch_tpu.ops.ivf import build_ivf

    rng = np.random.default_rng(11)
    vecs = rng.standard_normal((128, 8)).astype(np.float32)
    ivf = build_ivf(vecs, np.ones(128, bool), 128)
    blob = bytearray(write_ivf(ivf))
    blob[-3] ^= 0xFF  # flip a payload byte
    with pytest.raises(CorruptStoreException):
        read_ivf(bytes(blob))


# ---------------------------------------------------------------------------
# persisted-quantizer cache (index/ivf_cache.py): restart + restore warm ANN
# ---------------------------------------------------------------------------

def _index_ivf_corpus(node, name, n=160, dims=8, seed=7):
    node.create_index(name, {"mappings": {"properties": {
        "emb": {"type": "dense_vector", "dims": dims,
                "index_options": {"type": "ivf"}}}}})
    svc = node.indices[name]
    rng = np.random.default_rng(seed)
    for i in range(n):
        svc.index_doc(str(i), {"emb": [float(x) for x in rng.random(dims)]})
    svc.refresh()
    return svc


def test_ivf_cache_restart_reloads_quantizer(tmp_path):
    """A restarted node must reload the persisted IVF blob at replay-freeze
    (counter ivf_cache_hit), not re-run k-means (counter ivf_build)."""
    from elasticsearch_tpu.monitor import kernels
    from elasticsearch_tpu.node import Node

    n = Node(data_path=str(tmp_path))
    _index_ivf_corpus(n, "warm")
    before = kernels.snapshot()
    assert before.get("ivf_build", 0) >= 1
    seg = n.indices["warm"].shards[0].segments[0]
    ivf_a = seg.vectors["emb"]._ivf
    assert ivf_a not in (None, False)
    n.close()

    # simulate a new process: in-memory cache gone, disk tier remains
    from elasticsearch_tpu.index import ivf_cache
    ivf_cache.reset()

    n2 = Node(data_path=str(tmp_path))
    svc2 = n2.indices["warm"]
    svc2.refresh()
    after = kernels.snapshot()
    assert after.get("ivf_cache_hit", 0) > before.get("ivf_cache_hit", 0)
    assert after.get("ivf_build", 0) == before.get("ivf_build", 0)
    seg2 = svc2.shards[0].segments[0]
    ivf_b = seg2.vectors["emb"]._ivf
    assert ivf_b not in (None, False)
    np.testing.assert_allclose(np.asarray(ivf_a.centroids),
                               np.asarray(ivf_b.centroids), rtol=1e-6)
    # ANN search works on the reloaded quantizer
    target = svc2.shards[0].engine.get("42")["_source"]["emb"]
    r = n2.search("warm", {"query": {"knn": {
        "field": "emb", "query_vector": target, "k": 3,
        "num_candidates": 120}}, "size": 3})
    assert r["hits"]["hits"][0]["_id"] == "42"
    n2.close()


def test_ivf_cache_snapshot_restore_seeds_target(tmp_path):
    """Snapshot payloads carry IVF blobs; restore seeds the target cache so
    the restored index freezes without a k-means build."""
    from elasticsearch_tpu.index import ivf_cache
    from elasticsearch_tpu.index.snapshots import (FsRepository,
                                                   create_snapshot,
                                                   restore_snapshot)
    from elasticsearch_tpu.monitor import kernels
    from elasticsearch_tpu.node import Node

    src = Node()
    _index_ivf_corpus(src, "snapme")
    repo = FsRepository("r", str(tmp_path / "repo"))
    create_snapshot(src, repo, "s1")
    src.close()

    ivf_cache.reset()  # fresh process on the restore side
    dst = Node()
    before = kernels.snapshot()
    restore_snapshot(dst, repo, "s1", rename_pattern="snapme",
                     rename_replacement="restored")
    after = kernels.snapshot()
    assert after.get("ivf_cache_hit", 0) > before.get("ivf_cache_hit", 0)
    assert after.get("ivf_build", 0) == before.get("ivf_build", 0)
    seg = dst.indices["restored"].shards[0].segments[0]
    assert seg.vectors["emb"]._ivf not in (None, False)
    dst.close()


def test_ivf_cache_corrupt_disk_blob_is_a_miss(tmp_path):
    """A corrupt persisted blob must be discarded and rebuilt, never raised."""
    import os

    from elasticsearch_tpu.index import ivf_cache
    from elasticsearch_tpu.monitor import kernels
    from elasticsearch_tpu.node import Node

    n = Node(data_path=str(tmp_path))
    _index_ivf_corpus(n, "corrupt")
    n.close()

    ivf_cache.reset()
    ivf_dir = tmp_path / "_ivf"
    blobs = list(ivf_dir.glob("*.ivf"))
    assert blobs, "freeze must have persisted a blob"
    for p in blobs:
        raw = bytearray(p.read_bytes())
        raw[-3] ^= 0xFF
        p.write_bytes(bytes(raw))

    before = kernels.snapshot()
    n2 = Node(data_path=str(tmp_path))
    n2.indices["corrupt"].refresh()
    after = kernels.snapshot()
    assert after.get("ivf_build", 0) > before.get("ivf_build", 0)
    seg = n2.indices["corrupt"].shards[0].segments[0]
    assert seg.vectors["emb"]._ivf not in (None, False)
    # the rebuild re-persisted a good blob over the corrupt one
    assert all(not os.path.exists(str(p) + ".tmp") for p in blobs)
    n2.close()


def test_ivf_scatter_free_matches_scatter():
    """make_ivf_search(scatter_free=True) == the scatter form exactly
    (candidate ids are unique: one list per vector)."""
    import jax.numpy as jnp

    from elasticsearch_tpu.ops.ivf import build_ivf, make_ivf_search

    rng = np.random.default_rng(5)
    D, n, dims, C = 1024, 700, 16, 32
    vecs_np = rng.standard_normal((D, dims)).astype(np.float32)
    exists = np.zeros(D, bool)
    exists[:n] = True
    idx = build_ivf(vecs_np, exists, D, C=C)
    vecs = jnp.asarray(vecs_np)
    q = jnp.asarray(rng.standard_normal(dims).astype(np.float32))
    for nprobe in (2, 8):
        a = make_ivf_search(idx.C, idx.Lmax, D, nprobe, "cosine",
                            quantizer_metric=idx.metric,
                            scatter_free=False)(
            q, idx.centroids, idx.lists, vecs)
        b = make_ivf_search(idx.C, idx.Lmax, D, nprobe, "cosine",
                            quantizer_metric=idx.metric,
                            scatter_free=True)(
            q, idx.centroids, idx.lists, vecs)
        np.testing.assert_array_equal(np.asarray(a[1]), np.asarray(b[1]))
        sa, sb = np.asarray(a[0]), np.asarray(b[0])
        m = np.asarray(a[1])
        np.testing.assert_allclose(sa[m], sb[m], rtol=1e-6)
        assert np.all(np.isneginf(sb[~m]))
