"""Snapshot/restore, tiered merge policy, and peer recovery tests.

Reference: snapshots/SnapshotsService, index/merge/policy/
TieredMergePolicyProvider, indices/recovery/RecoverySourceHandler.
"""
import os

import pytest

from elasticsearch_tpu.index.index_service import IndexService
from elasticsearch_tpu.index.merge import TieredMergePolicy
from elasticsearch_tpu.index.recovery import recover_peer
from elasticsearch_tpu.index.snapshots import (
    FsRepository,
    SnapshotException,
    SnapshotMissingException,
    create_snapshot,
    restore_snapshot,
    snapshot_info,
)
from elasticsearch_tpu.node import Node


@pytest.fixture()
def node():
    n = Node()
    n.create_index("books", {"mappings": {"properties": {
        "title": {"type": "text"}, "price": {"type": "long"}}}})
    svc = n.indices["books"]
    for i in range(10):
        svc.index_doc(str(i), {"title": f"book number {i}", "price": i * 10})
    svc.delete_doc("9")
    svc.refresh()
    yield n
    for s in n.indices.values():
        s.close()


def test_snapshot_restore_roundtrip(node, tmp_path):
    repo = FsRepository("r1", str(tmp_path))
    create_snapshot(node, repo, "snap1", ["books"])
    assert "snap1" in repo.catalog()
    info = snapshot_info(repo, "snap1")
    assert info["state"] == "SUCCESS" and info["indices"] == ["books"]

    restored = restore_snapshot(node, repo, "snap1", indices=["books"],
                                rename_pattern="books", rename_replacement="books2")
    assert restored["snapshot"]["indices"] == ["books2"]
    svc2 = node.indices["books2"]
    assert svc2.num_docs == 9  # tombstoned doc 9 not restored
    r = svc2.search({"query": {"match": {"title": "number"}}, "size": 20})
    assert r["hits"]["total"] == 9
    # versions preserved
    got = svc2.get_doc("0")
    assert got["_version"] == node.indices["books"].get_doc("0")["_version"]


def test_snapshot_incremental_blobs(node, tmp_path):
    repo = FsRepository("r1", str(tmp_path))
    create_snapshot(node, repo, "s1", ["books"])
    blobs_before = set(os.listdir(os.path.join(str(tmp_path), "blobs")))
    # no changes: second snapshot adds no blobs
    create_snapshot(node, repo, "s2", ["books"])
    blobs_after = set(os.listdir(os.path.join(str(tmp_path), "blobs")))
    assert blobs_before == blobs_after
    # duplicate name rejected
    with pytest.raises(SnapshotException):
        create_snapshot(node, repo, "s1", ["books"])


def test_snapshot_delete_gcs_blobs(node, tmp_path):
    repo = FsRepository("r1", str(tmp_path))
    create_snapshot(node, repo, "s1", ["books"])
    repo.delete_snapshot("s1")
    assert repo.catalog() == []
    assert os.listdir(os.path.join(str(tmp_path), "blobs")) == []
    with pytest.raises(SnapshotMissingException):
        repo.get_manifest("s1")


def test_restore_refuses_open_index(node, tmp_path):
    repo = FsRepository("r1", str(tmp_path))
    create_snapshot(node, repo, "s1", ["books"])
    with pytest.raises(SnapshotException):
        restore_snapshot(node, repo, "s1", indices=["books"])


def test_snapshot_empty_pattern_errors_not_widens(node, tmp_path):
    repo = FsRepository("r1", str(tmp_path))
    with pytest.raises(SnapshotException):
        create_snapshot(node, repo, "s1", indices=[])  # resolved-empty pattern
    assert repo.catalog() == []


def test_restore_matches_patterns_against_manifest(node, tmp_path):
    repo = FsRepository("r1", str(tmp_path))
    create_snapshot(node, repo, "s1", ["books"])
    out = restore_snapshot(node, repo, "s1", indices=["boo*"],
                           rename_pattern="^", rename_replacement="re_")
    assert out["snapshot"]["indices"] == ["re_books"]


def test_rescore_with_sort_rejected():
    from elasticsearch_tpu.utils.errors import SearchParseException

    svc = IndexService("rs")
    svc.index_doc("1", {"v": 1})
    svc.refresh()
    with pytest.raises(SearchParseException):
        svc.search({"query": {"match_all": {}}, "sort": [{"v": "desc"}],
                    "rescore": {"query": {"rescore_query": {"match_all": {}}}}})
    svc.close()


def test_percolator_update_revalidates_and_reregisters():
    from elasticsearch_tpu.utils.errors import ElasticsearchTpuException

    svc = IndexService("pu")
    svc.index_doc("q1", {"query": {"match": {"m": "aaa"}}}, doc_type=".percolator")
    assert svc.percolate({"doc": {"m": "aaa"}})["total"] == 1
    # live update swaps the active query
    svc.update_doc("q1", {"doc": {"query": {"match": {"m": "bbb"}}}})
    assert svc.percolate({"doc": {"m": "aaa"}})["total"] == 0
    assert svc.percolate({"doc": {"m": "bbb"}})["total"] == 1
    # invalid update rejected before persisting
    with pytest.raises(ElasticsearchTpuException):
        svc.update_doc("q1", {"doc": {"query": {"frobnicate": {}}}})
    assert svc.percolate({"doc": {"m": "bbb"}})["total"] == 1
    svc.close()


def test_tiered_merge_policy_tier_overflow():
    class FakeSeg:
        _n = 0

        def __init__(self, live, deleted=0):
            FakeSeg._n += 1
            self.seg_id = FakeSeg._n
            self.live_docs = live
            self.num_docs = live + deleted
            self.deleted_count = deleted

    pol = TieredMergePolicy(segments_per_tier=4, max_merge_at_once=4)
    # 3 same-tier segments: no merge
    assert pol.find_merge([FakeSeg(10), FakeSeg(12), FakeSeg(11)]) is None
    # 4 same-tier segments: merge all 4, smallest first
    segs = [FakeSeg(10), FakeSeg(12), FakeSeg(11), FakeSeg(13)]
    found = pol.find_merge(segs)
    assert found is not None and len(found) == 4
    # deletes pressure: one heavily-deleted segment merges
    hot = FakeSeg(10, deleted=8)
    found = pol.find_merge([hot, FakeSeg(1000)])
    assert found is not None and hot in found


def test_engine_partial_merge_keeps_other_segments():
    svc = IndexService("m")
    eng = svc.shards[0].engine
    eng.merge_policy = TieredMergePolicy(segments_per_tier=3, max_merge_at_once=3)
    # 2 small segments + 1 big one; small tier does not overflow yet
    for i in range(2):
        svc.index_doc(f"a{i}", {"v": i})
        eng.refresh()
    for i in range(300):
        svc.index_doc(f"big{i}", {"v": i})
    eng.refresh()
    n_before = len(eng.segments)
    svc.index_doc("a2", {"v": 2})
    eng.refresh()  # 3 small segments now -> tier overflow -> partial merge
    small = [s for s in eng.segments if s.live_docs < 10]
    big = [s for s in eng.segments if s.live_docs >= 300]
    assert len(small) == 1 and len(big) == 1  # smalls merged, big untouched
    assert svc.num_docs == 303
    r = svc.search({"query": {"match_all": {}}, "size": 0})
    assert r["hits"]["total"] == 303
    svc.close()


def test_peer_recovery_copies_docs():
    src = IndexService("src")
    for i in range(5):
        src.index_doc(str(i), {"v": i}, doc_type="t")
    src.delete_doc("4")
    src.refresh()
    dst = IndexService("dst")
    stats = recover_peer(src.shards[0].engine, dst.shards[0].engine)
    # the source's translog still holds every op: recovery replays the op
    # suffix (5 indexes + 1 delete) instead of shipping live docs
    assert stats["mode"] == "ops"
    assert stats["ops_replayed"] == 6
    assert dst.num_docs == 4
    # checkpoints equal now: re-recovery replays NOTHING (incremental)
    stats2 = recover_peer(src.shards[0].engine, dst.shards[0].engine)
    assert stats2["mode"] == "ops" and stats2["ops_replayed"] == 0
    assert dst.num_docs == 4
    # flush drops the retained ops: the next out-of-date target falls
    # back to the full doc copy (which ships tombstones)
    src.shards[0].engine.flush()
    dst2 = IndexService("dst2")
    stats3 = recover_peer(src.shards[0].engine, dst2.shards[0].engine)
    assert stats3["mode"] == "full" and stats3["copied"] == 4
    assert dst2.num_docs == 4
    src.close()
    dst.close()
    dst2.close()


def test_url_repository_readonly_no_mkdir(node, tmp_path, monkeypatch):
    """url repositories must never mkdir their location (a non-file URL is
    not a path: a literal ``http:`` dir would appear in cwd), verify must
    succeed without a write probe, and snapshot writes must 400.
    Reference: repositories/uri/URLRepository.java (read-only)."""
    from elasticsearch_tpu.rest.server import (_put_repo, _put_snapshot,
                                               _delete_snapshot,
                                               _verify_repo)
    from elasticsearch_tpu.utils.errors import IllegalArgumentException
    import json

    monkeypatch.chdir(tmp_path)
    body = json.dumps({"type": "url",
                       "settings": {"url": "http://snapshot.probe"}}).encode()
    status, _ = _put_repo(node, {}, body, repo="urepo")
    assert status == 200
    assert not os.path.exists(os.path.join(str(tmp_path), "http:"))
    status, resp = _verify_repo(node, {}, b"", repo="urepo")
    assert status == 200 and "nodes" in resp
    assert not os.path.exists(os.path.join(str(tmp_path), "http:"))
    with pytest.raises(IllegalArgumentException):
        _put_snapshot(node, {}, b"{}", repo="urepo", snap="s1")
    with pytest.raises(IllegalArgumentException):
        _delete_snapshot(node, {}, b"", repo="urepo", snap="s1")
    assert not os.path.exists(os.path.join(str(tmp_path), "http:"))


def test_file_url_repository_restores_readonly(node, tmp_path):
    """A file: url repository reads snapshots written by an fs repository
    (the reference's URL-repo use case: serve a shared fs repo read-only)."""
    repo = FsRepository("w", str(tmp_path))
    create_snapshot(node, repo, "s1", indices=["books"])
    ro = FsRepository("ro", str(tmp_path), create=False)
    ro.readonly = True
    node.indices["books"].close()
    del node.indices["books"]
    restore_snapshot(node, ro, "s1")
    assert node.indices["books"].count({})["count"] == 9


def test_broken_analysis_config_rejected_at_creation():
    """Index creation with an unknown analyzer type (or malformed shared
    component) fails up front — reference: AnalysisService builds every
    configured analyzer at construction."""
    from elasticsearch_tpu.utils.errors import IllegalArgumentException

    n = Node()
    with pytest.raises(IllegalArgumentException):
        n.create_index("bad1", {"settings": {"analysis": {
            "analyzer": {"x": {"type": "nosuch"}}}}})
    with pytest.raises(IllegalArgumentException):
        n.create_index("bad2", {"settings": {"analysis": {
            "tokenizer": {"my_tok": {"pattern": "x"}},  # no "type"
            "analyzer": {"x": {"tokenizer": "my_tok"}}}}})
    # a valid custom config still creates
    n.create_index("ok", {"settings": {"analysis": {
        "analyzer": {"x": {"tokenizer": "standard",
                           "filter": ["lowercase"]}}}}})
    assert "ok" in n.indices


def test_unreferenced_broken_shared_component_rejected():
    """Even a shared tokenizer no analyzer references must build at
    creation (reference: AnalysisService constructs every configured
    component)."""
    from elasticsearch_tpu.utils.errors import IllegalArgumentException

    n = Node()
    with pytest.raises(IllegalArgumentException):
        n.create_index("bad3", {"settings": {"analysis": {
            "tokenizer": {"my_tok": {"pattern": "x"}}}}})  # no "type"


def test_restore_broken_analysis_fails_before_any_index(node, tmp_path):
    """A manifest carrying a broken analysis config (written before
    creation-time validation) fails the WHOLE restore up front — no index
    from the snapshot may exist afterwards."""
    import json as _json

    repo = FsRepository("r", str(tmp_path))
    create_snapshot(node, repo, "s1", indices=["books"])
    # corrupt the manifest: add a second index whose settings can't build.
    m = repo.get_manifest("s1")
    good = m["indices"]["books"]
    m["indices"]["zz_broken"] = {
        "settings": {"analysis": {"analyzer": {"x": {"type": "nosuch"}}}},
        "mappings": {}, "aliases": {}, "shards": good["shards"],
    }
    path = os.path.join(str(tmp_path), "snapshots", "s1.json")
    with open(path, "w") as fh:
        _json.dump(m, fh)
    node.indices["books"].close()
    del node.indices["books"]
    with pytest.raises(SnapshotException):
        restore_snapshot(node, repo, "s1")
    # fail-up-front: NOTHING restored, not even the healthy index
    assert "books" not in node.indices and "zz_broken" not in node.indices


def test_gateway_reopens_index_with_legacy_broken_analysis(tmp_path):
    """An on-disk index whose _meta carries a broken-but-unused analysis
    component (written before eager validation existed) must still re-open
    on restart — not silently vanish."""
    import json as _json

    data = str(tmp_path / "data")
    n = Node(data_path=data)
    n.create_index("legacy", {"mappings": {"properties": {
        "t": {"type": "text"}}}})
    n.indices["legacy"].index_doc("1", {"t": "hello"})
    n.indices["legacy"].refresh()
    for s in n.indices.values():
        s.close()
    # retro-break the persisted settings the way a pre-r5 node could have
    meta_path = os.path.join(data, "legacy", "_meta.json")
    with open(meta_path) as fh:
        meta = _json.load(fh)
    meta.setdefault("settings", {}).setdefault("analysis", {})[
        "tokenizer"] = {"broken": {"pattern": "x"}}  # no "type"
    with open(meta_path, "w") as fh:
        _json.dump(meta, fh)
    n2 = Node(data_path=data)
    assert "legacy" in n2.indices, "legacy index silently dropped"
    assert n2.indices["legacy"].count({})["count"] == 1
    for s in n2.indices.values():
        s.close()
