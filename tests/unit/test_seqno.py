"""Checkpoint math, primary-term fencing, and checkpoint-based recovery
(elasticsearch_tpu/index/seqno.py and its engine/replication wiring).

Covers the replication-safety invariants:
- local checkpoint: gaps from out-of-order replica appends hold it back;
  it advances exactly on gap fill
- global checkpoint: never exceeds the slowest IN-SYNC copy; ignores
  non-in-sync stragglers; monotonic under stale reports
- primary term: persisted across engine close/reopen via translog
  replay; stale ops fenced with a typed 409
- recovery: ops-replay when the target is a clean prefix and the
  translog covers the suffix; full copy on divergence/flush, shipping
  tombstones and pruning stale-era docs
"""
import os

import pytest

from elasticsearch_tpu.analysis.registry import AnalysisRegistry
from elasticsearch_tpu.index.engine import Engine
from elasticsearch_tpu.index.mappings import Mappings
from elasticsearch_tpu.index.recovery import recover_peer
from elasticsearch_tpu.index.seqno import (
    NO_OPS_PERFORMED,
    GlobalCheckpointTracker,
    LocalCheckpointTracker,
)
from elasticsearch_tpu.utils.errors import StalePrimaryException
from elasticsearch_tpu.utils.faults import FAULTS


@pytest.fixture(autouse=True)
def _clean_faults():
    FAULTS.clear()
    yield
    FAULTS.clear()


def _engine(tmp_path=None, name="t"):
    path = os.path.join(str(tmp_path), name, "translog") if tmp_path else None
    return Engine(Mappings({}), AnalysisRegistry({}), translog_path=path,
                  index_name=name)


# -- local checkpoint ----------------------------------------------------------

def test_local_checkpoint_contiguous_advance():
    t = LocalCheckpointTracker()
    assert t.checkpoint == NO_OPS_PERFORMED
    for i in range(5):
        assert t.generate() == i
        t.mark_processed(i)
    assert t.checkpoint == 4
    assert t.max_seq_no == 4
    assert not t.has_gaps()


def test_local_checkpoint_gap_holds_then_fills():
    t = LocalCheckpointTracker()
    # out-of-order replica appends: 0, 1, then 3 before 2
    t.mark_processed(0)
    t.mark_processed(1)
    t.mark_processed(3)
    assert t.checkpoint == 1          # the gap at 2 holds it back
    assert t.max_seq_no == 3
    assert t.has_gaps()
    t.mark_processed(2)               # gap fill
    assert t.checkpoint == 3          # advances over BOTH 2 and parked 3
    assert not t.has_gaps()


def test_local_checkpoint_duplicate_delivery_is_idempotent():
    t = LocalCheckpointTracker()
    t.mark_processed(0)
    t.mark_processed(0)  # retried fanout
    assert t.checkpoint == 0
    t.mark_processed(1)
    assert t.checkpoint == 1


def test_advance_to_adopts_wholesale():
    t = LocalCheckpointTracker()
    t.mark_processed(7)  # parked above the checkpoint
    t.advance_to(5)
    assert t.checkpoint == 5
    t.mark_processed(6)  # fills through the parked 7
    assert t.checkpoint == 7


# -- global checkpoint ---------------------------------------------------------

def test_global_checkpoint_is_slowest_in_sync_copy():
    g = GlobalCheckpointTracker(in_sync=["p", "r1", "r2"])
    g.update_local("p", 10)
    g.update_local("r1", 10)
    g.update_local("r2", 3)
    assert g.global_checkpoint == 3   # never exceeds the slowest in-sync
    g.update_local("r2", 9)
    assert g.global_checkpoint == 9
    # a stale (lower) report never moves it backwards
    g.update_local("r2", 4)
    assert g.global_checkpoint == 9


def test_global_checkpoint_ignores_non_in_sync_and_tracks_removal():
    g = GlobalCheckpointTracker(in_sync=["p", "r1"])
    g.update_local("p", 20)
    g.update_local("r1", 20)
    g.update_local("lagger", 1)       # initializing: NOT in-sync
    assert g.global_checkpoint == 20
    g.mark_in_sync("lagger", 2)       # graduates: now it holds it back...
    assert g.global_checkpoint == 20  # ...but monotonicity keeps the max
    g2 = GlobalCheckpointTracker(in_sync=["p", "slow"])
    g2.update_local("p", 20)
    assert g2.global_checkpoint == NO_OPS_PERFORMED  # unreported copy
    g2.remove("slow")                 # failed out of the in-sync set
    assert g2.global_checkpoint == 20


# -- engine: terms + persistence ----------------------------------------------

def test_engine_assigns_contiguous_seqnos_and_terms(tmp_path):
    e = _engine(tmp_path)
    for i in range(4):
        e.index(str(i), {"v": i})
    e.delete("0")
    assert e.max_seq_no == 4 and e.local_checkpoint == 4
    assert e._locations["1"].seq_no == 1
    assert e._locations["1"].term == 1
    e.close()


def test_engine_fences_stale_term_and_adopts_newer(tmp_path):
    e = _engine(tmp_path)
    e.index("a", {"v": 1})
    # replica-style op from a NEWER primary: engine adopts the term
    e.index("b", {"v": 2}, seq_no=1, primary_term=3)
    assert e.primary_term == 3
    # op from the OLD term is now fenced — before any state mutates
    with pytest.raises(StalePrimaryException) as ei:
        e.index("c", {"v": 3}, primary_term=1)
    assert ei.value.status == 409
    assert ei.value.error_type == "stale_primary_exception"
    assert not e.exists("c")
    with pytest.raises(StalePrimaryException):
        e.delete("a", primary_term=2)
    assert e.exists("a")
    e.close()


def test_term_bump_persists_across_close_reopen(tmp_path):
    e = _engine(tmp_path)
    e.index("a", {"v": 1})
    e.bump_term(5)                      # promotion
    e.index("b", {"v": 2})              # op under the new term
    assert e._locations["b"].term == 5
    e.close()
    e2 = _engine(tmp_path)
    e2.recover_from_translog()
    assert e2.primary_term == 5         # term survived via translog replay
    assert e2.local_checkpoint == 1
    assert e2.term_at(0) == 1 and e2.term_at(1) == 5
    with pytest.raises(StalePrimaryException):
        e2.index("c", {"v": 3}, primary_term=4)
    e2.close()


# -- recovery: ops replay vs full copy ----------------------------------------

def test_recover_peer_incremental_replays_only_the_suffix(tmp_path):
    src = _engine(tmp_path, "src")
    for i in range(10):
        src.index(str(i), {"v": i})
    dst = _engine(None, "dst")
    stats = recover_peer(src, dst)
    assert stats["mode"] == "ops" and stats["ops_replayed"] == 10
    assert dst.num_docs == 10 and dst.local_checkpoint == 9
    # five more ops on the source: the next recovery replays exactly five
    for i in range(10, 15):
        src.index(str(i), {"v": i})
    stats = recover_peer(src, dst)
    assert stats["mode"] == "ops" and stats["ops_replayed"] == 5
    assert dst.num_docs == 15
    src.close()
    dst.close()


def test_recover_peer_full_copy_after_flush_and_tombstones(tmp_path):
    src = _engine(tmp_path, "src")
    for i in range(6):
        src.index(str(i), {"v": i})
    dst = _engine(None, "dst")
    recover_peer(src, dst)              # dst in sync, holds doc "3"
    assert dst.exists("3")
    src.delete("3")
    src.flush()                         # commit drops the retained ops
    stats = recover_peer(src, dst)
    assert stats["mode"] == "full"      # retention gap → fallback
    # the tombstone rode the full copy: the doc deleted mid-stream is
    # gone from a target that already held it (the old id-snapshot bug)
    assert not dst.exists("3")
    assert dst.num_docs == 5
    src.close()
    dst.close()


def test_recover_peer_full_copy_prunes_diverged_stale_era_docs(tmp_path):
    src = _engine(tmp_path, "src")
    for i in range(4):
        src.index(str(i), {"v": i})
    dst = _engine(None, "dst")
    recover_peer(src, dst)
    # dst diverges as a zombie old-term copy: local-only doc, never acked
    dst.index("zombie", {"v": 99})
    assert dst.exists("zombie")
    # the real primary moved on under a bumped term
    src.bump_term(2)
    src.index("new", {"v": 5})
    stats = recover_peer(src, dst)
    assert stats["mode"] == "full"      # diverged history → full copy
    assert not dst.exists("zombie")     # stale-era doc pruned
    assert dst.exists("new")
    assert dst.primary_term == 2
    # the prune must NOT have consumed fresh seq nos: the copy's
    # checkpoint matches the source again, so the NEXT bounce is back on
    # the incremental path (a generated tombstone seqno would push the
    # checkpoint past the source's and doom every future handshake to
    # full copies)
    assert dst.local_checkpoint == src.local_checkpoint
    src.index("after", {"v": 6})
    stats = recover_peer(src, dst)
    assert stats["mode"] == "ops" and stats["ops_replayed"] == 1
    src.close()
    dst.close()


def test_recover_peer_ops_replay_fault_point(tmp_path):
    src = _engine(tmp_path, "src")
    for i in range(3):
        src.index(str(i), {"v": i})
    dst = _engine(None, "dst")
    FAULTS.inject("recovery.ops_replay", error=OSError, count=1, after=1)
    with pytest.raises(OSError):
        recover_peer(src, dst)
    assert FAULTS.fired("recovery.ops_replay") == 1
    # the aborted stream left a checkpointed prefix: the retry resumes
    # incrementally and replays only what is missing
    FAULTS.clear()
    already = dst.local_checkpoint
    stats = recover_peer(src, dst)
    assert stats["mode"] == "ops"
    assert stats["ops_replayed"] == 3 - (already + 1)
    assert dst.num_docs == 3
    src.close()
    dst.close()


def test_skipped_replay_op_is_a_noop_not_a_checkpoint_hole(tmp_path):
    src = _engine(tmp_path, "src")
    for i in range(5):
        src.index(str(i), {"v": i})
    dst = _engine(None, "dst")
    recover_peer(src, dst)              # dst ckpt = 4
    # two more updates of doc "0" on the source (seq 5 v2, seq 6 v3);
    # the LATEST fans out live to dst ahead of the recovery replay
    src.index("0", {"v": 100})
    src.index("0", {"v": 200})
    dst.index("0", {"v": 200}, version=3, version_type="external_gte",
              seq_no=6, primary_term=1, _replay=True)
    assert dst.local_checkpoint == 4    # gap at 5 holds it
    stats = recover_peer(src, dst)
    assert stats["mode"] == "ops"
    # the replayed seq-5 op conflicts (dst already has v3) and is
    # SKIPPED — but it must count as processed (a no-op), or the
    # checkpoint would stall on the hole forever and every later
    # recovery would re-replay from it (or full-copy once flushed away)
    assert dst.local_checkpoint == 6
    assert dst.get("0")["_version"] == 3
    src.close()
    dst.close()


def test_select_primary_promotes_in_sync_only():
    from elasticsearch_tpu.cluster.routing import select_primary

    # in-sync leader stays put
    assert select_primary(["a", "b"], ["a", "b"]) == ["a", "b"]
    # stale leader: the first in-sync copy is promoted ahead of it
    assert select_primary(["a", "b", "c"], ["b", "c"]) == ["b", "a", "c"]
    # NO in-sync survivor: red shard, never a silent ack-rollback
    assert select_primary(["a", "b"], []) == []
    assert select_primary([], ["a"]) == []


def test_select_primary_staggered_replicas_pick_highest_checkpoint():
    """PR 18 regression: with three replicas whose checkpoints are
    staggered (each lagging the primary by a different suffix), a dead
    primary must hand off to the HIGHEST-checkpoint in-sync survivor —
    not the first in owner order, which replays the longest suffix and,
    before the in-sync gate, could silently roll back acked ops."""
    from elasticsearch_tpu.cluster.routing import select_primary

    owners = ["p", "r1", "r2", "r3"]
    in_sync = ["r1", "r2", "r3"]  # p died and fell out of sync
    ckpts = {"r1": 4, "r2": 11, "r3": 7}
    got = select_primary(owners, in_sync, checkpoints=ckpts)
    assert got[0] == "r2", got
    # nobody is dropped — the stale ex-primary stays listed for
    # re-replication, just never first
    assert sorted(got) == sorted(owners)
    # ties break on the earlier owner index (deterministic handoff)
    ckpts_tied = {"r1": 9, "r2": 9, "r3": 9}
    assert select_primary(owners, in_sync, checkpoints=ckpts_tied)[0] \
        == "r1"
    # a sitting in-sync primary is NEVER reordered by checkpoints —
    # promotion is for succession, not rebalancing
    assert select_primary(["p", "r1"], ["p", "r1"],
                          checkpoints={"r1": 99})[0] == "p"
    # replicas missing a checkpoint report rank lowest among survivors
    assert select_primary(owners, in_sync,
                          checkpoints={"r3": 1})[0] == "r3"


def test_replication_group_promotion_bumps_term_and_fences_zombie():
    from elasticsearch_tpu.cluster.replication import ReplicationGroup
    from elasticsearch_tpu.index.shard import IndexShard

    mk = lambda: IndexShard("rg", 0, Mappings({}), AnalysisRegistry({}))
    p, r1, r2 = mk(), mk(), mk()
    g = ReplicationGroup(0, p, [r1, r2])
    for i in range(5):
        g.index(str(i), {"v": i})
    assert g.global_checkpoint == 4     # all copies caught up
    old_primary = g.primary
    promoted = g.fail_primary()
    assert promoted is r1
    assert g.primary_term == 2          # promotion bumped the term
    # zombie path: a stale group view still pointing at the old primary
    zombie = ReplicationGroup(0, old_primary, [promoted, r2])
    with pytest.raises(StalePrimaryException):
        zombie.index("late", {"v": 99})
    # the fenced write was never acked and never reached the new primary
    assert not promoted.engine.exists("late")
    # writes through the REAL group proceed under the new term
    g.index("ok", {"v": 1})
    assert promoted.engine._locations["ok"].term == 2


def test_replication_fanout_fault_demotes_copy_not_write():
    from elasticsearch_tpu.cluster.replication import ReplicationGroup
    from elasticsearch_tpu.index.shard import IndexShard

    mk = lambda: IndexShard("rg", 0, Mappings({}), AnalysisRegistry({}))
    p, r1 = mk(), mk()
    g = ReplicationGroup(0, p, [r1])
    FAULTS.inject("replication.fanout", error=OSError, count=1)
    rid, version, created, failed, seq_no, term = g.index("a", {"v": 1})
    assert failed == 1                  # the write itself succeeded...
    assert r1 in g.failed_replicas      # ...the copy was failed out
    # ...and it left the in-sync set: not promotable until re-synced
    assert r1.engine.commit_id not in g.checkpoints.in_sync
    with pytest.raises(Exception):
        g.fail_primary()                # no in-sync replica to promote
