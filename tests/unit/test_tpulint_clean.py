"""CI gate: `elasticsearch_tpu/` must be tpulint-clean.

Runs the analyzer over the real package in tier-1 and fails on any
violation not grandfathered in tools/tpulint/baseline.json. The baseline
is currently EMPTY — a new R001–R005 finding means the diff introduced a
recompile hazard, a per-hit host sync, a dynamic-shape leak, a tracer
leak, or an unlocked shared-state write. Fix it, or (only with a reviewed
justification) suppress in place with `# tpulint: allow[R00x]` / add a
baseline entry. See docs/STATIC_ANALYSIS.md.
"""
import os

from tools.tpulint import lint_paths
from tools.tpulint.baseline import (DEFAULT_BASELINE, filter_baselined,
                                    load_baseline)

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def test_elasticsearch_tpu_is_tpulint_clean():
    target = os.path.join(REPO_ROOT, "elasticsearch_tpu")
    found = lint_paths([target], root=REPO_ROOT)
    new, _old = filter_baselined(found, load_baseline(DEFAULT_BASELINE))
    assert new == [], (
        "tpulint found non-baselined violations:\n"
        + "\n".join(v.format() for v in new)
        + "\n\nrun `python -m tools.tpulint elasticsearch_tpu` locally; "
          "see docs/STATIC_ANALYSIS.md for the fix/suppress workflow"
    )


def test_tools_and_bench_are_tpulint_clean():
    """The linter's own neighbourhood (tools/, bench.py) stays clean too —
    benches are where jit-in-loop and per-hit sync bugs love to hide."""
    found = lint_paths([os.path.join(REPO_ROOT, "tools"),
                        os.path.join(REPO_ROOT, "bench.py")],
                       root=REPO_ROOT)
    new, _old = filter_baselined(found, load_baseline(DEFAULT_BASELINE))
    assert new == [], "\n".join(v.format() for v in new)
