"""CI gate: the whole repo must be tpulint-clean under the WHOLE-PROGRAM
analyzer.

One interprocedural pass (symbol table + call graph + traced-context
inference + R013 lock graph + R014 collective purity) over
`elasticsearch_tpu/` + `tools/` + `bench.py` in tier-1, failing on any
violation not grandfathered in tools/tpulint/baseline.json. A new
finding means the diff introduced a recompile hazard, a host sync
reachable from a jit/shard_map body, a tracer leak, an unlocked
shared-state write, a lock-order cycle, … Fix it, or (only with a
reviewed justification) suppress in place with `# tpulint: allow[R0xx]`
/ add a baseline entry. See docs/STATIC_ANALYSIS.md.

The gate also pins three meta-properties so the analyzer itself can't
rot: the real lock graph stays ACYCLIC (and non-trivial — the analysis
actually sees the cross-module locks), a seeded host sync inside the
mesh executor's collective round IS caught by R014 (the analysis
actually reaches through `wrap(body, ...)`), and a full-repo pass stays
under 30s (the gate can't drift into the slow lane).
"""
import pathlib
import time

from tools.tpulint.baseline import (DEFAULT_BASELINE, filter_baselined,
                                    load_baseline)
from tools.tpulint.project import build_project, lint_project

REPO_ROOT = pathlib.Path(__file__).resolve().parents[2]
SCOPE = [str(REPO_ROOT / "elasticsearch_tpu"), str(REPO_ROOT / "tools"),
         str(REPO_ROOT / "bench.py")]


def _gate(found):
    new, _old = filter_baselined(found, load_baseline(DEFAULT_BASELINE))
    assert new == [], (
        "tpulint found non-baselined violations:\n"
        + "\n".join(v.format() for v in new)
        + "\n\nrun `python -m tools.tpulint` from the repo root; "
          "see docs/STATIC_ANALYSIS.md for the fix/suppress workflow"
    )


def test_repo_is_tpulint_clean_interprocedural():
    """elasticsearch_tpu/ + tools/ + bench.py in ONE project pass, so
    traced-context inference sees every caller (a per-file split would
    sever the call graph at the package boundary)."""
    found = lint_project(SCOPE, root=str(REPO_ROOT))
    _gate(found)


def test_analyzer_full_repo_under_30s():
    """Self-benchmark: the whole-program pass over the full repo must
    stay fast enough for tier-1 — a gate nobody runs is a gate that
    rots. 30s is ~7x the current cost; breach means the analysis grew
    superlinear, not that the repo grew."""
    t0 = time.monotonic()
    lint_project(SCOPE, root=str(REPO_ROOT))
    assert time.monotonic() - t0 < 30.0


def test_real_lock_graph_is_acyclic_and_nontrivial():
    """The codebase's interprocedural held→acquired lock graph: no
    cycles (R013's deadlock precondition), AND the analysis genuinely
    sees the cross-module edges that motivated the rule (engine→translog
    at least) — an empty graph would make 'acyclic' vacuous."""
    index, errors = build_project(SCOPE, root=str(REPO_ROOT))
    assert errors == []
    assert index.lock_cycles == [], index.lock_cycles
    edges = set(index.lock_edges)
    assert ("elasticsearch_tpu.index.engine:Engine._lock",
            "elasticsearch_tpu.index.translog:Translog._lock") in edges, \
        sorted(edges)
    # cross-module reach is real: at least one edge ends outside the
    # module that holds the first lock
    assert any(h.split(":")[0] != l.split(":")[0] for h, l in edges)


def test_seeded_host_sync_in_collective_round_caught_by_r014():
    """Regression for the analyzer's core reach claim: a host sync
    seeded INSIDE the mesh executor's shard_map body (the collective
    round every chip participates in) must be flagged by R014 — this is
    exactly the class of bug ROADMAP #1's single-program query path
    cannot afford, and exactly what per-file linting could never see."""
    path = "elasticsearch_tpu/parallel/executor.py"
    src = (REPO_ROOT / path).read_text()
    anchor = "        masked = jnp.where(sl(live)[None, :], scores, -jnp.inf)"
    assert anchor in src, "executor body changed; update the seed anchor"
    seeded = src.replace(
        anchor, anchor + "\n        jax.device_get(masked)  # seeded", 1)
    found = lint_project([str(REPO_ROOT / "elasticsearch_tpu")],
                         root=str(REPO_ROOT), overlay={path: seeded})
    hits = [v for v in found if v.rule == "R014" and v.path == path]
    assert hits, "seeded device_get in the bm25 collective body not caught"
    assert any("device_get" in v.message for v in hits)
    # and the unseeded tree stays R014-clean (the seed is the only diff)
    clean = lint_project([str(REPO_ROOT / "elasticsearch_tpu")],
                         root=str(REPO_ROOT))
    assert [v for v in clean if v.rule == "R014" and v.path == path] == []


def test_traced_inference_reaches_helpers():
    """The whole-program pass marks the helpers the executor's program
    bodies call — ops/ helpers with no jit decorator of their own — as
    traced/collective; path-list scoping could never do this."""
    index, _errors = build_project(SCOPE, root=str(REPO_ROOT))
    assert "elasticsearch_tpu.ops.knn:exact_rescore_topk" in index.collective
    assert len(index.traced) > 50          # the traced world is substantial
    assert len(index.collective) >= 10     # ... and so is collective reach
