"""CI gate: the whole repo must be tpulint-clean under the WHOLE-PROGRAM
analyzer.

One interprocedural pass (symbol table + call graph + traced-context
inference + R013 lock graph + R014 collective purity + the pass-3
shapeflow lattice behind R017–R020) over `elasticsearch_tpu/` +
`tools/` + `bench.py` in tier-1, failing on any violation not
grandfathered in tools/tpulint/baseline.json. A new finding means the
diff introduced a recompile hazard, a host sync reachable from a
jit/shard_map body, a tracer leak, an unlocked shared-state write, a
lock-order cycle, a data-dependent dim riding a program cache key, an
unmasked reduction over padded lanes, … Fix it, or (only with a
reviewed justification) suppress in place with `# tpulint: allow[R0xx]`
/ add a baseline entry. See docs/STATIC_ANALYSIS.md.

The gate also pins three meta-properties so the analyzer itself can't
rot: the real lock graph stays ACYCLIC (and non-trivial — the analysis
actually sees the cross-module locks), a seeded host sync inside the
mesh executor's collective round IS caught by R014 (the analysis
actually reaches through `wrap(body, ...)`), and a full-repo pass stays
under 30s (the gate can't drift into the slow lane).
"""
import pathlib
import time

from tools.tpulint.baseline import (DEFAULT_BASELINE, filter_baselined,
                                    load_baseline)
from tools.tpulint.project import build_project, lint_project

REPO_ROOT = pathlib.Path(__file__).resolve().parents[2]
SCOPE = [str(REPO_ROOT / "elasticsearch_tpu"), str(REPO_ROOT / "tools"),
         str(REPO_ROOT / "bench.py")]


def _gate(found):
    new, _old = filter_baselined(found, load_baseline(DEFAULT_BASELINE))
    assert new == [], (
        "tpulint found non-baselined violations:\n"
        + "\n".join(v.format() for v in new)
        + "\n\nrun `python -m tools.tpulint` from the repo root; "
          "see docs/STATIC_ANALYSIS.md for the fix/suppress workflow"
    )


def test_repo_is_tpulint_clean_interprocedural():
    """elasticsearch_tpu/ + tools/ + bench.py in ONE project pass, so
    traced-context inference sees every caller (a per-file split would
    sever the call graph at the package boundary)."""
    found = lint_project(SCOPE, root=str(REPO_ROOT))
    _gate(found)


def test_analyzer_full_repo_under_30s():
    """Self-benchmark: the whole-program pass over the full repo —
    including the R015/R016 concurrency fixpoints — must stay fast
    enough for tier-1; a gate nobody runs is a gate that rots. 30s is
    ~6x the current cost; breach means the analysis grew superlinear,
    not that the repo grew. The measured time prints so the gate run
    itself is the benchmark record (`pytest -s` shows it)."""
    t0 = time.monotonic()
    lint_project(SCOPE, root=str(REPO_ROOT))
    dt = time.monotonic() - t0
    print(f"\ntpulint full-project pass: {dt:.2f}s (bound 30s)")
    assert dt < 30.0, f"analyzer self-benchmark breached: {dt:.2f}s"


def test_real_lock_graph_is_acyclic_and_nontrivial():
    """The codebase's interprocedural held→acquired lock graph: no
    cycles (R013's deadlock precondition), AND the analysis genuinely
    sees the cross-module edges that motivated the rule (engine→translog
    at least) — an empty graph would make 'acyclic' vacuous."""
    index, errors = build_project(SCOPE, root=str(REPO_ROOT))
    assert errors == []
    assert index.lock_cycles == [], index.lock_cycles
    edges = set(index.lock_edges)
    assert ("elasticsearch_tpu.index.engine:Engine._lock",
            "elasticsearch_tpu.index.translog:Translog._lock") in edges, \
        sorted(edges)
    # cross-module reach is real: at least one edge ends outside the
    # module that holds the first lock
    assert any(h.split(":")[0] != l.split(":")[0] for h, l in edges)


def test_seeded_host_sync_in_collective_round_caught_by_r014():
    """Regression for the analyzer's core reach claim: a host sync
    seeded INSIDE the mesh executor's shard_map body (the collective
    round every chip participates in) must be flagged by R014 — this is
    exactly the class of bug ROADMAP #1's single-program query path
    cannot afford, and exactly what per-file linting could never see."""
    path = "elasticsearch_tpu/parallel/executor.py"
    src = (REPO_ROOT / path).read_text()
    anchor = "        masked = jnp.where(sl(live)[None, :], scores, -jnp.inf)"
    assert anchor in src, "executor body changed; update the seed anchor"
    seeded = src.replace(
        anchor, anchor + "\n        jax.device_get(masked)  # seeded", 1)
    found = lint_project([str(REPO_ROOT / "elasticsearch_tpu")],
                         root=str(REPO_ROOT), overlay={path: seeded})
    hits = [v for v in found if v.rule == "R014" and v.path == path]
    assert hits, "seeded device_get in the bm25 collective body not caught"
    assert any("device_get" in v.message for v in hits)
    # and the unseeded tree stays R014-clean (the seed is the only diff)
    clean = lint_project([str(REPO_ROOT / "elasticsearch_tpu")],
                         root=str(REPO_ROOT))
    assert [v for v in clean if v.rule == "R014" and v.path == path] == []


def test_concurrency_analysis_sees_the_real_stack():
    """The R015/R016 substrate on the real repo: the daemon loops and
    REST/transport handlers are in CONCURRENT reach, and the lockset
    inference recovers the real guard disciplines — including the
    executor's `_prep` map, whose popitem-vs-move_to_end race was
    hand-found in review before this rule existed."""
    index, _errors = build_project(SCOPE, root=str(REPO_ROOT))
    for sid in (
            "elasticsearch_tpu.serving.coalescer:QueryCoalescer"
            "._drain_loop",
            "elasticsearch_tpu.monitor.watchdog:WatchdogService._loop",
            "elasticsearch_tpu.serving.warmup:WarmupService._loop",
            "elasticsearch_tpu.cluster.search_action:"
            "DistributedDataService._on_shard_sync"):
        assert sid in index.concurrent, sid
    assert len(index.concurrent) > 300   # REST reach is broad — by design
    expects = {
        "elasticsearch_tpu.serving.coalescer:QueryCoalescer._queues":
            "QueryCoalescer._cv",
        "elasticsearch_tpu.index.engine:Engine._locations":
            "Engine._lock",
        "elasticsearch_tpu.parallel.executor:MeshSearchExecutor._prep":
            "MeshSearchExecutor._prep_lock",
        "elasticsearch_tpu.cluster.bootstrap:MultiHostCluster"
        "._committed_snapshot": "MultiHostCluster._indices_lock",
    }
    for ident, want in expects.items():
        got = index.attr_guards.get(ident)
        assert got is not None and got[0].endswith(want), (ident, got)
    assert len(index.attr_guards) >= 100  # the inferred world is real


def test_seeded_race_and_atomicity_overlays_caught():
    """R015/R016 reach regression on REAL source (the R014 seed's
    sibling): an unguarded write seeded into the warmup worker loop and
    a check-then-act seeded into the coalescer's stats path must be
    caught — and the unseeded tree stays clean (the seeds are the only
    diff)."""
    wpath = "elasticsearch_tpu/serving/warmup.py"
    wsrc = (REPO_ROOT / wpath).read_text()
    wanchor = "    def _loop(self) -> None:\n" \
              "        while not self._stop.is_set():"
    assert wanchor in wsrc, "warmup _loop changed; update the seed anchor"
    wseed = wsrc.replace(
        wanchor, wanchor + "\n            self._queue.clear()  # seeded",
        1)
    cpath = "elasticsearch_tpu/serving/coalescer.py"
    csrc = (REPO_ROOT / cpath).read_text()
    canchor = ("    def _flush(self, batch: List[_Entry], "
               "reason: str) -> None:\n"
               "        from elasticsearch_tpu.search.batch import "
               "execute_batch\n")
    assert canchor in csrc, "coalescer _flush changed; update the seed"
    cseed = csrc.replace(canchor, canchor + (
        "\n"
        "        with self._cv:\n"
        "            _seed = self._queues.get((\"seed\", \"seed\"))\n"
        "        if _seed is None:\n"
        "            with self._cv:\n"
        "                self._queues[(\"seed\", \"seed\")] = []\n"), 1)
    found = lint_project([str(REPO_ROOT / "elasticsearch_tpu")],
                         root=str(REPO_ROOT),
                         overlay={wpath: wseed, cpath: cseed})
    r15 = [v for v in found if v.rule == "R015" and v.path == wpath]
    assert r15, "seeded unguarded write in the warmup loop not caught"
    assert any("_queue" in v.message and "WarmupService._lock"
               in v.message for v in r15)
    r16 = [v for v in found if v.rule == "R016" and v.path == cpath]
    assert r16, "seeded check-then-act in coalescer stats not caught"
    assert any("_queues" in v.message for v in r16)
    # the unseeded tree stays R015/R016-clean (the seeds are the diff)
    clean = lint_project([str(REPO_ROOT / "elasticsearch_tpu")],
                         root=str(REPO_ROOT))
    assert [v for v in clean if v.rule in ("R015", "R016")
            and v.path in (wpath, cpath)] == []


def test_seeded_shapeflow_overlays_caught():
    """Pass-3 (shapeflow) reach regression on REAL source: each of the
    four v3 rules must fire on a violation seeded into the actual device
    data plane — and the unseeded tree stays clean (the seeds are the
    only diff). R017 is seeded twice: a len()-derived batch width handed
    to a program factory from search/batch.py (cross-module flow), and
    the executor's own query-axis bucketing reverted in place (exactly
    the recompile storm the adoption pass fixed)."""
    epath = "elasticsearch_tpu/parallel/executor.py"
    esrc = (REPO_ROOT / epath).read_text()
    bpath = "elasticsearch_tpu/search/batch.py"
    bsrc = (REPO_ROOT / bpath).read_text()
    rpath = "elasticsearch_tpu/resources/residency.py"
    rsrc = (REPO_ROOT / rpath).read_text()
    scope = [str(REPO_ROOT / "elasticsearch_tpu")]

    # R017 (a): host batch.py feeds len(queries) straight into a factory
    imp_anchor = "from elasticsearch_tpu.search.service import ShardDoc"
    call_anchor = "    Q = len(queries)\n"
    assert imp_anchor in bsrc and call_anchor in bsrc, \
        "batch.py changed; update the R017 seed anchors"
    bseed = bsrc.replace(imp_anchor, imp_anchor + (
        "\nfrom elasticsearch_tpu.parallel.executor import "
        "_knn_program  # seeded"), 1)
    bseed = bseed.replace(call_anchor, call_anchor + (
        "    _knn_program(None, {}, Q=Q, dims=4, D=8, k=k, "
        "metric=\"dot\")  # seeded\n"), 1)

    # R017 (b): revert the executor's query-axis pow2 bucketing
    e17_anchor = ("        Qr = len(query_terms)\n"
                  "        Q = pow2_bucket(Qr, minimum=1)")
    assert e17_anchor in esrc, "executor changed; update the R017 anchor"
    e17seed = esrc.replace(
        e17_anchor, "        Qr = len(query_terms)\n"
                    "        Q = Qr  # seeded", 1)

    # R018/R019: seeded into the bm25 collective body itself
    body_anchor = ("        scores = jax.vmap(score1)(sl(starts), "
                   "sl(lens), sl(weights))  # [Q, D]")
    assert body_anchor in esrc, "bm25 body changed; update the anchor"
    e18seed = esrc.replace(
        body_anchor, body_anchor + "\n        _dbg = jnp.sum(tfnorm)"
        "  # seeded", 1)
    e19seed = esrc.replace(
        body_anchor, body_anchor +
        "\n        _w = scores.astype(jnp.float64)  # seeded", 1)

    # R020 (a): a fallible call between the executor's residency charge
    # and the store that hands the token off
    e20_anchor = ('                tok = resources.RESIDENCY.track('
                  'fresh_bytes,\n                                     '
                  '           label="executor.prep")')
    assert e20_anchor in esrc, "prep charge moved; update the R020 anchor"
    e20seed = esrc.replace(
        e20_anchor, e20_anchor + "\n                "
        "_audit_prep_entries(self.shards)  # seeded", 1)

    # R020 (b): the same leak shape seeded into resources/ itself
    r_anchor = ("    # -- pinned charges ------------------------------"
                "-----------------------")
    assert r_anchor in rsrc, "residency.py changed; update the anchor"
    rseed = rsrc.replace(r_anchor, (
        "    def seeded_prewarm(self, nbytes):  # seeded\n"
        "        tok = self.track(int(nbytes), \"seed\")  # seeded\n"
        "        self._rebuild_plan()  # seeded\n"
        "        self._seed_tok = tok  # seeded\n\n") + r_anchor, 1)

    for overlay, rule, path in [
            ({bpath: bseed}, "R017", bpath),
            ({epath: e17seed}, "R017", epath),
            ({epath: e18seed}, "R018", epath),
            ({epath: e19seed}, "R019", epath),
            ({epath: e20seed}, "R020", epath),
            ({rpath: rseed}, "R020", rpath)]:
        found = lint_project(scope, root=str(REPO_ROOT), overlay=overlay)
        hits = [v for v in found if v.rule == rule and v.path == path]
        assert hits, f"seeded {rule} violation in {path} not caught"
    # the unseeded tree stays clean of all four rules in those files
    clean = lint_project(scope, root=str(REPO_ROOT))
    assert [v for v in clean
            if v.rule in ("R017", "R018", "R019", "R020")
            and v.path in (epath, bpath, rpath)] == []


def test_traced_inference_reaches_helpers():
    """The whole-program pass marks the helpers the executor's program
    bodies call — ops/ helpers with no jit decorator of their own — as
    traced/collective; path-list scoping could never do this."""
    index, _errors = build_project(SCOPE, root=str(REPO_ROOT))
    assert "elasticsearch_tpu.ops.knn:exact_rescore_topk" in index.collective
    assert len(index.traced) > 50          # the traced world is substantial
    assert len(index.collective) >= 10     # ... and so is collective reach
