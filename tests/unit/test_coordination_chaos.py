"""Cluster-coordination chaos matrix (tier-1, seed-deterministic).

The control-plane counterpart of test_replication_chaos.py: three full
MultiHostClusters IN-PROCESS under the DEFAULT quorum (majority of the
master-eligible voting configuration = 2 of 3), ping_interval=0 so the
tests drive fault-detection rounds explicitly — deterministic, bounded.

Scenarios, each under a FIXED SEED MATRIX:

- kill-master-mid-bulk: the master dies while a bulk streams through a
  surviving coordinator. Within ``ping_retries`` fault-detection rounds
  the lowest-id survivor wins a term-2 quorum election, reconstructs the
  dist metadata, promotes primaries under BUMPED shard terms, and serves
  every ACKNOWLEDGED doc (zero acked-op loss); a zombie write raced to
  the dead-but-unaware old master is fenced with a typed 409.
- symmetric partition + heal: the isolated old master steps down (it can
  never gather a publish quorum), its writes fail typed 503
  ``cluster_block_exception`` while the majority keeps electing, writing
  and serving 200 searches; on heal the minority rejoins as a follower
  and adopts the majority's committed state.
- healed stale master: a master that never even noticed the partition
  has its first post-heal publication rejected stale (409) by the
  majority, steps down WITHOUT ever committing a conflicting state
  version, and rejoins as a follower.
"""
import socket

import pytest

from elasticsearch_tpu.cluster.routing import shard_id_for
from elasticsearch_tpu.cluster.transport import PeerBreaker
from elasticsearch_tpu.utils.faults import FAULTS

#: fixed seeds — same grammar as ESTPU_FAULTS for subprocess members
KILL_SEEDS = [101, 202, 303]
PARTITION_SEEDS = [11, 22]


@pytest.fixture(autouse=True)
def _clean_slate():
    FAULTS.clear()
    yield
    FAULTS.clear()


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


@pytest.fixture()
def trio():
    """Three MultiHostClusters, default quorum (2 of 3); index `evt`
    with 3 shards and 1 replica so every node is a primary owner."""
    from elasticsearch_tpu.cluster.bootstrap import MultiHostCluster
    from elasticsearch_tpu.node import Node

    port = _free_port()
    nodes, clusters = [], []
    for rank in range(3):
        n = Node(name=f"rank{rank}")
        c = MultiHostCluster(n, rank=rank, world=3, transport_port=port,
                             ping_interval=0)
        nodes.append(n)
        clusters.append(c)
    c0, c1, c2 = clusters
    assert c0.quorum() == 2
    c0.data.create_index("evt", {
        "settings": {"number_of_shards": 3, "number_of_replicas": 1},
        "mappings": {"properties": {"n": {"type": "integer"}}}})
    meta = c0.dist_indices["evt"]
    assert {v[0] for v in meta["assignment"].values()} == {
        c0.local.node_id, c1.local.node_id, c2.local.node_id}
    yield clusters
    FAULTS.clear()
    for c in reversed(clusters):
        try:
            c.close()
        except Exception:
            pass
    for n in reversed(nodes):
        n.close()


def _addr(c):
    host, port = c.local.transport_address.rsplit(":", 1)
    return host, int(port)


def _arm_kill(addr, prob, seed):
    """Seeded connect-refusal for every send TO `addr` — the
    deterministic stand-in for a dying node."""
    FAULTS.inject(
        "transport.send", error=ConnectionRefusedError, count=-1,
        prob=prob, seed=seed,
        match=lambda ctx: ctx.get("address") == addr)


def _arm_partition(minority, majority, seed):
    """Symmetric link-level drop between `minority` and every member of
    `majority`, BOTH directions, via the discovery.partition point."""
    min_id = minority.local.node_id
    min_addr = _addr(minority)
    maj_ids = {c.local.node_id for c in majority}
    maj_addrs = {_addr(c) for c in majority}
    FAULTS.inject(
        "discovery.partition", error=ConnectionRefusedError, count=-1,
        seed=seed,
        match=lambda ctx: (
            (ctx.get("local") == min_id
             and ctx.get("address") in maj_addrs)
            or (ctx.get("local") in maj_ids
                and ctx.get("address") == min_addr)))


def _bulk_with_midstream_kill(coord, victim, seed, n_docs=40, kill_at=10,
                              prob=0.6):
    """Index n_docs through `coord`, arming the seeded kill of `victim`
    after `kill_at` acks. Returns the ACKNOWLEDGED doc ids."""
    acked = set()
    for i in range(n_docs):
        if i == kill_at:
            _arm_kill(_addr(victim), prob, seed)
        doc_id = f"d{i}"
        try:
            res = coord.data.index_doc("evt", doc_id, {"n": i})
            assert res.get("_seq_no") is not None
            acked.add(doc_id)
        except Exception:
            pass  # unacked: the client was TOLD it failed
    return acked


@pytest.mark.parametrize("seed", KILL_SEEDS)
def test_kill_master_mid_bulk_new_master_zero_acked_loss(trio, seed):
    c0, c1, c2 = trio
    old_term = c1.node.cluster_state.term
    old_terms = {k: int(v)
                 for k, v in c0.dist_indices["evt"]["primary_terms"]
                 .items()}
    acked = _bulk_with_midstream_kill(c1, c0, seed)
    assert acked, "no write acked at all"

    # bounded takeover: the seeded kill fires probabilistically, so a
    # lucky ping can reset the strike count — but within a BOUNDED
    # number of rounds (deterministic per seed) the survivors declare
    # the master dead and the lowest-id survivor wins the election
    bound = 15 * c1._ping_retries
    rounds = 0
    while not c1.is_master and rounds < bound:
        c1.run_fd_round()
        c2.run_fd_round()
        rounds += 1
    assert c1.is_master, "lowest-id survivor must win the election"
    assert rounds <= bound
    assert c1.node.cluster_state.term == old_term + 1
    assert c2.node.cluster_state.master_node_id == c1.local.node_id
    assert c2.node.cluster_state.term == old_term + 1
    counters = c1.node.metrics.counter_values()
    assert counters.get(
        'estpu_discovery_elections_total{outcome="won"}', 0) >= 1

    # metadata takeover: every shard the dead master owned changed hands
    # to a survivor under a BUMPED primary term
    meta = c1.dist_indices["evt"]
    dead = c0.local.node_id
    for sid_s, owners in meta["assignment"].items():
        assert owners, f"shard {sid_s} lost every copy"
        assert dead not in owners
    bumped = [s for s, t in meta["primary_terms"].items()
              if int(t) > old_terms[s]]
    assert bumped, "no shard term bump despite the master's death"

    # ZERO acked-op loss: every acknowledged doc is served by the
    # promoted copies through the new master's committed metadata
    c1.node.indices["evt"].refresh()
    c2.node.indices["evt"].refresh()
    for doc_id in sorted(acked):
        got = c1.data.get_doc("evt", doc_id)
        assert got.get("found"), f"ACKED doc {doc_id} lost after takeover"

    # writes keep flowing through the new master's era
    res = c1.data.index_doc("evt", "after", {"n": 1000})
    assert res.get("_seq_no") is not None

    # a zombie write raced to the demoted OLD master: depending on the
    # seed it either still believes it is master+primary (its op carries
    # the stale shard term and the surviving copy fences it: typed 409)
    # or one of its in-flight publications already met the campaign
    # fence and it stepped down (writes blocked: typed 503) — EITHER
    # way the write is refused, never silently acked into the old era
    zombie_sid = next(
        s for s, t in meta["primary_terms"].items()
        if int(t) > old_terms[s])
    zombie_id = next(f"z{k}" for k in range(1000)
                     if shard_id_for(f"z{k}", 3) == int(zombie_sid))
    with pytest.raises(Exception) as ei:
        c0.data.index_doc("evt", zombie_id, {"n": -1})
    if c0.is_master:
        assert getattr(ei.value, "error_type", "") == \
            "stale_primary_exception"
        assert getattr(ei.value, "status", 0) == 409
    else:  # resigned on the stale-publication 409 — writes are blocked
        assert getattr(ei.value, "error_type", "") == \
            "cluster_block_exception"
        assert getattr(ei.value, "status", 0) == 503
    # the fenced write reached no promoted copy
    assert not c1.node.indices["evt"].shards[int(zombie_sid)] \
        .engine.exists(zombie_id)

    # observability: the new master's health carries the bumped term
    from elasticsearch_tpu.rest.server import RestController

    status, h = RestController(c1.node).dispatch(
        "GET", "/_cluster/health", {}, b"")
    assert status == 200
    assert h["master_node"] == c1.local.node_id
    assert h["term"] == old_term + 1
    assert h["no_master_block"] is False
    status, rows = RestController(c2.node).dispatch(
        "GET", "/_cat/master", {}, b"")
    assert status == 200 and rows[0]["id"] == c1.local.node_id


@pytest.mark.parametrize("seed", PARTITION_SEEDS)
def test_partition_minority_blocks_majority_serves_heal_rejoins(trio,
                                                                seed):
    import json

    from elasticsearch_tpu.rest.server import RestController
    from elasticsearch_tpu.utils.errors import ClusterBlockException

    c0, c1, c2 = trio
    for i in range(12 + seed % 5):
        c0.data.index_doc("evt", f"p{i}", {"n": i})
    c0.data.refresh("evt")
    committed_before = c0.committed
    history_before = list(c0.committed_history)

    _arm_partition(c0, [c1, c2], seed)

    # majority side: detects the master's death, elects c1 (lowest id)
    for _ in range(c1._ping_retries):
        c1.run_fd_round()
        c2.run_fd_round()
    assert c1.is_master
    new_term = c1.node.cluster_state.term
    assert new_term == 2
    assert c2.node.cluster_state.master_node_id == c1.local.node_id

    # minority side: the old master's own fault detection empties its
    # follower view below quorum -> it STEPS DOWN (publish could never
    # commit) and blocks writes
    for _ in range(c0._ping_retries):
        c0.run_fd_round()
    assert not c0.is_master
    assert c0.node.cluster_state.master_node_id is None
    counters = c0.node.metrics.counter_values()
    assert counters.get("estpu_discovery_master_stepdowns_total", 0) >= 1

    # minority writes: typed 503 cluster_block_exception, data plane...
    with pytest.raises(ClusterBlockException) as ei:
        c0.data.index_doc("evt", "minority", {"n": -1})
    assert ei.value.status == 503
    # ...and REST
    st, body = RestController(c0.node).dispatch(
        "PUT", "/evt/_doc/minority", {},
        json.dumps({"n": -1}).encode())
    assert st == 503
    assert body["error"]["type"] == "cluster_block_exception"
    # minority metadata ops: same block
    st, body = RestController(c0.node).dispatch(
        "PUT", "/minorix", {}, b"{}")
    assert st == 503

    # minority searches still answer 200 from the last committed state
    st, body = RestController(c0.node).dispatch(
        "GET", "/evt/_search", {"size": "0"}, b"")
    assert st == 200

    # the minority committed NOTHING during the partition
    assert c0.committed == committed_before
    assert list(c0.committed_history) == history_before

    # majority side: writes land and searches serve 200 clean
    res = c1.data.index_doc("evt", "majority", {"n": 7})
    assert res.get("_seq_no") is not None
    c1.data.refresh("evt")
    st, body = RestController(c1.node).dispatch(
        "GET", "/evt/_search", {"size": "0"}, b"")
    assert st == 200
    assert body["_shards"]["failed"] == 0

    # HEAL: the headless minority scans its known peers, finds the
    # term-2 master, joins it, and adopts the committed majority state
    FAULTS.clear()
    for c in (c0, c1, c2):
        c.transport.breaker = PeerBreaker()
    c0.run_fd_round()  # headless round = the rejoin scan
    assert not c0.is_master
    assert c0.node.cluster_state.master_node_id == c1.local.node_id
    assert c0.node.cluster_state.term == new_term
    assert c0.committed[0] == new_term
    # the write block lifted: a write through the healed member routes
    # to the quorum's owners and acks
    res = c0.data.index_doc("evt", "healed", {"n": 8})
    assert res.get("_seq_no") is not None
    st, h = RestController(c0.node).dispatch(
        "GET", "/_cluster/health", {}, b"")
    assert st == 200 and h["no_master_block"] is False
    assert h["master_node"] == c1.local.node_id and h["term"] == new_term


def test_healed_stale_master_steps_down_without_conflicting_commit(trio):
    """The partition heals before the old master ever NOTICED it: its
    first post-heal publication is rejected stale (typed 409) by the
    majority, it steps down without committing, and rejoins as a
    follower of the term-2 master."""
    from elasticsearch_tpu.utils.errors import ElasticsearchTpuException

    c0, c1, c2 = trio
    _arm_partition(c0, [c1, c2], seed=7)
    # ONLY the majority runs detection rounds: c0 never notices
    for _ in range(c1._ping_retries):
        c1.run_fd_round()
        c2.run_fd_round()
    assert c1.is_master and c1.node.cluster_state.term == 2
    majority_committed = c1.committed

    FAULTS.clear()  # heal — c0 still believes it is the term-1 master
    for c in (c0, c1, c2):
        c.transport.breaker = PeerBreaker()
    assert c0.is_master and c0.node.cluster_state.term == 1

    # the stale master's next metadata change cannot commit: the
    # majority fences its term-1 publication with the typed 409, the
    # master steps down, the op fails typed, and the half-created local
    # index rolls back
    with pytest.raises(ElasticsearchTpuException) as ei:
        c0.data.create_index("minor", {"settings":
                                       {"number_of_shards": 1}})
    assert getattr(ei.value, "status", 0) in (503, 409)
    assert not c0.is_master
    assert "minor" not in c0.dist_indices
    assert "minor" not in c1.dist_indices
    # the majority's committed line never regressed or forked
    assert c1.committed >= majority_committed
    assert c1.is_master

    # the stepped-down master rejoins as a follower and adopts term 2
    c0.run_fd_round()
    assert c0.node.cluster_state.master_node_id == c1.local.node_id
    assert c0.node.cluster_state.term == 2
    assert c0.committed[0] == 2


def test_env_spec_arms_coordination_points():
    """The ESTPU_FAULTS grammar covers the new coordination points
    (subprocess cluster members arm through it)."""
    from elasticsearch_tpu.utils.faults import FaultRegistry, _parse_env_spec

    r = FaultRegistry()
    _parse_env_spec(
        "discovery.vote:count=1;publish.commit:count=2;"
        "discovery.partition:prob=0.5:seed=9", r)
    assert r.active("discovery.vote")
    assert r.active("publish.commit")
    assert r.active("discovery.partition")
