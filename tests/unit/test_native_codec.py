"""Native codec + translog CRC framing + postings store tests.

Reference: Lucene vInt/PForDelta codecs, translog checksum
(BufferedChecksumStreamOutput / CRC32).
"""
import os
import zlib

import numpy as np
import pytest

from elasticsearch_tpu.index.index_service import IndexService
from elasticsearch_tpu.index.store import (
    CorruptStoreException,
    read_postings,
    write_postings,
)
from elasticsearch_tpu.index.translog import Translog
from elasticsearch_tpu.native import (
    crc32,
    delta_decode,
    delta_encode,
    native_available,
    vbyte_decode,
    vbyte_encode,
)


def test_native_lib_builds():
    # g++ is baked into the image; the native path must actually be active
    assert native_available()


def test_vbyte_roundtrip_matches_and_compresses():
    rng = np.random.default_rng(1)
    a = rng.integers(-(10**15), 10**15, 5000)
    enc = vbyte_encode(a)
    np.testing.assert_array_equal(vbyte_decode(enc, a.size), a)
    small = rng.integers(0, 64, 5000)
    assert len(vbyte_encode(small)) == 5000  # 1 byte per value in [-64, 63]


def test_delta_roundtrip_sorted_ids():
    rng = np.random.default_rng(2)
    ids = np.sort(rng.choice(10**8, size=4000, replace=False))
    enc = delta_encode(ids)
    np.testing.assert_array_equal(delta_decode(enc, ids.size), ids)
    assert len(enc) < len(vbyte_encode(ids))  # gaps beat absolutes


def test_crc32_matches_zlib():
    for data in (b"", b"x", os.urandom(10_000)):
        assert crc32(data) == zlib.crc32(data) & 0xFFFFFFFF


def test_truncated_input_safe():
    a = np.arange(1000, dtype=np.int64) * 1000
    enc = vbyte_encode(a)
    out = vbyte_decode(enc[: len(enc) // 2], 1000)
    assert 0 < len(out) < 1000


def test_translog_v2_roundtrip_and_torn_tail(tmp_path):
    p = str(tmp_path / "tl" / "translog")
    t = Translog(p)
    ops = [{"op": "index", "id": str(i), "source": {"v": i}} for i in range(50)]
    for op in ops:
        t.append(op)
    t.close()
    t2 = Translog(p)
    assert list(t2.replay()) == ops
    t2.close()
    # torn tail: truncate mid-frame — replay stops cleanly at the tear
    gen_file = p + ".1"
    size = os.path.getsize(gen_file)
    with open(gen_file, "r+b") as f:
        f.truncate(size - 7)
    t3 = Translog(p)
    replayed = list(t3.replay())
    assert replayed == ops[:-1]
    t3.close()


def test_translog_v2_detects_bitrot(tmp_path):
    p = str(tmp_path / "tl" / "translog")
    t = Translog(p)
    for i in range(10):
        t.append({"op": "index", "id": str(i), "source": {"v": i}})
    t.close()
    gen_file = p + ".1"
    with open(gen_file, "r+b") as f:
        f.seek(os.path.getsize(gen_file) - 3)
        f.write(b"\xff")  # corrupt the last frame's payload
    t2 = Translog(p)
    assert len(list(t2.replay())) == 9  # CRC catches the corrupt frame
    t2.close()


def test_translog_legacy_v1_still_readable(tmp_path):
    import json

    p = str(tmp_path / "tl" / "translog")
    os.makedirs(os.path.dirname(p))
    with open(p + ".1", "wb") as f:
        for i in range(5):
            f.write(json.dumps({"op": "index", "id": str(i), "source": {}}).encode() + b"\n")
    t = Translog(p)
    assert len(list(t.replay())) == 5
    t.close()


def test_postings_store_roundtrip():
    svc = IndexService("st")
    docs = ["quick brown fox", "quick dog", "lazy fox jumps high",
            "the quick quick fox"]
    for i, b in enumerate(docs):
        svc.index_doc(str(i), {"body": b})
    svc.refresh()
    inv = svc.shards[0].segments[0].inverted["body"]
    blob = write_postings(inv)
    out = read_postings(blob)
    assert out["terms"] == inv.terms
    np.testing.assert_array_equal(out["offsets"], inv.offsets)
    np.testing.assert_array_equal(out["doc_ids"], inv.doc_ids_host[: inv.nnz])
    np.testing.assert_array_equal(out["df"], inv.df)
    np.testing.assert_array_equal(out["tf"], inv.tf_host[: inv.nnz].astype(np.int64))
    np.testing.assert_array_equal(out["positions"], inv.positions)
    # corruption detected
    bad = bytearray(blob)
    bad[-2] ^= 0xFF
    with pytest.raises(CorruptStoreException):
        read_postings(bytes(bad))
    svc.close()


def test_node_gateway_recovers_indices_and_mappings(tmp_path):
    from elasticsearch_tpu.node import Node

    n = Node(data_path=str(tmp_path))
    n.create_index("g1", {"mappings": {"properties": {
        "m": {"type": "text", "analyzer": "english"}}},
        "aliases": {"ga": {}}})
    n.indices["g1"].index_doc("1", {"m": "running fast"})
    for s in n.indices.values():
        s.close()
    n2 = Node(data_path=str(tmp_path))
    assert "g1" in n2.indices
    assert n2.indices["g1"].aliases.get("ga") is not None
    n2.indices["g1"].refresh()
    # analyzer survived: stemmed query matches
    r = n2.search("g1", {"query": {"match": {"m": "run"}}})
    assert r["hits"]["total"] == 1
    # alias resolution survived
    r = n2.search("ga", {"query": {"match_all": {}}})
    assert r["hits"]["total"] == 1
    # delete removes on-disk state: next boot has nothing
    n2.delete_index("g1")
    n3 = Node(data_path=str(tmp_path))
    assert "g1" not in n3.indices


def test_translog_v1_file_not_mixed_with_v2(tmp_path):
    import json

    p = str(tmp_path / "tl" / "translog")
    os.makedirs(os.path.dirname(p))
    v1_ops = [{"op": "index", "id": str(i), "source": {}} for i in range(3)]
    with open(p + ".1", "wb") as f:
        for op in v1_ops:
            f.write(json.dumps(op).encode() + b"\n")
    t = Translog(p)
    assert t.generation == 2  # rolled: never append v2 frames to a v1 file
    t.append({"op": "index", "id": "new", "source": {}})
    t.close()
    t2 = Translog(p)
    replayed = list(t2.replay())
    assert replayed == v1_ops + [{"op": "index", "id": "new", "source": {}}]
    t2.close()


def test_gateway_persists_closed_state_and_dynamic_settings(tmp_path):
    from elasticsearch_tpu.cluster.metadata import (
        IndexClosedException,
        close_index,
        update_index_settings,
    )
    from elasticsearch_tpu.node import Node

    n = Node(data_path=str(tmp_path))
    n.create_index("cs")
    update_index_settings(n.indices["cs"], {"index": {"number_of_replicas": 1}},
                          node=n)
    close_index(n, "cs")
    for s in n.indices.values():
        s.close()
    n2 = Node(data_path=str(tmp_path))
    assert n2.indices["cs"].closed
    assert n2.indices["cs"].num_replicas == 1
    with pytest.raises(IndexClosedException):
        n2.indices["cs"].index_doc("1", {"v": 1})


def test_closed_index_via_alias_raises():
    from elasticsearch_tpu.cluster.metadata import IndexClosedException, close_index
    from elasticsearch_tpu.node import Node

    n = Node()
    n.create_index("al1", {"aliases": {"myalias": {}}})
    close_index(n, "al1")
    with pytest.raises(IndexClosedException):
        n.search("myalias", {"size": 0})
    for s in n.indices.values():
        s.close()


def test_replica_translog_does_not_accumulate():
    svc = IndexService("notl", settings={"index": {"number_of_replicas": 1}})
    for i in range(30):
        svc.index_doc(str(i), {"v": i})
    replica = svc.groups[0].replicas[0]
    assert replica.engine.translog.size_in_ops == 0  # no per-op log on replicas
    svc.close()


def test_engine_recovery_through_v2_translog(tmp_path):
    s = IndexService("rec2", data_path=str(tmp_path))
    for i in range(20):
        s.index_doc(str(i), {"v": i})
    s.delete_doc("5")
    s.close()
    s2 = IndexService("rec2", data_path=str(tmp_path))
    assert s2.num_docs == 19
    assert s2.get_doc("7")["found"]
    assert not s2.get_doc("5")["found"]
    s2.close()
