import math

import numpy as np
import pytest

from elasticsearch_tpu.analysis.registry import AnalysisRegistry
from elasticsearch_tpu.index.doc_parser import DocumentParser
from elasticsearch_tpu.index.mappings import Mappings
from elasticsearch_tpu.index.segment import SegmentBuilder, K1, B, split_i64
from elasticsearch_tpu.utils.shapes import pow2_bucket

DOCS = [
    "the quick brown fox jumps over the lazy dog",
    "quick brown foxes leap over lazy dogs in summer",
    "the rain in spain stays mainly in the plain",
    "quick wit beats slow brawn",
    "dogs and cats living together",
]


def build_segment(docs=DOCS, analyzer="standard"):
    mappings = Mappings({"properties": {"body": {"type": "text", "analyzer": analyzer}}})
    reg = AnalysisRegistry()
    parser = DocumentParser(mappings, reg)
    builder = SegmentBuilder(mappings)
    for i, text in enumerate(docs):
        builder.add(parser.parse(str(i), {"body": text}))
    return builder.freeze(), reg


def bm25_oracle(docs, query_terms, analyzer_tokens):
    """Independent BM25 (Lucene 5 formula) in pure python."""
    toks = [analyzer_tokens(d) for d in docs]
    N = len(docs)
    avg = sum(len(t) for t in toks) / N
    scores = [0.0] * N
    for term in query_terms:
        df = sum(1 for t in toks if term in t)
        if df == 0:
            continue
        idf = math.log(1 + (N - df + 0.5) / (df + 0.5))
        for i, t in enumerate(toks):
            tf = t.count(term)
            if tf == 0:
                continue
            tfn = tf * (K1 + 1) / (tf + K1 * (1 - B + B * len(t) / avg))
            scores[i] += idf * tfn
    return scores


def test_segment_structure():
    seg, _ = build_segment()
    assert seg.num_docs == 5
    assert seg.max_docs == 64
    inv = seg.inverted["body"]
    assert inv.vocab["quick"] >= 0
    assert int(inv.df[inv.vocab["quick"]]) == 3
    assert int(inv.df[inv.vocab["the"]]) == 2
    start, ln = inv.term_slice("quick")
    docs = np.asarray(inv.doc_ids)[start : start + ln]
    assert sorted(docs.tolist()) == [0, 1, 3]


def test_bm25_matches_oracle():
    from elasticsearch_tpu.ops.scoring import bm25_score_segment

    seg, reg = build_segment()
    inv = seg.inverted["body"]
    an = reg.get("standard")
    qterms = ["quick", "dogs"]
    starts, lens, weights = [], [], []
    for t in qterms:
        s, ln = inv.term_slice(t)
        starts.append(s)
        lens.append(ln)
        weights.append(inv.idf(t))
    P = pow2_bucket(max(lens))
    scores = bm25_score_segment(
        inv.doc_ids,
        inv.tfnorm,
        np.array(starts, np.int32),
        np.array(lens, np.int32),
        np.array(weights, np.float32),
        P=P,
        D=seg.max_docs,
    )
    got = np.asarray(scores)[: seg.num_docs]
    want = bm25_oracle(DOCS, qterms, lambda d: an.tokens(d))
    np.testing.assert_allclose(got, want, rtol=1e-5)


def test_bm25_chunk_splitting_equivalence():
    """A term split into 2 chunks must score identically to 1 chunk."""
    from elasticsearch_tpu.ops.scoring import bm25_score_segment

    seg, _ = build_segment()
    inv = seg.inverted["body"]
    s, ln = inv.term_slice("quick")
    assert ln == 3
    w = inv.idf("quick")
    one = bm25_score_segment(
        inv.doc_ids, inv.tfnorm,
        np.array([s], np.int32), np.array([ln], np.int32), np.array([w], np.float32),
        P=4, D=seg.max_docs,
    )
    two = bm25_score_segment(
        inv.doc_ids, inv.tfnorm,
        np.array([s, s + 2], np.int32), np.array([2, 1], np.int32),
        np.array([w, w], np.float32),
        P=2, D=seg.max_docs,
    )
    np.testing.assert_allclose(np.asarray(one), np.asarray(two), rtol=1e-6)


def test_term_mask_and_topk():
    from elasticsearch_tpu.ops.scoring import term_mask, topk_with_mask, bm25_score_segment

    seg, _ = build_segment()
    inv = seg.inverted["body"]
    s, ln = inv.term_slice("dogs")
    mask = term_mask(
        inv.doc_ids, np.array([s], np.int32), np.array([ln], np.int32), P=8, D=seg.max_docs
    )
    m = np.asarray(mask)
    assert m[[1, 4]].all() and m.sum() == 2

    s2, l2 = inv.term_slice("quick")
    scores = bm25_score_segment(
        inv.doc_ids, inv.tfnorm,
        np.array([s2], np.int32), np.array([l2], np.int32),
        np.array([1.0], np.float32), P=8, D=seg.max_docs,
    )
    vals, idx = topk_with_mask(scores, mask & seg.live, k=3)
    vals, idx = np.asarray(vals), np.asarray(idx)
    assert idx[0] == 1 and vals[0] > 0
    assert vals[1] == 0.0 and idx[1] == 4  # filter-only match scores 0
    assert not np.isfinite(vals[2])  # no third match


def test_delete_updates_live_mask():
    seg, _ = build_segment()
    assert seg.delete_local(1)
    assert not seg.delete_local(1)
    assert seg.live_docs == 4
    assert not np.asarray(seg.live)[1]


def test_split_i64_order():
    vals = np.array([-(2**62), -1, 0, 1, 2**31, 2**62], dtype=np.int64)
    hi, lo = split_i64(vals)
    packed = list(zip(hi.tolist(), lo.tolist()))
    assert packed == sorted(packed)


def test_keyword_and_numeric_columns():
    mappings = Mappings(
        {
            "properties": {
                "tag": {"type": "keyword"},
                "n": {"type": "long"},
                "price": {"type": "double"},
            }
        }
    )
    reg = AnalysisRegistry()
    parser = DocumentParser(mappings, reg)
    b = SegmentBuilder(mappings)
    rows = [
        {"tag": "red", "n": 10, "price": 1.5},
        {"tag": "blue", "n": 2**40, "price": 2.5},
        {"tag": ["red", "green"], "n": -5},
    ]
    for i, r in enumerate(rows):
        b.add(parser.parse(str(i), r))
    seg = b.freeze()
    kw = seg.keywords["tag"]
    inv = seg.inverted["tag"]
    assert inv.terms == ["blue", "green", "red"]
    s, ln = inv.term_slice("red")
    assert sorted(np.asarray(inv.doc_ids)[s : s + ln].tolist()) == [0, 2]
    assert np.asarray(kw.ords)[1] == 0  # "blue"
    col = seg.numerics["n"]
    assert col.exact[1] == 2**40
    assert col.hi is not None
    pr = seg.numerics["price"]
    assert np.asarray(pr.exists)[:3].tolist() == [True, True, False]


def test_knn_ops_match_numpy():
    from elasticsearch_tpu.ops.knn import knn_topk, knn_topk_chunked

    rng = np.random.default_rng(0)
    D, dims, Q, k = 256, 32, 4, 5
    vecs = rng.standard_normal((D, dims)).astype(np.float32)
    queries = rng.standard_normal((Q, dims)).astype(np.float32)
    mask = np.ones(D, dtype=bool)

    vals, idx = knn_topk(queries, vecs, mask, k=k, metric="cosine", use_bf16=False)
    qn = queries / np.linalg.norm(queries, axis=1, keepdims=True)
    vn = vecs / np.linalg.norm(vecs, axis=1, keepdims=True)
    sim = (1 + qn @ vn.T) / 2
    want_idx = np.argsort(-sim, axis=1)[:, :k]
    assert (np.asarray(idx) == want_idx).mean() > 0.95  # ties may reorder

    cvals, cidx = knn_topk_chunked(queries, vecs, mask, k=k, metric="cosine", chunk=64, use_bf16=False)
    np.testing.assert_allclose(np.sort(np.asarray(cvals)), np.sort(np.asarray(vals)), rtol=1e-5)


def test_knn_l2_and_dot():
    from elasticsearch_tpu.ops.knn import knn_scores

    rng = np.random.default_rng(1)
    vecs = rng.standard_normal((16, 8)).astype(np.float32)
    q = rng.standard_normal((2, 8)).astype(np.float32)
    s = np.asarray(knn_scores(q, vecs, metric="l2_norm", use_bf16=False))
    d2 = ((q[:, None, :] - vecs[None, :, :]) ** 2).sum(-1)
    np.testing.assert_allclose(s, 1 / (1 + d2), rtol=2e-3, atol=1e-4)
    sd = np.asarray(knn_scores(q, vecs, metric="dot_product", use_bf16=False))
    np.testing.assert_allclose(sd, (1 + q @ vecs.T) / 2, rtol=1e-4)


def test_hybrid_dense_sparse_matches_pure_scatter():
    """Hybrid (dense matmul + scatter tail) == pure scatter == numpy oracle
    on a synthetic corpus large enough to produce dense rows."""
    from elasticsearch_tpu.index.segment import build_dense_impact
    from elasticsearch_tpu.ops.scoring import (
        bm25_score_hybrid,
        bm25_score_hybrid_batch,
        bm25_score_segment,
        match_count_hybrid,
        term_mask,
        term_mask_hybrid,
    )

    rng = np.random.default_rng(7)
    n_docs, vocab = 512, 64
    D = pow2_bucket(n_docs)
    # zipf-ish postings: term t appears in ~n_docs/(t+1) docs
    doc_lists = [
        np.sort(rng.choice(n_docs, size=max(1, n_docs // (t + 1)), replace=False))
        for t in range(vocab)
    ]
    df = np.array([len(d) for d in doc_lists], np.int32)
    offsets = np.zeros(vocab + 1, np.int64)
    offsets[1:] = np.cumsum(df)
    nnz = int(df.sum())
    u_doc = np.concatenate(doc_lists).astype(np.int32)
    tfn = rng.random(nnz).astype(np.float32) + 0.5

    block = build_dense_impact(u_doc, tfn, offsets, df, D, df_threshold=64)
    assert block is not None
    dense_rows, impact = block
    assert (dense_rows >= 0).sum() > 0 and (dense_rows < 0).sum() > 0

    nnz_pad = pow2_bucket(nnz)
    d_doc = np.full(nnz_pad, D, np.int32)
    d_doc[:nnz] = u_doc
    d_tfn = np.zeros(nnz_pad, np.float32)
    d_tfn[:nnz] = tfn

    qterms = [0, 1, 40, 63]  # mix of dense (frequent) + sparse (rare) terms
    weights = [1.5, 0.7, 2.0, 1.1]
    F = impact.shape[0]
    qw = np.zeros(F, np.float32)
    qind = np.zeros(F, np.float32)
    runs = []
    for t, w in zip(qterms, weights):
        row = int(dense_rows[t])
        if row >= 0:
            qw[row] += w
            qind[row] = 1.0
        else:
            runs.append((int(offsets[t]), int(df[t]), w))
    P = pow2_bucket(max((ln for _, ln, _ in runs), default=1))
    T = pow2_bucket(max(len(runs), 1))
    starts = np.zeros(T, np.int32)
    lens = np.zeros(T, np.int32)
    ws = np.zeros(T, np.float32)
    for i, (s, ln, w) in enumerate(runs):
        starts[i], lens[i], ws[i] = s, ln, w

    # oracle
    want = np.zeros(D, np.float32)
    for t, w in zip(qterms, weights):
        s, e = int(offsets[t]), int(offsets[t + 1])
        want[u_doc[s:e]] += w * tfn[s:e]

    got_h = bm25_score_hybrid(
        impact, qw, d_doc, d_tfn, starts, lens, ws, P=P, D=D)
    counts = match_count_hybrid(impact, qind, d_doc, starts, lens, P=P, D=D)
    np.testing.assert_allclose(np.asarray(got_h), want, rtol=1e-5, atol=1e-5)

    got_b = bm25_score_hybrid_batch(
        impact, qw[None], d_doc, d_tfn, starts[None], lens[None], ws[None], P=P, D=D)
    np.testing.assert_allclose(np.asarray(got_b)[0], want, rtol=1e-5, atol=1e-5)

    # pure scatter path on the same query (all terms as runs)
    all_runs = [(int(offsets[t]), int(df[t]), w) for t, w in zip(qterms, weights)]
    P2 = pow2_bucket(max(ln for _, ln, _ in all_runs))
    st2 = np.array([r[0] for r in all_runs], np.int32)
    ln2 = np.array([r[1] for r in all_runs], np.int32)
    ws2 = np.array([r[2] for r in all_runs], np.float32)
    got_s = bm25_score_segment(d_doc, d_tfn, st2, ln2, ws2, P=P2, D=D)
    np.testing.assert_allclose(np.asarray(got_s), want, rtol=1e-5, atol=1e-5)

    # matched-term counts
    want_counts = np.zeros(D, np.int64)
    for t in qterms:
        s, e = int(offsets[t]), int(offsets[t + 1])
        want_counts[u_doc[s:e]] += 1
    np.testing.assert_array_equal(np.asarray(counts), want_counts)

    # any-of mask
    got_m = term_mask_hybrid(impact, qind, d_doc, starts, lens, P=P, D=D)
    np.testing.assert_array_equal(np.asarray(got_m), want_counts > 0)
    got_m2 = term_mask(d_doc, st2, ln2, P=P2, D=D)
    np.testing.assert_array_equal(np.asarray(got_m2), want_counts > 0)


def test_segment_dense_block_lazy():
    """Small segments have no qualifying terms -> dense_block() is None and
    cached as absent; query path falls back to pure scatter."""
    seg, _ = build_segment()
    inv = seg.inverted["body"]
    assert inv.dense_block() is None
    assert inv._dense is False


def test_exact_topk_matches_lax_including_ties():
    """Blocked two-stage top-k must be bit-identical to lax.top_k —
    values AND indices — including tie resolution (lowest index wins),
    1-D and batched, with non-finite entries present."""
    import jax.numpy as jnp
    import numpy as np
    from jax import lax

    from elasticsearch_tpu.ops.scoring import exact_topk

    rng = np.random.default_rng(11)
    for shape in ((8192,), (4, 8192)):
        # quantized values force many exact ties across blocks
        x = np.round(rng.standard_normal(shape) * 3).astype(np.float32)
        x[..., :7] = -np.inf  # masked entries
        xj = jnp.asarray(x)
        for k in (1, 10, 64):
            gv, gi = exact_topk(xj, k, block=1024)
            lv, li = lax.top_k(xj, k)
            assert np.array_equal(np.asarray(gv), np.asarray(lv)), (shape, k)
            assert np.array_equal(np.asarray(gi), np.asarray(li)), (shape, k)
    # fallback shapes route to plain lax.top_k
    x = jnp.asarray(rng.standard_normal(100).astype(np.float32))
    gv, gi = exact_topk(x, 5, block=1024)
    lv, li = lax.top_k(x, 5)
    assert np.array_equal(np.asarray(gv), np.asarray(lv))
    assert np.array_equal(np.asarray(gi), np.asarray(li))


def test_blocked_topk_env_product_equivalence(monkeypatch):
    """ESTPU_BLOCKED_TOPK must leave product search results identical —
    it only re-stages the top-k selection. A SMALL block (64) with a
    600-doc corpus (padded D=1024 >= 2*block, divisible) guarantees the
    blocked path actually executes, and the block is a STATIC part of
    every program/jit cache key, so flag-on and flag-off runs can share
    one process without stale-program contamination."""
    import random

    from elasticsearch_tpu.node import Node
    from elasticsearch_tpu.ops.scoring import topk_block_config

    rng = random.Random(5)
    words = ["alpha", "beta", "gamma", "delta"]
    docs = {str(i): {"body": " ".join(rng.choices(words, k=5))}
            for i in range(600)}

    def run():
        n = Node()
        try:
            n.create_index("bt", {"settings": {"number_of_shards": 1},
                                  "mappings": {"properties": {
                                      "body": {"type": "text"}}}})
            for i, src in docs.items():
                n.indices["bt"].index_doc(i, src)
            n.indices["bt"].refresh()
            seg = n.indices["bt"].shards[0].engine.segments[0]
            assert seg.max_docs >= 2 * 64  # the blocked path really runs
            return n.search("bt", {"query": {"match": {"body": "alpha"}},
                                   "size": 10})
        finally:
            n.close()

    monkeypatch.setenv("ESTPU_BLOCKED_TOPK", "64")
    assert topk_block_config() == 64
    r1 = run()
    monkeypatch.delenv("ESTPU_BLOCKED_TOPK")
    assert topk_block_config() == 0
    r2 = run()
    assert r1["hits"]["total"] == r2["hits"]["total"] > 0
    assert [(h["_id"], round(h["_score"], 5)) for h in r1["hits"]["hits"]] \
        == [(h["_id"], round(h["_score"], 5)) for h in r2["hits"]["hits"]]


def test_impact_precision_knob(monkeypatch):
    """ESTPU_IMPACT_PRECISION plumbs as a static arg (cache-key safe) and
    serves identical results on CPU, where precision hints are no-ops;
    a bad value warns once and falls back to highest."""
    import warnings

    from elasticsearch_tpu.node import Node
    from elasticsearch_tpu.ops import scoring

    monkeypatch.setattr(scoring, "_PREC_WARNED", False)
    monkeypatch.setenv("ESTPU_IMPACT_PRECISION", "turbo")
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        assert scoring.impact_precision() == "highest"
        assert len(w) == 1 and "turbo" in str(w[0].message)

    import random

    rng = random.Random(7)
    docs = {str(i): {"body": " ".join(rng.choices(
        ["ant", "bee", "cat", "dog"], k=6))} for i in range(300)}
    results = []
    for prec in ("highest", "default"):
        monkeypatch.setenv("ESTPU_IMPACT_PRECISION", prec)
        n = Node()
        try:
            n.create_index("ip", {"mappings": {"properties": {
                "body": {"type": "text"}}}})
            for i, src in docs.items():
                n.indices["ip"].index_doc(i, src)
            n.indices["ip"].refresh()
            r = n.search("ip", {"query": {"match": {"body": "ant bee"}},
                                "size": 10})
            results.append([(h["_id"], round(h["_score"], 5))
                            for h in r["hits"]["hits"]])
        finally:
            n.close()
    assert results[0] == results[1]


def test_gather_hybrid_matches_matmul_hybrid():
    """The row-gather single-query forms (bm25_score_hybrid_gather /
    match_count_hybrid_gather / term_mask_hybrid_gather) produce the same
    scores/counts/masks as the full-block matmul forms — they read only
    the query's R dense rows where the matmul reads all F (the r5
    single-query latency lever)."""
    from elasticsearch_tpu.index.segment import build_dense_impact
    from elasticsearch_tpu.ops.scoring import (
        bm25_score_hybrid, bm25_score_hybrid_gather, match_count_hybrid,
        match_count_hybrid_gather, pack_dense_rows, term_mask_hybrid,
        term_mask_hybrid_gather)

    rng = np.random.default_rng(11)
    n_docs, vocab = 512, 64
    D = pow2_bucket(n_docs)
    doc_lists = [
        np.sort(rng.choice(n_docs, size=max(1, n_docs // (t + 1)),
                           replace=False))
        for t in range(vocab)
    ]
    df = np.array([len(d) for d in doc_lists], np.int32)
    offsets = np.zeros(vocab + 1, np.int64)
    offsets[1:] = np.cumsum(df)
    nnz = int(df.sum())
    u_doc = np.concatenate(doc_lists).astype(np.int32)
    tfn = rng.random(nnz).astype(np.float32) + 0.5
    block = build_dense_impact(u_doc, tfn, offsets, df, D, df_threshold=64)
    dense_rows, impact = block
    nnz_pad = pow2_bucket(nnz)
    d_doc = np.full(nnz_pad, D, np.int32)
    d_doc[:nnz] = u_doc
    d_tfn = np.zeros(nnz_pad, np.float32)
    d_tfn[:nnz] = tfn

    qterms = [0, 1, 2, 40, 63]
    weights = [1.5, 0.7, 0.9, 2.0, 1.1]
    F = impact.shape[0]
    qw = np.zeros(F, np.float32)
    qind = np.zeros(F, np.float32)
    row_w = {}
    runs = []
    for t, w in zip(qterms, weights):
        row = int(dense_rows[t])
        if row >= 0:
            qw[row] += w
            qind[row] = 1.0
            row_w[row] = row_w.get(row, 0.0) + w
        else:
            runs.append((int(offsets[t]), int(df[t]), w))
    assert row_w and runs  # the query must exercise BOTH halves
    qrows, qrw = pack_dense_rows(row_w)
    assert qrows.shape[0] >= 8 and (qrows < 0).any()  # padded
    P = pow2_bucket(max(ln for _, ln, _ in runs))
    T = pow2_bucket(len(runs))
    starts = np.zeros(T, np.int32)
    lens = np.zeros(T, np.int32)
    ws = np.zeros(T, np.float32)
    for i, (s, ln, w) in enumerate(runs):
        starts[i], lens[i], ws[i] = s, ln, w

    want = np.asarray(bm25_score_hybrid(
        impact, qw, d_doc, d_tfn, starts, lens, ws, P=P, D=D))
    got = np.asarray(bm25_score_hybrid_gather(
        impact, qrows, qrw, d_doc, d_tfn, starts, lens, ws, P=P, D=D))
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)

    want_c = np.asarray(match_count_hybrid(
        impact, qind, d_doc, starts, lens, P=P, D=D))
    got_c = np.asarray(match_count_hybrid_gather(
        impact, qrows, d_doc, starts, lens, P=P, D=D))
    np.testing.assert_array_equal(got_c, want_c)

    want_m = np.asarray(term_mask_hybrid(
        impact, qind, d_doc, starts, lens, P=P, D=D))
    got_m = np.asarray(term_mask_hybrid_gather(
        impact, qrows, d_doc, starts, lens, P=P, D=D))
    np.testing.assert_array_equal(got_m, want_m)


def test_candidates_topk_matches_scatter_path():
    """bm25_hybrid_candidates_topk (scatter-free) == dense scatter path
    (score vector + masked top-k + count) — across duplicate tail docs,
    dense/tail overlap, dead docs, chunk-split runs, and exact ties."""
    import jax.numpy as jnp

    from elasticsearch_tpu.index.segment import build_dense_impact
    from elasticsearch_tpu.ops.scoring import (
        bm25_hybrid_candidates_topk, bm25_score_hybrid_gather,
        pack_dense_rows, topk_with_mask)

    rng = np.random.default_rng(23)
    n_docs, vocab, k = 512, 64, 10
    D = pow2_bucket(n_docs)
    doc_lists = [
        np.sort(rng.choice(n_docs, size=max(1, n_docs // (t + 1)),
                           replace=False))
        for t in range(vocab)
    ]
    df = np.array([len(d) for d in doc_lists], np.int32)
    offsets = np.zeros(vocab + 1, np.int64)
    offsets[1:] = np.cumsum(df)
    nnz = int(df.sum())
    u_doc = np.concatenate(doc_lists).astype(np.int32)
    tfn = rng.random(nnz).astype(np.float32) + 0.5
    tfn = (tfn * 8).round() / 8  # quantize -> exact ties exist
    block = build_dense_impact(u_doc, tfn, offsets, df, D, df_threshold=64)
    dense_rows, impact = block
    nnz_pad = pow2_bucket(nnz)
    d_doc = np.full(nnz_pad, D, np.int32)
    d_doc[:nnz] = u_doc
    d_tfn = np.zeros(nnz_pad, np.float32)
    d_tfn[:nnz] = tfn
    live = np.ones(D, bool)
    live[n_docs:] = False
    live[rng.choice(n_docs, 40, replace=False)] = False  # dead docs

    for trial, qterms in enumerate([[0, 1, 40, 41, 63],  # overlap-heavy
                                    [50, 60, 63],        # tail-only
                                    [0, 1],              # dense-only
                                    [0, 30, 31, 32, 60, 61, 62, 63]]):
        weights = [float(1.0 + 0.5 * i) for i in range(len(qterms))]
        row_w = {}
        runs = []
        for t, w in zip(qterms, weights):
            row = int(dense_rows[t])
            if row >= 0:
                row_w[row] = row_w.get(row, 0.0) + w
            else:
                runs.append((int(offsets[t]), int(df[t]), w))
        if not row_w:
            continue  # hybrid paths require >= 1 dense term
        qrows, qrw = pack_dense_rows(row_w)
        from elasticsearch_tpu.search.context import split_runs
        starts_l, lens_l, ws_l, max_len = (split_runs(runs) if runs
                                           else ([], [], [], 1))
        P = pow2_bucket(max_len)
        T = pow2_bucket(max(len(starts_l), 1))
        starts = np.zeros(T, np.int32)
        lens = np.zeros(T, np.int32)
        ws = np.zeros(T, np.float32)
        for i, (s, ln, w) in enumerate(zip(starts_l, lens_l, ws_l)):
            starts[i], lens[i], ws[i] = s, ln, w

        # reference: full scatter score vector -> masked topk + count
        scores = np.asarray(bm25_score_hybrid_gather(
            impact, qrows, qrw, d_doc, d_tfn, starts, lens, ws, P=P, D=D))
        m = (scores > 0) & live
        wv, wi = topk_with_mask(jnp.asarray(scores),
                                jnp.asarray(m), k=k)
        want_total = int(m.sum())

        gv, gi, gt = bm25_hybrid_candidates_topk(
            impact, qrows, qrw, d_doc, d_tfn, starts, lens, ws,
            jnp.asarray(live), P=P, D=D, k=k, topk_block=0)
        np.testing.assert_allclose(np.asarray(gv), np.asarray(wv),
                                   rtol=2e-5, atol=2e-5,
                                   err_msg=f"trial {trial} vals")
        finite = np.isfinite(np.asarray(wv))
        np.testing.assert_array_equal(np.asarray(gi)[finite],
                                      np.asarray(wi)[finite],
                                      err_msg=f"trial {trial} ids")
        assert int(gt) == want_total, (trial, int(gt), want_total)


def test_candidates_topk_batch_matches_scatter_batch():
    """bm25_hybrid_candidates_topk_batch == bm25_hybrid_topk_batch across
    a mixed batch (per-query different dense/tail splits, ties, dupes)."""
    import jax.numpy as jnp

    from elasticsearch_tpu.index.segment import build_dense_impact
    from elasticsearch_tpu.ops.scoring import (
        bm25_hybrid_candidates_topk_batch, bm25_hybrid_topk_batch)
    from elasticsearch_tpu.search.context import split_runs

    rng = np.random.default_rng(31)
    n_docs, vocab, k = 512, 64, 10
    D = pow2_bucket(n_docs)
    doc_lists = [
        np.sort(rng.choice(n_docs, size=max(1, n_docs // (t + 1)),
                           replace=False))
        for t in range(vocab)
    ]
    df = np.array([len(d) for d in doc_lists], np.int32)
    offsets = np.zeros(vocab + 1, np.int64)
    offsets[1:] = np.cumsum(df)
    nnz = int(df.sum())
    u_doc = np.concatenate(doc_lists).astype(np.int32)
    tfn = ((rng.random(nnz) + 0.5) * 8).round().astype(np.float32) / 8
    block = build_dense_impact(u_doc, tfn, offsets, df, D, df_threshold=64)
    dense_rows, impact = block
    F = impact.shape[0]
    nnz_pad = pow2_bucket(nnz)
    d_doc = np.full(nnz_pad, D, np.int32)
    d_doc[:nnz] = u_doc
    d_tfn = np.zeros(nnz_pad, np.float32)
    d_tfn[:nnz] = tfn
    live = np.ones(D, bool)
    live[n_docs:] = False
    live[rng.choice(n_docs, 30, replace=False)] = False

    batches = [[0, 1, 40, 63], [0, 50, 60], [1, 2], [30, 31, 62, 63],
               [0, 1, 2, 3, 60, 61]]
    qw = np.zeros((len(batches), F), np.float32)
    all_runs = []
    Pmax, Tmax = 1, 1
    for qi, qterms in enumerate(batches):
        runs = []
        for i, t in enumerate(qterms):
            w = 1.0 + 0.3 * i
            row = int(dense_rows[t])
            if row >= 0:
                qw[qi, row] += w
            else:
                runs.append((int(offsets[t]), int(df[t]), w))
        st, ln, ws_, mx = split_runs(runs) if runs else ([], [], [], 1)
        Pmax = max(Pmax, pow2_bucket(mx))
        Tmax = max(Tmax, len(st))
        all_runs.append((st, ln, ws_))
    T = pow2_bucket(max(Tmax, 1))
    starts = np.zeros((len(batches), T), np.int32)
    lens = np.zeros((len(batches), T), np.int32)
    ws = np.zeros((len(batches), T), np.float32)
    for qi, (st, ln, ws_) in enumerate(all_runs):
        starts[qi, :len(st)] = st
        lens[qi, :len(ln)] = ln
        ws[qi, :len(ws_)] = ws_

    wv, wi, wt = bm25_hybrid_topk_batch(
        impact, jnp.asarray(qw), d_doc, d_tfn, jnp.asarray(starts),
        jnp.asarray(lens), jnp.asarray(ws), jnp.asarray(live),
        P=Pmax, D=D, k=k, topk_block=0)
    gv, gi, gt = bm25_hybrid_candidates_topk_batch(
        impact, jnp.asarray(qw), d_doc, d_tfn, jnp.asarray(starts),
        jnp.asarray(lens), jnp.asarray(ws), jnp.asarray(live),
        P=Pmax, D=D, k=k, topk_block=0)
    np.testing.assert_allclose(np.asarray(gv), np.asarray(wv),
                               rtol=2e-5, atol=2e-5)
    finite = np.isfinite(np.asarray(wv))
    np.testing.assert_array_equal(np.asarray(gi)[finite],
                                  np.asarray(wi)[finite])
    np.testing.assert_array_equal(np.asarray(gt), np.asarray(wt))


def test_lookup_tail_matches_scatter_forms():
    """The scatter-free lookup forms produce identical [D] vectors to the
    scatter kernels (scores/counts/masks), including duplicate docs
    across terms and chunk-split runs."""
    from elasticsearch_tpu.ops.scoring import (
        bm25_score_segment, bm25_score_segment_lookup,
        match_count_segment, match_count_segment_lookup, term_mask,
        term_mask_lookup)
    from elasticsearch_tpu.search.context import split_runs

    rng = np.random.default_rng(41)
    n_docs, vocab = 512, 32
    D = pow2_bucket(n_docs)
    doc_lists = [
        np.sort(rng.choice(n_docs, size=max(1, n_docs // (t + 1)),
                           replace=False))
        for t in range(vocab)
    ]
    df = np.array([len(d) for d in doc_lists], np.int32)
    offsets = np.zeros(vocab + 1, np.int64)
    offsets[1:] = np.cumsum(df)
    nnz = int(df.sum())
    u_doc = np.concatenate(doc_lists).astype(np.int32)
    tfn = rng.random(nnz).astype(np.float32) + 0.5
    nnz_pad = pow2_bucket(nnz)
    d_doc = np.full(nnz_pad, D, np.int32)
    d_doc[:nnz] = u_doc
    d_tfn = np.zeros(nnz_pad, np.float32)
    d_tfn[:nnz] = tfn

    for qterms in ([0, 1, 5, 30], [2], [0, 1, 2, 3, 4, 5, 6, 7]):
        runs = [(int(offsets[t]), int(df[t]), 1.0 + 0.25 * i)
                for i, t in enumerate(qterms)]
        st, ln, ws_, mx = split_runs(runs)
        P = pow2_bucket(mx)
        T = pow2_bucket(len(st))
        starts = np.zeros(T, np.int32)
        lens = np.zeros(T, np.int32)
        ws = np.zeros(T, np.float32)
        for i, (s, l, w) in enumerate(zip(st, ln, ws_)):
            starts[i], lens[i], ws[i] = s, l, w
        want = np.asarray(bm25_score_segment(
            d_doc, d_tfn, starts, lens, ws, P=P, D=D))
        got = np.asarray(bm25_score_segment_lookup(
            d_doc, d_tfn, starts, lens, ws, P=P, D=D))
        np.testing.assert_allclose(got, want, rtol=1e-6, atol=1e-6)
        want_c = np.asarray(match_count_segment(
            d_doc, starts, lens, P=P, D=D))
        got_c = np.asarray(match_count_segment_lookup(
            d_doc, starts, lens, P=P, D=D))
        np.testing.assert_array_equal(got_c, want_c)
        want_m = np.asarray(term_mask(d_doc, starts, lens, P=P, D=D))
        got_m = np.asarray(term_mask_lookup(d_doc, starts, lens, P=P, D=D))
        np.testing.assert_array_equal(got_m, want_m)


def test_hybrid_lookup_matches_hybrid_gather():
    """The *_hybrid_lookup forms (scatter-free tail) == *_hybrid_gather
    (scatter tail) for scores, counts, and masks."""
    from elasticsearch_tpu.index.segment import build_dense_impact
    from elasticsearch_tpu.ops.scoring import (
        bm25_score_hybrid_gather, bm25_score_hybrid_lookup,
        match_count_hybrid_gather, match_count_hybrid_lookup,
        pack_dense_rows, term_mask_hybrid_gather, term_mask_hybrid_lookup)
    from elasticsearch_tpu.search.context import split_runs

    rng = np.random.default_rng(47)
    n_docs, vocab = 512, 64
    D = pow2_bucket(n_docs)
    doc_lists = [
        np.sort(rng.choice(n_docs, size=max(1, n_docs // (t + 1)),
                           replace=False))
        for t in range(vocab)
    ]
    df = np.array([len(d) for d in doc_lists], np.int32)
    offsets = np.zeros(vocab + 1, np.int64)
    offsets[1:] = np.cumsum(df)
    nnz = int(df.sum())
    u_doc = np.concatenate(doc_lists).astype(np.int32)
    tfn = rng.random(nnz).astype(np.float32) + 0.5
    block = build_dense_impact(u_doc, tfn, offsets, df, D, df_threshold=64)
    dense_rows, impact = block
    nnz_pad = pow2_bucket(nnz)
    d_doc = np.full(nnz_pad, D, np.int32)
    d_doc[:nnz] = u_doc
    d_tfn = np.zeros(nnz_pad, np.float32)
    d_tfn[:nnz] = tfn

    qterms = [0, 1, 2, 40, 63]
    row_w = {}
    runs = []
    for i, t in enumerate(qterms):
        w = 1.0 + 0.5 * i
        row = int(dense_rows[t])
        if row >= 0:
            row_w[row] = row_w.get(row, 0.0) + w
        else:
            runs.append((int(offsets[t]), int(df[t]), w))
    assert row_w and runs
    qrows, qrw = pack_dense_rows(row_w)
    st, ln, ws_, mx = split_runs(runs)
    P = pow2_bucket(mx)
    T = pow2_bucket(len(st))
    starts = np.zeros(T, np.int32)
    lens = np.zeros(T, np.int32)
    ws = np.zeros(T, np.float32)
    for i, (s, l, w) in enumerate(zip(st, ln, ws_)):
        starts[i], lens[i], ws[i] = s, l, w

    want = np.asarray(bm25_score_hybrid_gather(
        impact, qrows, qrw, d_doc, d_tfn, starts, lens, ws, P=P, D=D))
    got = np.asarray(bm25_score_hybrid_lookup(
        impact, qrows, qrw, d_doc, d_tfn, starts, lens, ws, P=P, D=D))
    np.testing.assert_allclose(got, want, rtol=1e-6, atol=1e-6)
    want_c = np.asarray(match_count_hybrid_gather(
        impact, qrows, d_doc, starts, lens, P=P, D=D))
    got_c = np.asarray(match_count_hybrid_lookup(
        impact, qrows, d_doc, starts, lens, P=P, D=D))
    np.testing.assert_array_equal(got_c, want_c)
    want_m = np.asarray(term_mask_hybrid_gather(
        impact, qrows, d_doc, starts, lens, P=P, D=D))
    got_m = np.asarray(term_mask_hybrid_lookup(
        impact, qrows, d_doc, starts, lens, P=P, D=D))
    np.testing.assert_array_equal(got_m, want_m)
