"""Rescore, search template, and warmer tests (reference:
search/rescore/QueryRescorer, script/mustache, search/warmer)."""
import pytest

from elasticsearch_tpu.index.index_service import IndexService
from elasticsearch_tpu.search.templates import render_template
from elasticsearch_tpu.utils.errors import SearchParseException


@pytest.fixture()
def svc():
    s = IndexService("r", mappings_json={"properties": {
        "body": {"type": "text"},
        "tag": {"type": "keyword"},
        "rank": {"type": "long"},
    }})
    s.index_doc("1", {"body": "quick fox", "tag": "a", "rank": 1})
    s.index_doc("2", {"body": "quick quick fox", "tag": "b", "rank": 2})
    s.index_doc("3", {"body": "quick brown wolf", "tag": "a", "rank": 3})
    for sh in s.shards:
        sh.refresh()
    yield s
    s.close()


def test_rescore_total_reorders_window(svc):
    base = {"query": {"match": {"body": "quick"}}, "rescore": {
        "window_size": 10,
        "query": {
            "rescore_query": {"term": {"tag": "a"}},
            "query_weight": 0.0,
            "rescore_query_weight": 10.0,
        },
    }}
    resp = svc.search(base)
    top2 = {h["_id"] for h in resp["hits"]["hits"][:2]}
    assert top2 == {"1", "3"}  # tag:a docs boosted above the bm25 winner


def test_rescore_multiply_keeps_nonmatching_scores(svc):
    resp0 = svc.search({"query": {"match": {"body": "quick"}}})
    orig = {h["_id"]: h["_score"] for h in resp0["hits"]["hits"]}
    resp = svc.search({"query": {"match": {"body": "quick"}}, "rescore": {
        "window_size": 10,
        "query": {"rescore_query": {"term": {"tag": "b"}},
                  "score_mode": "multiply"}}})
    got = {h["_id"]: h["_score"] for h in resp["hits"]["hits"]}
    # loose tolerance: the lazy dense-impact block may flip the BM25 path
    # from scatter to matmul between searches (different fp rounding)
    assert got["1"] == pytest.approx(orig["1"], rel=5e-2)  # non-matching unchanged
    assert got["2"] == pytest.approx(orig["2"] * 1.0, rel=5e-2)  # term filter scores 1.0


def test_rescore_window_limits_scope(svc):
    # window of 1: only the top doc is rescored; others keep their order
    resp = svc.search({"query": {"match": {"body": "quick"}}, "rescore": {
        "window_size": 1,
        "query": {"rescore_query": {"term": {"tag": "a"}},
                  "query_weight": 0.0, "rescore_query_weight": 5.0}}})
    assert len(resp["hits"]["hits"]) == 3


def test_render_template_scalars_and_tojson():
    out = render_template(
        {"query": {"match": {"{{field}}": "{{value}}"}}, "size": "{{size}}"},
        {"field": "body", "value": "quick fox", "size": 5})
    assert out == {"query": {"match": {"body": "quick fox"}}, "size": 5}

    out = render_template(
        '{"query": {"terms": {"tag": "{{#toJson}}tags{{/toJson}}"}}}',
        {"tags": ["a", "b"]})
    assert out == {"query": {"terms": {"tag": ["a", "b"]}}}


def test_render_template_missing_param_raises():
    with pytest.raises(SearchParseException):
        render_template({"q": "{{nope}}"}, {})


def test_template_search_end_to_end(svc):
    body = render_template(
        {"query": {"match": {"body": "{{q}}"}}}, {"q": "wolf"})
    resp = svc.search(body)
    assert [h["_id"] for h in resp["hits"]["hits"]] == ["3"]


def test_rescore_window_wider_than_size():
    s = IndexService("w")
    for i in range(20):
        s.index_doc(str(i), {"body": "common term", "rank": i})
    s.index_doc("special", {"body": "common term", "rank": 99, "tag": "boost"})
    for sh in s.shards:
        sh.refresh()
    # size=2 but window 50: the boosted doc must be promoted into the top 2
    resp = s.search({"query": {"match": {"body": "common"}}, "size": 2,
                     "rescore": {"window_size": 50, "query": {
                         "rescore_query": {"term": {"tag": "boost"}},
                         "query_weight": 1.0, "rescore_query_weight": 100.0}}})
    assert len(resp["hits"]["hits"]) == 2
    assert resp["hits"]["hits"][0]["_id"] == "special"
    s.close()


def test_render_template_literal_mustache_in_param():
    # a param VALUE containing {{...}} is data, not a placeholder
    out = render_template({"query": {"match": {"f": "{{q}}"}}},
                          {"q": "literal {{x}} text"})
    assert out == {"query": {"match": {"f": "literal {{x}} text"}}}


def test_percolate_total_not_truncated_by_size():
    s = IndexService("p")
    for i in range(5):
        s.index_doc(f"q{i}", {"query": {"match": {"m": "hit"}}},
                    doc_type=".percolator")
    r = s.percolate({"doc": {"m": "hit"}, "size": 2})
    assert r["total"] == 5 and len(r["matches"]) == 2
    s.close()


def test_invalid_percolator_doc_rejected_before_persist(tmp_path):
    import pytest as _pytest

    from elasticsearch_tpu.utils.errors import ElasticsearchTpuException

    s = IndexService("pp", data_path=str(tmp_path))
    with _pytest.raises(ElasticsearchTpuException):
        s.index_doc("bad", {"no_query": True}, doc_type=".percolator")
    with _pytest.raises(ElasticsearchTpuException):
        s.index_doc("bad2", {"query": {"frobnicate": {}}}, doc_type=".percolator")
    s.close()
    # recovery must come up clean — nothing bad was persisted
    s2 = IndexService("pp", data_path=str(tmp_path))
    assert len(s2.percolator) == 0
    assert s2.num_docs == 0
    s2.close()


def test_warmers_run_on_refresh(svc):
    svc.warmers["w1"] = {"query": {"match": {"body": "quick"}}}
    svc.index_doc("4", {"body": "quick badger", "tag": "c", "rank": 4})
    svc.refresh()  # must not raise; warmer pre-compiles the program
    resp = svc.search({"query": {"match": {"body": "badger"}}})
    assert resp["hits"]["total"] == 1
    # broken warmer never fails refresh
    svc.warmers["bad"] = {"query": {"frobnicate": {}}}
    svc.index_doc("5", {"body": "more text"})
    svc.refresh()
