"""Thread pools + global segment-HBM circuit breaker (round-2/3 verdict
item 5; reference: threadpool/ThreadPool.java:1-688,
common/breaker/CircuitBreaker.java:1-88)."""
import json
import threading
import urllib.request

import pytest

from elasticsearch_tpu.index import segment as seg_mod
from elasticsearch_tpu.index.segment import HbmBudget
from elasticsearch_tpu.node import Node
from elasticsearch_tpu.utils.errors import CircuitBreakingException
from elasticsearch_tpu.utils.threadpool import (EsRejectedExecutionException,
                                                FixedThreadPool, ThreadPool)


def test_fixed_pool_bounded_queue_rejects():
    pool = FixedThreadPool("t", size=1, queue_size=1)
    gate = threading.Event()
    started = threading.Event()

    def block():
        started.set()
        gate.wait(5)
        return "done"

    # occupy the single worker
    t1 = threading.Thread(target=lambda: pool.execute(block))
    t1.start()
    assert started.wait(5)
    # fill the queue slot
    t2 = threading.Thread(target=lambda: pool.execute(lambda: None))
    t2.start()
    import time

    for _ in range(100):  # wait until the queued item is actually enqueued
        if pool.stats()["queue"] >= 1:
            break
        time.sleep(0.01)
    # third submission: queue full → rejection
    with pytest.raises(EsRejectedExecutionException):
        pool.execute(lambda: None)
    assert pool.stats()["rejected"] == 1
    gate.set()
    t1.join(5)
    t2.join(5)
    assert pool.stats()["completed"] >= 2
    pool.shutdown()


def test_pool_propagates_result_and_errors():
    pool = FixedThreadPool("t2", size=2, queue_size=8)
    assert pool.execute(lambda a, b: a + b, 2, 3) == 5
    with pytest.raises(ValueError):
        pool.execute(lambda: (_ for _ in ()).throw(ValueError("boom")))
    pool.shutdown()


def test_threadpool_registry_sizing_and_stats():
    tp = ThreadPool(cores=4)
    assert tp.pools["search"].size == 3 * 4 // 2 + 1
    assert tp.pools["bulk"].queue_size == 50
    st = tp.stats()
    assert set(st) == {"search", "index", "bulk", "get", "management"}
    assert tp.execute("search", lambda: 42) == 42
    assert tp.pools["search"].stats()["completed"] == 1
    tp.shutdown()


def test_segment_breaker_trips_and_releases():
    old = seg_mod.SEGMENT_HBM_BUDGET
    seg_mod.SEGMENT_HBM_BUDGET = HbmBudget(total_bytes=1)  # trip immediately
    try:
        n = Node()
        n.create_index("cb", {})
        svc = n.indices["cb"]
        svc.index_doc("1", {"t": "hello world"})
        with pytest.raises(CircuitBreakingException):
            svc.refresh()
        # the doc stays buffered and searchable via realtime get
        assert svc.get_doc("1")["found"]
        n.close()
    finally:
        seg_mod.SEGMENT_HBM_BUDGET = old

    # generous budget: refresh charges, close releases
    old = seg_mod.SEGMENT_HBM_BUDGET
    seg_mod.SEGMENT_HBM_BUDGET = HbmBudget(total_bytes=64 << 20)
    try:
        n = Node()
        n.create_index("cb2", {})
        svc = n.indices["cb2"]
        for i in range(5):
            svc.index_doc(str(i), {"t": f"doc {i}"})
        svc.refresh()
        used_after = seg_mod.SEGMENT_HBM_BUDGET.used
        assert used_after > 0
        r = n.search("cb2", {"query": {"match_all": {}}})
        assert r["hits"]["total"] == 5
        n.close()
        assert seg_mod.SEGMENT_HBM_BUDGET.used == 0
    finally:
        seg_mod.SEGMENT_HBM_BUDGET = old


def test_merge_releases_old_charges():
    old = seg_mod.SEGMENT_HBM_BUDGET
    seg_mod.SEGMENT_HBM_BUDGET = HbmBudget(total_bytes=64 << 20)
    try:
        n = Node()
        n.create_index("mg", {})
        svc = n.indices["mg"]
        for i in range(8):
            svc.index_doc(str(i), {"t": f"word{i} common"})
            svc.refresh()
        before = seg_mod.SEGMENT_HBM_BUDGET.used
        svc.force_merge(1)
        after = seg_mod.SEGMENT_HBM_BUDGET.used
        assert after <= before  # merge nets memory down, never trips
        shard = svc.shards[0]
        assert sum(getattr(s, "_hbm_charged", 0)
                   for s in shard.segments) == after
        n.close()
        assert seg_mod.SEGMENT_HBM_BUDGET.used == 0
    finally:
        seg_mod.SEGMENT_HBM_BUDGET = old


def test_rest_429_and_cat_thread_pool():
    """REST surface: breaker → 429 envelope; _cat/thread_pool shows real
    counters; requests flow through the named pools."""
    from elasticsearch_tpu.rest.server import RestServer

    old = seg_mod.SEGMENT_HBM_BUDGET
    seg_mod.SEGMENT_HBM_BUDGET = HbmBudget(total_bytes=1)
    node = Node(name="tp-node")
    srv = RestServer(node, host="127.0.0.1", port=0)
    srv.start(background=True)

    def req(method, path, body=None):
        data = json.dumps(body).encode() if body is not None else None
        r = urllib.request.Request(
            f"http://127.0.0.1:{srv.port}{path}", data=data, method=method,
            headers={"Content-Type": "application/json"})
        try:
            with urllib.request.urlopen(r) as resp:
                return resp.status, json.loads(resp.read())
        except urllib.error.HTTPError as e:
            return e.code, json.loads(e.read())

    try:
        st, _ = req("PUT", "/cb3", {})
        assert st == 200
        st, _ = req("PUT", "/cb3/_doc/1", {"t": "x"})
        assert st in (200, 201)
        st, r = req("POST", "/cb3/_refresh")
        assert st == 429, (st, r)
        assert r["error"]["type"] == "circuit_breaking_exception"
        st, pools = req("GET", "/_cat/thread_pool?format=json&pools=true")
        assert st == 200
        by_name = {p["name"]: p for p in pools}
        assert by_name["index"]["completed"] >= 1  # the _doc PUT
        assert by_name["management"]["completed"] >= 2
    finally:
        srv.stop()
        node.close()
        seg_mod.SEGMENT_HBM_BUDGET = old
