"""Chaos tests over the deterministic fault-injection registry
(elasticsearch_tpu/utils/faults.py — MockTransportService in spirit).

Every scenario here is seed/count-deterministic: a fault fires on an
exact check (or an exact seeded probability stream), so a failure in CI
replays identically. Covered failure domains:

- registry semantics (count/after/match/seeded-prob determinism, env spec)
- typed transport failures + bounded-backoff retry + per-peer breaker
- dead shard owner mid-query → HTTP-200-style partial `_shards` results
- translog fsync fault → tragic event → engine fails CLOSED (typed 503),
  with replay proving no acknowledged op was lost
- corrupt translog tail → replay stops, frames/bytes-dropped accounting
- segment-freeze fault → refresh fails retryably, buffer survives
"""
import os
import socket
import time

import pytest

from elasticsearch_tpu.cluster.transport import (
    BackoffPolicy,
    ConnectTransportError,
    NodeUnavailableException,
    PeerBreaker,
    ReceiveTimeoutTransportError,
    RemoteException,
    TransportError,
    TransportService,
)
from elasticsearch_tpu.utils.faults import (
    FAULTS,
    FaultRegistry,
    _parse_env_spec,
)


@pytest.fixture(autouse=True)
def _clean_slate():
    from elasticsearch_tpu.monitor.stats import TRANSLOG_RECOVERY

    FAULTS.clear()
    TRANSLOG_RECOVERY.reset()
    yield
    FAULTS.clear()
    TRANSLOG_RECOVERY.reset()


# -- registry semantics --------------------------------------------------------

def test_count_and_after_gates():
    r = FaultRegistry()
    r.inject("translog.fsync", error=OSError, count=2, after=1)
    r.check("translog.fsync")  # after=1 lets the first through
    with pytest.raises(OSError):
        r.check("translog.fsync")
    with pytest.raises(OSError):
        r.check("translog.fsync")
    r.check("translog.fsync")  # count exhausted: disarmed
    assert not r.active("translog.fsync")
    assert len(r.history) == 2


def test_match_narrows_to_context():
    r = FaultRegistry()
    r.inject("transport.send", error=ConnectionRefusedError, count=-1,
             match=lambda ctx: ctx.get("action") == "a/query")
    r.check("transport.send", action="a/fetch")  # no match, no fire
    with pytest.raises(ConnectionRefusedError):
        r.check("transport.send", action="a/query")


def test_seeded_probability_is_deterministic():
    def pattern(seed):
        r = FaultRegistry()
        r.inject("transport.send", error=OSError, count=-1, prob=0.5,
                 seed=seed)
        out = []
        for _ in range(64):
            try:
                r.check("transport.send")
                out.append(0)
            except OSError:
                out.append(1)
        return out

    a, b = pattern(7), pattern(7)
    assert a == b            # same seed → identical chaos
    assert 0 < sum(a) < 64   # and it actually flakes both ways
    assert pattern(8) != a   # a different seed is a different storm


def test_env_spec_parsing_and_unknown_point():
    r = FaultRegistry()
    _parse_env_spec("translog.fsync:count=2;"
                    "transport.send:prob=0.5:seed=3:error=connrefused", r)
    assert r.active("translog.fsync")
    assert r.active("transport.send")
    with pytest.raises(ValueError):
        r.inject("no.such.point")


# -- backoff / breaker ---------------------------------------------------------

def test_backoff_deterministic_and_bounded():
    p = BackoffPolicy(base=0.05, multiplier=2.0, max_delay=0.4, seed=42)
    a, b = list(p.delays(6)), list(p.delays(6))
    assert a == b  # seeded jitter replays
    assert all(0 < d <= 0.4 for d in a)
    # the un-jittered envelope grows then clamps
    raw = [min(0.05 * 2 ** i, 0.4) for i in range(6)]
    assert all(d <= r for d, r in zip(a, raw))


def test_peer_breaker_opens_and_half_opens():
    clock = [0.0]
    br = PeerBreaker(threshold=3, cooldown=5.0, clock=lambda: clock[0])
    peer = ("127.0.0.1", 9999)
    assert br.allow(peer)
    for _ in range(3):
        br.record_failure(peer)
    assert not br.allow(peer)          # open: fail fast
    clock[0] = 5.1
    assert br.allow(peer)              # half-open: one probe
    assert not br.allow(peer)          # …and only one
    br.record_success(peer)
    assert br.allow(peer)              # success closes it fully


def test_peer_breaker_abandoned_probe_expires():
    # a probe whose caller died before reporting must not blacklist the
    # peer forever — the grant expires after another cooldown window
    clock = [0.0]
    br = PeerBreaker(threshold=1, cooldown=5.0, clock=lambda: clock[0])
    peer = ("127.0.0.1", 9999)
    br.record_failure(peer)
    assert not br.allow(peer)
    clock[0] = 5.1
    assert br.allow(peer)       # probe granted… and the caller vanishes
    assert not br.allow(peer)
    clock[0] = 10.3
    assert br.allow(peer)       # a fresh probe, not a permanent lockout


def test_backoff_salt_decorrelates_but_replays():
    p = BackoffPolicy(seed=1)
    assert list(p.delays(4, salt="peerA")) == list(p.delays(4, salt="peerA"))
    assert list(p.delays(4, salt="peerA")) != list(p.delays(4, salt="peerB"))


# -- typed transport failures --------------------------------------------------

def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_connect_refused_is_typed():
    ts = TransportService("n1")
    with pytest.raises(ConnectTransportError) as ei:
        ts.send_remote(("127.0.0.1", _free_port()), "x", {}, timeout=0.5)
    assert ei.value.error_type == "connect_transport_error"
    assert ei.value.status == 503


def test_mid_request_timeout_is_typed():
    ts = TransportService("n1")
    ts.register("slow", lambda p: time.sleep(1.0))
    addr = ts.bind()
    try:
        with pytest.raises(ReceiveTimeoutTransportError) as ei:
            ts.send_remote(addr, "slow", {}, timeout=0.25)
        assert ei.value.error_type == "receive_timeout_transport_error"
    finally:
        ts.close()


def test_retry_recovers_from_single_flake():
    ts = TransportService("n1")
    ts.register("echo", lambda p: p)
    addr = ts.bind()
    try:
        FAULTS.inject("transport.send", error=ConnectionRefusedError,
                      count=1)
        out = ts.send_with_retry(addr, "echo", {"v": 1}, timeout=2.0,
                                 retries=2)
        assert out == {"v": 1}
        assert FAULTS.fired("transport.send") == 1  # exactly one retry used
    finally:
        ts.close()


def test_remote_application_errors_never_retry():
    calls = []

    def boom(p):
        calls.append(1)
        from elasticsearch_tpu.utils.errors import DocumentMissingException

        raise DocumentMissingException("i", "1")

    ts = TransportService("n1")
    ts.register("boom", boom)
    addr = ts.bind()
    try:
        with pytest.raises(RemoteException) as ei:
            ts.send_with_retry(addr, "boom", {}, timeout=2.0, retries=3)
        assert ei.value.status == 404  # the peer ANSWERED; not a retry case
        assert len(calls) == 1
    finally:
        ts.close()


def test_breaker_fast_fails_repeatedly_dead_peer():
    ts = TransportService("n1")
    dead = ("127.0.0.1", _free_port())
    ts.backoff = BackoffPolicy(base=0.001, max_delay=0.002)
    with pytest.raises(ConnectTransportError):
        ts.send_with_retry(dead, "x", {}, timeout=0.2, retries=3)
    # ≥ threshold consecutive failures recorded: the breaker now skips it
    with pytest.raises(NodeUnavailableException) as ei:
        ts.send_with_retry(dead, "x", {}, timeout=0.2, retries=3)
    assert ei.value.error_type == "node_unavailable_exception"


def test_deadline_caps_total_retry_time():
    ts = TransportService("n1")
    dead = ("127.0.0.1", _free_port())
    t0 = time.monotonic()
    with pytest.raises(TransportError):
        ts.send_with_retry(dead, "x", {}, timeout=5.0, retries=50,
                           deadline=time.monotonic() + 0.3)
    assert time.monotonic() - t0 < 2.0  # nowhere near 50 retries' worth


# -- write-path durability: tragic events --------------------------------------

def test_fsync_fault_fails_engine_closed_and_loses_no_acked_op(tmp_path):
    from elasticsearch_tpu.index.index_service import IndexService
    from elasticsearch_tpu.index.translog import Translog
    from elasticsearch_tpu.utils.errors import EngineFailedException

    svc = IndexService("wal", settings={"index": {"number_of_shards": 1}},
                       data_path=str(tmp_path))
    try:
        svc.index_doc("1", {"v": 1})  # acknowledged
        FAULTS.inject("translog.fsync", error=OSError, count=1)
        with pytest.raises(EngineFailedException):
            svc.index_doc("2", {"v": 2})  # the triggering op is NOT acked
        # the fault is spent, but the engine stays failed CLOSED
        with pytest.raises(EngineFailedException) as ei:
            svc.index_doc("3", {"v": 3})
        assert ei.value.status == 503
        assert ei.value.error_type == "engine_failed_exception"
        engine = svc.groups[0].primary.engine
        assert engine.is_failed
        # replay proves the acked/acked-only invariant: doc 1 replays,
        # docs 2 and 3 were refused — nothing silently lost
        replayed = list(Translog(engine.translog.path).replay())
        assert [op["id"] for op in replayed if op["op"] == "index"] == ["1"]
    finally:
        svc.close()


def test_fsync_fault_surfaces_as_typed_503_through_rest(tmp_path):
    from elasticsearch_tpu.node import Node
    from elasticsearch_tpu.rest.server import RestController

    node = Node(name="chaos", data_path=str(tmp_path))
    ctrl = RestController(node)
    try:
        status, _ = ctrl.dispatch("PUT", "/logs/_doc/1", {}, b'{"v": 1}')
        assert status == 201
        FAULTS.inject("translog.fsync", error=OSError, count=1)
        status, body = ctrl.dispatch("PUT", "/logs/_doc/1", {}, b'{"v": 2}')
        assert status == 503
        assert body["error"]["type"] == "engine_failed_exception"
        # fault disarmed, engine still failed: the NEXT write 503s too
        status, body = ctrl.dispatch("PUT", "/logs/_doc/1", {}, b'{"v": 3}')
        assert status == 503
        assert body["error"]["type"] == "engine_failed_exception"
    finally:
        node.close()


def test_corrupt_tail_reported_not_half_parsed(tmp_path):
    from elasticsearch_tpu.index.translog import Translog
    from elasticsearch_tpu.monitor.stats import TRANSLOG_RECOVERY

    path = str(tmp_path / "translog")
    tl = Translog(path)
    for i in range(3):
        tl.append({"op": "index", "id": str(i), "source": {"v": i}})
    tl.close()
    gen = f"{path}.1"
    size = os.path.getsize(gen)
    with open(gen, "r+b") as f:  # flip a byte inside the LAST frame
        f.seek(size - 3)
        b = f.read(1)
        f.seek(size - 3)
        f.write(bytes([b[0] ^ 0xFF]))
    tl2 = Translog(path)
    ops = list(tl2.replay())
    assert [op["id"] for op in ops] == ["0", "1"]  # stops AT the tear
    stats = tl2.stats()
    assert stats["corrupt_tail_events"] == 1
    rec = TRANSLOG_RECOVERY.to_json()
    assert rec["corrupt_tail_frames_skipped"] == 1
    assert rec["corrupt_tail_bytes_dropped"] > 0
    assert rec["events"][0]["reason"] == "frame CRC mismatch"
    tl2.close()


def test_translog_append_after_tragic_close_is_refused(tmp_path):
    from elasticsearch_tpu.index.translog import (Translog,
                                                  TranslogClosedException)

    tl = Translog(str(tmp_path / "t"))
    tl.append({"op": "index", "id": "1", "source": {}})
    FAULTS.inject("translog.fsync", error=OSError, count=1)
    with pytest.raises(OSError):
        tl.append({"op": "index", "id": "2", "source": {}})
    # the channel is CLOSED: no later append can extend a torn tail
    with pytest.raises(TranslogClosedException):
        tl.append({"op": "index", "id": "3", "source": {}})
    assert tl.stats()["closed"]


def test_segment_freeze_fault_is_retryable_not_tragic():
    from elasticsearch_tpu.index.index_service import IndexService

    svc = IndexService("frz", settings={"index": {"number_of_shards": 1}})
    try:
        svc.index_doc("1", {"v": 1})
        FAULTS.inject("segment.freeze", error=OSError, count=1)
        with pytest.raises(OSError):
            svc.refresh()
        svc.refresh()  # buffer survived; the next refresh serves the doc
        assert svc.search({"size": 0})["hits"]["total"] == 1
        engine = svc.groups[0].primary.engine
        assert not engine.is_failed  # refresh faults never fail the engine
    finally:
        svc.close()


# -- dead owner mid-query → partial shard results ------------------------------

@pytest.fixture()
def two_node_cluster(tmp_path):
    """Two full MultiHostClusters IN-PROCESS (the TCP transport doesn't
    care): rank 0 is master+coordinator, rank 1 owns half the shards.
    ping_interval=0 — no fault detector, so the assignment keeps naming
    the 'dead' owner while faults make it unreachable (deterministic,
    unlike racing a process kill)."""
    from elasticsearch_tpu.cluster.bootstrap import MultiHostCluster
    from elasticsearch_tpu.node import Node

    port = _free_port()
    node0 = Node(name="rank0")
    # minimum_master_nodes=1: the 'dead' owner is simulated by faults
    # while the master keeps serving alone — the pre-quorum semantics
    # (coordination quorum/step-down has its own chaos matrix)
    c0 = MultiHostCluster(node0, rank=0, world=2, transport_port=port,
                          ping_interval=0, minimum_master_nodes=1)
    node1 = Node(name="rank1")
    c1 = MultiHostCluster(node1, rank=1, world=2, transport_port=port,
                          ping_interval=0, minimum_master_nodes=1)
    c0.data.create_index("evt", {
        "settings": {"number_of_shards": 2},
        "mappings": {"properties": {"n": {"type": "integer"}}}})
    assig = c0.dist_indices["evt"]["assignment"]
    assert len({o[0] for o in assig.values()}) == 2, assig
    for i in range(20):
        c0.data.index_doc("evt", str(i), {"n": i})
    c0.data.refresh("evt")
    yield c0, c1
    try:
        c1.close()
    finally:
        c0.close()
        node1.close()
        node0.close()


def test_dead_owner_mid_query_degrades_to_partial(two_node_cluster):
    from elasticsearch_tpu.cluster.search_action import ACTION_QUERY
    from elasticsearch_tpu.rest.server import RestController

    c0, _c1 = two_node_cluster
    full = c0.data.search("evt", {"size": 20})
    assert full["_shards"] == {"total": 2, "successful": 2, "failed": 0}
    assert full["hits"]["total"] == 20

    # kill the remote owner's QUERY phase only — everything else lives
    FAULTS.inject("transport.send", error=ConnectionRefusedError, count=-1,
                  match=lambda ctx: ctx.get("action") == ACTION_QUERY)
    r = c0.data.search("evt", {"size": 20})
    shards = r["_shards"]
    assert shards["total"] == 2 and shards["failed"] >= 1
    assert shards["successful"] == 2 - shards["failed"]
    fail = shards["failures"][0]
    assert fail["shard"] in (0, 1)                 # names the shard
    assert fail["index"] == "evt" and fail["node"] # …and the owner
    assert fail["reason"]["type"] == "connect_transport_error"
    # correct hits from the SURVIVING shard: exactly the locally-owned docs
    local_total = sum(
        g.primary.engine.num_docs
        for sid, g in enumerate(c0.node.indices["evt"].groups)
        if c0.dist_indices["evt"]["assignment"][str(sid)][0]
        == c0.local.node_id)
    assert r["hits"]["total"] == local_total > 0
    assert len(r["hits"]["hits"]) == local_total

    # acceptance shape: the REST layer serves this as HTTP 200
    ctrl = RestController(c0.node)
    status, body = ctrl.dispatch("POST", "/evt/_search", {},
                                 b'{"size": 20}')
    assert status == 200
    assert body["_shards"]["failed"] >= 1
    # by now the breaker may have opened for the dead peer: either the
    # raw connect failure or the breaker's fast-fail is a correct report
    assert body["_shards"]["failures"][0]["reason"]["type"] in (
        "connect_transport_error", "node_unavailable_exception")

    # clear the chaos (and the breaker's memory of it): full results again
    FAULTS.clear()
    c0.transport.breaker = PeerBreaker()
    r = c0.data.search("evt", {"size": 20})
    assert r["_shards"]["failed"] == 0
    assert r["hits"]["total"] == 20


def test_transport_flake_retries_within_deadline(two_node_cluster):
    c0, _c1 = two_node_cluster
    # ONE connect flake on the next send: the bounded backoff absorbs it
    FAULTS.inject("transport.send", error=ConnectionRefusedError, count=1)
    r = c0.data.search("evt", {"size": 20})
    assert r["_shards"]["failed"] == 0
    assert r["hits"]["total"] == 20
    assert FAULTS.fired("transport.send") == 1


def test_dead_owner_mid_fetch_drops_only_its_hits(two_node_cluster):
    from elasticsearch_tpu.cluster.search_action import ACTION_FETCH

    c0, _c1 = two_node_cluster
    FAULTS.inject("transport.send", error=ConnectionRefusedError, count=-1,
                  match=lambda ctx: ctx.get("action") == ACTION_FETCH)
    r = c0.data.search("evt", {"size": 20})
    # query phase saw BOTH shards (total counts everything)…
    assert r["hits"]["total"] == 20
    # …but the dead owner's page hits dropped and its shard is failed
    assert r["_shards"]["failed"] >= 1
    assert 0 < len(r["hits"]["hits"]) < 20
    assert {f["reason"]["type"] for f in r["_shards"]["failures"]} \
        == {"connect_transport_error"}


def test_recovery_stream_fault_point_is_wired(two_node_cluster):
    c0, _c1 = two_node_cluster
    FAULTS.inject("recovery.shard_sync", error=OSError, count=1)
    with pytest.raises(OSError):
        c0.data._on_shard_sync({"index": "evt", "shard": 0})
    assert FAULTS.fired("recovery.shard_sync") == 1
