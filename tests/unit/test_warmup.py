"""Zero-warmup serving tests (ISSUE 14): AOT executable cache
(parallel/aot.py) + census-driven pre-warm pipeline (serving/warmup.py).

- AOT failure edges: a corrupt serialized-executable blob is a DETECTED
  miss (deleted, counted) followed by a fresh compile with bit-identical
  results; a fingerprint-stale blob likewise; a store failure never
  costs the call its program.
- Warmup discipline: breaker-denied replay defers without failing a
  foreground search; a cancelled warmup task stops at a body boundary
  and leaves the task registry + program registry consistent; completed
  runs are cooldown-guarded; replays label warmup=prewarm and never
  inflate their own census.
- Census v2: per-key hit counts, replayable bodies, merge-on-store
  durability (the watchdog-tick flush path).
- Restart acceptance: a fresh process over the same data_path pre-warms
  from the persisted census and serves the first page of censused
  traffic with ZERO fresh compiles (estpu_program_compiles_total flat,
  warmup=true count 0).
"""
import json
import os
import subprocess
import sys
import tempfile
import threading

import numpy as np
import pytest

from elasticsearch_tpu.index import ivf_cache
from elasticsearch_tpu.monitor import compile_cache, programs
from elasticsearch_tpu.node import Node
from elasticsearch_tpu.parallel import aot
from elasticsearch_tpu.resources import census


@pytest.fixture(autouse=True)
def _fresh_state():
    programs.REGISTRY.reset()
    compile_cache.reset()
    aot.reset_enabled_for_tests()
    census._DECAYED.clear()
    yield
    programs.REGISTRY.reset()
    compile_cache.reset()
    aot.reset_enabled_for_tests()
    census._DECAYED.clear()


def _register_dir():
    d = tempfile.mkdtemp()
    ivf_cache.register(d)
    return d


def _make_node(data_path=None, index="wuidx", docs=16, name="wu"):
    n = Node(name=name, data_path=data_path)
    if index not in n.indices:
        n.create_index(index, {
            "mappings": {"properties": {"t": {"type": "text"}}}})
        svc = n.indices[index]
        for i in range(docs):
            svc.index_doc(str(i), {"t": f"alpha beta gamma delta word{i}"})
        svc.refresh()
    return n


# -- AOT executable cache ------------------------------------------------------

class TestAotCache:
    def _program(self, key=("p", 1)):
        import jax

        fn = jax.jit(lambda x, y: (x * 2.0 + y, x.sum()))
        return aot.wrap(fn, "unit_prog", key)

    def _args(self):
        return (np.arange(8, dtype=np.float32),
                np.ones(8, dtype=np.float32))

    def test_fresh_then_blob_hit_bit_identical(self):
        _register_dir()
        p1 = self._program()
        assert isinstance(p1, aot.AotProgram)
        out1 = p1(*self._args())
        ev = compile_cache.events_snapshot()
        assert ev["fresh"] + ev["xla_dir_hit"] == 1
        assert ev["store"] == 1
        # a NEW wrapper (fresh memo — the restart simulation) resolves
        # the same key from the blob: aot_hit, no compile, same bits
        p2 = self._program()
        out2 = p2(*self._args())
        ev = compile_cache.events_snapshot()
        assert ev["aot_hit"] == 1
        for a, b in zip(out1, out2):
            assert np.array_equal(np.asarray(a), np.asarray(b))

    def test_corrupt_blob_detected_deleted_fresh_compile(self):
        d = _register_dir()
        p1 = self._program()
        out1 = p1(*self._args())
        (path,) = [os.path.join(d, f) for f in os.listdir(d)
                   if f.endswith(".aotx")]
        with open(path, "wb") as fh:
            fh.write(b"deadbeef\nnot a pickle")
        # drop the memory tier so the corrupted DISK copy is what loads
        ivf_cache.reset()
        ivf_cache.register(d)
        p2 = self._program()
        out2 = p2(*self._args())
        ev = compile_cache.events_snapshot()
        assert ev["corrupt_miss"] == 1
        assert ev["fresh"] + ev["xla_dir_hit"] == 2  # recompiled
        assert not os.path.exists(path) or \
            open(path, "rb").read() != b"deadbeef\nnot a pickle"
        for a, b in zip(out1, out2):
            assert np.array_equal(np.asarray(a), np.asarray(b))

    def test_stale_fingerprint_blob_detected_deleted(self):
        d = _register_dir()
        p1 = self._program()
        p1(*self._args())  # learn the real key by listing the dir
        (fname,) = [f for f in os.listdir(d) if f.endswith(".aotx")]
        key = fname[: -len(".aotx")]
        # a structurally-valid blob claiming another backend/jax build
        # at the SAME key (hand-moved file / collision defense): the
        # fingerprint check inside the payload must catch it
        stale = aot._frame({
            "version": aot.VERSION, "program": "unit_prog", "sig": "x",
            "backend": "tpu/v99", "jax": "0.0.0", "host": "nope",
            "exe": b"", "in_tree": None, "out_tree": None})
        ivf_cache.reset()
        ivf_cache.register(d)
        ivf_cache.store_blob(key, stale, "aotx")
        p2 = self._program()
        out2 = p2(*self._args())
        ev = compile_cache.events_snapshot()
        assert ev["mismatch_miss"] == 1
        assert np.asarray(out2[0]).shape == (8,)
        # the stale blob was deleted and replaced by the fresh store
        reloaded = ivf_cache.load_blob(key, "aotx")
        assert reloaded is None or reloaded != stale

    def test_dir_hit_compile_never_stored(self, monkeypatch):
        """An executable rebuilt from the XLA persistent-cache dir lacks
        the object code serialize_executable needs — its blob fails
        deserialize with 'Symbols not found' in the next process, and
        storing it would poison every restart (deserialize_error →
        delete → re-store the same poison). Dir-served compiles must
        skip the store."""
        _register_dir()
        counter = {"n": 0}

        def fake_hits():
            counter["n"] += 1  # moves across the compile → "dir hit"
            return counter["n"]

        monkeypatch.setattr(aot, "_xla_hits", fake_hits)
        p = self._program(key=("dh", 4))
        p(*self._args())
        ev = compile_cache.events_snapshot()
        assert ev["xla_dir_hit"] == 1
        assert ev["store"] == 0
        assert ev["store_skipped"] == 1
        # nothing persisted: a fresh wrapper recompiles, never a
        # poisoned aot_hit
        p2 = self._program(key=("dh", 4))
        p2(*self._args())
        assert compile_cache.events_snapshot()["aot_hit"] == 0

    def test_disabled_env_returns_plain_fn(self, monkeypatch):
        monkeypatch.setenv("ESTPU_AOT_CACHE", "off")
        aot.reset_enabled_for_tests()
        import jax

        fn = jax.jit(lambda x: x + 1)
        assert aot.wrap(fn, "p", ("k",)) is fn
        assert compile_cache.enabled_state() is False

    def test_cache_source_lands_on_timed_observatory_key(self):
        _register_dir()
        p = self._program(key=("obs", 2))
        with programs.REGISTRY.timed("mesh_unit", "Q=1|k=8"):
            p(*self._args())
        (row,) = [r for r in programs.REGISTRY.snapshot()
                  if r["program"] == "mesh_unit"]
        src = row["cache_sources"]
        assert src.get("fresh", 0) + src.get("xla_dir_hit", 0) == 1

    def test_cache_source_does_not_pollute_census(self):
        _register_dir()
        p = self._program(key=("cen", 3))
        with programs.index_scope("ccidx"):
            with programs.REGISTRY.timed("mesh_cc", "Q=1|k=8",
                                         field="body"):
                p(*self._args())
        rows = [r for r in programs.REGISTRY.census("ccidx")
                if r["program"] == "mesh_cc"]
        # exactly the dispatch record's key — the AOT source accounting
        # must not plant a second field-less phantom row in the census
        assert [r["field"] for r in rows] == ["body"]


# -- census v2 -----------------------------------------------------------------

class TestCensusV2:
    def test_bodies_recorded_and_hottest_first(self):
        n = _make_node(index="cb_idx")
        try:
            hot = {"query": {"match": {"t": "alpha"}}, "size": 5}
            cold = {"query": {"match": {"t": "beta gamma"}}, "size": 3}
            for _ in range(3):
                n.search("cb_idx", hot)
            n.search("cb_idx", cold)
            bodies = programs.REGISTRY.bodies("cb_idx")
            assert len(bodies) == 2
            assert bodies[0]["hits"] == 3  # hottest first
            assert json.loads(bodies[0]["body"]) == hot
            ks = programs.REGISTRY.census("cb_idx")
            assert all(k["hits"] >= 1 for k in ks)
        finally:
            n.close()

    def test_profile_and_unserializable_bodies_excluded(self):
        n = _make_node(index="pb_idx")
        try:
            n.search("pb_idx", {"query": {"match": {"t": "alpha"}},
                                "profile": True})
            assert programs.REGISTRY.bodies("pb_idx") == []
        finally:
            n.close()

    def test_store_merges_with_persisted(self):
        _register_dir()
        census.store_census(
            "mg_idx",
            keys=[{"program": "a", "shapes": "s", "field": "", "hits": 5}],
            bodies=[{"body": "{\"q\":1}", "hits": 7}])
        # a later flush from a process that saw less traffic must not
        # regress the persisted hit counts, and new keys must join
        census.store_census(
            "mg_idx",
            keys=[{"program": "a", "shapes": "s", "field": "", "hits": 2},
                  {"program": "b", "shapes": "s2", "field": "", "hits": 1}],
            bodies=[{"body": "{\"q\":1}", "hits": 1}])
        payload = census.load_census("mg_idx")
        by_prog = {k["program"]: k for k in payload["keys"]}
        assert by_prog["a"]["hits"] == 5  # max, never double-counted
        assert by_prog["b"]["hits"] == 1
        assert payload["bodies"] == [{"body": "{\"q\":1}", "hits": 7}]

    def test_restore_reaches_disk_not_just_memory(self):
        d = _register_dir()
        census.store_census(
            "dk_idx", keys=[{"program": "a", "shapes": "s", "field": "",
                             "hits": 1}], bodies=[])
        census.store_census(
            "dk_idx", keys=[{"program": "b", "shapes": "s2", "field": "",
                             "hits": 1}], bodies=[])
        # drop the in-process memory tier: the DISK copy must carry the
        # second store (a skip-if-exists disk write would freeze the
        # blob at its first flush — the exact kill -9 durability hole)
        ivf_cache.reset()
        ivf_cache.register(d)
        payload = census.load_census("dk_idx")
        assert {k["program"] for k in payload["keys"]} == {"a", "b"}

    def test_body_cap_evicts_cold_for_shifted_workload(self):
        reg = programs.ProgramRegistry()
        for i in range(programs.ProgramRegistry._BODY_CAP):
            reg.record_body("ev_idx", f"early_{i}")
        # the workload shifts: a new hot body keeps arriving — it must
        # displace a cold early entry (first-come-forever would freeze
        # the replay set at boot-time traffic)
        for _ in range(3):
            reg.record_body("ev_idx", "late_hot")
        bodies = reg.bodies("ev_idx")
        assert any(b["body"] == "late_hot" for b in bodies)
        assert len(bodies) == programs.ProgramRegistry._BODY_CAP

    def test_unreinforced_rows_decay_across_restarts(self):
        _register_dir()
        census.store_census(
            "dc_idx", keys=[], merge=True,
            bodies=[{"body": "{\"old\":1}", "hits": 32}])
        # "restart": the first merge of a new process halves persisted
        # rows live traffic did not reinforce — a dead workload must
        # fall out of the capped hottest-first set within a few
        # generations instead of pinning it forever
        for gen in range(4):
            census._DECAYED.clear()  # simulate a fresh process
            census.store_census(
                "dc_idx", keys=[],
                bodies=[{"body": "{\"new\":1}", "hits": 2}])
        payload = census.load_census("dc_idx")
        by = {b["body"]: b["hits"] for b in payload["bodies"]}
        assert by["{\"old\":1}"] <= 2  # 32 → halved per restart
        assert by["{\"new\":1}"] == 2  # reinforced rows never decay

    def test_merge_bounded_by_blob_caps(self):
        _register_dir()
        # repeated shifting-workload flushes: the persisted union must
        # stay capped (hottest survive), never grow O(generations)
        for gen in range(3):
            census.store_census(
                "cap_idx",
                keys=[{"program": f"p{gen}_{i}", "shapes": "s",
                       "field": "", "hits": gen + 1} for i in range(40)],
                bodies=[{"body": json.dumps({"g": gen, "i": i}),
                         "hits": gen + 1} for i in range(40)])
        payload = census.load_census("cap_idx")
        assert len(payload["bodies"]) == census.BODY_CAP
        # hottest-first: the newest (highest-hits) generation survives
        assert all(json.loads(b["body"])["g"] == 2
                   for b in payload["bodies"][:40])

    def test_watchdog_tick_flushes_census(self, tmp_path):
        n = _make_node(data_path=str(tmp_path / "d"), index="wf_idx")
        try:
            n.search("wf_idx", {"query": {"match": {"t": "alpha"}}})
            assert census.load_census("wf_idx") is None  # not yet flushed
            n.watchdog.config["census_flush_every_s"] = 0.0
            n.watchdog.run_once()
            payload = census.load_census("wf_idx")
            assert payload is not None and payload["bodies"]
            # unchanged census: the next tick skips the write (generation
            # cursor) — store a sentinel and prove it survives the tick
            gen = programs.REGISTRY.census_generation()
            n.watchdog.run_once()
            assert programs.REGISTRY.census_generation() == gen
        finally:
            n.close()


# -- pre-warm service ----------------------------------------------------------

class TestWarmupService:
    def _censused_node(self, tmp_path, index="pw_idx", searches=3):
        n = _make_node(data_path=str(tmp_path / "d"), index=index)
        for i in range(searches):
            n.search(index, {"query": {"match": {"t": "alpha beta"}},
                             "size": 4 + i})
        census.store_census(index)
        return n

    def test_run_index_replays_and_labels_prewarm(self, tmp_path):
        n = self._censused_node(tmp_path)
        try:
            res = n.serving.warmup.run_index("pw_idx", "test")
            assert res["status"] == "complete"
            assert res["replayed"] == 3
            assert res["errors"] == 0
            rows = n.metrics.summaries()["estpu_search_duration_seconds"]
            by_warm = {r["labels"]["warmup"]: r["count"] for r in rows
                       if r["labels"]["index"] == "pw_idx"}
            assert by_warm.get("prewarm", 0) == 3
            # replays never inflate their own work list
            assert all(b["hits"] == 1
                       for b in programs.REGISTRY.bodies("pw_idx"))
            # cooldown: an immediate re-kick is a recorded no-op, and a
            # DIRECT run (a kick that sat queued past another trigger's
            # completed run) is re-checked at run time too — both skips
            # annotate the completed record instead of destroying its
            # diagnostics
            assert n.serving.warmup.kick("again", ["pw_idx"]) == []
            res2 = n.serving.warmup.run_index("pw_idx", "queued_kick")
            assert res2["status"] == "cooldown"
            assert res2["replayed"] == 0
            rec = n.serving.warmup.runs["pw_idx"]
            assert rec["status"] == "complete"  # diagnostics preserved
            assert rec["replayed"] == 3
            assert rec["cooldown_skips"] == 2
        finally:
            n.close()

    def test_breaker_denied_warmup_defers_not_foreground(self, tmp_path):
        from elasticsearch_tpu import resources

        n = self._censused_node(tmp_path, index="bd_idx")
        br = resources.BREAKERS.breaker("request")
        old_limit = br.limit
        try:
            br.limit = 0  # every reserve() denied
            n.serving.warmup.config["defer_wait_s"] = 0.001
            res = n.serving.warmup.run_index("bd_idx", "test")
            assert res["status"] == "deferred"
            assert res["replayed"] == 0
            assert res["deferrals"] >= 1
            # foreground search unaffected by the deferral
            r = n.search("bd_idx", {"query": {"match": {"t": "alpha"}}})
            assert r["hits"]["total"] > 0
            # deferred ≠ complete: no cooldown, a later kick retries
            br.limit = old_limit
            res2 = n.serving.warmup.run_index("bd_idx", "retry")
            assert res2["status"] == "complete"
        finally:
            br.limit = old_limit
            n.close()

    def test_cancelled_warmup_leaves_registry_consistent(self, tmp_path):
        n = self._censused_node(tmp_path, index="cx_idx", searches=4)
        try:
            svc = n.indices["cx_idx"]
            started, release = threading.Event(), threading.Event()
            real_search = svc.search

            def slow_search(body, **kw):
                started.set()
                release.wait(timeout=10.0)
                return real_search(body, **kw)

            svc.search = slow_search
            out = {}

            def run():
                out["res"] = n.serving.warmup.run_index("cx_idx", "test")

            th = threading.Thread(target=run, daemon=True)
            th.start()
            assert started.wait(timeout=10.0)
            (task,) = [t for t in n.tasks.list_tasks()
                       if t.action == "cluster:admin/warmup"]
            n.tasks.cancel(task.id, reason="test cancel")
            release.set()
            th.join(timeout=10.0)
            svc.search = real_search
            assert out["res"]["status"] == "canceled"
            assert out["res"]["replayed"] <= 2
            # registry consistent: the parent task is gone, no dispatch
            # left in flight, and foreground searches still serve
            assert not [t for t in n.tasks.list_tasks()
                        if t.action == "cluster:admin/warmup"]
            assert programs.REGISTRY.inflight_snapshot() == []
            r = n.search("cx_idx", {"query": {"match": {"t": "alpha"}}})
            assert r["hits"]["total"] > 0
        finally:
            n.close()

    def test_backend_mismatch_refused(self, tmp_path):
        n = self._censused_node(tmp_path, index="bm_idx")
        try:
            payload = census.load_census("bm_idx")
            payload["backend"] = "tpu/v99"
            ivf_cache.store_blob(census.census_key("bm_idx"),
                                 ivf_cache.frame_blob(payload), "census")
            res = n.serving.warmup.run_index("bm_idx", "test")
            assert res["status"] == "backend_mismatch"
            assert res["replayed"] == 0
        finally:
            n.close()

    def test_kick_and_rest_surface(self, tmp_path):
        from elasticsearch_tpu.rest.server import RestController

        n = self._censused_node(tmp_path, index="rk_idx")
        try:
            rc = RestController(n)
            status, out = rc.dispatch("POST", "/rk_idx/_warmup", {}, b"")
            assert status == 200 and out["queued"] == ["rk_idx"]
            assert n.serving.warmup.wait_idle(timeout=30.0)
            status, out = rc.dispatch("GET", "/_warmup", {}, b"")
            assert status == 200
            assert out["runs"]["rk_idx"]["status"] == "complete"
            # serving stats section carries the same view
            st = n.nodes_stats()["nodes"][n.node_id]["serving"]["warmup"]
            assert st["runs"]["rk_idx"]["status"] == "complete"
        finally:
            n.close()

    def test_disabled_env_kick_is_noop(self, tmp_path, monkeypatch):
        n = self._censused_node(tmp_path, index="dk_idx")
        try:
            monkeypatch.setenv("ESTPU_WARMUP", "0")
            assert n.serving.warmup.kick("boot") == []
        finally:
            n.close()


# -- census rides shard-relocation streams (ISSUE 15) --------------------------


class TestRelocationCensus:
    def _served_node(self, tmp_path, index="rc_idx"):
        n = _make_node(data_path=str(tmp_path / "src"), index=index)
        for body in ({"query": {"match": {"t": "alpha"}}, "size": 5},
                     {"query": {"match": {"t": "beta gamma"}}, "size": 3}):
            n.search(index, body)
        return n

    def test_export_then_adopt_across_isolated_blob_tiers(self, tmp_path):
        """The in-band path: a target node sharing NO blob directory
        with the source gets the census through the payload alone."""
        n = self._served_node(tmp_path, index="xa_idx")
        try:
            payload = census.export_census("xa_idx")
            assert payload is not None
            assert payload["keys"] and payload["bodies"]
            assert payload["index"] == "xa_idx"
            # the relocation target's world: a DIFFERENT durable tier
            # where this index has never been seen
            ivf_cache.reset()
            ivf_cache.register(str(tmp_path / "target"))
            assert census.load_census("xa_idx") is None
            assert census.adopt_census("xa_idx", payload) is True
            got = census.load_census("xa_idx")
            assert got is not None
            assert {k["program"] for k in got["keys"]} == \
                {k["program"] for k in payload["keys"]}
            assert got["bodies"] == payload["bodies"]
        finally:
            n.close()

    def test_adopt_refuses_foreign_backend_and_garbage(self, tmp_path):
        from elasticsearch_tpu.monitor import programs

        ivf_cache.register(str(tmp_path / "t2"))
        good = {"version": census.VERSION, "index": "fb_idx",
                "backend": "tpu/v99",
                "keys": [{"program": "p", "shapes": "s", "field": "",
                          "hits": 1}],
                "bodies": []}
        assert census.adopt_census("fb_idx", good) is False  # backend
        assert census.adopt_census("fb_idx", None) is False
        assert census.adopt_census("fb_idx", {"index": "other"}) is False
        assert census.load_census("fb_idx") is None  # nothing persisted
        # malformed ROWS from a skewed source are skipped, never raised
        # (a raise would cancel the caller's flush + pre-warm kick):
        # the good row still adopts
        mixed = {"version": census.VERSION, "index": "fb_idx",
                 "backend": programs.backend_fingerprint(),
                 "keys": [{"program": "bad", "shapes": "s", "field": "",
                           "hits": None},
                          {"program": "ok", "shapes": "s", "field": "",
                           "hits": "1.5"},
                          {"program": "good", "shapes": "s", "field": "",
                           "hits": 3}],
                 "bodies": [{"body": "", "hits": 1}]}
        assert census.adopt_census("fb_idx", mixed) is True
        got = census.load_census("fb_idx")
        assert {k["program"] for k in got["keys"]} == {"good"}

    @staticmethod
    def _cluster_pair():
        import socket

        from elasticsearch_tpu.cluster.bootstrap import MultiHostCluster
        from elasticsearch_tpu.node import Node

        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
        s.close()
        n0 = Node(name="rc-rank0")
        c0 = MultiHostCluster(n0, rank=0, world=2, transport_port=port,
                              ping_interval=0)
        n1 = Node(name="rc-rank1")
        c1 = MultiHostCluster(n1, rank=1, world=2, transport_port=port,
                              ping_interval=0)
        return c0, c1

    @staticmethod
    def _close_pair(c0, c1):
        try:
            c1.close()
        finally:
            c0.close()
            c1.node.close()
            c0.node.close()

    def test_shard_sync_response_carries_census(self):
        c0, c1 = self._cluster_pair()
        try:
            c0.data.create_index("ss_idx", {
                "settings": {"number_of_shards": 1,
                             "number_of_replicas": 0},
                "mappings": {"properties": {"t": {"type": "text"}}}})
            for i in range(8):
                c0.data.index_doc("ss_idx", str(i), {"t": f"alpha w{i}"})
            c0.data.refresh("ss_idx")
            c0.node.search("ss_idx", {"query": {"match": {"t": "alpha"}},
                                      "size": 5})
            resp = c0.data._on_shard_sync({"index": "ss_idx", "shard": 0})
            shipped = resp.get("census")
            assert shipped is not None
            assert shipped["index"] == "ss_idx"
            # per-shard handshakes of one relocation reuse ONE computed
            # payload (the debounce window): no P× load+merge+serialize
            resp2 = c0.data._on_shard_sync({"index": "ss_idx", "shard": 0})
            assert resp2.get("census") is shipped
            # the REPLAYABLE half must always ship — it is what the
            # target's pre-warm consumes. Keys are compile-time records,
            # so in a shared-process test run a pre-warmed program
            # legitimately contributes none (the subprocess acceptance
            # test covers the cold-source case end to end).
            assert shipped["bodies"], "replayable bodies must ride along"
        finally:
            self._close_pair(c0, c1)

    def test_relocation_target_adopts_and_prewarms(self, tmp_path,
                                                   monkeypatch):
        """End-to-end through the real recovery handlers: _on_recover on
        the target adopts the census that rode the _on_shard_sync
        response and kicks pre-warm — with the disk-flush side channels
        disabled, the in-band copy is the ONLY way it can arrive."""
        from elasticsearch_tpu.cluster.search_action import \
            DistributedDataService

        c0, c1 = self._cluster_pair()
        try:
            body = {"settings": {"number_of_shards": 1,
                                 "number_of_replicas": 0},
                    "mappings": {"properties": {"t": {"type": "text"}}}}
            c0.data.create_index("mv_idx", dict(body))
            for i in range(8):
                c0.data.index_doc("mv_idx", str(i),
                                  {"t": f"alpha beta w{i}"})
            c0.data.refresh("mv_idx")
            c0.node.search("mv_idx", {"query": {"match": {"t": "alpha"}},
                                      "size": 4})
            # no side channels: neither node's debounced flush may seed
            # the blob tier — only the in-band adoption can
            monkeypatch.setattr(DistributedDataService,
                                "_flush_census_debounced",
                                lambda self, ix: None)
            ivf_cache.reset()
            ivf_cache.register(str(tmp_path / "target-tier"))
            assert census.load_census("mv_idx") is None
            res = c1.data._on_recover({
                "index": "mv_idx", "shard": 0,
                "source": c0.local.node_id,
                "target": c1.local.node_id, "body": body})
            assert res["mode"] in ("ops", "full")
            # the census arrived in-band and was persisted on the target
            got = census.load_census("mv_idx")
            assert got is not None and got["bodies"]
            # ... and pre-warm was kicked for the relocated index
            wu = c1.node.serving.warmup
            assert wu.wait_idle(timeout=30.0)
            run = wu.runs.get("mv_idx")
            assert run is not None
            assert run["status"] in ("complete", "cooldown")
        finally:
            self._close_pair(c0, c1)

    def test_relocation_target_zero_compile_delta(self, tmp_path):
        """ISSUE 15 acceptance: a relocation target in a FRESH process
        with its own (empty) data path adopts the shipped census,
        pre-warms, and serves the censused first page with compile
        delta 0 — the compiles all land in the warmup replay, none on
        the request path."""
        from elasticsearch_tpu.tracing import retrace

        if retrace.auditor() is None:
            pytest.skip("trace auditor unavailable")
        bodies = [{"query": {"match": {"t": t}}, "size": s}
                  for t in ("alpha", "alpha beta") for s in (5, 10)]
        src = _make_node(data_path=str(tmp_path / "srcdata"),
                         index="relidx", docs=24)
        for b in bodies:
            assert src.search("relidx", b)["hits"]["total"] > 0
        shipped = census.export_census("relidx")
        src.close()
        assert shipped is not None and shipped["bodies"]
        payload_file = tmp_path / "census_payload.json"
        payload_file.write_text(json.dumps(shipped))
        script = """
import json, os, sys
os.environ["JAX_PLATFORMS"] = "cpu"
from elasticsearch_tpu.node import Node
from elasticsearch_tpu.monitor import programs
from elasticsearch_tpu.resources import census
from elasticsearch_tpu.tracing import retrace
data, payload_file, bodies = sys.argv[1], sys.argv[2], \\
    json.loads(sys.argv[3])
n = Node(name="rel-target", data_path=data)
n.create_index("relidx", {
    "mappings": {"properties": {"t": {"type": "text"}}}})
svc = n.indices["relidx"]
for i in range(24):
    svc.index_doc(str(i), {"t": f"alpha beta gamma delta word{i}"})
svc.refresh()
assert census.load_census("relidx") is None  # nothing local: must ship
adopted = census.adopt_census("relidx",
                              json.loads(open(payload_file).read()))
res = n.serving.warmup.run_index("relidx", "relocation")
stats0 = programs.REGISTRY.stats()
t0 = retrace.auditor().total() if retrace.auditor() else -1
hits = [n.search("relidx", b)["hits"]["total"] for b in bodies]
stats1 = programs.REGISTRY.stats()
t1 = retrace.auditor().total() if retrace.auditor() else -1
print("RESULT " + json.dumps({
    "adopted": adopted, "warmup_run": res, "hits": hits,
    "compiles_during_page": stats1["compiles"] - stats0["compiles"],
    "traces_during_page": (t1 - t0) if t0 >= 0 else None}))
n.close()
"""
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        env.pop("ESTPU_WARMUP", None)
        p = subprocess.run(
            [sys.executable, "-c", script, str(tmp_path / "tgtdata"),
             str(payload_file), json.dumps(bodies)],
            capture_output=True, text=True, env=env, timeout=300)
        assert p.returncode == 0, p.stderr[-2000:]
        line = [ln for ln in p.stdout.splitlines()
                if ln.startswith("RESULT ")][-1]
        out = json.loads(line[len("RESULT "):])
        assert out["adopted"] is True
        assert out["warmup_run"]["status"] == "complete"
        assert out["warmup_run"]["replayed"] == len(bodies)
        assert all(h > 0 for h in out["hits"])
        # THE acceptance number: the relocated shard's first censused
        # page compiles NOTHING — warmup ate the whole cost
        assert out["compiles_during_page"] == 0
        assert out["traces_during_page"] == 0


# -- restart acceptance --------------------------------------------------------

class TestRestartAcceptance:
    def test_restart_prewarm_zero_fresh_compiles_first_page(
            self, tmp_path):
        """ISSUE 14 acceptance: a node with a persisted census restarts
        (REAL fresh process), pre-warm completes, and the first page of
        requests over censused keys records zero fresh compiles and zero
        warmup=true searches."""
        from elasticsearch_tpu.tracing import retrace

        if retrace.auditor() is None:
            pytest.skip("trace auditor unavailable")
        data = str(tmp_path / "data")
        bodies = [{"query": {"match": {"t": t}}, "size": s}
                  for t in ("alpha", "alpha beta", "beta gamma delta")
                  for s in (5, 10)]
        # phase A (this process): serve, persist census + AOT blobs
        n = _make_node(data_path=data, index="accidx", docs=24)
        for b in bodies:
            assert n.search("accidx", b)["hits"]["total"] > 0
        n.close()  # persists the census (keys + bodies, merged)
        assert census.load_census("accidx") is not None
        # phase B (fresh process): boot, pre-warm, serve the first page
        script = """
import json, os, sys
os.environ["JAX_PLATFORMS"] = "cpu"
from elasticsearch_tpu.node import Node
from elasticsearch_tpu.monitor import compile_cache, programs
from elasticsearch_tpu.tracing import retrace
bodies = json.loads(sys.argv[2])
n = Node(name="restart", data_path=sys.argv[1])
res = n.serving.warmup.run_index("accidx", "boot")
stats0 = programs.REGISTRY.stats()
t0 = retrace.auditor().total() if retrace.auditor() else -1
hits = [n.search("accidx", b)["hits"]["total"] for b in bodies]
stats1 = programs.REGISTRY.stats()
t1 = retrace.auditor().total() if retrace.auditor() else -1
rows = n.metrics.summaries().get("estpu_search_duration_seconds", [])
warm = {}
for r in rows:
    if r["labels"]["index"] == "accidx":
        warm[r["labels"]["warmup"]] = r["count"]
print("RESULT " + json.dumps({
    "warmup_run": res, "hits": hits,
    "compiles_during_page": stats1["compiles"] - stats0["compiles"],
    "traces_during_page": (t1 - t0) if t0 >= 0 else None,
    "warm_counts": warm,
    "compile_cache": compile_cache.events_snapshot()}))
n.close()
"""
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        env.pop("ESTPU_WARMUP", None)
        env.pop("ESTPU_AOT_CACHE", None)
        p = subprocess.run(
            [sys.executable, "-c", script, data, json.dumps(bodies)],
            capture_output=True, text=True, env=env, timeout=300)
        assert p.returncode == 0, p.stderr[-2000:]
        line = [ln for ln in p.stdout.splitlines()
                if ln.startswith("RESULT ")][-1]
        out = json.loads(line[len("RESULT "):])
        assert out["warmup_run"]["status"] == "complete"
        assert out["warmup_run"]["replayed"] == len(bodies)
        assert all(h > 0 for h in out["hits"])
        # THE acceptance numbers: zero fresh compiles on the first page,
        # zero warmup=cold searches — the restart cliff is gone
        assert out["compiles_during_page"] == 0
        assert out["traces_during_page"] == 0
        assert out["warm_counts"].get("true", 0) == 0
        assert out["warm_counts"].get("false", 0) == len(bodies)
        assert out["warm_counts"].get("prewarm", 0) >= 1
        # and the programs came from the AOT tier, not XLA
        assert out["compile_cache"]["aot_hit"] >= 1
        assert out["compile_cache"]["fresh"] == 0
