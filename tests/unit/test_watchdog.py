"""Flight recorder, stall watchdogs, and the cluster diagnostics bundle.

Covers the ISSUE-13 acceptance surface: bounded trace-linked flight
rings, each detector's trip math (adaptive program bound from the
ProgramRegistry's own p99 history, threadpool queue age, fsync latency,
publish-commit window, coalescer drain age), fault-injected stalls
(``watchdog.program_stall``, reused ``publish.commit``) producing
retrievable incident dumps, incident persistence across restart through
the generic blob helpers, the ``/_cluster/diagnostics`` bundle's
schema gate (stable top-level keys, bounded ring sizes), its 2-node
fan-out surviving a dead peer, and the running_time satellite.
"""
import re
import socket
import threading
import time

import pytest

from elasticsearch_tpu.monitor import flight, programs
from elasticsearch_tpu.monitor.watchdog import (WatchdogService,
                                                hot_threads_snapshot)
from elasticsearch_tpu.node import Node
from elasticsearch_tpu.rest.server import RestController
from elasticsearch_tpu.utils.faults import FAULTS


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


@pytest.fixture(autouse=True)
def _clean_faults():
    FAULTS.clear()
    yield
    FAULTS.clear()


@pytest.fixture()
def node():
    n = Node(name="wd-node")
    yield n
    n.close()


# ---------------------------------------------------------------------------
# flight recorder
# ---------------------------------------------------------------------------

class TestFlightRecorder:
    def test_rings_are_bounded_counts_exact(self):
        rec = flight.FlightRecorder("n1", "one")
        cap = flight.RING_CAPS["trips"]
        for i in range(cap * 2):
            rec.record("trips", seq=i)
        snap = rec.snapshot()
        assert len(snap["rings"]["trips"]) == cap
        assert snap["counts"]["trips"] == cap * 2
        # the retained window is the NEWEST cap entries
        assert snap["rings"]["trips"][-1]["seq"] == cap * 2 - 1
        assert snap["ring_caps"] == flight.RING_CAPS

    def test_unknown_ring_raises(self):
        rec = flight.FlightRecorder()
        with pytest.raises(KeyError):
            rec.record("not_a_ring", x=1)

    def test_entries_are_monotonic_stamped_and_trace_linked(self, node):
        with node.tracer.span("outer") as sp:
            node.flight.record("slow_ops", detector="t")
        e = node.flight.ring("slow_ops")[-1]
        assert e["ts_monotonic"] > 0
        assert e["timestamp_ms"] > 0
        assert e["trace_id"] == sp.trace_id

    def test_process_fan_reaches_every_registered_recorder(self):
        a, b = flight.FlightRecorder("a"), flight.FlightRecorder("b")
        flight.register(a)
        flight.register(b)
        try:
            flight.record("engine_failures", index="i", reason="r")
            assert a.ring("engine_failures")[-1]["index"] == "i"
            assert b.ring("engine_failures")[-1]["index"] == "i"
        finally:
            flight.unregister(a)
            flight.unregister(b)

    def test_breaker_trip_lands_in_ring(self, node):
        from elasticsearch_tpu import resources
        from elasticsearch_tpu.utils.errors import CircuitBreakingException

        br = resources.BREAKERS.breaker("request")
        with pytest.raises(CircuitBreakingException):
            br.break_or_reserve(1 << 62, "<test>")
        entries = node.flight.ring("breaker_trips")
        assert any(e["breaker"] == "request" for e in entries)

    def test_engine_failure_lands_in_ring(self, node):
        node.create_index("ef", {"settings": {"number_of_shards": 1}})
        node.indices["ef"].groups[0].copies[0].engine.fail("injected boom")
        entries = node.flight.ring("engine_failures")
        assert any(e["index"] == "ef" and "boom" in e["reason"]
                   for e in entries)


# ---------------------------------------------------------------------------
# detectors
# ---------------------------------------------------------------------------

class TestProgramStallDetector:
    def test_inflight_past_bound_trips_with_offending_key(self, node):
        wd = WatchdogService(node, program_default_bound_s=0.0,
                             cooldown_s=0.0)
        tok = programs.REGISTRY.begin_dispatch("mesh_dsl", "f32[8,128]")
        try:
            trips = [t for t in wd.run_once()
                     if t["detector"] == "program_stall"]
        finally:
            programs.REGISTRY.end_dispatch(tok)
        assert trips, "an aged in-flight dispatch must trip"
        d = trips[0]["detail"]
        assert d["program"] == "mesh_dsl" and d["shapes"] == "f32[8,128]"
        assert not d["injected"]

    def test_adaptive_bound_derives_from_key_p99(self, node):
        wd = WatchdogService(node, program_floor_s=0.0,
                             program_p99_mult=4.0, program_min_calls=4)
        for _ in range(8):
            programs.REGISTRY.record_execute("k_adapt", "f32[4]", 0.002)
        bound = wd._program_bound("k_adapt", "f32[4]")
        p99, calls = programs.REGISTRY.execute_p99("k_adapt", "f32[4]")
        assert calls == 8
        assert bound == pytest.approx(4.0 * p99)
        assert bound < wd.config["program_default_bound_s"]
        # a key with no history gets the absolute default
        assert wd._program_bound("k_unknown", "f32[4]") == \
            wd.config["program_default_bound_s"]

    def test_injected_fault_trips_and_incident_is_retrievable(self, node):
        wd = node.watchdog
        tok = programs.REGISTRY.begin_dispatch("mesh_bm25", "f32[16,1024]")
        FAULTS.inject("watchdog.program_stall", count=1)
        try:
            trips = [t for t in wd.run_once()
                     if t["detector"] == "program_stall"]
        finally:
            programs.REGISTRY.end_dispatch(tok)
        assert trips and trips[0]["detail"]["injected"]
        iid = trips[0]["incident_id"]
        assert iid
        inc = wd.incidents.load(iid)
        assert inc is not None
        # the acceptance triad: flight ring + hot threads + offending key
        assert set(inc["flight"]["rings"]) == set(flight.RING_CAPS)
        assert inc["hot_threads"], "hot-threads snapshot must be captured"
        assert any(r["program"] == "mesh_bm25"
                   for r in inc["programs"]["inflight"])
        # and the trip is a Prometheus counter + /_tasks-style stats row
        expo = node.metrics.expose()
        assert 'estpu_watchdog_trips_total{detector="program_stall"}' \
            in expo
        assert wd.stats()["trips"]["program_stall"] >= 1

    def test_cooldown_debounces_incident_capture(self, node):
        wd = WatchdogService(node, cooldown_s=3600.0)
        FAULTS.inject("watchdog.program_stall", count=2)
        first = wd.run_once()
        second = wd.run_once()
        t1 = [t for t in first if t["detector"] == "program_stall"][0]
        t2 = [t for t in second if t["detector"] == "program_stall"][0]
        assert t1["incident_id"] is not None
        assert t2["incident_id"] is None  # counted, recorded, not dumped
        assert wd.stats()["trips"]["program_stall"] == 2
        assert wd.stats()["incidents_captured"] == 1


class TestOtherDetectors:
    def test_threadpool_starvation_needs_old_head_and_busy_workers(
            self, node):
        from types import SimpleNamespace

        from elasticsearch_tpu.utils.threadpool import FixedThreadPool

        pool = FixedThreadPool("stall", size=1, queue_size=4)
        release = threading.Event()
        threading.Thread(target=pool.execute, args=(release.wait,),
                         daemon=True).start()
        threading.Thread(target=pool.execute, args=(lambda: None,),
                         daemon=True).start()
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            if pool.stats()["queue"] >= 1 and pool.stats()["active"] >= 1:
                break
            time.sleep(0.01)
        assert pool.oldest_queue_age() is not None
        wd = WatchdogService(node, threadpool_age_bound_s=0.0,
                             cooldown_s=0.0)
        saved = node._thread_pool
        node._thread_pool = SimpleNamespace(pools={"stall": pool})
        try:
            trips = [t for t in wd.run_once()
                     if t["detector"] == "threadpool_starve"]
        finally:
            node._thread_pool = saved
            release.set()
            pool.shutdown()
        assert trips and trips[0]["detail"]["pool"] == "stall"

    def test_fsync_latency_over_bound_trips(self, node):
        from elasticsearch_tpu.monitor.metrics import SHARED

        wd = WatchdogService(node, fsync_bound_s=1.0, cooldown_s=0.0)
        wd.run_once()  # baseline the cursor past prior tests' syncs
        SHARED.histogram("estpu_translog_fsync_duration_seconds",
                         "Translog flush+fsync latency").observe(5.0)
        trips = [t for t in wd.run_once()
                 if t["detector"] == "translog_fsync"]
        assert trips
        assert trips[0]["detail"]["avg_seconds"] >= 1.0

    def test_coalescer_drain_age_trips(self, node):
        from elasticsearch_tpu.serving.coalescer import _Entry

        co = node.serving.coalescer
        e = _Entry(None, {}, None)
        e.enqueued = time.perf_counter() - 10.0
        with co._cv:
            co._queues[("idx", "f")] = [e]
        try:
            assert co.oldest_queue_age() >= 10.0
            wd = WatchdogService(node, coalescer_bound_s=1.0,
                                 cooldown_s=0.0)
            trips = [t for t in wd.run_once()
                     if t["detector"] == "coalescer_drain"]
        finally:
            with co._cv:
                co._queues.clear()
        assert trips
        assert trips[0]["detail"]["oldest_age_seconds"] >= 10.0

    def test_metric_delta_snapshots_land_in_ring(self, node):
        wd = WatchdogService(node)
        wd.run_once()  # first tick establishes the baseline
        from elasticsearch_tpu.monitor import kernels

        kernels.record("wd_test_kernel")
        wd.run_once()
        deltas = node.flight.ring("metrics")
        assert any("kernels.wd_test_kernel" in e.get("delta", {})
                   for e in deltas)

    def test_trips_visible_to_bench_counter_delta(self, node):
        from elasticsearch_tpu.monitor.metrics import (counters_delta,
                                                       process_counters)

        before = process_counters()
        FAULTS.inject("watchdog.program_stall", count=1)
        node.watchdog.run_once()
        delta = counters_delta(before, process_counters())
        assert delta.get("watchdog.trips", 0) >= 1
        assert delta.get("watchdog.incidents", 0) >= 1


# ---------------------------------------------------------------------------
# incident persistence (generic blob tier)
# ---------------------------------------------------------------------------

class TestIncidentPersistence:
    def test_incident_survives_restart(self, tmp_path):
        n1 = Node(name="persist-1", data_path=str(tmp_path))
        FAULTS.inject("watchdog.program_stall", count=1)
        trips = n1.watchdog.run_once()
        iid = [t["incident_id"] for t in trips if t["incident_id"]][0]
        n1.close()
        FAULTS.clear()
        n2 = Node(name="persist-2", data_path=str(tmp_path))
        try:
            listed = n2.watchdog.incidents.list()
            mine = [e for e in listed if e["id"] == iid]
            assert mine and mine[0].get("persisted")
            payload = n2.watchdog.incidents.load(iid)
            assert payload is not None
            assert payload["detector"] == "program_stall"
            assert "flight" in payload and "hot_threads" in payload
        finally:
            n2.close()

    def test_corrupt_blob_reads_as_clean_miss(self, tmp_path):
        from elasticsearch_tpu.index import ivf_cache

        n1 = Node(name="corrupt-1", data_path=str(tmp_path))
        try:
            FAULTS.inject("watchdog.program_stall", count=1)
            trips = n1.watchdog.run_once()
            iid = [t["incident_id"] for t in trips if t["incident_id"]][0]
            key = flight.incident_key(iid)
            ivf_cache.store_blob(key, b"deadbeef\n{not json", "incident")
            # drop the in-memory copy so load() must go through the blob
            n1.watchdog.incidents._payloads.clear()
            assert n1.watchdog.incidents.load(iid) is None
            assert ivf_cache.load_blob(key, "incident") is None  # deleted
        finally:
            n1.close()


# ---------------------------------------------------------------------------
# REST surface + bundle schema gate (tier-1)
# ---------------------------------------------------------------------------

#: the diagnostics bundle's schema contract — changing either set is an
#: intentional, reviewed act (support tooling parses this artifact)
BUNDLE_KEYS = {"version", "cluster_name", "timestamp", "master_node",
               "_nodes", "nodes", "failures"}
NODE_KEYS = {"name", "flight", "watchdog", "incidents",
             "incident_payloads", "hot_threads", "tasks", "programs",
             "breakers", "thread_pool"}


class TestDiagnosticsSchema:
    def test_bundle_schema_and_bounded_rings(self, node):
        FAULTS.inject("watchdog.program_stall", count=1)
        node.watchdog.run_once()
        rc = RestController(node)
        s, out = rc.dispatch("GET", "/_cluster/diagnostics", {}, b"")
        assert s == 200
        assert set(out) == BUNDLE_KEYS
        assert out["version"] == 1
        assert out["_nodes"]["successful"] == 1
        assert out["_nodes"]["failed"] == 0
        entry = out["nodes"][node.node_id]
        assert set(entry) == NODE_KEYS
        fl = entry["flight"]
        assert set(fl["rings"]) == set(flight.RING_CAPS)
        for name, events in fl["rings"].items():
            assert len(events) <= flight.RING_CAPS[name], name
        # inline incident payloads are bounded by the ?incidents= cap
        assert len(entry["incident_payloads"]) <= 8
        # monotonic + display stamps on every event, never a raw delta
        for events in fl["rings"].values():
            for e in events:
                assert "ts_monotonic" in e and "timestamp_ms" in e

    def test_node_flight_and_cat_incidents(self, node):
        FAULTS.inject("watchdog.program_stall", count=1)
        trips = node.watchdog.run_once()
        iid = [t["incident_id"] for t in trips if t["incident_id"]][0]
        rc = RestController(node)
        s, out = rc.dispatch("GET", "/_nodes/_local/flight", {}, b"")
        assert s == 200
        assert out["flight"]["counts"]["trips"] >= 1
        assert any(e["id"] == iid for e in out["incidents"])
        s, rows = rc.dispatch("GET", "/_cat/incidents", {}, b"")
        assert s == 200
        row = [r for r in rows if r["id"] == iid][0]
        assert row["detector"] == "program_stall"
        s, payload = rc.dispatch(
            "GET", f"/_cluster/diagnostics/incidents/{iid}", {}, b"")
        assert s == 200 and payload["id"] == iid
        s, _ = rc.dispatch(
            "GET", "/_cluster/diagnostics/incidents/nope:1", {}, b"")
        assert s == 404

    def test_hot_threads_snapshot_is_sleepless_and_capped(self):
        t0 = time.perf_counter()
        snap = hot_threads_snapshot(limit=4)
        assert time.perf_counter() - t0 < 0.5
        assert len(snap) <= 4
        for row in snap:
            assert row["stack"] and isinstance(row["stack"][0], str)


class TestRunningTimeSatellite:
    def test_human_time_scales(self):
        from elasticsearch_tpu.tracing.tasks import human_time

        assert human_time(850_000) == "850micros"
        assert human_time(770_000_000) == "770ms"
        assert human_time(int(12.3e9)) == "12.3s"
        assert human_time(int(4.5 * 60e9)) == "4.5m"
        assert human_time(int(2.2 * 3600e9)) == "2.2h"

    def test_tasks_json_and_cat_carry_both_forms(self, node):
        t = node.tasks.register("indices:data/read/search", "wedged")
        try:
            j = t.to_json()
            assert j["running_time_in_nanos"] >= 0
            assert re.fullmatch(r"[\d.]+(micros|ms|s|m|h)",
                                j["running_time"])
            rc = RestController(node)
            s, rows = rc.dispatch("GET", "/_cat/tasks", {}, b"")
            assert s == 200
            row = [r for r in rows
                   if r["task_id"] == t.tagged_id][0]
            assert re.fullmatch(r"[\d.]+(micros|ms|s|m|h)",
                                row["running_time"])
            assert int(row["running_time_in_nanos"]) >= 0
            assert "running_time" in rows.default
        finally:
            node.tasks.unregister(t)


# ---------------------------------------------------------------------------
# 2-node cluster: publish-commit window fault + bundle fan-out + dead peer
# ---------------------------------------------------------------------------

class TestClusterDiagnostics:
    def test_publish_window_fault_trips_and_bundle_merges_members(self):
        from elasticsearch_tpu.cluster.bootstrap import MultiHostCluster

        port = _free_port()
        node0 = Node(name="rank0")
        c0 = MultiHostCluster(node0, rank=0, world=2, transport_port=port,
                              ping_interval=0)
        node1 = Node(name="rank1")
        c1 = MultiHostCluster(node1, rank=1, world=2, transport_port=port)
        try:
            # a publish that dies inside the commit window (the
            # publish.commit fault domain PR 10 established)
            FAULTS.inject("publish.commit", count=1)
            c0.data.create_index("diag", {
                "settings": {"number_of_shards": 2}})
            assert any(
                e.get("event") == "publish_commit_window_fault"
                for e in node0.flight.ring("cluster"))
            # the watchdog (manual tick or the always-on thread) trips
            deadline = time.monotonic() + 5.0
            while time.monotonic() < deadline:
                node0.watchdog.run_once()
                if node0.watchdog.stats()["trips"].get(
                        "publish_stall", 0) >= 1:
                    break
                time.sleep(0.05)
            assert node0.watchdog.stats()["trips"].get(
                "publish_stall", 0) >= 1
            # the bundle, requested FROM THE OTHER MEMBER, carries both
            # nodes and rank0's incident evidence
            rc1 = RestController(node1)
            s, out = rc1.dispatch("GET", "/_cluster/diagnostics",
                                  {"incidents": "4"}, b"")
            assert s == 200
            assert set(out) == BUNDLE_KEYS
            assert out["_nodes"]["successful"] == 2
            assert out["_nodes"]["failed"] == 0
            n0_entry = out["nodes"][node0.node_id]
            assert n0_entry["watchdog"]["trips"].get("publish_stall",
                                                     0) >= 1
            assert any(i["detector"] == "publish_stall"
                       for i in n0_entry["incidents"])
            payloads = [p for p in n0_entry["incident_payloads"]
                        if p["detector"] == "publish_stall"]
            assert payloads, "the dump must ride the bundle inline"
            inc = payloads[-1]
            assert inc["hot_threads"]
            assert any(e.get("event") == "publish_commit_window_fault"
                       for e in inc["flight"]["rings"]["cluster"])
            # dead peer: kill rank1 ABRUPTLY (no cluster:leave — a crash,
            # not a drain); the bundle from the survivor still answers
            # 200 and counts the corpse in _nodes.failed
            c1._stop.set()
            c1.transport.close()
            rc0 = RestController(node0)
            s, out = rc0.dispatch("GET", "/_cluster/diagnostics", {}, b"")
            assert s == 200
            assert out["_nodes"]["failed"] >= 1
            assert out["failures"]
            assert node0.node_id in out["nodes"]
        finally:
            c1.close()
            c0.close()
            node1.close()
            node0.close()
